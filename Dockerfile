# Container build for the TPU-native log parser — mirrors the 3-stage
# shape of the reference image (/root/reference/src/main/docker/
# Dockerfile.native:1-30: dependencies stage, build stage, slim runtime
# serving :8080) with Python/JAX in place of Mandrel/GraalVM.
#
# Build:    docker build -t log-parser-tpu .
# Run:      docker run -p 8080:8080 -v /shared/patterns:/patterns log-parser-tpu
# TPU hosts: build with --build-arg JAX_EXTRA="jax[tpu]" on a machine with
# the libtpu wheel source configured; default is the CPU wheel so the image
# runs anywhere (the engine is platform-agnostic at import time).

ARG PYTHON_IMAGE=python:3.12-slim

# ---- stage 1: dependencies (cache-friendly, mirrors "dependencies") ----
FROM ${PYTHON_IMAGE} AS dependencies
ARG JAX_EXTRA="jax[cpu]"
WORKDIR /build
RUN python -m venv /opt/venv
ENV PATH=/opt/venv/bin:$PATH
COPY pyproject.toml .
# resolve third-party deps before source is copied so edits to code don't
# bust this layer (the reference does the same with mvn dependency:go-offline)
RUN pip install --no-cache-dir "${JAX_EXTRA}" numpy pyyaml

# ---- stage 2: build (wheel + native runtime library) -------------------
FROM dependencies AS build
RUN apt-get update && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/*
COPY . /build
# the native ingest/DFA library is an accelerator, never a requirement —
# prebuild it here so the runtime stage needs no toolchain
RUN g++ -O3 -std=c++17 -shared -fPIC native/log_parser_native.cpp \
        -o native/build/log_parser_native.so \
    && pip install --no-cache-dir --no-deps .

# ---- optional: native-rebuild (GLIBCXX mismatch recovery) --------------
# A prebuilt log_parser_native.so carried over from a newer build host
# fails dlopen with "GLIBCXX_x.y.z not found" and the server silently
# runs the scalar fallback (python tools/check_native.py prints the
# required-vs-provided diagnosis; /metrics shows it as
# logparser_native_loaded{reason="glibcxx_mismatch"}). This stage
# rebuilds the scanner from source against THIS image's own libstdc++,
# so the produced .so can never outrun the runtime stage's C++ ABI:
#   docker build --target native-rebuild -t lp-native .
#   docker run --rm -v "$PWD/native/build:/out" lp-native
FROM ${PYTHON_IMAGE} AS native-rebuild
RUN apt-get update && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/*
WORKDIR /build
COPY native/log_parser_native.cpp native/
RUN mkdir -p native/build \
    && g++ -O3 -std=c++17 -shared -fPIC native/log_parser_native.cpp \
        -o native/build/log_parser_native.so
CMD ["cp", "/build/native/build/log_parser_native.so", "/out/"]

# ---- stage 3: slim runtime serving :8080 (mirrors ubi-minimal stage) ---
FROM ${PYTHON_IMAGE}
WORKDIR /work
COPY --from=dependencies /opt/venv /opt/venv
COPY --from=build /opt/venv/lib/python*/site-packages/log_parser_tpu \
     /opt/venv/lib/python3.12/site-packages/log_parser_tpu
# the loader resolves native/build/ relative to the installed package root
# (log_parser_tpu/native/__init__.py), two levels above the package — i.e.
# site-packages/native/build/. Ship only the prebuilt .so: with no source
# alongside, the loader uses it as-is and never needs a toolchain.
COPY --from=build /build/native/build/log_parser_native.so \
     /opt/venv/lib/python3.12/site-packages/native/build/
ENV PATH=/opt/venv/bin:$PATH \
    PATTERN_DIRECTORY=/patterns
EXPOSE 8080
CMD ["python", "-m", "log_parser_tpu.serve", "--host", "0.0.0.0", "--port", "8080"]
