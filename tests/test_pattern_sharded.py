"""Pattern-axis sharding (SURVEY.md §2.2 TP analogue) vs golden: block
partitioning, global index remap, and merge-order correctness."""

from __future__ import annotations

import random

import pytest

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.golden import GoldenAnalyzer
from log_parser_tpu.models import PodFailureData
from log_parser_tpu.parallel.pattern_sharded import (
    PatternShardedEngine,
    partition_pattern_sets,
)
from tests.conftest import FakeClock
from tests.test_engine_parity import assert_results_match, random_library, random_logs


def test_partition_preserves_discovery_order():
    rng = random.Random(3)
    sets = random_library(rng, 5)
    blocks = partition_pattern_sets(sets, 4)
    flat = [p.id for ps in sets for p in ps.patterns or []]
    flat_blocks = [p.id for block in blocks for ps in block for p in ps.patterns or []]
    assert flat == flat_blocks
    assert len(blocks) == min(4, max(1, len(flat)))


@pytest.mark.parametrize("seed", range(3))
@pytest.mark.parametrize("n_blocks", [1, 3, 4])
def test_random_parity_vs_golden(seed, n_blocks):
    rng = random.Random(9000 + seed)
    sets = random_library(rng, rng.randrange(3, 7))
    config = ScoringConfig(frequency_threshold=rng.choice([2.0, 10.0]))
    engine = PatternShardedEngine(
        sets, config, n_blocks=n_blocks, clock=FakeClock()
    )
    golden = GoldenAnalyzer(sets, config, clock=FakeClock())
    for _ in range(2):
        logs = random_logs(rng, rng.randrange(20, 200))
        data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=logs)
        assert_results_match(engine.analyze(data), golden.analyze(data))
    assert (
        engine.frequency.get_frequency_statistics()
        == golden.frequency.get_frequency_statistics()
    )
