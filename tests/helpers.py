"""Shared builders for pattern-set fixtures used across the test suite."""

from __future__ import annotations

from log_parser_tpu.models.pattern import (
    ContextExtraction,
    Pattern,
    PatternSet,
    PatternSetMetadata,
    PrimaryPattern,
    SecondaryPattern,
    SequenceEvent,
    SequencePattern,
)


def make_pattern(
    pattern_id: str = "p1",
    regex: str = "ERROR",
    confidence: float = 0.8,
    severity: str = "HIGH",
    secondaries: list[tuple[str, float, int]] | None = None,
    sequences: list[tuple[float, list[str]]] | None = None,
    context: tuple[int, int] | None = None,
    name: str | None = None,
) -> Pattern:
    return Pattern(
        id=pattern_id,
        name=name or pattern_id,
        severity=severity,
        primary_pattern=PrimaryPattern(regex=regex, confidence=confidence),
        secondary_patterns=(
            [
                SecondaryPattern(regex=r, weight=w, proximity_window=win)
                for r, w, win in secondaries
            ]
            if secondaries
            else None
        ),
        sequence_patterns=(
            [
                SequencePattern(
                    description=f"seq{i}",
                    bonus_multiplier=bonus,
                    events=[SequenceEvent(regex=r) for r in event_regexes],
                )
                for i, (bonus, event_regexes) in enumerate(sequences)
            ]
            if sequences
            else None
        ),
        context_extraction=(
            ContextExtraction(lines_before=context[0], lines_after=context[1])
            if context
            else None
        ),
    )


def make_pattern_set(patterns: list[Pattern], library_id: str = "lib1") -> PatternSet:
    return PatternSet(
        metadata=PatternSetMetadata(library_id=library_id, name=library_id),
        patterns=patterns,
    )
