"""REST contract tests — the role rest-assured was meant to play in the
reference (declared at pom.xml:73-77, never used; SURVEY.md §4)."""

import json
import threading
import urllib.error
import urllib.request

import pytest

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.runtime import AnalysisEngine
from log_parser_tpu.serve import make_server
from tests.helpers import make_pattern, make_pattern_set


@pytest.fixture(scope="module")
def server_url():
    patterns = [
        make_pattern("oom", regex="OutOfMemoryError", confidence=0.9,
                     severity="CRITICAL", context=(1, 1)),
        make_pattern("err", regex=r"\bERROR\b", confidence=0.5, severity="LOW"),
    ]
    engine = AnalysisEngine([make_pattern_set(patterns, "lib")], ScoringConfig())
    server = make_server(engine, host="127.0.0.1", port=0)
    port = server.server_address[1]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{port}"
    server.shutdown()


def post(url: str, payload, raw: bytes | None = None):
    body = raw if raw is not None else json.dumps(payload).encode()
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"}
    )
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def get(url: str):
    try:
        with urllib.request.urlopen(url) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestParseEndpoint:
    def test_success_contract(self, server_url):
        status, body = post(
            server_url + "/parse",
            {
                "pod": {"metadata": {"name": "web-1"}},
                "logs": "INFO boot\njava.lang.OutOfMemoryError: heap\nafter",
            },
        )
        assert status == 200
        assert body["summary"]["significantEvents"] == 1
        assert body["summary"]["highestSeverity"] == "CRITICAL"
        event = body["events"][0]
        assert event["lineNumber"] == 2
        assert event["matchedPattern"]["id"] == "oom"
        assert event["context"]["matchedLine"].startswith("java.lang")
        assert event["context"]["linesBefore"] == ["INFO boot"]
        assert event["score"] > 0
        assert body["metadata"]["totalLines"] == 3
        assert body["metadata"]["patternsUsed"] == ["lib"]
        assert body["analysisId"]

    def test_null_pod_is_400(self, server_url):
        status, body = post(server_url + "/parse", {"logs": "x"})
        assert status == 400
        assert body == {"error": "Invalid PodFailureData provided"}

    def test_null_body_is_400(self, server_url):
        status, body = post(server_url + "/parse", None, raw=b"")
        assert status == 400

    def test_malformed_json_is_400(self, server_url):
        status, _ = post(server_url + "/parse", None, raw=b"{not json")
        assert status == 400

    def test_json_array_body_is_400(self, server_url):
        status, _ = post(server_url + "/parse", [1, 2, 3])
        assert status == 400

    def test_unknown_route_404(self, server_url):
        status, _ = post(server_url + "/nope", {})
        assert status == 404


class TestOperationalEndpoints:
    def test_health(self, server_url):
        for path in ("/health", "/health/live", "/health/ready", "/q/health"):
            status, body = get(server_url + path)
            assert status == 200 and body["status"] == "UP"

    def test_frequency_stats_and_reset(self, server_url):
        post(
            server_url + "/parse",
            {"pod": {"metadata": {"name": "p"}}, "logs": "an ERROR here"},
        )
        status, stats = get(server_url + "/frequency/stats")
        assert status == 200 and stats.get("err", 0) >= 1
        status, _ = post(server_url + "/frequency/reset/err", None, raw=b"")
        assert status == 200
        _, stats = get(server_url + "/frequency/stats")
        assert stats.get("err") == 0
        status, _ = post(server_url + "/frequency/reset", None, raw=b"")
        assert status == 200


class TestAnalysisFailure:
    def test_analysis_exception_is_json_500(self):
        """A bug that propagates out of analyze() must answer with a JSON
        500, not a dropped connection (round-2 review finding)."""
        engine = AnalysisEngine(
            [make_pattern_set([make_pattern("e", regex="ERROR")], "lib")],
            ScoringConfig(),
        )
        engine.analyze_pipelined = lambda data, **kw: (_ for _ in ()).throw(TypeError("bug"))
        server = make_server(engine, host="127.0.0.1", port=0)
        port = server.server_address[1]
        threading.Thread(target=server.serve_forever, daemon=True).start()
        try:
            status, body = post(
                f"http://127.0.0.1:{port}/parse",
                {"pod": {"metadata": {"name": "p"}}, "logs": "x"},
            )
            assert status == 500
            assert body == {"error": "Internal analysis failure"}
        finally:
            server.shutdown()


class TestFrequencyRestoreValidation:
    """POST /frequency/restore is all-or-nothing: any invalid entry fails
    the whole request with 400 and existing state stays untouched."""

    @pytest.fixture()
    def fresh_server(self):
        engine = AnalysisEngine(
            [make_pattern_set([make_pattern("err", regex=r"\bERROR\b",
                                            confidence=0.5)], "lib")],
            ScoringConfig(),
        )
        server = make_server(engine, host="127.0.0.1", port=0)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        yield f"http://127.0.0.1:{server.server_address[1]}"
        server.shutdown()
        server.server_close()

    def _warm(self, url):
        """Record one real match so 'state untouched' is observable."""
        post(url + "/parse",
             {"pod": {"metadata": {"name": "p"}}, "logs": "an ERROR here"})
        _, stats = get(url + "/frequency/stats")
        assert stats == {"err": 1}

    def test_valid_restore_replaces_state(self, fresh_server):
        self._warm(fresh_server)
        status, body = post(
            fresh_server + "/frequency/restore", {"oom": [0.0, 12.5]}
        )
        assert status == 200 and body == {"status": "restored", "epoch": 0}
        _, stats = get(fresh_server + "/frequency/stats")
        assert stats == {"oom": 2}  # replaced, not merged: "err" is gone

    @pytest.mark.parametrize(
        "payload",
        [
            {"ok": [1.0], "bad": [2.0, -0.5]},  # one negative age poisons all
            {"ok": [1.0], "bad": 7},  # non-list value
            {"ok": [1.0], "bad": [1.0, "soon"]},  # non-numeric age
            {"ok": [-1.0]},  # negative age alone
            [["ok", [1.0]]],  # non-dict payload
            "nope",
        ],
    )
    def test_invalid_payload_is_400_and_state_untouched(
        self, fresh_server, payload
    ):
        self._warm(fresh_server)
        status, body = post(fresh_server + "/frequency/restore", payload)
        assert status == 400
        assert body == {"error": "expected {patternId: [ageSeconds >= 0]}"}
        _, stats = get(fresh_server + "/frequency/stats")
        assert stats == {"err": 1}  # nothing partially applied

    def test_malformed_json_is_400(self, fresh_server):
        self._warm(fresh_server)
        status, _ = post(fresh_server + "/frequency/restore", None, raw=b"{oops")
        assert status == 400
        _, stats = get(fresh_server + "/frequency/stats")
        assert stats == {"err": 1}


class TestDroppedResponses:
    def test_client_gone_is_counted_not_raised(self):
        """A client that hangs up before the response lands (BrokenPipe /
        ConnectionReset on write) is counted in droppedResponses and
        logged at debug — no traceback spew, no handler crash."""
        from log_parser_tpu.serve.http import ParseServer, _Handler

        engine = AnalysisEngine(
            [make_pattern_set([make_pattern("e", regex="E")])], ScoringConfig()
        )
        server = make_server(engine, host="127.0.0.1", port=0)
        url = f"http://127.0.0.1:{server.server_address[1]}"
        threading.Thread(target=server.serve_forever, daemon=True).start()
        try:

            class _GonePipe:
                def write(self, data):
                    raise BrokenPipeError(32, "Broken pipe")

                def flush(self):
                    pass

            for exc in (BrokenPipeError, ConnectionResetError):
                handler = _Handler.__new__(_Handler)
                handler.server = server
                handler.client_address = ("127.0.0.1", 1)
                handler.request_version = "HTTP/1.1"
                handler.requestline = "POST /parse HTTP/1.1"
                handler.close_connection = False
                pipe = _GonePipe()
                pipe.write = lambda data, exc=exc: (_ for _ in ()).throw(
                    exc(32, "gone")
                )
                handler.wfile = pipe
                handler._send_json(200, b"{}")  # must not raise
                assert handler.close_connection is True

            assert server.dropped_responses == 2
            _, trace = get(url + "/trace/last")
            assert trace["droppedResponses"] == 2
        finally:
            server.shutdown()
            server.server_close()


class TestDegradedHealth:
    def test_health_reports_device_circuit(self):
        """Health stays UP with the watchdog circuit open (requests serve
        from the host path) but surfaces the degradation; /trace/last
        carries deviceCircuitOpen."""
        engine = AnalysisEngine(
            [make_pattern_set([make_pattern("e", regex="E", confidence=0.5)])],
            ScoringConfig(),
        )
        server = make_server(engine, host="127.0.0.1", port=0)
        url = f"http://127.0.0.1:{server.server_address[1]}"
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        try:
            status, body = get(url + "/health")
            assert status == 200 and body == {"status": "UP"}
            with engine.watchdog._lock:
                engine.watchdog._open = True  # simulate a tripped breaker
            status, body = get(url + "/health")
            assert status == 200 and body["status"] == "UP"
            assert body["checks"] == [{"name": "device", "status": "DEGRADED"}]
            _, tr = get(url + "/trace/last")
            assert tr["deviceCircuitOpen"] is True
            with engine.watchdog._lock:
                engine.watchdog._open = False
            _, body = get(url + "/health")
            assert body == {"status": "UP"}
        finally:
            server.shutdown()
