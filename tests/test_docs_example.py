"""Anchor the reference docs' worked scoring example.

``/root/reference/docs/SCORING_ALGORITHM.md`` ("Example Calculation",
lines 193-208) walks one event through the seven-factor formula:

    Final Score = 0.8 x 3.0 x 2.1 x 1.4 x 1.0 x 1.5 x (1.0 - 0.0) = 21.17

Two things are pinned here:

1. The product of the doc's own stated factors is 10.584 — the printed
   21.17 is exactly ``2 x 10.584 = 21.168`` rounded to two places, an
   arithmetic slip in the reference doc.  Both facts are asserted so the
   discrepancy is on record rather than silently "fixed" either way.

2. An end-to-end scenario engineered so every factor is analytically
   exact under the reference formulas (ScoringService.java:100-150,
   ContextAnalysisService.java:56-116) — chronological exactly 2.1
   (position 8% through a 100-line log), proximity ``1 + 0.6*e^{-3/10}``
   (one secondary at distance 3, weight 0.6, decay constant 10), temporal
   1.0 (no sequences), context 2.0 (two ERROR lines + one stack-trace
   line -> score 0.4+0.4+0.1+min(0.1,0.5)=1.0), frequency penalty 0.0
   (first sighting, threshold 10).  The device engine and the golden
   analyzer must both reproduce the hand-computed IEEE-double product.
"""

from __future__ import annotations

import math

import pytest

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.golden import GoldenAnalyzer
from log_parser_tpu.models import PodFailureData
from log_parser_tpu.runtime import AnalysisEngine
from tests.conftest import FakeClock
from tests.helpers import make_pattern, make_pattern_set


def test_doc_example_factor_product():
    # SCORING_ALGORITHM.md:193-208 — the stated factors...
    product = 0.8 * 3.0 * 2.1 * 1.4 * 1.0 * 1.5 * (1.0 - 0.0)
    assert product == pytest.approx(10.584, abs=1e-12)
    # ...and the doc's printed total, which is exactly twice their product.
    assert round(2 * product, 2) == 21.17


def _example_fixture():
    pattern = make_pattern(
        pattern_id="doc-example",
        regex="OOMKILL detected",
        confidence=0.8,
        severity="HIGH",
        secondaries=[("HEAPDUMP written", 0.6, 10)],
        context=(3, 3),
    )
    lines = [f"reconcile tick {i} status=ok" for i in range(100)]
    lines[5] = "first ERROR in context"
    lines[6] = "second ERROR in context"
    lines[7] = "  at com.example.Foo.bar(Foo.java:17)"
    lines[8] = "OOMKILL detected"  # 1-based line 9 -> position 8/100 = 0.08
    lines[11] = "HEAPDUMP written"  # distance 3 from the primary
    return [make_pattern_set([pattern])], "\n".join(lines)


def _expected_score() -> float:
    # Hand-computed in the reference's own double-op order
    # (ScoringService.java:100-109).
    chrono = 1.5 + (0.2 - 0.08) * ((2.5 - 1.5) / 0.2)  # = 2.1
    proximity = 1.0 + 0.6 * math.exp(-3.0 / 10.0)  # ~1.4445
    context = 1.0 + (0.4 + 0.4 + 0.1 + 0.1)  # = 2.0
    return 0.8 * 3.0 * chrono * proximity * 1.0 * context * (1.0 - 0.0)


@pytest.mark.parametrize("engine_cls", [AnalysisEngine, GoldenAnalyzer])
def test_doc_example_end_to_end(engine_cls):
    sets, log_text = _example_fixture()
    engine = engine_cls(sets, ScoringConfig(), clock=FakeClock())
    result = engine.analyze(
        PodFailureData(pod={"metadata": {"name": "doc-example"}}, logs=log_text)
    )
    events = result.events
    assert len(events) == 1
    ev = events[0]
    assert ev.line_number == 9
    assert ev.score == pytest.approx(_expected_score(), abs=1e-12)
    # With the doc's loose "~" factor values replaced by the exact formula
    # outputs, the example's true final score:
    assert ev.score == pytest.approx(14.56046860, abs=1e-6)
