"""Device match kernels vs their host reference executors."""

import random

import numpy as np

from log_parser_tpu.ops.encode import encode_lines
from log_parser_tpu.ops.match import AcRunner, DfaBank
from log_parser_tpu.patterns.regex import AhoCorasick, compile_regex_to_dfa
from tests.test_regex_dfa import REGEXES, random_lines


class TestDfaBank:
    def test_bank_matches_individual_dfas(self):
        dfas = [compile_regex_to_dfa(rx) for rx in REGEXES[:10]]
        bank = DfaBank(dfas)
        lines = random_lines(12345, count=100)
        enc = encode_lines(lines)
        cube = bank.match(enc.u8, enc.lengths)
        for i, line in enumerate(lines):
            blob = line.encode()
            for r, dfa in enumerate(dfas):
                assert cube[i, r] == dfa.matches(blob), (line, dfa.regex)

    def test_empty_bank(self):
        bank = DfaBank([])
        enc = encode_lines(["abc"])
        assert bank.match(enc.u8, enc.lengths).shape == (enc.u8.shape[0], 0)

    def test_padding_rows_inert(self):
        dfas = [compile_regex_to_dfa(r".*")]  # matches everything incl empty
        bank = DfaBank(dfas)
        enc = encode_lines(["a"])  # padded to 8 rows
        cube = bank.match(enc.u8, enc.lengths)
        assert cube[0, 0]
        # padded rows run length 0 -> accept_end[start] which for .* is True;
        # the engine masks by n_lines, so values beyond row 0 are don't-care
        assert cube.shape[0] >= 8


class TestAcRunner:
    def test_device_matches_host_scan(self):
        rng = random.Random(3)
        lits = [b"err", b"OOM", b"refused", b"at ", b"x509"]
        ac = AhoCorasick(lits)
        runner = AcRunner(ac)
        lines = [
            "".join(rng.choice("erOMx509atdzfu s") for _ in range(rng.randrange(40)))
            for _ in range(64)
        ]
        enc = encode_lines(lines)
        masks = runner.scan(enc.u8, enc.lengths)
        for i, line in enumerate(lines):
            want = ac.scan(line.encode())
            got = {
                w * 32 + b
                for w in range(ac.n_words)
                for b in range(32)
                if int(masks[i, w]) >> b & 1
            }
            assert got == want, line


class TestEncode:
    def test_roundtrip(self):
        lines = ["abc", "", "x" * 300, "naïve"]
        enc = encode_lines(lines)
        assert enc.n_lines == 4
        assert bytes(enc.u8[0, :3]) == b"abc"
        assert enc.lengths[1] == 0
        assert enc.lengths[2] == 300
        assert enc.needs_host[3]  # non-ASCII
        assert not enc.needs_host[0]

    def test_overlong_flagged(self):
        enc = encode_lines(["y" * 5000], max_line_bytes=4096)
        assert enc.needs_host[0]

    def test_empty_input(self):
        enc = encode_lines([])
        assert enc.n_lines == 0 and enc.u8.shape[0] >= 8

    def test_width_alignment(self):
        # T is the scan axis (B carries the 128-lane alignment); it pads
        # to the width multiple and stays even for the pair scan
        enc = encode_lines(["abc"])
        assert enc.u8.shape[1] % 32 == 0

    def test_width_capped_tail_reflagged(self):
        # one pathological long line must not widen every row's scan:
        # width rides the 99.5% quantile and the tail re-matches on host
        lines = ["short line"] * 999 + ["x" * 2000]
        enc = encode_lines(lines)
        assert enc.u8.shape[1] <= 64
        assert enc.needs_host[999] and not enc.needs_host[0]


def test_pair_stride_equals_single_stride():
    """The precomposed pair tables must be byte-for-byte equivalent to the
    single-stride scan, including odd lengths and the T-padding step."""
    import numpy as np

    from log_parser_tpu.ops.encode import encode_lines
    from log_parser_tpu.ops.match import DfaBank
    from log_parser_tpu.patterns.regex import compile_regex_to_dfa

    rng = np.random.default_rng(7)
    regexes = ["ERROR", "time(out|r)+", "^\\s*at\\s", "[A-Z][a-z]+Exception", "x.?y"]
    dfas = [compile_regex_to_dfa(r, False) for r in regexes]
    single = DfaBank(dfas, stride=1)
    pair = DfaBank(dfas, stride=2)
    assert pair.pair_stride and not single.pair_stride

    alphabet = list("aAtxyERORtimeou rs.() \t")
    lines = [
        "".join(rng.choice(alphabet, size=int(n)))
        for n in rng.integers(0, 37, size=64)
    ]
    enc = encode_lines(lines)
    np.testing.assert_array_equal(
        single.match(enc.u8, enc.lengths), pair.match(enc.u8, enc.lengths)
    )
