"""Exact-match line cache (runtime/linecache.py + the engine/batcher
routing tier).

The contract under test: caching per-line device bit rows changes
THROUGHPUT, never semantics. Cache-on output — events, scores, frequency
snapshots — is identical to cache-off on the same stream, batched and
unbatched; a reload-epoch bump makes a stale hit structurally impossible;
an open per-pattern breaker overrides cached bits exactly like fresh
ones (per-pattern invalidation by construction); and a request served
wholly from cache never reaches the device step, so it can neither
strike quarantine nor trip the watchdog.
"""

from __future__ import annotations

import json
import threading

import numpy as np
import pytest

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.models.pod import PodFailureData
from log_parser_tpu.native.ingest import Corpus, normalize_blob
from log_parser_tpu.runtime import AnalysisEngine, faults
from log_parser_tpu.runtime.faults import FaultRegistry
from log_parser_tpu.runtime.linecache import (
    KeyInterner,
    LineCache,
    dedup_slots,
    line_key,
)
from log_parser_tpu.runtime.quarantine import QuarantineTable

from conftest import FakeClock
from helpers import make_pattern, make_pattern_set


@pytest.fixture(autouse=True)
def clean_registry():
    faults.install(None)
    yield
    faults.install(None)


def _sets():
    return [
        make_pattern_set(
            [
                make_pattern(
                    "oom",
                    regex="OutOfMemoryError",
                    confidence=0.9,
                    severity="CRITICAL",
                    secondaries=[("GC overhead", 0.3, 10)],
                    sequences=[(1.5, ["Full GC", "OutOfMemoryError"])],
                    context=(2, 2),
                ),
                make_pattern("conn", regex="Connection refused", confidence=0.7),
                make_pattern("fatal", regex="FATAL", confidence=0.8),
            ]
        )
    ]


def _pod(logs: str) -> PodFailureData:
    return PodFailureData(pod={"metadata": {"name": "lc"}}, logs=logs)


# repeat-heavy stream over a small template set, including lines that
# exercise every factor: secondary proximity, sequence chain, context
REPEAT_TEMPLATES = [
    "INFO steady-state heartbeat",
    "Full GC pause",
    "GC overhead limit reached",
    "java.lang.OutOfMemoryError: heap",
    "dial tcp 10.0.0.1: Connection refused",
    "FATAL disk controller",
]


def _stream(n_requests: int = 6, lines_per: int = 12) -> list[PodFailureData]:
    out = []
    for r in range(n_requests):
        lines = [
            REPEAT_TEMPLATES[(r * 7 + i * 3) % len(REPEAT_TEMPLATES)]
            for i in range(lines_per)
        ]
        # every third request carries one novel line (cache miss traffic)
        if r % 3 == 0:
            lines.append(f"WARN novel line {r}")
        out.append(_pod("\n".join(lines)))
    return out


def _events(result):
    return [
        (e.line_number, e.matched_pattern.id, e.score) for e in result.events
    ]


def _ctx(result):
    return [e.context for e in result.events]


def _freq_counts(engine) -> dict:
    return {k: len(v) for k, v in engine.frequency._save_state().items()}


def _cached_engine(mb: float = 4.0) -> AnalysisEngine:
    engine = AnalysisEngine(_sets(), ScoringConfig())
    engine.enable_line_cache(mb)
    return engine


# ------------------------------------------------------------ LRU mechanics


class TestLineCacheUnit:
    def test_lookup_populate_and_counters(self):
        cache = LineCache(n_columns=10, budget_bytes=1 << 20)
        k1, k2 = line_key(b"alpha"), line_key(b"beta")
        assert cache.lookup([k1, k2, k1]) == [None, None, None]
        assert cache.stats()["misses"] == 3

        row = np.zeros(10, dtype=bool)
        row[3] = True
        cache.populate([(k1, row)])
        got = cache.lookup([k1, k2])
        assert got[1] is None
        np.testing.assert_array_equal(got[0], row)
        s = cache.stats()
        assert s["hits"] == 1 and s["misses"] == 4 and s["entries"] == 1

    def test_lru_eviction_bounded_by_resident_bytes(self):
        cache = LineCache(n_columns=64, budget_bytes=2000)
        rows = [(line_key(b"line-%d" % i), np.zeros(64, dtype=bool)) for i in range(100)]
        cache.populate(rows)
        s = cache.stats()
        assert s["evictions"] > 0
        assert s["residentBytes"] <= 2000
        assert s["entries"] < 100
        # the survivors are the most recently inserted (LRU order)
        assert cache.lookup([rows[-1][0]])[0] is not None
        assert cache.lookup([rows[0][0]])[0] is None

    def test_flush_clears_and_rebinds_columns(self):
        cache = LineCache(n_columns=16, budget_bytes=1 << 20)
        cache.populate([(line_key(b"x"), np.ones(16, dtype=bool))])
        cache.flush(n_columns=24)
        s = cache.stats()
        assert s["entries"] == 0
        assert s["residentBytes"] == 0
        assert s["epochFlushes"] == 1
        assert cache.n_columns == 24
        assert cache.lookup([line_key(b"x")]) == [None]


# ----------------------------------------------------------- exact parity


class TestParity:
    def test_unbatched_stream_parity(self):
        """The same request stream through a cache-off and a cache-on
        engine: identical events, contexts, scores (exact), and frequency
        snapshot counts — including requests served entirely from cache."""
        stream = _stream()
        off = AnalysisEngine(_sets(), ScoringConfig())
        on = _cached_engine()
        for data in stream:
            r_off = off.analyze_pipelined(data)
            r_on = on.analyze_pipelined(data)
            assert _events(r_off) == _events(r_on)
            assert _ctx(r_off) == _ctx(r_on)
        assert _freq_counts(off) == _freq_counts(on)
        s = on.line_cache.stats()
        assert s["hits"] > 0 and s["residualRows"] > 0
        assert on.fallback_count == 0

    def test_all_hit_request_skips_device_entirely(self):
        engine = _cached_engine()
        data = _pod("\n".join(REPEAT_TEMPLATES))
        engine.analyze_pipelined(data)
        before = engine.line_cache.stats()
        engine.analyze_pipelined(data)
        after = engine.line_cache.stats()
        assert after["residualRows"] == before["residualRows"]
        assert after["hits"] == before["hits"] + len(REPEAT_TEMPLATES)
        # no device phase in the trace: the request never dispatched
        assert "device" not in engine.last_trace.as_dict()

    def test_in_request_dedup_one_device_row_per_unique_line(self):
        engine = _cached_engine()
        logs = "\n".join(["java.lang.OutOfMemoryError: heap"] * 9 + ["INFO x"] * 3)
        engine.analyze_pipelined(_pod(logs))
        s = engine.line_cache.stats()
        assert s["residualRows"] == 2  # 12 lines, 2 unique
        assert s["dedupFanout"] == 10

    def test_needs_host_lines_cached_request_parity(self):
        """Non-ASCII lines (python-fallback encode → needs_host) ride the
        override splice: parity holds and they are never populated — a
        repeat still pays a residual row for them."""
        logs = (
            "INFO café latte ☃\n"
            "java.lang.OutOfMemoryError: heap\n"
            "INFO café latte ☃"
        )
        off = AnalysisEngine(_sets(), ScoringConfig())
        on = _cached_engine()
        assert _events(off.analyze_pipelined(_pod(logs))) == _events(
            on.analyze_pipelined(_pod(logs))
        )
        first = on.line_cache.stats()["residualRows"]
        assert _events(off.analyze_pipelined(_pod(logs))) == _events(
            on.analyze_pipelined(_pod(logs))
        )
        # the ASCII line is a hit; the non-ASCII line misses again
        assert on.line_cache.stats()["residualRows"] > first

    def test_empty_and_trivial_logs(self):
        off = AnalysisEngine(_sets(), ScoringConfig())
        on = _cached_engine()
        for logs in ("", "\n", "INFO only"):
            assert _events(off.analyze_pipelined(_pod(logs))) == _events(
                on.analyze_pipelined(_pod(logs))
            )

    def test_batched_stream_parity(self):
        """Full-batch flushes through the cached path == the same stream
        served serially by a cache-off engine — exact equality, with the
        cross-flush dedup visible in the counters."""
        stream = _stream(n_requests=4, lines_per=8)
        serial = AnalysisEngine(_sets(), ScoringConfig())
        expected = [_events(serial.analyze_pipelined(d)) for d in stream]

        engine = _cached_engine()
        engine.enable_batching(wait_ms=5000.0, batch_max=len(stream))
        try:
            pend = [engine.batcher._enqueue(d, None) for d in stream]
            for p in pend:
                assert p.done.wait(60.0)
            for p, want in zip(pend, expected):
                assert p.error is None
                assert _events(p.result) == want
            assert _freq_counts(serial) == _freq_counts(engine)
            s = engine.line_cache.stats()
            # cross-flush dedup: way fewer device rows than total lines
            assert 0 < s["residualRows"] <= len(REPEAT_TEMPLATES) + 4
            assert s["dedupFanout"] > 0
            assert engine.fallback_count == 0
        finally:
            engine.batcher.close()

    def test_batched_all_hit_flush_zero_device_rows(self):
        engine = _cached_engine()
        engine.enable_batching(wait_ms=5000.0, batch_max=2)
        data = _pod("\n".join(REPEAT_TEMPLATES[:4]))
        try:
            engine.analyze_batched(data)  # populates (single-item flush)
            base = engine.line_cache.stats()["residualRows"]
            pend = [engine.batcher._enqueue(data, None) for _ in range(2)]
            for p in pend:
                assert p.done.wait(60.0)
                assert p.error is None
            assert engine.line_cache.stats()["residualRows"] == base
        finally:
            engine.batcher.close()


# ----------------------------------------------------- epoch invalidation


class TestInvalidation:
    def test_reload_epoch_flush_makes_stale_hit_impossible(self):
        """Swap the library under a warm cache: the new bank's results
        must be what a cold cache-off engine produces — no bit row from
        the old library may survive the swap."""
        engine = _cached_engine()
        logs = "INFO boot\njava.lang.OutOfMemoryError: heap\nNo space left on device"
        engine.analyze_pipelined(_pod(logs))  # warm: oom matches
        assert engine.line_cache.stats()["entries"] > 0

        v2 = [
            make_pattern_set(
                [
                    # same id, CHANGED regex: a stale cached row would
                    # keep matching the old semantics
                    make_pattern("oom", regex="No space left on device",
                                 confidence=0.9, severity="CRITICAL"),
                ],
                "lib-v2",
            )
        ]
        source = AnalysisEngine(v2, ScoringConfig())
        engine.apply_library(source)
        s = engine.line_cache.stats()
        assert s["epochFlushes"] == 1
        assert s["entries"] == 0

        fresh = AnalysisEngine(v2, ScoringConfig())
        r_on = engine.analyze_pipelined(_pod(logs))
        r_off = fresh.analyze_pipelined(_pod(logs))
        assert _events(r_on) == _events(r_off)
        # the old regex must NOT fire: line 3 matches, line 2 does not
        assert [e[0] for e in _events(r_on)] == [3]

    def test_breaker_trip_overrides_cached_bits_per_pattern(self):
        """Per-pattern invalidation by construction: an OPEN breaker's
        columns are re-evaluated from the host regex over cached rows
        too. Corrupt one pattern's cached bit and trip its breaker — the
        corruption is contained the moment the breaker opens, while the
        OTHER patterns' cached bits keep serving."""
        engine = _cached_engine()
        logs = "java.lang.OutOfMemoryError: heap\ndial tcp: Connection refused"
        want = _events(engine.analyze_pipelined(_pod(logs)))
        assert [e[1] for e in want] == ["oom", "conn"]

        # simulate a divergent device result resident in the cache:
        # clear the oom primary bit of the cached OOM line
        cache = engine.line_cache
        key = line_key(b"java.lang.OutOfMemoryError: heap")
        oom_pat = [p.id for p in engine.bank.patterns].index("oom")
        oom_col = int(engine.bank.primary_columns[oom_pat])
        with cache.lock:
            packed = np.frombuffer(cache._entries[key], dtype=np.uint8).copy()
            row = np.unpackbits(packed, count=cache.n_columns).astype(bool)
            row[oom_col] = False
            cache._entries[key] = np.packbits(row).tobytes()

        # corrupted bits ARE served (proves the hit path is live)
        broken = _events(engine.analyze_pipelined(_pod(logs)))
        assert [e[1] for e in broken] == ["conn"]

        # breaker trip: oom's columns now come from the exact host regex
        # on every request — cached rows included
        engine.breakers.trip("oom")
        healed = _events(engine.analyze_pipelined(_pod(logs)))
        assert [(ln, pid) for ln, pid, _ in healed] == [
            (ln, pid) for ln, pid, _ in want
        ]
        # conn kept serving from cache throughout
        assert engine.line_cache.stats()["hits"] > 0


# ------------------------------------------------- quarantine interaction


class TestQuarantine:
    def _engine(self):
        engine = _cached_engine()
        engine.fallback_to_golden = True
        engine.quarantine = QuarantineTable(
            strikes=1, ttl_s=600.0, clock=FakeClock()
        )
        return engine

    def test_cache_hits_never_strike(self):
        """Arm a keyed poison fault AFTER the cache is warm: the repeat
        request is served entirely from cache, never reaches the device
        step, and the fault's fired counter pins that. A novel request
        sharing the key DOES pay a residual and strikes."""
        engine = self._engine()
        logs = "INFO boot\njava.lang.OutOfMemoryError: heap"
        want = _events(engine.analyze_pipelined(_pod(logs)))  # warm, healthy

        reg = FaultRegistry.parse("quarantine_raise@match=INFO boot")
        faults.install(reg)
        repeat = engine.analyze_pipelined(_pod(logs))
        assert _events(repeat) == want
        assert reg.specs[0].fired == 0  # device step never entered
        assert engine.fallback_count == 0
        assert engine.quarantine.stats()["strikes"] == 0

        # novel content with the same fault key: residual dispatch fires
        novel = engine.analyze_pipelined(_pod(logs + "\nWARN never seen"))
        assert novel.events  # served from golden fallback
        assert reg.specs[0].fired == 1
        assert engine.fallback_count == 1
        assert engine.quarantine.stats()["strikes"] == 1

    def test_batched_cached_flush_poison_falls_back_to_bisection(self):
        """A poisoned residual in a cached flush retries wholesale on the
        uncached path, where bisection isolates the poison row — healthy
        batchmates stay on-device, only the culprit strikes."""
        engine = self._engine()
        engine.enable_batching(wait_ms=5000.0, batch_max=2)
        poison = _pod("POISON-PILL marker\nINFO filler")
        healthy = _pod("dial tcp: Connection refused\nINFO filler")
        faults.install(FaultRegistry.parse("quarantine_raise@match=POISON-PILL"))
        try:
            pend = [
                engine.batcher._enqueue(d, None) for d in (poison, healthy)
            ]
            for p in pend:
                assert p.done.wait(60.0)
            assert pend[0].error is None and pend[1].error is None
            assert [e[1] for e in _events(pend[1].result)] == ["conn"]
            assert engine.fallback_count == 1  # poison only
            assert engine.quarantine.stats()["quarantined"] == 1
            assert engine.batcher.stats()["bisects"] >= 1
        finally:
            engine.batcher.close()


# ------------------------------------------------------- one hash path


class TestKeyStability:
    def test_key_material_is_the_ingest_normalized_blob(self):
        """``line_key_bytes`` slices the SAME normalization the quarantine
        fingerprint hashes (normalize_blob) — no second normalization
        pass, surrogates and all."""
        logs = "plain ascii\ncafé ☃\nbad \ud800 surrogate"
        corpus = Corpus(logs)
        joined = b"\n".join(
            corpus.line_key_bytes(i) for i in range(corpus.n_lines)
        )
        assert joined == normalize_blob(logs)

    def test_key_stable_across_http_framed_grpc_ingest(self):
        """One payload through all three transport codecs: HTTP JSON,
        the framed shim's protobuf Envelope, and the gRPC ParseRequest —
        every decode yields byte-identical per-line cache keys."""
        from log_parser_tpu.shim import logparser_pb2 as pb

        logs = "INFO café\njava.lang.OutOfMemoryError: heap\n☃ snow"

        # HTTP: JSON body round-trip (serve/http.py reads payload["logs"])
        http_logs = json.loads(json.dumps({"logs": logs}))["logs"]
        # gRPC: ParseRequest proto round-trip
        grpc_logs = pb.ParseRequest.FromString(
            pb.ParseRequest(logs=logs).SerializeToString()
        ).logs
        # framed shim: Envelope-wrapped ParseRequest round-trip
        env = pb.Envelope(
            method="Parse",
            payload=pb.ParseRequest(logs=logs).SerializeToString(),
        )
        framed_logs = pb.ParseRequest.FromString(
            pb.Envelope.FromString(env.SerializeToString()).payload
        ).logs

        keys = []
        for decoded in (http_logs, grpc_logs, framed_logs):
            corpus = Corpus(decoded)
            keys.append(
                [
                    line_key(corpus.line_key_bytes(i))
                    for i in range(corpus.n_lines)
                ]
            )
        assert keys[0] == keys[1] == keys[2]

    def test_python_fallback_keys_match_native_blob_slices(self, monkeypatch):
        """The python-fallback encode produces the same key bytes as the
        native blob slices, so a warm cache survives either ingest path."""
        import log_parser_tpu.native.ingest as ingest_mod

        logs = "INFO a\njava.lang.OutOfMemoryError: heap\nINFO b"
        native_corpus = Corpus(logs)
        monkeypatch.setattr(ingest_mod, "get_lib", lambda: None)
        fallback_corpus = Corpus(logs)
        # the vectorized fallback is blob-backed like the native path;
        # only the lone-surrogate scalar path keeps materialized strings
        assert fallback_corpus._blob is not None
        for i in range(native_corpus.n_lines):
            assert native_corpus.line_key_bytes(i) == fallback_corpus.line_key_bytes(i)
        # surrogate corpora take the scalar path and still agree per line
        scalar_corpus = Corpus("INFO a\n\ud800INFO b")
        assert scalar_corpus._lines is not None
        assert scalar_corpus.line_key_bytes(0) == b"INFO a"


# ----------------------------------------------------------- concurrency


def test_concurrent_cached_requests_thread_safe():
    """Pipelined requests sharing one cache race lookups against
    populates; results must stay per-request correct."""
    engine = _cached_engine()
    stream = _stream(n_requests=8, lines_per=6)
    serial = AnalysisEngine(_sets(), ScoringConfig())
    expected = [_events(serial.analyze_pipelined(d)) for d in stream]

    results: list = [None] * len(stream)

    def worker(j):
        results[j] = _events(engine.analyze_pipelined(stream[j]))

    threads = [
        threading.Thread(target=worker, args=(j,)) for j in range(len(stream))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # per-request events and scores are frequency-independent here only
    # for line/pattern identity; frequency-coupled scores may differ by
    # arrival order, so compare line/pattern sets per request
    for got, want in zip(results, expected):
        assert [(ln, pid) for ln, pid, _ in got] == [
            (ln, pid) for ln, pid, _ in want
        ]
    assert _freq_counts(engine) == _freq_counts(serial)


# ------------------------------------------- two-level keying (interner)


class TestKeyInterner:
    """dedup_slots with an interner must return digests bit-identical to
    the blake2b path — cold, warm, across corpus shapes, past the
    512-byte interning ceiling, and through eviction."""

    def _parity(self, corpus, interner):
        ref = dedup_slots(corpus)
        got = dedup_slots(corpus, interner=interner)
        assert ref is not None and got is not None
        np.testing.assert_array_equal(ref[0], got[0])
        np.testing.assert_array_equal(ref[1], got[1])
        assert ref[2] == got[2]
        np.testing.assert_array_equal(ref[3], got[3])

    def test_cold_and_warm_parity(self):
        lines = [
            REPEAT_TEMPLATES[(i * 5) % len(REPEAT_TEMPLATES)]
            for i in range(200)
        ] + [f"novel line {i}" for i in range(40)]
        corpus = Corpus("\n".join(lines))
        interner = KeyInterner()
        self._parity(corpus, interner)  # cold: every unique line inserts
        cold = interner.stats()
        assert cold["inserts"] > 0 and cold["collisions"] == 0
        self._parity(corpus, interner)  # warm: pure probe hits
        warm = interner.stats()
        assert warm["inserts"] == cold["inserts"]
        assert warm["probeHits"] >= cold["inserts"]
        # a different corpus shape (other width bucket) stays exact
        self._parity(Corpus("\n".join(lines + ["x" * 200])), interner)

    def test_long_lines_stay_on_blake2b(self):
        long = "L" + "x" * 600  # past the 64-word interning ceiling
        corpus = Corpus("\n".join(["short line", long, "short line", long]))
        interner = KeyInterner()
        self._parity(corpus, interner)
        self._parity(corpus, interner)
        # the long line is never interned — it pays blake2b every pass
        assert interner.stats()["entries"] <= 1

    def test_truncated_rows_never_intern(self):
        """Regression: under a narrow device width (< the 512-byte
        interning ceiling), rows longer than the width are TRUNCATED in
        the key matrix. Two distinct long lines sharing a width prefix
        and a byte length must not share a digest — the second warm
        pass used to probe-hit the first line's entry and serve its
        blake2b key (and therefore its cached match bits)."""
        shorts = [f"short {i:04d}" for i in range(600)]
        prefix = "P" * 120
        a = prefix + "A" * 40
        b = prefix + "B" * 40  # differs only past the device width
        corpus = Corpus("\n".join(shorts + [a, b]))
        width = corpus.encoded.u8.shape[1]
        assert width < len(a), "corpus must exercise the truncated branch"
        interner = KeyInterner()
        self._parity(corpus, interner)  # cold: both pay blake2b
        self._parity(corpus, interner)  # warm: B must NOT reuse A's key
        keys = dedup_slots(corpus, interner=interner)[2]
        assert keys[-1] != keys[-2]
        assert keys[-2] == line_key(a.encode())
        assert keys[-1] == line_key(b.encode())

    def test_eviction_keeps_parity(self):
        # a budget of ~100 entries against 300 unique lines: every pass
        # evicts, digests stay exact throughout
        from log_parser_tpu.runtime.linecache import _INTERN_ENTRY_BYTES

        interner = KeyInterner(budget_bytes=100 * _INTERN_ENTRY_BYTES)
        for r in range(3):
            lines = [f"round {r} line {i}" for i in range(300)]
            self._parity(Corpus("\n".join(lines)), interner)
        s = interner.stats()
        assert s["evictions"] > 0
        assert s["entries"] <= interner.max_entries

    def test_engine_cache_path_uses_interner(self):
        engine = _cached_engine()
        data = _pod("\n".join(REPEAT_TEMPLATES))
        engine.analyze_pipelined(data)
        engine.analyze_pipelined(data)
        s = engine.key_interner.stats()
        assert s["inserts"] > 0
        assert s["probeHits"] > 0
