"""Warm-standby replication (runtime/replicate.py): the fenced-failover
single-owner contract.

The anchor is the crash matrix: a simulated ``kill -9`` (the
``crash_after`` hook — fsync'd protocol record, no cleanup) at every
replication/promotion journal-record boundary (``epoch`` adoption,
``promote``, ``demote``) × fresh-process ``recover()`` must converge to
exactly one owner per tenant, and the promoted standby's frequency
state must be bit-identical to an acked-prefix replay control under a
frozen clock (the PR 16 technique). Around it: WAL shipping (barrier
seed, incremental whole-frame batches, rotation fallback, offset
re-sync, backoff), the receiver's reject-whole-batch rule for torn and
CRC-corrupt frames (the satellite mirror of the WAL torn-tail tests),
the registry-wide fence (default tenant included), and the
FailoverSupervisor's consecutive-failure promotion.
"""

from __future__ import annotations

import base64
import time
import zlib

import pytest

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.models.pod import PodFailureData
from log_parser_tpu.patterns import load_pattern_directory
from log_parser_tpu.runtime import AnalysisEngine
from log_parser_tpu.runtime.journal import _FRAME
from log_parser_tpu.runtime.replicate import (
    FailoverSupervisor,
    LocalReplicaTarget,
    PROTOCOL_RECORDS,
    ReplicaCrash,
    ReplicationError,
    Replicator,
)
from log_parser_tpu.runtime.tenancy import (
    DEFAULT_TENANT,
    TenantForwarded,
    TenantRegistry,
)

from helpers import make_pattern, make_pattern_set

ACME_YAML = """
metadata:
  library_id: acme-lib
patterns:
  - id: oom
    name: Out of memory
    severity: CRITICAL
    primary_pattern:
      regex: OutOfMemoryError
      confidence: 0.9
  - id: err
    name: Errors
    severity: LOW
    primary_pattern:
      regex: "\\\\bERROR\\\\b"
      confidence: 0.5
"""

TRAFFIC = [
    "INFO boot\njava.lang.OutOfMemoryError: heap\nan ERROR here",
    "ERROR twice\nERROR again\nOutOfMemoryError",
    "nothing to see",
    "java.lang.OutOfMemoryError: metaspace\nERROR",
    "INFO a\nINFO b\nan ERROR here",
]


class FakeClock:
    """Shared frozen clock: integer-valued steps keep the age/timestamp
    round trips float-exact, which bit-identical parity depends on."""

    def __init__(self):
        self.t = 1000.0

    def __call__(self) -> float:
        return self.t


@pytest.fixture()
def root(tmp_path):
    d = tmp_path / "tenants" / "acme"
    d.mkdir(parents=True)
    (d / "lib.yaml").write_text(ACME_YAML)
    return str(tmp_path / "tenants")


def _default_engine(clk) -> AnalysisEngine:
    return AnalysisEngine(
        [make_pattern_set([make_pattern("base", regex="BASE")], "base-lib")],
        ScoringConfig(),
        clock=clk,
    )


def _data(blob: str) -> PodFailureData:
    return PodFailureData(pod={"metadata": {"name": "t"}}, logs=blob)


def _node(tmp_path, root, name, clk, *, peer=None, target=None,
          crash_after=None):
    """One 'process': a journaled registry + its Replicator over a
    per-side state dir. Re-calling with the same name over the same
    dirs is the restart half of a kill -9 simulation."""
    state = tmp_path / name
    state.mkdir(exist_ok=True)

    def setup(eng, tid):
        # WAL wall-time frozen to the shared clock: parity across a
        # simulated restart and across the replication channel needs
        # every side to stamp records at the same instant
        eng.attach_journal(str(state / "wal" / tid), wall=clk)

    reg = TenantRegistry(
        _default_engine(clk), root=root, clock=clk, engine_setup=setup
    )
    rep = Replicator(
        reg, state_root=str(state), node_url=f"local://{name}",
        peer_url=peer, target=target, clock=clk, wall=clk,
        crash_after=crash_after,
    )
    return reg, rep


def _pair(tmp_path, root, clk, *, standby_crash=None, primary_crash=None):
    """primary 'a' shipping to standby 'b' (in-process target)."""
    reg_b, rep_b = _node(
        tmp_path, root, "b", clk, peer="local://a",
        crash_after=standby_crash,
    )
    rep_b.recover()  # installs the boot fence
    target = LocalReplicaTarget(rep_b, url="local://b")
    reg_a, rep_a = _node(
        tmp_path, root, "a", clk, target=target, crash_after=primary_crash
    )
    rep_a.recover()
    return (reg_a, rep_a), (reg_b, rep_b), target


def _serve(reg, rep, tenant, blob):
    ctx = reg.resolve(tenant)
    try:
        ctx.engine.analyze(_data(blob))
    finally:
        ctx.unpin()


def _sender(reg, rep, tenant="acme"):
    ctx = reg.resolve(tenant)
    sender = rep.attach_sender(tenant, ctx.engine)
    ctx.unpin()
    assert sender is not None
    return sender


def _control(tmp_path, root, clk, prefix, step=lambda c: None):
    """Unreplicated control: a fresh acme engine fed ``prefix`` at the
    same clock instants (the caller's ``step`` mirrors its stepping)."""
    eng = AnalysisEngine(
        load_pattern_directory(f"{root}/acme"), ScoringConfig(), clock=clk
    )
    for blob in prefix:
        eng.analyze(_data(blob))
        step(clk)
    return eng


def _snapshot(reg, tenant="acme"):
    ctx = reg.resolve(tenant, ignore_forward=True)
    try:
        with ctx.engine.state_lock:
            return ctx.engine.frequency.snapshot()
    finally:
        ctx.unpin()


# ------------------------------------------------------------- shipping


class TestShipping:
    def test_seed_then_incremental_batches_apply(self, root, tmp_path):
        clk = FakeClock()
        (reg_a, rep_a), (reg_b, rep_b), _ = _pair(tmp_path, root, clk)
        sender = _sender(reg_a, rep_a)
        _serve(reg_a, rep_a, "acme", TRAFFIC[0])
        assert sender.pump() == "seeded"
        clk.t += 1.0
        _serve(reg_a, rep_a, "acme", TRAFFIC[1])
        assert sender.pump() == "shipped"
        assert sender.pump() == "idle"
        assert rep_b.stats()["appliedBatches"] == 2
        # the standby's warm bank equals the primary's live state
        assert _snapshot(reg_b) == _snapshot(reg_a)

    def test_standby_state_is_durable_in_its_own_wal(self, root, tmp_path):
        clk = FakeClock()
        (reg_a, rep_a), (reg_b, rep_b), _ = _pair(tmp_path, root, clk)
        sender = _sender(reg_a, rep_a)
        _serve(reg_a, rep_a, "acme", TRAFFIC[0])
        assert sender.pump() == "seeded"
        before = _snapshot(reg_b)
        assert before  # non-trivial state actually shipped
        # standby process dies (no clean close) and reboots: the fed
        # state must come back from the standby's OWN journal
        reg_b2, rep_b2 = _node(tmp_path, root, "b", clk, peer="local://a")
        rep_b2.recover()
        assert _snapshot(reg_b2) == before

    def test_rotation_falls_back_to_fresh_barrier(self, root, tmp_path):
        clk = FakeClock()
        (reg_a, rep_a), (reg_b, rep_b), _ = _pair(tmp_path, root, clk)
        sender = _sender(reg_a, rep_a)
        _serve(reg_a, rep_a, "acme", TRAFFIC[0])
        assert sender.pump() == "seeded"
        ctx = reg_a.resolve("acme")
        try:
            journal = ctx.engine.journal
            _serve(reg_a, rep_a, "acme", TRAFFIC[1])
            # rotate: snapshot + truncate bumps the WAL epoch and drops
            # the frames the sender was about to ship
            assert journal.snapshot_now()
        finally:
            ctx.unpin()
        assert sender.pump() == "seeded"
        assert sender.reseeds == 2
        assert _snapshot(reg_b) == _snapshot(reg_a)

    def test_offset_mismatch_resyncs_from_receiver_position(
        self, root, tmp_path
    ):
        clk = FakeClock()
        (reg_a, rep_a), (reg_b, rep_b), _ = _pair(tmp_path, root, clk)
        sender = _sender(reg_a, rep_a)
        _serve(reg_a, rep_a, "acme", TRAFFIC[0])
        assert sender.pump() == "seeded"
        _serve(reg_a, rep_a, "acme", TRAFFIC[1])
        # the standby process restarts: its in-memory feed position is
        # gone (acked=0, walEpoch=-1); the sender's next incremental
        # batch is refused with the receiver's position and the sender
        # re-syncs via a fresh barrier
        reg_b2, rep_b2 = _node(tmp_path, root, "b", clk, peer="local://a")
        rep_b2.recover()
        sender.target = LocalReplicaTarget(rep_b2, url="local://b")
        assert sender.pump() == "resync"
        assert sender.pump() == "seeded"
        assert sender.resyncs == 1
        assert _snapshot(reg_b2) == _snapshot(reg_a)

    def test_misaligned_resume_offset_reseeds(self, root, tmp_path):
        clk = FakeClock()
        (reg_a, rep_a), (reg_b, rep_b), _ = _pair(tmp_path, root, clk)
        sender = _sender(reg_a, rep_a)
        _serve(reg_a, rep_a, "acme", TRAFFIC[0])
        assert sender.pump() == "seeded"
        _serve(reg_a, rep_a, "acme", TRAFFIC[1])
        # corrupt ack bookkeeping: the resume point lands mid-frame, so
        # no incremental batch can ever parse — must not wedge on idle
        sender.acked_offset = max(0, sender.acked_offset - 3)
        assert sender.pump() == "seeded"
        assert _snapshot(reg_b) == _snapshot(reg_a)

    def test_unreachable_standby_backs_off_with_jitter(self, root, tmp_path):
        clk = FakeClock()
        (reg_a, rep_a), (reg_b, rep_b), target = _pair(tmp_path, root, clk)
        sender = _sender(reg_a, rep_a)

        class Down:
            url = "local://b"

            def feed(self, body):
                raise ReplicationError("standby unreachable", status=0)

        sender.target = Down()
        _serve(reg_a, rep_a, "acme", TRAFFIC[0])
        assert sender.pump() == "error"
        assert sender.pump() == "backoff"
        assert 0.0 < sender.backoff_s() <= 15.0
        # reconnect resumes — and because nothing was ever acked, the
        # resume is the fresh-snapshot path
        sender.target = target
        clk.t += 60.0
        assert sender.pump() == "seeded"
        assert sender.send_errors == 1
        assert _snapshot(reg_b) == _snapshot(reg_a)

    def test_lag_gauges_and_metrics_render(self, root, tmp_path):
        clk = FakeClock()
        (reg_a, rep_a), (reg_b, rep_b), _ = _pair(tmp_path, root, clk)
        sender = _sender(reg_a, rep_a)
        _serve(reg_a, rep_a, "acme", TRAFFIC[0])
        assert sender.pump() == "seeded"
        clk.t += 5.0
        _serve(reg_a, rep_a, "acme", TRAFFIC[1])
        clk.t += 3.0
        # peek at the lag without shipping: wedge the target
        real_target, sender.target = sender.target, None
        try:
            sender.pump()
        except AttributeError:
            pass
        finally:
            sender.target = real_target
        stats = rep_a.stats()
        assert stats["lagBytes"] > 0
        assert stats["lagRecords"] > 0
        assert stats["lagSeconds"] >= 3.0
        text = reg_a.default_engine.obs.registry.render()
        assert "logparser_replication_lag_bytes" in text
        assert "logparser_replication_lag_records" in text
        assert "logparser_replication_epoch" in text


# ----------------------------------------------- receiver verification


class TestReceiverIntegrity:
    """Satellite: a torn or CRC-corrupt frame mid-stream must reject the
    batch WHOLE, keep the acked offset, and force a re-send — a partial
    record is never applied."""

    def _shipped_body(self, reg_a, rep_a, sender):
        """A valid incremental feed body, captured without sending."""
        ctx = reg_a.resolve("acme")
        try:
            journal = ctx.engine.journal
            epoch, size, data = journal.wal_feed(sender.acked_offset, 1 << 20)
        finally:
            ctx.unpin()
        assert data, "test needs pending WAL frames"
        return {
            "tenant": "acme",
            "epoch": rep_a.epoch,
            "walEpoch": epoch,
            "offset": sender.acked_offset,
            "frames": base64.b64encode(data).decode("ascii"),
            "barrier": None,
            "wall": rep_a.wall(),
        }

    @pytest.fixture()
    def fed_pair(self, root, tmp_path):
        clk = FakeClock()
        (reg_a, rep_a), (reg_b, rep_b), _ = _pair(tmp_path, root, clk)
        sender = _sender(reg_a, rep_a)
        _serve(reg_a, rep_a, "acme", TRAFFIC[0])
        assert sender.pump() == "seeded"
        clk.t += 1.0
        _serve(reg_a, rep_a, "acme", TRAFFIC[1])
        return clk, (reg_a, rep_a, sender), (reg_b, rep_b)

    def _reject_roundtrip(self, corrupt, fed_pair):
        clk, (reg_a, rep_a, sender), (reg_b, rep_b) = fed_pair
        body = self._shipped_body(reg_a, rep_a, sender)
        raw = base64.b64decode(body["frames"])
        acked_before = rep_b.stats()["feeds"]["acme"]["acked"]
        state_before = _snapshot(reg_b)
        bad = dict(body)
        bad["frames"] = base64.b64encode(corrupt(raw)).decode("ascii")
        with pytest.raises(ReplicationError) as exc:
            rep_b.feed(bad)
        assert exc.value.status == 409
        assert exc.value.extra["acked"] == acked_before
        # NOTHING applied — not even the whole frames before the bad one
        assert rep_b.stats()["feeds"]["acme"]["acked"] == acked_before
        assert _snapshot(reg_b) == state_before
        assert rep_b.stats()["rejectedBatches"] == 1
        # the sender re-sends the intact batch and converges
        assert sender.pump() == "shipped"
        assert _snapshot(reg_b) == _snapshot(reg_a)

    def test_torn_final_frame_rejects_batch(self, fed_pair):
        self._reject_roundtrip(lambda raw: raw[:-3], fed_pair)

    def test_crc_corrupt_frame_mid_stream_rejects_batch(self, fed_pair):
        def flip(raw):
            # corrupt one payload byte of the FIRST frame: every later
            # frame in the batch is intact, and must still not apply
            length, _crc = _FRAME.unpack_from(raw, 0)
            assert _FRAME.size + length < len(raw), "need 2+ frames"
            i = _FRAME.size
            return raw[:i] + bytes([raw[i] ^ 0xFF]) + raw[i + 1:]

        self._reject_roundtrip(flip, fed_pair)

    def test_non_json_payload_rejects_batch(self, fed_pair):
        def forge(raw):
            payload = b"\xff{not json"
            frame = _FRAME.pack(len(payload), zlib.crc32(payload)) + payload
            return raw + frame

        self._reject_roundtrip(forge, fed_pair)

    def test_stale_epoch_feed_is_refused_with_owner(self, fed_pair):
        clk, (reg_a, rep_a, sender), (reg_b, rep_b) = fed_pair
        body = self._shipped_body(reg_a, rep_a, sender)
        rep_b.promote(reason="test")
        body["epoch"] = 0
        with pytest.raises(ReplicationError) as exc:
            rep_b.feed(body)
        assert exc.value.status == 409
        assert exc.value.extra["epoch"] == rep_b.epoch == 1
        assert exc.value.extra["location"] == "local://b"


# ------------------------------------------------------------- fencing


class TestFence:
    def test_standby_fences_every_tenant_including_default(
        self, root, tmp_path
    ):
        clk = FakeClock()
        (reg_a, rep_a), (reg_b, rep_b), _ = _pair(tmp_path, root, clk)
        for tid in (None, DEFAULT_TENANT, "acme"):
            with pytest.raises(TenantForwarded) as exc:
                reg_b.resolve(tid)
            assert exc.value.status == 307
            assert exc.value.location == "local://a"
        assert reg_b.stats()["fenced"] == 3
        assert reg_b.stats()["fence"] == "local://a"
        # the primary is NOT fenced
        reg_a.resolve("acme").unpin()

    def test_promote_lifts_fence_and_serves(self, root, tmp_path):
        clk = FakeClock()
        (reg_a, rep_a), (reg_b, rep_b), _ = _pair(tmp_path, root, clk)
        sender = _sender(reg_a, rep_a)
        _serve(reg_a, rep_a, "acme", TRAFFIC[0])
        assert sender.pump() == "seeded"
        summary = rep_b.promote(reason="drill")
        assert summary["status"] == "promoted"
        assert summary["epoch"] == 1
        assert "acme" in summary["tenants"]
        # promoted standby serves everything, fence gone
        reg_b.resolve(None).unpin()
        reg_b.resolve("acme").unpin()
        # idempotent second promote
        assert rep_b.promote(reason="again")["status"] == "primary"
        assert rep_b.epoch == 1

    def test_stale_primary_demotes_and_forwards(self, root, tmp_path):
        clk = FakeClock()
        (reg_a, rep_a), (reg_b, rep_b), _ = _pair(tmp_path, root, clk)
        sender = _sender(reg_a, rep_a)
        _serve(reg_a, rep_a, "acme", TRAFFIC[0])
        assert sender.pump() == "seeded"
        rep_b.promote(reason="partition")
        # the partition heals: the stale primary's next ship sees the
        # higher epoch in the refusal and steps down
        _serve(reg_a, rep_a, "acme", TRAFFIC[1])
        assert sender.pump() == "demoted"
        assert rep_a.role == "standby"
        assert rep_a.epoch == 1
        with pytest.raises(TenantForwarded) as exc:
            reg_a.resolve("acme")
        assert exc.value.location == "local://b"
        with pytest.raises(TenantForwarded):
            reg_a.resolve(None)
        # exactly one owner: b serves, a forwards
        reg_b.resolve("acme").unpin()


# -------------------------------------------------------- crash matrix


def _step(clk):
    clk.t += 1.0


class TestCrashMatrix:
    """kill -9 at every protocol journal-record boundary × fresh-process
    recover() → exactly one owner, state bit-identical to the
    acked-prefix control."""

    def _shipped_prefix(self, tmp_path, root, clk, n_acked, **pair_kw):
        (reg_a, rep_a), (reg_b, rep_b), target = _pair(
            tmp_path, root, clk, **pair_kw
        )
        sender = _sender(reg_a, rep_a)
        for i, blob in enumerate(TRAFFIC[:n_acked]):
            _serve(reg_a, rep_a, "acme", blob)
            outcome = sender.pump()
            # "idle" happens when the blob matched nothing (no new
            # WAL frames) — still a fully acked position
            assert outcome in ("seeded", "shipped", "idle")
            _step(clk)
        # un-acked tail: served on the primary but never shipped — the
        # standby must NOT know it (TRAFFIC[0] always produces frames)
        _serve(reg_a, rep_a, "acme", TRAFFIC[0])
        return (reg_a, rep_a, sender), (reg_b, rep_b), target

    @pytest.mark.parametrize("n_acked", [1, 2, 4])
    def test_promoted_state_equals_acked_prefix_control(
        self, root, tmp_path, n_acked
    ):
        clk = FakeClock()
        (reg_a, rep_a, _s), (reg_b, rep_b), _t = self._shipped_prefix(
            tmp_path, root, clk, n_acked
        )
        # primary dies (kill -9: nothing folded); the standby promotes
        rep_b.promote(reason="health")
        control_clk = FakeClock()
        control = _control(
            tmp_path, root, control_clk, TRAFFIC[:n_acked], step=_step
        )
        assert control_clk.t == clk.t
        assert _snapshot(reg_b) == control.frequency.snapshot()
        # and the promoted standby's scoring matches the control's
        ctx = reg_b.resolve("acme")
        try:
            got = ctx.engine.analyze(_data(TRAFFIC[4])).to_dict(drop_none=True)
        finally:
            ctx.unpin()
        want = control.analyze(_data(TRAFFIC[4])).to_dict(drop_none=True)
        assert [e["score"] for e in got.get("events", [])] == [
            e["score"] for e in want.get("events", [])
        ]

    def test_crash_after_promote_record_recovers_promoted(
        self, root, tmp_path
    ):
        clk = FakeClock()
        (reg_a, rep_a, sender), (reg_b, rep_b), target = self._shipped_prefix(
            tmp_path, root, clk, 2, standby_crash={"promote"}
        )
        with pytest.raises(ReplicaCrash):
            rep_b.promote(reason="health")
        # the record IS durable: a fresh process over the same dirs must
        # come up as the owner (idempotent re-activation)
        reg_b2, rep_b2 = _node(tmp_path, root, "b", clk, peer="local://a")
        summary = rep_b2.recover()
        assert summary["role"] == "primary"
        assert rep_b2.epoch == 1
        reg_b2.resolve("acme").unpin()  # serves — fence lifted
        # double boot (crash during recovery): recover() again over the
        # same journals must re-install the same state and nothing else
        reg_b3, rep_b3 = _node(tmp_path, root, "b", clk, peer="local://a")
        assert rep_b3.recover() == summary
        # the revived stale primary sees epoch 1 and steps down
        target.replicator = rep_b2
        _serve(reg_a, rep_a, "acme", TRAFFIC[3])
        assert sender.pump() == "demoted"
        with pytest.raises(TenantForwarded):
            reg_a.resolve("acme")
        # control parity for the acked prefix survives the crash
        control_clk = FakeClock()
        control = _control(
            tmp_path, root, control_clk, TRAFFIC[:2], step=_step
        )
        assert _snapshot(reg_b2) == control.frequency.snapshot()

    def test_crash_after_demote_record_recovers_fenced(self, root, tmp_path):
        clk = FakeClock()
        (reg_a, rep_a, sender), (reg_b, rep_b), _t = self._shipped_prefix(
            tmp_path, root, clk, 2, primary_crash={"demote"}
        )
        rep_b.promote(reason="partition")
        with pytest.raises(ReplicaCrash):
            sender.pump()
        # fresh process over the stale primary's dirs: the DEMOTE record
        # is durable, so it must come up standby + fenced
        reg_a2, rep_a2 = _node(tmp_path, root, "a", clk)
        summary = rep_a2.recover()
        assert summary["role"] == "standby"
        assert rep_a2.epoch == 1
        with pytest.raises(TenantForwarded) as exc:
            reg_a2.resolve("acme")
        assert exc.value.location == "local://b"
        with pytest.raises(TenantForwarded):
            reg_a2.resolve(None)
        # exactly one owner throughout
        reg_b.resolve("acme").unpin()

    def test_crash_after_epoch_adoption_record(self, root, tmp_path):
        clk = FakeClock()
        # a re-provisioned standby at epoch 0 fed by a primary already
        # at epoch 2 (two failovers ago)
        reg_b, rep_b = _node(
            tmp_path, root, "b", clk, peer="local://a",
            crash_after={"epoch"},
        )
        rep_b.recover()
        body = {
            "tenant": "acme", "epoch": 2, "walEpoch": 0, "offset": 0,
            "frames": "", "barrier": {"k": "b", "ages": {"oom": [0.0]},
                                      "w": clk()},
            "wall": clk(),
        }
        with pytest.raises(ReplicaCrash):
            rep_b.feed(body)
        # the adoption record is durable: recover() resumes at epoch 2
        # and the SAME feed then applies
        reg_b2, rep_b2 = _node(tmp_path, root, "b", clk, peer="local://a")
        assert rep_b2.recover()["epoch"] == 2
        ack = rep_b2.feed(body)
        assert ack["epoch"] == 2
        assert rep_b2.stats()["adoptions"] == 0  # no second adoption

    def test_protocol_record_vocabulary_is_pinned(self):
        assert PROTOCOL_RECORDS == (
            "epoch", "promote", "demote", "release", "adopt",
        )

    def test_recover_is_idempotent_without_records(self, root, tmp_path):
        clk = FakeClock()
        reg_a, rep_a = _node(tmp_path, root, "a", clk)
        assert rep_a.recover()["role"] == "primary"
        assert rep_a.recover() == {
            "role": "primary", "epoch": 0, "records": 0, "tenants": [],
            "released": [],
        }


# ----------------------------------------------------------- failover


class TestFailoverSupervisor:
    def _supervised(self, root, tmp_path, after_s=5.0):
        clk = FakeClock()
        (reg_a, rep_a), (reg_b, rep_b), _ = _pair(tmp_path, root, clk)
        health = {"up": True}
        sup = FailoverSupervisor(
            rep_b, "local://a", after_s=after_s, poll_s=1.0, clock=clk,
            probe=lambda: health["up"],
        )
        return clk, rep_b, sup, health

    def test_promotes_after_consecutive_failures(self, root, tmp_path):
        clk, rep_b, sup, health = self._supervised(root, tmp_path, 5.0)
        assert sup.check_once() is None  # healthy
        health["up"] = False
        assert sup.check_once() is None  # failure clock starts
        clk.t += 4.0
        assert sup.check_once() is None  # 4s down < 5s
        clk.t += 1.0
        assert sup.check_once() == "promoted"
        assert rep_b.role == "primary"
        assert rep_b.epoch == 1
        assert sup.check_once() is None  # already primary: watch is done
        assert sup.stats()["failures"] == 3

    def test_flapping_primary_never_trips(self, root, tmp_path):
        clk, rep_b, sup, health = self._supervised(root, tmp_path, 5.0)
        for _ in range(10):
            health["up"] = False
            assert sup.check_once() is None
            clk.t += 4.0
            health["up"] = True
            assert sup.check_once() is None  # resets the down clock
            clk.t += 1.0
        assert rep_b.role == "standby"
        assert rep_b.promotions == 0

    def test_stats_shape(self, root, tmp_path):
        clk, rep_b, sup, health = self._supervised(root, tmp_path, 5.0)
        health["up"] = False
        sup.check_once()
        s = sup.stats()
        assert s["primary"] == "local://a"
        assert s["afterS"] == 5.0
        assert s["probes"] == 1 and s["failures"] == 1
        assert s["downS"] == 0.0 and s["armed"] is False
