"""Failure containment (SURVEY.md §5.3): a dead device batch falls back to
the golden host path — same result, same frequency-state evolution."""

from __future__ import annotations

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.golden import GoldenAnalyzer
from log_parser_tpu.models import PodFailureData
from log_parser_tpu.runtime import AnalysisEngine

from conftest import FakeClock
from helpers import make_pattern, make_pattern_set
from test_engine_parity import assert_results_match

LOGS = "ok\nERROR boom\nok\nERROR again"


def _sets():
    return [make_pattern_set([make_pattern("e", regex="ERROR", confidence=0.7)])]


def test_device_failure_served_by_golden(monkeypatch):
    engine = AnalysisEngine(_sets(), ScoringConfig(), clock=FakeClock())
    engine.fallback_to_golden = True

    def boom(*a, **k):
        raise RuntimeError("injected device loss")

    monkeypatch.setattr(engine, "_run_device", boom)
    golden = GoldenAnalyzer(_sets(), ScoringConfig(), clock=FakeClock())
    data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=LOGS)
    assert_results_match(engine.analyze(data), golden.analyze(data))
    # the fallback recorded into the SAME tracker the device path uses
    assert engine.frequency.get_frequency_statistics() == {"e": 2}


def test_late_failure_rolls_back_frequency_state(monkeypatch):
    """A device request that dies AFTER recording its matches must not
    leave the tracker double-counted when golden re-serves it."""
    import log_parser_tpu.runtime.engine as engine_mod

    engine = AnalysisEngine(_sets(), ScoringConfig(), clock=FakeClock())
    engine.fallback_to_golden = True

    def boom(events):
        raise RuntimeError("injected post-record failure")

    monkeypatch.setattr(engine_mod, "build_summary", boom)
    golden = GoldenAnalyzer(_sets(), ScoringConfig(), clock=FakeClock())
    data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=LOGS)
    r1, r2 = engine.analyze(data), golden.analyze(data)
    assert [e.score for e in r1.events] == [e.score for e in r2.events]
    # exactly one batch recorded — not the device batch plus the golden one
    assert engine.frequency.get_frequency_statistics() == {"e": 2}
    assert engine.last_trace is None and engine.last_finalized is None


def test_fallback_disabled_raises(monkeypatch):
    engine = AnalysisEngine(_sets(), ScoringConfig())
    engine.fallback_to_golden = False
    monkeypatch.setattr(
        engine, "_run_device", lambda *a, **k: (_ for _ in ()).throw(RuntimeError("x"))
    )
    data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=LOGS)
    try:
        engine.analyze(data)
        raise AssertionError("expected RuntimeError")
    except RuntimeError:
        pass


def test_frequency_snapshot_roundtrip():
    clock = FakeClock()
    engine = AnalysisEngine(_sets(), ScoringConfig(), clock=clock)
    data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=LOGS)
    engine.analyze(data)
    engine.analyze(data)
    snap = engine.frequency.snapshot()
    assert snap == {"e": [0.0, 0.0, 0.0, 0.0]}

    # a fresh process (same clock model) restores to identical state
    clock2 = FakeClock()
    engine2 = AnalysisEngine(_sets(), ScoringConfig(), clock=clock2)
    engine2.frequency.restore(snap)
    assert engine2.frequency.get_frequency_statistics() == {"e": 4}
    # scores after restore match continuing with the original engine
    r1 = engine.analyze(data)
    r2 = engine2.analyze(data)
    assert [e.score for e in r1.events] == [e.score for e in r2.events]
