"""Failure containment (SURVEY.md §5.3): a dead device batch falls back to
the golden host path — same result, same frequency-state evolution. Only
device/XLA-layer errors may degrade; logic bugs propagate."""

from __future__ import annotations

import time

import jax.errors
import pytest

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.golden import GoldenAnalyzer
from log_parser_tpu.models import PodFailureData
from log_parser_tpu.runtime import AnalysisEngine
from log_parser_tpu.runtime.engine import is_device_error

from conftest import FakeClock
from helpers import make_pattern, make_pattern_set
from test_engine_parity import assert_results_match

LOGS = "ok\nERROR boom\nok\nERROR again"


def _sets():
    return [make_pattern_set([make_pattern("e", regex="ERROR", confidence=0.7)])]


def test_device_failure_served_by_golden(monkeypatch):
    engine = AnalysisEngine(_sets(), ScoringConfig(), clock=FakeClock())
    engine.fallback_to_golden = True

    def boom(*a, **k):
        raise jax.errors.JaxRuntimeError("injected device loss")

    monkeypatch.setattr(engine, "_run_device", boom)
    golden = GoldenAnalyzer(_sets(), ScoringConfig(), clock=FakeClock())
    data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=LOGS)
    assert_results_match(engine.analyze(data), golden.analyze(data))
    # the fallback recorded into the SAME tracker the device path uses
    assert engine.frequency.get_frequency_statistics() == {"e": 2}
    assert engine.fallback_count == 1


def test_late_failure_rolls_back_frequency_state(monkeypatch):
    """A device request that dies AFTER recording its matches must not
    leave the tracker double-counted when golden re-serves it."""
    import log_parser_tpu.runtime.engine as engine_mod

    engine = AnalysisEngine(_sets(), ScoringConfig(), clock=FakeClock())
    engine.fallback_to_golden = True

    def boom(events):
        # device errors can surface this late: transfers are async, so a
        # dead chip is often first observed at np.asarray() time downstream
        raise jax.errors.JaxRuntimeError("injected post-record failure")

    monkeypatch.setattr(engine_mod, "build_summary", boom)
    golden = GoldenAnalyzer(_sets(), ScoringConfig(), clock=FakeClock())
    data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=LOGS)
    r1, r2 = engine.analyze(data), golden.analyze(data)
    assert [e.score for e in r1.events] == [e.score for e in r2.events]
    # exactly one batch recorded — not the device batch plus the golden one
    assert engine.frequency.get_frequency_statistics() == {"e": 2}
    assert engine.last_trace is None and engine.last_finalized is None


def test_fallback_disabled_raises(monkeypatch):
    engine = AnalysisEngine(_sets(), ScoringConfig())
    engine.fallback_to_golden = False
    monkeypatch.setattr(
        engine,
        "_run_device",
        lambda *a, **k: (_ for _ in ()).throw(jax.errors.JaxRuntimeError("x")),
    )
    data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=LOGS)
    with pytest.raises(RuntimeError):
        engine.analyze(data)


def test_logic_bug_propagates_despite_fallback(monkeypatch):
    """A non-device bug must NOT be masked by the golden fallback — round-1
    regression: a masked failure re-served a 200k-line bench from pure
    Python and turned a fast failure into a timeout (VERDICT.md weak #1)."""
    engine = AnalysisEngine(_sets(), ScoringConfig(), clock=FakeClock())
    engine.fallback_to_golden = True

    monkeypatch.setattr(
        engine,
        "_run_device",
        lambda *a, **k: (_ for _ in ()).throw(TypeError("assembly bug")),
    )
    data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=LOGS)
    with pytest.raises(TypeError):
        engine.analyze(data)
    assert engine.fallback_count == 0


def _raised_from(module_name: str, msg: str) -> RuntimeError:
    """Raise-and-catch a RuntimeError from a frame whose module is
    ``module_name`` (simulates an error originating inside jax/jaxlib)."""
    g = {"__name__": module_name, "__builtins__": __builtins__}
    exec("def r(msg):\n    raise RuntimeError(msg)", g)
    try:
        g["r"](msg)
    except RuntimeError as exc:
        return exc
    raise AssertionError("unreachable")


def test_is_device_error_classification():
    assert is_device_error(jax.errors.JaxRuntimeError("boom"))
    # device-layer marker AND raised from a jax frame → device error
    assert is_device_error(
        _raised_from("jax._src.xla_bridge", "Unable to initialize backend 'axon'")
    )
    assert is_device_error(_raised_from("jaxlib.xla_client", "DEADLINE_EXCEEDED: poll"))
    # marker text quoted by NON-jax code must propagate (ADVICE.md r2): a
    # log line or downstream response embedding "UNAVAILABLE" is not a
    # device failure
    assert not is_device_error(
        RuntimeError("downstream said: UNAVAILABLE, Unable to initialize backend")
    )
    assert not is_device_error(
        _raised_from("log_parser_tpu.runtime.engine", "quoting UNAVAILABLE text")
    )
    # jax frame but no marker → still not classified as a device error
    assert not is_device_error(_raised_from("jax._src.core", "some tracing bug"))
    assert not is_device_error(RuntimeError("some unrelated runtime issue"))
    assert not is_device_error(TypeError("bug"))
    assert not is_device_error(ValueError("bad value"))


def test_frequency_snapshot_roundtrip():
    clock = FakeClock()
    engine = AnalysisEngine(_sets(), ScoringConfig(), clock=clock)
    data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=LOGS)
    engine.analyze(data)
    engine.analyze(data)
    snap = engine.frequency.snapshot()
    assert snap == {"e": [0.0, 0.0, 0.0, 0.0]}

    # a fresh process (same clock model) restores to identical state
    clock2 = FakeClock()
    engine2 = AnalysisEngine(_sets(), ScoringConfig(), clock=clock2)
    engine2.frequency.restore(snap)
    assert engine2.frequency.get_frequency_statistics() == {"e": 4}
    # scores after restore match continuing with the original engine
    r1 = engine.analyze(data)
    r2 = engine2.analyze(data)
    assert [e.score for e in r1.events] == [e.score for e in r2.events]


def test_logic_bug_rolls_back_frequency_state(monkeypatch):
    """Even a propagating (non-device) failure must not leak its partial
    match counts into the tracker — a client retry would double-count."""
    import log_parser_tpu.runtime.engine as engine_mod

    engine = AnalysisEngine(_sets(), ScoringConfig(), clock=FakeClock())
    engine.fallback_to_golden = True

    monkeypatch.setattr(
        engine_mod,
        "build_summary",
        lambda events: (_ for _ in ()).throw(TypeError("assembly bug")),
    )
    data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=LOGS)
    with pytest.raises(TypeError):
        engine.analyze(data)  # matches were recorded before the failure
    # rolled back to the pre-request (empty) tracker state
    assert engine.frequency.get_frequency_statistics() == {}
    assert not engine.frequency.has_entry("e")


def test_no_fallback_late_failure_still_rolls_back(monkeypatch):
    """The rollback invariant holds on the fallback-DISABLED path too
    (LOG_PARSER_TPU_NO_FALLBACK=1 servers return a 500; the retry must not
    double-count)."""
    import log_parser_tpu.runtime.engine as engine_mod

    engine = AnalysisEngine(_sets(), ScoringConfig(), clock=FakeClock())
    engine.fallback_to_golden = False

    monkeypatch.setattr(
        engine_mod,
        "build_summary",
        lambda events: (_ for _ in ()).throw(TypeError("assembly bug")),
    )
    data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=LOGS)
    with pytest.raises(TypeError):
        engine.analyze(data)
    assert engine.frequency.get_frequency_statistics() == {}


def test_restore_replaces_all_state():
    """restore() rebuilds from the snapshot — ids absent from the payload
    are cleared, not merged (round-1 advisor finding)."""
    clock = FakeClock()
    engine = AnalysisEngine(_sets(), ScoringConfig(), clock=clock)
    engine.analyze(PodFailureData(pod={"metadata": {"name": "p"}}, logs=LOGS))
    assert engine.frequency.get_frequency_statistics() == {"e": 2}

    engine.frequency.restore({"other": [1.0, 2.0]})
    assert engine.frequency.get_frequency_statistics() == {"other": 2}
    assert not engine.frequency.has_entry("e")


def test_restore_rejects_negative_ages():
    """Negative ages are future timestamps that never prune; the whole
    payload is rejected before any state is touched (all-or-nothing)."""
    clock = FakeClock()
    engine = AnalysisEngine(_sets(), ScoringConfig(), clock=clock)
    engine.analyze(PodFailureData(pod={"metadata": {"name": "p"}}, logs=LOGS))
    with pytest.raises(ValueError):
        engine.frequency.restore({"e": [1.0], "x": [-0.5]})
    # prior state untouched
    assert engine.frequency.get_frequency_statistics() == {"e": 2}


def test_is_device_error_walks_cause_chain():
    """jax's traceback filtering strips jax frames from the primary
    traceback and re-parents the unfiltered exception via __cause__ —
    classification must follow the chain."""
    inner = _raised_from("jax._src.xla_bridge", "Unable to initialize backend 'axon'")
    try:
        raise RuntimeError("Unable to initialize backend 'axon'") from inner
    except RuntimeError as outer:
        assert is_device_error(outer)
    # implicit chaining (__context__) counts too
    try:
        try:
            raise _raised_from("jaxlib.xla_client", "UNAVAILABLE: socket closed")
        except RuntimeError:
            raise RuntimeError("UNAVAILABLE: socket closed")
    except RuntimeError as outer:
        assert is_device_error(outer)


def test_watchdog_hang_trips_circuit_and_recovers(monkeypatch):
    """A wedged device step (never returns) times out, serves from
    golden, opens the circuit (immediate fallback, no thread stacking),
    and the circuit closes when the hung worker finally responds."""
    import threading

    from log_parser_tpu.runtime.engine import DeviceWatchdog

    engine = AnalysisEngine(_sets(), ScoringConfig(), clock=FakeClock())
    engine.fallback_to_golden = True
    engine.watchdog = DeviceWatchdog(timeout_s=0.2)
    release = threading.Event()
    real_run = engine._run_device
    hang = {"on": True}
    started = []

    def wedged(*a, **k):
        if hang["on"]:
            started.append(1)
            release.wait(10)
        return real_run(*a, **k)

    monkeypatch.setattr(engine, "_run_device", wedged)
    golden = GoldenAnalyzer(_sets(), ScoringConfig(), clock=FakeClock())
    data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=LOGS)

    # 1) hang -> timeout -> golden serves; circuit opens
    assert_results_match(engine.analyze(data), golden.analyze(data))
    assert engine.fallback_count == 1 and engine.watchdog.circuit_open

    # 2) circuit open: immediate fallback, the wedged fn is NOT re-entered
    assert_results_match(engine.analyze(data), golden.analyze(data))
    assert engine.fallback_count == 2 and len(started) == 1

    # 3) backend recovers: hung worker completes, circuit closes,
    #    the next request runs on the device again
    hang["on"] = False
    release.set()
    deadline = time.time() + 5
    while engine.watchdog.circuit_open and time.time() < deadline:
        time.sleep(0.01)
    assert not engine.watchdog.circuit_open
    assert_results_match(engine.analyze(data), golden.analyze(data))
    assert engine.fallback_count == 2  # served by the device this time


def test_watchdog_disabled_runs_inline():
    from log_parser_tpu.runtime.engine import DeviceWatchdog

    wd = DeviceWatchdog(timeout_s=0)
    calls = []
    assert wd.run(lambda: calls.append(1) or 42) == 42
    assert calls == [1] and not wd.circuit_open


def test_watchdog_propagates_worker_errors():
    """Errors from the device step pass through the watchdog unchanged
    (device errors keep their class for is_device_error)."""
    from log_parser_tpu.runtime.engine import DeviceWatchdog

    wd = DeviceWatchdog(timeout_s=5.0)

    def boom():
        raise jax.errors.JaxRuntimeError("injected")

    with pytest.raises(jax.errors.JaxRuntimeError):
        wd.run(boom)
    assert not wd.circuit_open
