"""Multi-tenant serving (runtime/tenancy.py): the isolation contract.

The anchor is interleaved-traffic parity: a tenant's responses under
interleaved multi-tenant traffic must be bit-identical to a dedicated
single-tenant engine run of its subsequence alone — unbatched, batched,
and streaming, line cache on and off. Around it: per-tenant state
non-bleed (frequency, line cache, quarantine), the quota 429 envelope
(Retry-After + ``tenant rate``/``tenant inflight``/``tenant queue``
reasons, plus the futile 413 ``tenant burst`` shed with NO Retry-After
for requests larger than the bucket's whole capacity), the resolve
lease (a pinned context is eviction-proof from resolution to the
transport's release), tenant-scoped hot reload that provably never quiesces another
tenant's engine, LRU eviction/rebuild under a bank budget, id
validation, and the two-level line-cache keying parity pin
(KeyInterner ≡ blake2b digests).
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request

import pytest

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.models.pod import PodFailureData
from log_parser_tpu.patterns import load_pattern_directory
from log_parser_tpu.runtime import AnalysisEngine
from log_parser_tpu.runtime.stream import StreamManager
from log_parser_tpu.runtime.tenancy import (
    DEFAULT_TENANT,
    TenantError,
    TenantQuota,
    TenantRegistry,
)
from log_parser_tpu.serve import make_server
from log_parser_tpu.serve.admission import AdmissionController, AdmissionRejected

from helpers import make_pattern, make_pattern_set

# two tenants with DIFFERENT libraries over the same traffic: outputs
# must differ between tenants (separate banks) while each stays
# bit-identical to its dedicated engine
ACME_YAML = """
metadata:
  library_id: acme-lib
patterns:
  - id: oom
    name: Out of memory
    severity: CRITICAL
    primary_pattern:
      regex: OutOfMemoryError
      confidence: 0.9
  - id: err
    name: Errors
    severity: LOW
    primary_pattern:
      regex: "\\\\bERROR\\\\b"
      confidence: 0.5
"""

GLOBEX_YAML = """
metadata:
  library_id: globex-lib
patterns:
  - id: conn
    name: Connection refused
    severity: HIGH
    primary_pattern:
      regex: "Connection refused"
      confidence: 0.7
  - id: err
    name: Errors
    severity: MEDIUM
    primary_pattern:
      regex: "\\\\bERROR\\\\b"
      confidence: 0.6
"""

TRAFFIC = [
    "INFO boot\njava.lang.OutOfMemoryError: heap\nan ERROR here",
    "Connection refused by peer\nINFO ok",
    "ERROR twice\nERROR again\nOutOfMemoryError",
    "nothing to see",
    "Connection refused\njava.lang.OutOfMemoryError: metaspace\nERROR",
    "INFO a\nINFO b\nan ERROR here",
]


@pytest.fixture()
def root(tmp_path):
    for tid, text in (("acme", ACME_YAML), ("globex", GLOBEX_YAML)):
        d = tmp_path / "tenants" / tid
        d.mkdir(parents=True)
        (d / "lib.yaml").write_text(text)
    return str(tmp_path / "tenants")


def _default_engine() -> AnalysisEngine:
    return AnalysisEngine(
        [make_pattern_set([make_pattern("base", regex="BASE")], "base-lib")],
        ScoringConfig(),
    )


def _registry(root, **kw) -> TenantRegistry:
    return TenantRegistry(_default_engine(), root=root, **kw)


def _dedicated(root, tid, setup=None) -> AnalysisEngine:
    eng = AnalysisEngine(
        load_pattern_directory(f"{root}/{tid}"), ScoringConfig()
    )
    if setup is not None:
        setup(eng, tid)
    return eng


def _events(result) -> list[tuple]:
    d = result.to_dict(drop_none=True)
    return [
        (e["lineNumber"], e["matchedPattern"]["id"], e["score"])
        for e in d.get("events", [])
    ] + [
        (d["summary"]["significantEvents"], d["summary"]["highestSeverity"])
    ]


def _data(blob: str) -> PodFailureData:
    return PodFailureData(pod={"metadata": {"name": "t"}}, logs=blob)


# --------------------------------------------- interleaved-traffic parity


class TestInterleavedParity:
    @pytest.mark.parametrize("cache", [False, True], ids=["nocache", "cache"])
    def test_unbatched(self, root, cache):
        setup = (
            (lambda eng, tid: eng.enable_line_cache(8)) if cache else None
        )
        reg = _registry(root, engine_setup=setup)
        try:
            ded = {t: _dedicated(root, t, setup) for t in ("acme", "globex")}
            for i, blob in enumerate(TRAFFIC):
                tid = ("acme", "globex")[i % 2]
                got = _events(reg.resolve(tid).engine.analyze(_data(blob)))
                want = _events(ded[tid].analyze(_data(blob)))
                assert got == want, (tid, blob)
            # same traffic, different libraries: the tenants' outputs for
            # the shared ERROR line differ — banks are really separate
            a = _events(reg.resolve("acme").engine.analyze(_data(TRAFFIC[0])))
            g = _events(reg.resolve("globex").engine.analyze(_data(TRAFFIC[0])))
            assert a != g
        finally:
            reg.shutdown()

    def test_batched(self, root):
        def setup(eng, tid):
            eng.enable_batching(wait_ms=1.0, batch_max=4)

        reg = _registry(root, engine_setup=setup)
        try:
            ded = {t: _dedicated(root, t, setup) for t in ("acme", "globex")}
            try:
                for i, blob in enumerate(TRAFFIC):
                    tid = ("acme", "globex")[i % 2]
                    got = _events(
                        reg.resolve(tid).engine.analyze_batched(_data(blob))
                    )
                    want = _events(ded[tid].analyze_batched(_data(blob)))
                    assert got == want, (tid, blob)
            finally:
                for eng in ded.values():
                    eng.batcher.close()
        finally:
            reg.shutdown()

    def test_streaming(self, root):
        reg = _registry(root)
        try:
            ded = {t: _dedicated(root, t) for t in ("acme", "globex")}
            mgrs = {
                t: StreamManager(reg.resolve(t).engine)
                for t in ("acme", "globex")
            }
            dmgrs = {t: StreamManager(ded[t]) for t in ("acme", "globex")}
            try:
                blob = ("\n".join(TRAFFIC) + "\n").encode()
                chunks = [blob[i : i + 37] for i in range(0, len(blob), 37)]
                sess = {t: m.open() for t, m in mgrs.items()}
                dsess = {t: m.open() for t, m in dmgrs.items()}
                # interleave: both tenants' sessions advance chunk by chunk
                for c in chunks:
                    for t in ("acme", "globex"):
                        assert [
                            f["type"] for f in sess[t].feed(c)
                        ] == [f["type"] for f in dsess[t].feed(c)]
                for t in ("acme", "globex"):
                    got = sess[t].close()[-1]
                    want = dsess[t].close()[-1]
                    assert got["type"] == want["type"] == "final"
                    # analysisId / timing metadata are request-unique;
                    # the contract is on events + summary
                    for k in ("events", "summary"):
                        assert got["result"].get(k) == want["result"].get(k), t
            finally:
                for m in (*mgrs.values(), *dmgrs.values()):
                    m.shutdown()
        finally:
            reg.shutdown()


# ------------------------------------------------------ state non-bleed


class TestNonBleed:
    def test_frequency(self, root):
        reg = _registry(root)
        try:
            for _ in range(3):
                reg.resolve("acme").engine.analyze(_data("an ERROR here"))
            acme = reg.resolve("acme").engine.frequency
            globex = reg.resolve("globex").engine.frequency
            assert acme.get_frequency_statistics().get("err", 0) >= 3
            assert globex.get_frequency_statistics().get("err", 0) == 0
            assert (
                reg.default_context.engine.frequency
                .get_frequency_statistics().get("err", 0) == 0
            )
        finally:
            reg.shutdown()

    def test_line_cache(self, root):
        reg = _registry(
            root, engine_setup=lambda eng, tid: eng.enable_line_cache(8)
        )
        try:
            blob = TRAFFIC[0]
            reg.resolve("acme").engine.analyze(_data(blob))
            reg.resolve("acme").engine.analyze(_data(blob))
            reg.resolve("globex").engine.analyze(_data(blob))
            acme = reg.resolve("acme").engine.line_cache.stats()
            globex = reg.resolve("globex").engine.line_cache.stats()
            assert acme["hits"] > 0
            # globex saw the blob ONCE: its (separate) cache has no hits
            assert globex["hits"] == 0
        finally:
            reg.shutdown()

    def test_quarantine(self, root):
        reg = _registry(root)
        try:
            q = reg.resolve("acme").engine.quarantine
            fp = "deadbeef"
            for _ in range(10):
                if q.strike(fp):
                    break
            assert q.stats()["active"] >= 1
            assert reg.resolve("globex").engine.quarantine.stats()["active"] == 0
        finally:
            reg.shutdown()


# --------------------------------------------------------- quota ladder


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


class TestQuota:
    def test_rate_bucket_sheds_429(self):
        clk = _Clock()
        gate = AdmissionController(clock=clk)
        q = TenantQuota(lines_per_s=2.0, clock=clk)  # 4-token bucket
        assert gate.acquire(tenant=q, lines=3) == "device"
        gate.release(tenant=q)
        with pytest.raises(AdmissionRejected) as exc:
            gate.acquire(tenant=q, lines=3)
        assert exc.value.reason == "tenant rate"
        assert exc.value.status == 429
        assert exc.value.retry_after_s >= 1
        assert gate.stats()["shedTenant"] == 1
        assert q.stats()["shedRate"] == 1
        # the bucket refills with time: admitted again after 1s
        clk.t += 1.0
        assert gate.acquire(tenant=q, lines=3) == "device"
        gate.release(tenant=q)

    def test_inflight_cap_sheds_429(self):
        gate = AdmissionController()
        q = TenantQuota(max_inflight=1)
        gate.acquire(tenant=q, lines=1)
        with pytest.raises(AdmissionRejected) as exc:
            gate.acquire(tenant=q, lines=1)
        assert exc.value.reason == "tenant inflight"
        assert exc.value.status == 429
        assert q.stats()["shedInflight"] == 1
        gate.release(tenant=q)
        assert gate.acquire(tenant=q, lines=1) == "device"
        gate.release(tenant=q)

    def test_queue_share_sheds_429(self):
        gate = AdmissionController(max_inflight=1, max_queue=8)
        other = TenantQuota()
        gate.acquire(tenant=other, lines=1)  # saturate the global slot
        q = TenantQuota(max_queued=1)
        q.queued = 1  # the tenant's queue share is already taken
        with pytest.raises(AdmissionRejected) as exc:
            gate.acquire(tenant=q, lines=1)
        assert exc.value.reason == "tenant queue"
        assert exc.value.status == 429
        assert q.stats()["shedQueue"] == 1
        gate.release(tenant=other)

    def test_oversize_request_sheds_413_futile(self):
        """A request declaring more lines than the bucket can EVER hold
        (capacity = lines_per_s × burst) must not get a small finite
        Retry-After — that used to send the client into a permanent 429
        loop. It sheds 413 ``tenant burst`` with retry_after_s == 0."""
        clk = _Clock()
        gate = AdmissionController(clock=clk)
        q = TenantQuota(lines_per_s=2.0, clock=clk)  # 4-token bucket
        with pytest.raises(AdmissionRejected) as exc:
            gate.acquire(tenant=q, lines=5)
        assert exc.value.reason == "tenant burst"
        assert exc.value.status == 413
        assert exc.value.retry_after_s == 0
        assert "retrying will not help" in str(exc.value)
        assert q.stats()["shedOversize"] == 1
        assert q.stats()["shedRate"] == 0
        # time cannot help: the same request is still futile much later
        clk.t += 3600.0
        with pytest.raises(AdmissionRejected) as exc:
            gate.acquire(tenant=q, lines=5)
        assert exc.value.status == 413
        # a request that fits the whole burst still admits normally
        assert gate.acquire(tenant=q, lines=4) == "device"
        gate.release(tenant=q)

    def test_streams_bypass_the_bucket(self):
        # a session open carries lines=0: the bucket never debits
        clk = _Clock()
        gate = AdmissionController(clock=clk)
        q = TenantQuota(lines_per_s=1.0, clock=clk)
        for _ in range(5):
            gate.acquire(tenant=q, lines=0)
            gate.release(tenant=q)
        assert q.stats()["shedRate"] == 0


# ------------------------------------------------- HTTP quota envelope


def _post(url, payload, headers=None):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


class TestHTTPQuotaEnvelope:
    def _serve(self, reg):
        server = make_server(reg.default_engine, "127.0.0.1", 0, tenants=reg)
        port = server.server_address[1]
        threading.Thread(target=server.serve_forever, daemon=True).start()
        return server, f"http://127.0.0.1:{port}/parse"

    def test_429_with_retry_after(self, root):
        # 4-token bucket for acme only: its 3-line request fits ONCE,
        # then the drained bucket sheds with a real retry window, while
        # globex and the default tenant are unbounded. The refill rate is
        # deliberately slow (0.2/s: the 2-token shortfall takes 10s to
        # recover) so a loaded host can't refill the bucket in the wall
        # clock between the two posts.
        reg = _registry(
            root,
            quota_factory=lambda tid: TenantQuota(
                lines_per_s=0.2 if tid == "acme" else 0.0,
                burst_s=20.0,
            ),
        )
        server, url = self._serve(reg)
        payload = {"pod": {"metadata": {"name": "q"}}, "logs": TRAFFIC[0]}
        try:
            assert _post(url, payload, {"X-Tenant": "acme"})[0] == 200
            status, body, headers = _post(
                url, payload, {"X-Tenant": "acme"}
            )
            assert status == 429, body
            assert body == {"error": "overloaded", "reason": "tenant rate"}
            assert int(headers["Retry-After"]) >= 1
            assert _post(url, payload, {"X-Tenant": "globex"})[0] == 200
            assert _post(url, payload)[0] == 200
        finally:
            server.shutdown()
            server.server_close()
            reg.shutdown()

    def test_oversize_request_is_413_without_retry_after(self, root):
        # 2-token bucket: acme's 3-line request can NEVER fit — the shed
        # must say so (413, no Retry-After) instead of promising a
        # retry window that will never help
        reg = _registry(
            root,
            quota_factory=lambda tid: TenantQuota(
                lines_per_s=1.0 if tid == "acme" else 0.0
            ),
        )
        server, url = self._serve(reg)
        payload = {"pod": {"metadata": {"name": "q"}}, "logs": TRAFFIC[0]}
        try:
            status, body, headers = _post(
                url, payload, {"X-Tenant": "acme"}
            )
            assert status == 413, body
            assert body == {"error": "overloaded", "reason": "tenant burst"}
            assert "Retry-After" not in headers
            assert _post(url, payload, {"X-Tenant": "globex"})[0] == 200
        finally:
            server.shutdown()
            server.server_close()
            reg.shutdown()


# ------------------------------------------------ tenant-scoped reload


class TestTenantReload:
    def test_reload_never_touches_other_tenants(self, root):
        """The pin for 'tenant hot reload completes while another
        tenant's requests are served': run acme's reload WHILE holding
        globex's engine.state_lock and while a thread hammers globex
        traffic. A global quiesce would deadlock on the held lock; the
        tenant-scoped one completes and bumps only acme's epoch."""
        reg = _registry(root)
        try:
            ctx_a = reg.resolve("acme")
            ctx_g = reg.resolve("globex")
            stop = threading.Event()
            errors: list[Exception] = []

            def hammer():
                while not stop.is_set():
                    try:
                        ctx_g.engine.analyze(_data(TRAFFIC[1]))
                    except Exception as exc:  # pragma: no cover
                        errors.append(exc)
                        return

            t = threading.Thread(target=hammer, daemon=True)
            t.start()
            done = threading.Event()
            out: dict = {}

            def reload_a():
                out["envelope"] = ctx_a.reloader().reload()
                ctx_a.note_reloaded()
                done.set()

            with ctx_g.engine.state_lock:
                r = threading.Thread(target=reload_a, daemon=True)
                r.start()
                assert done.wait(timeout=60), (
                    "tenant reload stalled behind another tenant's lock"
                )
            stop.set()
            t.join(timeout=30)
            assert not errors, errors
            assert ctx_a.engine.reload_epoch == 1
            assert ctx_g.engine.reload_epoch == 0
            assert reg.default_context.engine.reload_epoch == 0
        finally:
            reg.shutdown()


# ------------------------------------------------- residency / eviction


class TestResidency:
    def test_evict_and_rebuild_under_budget(self, root):
        probe = _registry(root)
        try:
            bank_bytes = probe.resolve("acme").bank_bytes
        finally:
            probe.shutdown()
        reg = _registry(root, budget_mb=bank_bytes * 1.5 / 2**20)
        try:
            first = reg.resolve("acme")
            assert _events(first.engine.analyze(_data(TRAFFIC[0])))
            first.unpin()  # request finished: the resolve lease ends
            # over budget: acme (LRU, idle) evicted
            reg.resolve("globex").unpin()
            assert reg.evicted == 1
            assert reg.context_if_resident("acme") is None
            rebuilt = reg.resolve("acme")  # rebuilds (and evicts globex)
            assert reg.rebuilds == 1
            assert rebuilt is not first
            # the rebuilt engine answers identically
            assert _events(rebuilt.engine.analyze(_data(TRAFFIC[0]))) == (
                _events(_dedicated(root, "acme").analyze(_data(TRAFFIC[0])))
            )
        finally:
            reg.shutdown()

    def test_busy_tenants_are_never_evicted(self, root):
        reg = _registry(root, budget_mb=0.001)  # everything is over budget
        try:
            ctx = reg.resolve("acme")
            ctx.unpin()  # lease released: quota state alone drives this
            ctx.quota.inflight = 1  # in-flight request holds the engine
            reg.resolve("globex").unpin()
            assert reg.context_if_resident("acme") is ctx  # deferred
            ctx.quota.inflight = 0
            # next resolve evicts the idle LRU
            reg.resolve("globex").unpin()
            assert reg.context_if_resident("acme") is None
        finally:
            reg.shutdown()

    def test_resolve_lease_pins_until_released(self, root):
        """The resolve→acquire window (review finding): a request holds
        its context from resolve() until the transport's release, with
        quota.inflight/queued still zero. Another tenant's resolve in
        that window must NOT evict and close() the engine out from
        under it — the pin makes the context busy for its whole life."""
        reg = _registry(root, budget_mb=0.001)  # everything is over budget
        try:
            ctx = reg.resolve("acme")  # pinned, no quota state yet
            assert ctx.quota.inflight == 0 and ctx.quota.queued == 0
            reg.resolve("globex").unpin()
            # acme survived: its journal/batcher were not closed under
            # the request that is still holding the context
            assert reg.context_if_resident("acme") is ctx
            assert _events(ctx.engine.analyze(_data(TRAFFIC[0])))
            ctx.unpin()  # transport finished: lease ends, eviction may run
            reg.resolve("globex").unpin()
            assert reg.context_if_resident("acme") is None
        finally:
            reg.shutdown()

    def test_stats_shape(self, root):
        reg = _registry(root)
        try:
            reg.resolve("acme")
            s = reg.stats()
            assert set(s) == {
                "residentTenants", "budgetMb", "residentBankMb", "resolved",
                "created", "evicted", "rebuilds", "unknown", "invalid",
                "forwarded", "forwards", "fenced", "fence", "perTenant",
            }
            assert set(s["perTenant"]) == {DEFAULT_TENANT, "acme"}
            per = s["perTenant"]["acme"]
            assert set(per) == {
                "bankBytes", "patterns", "reloadEpoch", "quota",
            }
            assert per["bankBytes"] > 0 and per["patterns"] == 2
        finally:
            reg.shutdown()


# ------------------------------------------------------- id resolution


class TestResolution:
    def test_default_and_none_map_to_default_tenant(self, root):
        reg = _registry(root)
        try:
            assert reg.resolve(None) is reg.default_context
            assert reg.resolve("") is reg.default_context
            assert reg.resolve(DEFAULT_TENANT) is reg.default_context
        finally:
            reg.shutdown()

    @pytest.mark.parametrize(
        "bad", ["../evil", "a/b", "", ".hidden", "x" * 65]
    )
    def test_traversal_ids_are_400(self, root, bad):
        reg = _registry(root)
        try:
            if bad == "":
                return  # empty maps to default, covered above
            with pytest.raises(TenantError) as exc:
                reg.resolve(bad)
            assert exc.value.status == 400
            assert reg.invalid >= 1
        finally:
            reg.shutdown()

    def test_unknown_tenant_is_404(self, root):
        reg = _registry(root)
        try:
            with pytest.raises(TenantError) as exc:
                reg.resolve("ghost")
            assert exc.value.status == 404
            assert reg.unknown == 1
        finally:
            reg.shutdown()

    def test_no_root_means_single_tenant_404(self):
        reg = TenantRegistry(_default_engine())
        try:
            with pytest.raises(TenantError) as exc:
                reg.resolve("acme")
            assert exc.value.status == 404
            assert "tenant-root" in str(exc.value)
        finally:
            reg.shutdown()

    def test_concurrent_first_touch_builds_once(self, root):
        reg = _registry(root)
        try:
            got: list = []
            lock = threading.Lock()

            def one():
                ctx = reg.resolve("acme")
                with lock:
                    got.append(ctx)

            threads = [threading.Thread(target=one) for _ in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert len(got) == 6
            assert all(c is got[0] for c in got)
            assert reg.created == 1  # coalesced: ONE build
        finally:
            reg.shutdown()
