"""Model (de)serialization: snake/camel acceptance, round-tripping."""

from log_parser_tpu.models import (
    AnalysisResult,
    EventContext,
    MatchedEvent,
    PatternFrequency,
    PatternSet,
    PodFailureData,
)


class TestPatternModels:
    def test_yaml_shape_snake_case(self):
        # the YAML schema from docs/SCORING_ALGORITHM.md:29-33
        data = {
            "metadata": {"library_id": "core", "name": "Core patterns"},
            "patterns": [
                {
                    "id": "oom",
                    "name": "Out of memory",
                    "severity": "CRITICAL",
                    "primary_pattern": {"regex": "OutOfMemoryError", "confidence": 0.9},
                    "secondary_patterns": [
                        {"regex": "memory pressure", "weight": 0.6, "proximity_window": 20}
                    ],
                    "sequence_patterns": [
                        {
                            "description": "gc thrash then oom",
                            "bonus_multiplier": 0.3,
                            "events": [{"regex": "Full GC"}, {"regex": "OutOfMemoryError"}],
                        }
                    ],
                    "context_extraction": {
                        "lines_before": 5,
                        "lines_after": 10,
                        "include_stack_trace": True,
                    },
                    "remediation": {"description": "raise memory limits"},
                }
            ],
        }
        ps = PatternSet.from_dict(data)
        assert ps.metadata.library_id == "core"
        p = ps.patterns[0]
        assert p.primary_pattern.confidence == 0.9
        assert p.secondary_patterns[0].proximity_window == 20
        assert p.sequence_patterns[0].events[1].regex == "OutOfMemoryError"
        assert p.context_extraction.include_stack_trace is True
        assert p.remediation == {"description": "raise memory limits"}
        # round trip preserves everything
        assert PatternSet.from_dict(ps.to_dict()).to_dict() == ps.to_dict()

    def test_camel_case_also_accepted(self):
        ps = PatternSet.from_dict(
            {
                "metadata": {"libraryId": "x"},
                "patterns": [
                    {"id": "a", "primaryPattern": {"regex": "E", "confidence": 0.5}}
                ],
            }
        )
        assert ps.metadata.library_id == "x"
        assert ps.patterns[0].primary_pattern.regex == "E"


class TestAnalysisModels:
    def test_event_serializes_camel_case(self):
        event = MatchedEvent(
            line_number=7,
            context=EventContext(matched_line="boom", lines_before=["a"], lines_after=[]),
            score=1.25,
        )
        d = event.to_dict()
        assert d["lineNumber"] == 7
        assert d["context"]["matchedLine"] == "boom"
        assert d["context"]["linesBefore"] == ["a"]

    def test_result_round_trip(self):
        result = AnalysisResult.from_dict(
            {
                "events": [],
                "analysisId": "abc",
                "metadata": {"processingTimeMs": 3, "totalLines": 10},
                "summary": {"significantEvents": 0, "highestSeverity": "NONE"},
            }
        )
        assert result.metadata.total_lines == 10
        assert result.to_dict()["summary"]["highestSeverity"] == "NONE"


class TestPodFailureData:
    def test_pod_name(self):
        data = PodFailureData.from_dict(
            {"pod": {"metadata": {"name": "web-1"}}, "logs": "a\nb"}
        )
        assert data.pod_name == "web-1"

    def test_null_pod(self):
        assert PodFailureData.from_dict({"logs": "x"}).pod_name is None


class TestPatternFrequency:
    def test_sliding_window(self):
        clock = lambda: clock.now  # noqa: E731
        clock.now = 0.0
        freq = PatternFrequency(3600.0, clock=clock)
        for _ in range(5):
            freq.increment_count()
        assert freq.get_current_count() == 5
        assert freq.get_hourly_rate() == 5.0
        clock.now = 3601.0
        assert freq.get_current_count() == 0
        freq.increment_count()
        assert freq.get_hourly_rate() == 1.0

    def test_reset(self):
        freq = PatternFrequency(3600.0)
        freq.increment_count()
        freq.reset()
        assert freq.get_current_count() == 0

    def test_bulk_increment_window_semantics(self):
        """Bulk recording is count- and window-equivalent to the loop:
        same windowed counts, same expiry, same interleaving with
        singles; n<=0 is a no-op."""
        clock = lambda: clock.now  # noqa: E731
        clock.now = 0.0
        freq = PatternFrequency(3600.0, clock=clock)
        freq.increment_count_bulk(1000)
        assert freq.get_current_count() == 1000
        clock.now = 1800.0
        freq.increment_count()
        freq.increment_count_bulk(4)
        assert freq.get_current_count() == 1005
        clock.now = 3601.0  # first bulk expired, the t=1800 five remain
        assert freq.get_current_count() == 5
        freq.increment_count_bulk(0)
        freq.increment_count_bulk(-3)
        assert freq.get_current_count() == 5
        clock.now = 5401.0
        assert freq.get_current_count() == 0
