"""Causal span store (obs/spans.py): vocabulary, deterministic
sampling, staging (no-orphan) invariants, the batched flush tree with
links both ways, the stream session span across a hot-reload re-base,
duration reconciliation against PhaseTrace (span trees, the trace ring
and the phase histograms must never disagree), and the
``GET /trace/spans`` HTTP surface."""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.models.pod import PodFailureData
from log_parser_tpu.obs.spans import SPANS, SpanStore, _span_id
from log_parser_tpu.runtime import AnalysisEngine
from log_parser_tpu.runtime.stream import StreamManager
from log_parser_tpu.serve import make_server

from helpers import make_pattern, make_pattern_set


def _engine() -> AnalysisEngine:
    patterns = [
        make_pattern("oom", regex="OutOfMemoryError", confidence=0.9,
                     severity="CRITICAL", context=(1, 1)),
        make_pattern("err", regex=r"\bERROR\b", confidence=0.5,
                     severity="LOW"),
    ]
    return AnalysisEngine(
        [make_pattern_set(patterns, "lib")], ScoringConfig()
    )


LOGS = "INFO boot\njava.lang.OutOfMemoryError: heap\nINFO after"


def _data() -> PodFailureData:
    return PodFailureData(pod={"metadata": {"name": "web-1"}}, logs=LOGS)


def _wait(pred, timeout: float = 15.0):
    """Poll ``pred`` (flush/session traces commit on scheduler threads,
    a beat after the request responses return)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = pred()
        if got:
            return got
    raise AssertionError("span predicate never held")


def _names(trace: dict) -> list[str]:
    return [s["name"] for s in trace["spans"]]


# -------------------------------------------------------------- store


class TestSpanStore:
    def test_unknown_span_name_rejected(self):
        store = SpanStore()
        with pytest.raises(ValueError):
            store.annotate("rid-1", "warp", 0.001)
        with pytest.raises(ValueError):
            store.end_trace("rid-1", 0.001, name="warp")

    def test_sampling_is_deterministic_on_the_trace_id(self):
        # the same id gives the same verdict on every store instance, so
        # a replayed request is reproducibly kept or reproducibly cheap
        a, b = SpanStore(sample=0.37), SpanStore(sample=0.37)
        ids = [f"rid-{i:03d}" for i in range(256)]
        verdicts = [a.sampled(t) for t in ids]
        assert verdicts == [b.sampled(t) for t in ids]
        assert 0 < sum(verdicts) < len(ids)  # neither degenerate

    def test_dropped_sample_pops_staged_children(self):
        store = SpanStore(sample=0.0, slow_ms=1e9)
        store.annotate("rid-1", "admission", 0.001)
        assert store.stats()["staged"] == 1
        assert store.end_trace("rid-1", 0.010) is False
        st = store.stats()
        assert st["staged"] == 0, "dropped sample orphaned a staged span"
        assert st["committed"] == 0 and st["droppedTraces"] == 1
        # forced traces (flush/session/tenancy) still commit at sample 0
        store.annotate("fl-1", "dispatch", 0.002)
        assert store.end_trace("fl-1", 0.010, name="flush", force=True)
        st = store.stats()
        assert st["committed"] == 1 and st["staged"] == 0

    def test_slow_trace_always_kept(self):
        store = SpanStore(sample=0.0, slow_ms=5.0)
        assert store.end_trace("rid-slow", 0.006) is True
        assert store.find("rid-slow")["slow"] is True

    def test_committed_bound_and_staging_eviction(self):
        store = SpanStore(capacity=2, staging_capacity=2)
        for i in range(4):
            store.end_trace(f"r{i}", 0.001, force=True)
        st = store.stats()
        assert st["retained"] == 2 and st["committed"] == 4
        assert [t["traceId"] for t in store.traces()] == ["r3", "r2"]
        # staging evicts the OLDEST trace whole, never single spans
        for i in range(3):
            store.annotate(f"s{i}", "chunk", 0.001)
        st = store.stats()
        assert st["staged"] == 2 and st["stagingEvicted"] == 1

    def test_phase_children_reconcile_exactly(self):
        # phase children are built from the PhaseTrace dict itself, so
        # their summed durations equal the phase total by construction
        store = SpanStore()
        phases = {"ingest": 0.001205, "device": 0.044011, "finalize": 3.1e-4}
        assert store.end_trace("rid-1", 0.0482, phases=phases, force=True)
        tr = store.find("rid-1")
        kids = [s for s in tr["spans"] if s["name"] == "phase"]
        assert [k["attrs"]["phase"] for k in kids] == list(phases)
        for kid, seconds in zip(kids, phases.values()):
            assert kid["durationMs"] == round(seconds * 1e3, 6)
        slack = abs(sum(k["durationMs"] for k in kids)
                    - sum(phases.values()) * 1e3)
        assert slack < 1e-6
        # sequential offsets: each child starts where the previous ended
        # (modulo float->nano rounding of the shared t0)
        for prev, nxt in zip(kids, kids[1:]):
            want = prev["startUnixNano"] + prev["durationMs"] * 1e6
            assert abs(nxt["startUnixNano"] - want) <= 1_000

    def test_links_resolve_without_lookup_and_export_otlp(self):
        store = SpanStore()
        # the member links the flush BEFORE the flush trace commits —
        # root span ids are deterministic on the trace id, so a link
        # mints without looking the other trace up
        assert store.end_trace("rid-1", 0.01, links=["flush-1"], force=True)
        store.annotate("flush-1", "dispatch", 0.002, attrs={"tier": "xla"})
        assert store.end_trace("flush-1", 0.02, name="flush",
                               links=["rid-1"], force=True)
        rid = store.find("rid-1")
        assert rid["spans"][0]["links"] == [
            {"traceId": "flush-1", "spanId": _span_id("flush-1")}
        ]
        assert store.find("flush-1")["spans"][0]["links"][0]["spanId"] == (
            rid["spans"][0]["spanId"]
        )
        doc = store.export_otlp()
        spans = doc["resourceSpans"][0]["scopeSpans"][0]["spans"]
        assert {s["name"] for s in spans} == {"request", "flush", "dispatch"}
        for s in spans:
            assert len(s["traceId"]) == 32  # OTLP ids, not wire ids
            assert int(s["endTimeUnixNano"]) >= int(s["startTimeUnixNano"])
            keys = {kv["key"] for kv in s["attributes"]}
            assert "trace.wire_id" in keys and "tenant" in keys
        linked = next(s for s in spans if s["name"] == "flush")
        assert len(linked["links"][0]["traceId"]) == 32

    def test_dump_writes_importable_json(self, tmp_path):
        store = SpanStore()
        store.end_trace("rid-1", 0.01, force=True)
        path = store.dump(str(tmp_path / "spans.otlp.json"))
        with open(path) as fh:
            assert "resourceSpans" in json.load(fh)

    def test_vocabulary_is_closed(self):
        store = SpanStore()
        for name in SPANS:
            store.annotate(f"t-{name}", name, 0.001)  # every name records
        assert store.stats()["staged"] == len(SPANS)


# ------------------------------------------------- batched flush tree


class TestBatchedFlushTree:
    def test_flush_links_every_member_and_members_link_back(self):
        engine = _engine()
        engine.enable_batching(wait_ms=250.0, batch_max=4)
        rids = ["rid-a", "rid-b", "rid-c"]
        barrier = threading.Barrier(len(rids))
        errs: list[BaseException] = []

        def one(rid):
            try:
                barrier.wait()
                engine.analyze_batched(_data(), request_id=rid)
            except BaseException as exc:  # noqa: BLE001 - surfaced below
                errs.append(exc)

        threads = [threading.Thread(target=one, args=(r,)) for r in rids]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(120)
            assert not errs, errs
            spans = engine.obs.spans

            def flush_of(n):
                return next(
                    (t for t in spans.traces() if t["name"] == "flush"
                     and len(t["spans"][0].get("links") or []) >= n),
                    None,
                )

            flush = _wait(lambda: flush_of(2))
            linked = {ln["traceId"] for ln in flush["spans"][0]["links"]}
            # one flush, >= 2 coalesced members, every member linked
            assert len(linked & set(rids)) >= 2, (linked, rids)
            assert flush["spans"][0]["attrs"]["members"] == len(linked)
            assert "demux" in _names(flush), _names(flush)
            assert "dispatch" in _names(flush), _names(flush)
            for rid in linked & set(rids):
                req = _wait(lambda r=rid: spans.find(r))
                root = req["spans"][0]
                assert root["name"] == "request"
                assert root["attrs"]["route"] == "batched"
                assert root["attrs"]["flush"] == flush["traceId"]
                # ... and the back-link closes the cycle
                assert any(
                    ln["traceId"] == flush["traceId"]
                    for ln in root.get("links") or []
                ), root
                names = _names(req)
                assert "enqueue" in names and "phase" in names, names
            assert spans.stats()["staged"] == 0
        finally:
            engine.batcher.close()


# ------------------------------------------------ stream session span


class TestStreamSessionSpan:
    def test_session_span_survives_hot_reload_rebase(self):
        engine = _engine()
        mgr = StreamManager(engine, ttl_s=0, start_reaper=False)
        sess = mgr.open()
        sess.feed(b"java.lang.OutOfMemoryError: heap\n")
        engine.apply_library(_engine())  # hot reload between chunks
        sess.feed(b"INFO after\n")  # re-bases, then ingests
        sess.close()
        assert mgr.stats()["sessionsRebased"] == 1
        tr = engine.obs.spans.find(sess.session_id)
        assert tr is not None and tr["name"] == "session"
        root = tr["spans"][0]
        assert root["attrs"]["outcome"] == "closed"
        assert root["attrs"]["chunks"] == 2
        names = _names(tr)
        assert names.count("chunk") == 2, names
        rebase = next(s for s in tr["spans"] if s["name"] == "rebase")
        assert rebase["attrs"]["epoch"] >= 1
        assert engine.obs.spans.stats()["staged"] == 0

    def test_killed_session_still_commits_its_tree(self):
        engine = _engine()
        mgr = StreamManager(engine, ttl_s=0, start_reaper=False)
        sess = mgr.open()
        sess.feed(b"INFO boot\n")
        sess.kill("ttl")
        tr = engine.obs.spans.find(sess.session_id)
        assert tr is not None
        assert tr["spans"][0]["attrs"]["outcome"] == "ttl"
        assert "chunk" in _names(tr)


# ------------------------------------------- sampling, engine-level


class TestSamplingEndToEnd:
    def test_sample_zero_drops_request_without_orphans(self):
        engine = _engine()
        engine.obs.spans.sample = 0.0
        engine.obs.spans.slow_ms = 1e9  # slow path out of reach
        engine.analyze_pipelined(_data(), request_id="rid-drop")
        st = engine.obs.spans.stats()
        assert engine.obs.spans.find("rid-drop") is None
        assert st["droppedTraces"] >= 1 and st["staged"] == 0
        # the ring still recorded it — sampling bounds span cost, not
        # request accounting
        assert any(
            e["requestId"] == "rid-drop" for e in engine.obs.ring.recent(10)
        )


# ----------------------------------------------------- reconciliation


class TestReconciliation:
    def test_span_tree_agrees_with_trace_ring(self):
        engine = _engine()
        engine.analyze_pipelined(_data(), request_id="rid-recon")
        entry = next(e for e in engine.obs.ring.recent(10)
                     if e["requestId"] == "rid-recon")
        tr = engine.obs.spans.find("rid-recon")
        assert tr is not None
        # both surfaces were built from the same clock delta and the
        # same PhaseTrace dict inside note_served: <= 1 ms slack is the
        # acceptance bar, equality-modulo-rounding is the reality
        assert abs(tr["totalMs"] - entry["totalMs"]) <= 1.0
        span_phases = {
            s["attrs"]["phase"]: s["durationMs"]
            for s in tr["spans"] if s["name"] == "phase"
        }
        assert set(span_phases) == set(entry["phasesMs"])
        for name, ms in entry["phasesMs"].items():
            assert abs(span_phases[name] - ms) <= 0.001, name
        slack = abs(sum(span_phases.values())
                    - sum(entry["phasesMs"].values()))
        assert slack <= 1.0


# ------------------------------------------------------- HTTP surface


@pytest.fixture(scope="module")
def spans_server():
    engine = _engine()
    engine.enable_batching(wait_ms=250.0, batch_max=4)
    server = make_server(engine, host="127.0.0.1", port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{port}", engine
    server.shutdown()
    engine.batcher.close()


def _post(url, payload, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    with urllib.request.urlopen(req) as resp:
        return resp.status, json.loads(resp.read()), dict(resp.headers)


def _get(url):
    with urllib.request.urlopen(url) as resp:
        return resp.status, json.loads(resp.read())


class TestHttpTraceSpans:
    def test_batched_replay_yields_complete_causal_tree(self, spans_server):
        url, engine = spans_server
        rids = ["http-rid-1", "http-rid-2", "http-rid-3"]
        barrier = threading.Barrier(len(rids))
        statuses: dict[str, int] = {}

        def one(rid):
            barrier.wait()
            statuses[rid], _, _ = _post(
                url + "/parse",
                {"pod": {"metadata": {"name": "web-1"}}, "logs": LOGS},
                headers={"X-Request-Id": rid},
            )

        threads = [threading.Thread(target=one, args=(r,)) for r in rids]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert statuses == {r: 200 for r in rids}, statuses

        def tree():
            _, body = _get(url + "/trace/spans?n=64")
            flushes = [
                t for t in body["traces"] if t["name"] == "flush"
                and {ln["traceId"] for ln in t["spans"][0]["links"]}
                & set(rids)
            ]
            complete = [
                f for f in flushes
                if "dispatch" in _names(f) and "demux" in _names(f)
            ]
            return (body, complete[0]) if complete else None

        body, flush = _wait(lambda: tree())
        # the acceptance tree: request -> flush(link) -> dispatch ->
        # finalize, readable off one GET
        member = next(
            ln["traceId"] for ln in flush["spans"][0]["links"]
            if ln["traceId"] in rids
        )
        req = next(t for t in body["traces"] if t["traceId"] == member)
        names = _names(req)
        assert "admission" in names and "enqueue" in names, names
        assert any(
            ln["traceId"] == flush["traceId"]
            for ln in req["spans"][0].get("links") or []
        )
        # cross-surface reconciliation over HTTP: /trace/spans vs
        # /trace/recent for the same request id, <= 1 ms slack
        _, recent = _get(url + "/trace/recent?n=20")
        entry = next(e for e in recent["requests"]
                     if e["requestId"] == member)
        assert abs(req["totalMs"] - entry["totalMs"]) <= 1.0
        # the vocabulary rides the payload so a dashboard can label
        # spans without importing the package
        assert set(body["vocabulary"]) == set(SPANS)

    def test_trace_spans_bad_n_is_400(self, spans_server):
        url, _ = spans_server
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(url + "/trace/spans?n=bogus")
        assert exc.value.code == 400

    def test_trace_last_spans_block_matches_store(self, spans_server):
        url, engine = spans_server
        _, trace = _get(url + "/trace/last")
        want = engine.obs.spans.stats()
        got = trace["spans"]
        # counters move between the two reads under concurrent tests;
        # the shape and the bounds are the contract
        assert sorted(got) == sorted(want)
        assert got["capacity"] == want["capacity"]
        assert got["sample"] == want["sample"]
