"""Sharded (shard_map) pipeline vs golden: multi-device parity on the
virtual 8-device CPU mesh — halo exchange, all_gather chains, cross-shard
frequency prefix, and shard-boundary window correctness."""

import random

import pytest

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.golden import GoldenAnalyzer
from log_parser_tpu.models import PodFailureData
from log_parser_tpu.parallel import ShardedEngine, make_mesh
from tests.conftest import FakeClock
from tests.helpers import make_pattern, make_pattern_set
from tests.test_engine_parity import assert_results_match, random_library, random_logs


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(8)


@pytest.mark.parametrize("seed", range(4))
def test_random_parity_small_batches(seed, mesh8):
    """Small logs: shards smaller than halos -> the all_gather fallback."""
    rng = random.Random(1000 + seed)
    sets = random_library(rng, rng.randrange(2, 6))
    config = ScoringConfig(frequency_threshold=rng.choice([2.0, 10.0]))
    engine = ShardedEngine(sets, config, mesh=mesh8, clock=FakeClock())
    golden = GoldenAnalyzer(sets, config, clock=FakeClock())
    for _ in range(2):
        logs = random_logs(rng, rng.randrange(5, 90))
        data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=logs)
        assert_results_match(engine.analyze(data), golden.analyze(data))
    assert (
        engine.frequency.get_frequency_statistics()
        == golden.frequency.get_frequency_statistics()
    )


def test_halo_path_large_batch(mesh8):
    """~1200 lines over 8 shards (Bl=256 > halo) -> ppermute halo path, with
    matches planted straddling every shard boundary."""
    patterns = [
        make_pattern(
            "oom", regex="OutOfMemoryError", confidence=0.9, severity="CRITICAL",
            secondaries=[("GC overhead", 0.6, 100)], context=(5, 5),
        ),
        make_pattern(
            "seq", regex="FAILURE", confidence=0.8, severity="HIGH",
            sequences=[(0.5, ["first thing", "second thing", "FAILURE"])],
        ),
    ]
    lines = [f"line {i}" for i in range(1200)]
    # matches exactly at and around the 8 x 256-row shard edges (256 rows
    # because 1200 pads to 2048... compute: next pow2 of 1200 is 2048 -> Bl=256)
    for edge in range(256, 2048, 256):
        if edge - 1 < 1200:
            lines[edge - 1] = "GC overhead spike"  # secondary on last row of shard
        if edge + 2 < 1200:
            lines[edge + 2] = "java.lang.OutOfMemoryError"  # primary 3 past edge
    lines[10] = "first thing"
    lines[400] = "second thing"
    lines[403] = "FAILURE detected"
    lines[500] = "ERROR context"
    lines[501] = "java.lang.OutOfMemoryError"
    logs = "\n".join(lines)
    sets = [make_pattern_set(patterns)]
    engine = ShardedEngine(sets, ScoringConfig(), mesh=make_mesh(8), clock=FakeClock())
    golden = GoldenAnalyzer(sets, ScoringConfig(), clock=FakeClock())
    data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=logs)
    r1, r2 = engine.analyze(data), golden.analyze(data)
    # 4 shard edges fall below line 1200 (256,512,768,1024) + oom@501 + seq
    assert len(r1.events) == 6  # every planted boundary match fired
    assert_results_match(r1, r2)


def test_single_device_mesh():
    patterns = [make_pattern("e", regex="ERROR", confidence=0.5, severity="LOW")]
    sets = [make_pattern_set(patterns)]
    engine = ShardedEngine(sets, ScoringConfig(), mesh=make_mesh(1), clock=FakeClock())
    golden = GoldenAnalyzer(sets, ScoringConfig(), clock=FakeClock())
    data = PodFailureData(pod={"metadata": {"name": "p"}}, logs="an ERROR\nok")
    assert_results_match(engine.analyze(data), golden.analyze(data))


def test_cross_shard_frequency_order(mesh8):
    """Matches of one pattern spread across shards must see a globally
    consistent read-before-record count order."""
    patterns = [make_pattern("rep", regex="REPEAT", confidence=1.0, severity="INFO")]
    sets = [make_pattern_set(patterns)]
    config = ScoringConfig(frequency_threshold=3.0)
    lines = ["x"] * 640
    for i in range(0, 640, 40):  # 16 matches spread over all shards
        lines[i] = "REPEAT hit"
    logs = "\n".join(lines)
    engine = ShardedEngine(sets, config, mesh=mesh8, clock=FakeClock())
    golden = GoldenAnalyzer(sets, config, clock=FakeClock())
    data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=logs)
    assert_results_match(engine.analyze(data), golden.analyze(data))
