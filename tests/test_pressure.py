"""Resource-exhaustion robustness (runtime/pressure.py).

The contract under test: disk-full, memory pressure and retry storms
NEVER 5xx a request. ENOSPC at any of the six guarded durability sites
(``pressure.DISK_SITES``) is contained where it lands, escalates the
disk ladder to hard, and degrades durability honestly — every response
envelope carries ``durability: degraded`` until recovery re-arms
fsync'd journaling from a clean snapshot barrier. The acceptance
anchor is crash parity ACROSS a pressure episode: a ``kill -9``
(``journal.abandon()``) after the ladder recovered must replay
bit-identically to a run that never saw pressure, because the rearm
barrier snapshots the live tracker that the degraded ring merely
echoed. Around it: the hysteretic ladder itself (forced probes,
watermarks, the 1.25x margin + probe write), the memory lever ladder
(applied one per poll in severity order, released in reverse), retry
budgets (the 10% rule; ``--retry-budget 0`` is the unbounded control),
protocol-journal compaction (migration + epoch) with crash safety at
the compaction boundary, shutdown-writer containment, and the router's
override journal replay. tools/chaos_sweep.py ``--group pressure``
drives the same ladders through live subprocesses.
"""

from __future__ import annotations

import errno
import json
import os
import threading
import urllib.error
import urllib.request

import pytest

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.fleet.ring import HashRing
from log_parser_tpu.fleet.router import OverrideJournal
from log_parser_tpu.models.pod import PodFailureData
from log_parser_tpu.runtime import AnalysisEngine, faults, pressure
from log_parser_tpu.runtime.faults import FaultRegistry
from log_parser_tpu.runtime.journal import JOURNAL_NAME, FrequencyJournal
from log_parser_tpu.runtime.migrate import (
    MIGRATE_DIR,
    DrainSupervisor,
    LocalTarget,
    MigrationError,
    MigrationJournal,
    Migrator,
    compact_journal,
)
from log_parser_tpu.runtime.replicate import (
    EPOCH_JOURNAL,
    REPLICA_DIR,
    LocalReplicaTarget,
    Replicator,
)
from log_parser_tpu.runtime.tenancy import TenantRegistry
from log_parser_tpu.serve import make_server

from helpers import make_pattern, make_pattern_set


@pytest.fixture(autouse=True)
def clean_switchboards():
    faults.install(None)
    pressure.install(None)
    yield
    faults.install(None)
    pressure.install(None)


# ----------------------------------------------------------- harness


def _sets():
    return [
        make_pattern_set(
            [
                make_pattern("oom", regex="OutOfMemoryError", confidence=0.9,
                             severity="CRITICAL", context=(1, 1)),
                make_pattern("conn", regex="Connection refused",
                             confidence=0.7),
                make_pattern("fatal", regex="FATAL", confidence=0.8),
            ]
        )
    ]


REQUESTS = [
    "INFO boot\njava.lang.OutOfMemoryError: heap\nINFO after",
    "WARN x\nConnection refused\nFATAL crash",
    "java.lang.OutOfMemoryError: heap\nINFO again",
    "Connection refused\njava.lang.OutOfMemoryError: heap\nFATAL boom",
]


def _pod(logs: str) -> PodFailureData:
    return PodFailureData(pod={"metadata": {"name": "crash"}}, logs=logs)


def _events(result) -> list[tuple]:
    return [
        (
            e.line_number,
            e.matched_pattern.id if e.matched_pattern else None,
            e.score,
        )
        for e in result.events
    ]


def _ctl(tmp_path, **kw) -> pressure.PressureController:
    return pressure.PressureController(str(tmp_path), **kw)


def _wal(dirname) -> str:
    return os.path.join(str(dirname), JOURNAL_NAME)


def _started_journal(tmp_path, source=None) -> FrequencyJournal:
    """A bare journal with maintenance started (snapshot source wired),
    so degrade()/rearm()/snapshot_now() behave as they do under an
    engine — the rearm barrier needs a live tracker to snapshot."""
    j = FrequencyJournal(str(tmp_path / "wal"), fsync_ms=10_000)
    j.start(source or (lambda: {}), threading.Lock())
    return j


ENOSPC = OSError(errno.ENOSPC, "No space left on device")


def post(url: str, payload):
    body = json.dumps(payload).encode()
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"}
    )
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def get(url: str):
    try:
        with urllib.request.urlopen(url) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


# tenant-root fixtures for the protocol-path legs (migrate/replicate)

ACME_YAML = """
metadata:
  library_id: acme-lib
patterns:
  - id: oom
    name: Out of memory
    severity: CRITICAL
    primary_pattern:
      regex: OutOfMemoryError
      confidence: 0.9
  - id: err
    name: Errors
    severity: LOW
    primary_pattern:
      regex: "\\\\bERROR\\\\b"
      confidence: 0.5
"""


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self) -> float:
        return self.t


@pytest.fixture()
def tenant_root(tmp_path):
    d = tmp_path / "tenants" / "acme"
    d.mkdir(parents=True)
    (d / "lib.yaml").write_text(ACME_YAML)
    return str(tmp_path / "tenants")


def _base_engine(clk=None) -> AnalysisEngine:
    import time as _time

    return AnalysisEngine(
        [make_pattern_set([make_pattern("base", regex="BASE")], "base-lib")],
        ScoringConfig(),
        clock=clk or _time.monotonic,
    )


def _data(blob: str) -> PodFailureData:
    return PodFailureData(pod={"metadata": {"name": "t"}}, logs=blob)


def _mig_side(tmp_path, root, name, clk=None, journaled=False):
    state = tmp_path / name
    state.mkdir(exist_ok=True)
    setup = None
    if journaled:
        def setup(eng, tid):
            eng.attach_journal(str(state / "wal" / tid), wall=clk)

    import time as _time

    reg = TenantRegistry(
        _base_engine(clk), root=root, clock=clk or _time.monotonic,
        engine_setup=setup,
    )
    mig = Migrator(reg, state_root=str(state), node_url=f"local://{name}")
    return reg, mig


def _rep_node(tmp_path, root, name, clk, *, peer=None, target=None):
    state = tmp_path / name
    state.mkdir(exist_ok=True)

    def setup(eng, tid):
        eng.attach_journal(str(state / "wal" / tid), wall=clk)

    reg = TenantRegistry(
        _base_engine(clk), root=root, clock=clk, engine_setup=setup
    )
    rep = Replicator(
        reg, state_root=str(state), node_url=f"local://{name}",
        peer_url=peer, target=target, clock=clk, wall=clk,
    )
    return reg, rep


def _rep_snapshot(reg, tenant="acme"):
    ctx = reg.resolve(tenant, ignore_forward=True)
    try:
        with ctx.engine.state_lock:
            return ctx.engine.frequency.snapshot()
    finally:
        ctx.unpin()


# -------------------------------------------------------- retry budget


class TestRetryBudget:
    def test_floor_lets_cold_destinations_retry_then_sheds(self):
        b = pressure.RetryBudget(0.1)
        assert [b.allow("d") for _ in range(3)] == [True, True, True]
        assert b.allow("d") is False
        assert b.stats()["shed"] == 1 and b.stats()["allowed"] == 3

    def test_first_attempts_deposit_ratio_tokens(self):
        b = pressure.RetryBudget(0.5)
        for _ in range(3):
            assert b.allow("d")
        assert not b.allow("d")  # dry
        for _ in range(4):
            b.note_request("d")  # 4 first attempts x 0.5 = 2 tokens
        assert b.allow("d") and b.allow("d")
        assert not b.allow("d")

    def test_cap_bounds_a_banked_burst(self):
        b = pressure.RetryBudget(1.0, cap=5.0)
        for _ in range(100):
            b.note_request("d")
        assert sum(1 for _ in range(10) if b.allow("d")) == 5

    def test_destinations_are_isolated(self):
        b = pressure.RetryBudget(0.1)
        for _ in range(3):
            assert b.allow("a")
        assert not b.allow("a")
        assert b.allow("b")  # a storm toward one backend starves only it

    def test_retry_storm_fault_sheds_deterministically(self):
        faults.install(FaultRegistry.parse("retry_storm_raise"))
        b = pressure.RetryBudget(0.1)
        assert b.allow("d") is False
        assert b.stats()["shed"] == 1

    def test_zero_ratio_disables_even_under_the_fault(self):
        # the chaos drill's unbounded control: --retry-budget 0 with the
        # same fault armed must allow every retry
        faults.install(FaultRegistry.parse("retry_storm_raise"))
        b = pressure.RetryBudget(0.0)
        assert all(b.allow("d") for _ in range(50))
        assert b.stats()["enabled"] is False and b.stats()["shed"] == 0


# ---------------------------------------------------------- disk ladder


class TestDiskLadder:
    def test_inert_without_watermarks_or_faults(self, tmp_path):
        c = _ctl(tmp_path)
        c.poll()
        assert c.disk_state == "ok" and c.mem_state == "ok"
        assert c.health_check()["status"] == "UP"
        assert not c.durability_degraded()

    def test_soft_reclaims_and_recovers(self, tmp_path):
        c = _ctl(tmp_path)
        pressure.install(c)
        j = _started_journal(tmp_path)
        j.append_match("a", 1)
        assert os.path.getsize(_wal(tmp_path / "wal")) > 0
        c.register_journal(j)
        c.register_compactor("migration", lambda: 2)
        faults.install(FaultRegistry.parse(
            "disk_enospc_raise@match=watermark:soft@times=1"))
        c.poll()
        assert c.disk_state == "soft"
        assert c.miner_park_paused() and pressure.miner_park_paused()
        assert not c.writes_paused()  # soft still journals fsync'd
        assert j.snapshots == 1  # snapshot+truncate rode the soft entry
        assert os.path.getsize(_wal(tmp_path / "wal")) == 0
        assert c.compacted["migration"] == 2
        assert c.health_check()["status"] == "DEGRADED"
        c.poll()  # fault exhausted; no watermark set -> clears at once
        assert c.disk_state == "ok"
        assert c.stats()["transitions"] == {"disk:ok": 1, "disk:soft": 1}
        j.abandon()

    def test_hard_degrades_journals_then_rearms(self, tmp_path):
        c = _ctl(tmp_path)
        pressure.install(c)
        j = _started_journal(tmp_path, source=lambda: {"a": [1.0]})
        c.register_journal(j)
        faults.install(FaultRegistry.parse(
            "disk_enospc_raise@match=watermark:hard@times=2"))
        c.poll()
        assert c.disk_state == "hard"
        assert c.writes_paused() and pressure.durability_degraded()
        assert j.degraded is True
        j.append_match("a", 1)  # diverted: the ring is an echo
        assert j.degraded_records == 1
        assert c.degraded_writes() == 1
        assert pressure.stamp({})["durability"] == "degraded"
        c.poll()  # fault still firing: pinned hard, no flap
        assert c.disk_state == "hard"
        c.poll()  # exhausted -> the probe write proves the disk again
        assert c.disk_state == "ok"
        assert j.degraded is False  # rearm barrier: snapshot + truncate
        assert j.snapshots >= 1
        assert "durability" not in pressure.stamp({})
        assert c.health_check()["status"] == "UP"
        j.abandon()

    def test_watermarks_drive_states_with_hysteresis(self, tmp_path):
        c = _ctl(tmp_path)
        free = c.free_disk_bytes()
        assert free > 0
        c.disk_soft_bytes = free * 2  # free <= soft watermark
        c.poll()
        assert c.disk_state == "soft"
        # free is above the watermark but NOT by the recovery margin:
        # the ladder must hold (hysteresis), not flap
        c.disk_soft_bytes = int(c.free_disk_bytes() / 1.1)
        c.poll()
        assert c.disk_state == "soft"
        # well clear of margin x watermark -> recovers
        c.disk_soft_bytes = int(c.free_disk_bytes() / 2)
        c.poll()
        assert c.disk_state == "ok"

    def test_hard_watermark_goes_straight_to_hard(self, tmp_path):
        c = _ctl(tmp_path)
        c.disk_hard_bytes = c.free_disk_bytes() * 2
        c.poll()
        assert c.disk_state == "hard"
        assert c.stats()["transitions"] == {"disk:hard": 1}

    def test_write_error_pins_hard_immediately(self, tmp_path):
        # ENOSPC observed by a durability writer cannot wait for the
        # next watermark poll — the very next append would race it
        c = _ctl(tmp_path)
        c.note_write_error(ENOSPC, "wal_append")
        assert c.disk_state == "hard" and c.write_errors == 1
        c2 = _ctl(tmp_path)
        c2.note_write_error(OSError(errno.EIO, "I/O error"), "fsync")
        assert c2.disk_state == "hard"

    def test_non_disk_errors_do_not_escalate(self, tmp_path):
        c = _ctl(tmp_path)
        c.note_write_error(OSError(errno.EPERM, "denied"), "wal_append")
        c.note_write_error(ValueError("not an os error"), "wal_append")
        assert c.disk_state == "ok" and c.write_errors == 0

    def test_register_while_hard_degrades_immediately(self, tmp_path):
        c = _ctl(tmp_path)
        c.note_write_error(ENOSPC, "wal_append")
        j = _started_journal(tmp_path)
        c.register_journal(j)
        assert j.degraded is True  # a late tenant WAL gets no fsync lie
        j.abandon()

    def test_closed_journals_are_pruned_not_degraded(self, tmp_path):
        c = _ctl(tmp_path)
        j = _started_journal(tmp_path)
        c.register_journal(j)
        j.close()  # tenant eviction closes its WAL; nothing unregisters
        c.note_write_error(ENOSPC, "wal_append")
        assert j.degraded is False
        assert c.degraded_writes() == 0


# -------------------------------------------------------- memory ladder


class TestMemoryLadder:
    def test_levers_apply_in_order_release_in_reverse(self, tmp_path):
        order = []
        c = _ctl(tmp_path)
        c.add_lever("one", lambda: order.append("+one"),
                    lambda: order.append("-one"))
        c.add_lever("two", lambda: order.append("+two"),
                    lambda: order.append("-two"))
        c.add_lever("three", lambda: order.append("+three"))  # no release
        faults.install(FaultRegistry.parse("mem_pressure_raise@times=2"))
        c.poll()
        assert c.mem_state == "soft" and order == ["+one"]
        c.poll()  # one lever per poll, severity order
        assert order == ["+one", "+two"]
        c.poll()  # fault exhausted -> released in reverse
        assert c.mem_state == "ok"
        assert order == ["+one", "+two", "-two", "-one"]
        assert c.lever_counts == {"one": 1, "two": 1}
        assert c.stats()["transitions"] == {
            "memory:ok": 1, "memory:soft": 1,
        }

    def test_broken_lever_does_not_stop_the_ladder(self, tmp_path):
        order = []
        c = _ctl(tmp_path)

        def boom():
            raise RuntimeError("lever broke")

        c.add_lever("boom", boom)
        c.add_lever("two", lambda: order.append("+two"))
        faults.install(FaultRegistry.parse("mem_pressure_raise@times=2"))
        c.poll()
        c.poll()
        assert order == ["+two"]
        assert "boom" not in c.lever_counts


# --------------------------------------------------- module switchboard


class TestModuleSwitchboard:
    def test_inert_defaults_without_a_controller(self):
        assert pressure.current() is None
        assert pressure.durability_degraded() is False
        assert pressure.writes_paused() is False
        assert pressure.miner_park_paused() is False
        assert pressure.retry_budget() is None
        payload = {"a": 1}
        assert pressure.stamp(payload) is payload
        assert "durability" not in payload
        pressure.note_write_error(ENOSPC, "wal_append")  # no-op, no raise

    def test_installed_controller_answers_for_the_process(self, tmp_path):
        c = _ctl(tmp_path)
        pressure.install(c)
        assert pressure.current() is c
        assert pressure.retry_budget() is c.retry
        c.note_write_error(ENOSPC, "wal_append")
        assert pressure.writes_paused() is True
        assert pressure.stamp({})["durability"] == "degraded"


# -------------------------------- ENOSPC matrix: request-serving sites


class TestEnospcServingPath:
    """wal_append / fsync / snapshot_rotate through a live in-process
    server: every response stays 200 (zero 5xx), the envelope is
    stamped while degraded, and recovery drops the stamp."""

    @pytest.fixture()
    def served(self, tmp_path):
        engine = AnalysisEngine(_sets(), ScoringConfig())
        journal = engine.attach_journal(str(tmp_path / "state"),
                                        fsync_ms=10_000)
        ctl = pressure.PressureController(str(tmp_path / "state"))
        pressure.install(ctl)
        ctl.register_journal(journal)
        server = make_server(engine, host="127.0.0.1", port=0)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        url = f"http://127.0.0.1:{server.server_address[1]}"
        yield url, journal, ctl
        server.shutdown()
        server.server_close()
        journal.abandon()

    def _parse(self, url, logs=REQUESTS[0]):
        return post(url + "/parse",
                    {"pod": {"metadata": {"name": "p"}}, "logs": logs})

    def test_wal_append_enospc_degrades_never_500s(self, served):
        url, journal, ctl = served
        status, body = self._parse(url)
        assert status == 200 and "durability" not in body
        faults.install(FaultRegistry.parse(
            "disk_enospc_raise@match=wal_append@times=1"))
        status, body = self._parse(url)
        assert status == 200  # the injected ENOSPC never surfaces
        assert body.get("durability") == "degraded"
        assert ctl.disk_state == "hard" and journal.degraded
        assert ctl.stats()["writeErrors"] == 1
        # /q/health carries the pressure check; /trace/last the block
        status, health = get(url + "/q/health")
        assert status == 200
        row = next(ch for ch in health["checks"]
                   if ch["name"] == "pressure")
        assert row["status"] == "DEGRADED"
        assert row["data"]["disk"] == "hard"
        status, trace = get(url + "/trace/last")
        assert status == 200 and trace["pressure"]["disk"] == "hard"
        # further requests are echoed to the ring, still 200 + stamped
        status, body = self._parse(url, REQUESTS[1])
        assert status == 200 and body.get("durability") == "degraded"
        assert journal.degraded_records >= 1
        # fault exhausted: one poll probes the disk and re-arms
        faults.install(None)
        ctl.poll()
        status, body = self._parse(url)
        assert status == 200 and "durability" not in body
        assert journal.degraded is False

    def test_fsync_enospc_contained_and_escalates(self, served):
        url, journal, ctl = served
        assert self._parse(url)[0] == 200  # a dirty WAL to fsync
        faults.install(FaultRegistry.parse(
            "disk_enospc_raise@match=fsync@times=1"))
        journal.flush()  # the group-fsync interval, driven by hand
        faults.install(None)
        assert ctl.disk_state == "hard"
        assert journal.healthy is False
        status, body = self._parse(url)
        assert status == 200 and body.get("durability") == "degraded"

    def test_snapshot_rotate_enospc_keeps_the_tail(self, served):
        url, journal, ctl = served
        assert self._parse(url)[0] == 200
        journal.flush()
        tail = os.path.getsize(journal._wal_path)
        assert tail > 0
        faults.install(FaultRegistry.parse(
            "disk_enospc_raise@match=snapshot_rotate@times=1"))
        assert journal.snapshot_now() is False  # aborts WITHOUT truncate
        faults.install(None)
        assert os.path.getsize(journal._wal_path) == tail
        assert journal.snapshot_errors == 1
        assert ctl.disk_state == "hard"
        status, body = self._parse(url)
        assert status == 200 and body.get("durability") == "degraded"


# ------------------------------- ENOSPC matrix: protocol-journal sites


class TestEnospcProtocolPaths:
    def test_bundle_write_enospc_refuses_the_move(self, tenant_root,
                                                  tmp_path):
        reg_a, mig_a = _mig_side(tmp_path, tenant_root, "a")
        reg_b, mig_b = _mig_side(tmp_path, tenant_root, "b")
        ctl = pressure.PressureController(str(tmp_path / "a"))
        pressure.install(ctl)
        try:
            reg_a.resolve("acme").engine.analyze(
                _data("java.lang.OutOfMemoryError: heap"))
            faults.install(FaultRegistry.parse(
                "disk_enospc_raise@match=bundle_write@times=1"))
            with pytest.raises(MigrationError):
                mig_a.migrate("acme", LocalTarget(mig_b, url="local://b"))
            faults.install(None)
            assert ctl.disk_state == "hard"
            # a full disk refuses the move: the tenant stays owned and
            # serving on the source, nothing was staged half-exported
            ctx = reg_a.resolve("acme")
            ctx.engine.analyze(_data("an ERROR here"))
            ctx.unpin()
            assert mig_b.stats()["staged"] == 0
        finally:
            reg_a.shutdown()
            reg_b.shutdown()

    def test_replica_rejournal_enospc_pauses_then_resends(self, tenant_root,
                                                          tmp_path):
        clk = FakeClock()
        reg_b, rep_b = _rep_node(tmp_path, tenant_root, "b", clk,
                                 peer="local://a")
        rep_b.recover()
        target = LocalReplicaTarget(rep_b, url="local://b")
        reg_a, rep_a = _rep_node(tmp_path, tenant_root, "a", clk,
                                 target=target)
        rep_a.recover()
        ctl = pressure.PressureController(str(tmp_path / "b"))
        pressure.install(ctl)
        try:
            ctx = reg_a.resolve("acme")
            sender = rep_a.attach_sender("acme", ctx.engine)
            ctx.engine.analyze(
                _data("java.lang.OutOfMemoryError: heap\nan ERROR here"))
            ctx.unpin()
            faults.install(FaultRegistry.parse(
                "disk_enospc_raise@match=replica_rejournal@times=1"))
            # the standby 503s the batch; the sender contains and backs
            # off — restore is a barrier, so nothing is half-applied
            assert sender.pump() == "error"
            faults.install(None)
            assert sender.send_errors == 1
            assert ctl.disk_state == "hard"
            # while the ladder is hard the sender parks outright
            clk.t += 3600.0
            assert sender.pump() == "paused"
            ctl.poll()  # disk takes writes again
            assert ctl.disk_state == "ok"
            clk.t += 3600.0  # clear the failure backoff
            assert sender.pump() == "seeded"  # the re-send converges
            assert _rep_snapshot(reg_b) == _rep_snapshot(reg_a)
        finally:
            reg_a.shutdown()
            reg_b.shutdown()
            rep_a.stop()
            rep_b.stop()

    def test_otlp_dump_enospc_raises_then_hard_skips(self, tmp_path):
        engine = AnalysisEngine(_sets(), ScoringConfig())
        ctl = pressure.PressureController(str(tmp_path))
        pressure.install(ctl)
        path = str(tmp_path / "spans.json")
        faults.install(FaultRegistry.parse(
            "disk_enospc_raise@match=otlp_dump@times=1"))
        with pytest.raises(OSError):
            engine.obs.spans.dump(path)
        faults.install(None)
        assert ctl.disk_state == "hard"
        assert not os.path.exists(path)  # tmp+rename: no torn file
        # under hard the writer skips atomically instead of raising
        assert engine.obs.spans.dump(path) is None
        ctl.poll()
        assert ctl.disk_state == "ok"
        assert engine.obs.spans.dump(path) == path

    def test_shutdown_containment_is_per_writer(self, tenant_root,
                                                tmp_path):
        # satellite 2: one failing writer during finalization is logged
        # and counted — the drain completes and every OTHER writer runs
        reg_a, mig_a = _mig_side(tmp_path, tenant_root, "a",
                                 journaled=True)
        ctl = pressure.PressureController(str(tmp_path / "a"))
        pressure.install(ctl)
        try:
            reg_a.resolve("acme").engine.analyze(_data("an ERROR here"))
            span_path = str(tmp_path / "spans.json")
            ds = DrainSupervisor(reg_a, mig_a, span_dump_path=span_path)
            faults.install(FaultRegistry.parse(
                "disk_enospc_raise@match=otlp_dump@times=1"))
            out = ds.finalize_all()  # must not raise
            faults.install(None)
            assert out["writerErrors"] == 1  # the span dump, contained
            assert out["folded"] == ["acme"]  # journals still folded
            assert ctl.disk_state == "hard"
            # under hard pressure folds SKIP honestly (rearm owns the
            # recovery barrier) instead of counting phantom errors
            out2 = ds.finalize_all()
            assert out2["writerErrors"] == 0
            assert out2["writersSkipped"] >= 2  # acme + default + span
        finally:
            reg_a.shutdown()


# --------------------------------------- crash parity across pressure


class TestCrashParityAcrossPressure:
    """The acceptance anchor: recovery re-arms fsync'd journaling from
    a clean snapshot barrier, so a kill -9 AFTER a pressure episode
    replays bit-identically to a run that never saw pressure."""

    def _control(self, extra):
        engine = AnalysisEngine(_sets(), ScoringConfig())
        results = [engine.analyze(_pod(logs))
                   for logs in REQUESTS + [extra]]
        return (_events(results[-1]),
                engine.frequency.get_frequency_statistics())

    def test_kill9_after_recovery_replays_bit_identically(self, tmp_path):
        extra = REQUESTS[1]
        want_events, want_stats = self._control(extra)

        first = AnalysisEngine(_sets(), ScoringConfig())
        journal = first.attach_journal(str(tmp_path), fsync_ms=10_000)
        ctl = pressure.PressureController(str(tmp_path))
        pressure.install(ctl)
        ctl.register_journal(journal)

        first.analyze(_pod(REQUESTS[0]))  # fsync'd
        ctl.note_write_error(ENOSPC, "wal_append")  # disk fills
        assert journal.degraded is True
        for logs in REQUESTS[1:3]:  # echoed to the ring only
            first.analyze(_pod(logs))
        assert journal.degraded_records >= 1
        ctl.poll()  # disk takes writes again: hard -> ok + rearm barrier
        assert ctl.disk_state == "ok" and journal.degraded is False
        first.analyze(_pod(REQUESTS[3]))  # fsync'd again
        journal.abandon()  # kill -9 after the episode
        pressure.install(None)

        second = AnalysisEngine(_sets(), ScoringConfig())
        second.attach_journal(str(tmp_path), fsync_ms=10_000)
        result = second.analyze(_pod(extra))
        assert _events(result) == want_events
        assert second.frequency.get_frequency_statistics() == want_stats
        second.journal.abandon()

    def test_kill9_during_hard_loses_only_the_diverted_window(
            self, tmp_path):
        # the documented exposure: a crash WHILE degraded loses exactly
        # the ring-diverted records — never the fsync'd prefix
        control = AnalysisEngine(_sets(), ScoringConfig())
        control.analyze(_pod(REQUESTS[0]))
        want = control.frequency.get_frequency_statistics()

        first = AnalysisEngine(_sets(), ScoringConfig())
        journal = first.attach_journal(str(tmp_path), fsync_ms=10_000)
        ctl = pressure.PressureController(str(tmp_path))
        pressure.install(ctl)
        ctl.register_journal(journal)
        first.analyze(_pod(REQUESTS[0]))
        ctl.note_write_error(ENOSPC, "wal_append")
        first.analyze(_pod(REQUESTS[1]))  # diverted, stamped degraded
        journal.abandon()
        pressure.install(None)

        second = AnalysisEngine(_sets(), ScoringConfig())
        second.attach_journal(str(tmp_path), fsync_ms=10_000)
        assert second.frequency.get_frequency_statistics() == want
        second.journal.abandon()


# --------------------------------------- protocol-journal compaction


class TestMigrationJournalCompaction:
    def _terminal_src(self, path):
        jr = MigrationJournal(path)
        jr.append("begin", mid="m1", tenant="ghost", target="local://b")
        jr.append("quiesce")
        jr.append("export", sha="x")
        jr.append("import_ack", sha="x")
        jr.append("cutover", location="local://b", retryAfterS=5)
        jr.append("complete")
        jr.close()

    def test_terminal_source_compacts_to_decision_records(self, tmp_path):
        path = str(tmp_path / "m1.src.wal")
        self._terminal_src(path)
        before = os.stat(path).st_mtime
        assert compact_journal(path) is True
        recs = MigrationJournal.replay(path)
        assert [r["k"] for r in recs] == ["begin", "cutover", "complete"]
        assert recs[1]["location"] == "local://b"
        # mtime arbitrates ownership verdicts: compaction preserves it
        assert os.stat(path).st_mtime == before
        assert compact_journal(path) is False  # idempotent

    def test_non_terminal_journals_are_left_alone(self, tmp_path):
        path = str(tmp_path / "m2.src.wal")
        jr = MigrationJournal(path)
        jr.append("begin", mid="m2", tenant="ghost", target="local://b")
        jr.append("quiesce")
        jr.close()
        assert compact_journal(path) is False
        assert len(MigrationJournal.replay(path)) == 2

    def test_crash_at_the_compaction_boundary_is_safe(self, tenant_root,
                                                      tmp_path):
        # satellite 1: a crash between tmp write and replace leaves the
        # original journal intact plus a stale .compact tmp; the next
        # pass sweeps the tmp, compacts, and recover() still installs
        # the same forward from the decision records
        reg, mig = _mig_side(tmp_path, tenant_root, "a")
        try:
            mdir = os.path.join(str(tmp_path / "a"), MIGRATE_DIR)
            os.makedirs(mdir, exist_ok=True)
            path = os.path.join(mdir, "m1.src.wal")
            self._terminal_src(path)
            with open(path + ".compact", "wb") as f:
                f.write(b"torn garbage from a crashed pass")
            assert mig.compact() == 1
            assert not os.path.exists(path + ".compact")
            recs = MigrationJournal.replay(path)
            assert [r["k"] for r in recs] == ["begin", "cutover",
                                              "complete"]
            mig.recover()
            assert reg.forward_for("ghost") == ("local://b", 5)
        finally:
            reg.shutdown()


class TestEpochJournalCompaction:
    def test_compaction_preserves_the_recover_verdict(self, tenant_root,
                                                      tmp_path):
        state = tmp_path / "b"
        state.mkdir()
        jr = MigrationJournal(str(state / REPLICA_DIR / EPOCH_JOURNAL))
        jr.append("epoch", epoch=1, tenants=["acme"])
        jr.append("epoch", epoch=3, tenants=["globex"])
        jr.append("epoch", epoch=2, tenants=["acme"])
        jr.close()
        reg1, rep1 = _rep_node(tmp_path, tenant_root, "b", FakeClock())
        try:
            s1 = rep1.recover()
            assert s1["records"] == 3 and s1["epoch"] == 3
            assert rep1.compact_epoch_journal() == 1
        finally:
            reg1.shutdown()
            rep1.stop()
        reg2, rep2 = _rep_node(tmp_path, tenant_root, "b", FakeClock())
        try:
            s2 = rep2.recover()
            assert s2["records"] == 1  # the whole history, one record
            assert s2["epoch"] == s1["epoch"]
            assert s2["tenants"] == s1["tenants"]
            assert s2["role"] == s1["role"]
        finally:
            reg2.shutdown()
            rep2.stop()


# ------------------------------------------------- override journal


class TestOverrideJournal:
    BACKENDS = ["http://10.0.0.1:8080", "http://10.0.0.2:8080"]

    def _other(self, ring, tenant):
        owner = ring.owner(tenant)
        return next(b for b in self.BACKENDS if b != owner)

    def test_replay_restores_learned_placements(self, tmp_path):
        ring = HashRing(list(self.BACKENDS))
        oj = OverrideJournal(str(tmp_path))
        moved = self._other(ring, "acme")
        assert ring.set_override("acme", moved)
        oj.note("acme", moved)
        oj.close()
        # router restart: replay teaches the fresh ring the placement
        ring2 = HashRing(list(self.BACKENDS))
        oj2 = OverrideJournal(str(tmp_path))
        out = oj2.recover(ring2)
        assert out == {"applied": 1, "stale": 0}
        assert ring2.owner("acme") == moved
        # and the log is compacted to exactly the live set
        recs = MigrationJournal.replay(oj2.path)
        assert [(r["tenant"], r["backend"]) for r in recs] == [
            ("acme", moved)]
        oj2.close()

    def test_cleared_stale_and_redundant_records_self_resolve(
            self, tmp_path):
        ring = HashRing(list(self.BACKENDS))
        oj = OverrideJournal(str(tmp_path))
        oj.note("t-cleared", self._other(ring, "t-cleared"))
        oj.note("t-cleared", None)  # cleared later: last record wins
        oj.note("t-stale", "http://gone.example:1")  # left the ring
        oj.note("t-redundant", ring.owner("t-redundant"))  # hash owner
        oj.close()
        ring2 = HashRing(list(self.BACKENDS))
        oj2 = OverrideJournal(str(tmp_path))
        out = oj2.recover(ring2)
        assert out == {"applied": 1, "stale": 1}  # redundant applies,
        # drops out; the non-member backend is the only stale entry
        assert ring2.overrides() == {}
        assert MigrationJournal.replay(oj2.path) == []  # compacted away
        oj2.close()

    def test_append_failure_is_contained_and_escalates(self, tmp_path):
        ctl = pressure.PressureController(str(tmp_path))
        pressure.install(ctl)
        oj = OverrideJournal(str(tmp_path))

        def boom(*a, **k):
            raise OSError(errno.ENOSPC, "No space left on device")

        oj._journal.append = boom
        oj.note("acme", self.BACKENDS[0])  # contained: never raises
        assert oj.stats()["writeErrors"] == 1
        assert ctl.disk_state == "hard"  # the ladder heard about it
        oj.close()
