"""AC prefilter + per-record verify tier (ops/prefilter.py) vs the host
reference and the dense DFA path it replaces — including the in-program
dense fallback on capacity overflow (VERDICT.md round-1 next #3)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.models.pattern import (
    Pattern,
    PatternSet,
    PatternSetMetadata,
    PrimaryPattern,
)
from log_parser_tpu.ops.encode import encode_lines
from log_parser_tpu.ops.match import MatcherBanks
from log_parser_tpu.patterns.bank import PatternBank

from helpers import make_pattern, make_pattern_set


def _bank_of(regexes: list[str]) -> PatternBank:
    patterns = [
        Pattern(
            id=f"p{i}",
            name=f"p{i}",
            severity="HIGH",
            primary_pattern=PrimaryPattern(regex=rx, confidence=0.5),
        )
        for i, rx in enumerate(regexes)
    ]
    return PatternBank(
        [PatternSet(metadata=PatternSetMetadata(library_id="t"), patterns=patterns)]
    )


# literal-bearing but not literal-shaped: these must land in the prefilter
# tier when it is engaged
PREF_REGEXES = [
    "conn-%03d: (refused|reset)" % i for i in range(20)
] + [
    "svc-%03d\\s+(fatal|panic)" % i for i in range(20)
] + [
    "^\\d+ node-%03d down" % i for i in range(20)
]


def _host_cube(bank: PatternBank, lines: list[str]) -> np.ndarray:
    out = np.zeros((len(lines), bank.n_columns), dtype=bool)
    for i, line in enumerate(lines):
        for c, col in enumerate(bank.columns):
            out[i, c] = bool(col.host.search(line))
    return out


def _device_cube(mb: MatcherBanks, lines: list[str]) -> np.ndarray:
    enc = encode_lines(lines)
    cube = np.asarray(mb.cube(jnp.asarray(enc.u8.T), jnp.asarray(enc.lengths)))
    return cube[: len(lines)]


def _lines_sparse(n: int = 200) -> list[str]:
    rng = np.random.default_rng(11)
    lines = []
    for j in range(n):
        r = j % 17
        if r == 3:
            i = int(rng.integers(0, 20))
            lines.append(f"conn-{i:03d}: refused")
        elif r == 5:
            i = int(rng.integers(0, 20))
            lines.append(f"svc-{i:03d}  fatal")
        elif r == 7:
            i = int(rng.integers(0, 20))
            lines.append(f"77 node-{i:03d} down")
        elif r == 9:  # literal present but regex does NOT match (verify must kill)
            lines.append("conn-001: accepted")
        elif r == 11:  # case-folded literal hit, regex is case-sensitive
            lines.append("CONN-002: REFUSED")
        else:
            lines.append(f"INFO tick {j} all ok")
    return lines


class TestPrefilterTier:
    def test_engaged_for_wide_banks(self):
        bank = _bank_of(PREF_REGEXES)
        mb = MatcherBanks(bank, prefilter_min_columns=32, shiftor_min_columns=10 ** 9,
                          multi_min_columns=10 ** 9, bitglush_max_words=0)
        assert mb.prefilter is not None
        assert len(mb.prefilter_cols) >= 32
        # dense DFA bank shrank accordingly
        assert set(mb.prefilter_cols).isdisjoint(mb.dfa_cols)

    def test_not_engaged_below_threshold(self):
        bank = _bank_of(PREF_REGEXES[:10])
        mb = MatcherBanks(bank)
        assert mb.prefilter is None

    def test_sparse_path_parity_with_host(self):
        bank = _bank_of(PREF_REGEXES)
        pref = MatcherBanks(bank, prefilter_min_columns=32, shiftor_min_columns=10 ** 9,
                            multi_min_columns=10 ** 9, bitglush_max_words=0)
        dense = MatcherBanks(bank, prefilter_min_columns=10 ** 9, shiftor_min_columns=10 ** 9,
                             multi_min_columns=10 ** 9, bitglush_max_words=0)
        assert pref.prefilter is not None and dense.prefilter is None
        lines = _lines_sparse()
        want = _host_cube(bank, lines)
        np.testing.assert_array_equal(_device_cube(pref, lines), want)
        np.testing.assert_array_equal(_device_cube(dense, lines), want)

    def test_overflow_falls_back_dense_and_stays_exact(self):
        """Every line carries literals -> hit compaction overflows -> the
        lax.cond dense branch must produce identical results."""
        bank = _bank_of(PREF_REGEXES)
        pref = MatcherBanks(bank, prefilter_min_columns=32, shiftor_min_columns=10 ** 9,
                            multi_min_columns=10 ** 9, bitglush_max_words=0)
        lines = [f"conn-{i % 20:03d}: refused and svc-{i % 20:03d}  fatal" for i in range(512)]
        want = _host_cube(bank, lines)
        np.testing.assert_array_equal(_device_cube(pref, lines), want)

    def test_engine_parity_with_prefilter_engaged(self):
        """Full engine vs golden on a library wide enough to engage the
        prefilter via the default threshold."""
        from log_parser_tpu.golden import GoldenAnalyzer
        from log_parser_tpu.models import PodFailureData
        from log_parser_tpu.runtime import AnalysisEngine

        from test_engine_parity import assert_results_match

        # \s+ keeps these out of the fixed-length Shift-Or tier so they
        # exercise the prefilter through the default thresholds
        regexes = ["conn-%03d:\\s+(refused|reset)" % i for i in range(70)]
        patterns = [
            make_pattern(f"p{i}", regex=rx, confidence=0.6, severity="MEDIUM")
            for i, rx in enumerate(regexes)
        ]
        sets = [make_pattern_set(patterns)]
        engine = AnalysisEngine(sets, ScoringConfig())
        # a gather-free tier absorbs these columns at default thresholds
        # (bit-parallel first, union multi-DFA for what it rejects)
        assert engine.matchers.multi_groups or engine.matchers.bitglush_cols
        logs = "\n".join(_lines_sparse(150))
        data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=logs)
        golden = GoldenAnalyzer(sets, ScoringConfig())
        assert_results_match(engine.analyze(data), golden.analyze(data))
