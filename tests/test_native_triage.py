"""Native loader triage (native/__init__.py + tools/check_native.py):
the GLIBCXX required-vs-provided diagnosis and its bounded /metrics
reason. These run with or without a loadable library — the triage is
exactly for the hosts where it does NOT load."""

from __future__ import annotations

import os
import sys

from log_parser_tpu import native
from log_parser_tpu.obs import native_load_reason


def test_glibcxx_versions_reads_symbol_tags(tmp_path):
    blob = tmp_path / "fake.so"
    blob.write_bytes(
        b"\x00GLIBCXX_3.4\x00junk\x00GLIBCXX_3.4.29\x00GLIBCXX_3.4.21\x00"
        b"GLIBCXX_3.4\x00not-a-tag GLIBCX_9.9\x00"
    )
    got = native._glibcxx_versions(blob)
    assert got == [(3, 4), (3, 4, 21), (3, 4, 29)]
    assert native._glibcxx_versions(tmp_path / "absent.so") == []


def test_triage_names_the_gap(tmp_path, monkeypatch):
    so = tmp_path / "scanner.so"
    so.write_bytes(b"\x00GLIBCXX_3.4\x00GLIBCXX_3.4.99\x00")
    host = tmp_path / "libstdc++.so.6"
    host.write_bytes(b"\x00GLIBCXX_3.4\x00GLIBCXX_3.4.28\x00")
    monkeypatch.setattr(native, "find_libstdcxx", lambda: str(host))
    tri = native.glibcxx_triage(so)
    assert tri["required"] == ["GLIBCXX_3.4", "GLIBCXX_3.4.99"]
    assert tri["provided"] == ["GLIBCXX_3.4", "GLIBCXX_3.4.28"]
    # only versions NEWER than everything the host exports are the gap
    assert tri["missing"] == ["GLIBCXX_3.4.99"]
    assert tri["libstdcxx"] == str(host)


def test_find_libstdcxx_points_at_a_real_file():
    path = native.find_libstdcxx()
    # every host this suite runs on links C++ somewhere (JAX does)
    assert path is not None and os.path.exists(path)
    assert "libstdc++" in os.path.basename(path)


def test_reason_vocabulary_maps_glibcxx_mismatch():
    err = ("glibcxx mismatch: needs GLIBCXX_3.4.29; host libstdc++ tops "
           "out at GLIBCXX_3.4.28 — rebuild on this host")
    doc = {"available": False, "loadError": err}
    assert native_load_reason(doc) == "glibcxx_mismatch"
    assert native_load_reason({"available": True}) == "ok"
    assert native_load_reason(
        {"available": False, "loadError": "load failed: boom"}
    ) == "load_failed"


def test_check_native_tool_reports_without_booting():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    try:
        import check_native
    finally:
        sys.path.pop(0)
    doc = check_native.triage()
    assert doc["source_exists"] is True
    assert isinstance(doc["glibcxx"]["required"], list)
    # the tool's verdict agrees with the runtime loader's
    assert doc["loaded"] == native.available()
    if not doc["loaded"]:
        assert doc["load_error"]
