"""Multi-process (DCN) scale-out: a 2-process CPU mesh running the sharded
engine in lockstep (parallel/distributed.py; SURVEY.md §2.2/§5.8 — the
reference has no inter-process story at all; this is the jax.distributed
equivalent of scaling past one host).

The test spawns two fresh Python processes (4 virtual CPU devices each →
one 8-device global mesh), has the coordinator broadcast two requests
through DistributedShardedEngine, and asserts the coordinator's scores
match the single-process GoldenAnalyzer exactly. The subprocess boundary
is real: collectives ride the distributed runtime (Gloo), not shared
memory.
"""

from __future__ import annotations

import json
import socket
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]

# some jaxlib builds (e.g. 0.4.x) have no multi-process collective support
# on the CPU backend at all — then the 2-process harness cannot run here
# and the stubbed single-process coverage in test_resilience.py carries
# the dispatch/degrade logic instead
_NO_CPU_MULTIPROCESS = "Multiprocess computations aren't implemented"


def _skip_if_unsupported(outs):
    if any(_NO_CPU_MULTIPROCESS in out for out in outs):
        pytest.skip("CPU backend lacks multi-process collectives")

_WORKER = textwrap.dedent(
    """
    import json, os, sys

    pid = int(sys.argv[1])
    port = sys.argv[2]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    os.environ["LOG_PARSER_TPU_NO_FALLBACK"] = "1"
    import jax
    jax.config.update("jax_platforms", "cpu")

    from log_parser_tpu.parallel.distributed import (
        DistributedShardedEngine,
        init_distributed,
    )

    init_distributed(f"127.0.0.1:{port}", num_processes=2, process_id=pid)
    assert jax.device_count() == 8, jax.device_count()
    assert jax.local_device_count() == 4

    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.models.pod import PodFailureData
    from log_parser_tpu.models.pattern import (
        ContextExtraction, Pattern, PatternSet, PatternSetMetadata,
        PrimaryPattern, SecondaryPattern,
    )
    from log_parser_tpu.parallel import make_mesh

    sets = [PatternSet(
        metadata=PatternSetMetadata(library_id="dist-lib", name="dist"),
        patterns=[
            Pattern(
                id="oom", name="oom", severity="HIGH",
                primary_pattern=PrimaryPattern(regex="OutOfMemoryError", confidence=0.8),
                secondary_patterns=[SecondaryPattern(
                    regex="GC overhead", weight=0.6, proximity_window=10)],
                context_extraction=ContextExtraction(lines_before=2, lines_after=1),
            ),
            Pattern(
                id="conn", name="conn", severity="MEDIUM",
                primary_pattern=PrimaryPattern(regex="Connection refused", confidence=0.7),
            ),
        ],
    )]

    engine = DistributedShardedEngine(sets, ScoringConfig(), mesh=make_mesh())

    logs = "\\n".join(
        "GC overhead limit" if i == 17
        else "java.lang.OutOfMemoryError: heap" if i == 20
        else "dial tcp: Connection refused" if i in (3, 44)
        else f"INFO tick {i}"
        for i in range(64)
    )
    data = PodFailureData(pod={"metadata": {"name": "dist"}}, logs=logs)

    if pid == 0:
        r1 = engine.analyze(data)
        r2 = engine.analyze(data)  # second batch: frequency state advanced
        engine.shutdown_followers()
        print("RESULT " + json.dumps({
            "scores1": [e.score for e in r1.events],
            "lines1": [e.line_number for e in r1.events],
            "ids1": [e.matched_pattern.id for e in r1.events],
            "scores2": [e.score for e in r2.events],
        }), flush=True)
    else:
        engine.follower_loop()
        print("FOLLOWER_DONE", flush=True)
    """
)


def test_two_process_mesh_matches_golden():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(pid), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            cwd=REPO,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    _skip_if_unsupported(outs)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out[-3000:]}"
    assert "FOLLOWER_DONE" in outs[1], outs[1][-2000:]

    result = json.loads(outs[0].split("RESULT ", 1)[1].splitlines()[0])

    # golden single-process truth for the same two-batch request stream
    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.golden import GoldenAnalyzer
    from log_parser_tpu.models import PodFailureData

    from helpers import make_pattern, make_pattern_set

    sets = [
        make_pattern_set(
            [
                make_pattern(
                    "oom", regex="OutOfMemoryError", confidence=0.8,
                    severity="HIGH", secondaries=[("GC overhead", 0.6, 10)],
                    context=(2, 1),
                ),
                make_pattern(
                    "conn", regex="Connection refused", confidence=0.7,
                    severity="MEDIUM",
                ),
            ],
            library_id="dist-lib",
        )
    ]
    logs = "\n".join(
        "GC overhead limit" if i == 17
        else "java.lang.OutOfMemoryError: heap" if i == 20
        else "dial tcp: Connection refused" if i in (3, 44)
        else f"INFO tick {i}"
        for i in range(64)
    )
    golden = GoldenAnalyzer(sets, ScoringConfig())
    data = PodFailureData(pod={"metadata": {"name": "dist"}}, logs=logs)
    g1 = golden.analyze(data)
    g2 = golden.analyze(data)

    assert result["ids1"] == [e.matched_pattern.id for e in g1.events]
    assert result["lines1"] == [e.line_number for e in g1.events]
    assert result["scores1"] == [e.score for e in g1.events]
    assert result["scores2"] == [e.score for e in g2.events]


_CHAOS_WORKER = textwrap.dedent(
    """
    import json, os, sys

    pid = int(sys.argv[1])
    port = sys.argv[2]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    os.environ["LOG_PARSER_TPU_NO_FALLBACK"] = "1"
    if pid == 0:
        # a follower stalls every dispatch after the first request; the
        # bounded broadcast (2s x 2 attempts) must flip the coordinator to
        # degrade-to-local instead of deadlocking
        os.environ["LOG_PARSER_TPU_FAULTS"] = "follower_hang:30@after=1"
        os.environ["LOG_PARSER_TPU_BROADCAST_TIMEOUT_S"] = "2"
        os.environ["LOG_PARSER_TPU_BROADCAST_RETRIES"] = "1"
        os.environ["LOG_PARSER_TPU_BROADCAST_BACKOFF_S"] = "0.05"
        os.environ["LOG_PARSER_TPU_DEAD_AFTER"] = "2"
    import jax
    jax.config.update("jax_platforms", "cpu")

    from log_parser_tpu.parallel.distributed import (
        DistributedShardedEngine,
        init_distributed,
    )

    init_distributed(f"127.0.0.1:{port}", num_processes=2, process_id=pid)

    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.models.pod import PodFailureData
    from log_parser_tpu.models.pattern import (
        Pattern, PatternSet, PatternSetMetadata, PrimaryPattern,
    )
    from log_parser_tpu.parallel import make_mesh
    from log_parser_tpu.runtime import faults

    faults.ensure_env()
    sets = [PatternSet(
        metadata=PatternSetMetadata(library_id="chaos-lib", name="chaos"),
        patterns=[
            Pattern(
                id="oom", name="oom", severity="HIGH",
                primary_pattern=PrimaryPattern(
                    regex="OutOfMemoryError", confidence=0.8),
            ),
            Pattern(
                id="conn", name="conn", severity="MEDIUM",
                primary_pattern=PrimaryPattern(
                    regex="Connection refused", confidence=0.7),
            ),
        ],
    )]
    engine = DistributedShardedEngine(sets, ScoringConfig(), mesh=make_mesh())

    logs = "\\n".join(
        "java.lang.OutOfMemoryError: heap" if i == 20
        else "dial tcp: Connection refused" if i in (3, 44)
        else f"INFO tick {i}"
        for i in range(64)
    )
    data = PodFailureData(pod={"metadata": {"name": "chaos"}}, logs=logs)

    if pid == 0:
        # r1 dispatches cleanly; r2 exhausts the retry budget against the
        # hang and flips degraded; r3 serves inside the degraded window
        results = [engine.analyze(data) for _ in range(3)]
        faults.active().lift()  # the "follower" recovers
        probed = engine.probe_mesh()
        results.append(engine.analyze(data))  # back on the full mesh
        stats = engine.mesh_health.stats()
        engine.shutdown_followers()
        print("RESULT " + json.dumps({
            "degraded": [
                r.metadata.degraded if r.metadata else None for r in results
            ],
            "ids": [[e.matched_pattern.id for e in r.events] for r in results],
            "lines": [[e.line_number for e in r.events] for r in results],
            "probed": probed,
            "mode": stats["mode"],
            "timeouts": stats["broadcastTimeouts"],
            "degradedRequests": stats["degradedRequests"],
            "readmissions": stats["readmissions"],
        }), flush=True)
    else:
        engine.follower_loop()
        print("FOLLOWER_DONE", flush=True)
    """
)


@pytest.mark.slow
@pytest.mark.chaos
def test_follower_hang_degrades_to_local_then_readmits():
    """ISSUE 2 acceptance: with a seeded follower hang every request still
    completes — the degraded window is visible in response metadata, the
    probe re-admits the mesh, and the group shuts down cleanly."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _CHAOS_WORKER, str(pid), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            cwd=REPO,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    _skip_if_unsupported(outs)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out[-3000:]}"
    assert "FOLLOWER_DONE" in outs[1], outs[1][-2000:]

    result = json.loads(outs[0].split("RESULT ", 1)[1].splitlines()[0])
    marker = "distributed-fallback"
    assert result["degraded"] == [None, marker, marker, None]
    # every request found the same events regardless of serving path
    assert all(ids == result["ids"][0] for ids in result["ids"][1:])
    assert all(ln == result["lines"][0] for ln in result["lines"][1:])
    assert sorted(result["ids"][0]) == ["conn", "conn", "oom"]
    assert result["probed"] is True
    assert result["mode"] == "distributed"  # re-admitted before shutdown
    assert result["timeouts"] == 2  # r2: initial attempt + one retry
    assert result["degradedRequests"] == 2
    assert result["readmissions"] == 1
