"""Observability: per-phase timers and the factor-dump debug surface
(SURVEY.md §5.1/§5.5 — absent in the reference, whose only timing is
processingTimeMs, AnalysisService.java:169)."""

from __future__ import annotations

import json
import math

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.models.pod import PodFailureData
from log_parser_tpu.runtime import AnalysisEngine

from helpers import make_pattern, make_pattern_set


def _engine():
    ps = make_pattern_set(
        [
            make_pattern(
                "oom", regex="OutOfMemoryError", confidence=0.8, severity="HIGH",
                secondaries=[("GC overhead", 0.6, 10)], context=(2, 2),
            )
        ]
    )
    return AnalysisEngine([ps], ScoringConfig())


def test_phase_trace_and_factor_dump():
    engine = _engine()
    logs = "boot\nGC overhead limit\nfiller\njava.lang.OutOfMemoryError: heap\ndone"
    result = engine.analyze(PodFailureData(pod={"metadata": {"name": "p"}}, logs=logs))
    assert len(result.events) == 1

    trace = engine.last_trace
    assert trace is not None
    assert set(trace.phases) >= {"ingest", "device", "finalize", "assemble"}
    assert trace.total > 0

    fin = engine.last_finalized
    rows = fin.factor_rows(engine.bank)
    assert len(rows) == 1
    row = rows[0]
    assert row["patternId"] == "oom"
    assert row["lineNumber"] == 4
    # product of the dumped factors must reproduce the score exactly
    product = (
        row["confidence"] * row["severityMultiplier"] * row["chronological"]
        * row["proximity"] * row["temporal"] * row["context"]
        * (1.0 - row["frequencyPenalty"])
    )
    assert math.isclose(product, row["score"], rel_tol=0, abs_tol=0)
    assert json.dumps(rows)  # JSON-ready


def test_factor_values_match_hand_computation():
    engine = _engine()
    logs = "boot\nGC overhead limit\nfiller\njava.lang.OutOfMemoryError: heap\ndone"
    engine.analyze(PodFailureData(pod={"metadata": {"name": "p"}}, logs=logs))
    row = engine.last_finalized.factor_rows(engine.bank)[0]
    assert row["proximity"] == 1.0 + 0.6 * math.exp(-2.0 / 10.0)
    assert row["temporal"] == 1.0
    # window lines 2-5: only the matched line hits \w*Error -> +0.3
    assert row["context"] == 1.3
    assert row["frequencyPenalty"] == 0.0
