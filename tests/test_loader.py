"""Pattern-directory loading: recursion, filtering, skip-bad-files."""

import os

from log_parser_tpu.patterns import load_pattern_directory

GOOD_YAML = """
metadata:
  library_id: core
patterns:
  - id: oom
    name: Out of memory
    severity: CRITICAL
    primary_pattern:
      regex: OutOfMemoryError
      confidence: 0.9
"""

OTHER_YAML = """
metadata:
  library_id: net
patterns:
  - id: conn
    name: Connection refused
    severity: HIGH
    primary_pattern:
      regex: "Connection refused"
      confidence: 0.7
"""


def test_loads_recursively_and_skips_bad(tmp_path):
    (tmp_path / "core.yaml").write_text(GOOD_YAML)
    sub = tmp_path / "sub"
    sub.mkdir()
    (sub / "net.yml").write_text(OTHER_YAML)
    (tmp_path / "broken.yaml").write_text("patterns: [unclosed")  # invalid YAML
    (tmp_path / "scalar.yml").write_text("just a string")  # not a mapping
    (tmp_path / "notes.txt").write_text("ignored")  # wrong extension

    sets = load_pattern_directory(str(tmp_path))
    ids = sorted(ps.metadata.library_id for ps in sets)
    assert ids == ["core", "net"]


def test_missing_directory_yields_empty(tmp_path):
    assert load_pattern_directory(str(tmp_path / "nope")) == []


def test_file_path_yields_empty(tmp_path):
    path = tmp_path / "f.yaml"
    path.write_text(GOOD_YAML)
    assert load_pattern_directory(str(path)) == []


def test_deterministic_order(tmp_path):
    for name in ["b.yaml", "a.yaml", "c.yml"]:
        lib = name.split(".")[0]
        (tmp_path / name).write_text(f"metadata:\n  library_id: {lib}\npatterns: []\n")
    sets = load_pattern_directory(str(tmp_path))
    assert [ps.metadata.library_id for ps in sets] == ["a", "b", "c"]
    assert os.path.isdir(tmp_path)
