"""Regex→DFA compiler: equivalence with Python re (Java-default semantics),
state caps, unsupported-construct rejection."""

import random
import re

import pytest

from log_parser_tpu.patterns.regex import (
    DfaLimitError,
    RegexUnsupportedError,
    compile_regex_to_dfa,
)

# Regexes spanning the dialect floor (the reference's own context regexes,
# ContextAnalysisService.java:27-34) plus the constructs pattern libraries use.
REGEXES = [
    r"OutOfMemoryError",
    r"Connection refused",
    r"\b(ERROR|FATAL|CRITICAL|SEVERE)\b",
    r"\b(WARN|WARNING)\b",
    r"^\s*at\s+[\w\.\$]+\(.*\)\s*$",
    r"\b\w*Exception\b|\b\w*Error\b",
    r"a{2,4}b",
    r"x(yz)+w",
    r"foo$",
    r"^foo",
    r"a.c",
    r"\d+\.\d+",
    r"\bat\b",
    r"colou?r",
    r"[A-Fa-f0-9]{8}",
    r"(GET|POST|PUT)\s+/\S*",
    r"exit code [1-9]\d*",
    r"\bOOM[- ]?killed\b",
    r"[a-z]+_[a-z]+",
    r"^$",
    r".*",
    r"err(or)*s?",
    r"\.{3}",
    r"[^abc]+z",
    r"\x41\x42",
]

CI_REGEXES = [
    r"\b(error|fatal)\b",
    r"warn(ing)?",
    r"out of memory",
]

ALPHABET = "abcERORWatx yz_()$.0189\tF/+-"


def random_lines(seed: int, count: int = 200, maxlen: int = 40) -> list[str]:
    rng = random.Random(seed)
    lines = []
    for _ in range(count):
        n = rng.randrange(maxlen)
        lines.append("".join(rng.choice(ALPHABET) for _ in range(n)))
    # adversarial seeds: fragments of the regexes themselves
    for rx in REGEXES:
        stripped = re.sub(r"[\\^$*+?{}()\[\]|]", "", rx)
        lines.append(stripped)
        lines.append(stripped[: len(stripped) // 2])
        lines.append(" " + stripped + " ")
    return lines


class TestDfaEquivalence:
    @pytest.mark.parametrize("rx", REGEXES)
    def test_matches_python_re(self, rx):
        dfa = compile_regex_to_dfa(rx)
        py = re.compile(rx, re.ASCII)
        for line in random_lines(hash(rx) % 2**32):
            want = bool(py.search(line))
            got = dfa.matches(line.encode())
            assert got == want, f"{rx!r} on {line!r}: dfa={got} re={want}"

    @pytest.mark.parametrize("rx", CI_REGEXES)
    def test_case_insensitive(self, rx):
        dfa = compile_regex_to_dfa(rx, case_insensitive=True)
        py = re.compile(rx, re.ASCII | re.IGNORECASE)
        for line in random_lines(hash(rx) % 2**32):
            for variant in (line, line.upper(), line.lower()):
                want = bool(py.search(variant))
                got = dfa.matches(variant.encode())
                assert got == want, f"{rx!r} on {variant!r}"

    def test_empty_line(self):
        assert compile_regex_to_dfa(r".*").matches(b"")
        assert compile_regex_to_dfa(r"^$").matches(b"")
        assert not compile_regex_to_dfa(r"x").matches(b"")

    def test_word_boundary_at_line_edges(self):
        dfa = compile_regex_to_dfa(r"\bERROR\b")
        assert dfa.matches(b"ERROR")  # boundaries at both line edges
        assert dfa.matches(b"ERROR at end")
        assert dfa.matches(b"at start ERROR")
        assert not dfa.matches(b"ERRORx")
        assert not dfa.matches(b"xERROR")

    def test_non_word_boundary(self):
        dfa = compile_regex_to_dfa(r"er\Br")
        py = re.compile(r"er\Br", re.ASCII)
        for line in ["error", "er r", "xerr", "er"]:
            assert dfa.matches(line.encode()) == bool(py.search(line))

    def test_quoted_literal(self):
        # \Q...\E quoting (Java-only syntax; Python re has no equivalent)
        dfa = compile_regex_to_dfa(r"\Qa+b\E")
        assert dfa.matches(b"xa+by")
        assert not dfa.matches(b"aab")  # '+' is literal, not a quantifier

    def test_inline_ci_flag(self):
        dfa = compile_regex_to_dfa(r"(?i)warning")
        assert dfa.matches(b"WARNING")
        assert dfa.matches(b"WaRnInG")

    def test_scoped_ci_group(self):
        dfa = compile_regex_to_dfa(r"(?i:warn)ING")
        assert dfa.matches(b"WARNING")
        assert dfa.matches(b"warnING")
        assert not dfa.matches(b"warning")

    def test_inline_flag_expires_at_group_close(self):
        # Java scopes (?i) to the enclosing group: B stays case-sensitive
        dfa = compile_regex_to_dfa(r"((?i)a)B")
        assert dfa.matches(b"aB")
        assert dfa.matches(b"AB")
        assert not dfa.matches(b"Ab")

    def test_dollar_before_trailing_cr(self):
        # Java $ matches before a final lone-\r terminator
        dfa = compile_regex_to_dfa(r"c$")
        assert dfa.matches(b"abc")
        assert dfa.matches(b"abc\r")
        assert not dfa.matches(b"abc\rx")
        assert not dfa.matches(b"abc\r\r")

    def test_dot_excludes_cr(self):
        dfa = compile_regex_to_dfa(r"a.b")
        assert not dfa.matches(b"a\rb")
        assert dfa.matches(b"axb")


class TestLimitsAndRejection:
    def test_state_cap(self):
        # .{0,50}x{50} style blowup is capped by counted-repetition guard;
        # force a genuine subset blowup with a small cap instead
        with pytest.raises(DfaLimitError):
            compile_regex_to_dfa(r"[ab]*a[ab]{10}", max_states=64)

    def test_counted_repetition_guard(self):
        with pytest.raises(RegexUnsupportedError):
            compile_regex_to_dfa(r"a{1,500}")

    @pytest.mark.parametrize(
        "rx",
        [
            r"(?=look)ahead",
            r"(?<=look)behind",
            r"(?!neg)",
            r"back\1ref",
            r"a*+possessive",
            r"(?>atomic)",
            r"[a-z&&[^aeiou]]",
            r"\p{IsGreek}",
            r"\G",
        ],
    )
    def test_unsupported_rejected(self, rx):
        with pytest.raises(RegexUnsupportedError):
            compile_regex_to_dfa(rx)

    def test_named_group_supported(self):
        dfa = compile_regex_to_dfa(r"(?<code>\d+) error")
        assert dfa.matches(b"status 404 error")

    def test_posix_classes(self):
        dfa = compile_regex_to_dfa(r"\p{Digit}+\p{Alpha}")
        assert dfa.matches(b"123x")
        assert not dfa.matches(b"123 ")


def test_dfa_disk_cache_roundtrip(tmp_path, monkeypatch):
    """A cache hit must reproduce the compiled automaton exactly; corrupt
    pack data is ignored and the entry rebuilt."""
    import numpy as np

    from log_parser_tpu.patterns.regex import cache as c

    monkeypatch.setenv("LOG_PARSER_TPU_CACHE", str(tmp_path))
    first = c.compile_regex_to_dfa_cached("time(out|r)+x", False)
    assert c.flush(10.0)  # entries land as a pack + index pair
    packs = list(tmp_path.glob("*.pack"))
    idxs = list(tmp_path.glob("*.packidx.json"))
    assert len(packs) == 1 and len(idxs) == 1
    # a FRESH process (cleared in-memory index) must hit the pack: patch
    # the module-level index cache back to unloaded
    monkeypatch.setattr(c, "_pack_index", None)
    key = c._key("time(out|r)+x", False, 4096)
    assert c._pack_lookup(tmp_path, key) is not None  # real disk hit
    second = c.compile_regex_to_dfa_cached("time(out|r)+x", False)
    np.testing.assert_array_equal(first.trans, second.trans)
    np.testing.assert_array_equal(first.byte_class, second.byte_class)
    np.testing.assert_array_equal(first.accept_end, second.accept_end)
    assert (first.start, first.n_states, first.n_classes) == (
        second.start, second.n_states, second.n_classes
    )
    packs[0].write_bytes(b"garbage")  # corrupt the pack data
    monkeypatch.setattr(c, "_pack_index", None)
    third = c.compile_regex_to_dfa_cached("time(out|r)+x", False)  # rebuild
    np.testing.assert_array_equal(first.trans, third.trans)
    assert third.matches(b"timeoutx") and not third.matches(b"time")
    # the rebuild republished under a LATER time-ordered stem: a fresh
    # process's index must serve the good entry even though the torn
    # pack is still on disk (newest-wins collision rule)
    assert c.flush(10.0)
    monkeypatch.setattr(c, "_pack_index", None)
    blob = c._pack_lookup(tmp_path, key)
    assert blob is not None
    z = c._read_arrays(blob)  # parses cleanly -> the repair won
    assert int(z["start"]) == third.start


def test_dfa_pack_compaction(tmp_path, monkeypatch):
    """Session packs accumulate one pair per cold build; crossing the
    compaction threshold must merge live entries into ONE pack, drop the
    old files, and keep every entry readable."""
    from log_parser_tpu.patterns.regex import cache as c

    monkeypatch.setenv("LOG_PARSER_TPU_CACHE", str(tmp_path))
    monkeypatch.setattr(c, "_PACK_COMPACT_AT", 100)  # no mid-loop compaction
    regexes = [f"compacted{i}[0-9]+" for i in range(6)]
    for rx in regexes:  # one flush per regex = one pack pair each
        monkeypatch.setattr(c, "_pack_index", None)
        c.compile_regex_to_dfa_cached(rx, False)
        assert c.flush(10.0)
    assert len(list(tmp_path.glob("*.packidx.json"))) == 6
    monkeypatch.setattr(c, "_PACK_COMPACT_AT", 4)
    monkeypatch.setattr(c, "_pack_index", None)
    idx = c._load_pack_index(tmp_path)  # crosses threshold -> compacts
    assert len(list(tmp_path.glob("*.packidx.json"))) == 1
    assert len(list(tmp_path.glob("*.pack"))) == 1
    for rx in regexes:  # every entry survived, via the caller's view
        key = c._key(rx, False, 4096)
        assert idx.get(key) is not None
        assert c._read_arrays(c._pack_lookup(tmp_path, key))["trans"].size
    # and via a fresh load
    monkeypatch.setattr(c, "_pack_index", None)
    for rx in regexes:
        assert c._pack_lookup(tmp_path, c._key(rx, False, 4096)) is not None
