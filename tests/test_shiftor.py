"""Bit-parallel Shift-Or matcher: packing, exactness vs host re, and the
adaptive tier split."""

from __future__ import annotations

import random
import re

import numpy as np
import pytest

from log_parser_tpu.golden.javacompat import compile_java_regex
from log_parser_tpu.ops.encode import encode_lines
from log_parser_tpu.ops.match import MatcherBanks
from log_parser_tpu.ops.shiftor import ShiftOrBank
from log_parser_tpu.patterns.regex import parse_java_regex
from log_parser_tpu.patterns.regex.literals import exact_sequences


REGEXES = [
    "OutOfMemoryError",
    "Connection refused",
    "(GC|gc) overhead",
    "x(code|status)=[45]\\d\\d",
    "a{3}b",
    "[Tt]imeout",
]


def _bank_for(regexes: list[str]) -> tuple[ShiftOrBank, list[re.Pattern]]:
    entries = []
    hosts = []
    for i, rx in enumerate(regexes):
        seqs = exact_sequences(parse_java_regex(rx, False))
        assert seqs is not None, rx
        entries.append((i, seqs))
        hosts.append(compile_java_regex(rx))
    return ShiftOrBank(entries), hosts


def test_exactness_vs_host_re():
    bank, hosts = _bank_for(REGEXES)
    rng = random.Random(11)
    alphabet = "aAbx45 GCgcOutfMemoryErrConnectionRefusedTimeoutcodestatus=d019"
    lines = [
        "".join(rng.choice(alphabet) for _ in range(rng.randrange(0, 60)))
        for _ in range(256)
    ]
    # plant guaranteed positives
    lines += [
        "java.lang.OutOfMemoryError: heap",
        "dial tcp: Connection refused",
        "gc overhead limit",
        "xstatus=503 from upstream",
        "aaab here",
        "Timeout after 3s",
        "xcode=99",  # negative: [45] required
        "aab",  # negative: needs aaa
    ]
    enc = encode_lines(lines)
    got = np.asarray(
        bank._run(np.asarray(enc.u8.T), np.asarray(enc.lengths))
    )
    for i, host in enumerate(hosts):
        expect = np.zeros(len(lines), dtype=bool)
        for j, line in enumerate(lines):
            expect[j] = bool(host.search(line))
        np.testing.assert_array_equal(
            got[: len(lines), i], expect, err_msg=REGEXES[i]
        )


def test_word_packing_isolates_neighbors():
    """Sequences packed into one word must not leak shift bits into each
    other: 'ab' and 'ba' share a word; 'aba' contains both, 'aa' neither."""
    bank, _ = _bank_for(["ab", "ba"])
    assert bank.n_words == 1
    enc = encode_lines(["aba", "aa", "ab", "ba", ""])
    got = np.asarray(bank._run(np.asarray(enc.u8.T), np.asarray(enc.lengths)))
    np.testing.assert_array_equal(
        got[:5], [[True, True], [False, False], [True, False], [False, True], [False, False]]
    )


def test_adaptive_tier_split(monkeypatch):
    from log_parser_tpu.patterns.bank import PatternBank
    from helpers import make_pattern, make_pattern_set

    patterns = [
        make_pattern(f"p{i}", regex=f"literal-{i:03d}", confidence=0.5)
        for i in range(8)
    ]
    bank = PatternBank([make_pattern_set(patterns)])
    # under the Shift-Or threshold: nothing on the Shift-Or tier; the
    # columns ride the union multi-DFA (or the dense bank without it)
    small = MatcherBanks(bank, multi_min_columns=10**9, bitglush_max_words=0)
    assert small.shiftor is None and len(small.dfa_cols) > 0
    multi = MatcherBanks(bank, bitglush_max_words=0)
    assert multi.shiftor is None
    # every column the no-multi config kept dense rides the union instead
    assert sorted(multi.multi_cols + multi.dfa_cols) == sorted(small.dfa_cols)
    wide = MatcherBanks(bank, shiftor_min_columns=1)
    assert wide.shiftor is not None
    assert len(wide.shiftor_cols) == 8  # all literal-shaped primaries


def test_word_budget_gate_reroutes_and_stays_exact():
    """A small shiftor_max_words reroutes DFA-backed literal columns off
    Shift-Or (no-DFA columns stay — it is their only device tier) and the
    rerouted bank produces an identical match cube."""
    import jax.numpy as jnp

    from helpers import make_pattern, make_pattern_set
    from log_parser_tpu.ops.match import MatcherBanks
    from log_parser_tpu.patterns.bank import PatternBank

    patterns = [
        make_pattern(f"p{i}", regex=f"needle-{i:04d}", confidence=0.5)
        for i in range(80)  # ~80 x 11 bytes -> ~28 packed words
    ]
    bank = PatternBank([make_pattern_set(patterns)])

    wide = MatcherBanks(bank, shiftor_min_columns=1)
    assert wide.shiftor is not None and len(wide.shiftor_cols) == 80

    gated = MatcherBanks(bank, shiftor_min_columns=1, shiftor_max_words=4)
    assert gated.shiftor is None
    assert len(gated.multi_cols) + len(gated.prefilter_cols) + len(
        gated.dfa_cols
    ) + len(gated.bitglush_cols) >= 80  # every literal column found another tier

    lines = [f"x needle-{i:04d} y" for i in range(0, 80, 7)] + ["no match here"]
    enc = encode_lines(lines)
    lt = jnp.asarray(enc.u8.T)
    ln = jnp.asarray(enc.lengths)
    np.testing.assert_array_equal(
        np.asarray(wide.cube(lt, ln))[: len(lines)],
        np.asarray(gated.cube(lt, ln))[: len(lines)],
    )
