"""Bit-parallel Shift-Or matcher: packing, exactness vs host re, and the
adaptive tier split."""

from __future__ import annotations

import random
import re

import numpy as np
import pytest

from log_parser_tpu.golden.javacompat import compile_java_regex
from log_parser_tpu.ops.encode import encode_lines
from log_parser_tpu.ops.match import MatcherBanks
from log_parser_tpu.ops.shiftor import ShiftOrBank
from log_parser_tpu.patterns.regex import parse_java_regex
from log_parser_tpu.patterns.regex.literals import exact_sequences


REGEXES = [
    "OutOfMemoryError",
    "Connection refused",
    "(GC|gc) overhead",
    "x(code|status)=[45]\\d\\d",
    "a{3}b",
    "[Tt]imeout",
]


def _bank_for(
    regexes: list[str], sinks: bool = True
) -> tuple[ShiftOrBank, list[re.Pattern]]:
    entries = []
    hosts = []
    for i, rx in enumerate(regexes):
        seqs = exact_sequences(parse_java_regex(rx, False))
        assert seqs is not None, rx
        entries.append((i, seqs))
        hosts.append(compile_java_regex(rx))
    return ShiftOrBank(entries, sinks=sinks), hosts


BOTH_LAYOUTS = pytest.mark.parametrize(
    "sinks", [True, False], ids=["sinks", "bare"]
)


@BOTH_LAYOUTS
def test_exactness_vs_host_re(sinks):
    bank, hosts = _bank_for(REGEXES, sinks)
    rng = random.Random(11)
    alphabet = "aAbx45 GCgcOutfMemoryErrConnectionRefusedTimeoutcodestatus=d019"
    lines = [
        "".join(rng.choice(alphabet) for _ in range(rng.randrange(0, 60)))
        for _ in range(256)
    ]
    # plant guaranteed positives
    lines += [
        "java.lang.OutOfMemoryError: heap",
        "dial tcp: Connection refused",
        "gc overhead limit",
        "xstatus=503 from upstream",
        "aaab here",
        "Timeout after 3s",
        "xcode=99",  # negative: [45] required
        "aab",  # negative: needs aaa
    ]
    enc = encode_lines(lines)
    got = np.asarray(
        bank._run(np.asarray(enc.u8.T), np.asarray(enc.lengths))
    )
    for i, host in enumerate(hosts):
        expect = np.zeros(len(lines), dtype=bool)
        for j, line in enumerate(lines):
            expect[j] = bool(host.search(line))
        np.testing.assert_array_equal(
            got[: len(lines), i], expect, err_msg=REGEXES[i]
        )


def _check_exact(regexes: list[str], lines: list[str], sinks: bool = True):
    bank, hosts = _bank_for(regexes, sinks)
    enc = encode_lines(lines)
    got = np.asarray(bank._run(np.asarray(enc.u8.T), np.asarray(enc.lengths)))
    for i, host in enumerate(hosts):
        for j, line in enumerate(lines):
            want = bool(host.search(line))
            assert bool(got[j, i]) == want, (regexes[i], line)


@BOTH_LAYOUTS
def test_sink_full_width_lines(sinks):
    """Completions at the scan's very last byte rely on finish()'s
    virtual padding pair to sweep the end bit into a sink — exercised by
    lines that exactly fill the padded width (multiples of 32)."""
    regexes = ["Error", "ab"]
    lines = [
        "x" * 27 + "Error",      # 32 chars, completion at byte 31
        "x" * 59 + "Error",      # 64 chars, completion at byte 63
        "x" * 26 + "Error" + "y",  # completion one byte before the end
        "x" * 30 + "ab",         # 2-seq completion at full width
        "x" * 31 + "a",          # suffix is only a prefix of the seq
        "Error" + "z" * 27,      # completion early in a full-width row
        "",
    ]
    _check_exact(regexes, lines, sinks)


@BOTH_LAYOUTS
def test_sink_one_byte_sequences(sinks):
    """m=1 sequences: start == end; the sink pair sits right after."""
    _check_exact(["q", "[0-9]"], ["q", "zq", "3", "zzz3", "none", ""], sinks)


@BOTH_LAYOUTS
def test_sink_31_32_length_sequences_chain(sinks):
    """Lengths 31-32 now allocate 33-34 bits and ride cross-word chains;
    exactness must survive the chain carry in both shift parities."""
    s31 = "abcdefghijklmnopqrstuvwxyz01234"
    s32 = s31 + "5"
    bank, _ = _bank_for([s31, s32], sinks)
    assert bank.has_chains or not sinks
    _check_exact(
        [s31, s32],
        [
            s31, s32, "x" + s31, "xy" + s31, s31[:-1],
            "x" * 30 + s32, s32 + "tail", s32[1:],
        ],
        sinks,
    )


@BOTH_LAYOUTS
def test_sink_long_chain_sequences(sinks):
    """>32-length sequences (multi-word chains) with the composed
    stepper: carries cross two word boundaries."""
    s62 = "A fatal error has been detected by the Java Runtime Environmen"
    bank, _ = _bank_for([s62], sinks)
    assert bank.has_chains
    _check_exact(
        [s62],
        [s62, "x" + s62 + "y", s62[:-1] + "X", "pad " * 8 + s62, ""],
        sinks,
    )


@BOTH_LAYOUTS
def test_word_packing_isolates_neighbors(sinks):
    """Sequences packed into one word must not leak shift bits into each
    other: 'ab' and 'ba' share a word; 'aba' contains both, 'aa' neither."""
    bank, _ = _bank_for(["ab", "ba"], sinks)
    assert bank.n_words == 1
    enc = encode_lines(["aba", "aa", "ab", "ba", ""])
    got = np.asarray(bank._run(np.asarray(enc.u8.T), np.asarray(enc.lengths)))
    np.testing.assert_array_equal(
        got[:5], [[True, True], [False, False], [True, False], [False, True], [False, False]]
    )


@BOTH_LAYOUTS
def test_cross_word_chain_sequences(sinks):
    """Sequences longer than 32 positions span words via the carry chain
    (cont_mask): exactness at every boundary-straddling offset, no leak
    into co-packed short sequences, correct restart mid-line."""
    long_a = "A fatal error has been detected by the Java Runtime Environ"
    long_b = "b" * 33
    entries = [
        (0, (tuple(frozenset([ord(c)]) for c in long_a),)),
        (1, (tuple(frozenset([ord("b")]) for _ in range(33)),)),
        (2, (tuple(frozenset([ord(c)]) for c in "xy"),)),
    ]
    bank = ShiftOrBank(entries, sinks=sinks)
    assert bank.has_chains and bank.n_words >= 3
    lines = [
        long_a,                       # exact
        "zz" + long_a + " tail",      # offset start (chain restarts)
        long_a[:-1],                  # one byte short: no match
        long_a[:30] + "X" + long_a[30:],  # broken at a word boundary
        long_b,                       # 33 b's
        "b" * 32,                     # one short
        "b" * 40,                     # long run: matches
        "xy " + "b" * 33,             # co-packed short + chain in one line
        "",
    ]
    enc = encode_lines(lines)
    got = np.asarray(bank._run(np.asarray(enc.u8.T), np.asarray(enc.lengths)))
    hosts = [re.compile(re.escape(long_a)), re.compile("b{33}"), re.compile("xy")]
    for i, host in enumerate(hosts):
        expect = [bool(host.search(ln)) for ln in lines]
        np.testing.assert_array_equal(
            got[: len(lines), i], expect, err_msg=f"col {i}"
        )


def test_mixed_literal_alternation_column_truncated_superset():
    """A primary-only column mixing a >31-position literal alternative
    with a \\d+ alternative rides bitglush TRUNCATED (the long
    alternative is cut so the bank stays chainless): the cube must be a
    SUPERSET of host re — exact on every short alternative, and exactly
    the 31-item prefix condition on the long one — and the column must
    be flagged in ``approx_cols`` so the engine re-verifies its events
    (tests/test_bitglush.py covers end-to-end exactness)."""
    from log_parser_tpu.patterns.bank import PatternBank
    from helpers import make_pattern, make_pattern_set

    rx = (
        "Connection is not available, request timed out after"
        "|HikariPool-\\d+ - Connection marked as broken"
        "|short one"
    )
    bank = PatternBank(
        [make_pattern_set([make_pattern("p0", regex=rx, confidence=0.5)])]
    )
    mb = MatcherBanks(bank, bitglush_max_words=192)
    assert mb.shiftor is None  # no exact-sequence columns in this bank
    col = next(i for i, c in enumerate(bank.columns) if c.regex == rx)
    assert mb.approx_cols == [col]
    assert mb.bitglush is not None and not mb.bitglush.has_chains
    lines = [
        "Connection is not available, request timed out after 30000ms",
        "HikariPool-1 - Connection marked as broken",
        "a short one here",
        "Connection is not available, request timed out",  # prefix only
        "HikariPool- - Connection marked as broken",  # \\d+ unmet
        "nothing",
    ]
    enc = encode_lines(lines)
    got = np.asarray(
        mb.cube(np.asarray(enc.u8.T), np.asarray(enc.lengths))
    )[: len(lines), col]
    host = compile_java_regex(rx)
    want = [bool(host.search(ln)) for ln in lines]
    # superset: every true match is flagged
    assert all(g or not w for g, w in zip(got, want))
    # exact everywhere except the long alternative's prefix-only line
    np.testing.assert_array_equal(
        got, [True, True, True, True, False, False]
    )


def test_adaptive_tier_split(monkeypatch):
    from log_parser_tpu.patterns.bank import PatternBank
    from helpers import make_pattern, make_pattern_set

    patterns = [
        make_pattern(f"p{i}", regex=f"literal-{i:03d}", confidence=0.5)
        for i in range(8)
    ]
    bank = PatternBank([make_pattern_set(patterns)])
    # under the Shift-Or threshold: nothing on the Shift-Or tier; the
    # columns ride the union multi-DFA (or the dense bank without it)
    small = MatcherBanks(bank, multi_min_columns=10**9, bitglush_max_words=0)
    assert small.shiftor is None and len(small.dfa_cols) > 0
    multi = MatcherBanks(bank, bitglush_max_words=0)
    assert multi.shiftor is None
    # every column the no-multi config kept dense rides the union instead
    assert sorted(multi.multi_cols + multi.dfa_cols) == sorted(small.dfa_cols)
    wide = MatcherBanks(bank, shiftor_min_columns=1)
    assert wide.shiftor is not None
    assert len(wide.shiftor_cols) == 8  # all literal-shaped primaries


def test_word_budget_gate_reroutes_and_stays_exact():
    """A small shiftor_max_words reroutes DFA-backed literal columns off
    Shift-Or (no-DFA columns stay — it is their only device tier) and the
    rerouted bank produces an identical match cube."""
    import jax.numpy as jnp

    from helpers import make_pattern, make_pattern_set
    from log_parser_tpu.ops.match import MatcherBanks
    from log_parser_tpu.patterns.bank import PatternBank

    patterns = [
        make_pattern(f"p{i}", regex=f"needle-{i:04d}", confidence=0.5)
        for i in range(80)  # ~80 x 11 bytes -> ~28 packed words
    ]
    bank = PatternBank([make_pattern_set(patterns)])

    wide = MatcherBanks(bank, shiftor_min_columns=1)
    assert wide.shiftor is not None and len(wide.shiftor_cols) == 80

    gated = MatcherBanks(bank, shiftor_min_columns=1, shiftor_max_words=4)
    assert gated.shiftor is None
    assert len(gated.multi_cols) + len(gated.prefilter_cols) + len(
        gated.dfa_cols
    ) + len(gated.bitglush_cols) >= 80  # every literal column found another tier

    lines = [f"x needle-{i:04d} y" for i in range(0, 80, 7)] + ["no match here"]
    enc = encode_lines(lines)
    lt = jnp.asarray(enc.u8.T)
    ln = jnp.asarray(enc.lengths)
    np.testing.assert_array_equal(
        np.asarray(wide.cube(lt, ln))[: len(lines)],
        np.asarray(gated.cube(lt, ln))[: len(lines)],
    )


def test_bare_layout_through_matcher_banks():
    """The TPU-side bank layout (shiftor_sinks=False — no sink bits,
    ungated hits stepper) produces an identical match cube to the CPU
    sink layout through the full fused MatcherBanks path, at fewer
    packed words."""
    import jax.numpy as jnp

    from helpers import make_pattern, make_pattern_set
    from log_parser_tpu.ops.match import MatcherBanks
    from log_parser_tpu.patterns.bank import PatternBank

    patterns = [
        make_pattern(f"p{i}", regex=rx, confidence=0.5)
        for i, rx in enumerate(
            [
                "OutOfMemoryError",
                "Connection refused",
                "[Tt]imeout waiting",
                "status=[45]\\d\\d",
                "q",  # one-byte sequence: start == end
                "A fatal error has been detected by the Java Runtime",
            ]
        )
    ]
    bank = PatternBank([make_pattern_set(patterns)])
    sink = MatcherBanks(bank, shiftor_min_columns=1, shiftor_sinks=True)
    bare = MatcherBanks(bank, shiftor_min_columns=1, shiftor_sinks=False)
    assert sink.shiftor is not None and bare.shiftor is not None
    assert sink.shiftor.sinks and not bare.shiftor.sinks
    assert bare.shiftor.n_words < sink.shiftor.n_words

    lines = [
        "java.lang.OutOfMemoryError: heap",
        "dial tcp: Connection refused",
        "Timeout waiting for connection",
        "status=503 from upstream",
        "status=200 ok",
        "zq",
        "A fatal error has been detected by the Java Runtime",
        "x" * 27 + "Error",  # full-width completion parity
        "",
        "no match here",
    ]
    enc = encode_lines(lines)
    lt = jnp.asarray(enc.u8.T)
    ln = jnp.asarray(enc.lengths)
    np.testing.assert_array_equal(
        np.asarray(sink.cube(lt, ln))[: len(lines)],
        np.asarray(bare.cube(lt, ln))[: len(lines)],
    )
