"""Observability plane (log_parser_tpu/obs/): metrics registry contract
(cardinality bounds, bucket edges, concurrency, Prometheus exposition
conformance), the request-trace ring, SLO burn accounting, and the HTTP /
shim integration — request-id propagation through a batched flush, the
`/metrics` scrape, and bit-for-bit agreement between `/trace/last` and
the registry (no dual bookkeeping)."""

from __future__ import annotations

import json
import re
import threading
import urllib.error
import urllib.request

import pytest

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.obs import Obs
from log_parser_tpu.obs.registry import Registry, samples_from_stats
from log_parser_tpu.obs.ring import TraceRing
from log_parser_tpu.obs.slo import SloTracker
from log_parser_tpu.runtime import AnalysisEngine
from log_parser_tpu.serve import make_server

from helpers import make_pattern, make_pattern_set


# --------------------------------------------------------------- registry


class TestRegistry:
    def test_counter_inc_and_total(self):
        reg = Registry()
        c = reg.counter("logparser_requests_total",
                        ("transport", "route", "status", "tenant"))
        c.inc(transport="http", route="device", status="200", tenant="a")
        c.inc(2, transport="http", route="device", status="200", tenant="a")
        c.inc(transport="shim", route="batched", status="200", tenant="b")
        assert c.value(transport="http", route="device", status="200",
                       tenant="a") == 3
        assert c.total() == 4

    def test_unknown_metric_name_rejected(self):
        reg = Registry()
        with pytest.raises(ValueError):
            reg.counter("logparser_not_in_vocabulary_total")

    def test_factories_are_idempotent_not_kind_confusable(self):
        reg = Registry()
        c1 = reg.counter("logparser_fallback_total", ("tenant",))
        assert reg.counter("logparser_fallback_total", ("tenant",)) is c1
        with pytest.raises(ValueError):
            reg.gauge("logparser_fallback_total", ("tenant",))

    def test_cardinality_bound_folds_to_overflow(self):
        reg = Registry()
        c = reg.counter("logparser_requests_total",
                        ("transport", "route", "status", "tenant"),
                        max_series=4)
        for i in range(10):
            c.inc(transport="http", route="device", status="200",
                  tenant=f"t{i}")
        # 4 real series kept; 6 increments folded into one overflow series
        keys = [k for k, _ in c.series()]
        assert len(keys) == 5
        assert ("_overflow",) * 4 in keys
        assert c.value(transport="_overflow", route="_overflow",
                       status="_overflow", tenant="_overflow") == 6
        assert c.total() == 10  # folding never loses counts
        assert reg.total("logparser_metric_series_overflow_total") == 6

    def test_histogram_bucket_edges_inclusive_le(self):
        reg = Registry()
        h = reg.histogram("logparser_request_seconds", ("route",),
                          buckets=(0.1, 1.0))
        # exactly on an edge counts into that bucket (Prometheus `le`)
        h.observe(0.1, route="device")
        h.observe(0.05, route="device")
        h.observe(0.5, route="device")
        h.observe(9.0, route="device")
        counts, total, n = h.snapshot(route="device")
        # cumulative per Prometheus `le`: 0.1 lands IN the 0.1 bucket
        assert counts == [2, 3, 4]  # le=0.1, le=1.0, le=+Inf
        assert n == 4
        assert total == pytest.approx(9.65)

    def test_concurrent_hammer_loses_nothing(self):
        reg = Registry()
        c = reg.counter("logparser_requests_total",
                        ("transport", "route", "status", "tenant"))
        h = reg.histogram("logparser_request_seconds", ("route",))

        def hammer(tenant):
            for _ in range(1000):
                c.inc(transport="http", route="device", status="200",
                      tenant=tenant)
                h.observe(0.01, route="device")

        threads = [threading.Thread(target=hammer, args=(f"t{i}",))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.total() == 8000
        _, _, n = h.snapshot(route="device")
        assert n == 8000

    def test_collector_backed_series_and_bad_collector_contained(self):
        reg = Registry()
        spec = (("fallbackCount", "logparser_fallback_total", {}),)
        reg.register_collector(
            "eng", lambda: samples_from_stats(
                {"fallbackCount": 7}, spec, {"tenant": "default"}))
        reg.register_collector("bad", lambda: 1 / 0)
        text = reg.render()  # the broken collector must not kill the scrape
        assert 'logparser_fallback_total{tenant="default"} 7' in text
        assert reg.collected_value(
            "logparser_fallback_total", tenant="default") == 7
        reg.unregister_collector("eng")
        assert reg.collected_value(
            "logparser_fallback_total", tenant="default") is None


EXPOSITION_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?[0-9.e+-]+(?:inf)?$"
)


class TestExposition:
    def test_render_conformance(self):
        reg = Registry()
        c = reg.counter("logparser_requests_total",
                        ("transport", "route", "status", "tenant"))
        c.inc(transport="http", route="device", status="200",
              tenant='we"ird\\ten\nant')
        h = reg.histogram("logparser_request_seconds", ("route",),
                          buckets=(0.1, 1.0))
        h.observe(0.05, route="device")
        g = reg.gauge("logparser_inflight")
        g.set(3)
        text = reg.render()
        assert text.endswith("\n")  # exposition ends with a newline
        lines = text.splitlines()
        seen_types = {}
        for line in lines:
            if line.startswith("# HELP") or line.startswith("# TYPE"):
                if line.startswith("# TYPE"):
                    _, _, name, kind = line.split(" ")
                    seen_types[name] = kind
                continue
            assert EXPOSITION_LINE.match(line), line
        assert seen_types["logparser_requests_total"] == "counter"
        assert seen_types["logparser_request_seconds"] == "histogram"
        assert seen_types["logparser_inflight"] == "gauge"
        # label escaping: backslash, quote and newline per the text format
        assert 'tenant="we\\"ird\\\\ten\\nant"' in text
        # histogram series: cumulative buckets + +Inf + _sum + _count
        assert 'logparser_request_seconds_bucket{route="device",le="0.1"} 1' in text
        assert 'logparser_request_seconds_bucket{route="device",le="1.0"} 1' in text
        assert 'logparser_request_seconds_bucket{route="device",le="+Inf"} 1' in text
        assert 'logparser_request_seconds_count{route="device"} 1' in text
        # un-labeled gauge renders bare
        assert "logparser_inflight 3" in text


# ------------------------------------------------------------- trace ring


class TestTraceRing:
    def test_eviction_order_newest_first(self):
        ring = TraceRing(capacity=4, slow_ms=10_000)
        for i in range(6):
            ring.record({"requestId": f"r{i}", "totalMs": 1.0})
        ids = [e["requestId"] for e in ring.recent(10)]
        assert ids == ["r5", "r4", "r3", "r2"]  # r0/r1 evicted
        assert [e["requestId"] for e in ring.recent(2)] == ["r5", "r4"]
        stats = ring.stats()
        assert stats["recorded"] == 6 and stats["retained"] == 4

    def test_slow_capture_survives_main_ring_churn(self):
        ring = TraceRing(capacity=2, slow_ms=100)
        assert ring.record({"requestId": "slow-1", "totalMs": 250.0}) is True
        for i in range(5):
            assert ring.record(
                {"requestId": f"fast-{i}", "totalMs": 1.0}) is False
        assert "slow-1" not in [e["requestId"] for e in ring.recent(10)]
        [slow] = ring.slow_recent(10)
        assert slow["requestId"] == "slow-1" and slow["slow"] is True
        assert ring.stats()["slowCaptured"] == 1


# -------------------------------------------------------------------- SLO


class TestSloTracker:
    def test_disabled_without_objectives(self):
        slo = SloTracker()
        assert not slo.enabled
        assert slo.health() is None

    def test_availability_burn_degrades_and_recovers(self):
        now = [1000.0]
        slo = SloTracker(availability=0.9, windows_s=(10, 60),
                         clock=lambda: now[0])
        for _ in range(10):
            slo.note(ok=False, duration_ms=5.0)
        health = slo.health()
        assert health["status"] == "DEGRADED"
        assert health["burning"] == ["availability"]
        # 100% errors against a 10% budget: burn 10x on every window
        assert health["burnRates"]["availability"]["10s"] == pytest.approx(10.0)
        # healthy traffic + time passing ages the errors out of the short
        # window first — multi-window AND means no longer degraded
        now[0] += 15
        for _ in range(10):
            slo.note(ok=True, duration_ms=5.0)
        assert slo.health()["status"] == "UP"

    def test_one_bad_second_does_not_flip_long_window(self):
        now = [1000.0]
        slo = SloTracker(availability=0.99, windows_s=(2, 300),
                         clock=lambda: now[0])
        slo.note(ok=False, duration_ms=5.0)
        # long window needs sustained burn: pad it with healthy history
        now[0] -= 200
        for _ in range(200):
            slo.note(ok=True, duration_ms=5.0)
        now[0] += 200
        health = slo.health()
        assert health["status"] == "UP", health

    def test_latency_objective_counts_slow_fraction(self):
        now = [50.0]
        slo = SloTracker(p99_ms=100, windows_s=(10,), clock=lambda: now[0])
        for _ in range(50):
            slo.note(ok=True, duration_ms=10.0)
        for _ in range(50):
            slo.note(ok=True, duration_ms=500.0)
        health = slo.health()
        # 50% slow against the 1% tail budget: burn 50x
        assert health["burnRates"]["latency"]["10s"] == pytest.approx(50.0)
        assert health["burning"] == ["latency"]

    def test_samples_feed_burn_gauge(self):
        slo = SloTracker(availability=0.9, windows_s=(60,))
        slo.note(ok=False, duration_ms=1.0)
        samples = list(slo.samples())
        assert samples, "expected logparser_slo_burn_rate samples"
        name, labels, value = samples[0]
        assert name == "logparser_slo_burn_rate"
        assert labels == {"objective": "availability", "window": "60s"}
        assert value == pytest.approx(10.0)


# --------------------------------------------------- request-id plumbing


class TestRequestIds:
    def test_clean_request_id(self):
        assert Obs.clean_request_id(None) is None
        assert Obs.clean_request_id("  ") is None
        assert Obs.clean_request_id("abc-123") == "abc-123"
        assert Obs.clean_request_id("bad\x00id\nhere") == "badidhere"
        assert Obs.clean_request_id("x" * 500) == "x" * 128
        rid = Obs.new_request_id()
        assert re.fullmatch(r"[0-9a-f]{16}", rid)


# --------------------------------------------------------- HTTP contract


@pytest.fixture(scope="module")
def obs_server():
    patterns = [
        make_pattern("oom", regex="OutOfMemoryError", confidence=0.9,
                     severity="CRITICAL", context=(1, 1)),
        make_pattern("err", regex=r"\bERROR\b", confidence=0.5, severity="LOW"),
    ]
    engine = AnalysisEngine([make_pattern_set(patterns, "lib")], ScoringConfig())
    engine.enable_batching(wait_ms=1.0, batch_max=4)
    server = make_server(engine, host="127.0.0.1", port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{port}", engine
    server.shutdown()
    engine.batcher.close()


def _post(url, payload, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _get(url):
    with urllib.request.urlopen(url) as resp:
        return resp.status, resp.read().decode(), dict(resp.headers)


PAYLOAD = {
    "pod": {"metadata": {"name": "web-1"}},
    "logs": "INFO boot\njava.lang.OutOfMemoryError: heap\nINFO after",
}


class TestHttpObservability:
    def test_request_id_echo_and_batched_flush_propagation(self, obs_server):
        url, engine = obs_server
        status, _, headers = _post(
            url + "/parse", PAYLOAD, headers={"X-Request-Id": "my-rid-1"})
        assert status == 200
        assert headers["X-Request-Id"] == "my-rid-1"
        # the id rode admission -> batcher enqueue -> coalesced device
        # flush -> finalize, and lands in the ring as route "batched"
        _, body, _ = _get(url + "/trace/recent?n=5")
        recent = json.loads(body)
        entry = next(e for e in recent["requests"]
                     if e["requestId"] == "my-rid-1")
        assert entry["route"] == "batched"
        assert entry["outcome"] == "ok"
        assert entry["phasesMs"], "phase breakdown missing"
        assert entry["totalMs"] > 0

    def test_request_id_minted_when_absent(self, obs_server):
        url, _ = obs_server
        status, _, headers = _post(url + "/parse", PAYLOAD)
        assert status == 200
        assert re.fullmatch(r"[0-9a-f]{16}", headers["X-Request-Id"])

    def test_metrics_scrape_is_valid_exposition(self, obs_server):
        url, _ = obs_server
        _post(url + "/parse", PAYLOAD)
        status, text, headers = _get(url + "/metrics")
        assert status == 200
        assert headers["Content-Type"].startswith("text/plain; version=0.0.4")
        for line in text.splitlines():
            if line and not line.startswith("#"):
                assert EXPOSITION_LINE.match(line), line
        assert 'le="+Inf"' in text
        assert re.search(
            r'logparser_requests_total\{transport="http",route="batched",'
            r'status="200",tenant="default"\} \d+', text)

    def test_trace_last_and_registry_agree(self, obs_server):
        url, engine = obs_server
        _post(url + "/parse", PAYLOAD)
        _, body, _ = _get(url + "/trace/last")
        trace = json.loads(body)
        reg = engine.obs.registry
        # collector-backed series read the SAME stats dicts /trace/last
        # serves — agreement is by construction, checked bit-for-bit
        assert trace["fallbackCount"] == reg.collected_value(
            "logparser_fallback_total", tenant="default")
        assert trace["batcher"]["requestsBatched"] == reg.collected_value(
            "logparser_requests_batched_total", tenant="default")
        assert trace["admission"]["admittedDevice"] == reg.collected_value(
            "logparser_admission_total", outcome="device")
        assert trace["droppedResponses"] == engine.obs.dropped_responses
        assert trace["traceRing"] == engine.obs.ring.stats()

    def test_trace_recent_bad_n_is_400(self, obs_server):
        url, _ = obs_server
        try:
            urllib.request.urlopen(url + "/trace/recent?n=bogus")
            raise AssertionError("expected 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400

    def test_non_200_outcomes_recorded_with_status_label(self, obs_server):
        url, engine = obs_server
        status, _, headers = _post(
            url + "/parse", {"pod": None},
            headers={"X-Request-Id": "bad-req-1"})
        assert status == 400
        assert headers["X-Request-Id"] == "bad-req-1"
        assert engine.obs.requests_total.value(
            transport="http", route="device", status="400",
            tenant="default") >= 1
        _, body, _ = _get(url + "/trace/recent?n=10")
        entry = next(e for e in json.loads(body)["requests"]
                     if e["requestId"] == "bad-req-1")
        assert entry["outcome"] == "http_400"

    def test_profile_route_unconfigured_is_503(self, obs_server):
        url, _ = obs_server
        try:
            urllib.request.urlopen(urllib.request.Request(
                url + "/debug/profile", data=b'{"seconds": 1}'))
            raise AssertionError("expected 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503

    def test_profile_route_bad_seconds_is_400(self, obs_server, tmp_path):
        url, engine = obs_server
        engine.obs.profiler.configure(str(tmp_path))
        try:
            for bad in (b'{"seconds": 0}', b'{"seconds": 1e9}', b"[]"):
                try:
                    urllib.request.urlopen(urllib.request.Request(
                        url + "/debug/profile", data=bad))
                    raise AssertionError("expected 400")
                except urllib.error.HTTPError as e:
                    assert e.code == 400, bad
        finally:
            engine.obs.profiler.base_dir = None


# --------------------------------------------------------- shim contract


def test_shim_metrics_frame():
    from log_parser_tpu.shim import ShimClient, make_shim_server
    from log_parser_tpu.shim import logparser_pb2 as pb

    engine = AnalysisEngine(
        [make_pattern_set([make_pattern("oom", regex="OutOfMemoryError")])],
        ScoringConfig(),
    )
    server = make_shim_server(engine, host="127.0.0.1", port=0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        with ShimClient("127.0.0.1", server.server_address[1]) as c:
            c.parse({"metadata": {"name": "p"}},
                    "java.lang.OutOfMemoryError: heap")
            env = c.call("Metrics", pb.HealthRequest())
            assert not env.error
            text = env.payload.decode()
            assert "# TYPE logparser_requests_total counter" in text
            assert re.search(
                r'logparser_requests_total\{transport="shim",[^}]*'
                r'status="200",tenant="default"\} 1', text)
    finally:
        server.shutdown()
