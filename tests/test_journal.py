"""Durable frequency state (runtime/journal.py).

The contract under test: with ``--state-dir`` attached, NO crash loses
frequency state — ``kill -9`` mid-stream resumes with windowed counts
and scores identical to an uninterrupted run. ``journal.abandon()`` is
the in-process crash: appends write+flush to the OS page cache, so
closing the fd without the final fsync/snapshot leaves byte-for-byte
what SIGKILL leaves (a genuine subprocess SIGKILL run is the slow-marked
test at the bottom). Torn final records are an EXPECTED crash artifact:
quarantined to ``.torn``, never an error.
"""

from __future__ import annotations

import os
import signal
import struct
import subprocess
import sys
import textwrap

import pytest

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.golden.engine import SnapshotValidationError
from log_parser_tpu.models.pod import PodFailureData
from log_parser_tpu.runtime import AnalysisEngine, faults
from log_parser_tpu.runtime.faults import FaultRegistry
from log_parser_tpu.runtime.journal import (
    JOURNAL_NAME,
    SNAPSHOT_NAME,
    DurableFrequencyTracker,
    FrequencyJournal,
)
from tests.conftest import FakeClock
from tests.helpers import make_pattern, make_pattern_set


@pytest.fixture(autouse=True)
def clean_registry():
    faults.install(None)
    yield
    faults.install(None)


def _journal(tmp_path, **kw) -> FrequencyJournal:
    return FrequencyJournal(str(tmp_path), **kw)


def _wal(tmp_path) -> str:
    return os.path.join(str(tmp_path), JOURNAL_NAME)


def _snap(tmp_path) -> str:
    return os.path.join(str(tmp_path), SNAPSHOT_NAME)


# ------------------------------------------------------------ WAL framing


class TestWalReplay:
    def test_round_trip(self, tmp_path):
        j = _journal(tmp_path)
        j.append_match("a", 2)
        j.append_match("b", 1)
        j.append_reset("a")  # entry kept, timestamps cleared
        j.abandon()

        j2 = _journal(tmp_path)
        assert j2.replayed == 3
        assert j2.torn_tails == 0
        assert set(j2.recovered_ages) == {"a", "b"}
        assert j2.recovered_ages["a"] == []
        assert len(j2.recovered_ages["b"]) == 1
        assert j2.recovered_ages["b"][0] >= 0.0
        j2.abandon()

    def test_reset_all(self, tmp_path):
        j = _journal(tmp_path)
        j.append_match("a", 1)
        j.append_reset(None)
        j.abandon()
        j2 = _journal(tmp_path)
        assert j2.recovered_ages == {}
        j2.abandon()

    def test_barrier_replaces_everything_before_it(self, tmp_path):
        j = _journal(tmp_path)
        j.append_match("a", 5)
        j.append_barrier({"c": [7.0]})
        j.abandon()
        j2 = _journal(tmp_path)
        assert set(j2.recovered_ages) == {"c"}
        assert len(j2.recovered_ages["c"]) == 1
        assert j2.recovered_ages["c"][0] >= 7.0
        j2.abandon()

    def test_torn_short_payload_quarantined(self, tmp_path):
        j = _journal(tmp_path)
        j.append_match("a", 1)
        j.append_match("b", 1)
        j.abandon()
        good_size = os.path.getsize(_wal(tmp_path))
        with open(_wal(tmp_path), "ab") as f:
            # header promises 64 payload bytes; only 4 follow — a crash
            # mid-write
            f.write(struct.pack("<II", 64, 0) + b"torn")

        j2 = _journal(tmp_path)
        assert j2.replayed == 2
        assert j2.torn_tails == 1
        assert os.path.exists(_wal(tmp_path) + ".torn")
        assert os.path.getsize(_wal(tmp_path)) == good_size
        j2.abandon()

        # the truncated journal is clean: a second boot replays quietly
        j3 = _journal(tmp_path)
        assert j3.replayed == 2 and j3.torn_tails == 0
        j3.abandon()

    def test_crc_mismatch_tail_quarantined(self, tmp_path):
        j = _journal(tmp_path)
        j.append_match("a", 1)
        j.append_match("b", 1)
        j.abandon()
        with open(_wal(tmp_path), "r+b") as f:
            f.seek(-1, os.SEEK_END)
            last = f.read(1)
            f.seek(-1, os.SEEK_END)
            f.write(bytes([last[0] ^ 0xFF]))

        j2 = _journal(tmp_path)
        assert j2.replayed == 1  # the flipped record is gone, not fatal
        assert j2.torn_tails == 1
        assert set(j2.recovered_ages) == {"a"}
        j2.abandon()


# ------------------------------------------------------------ fault sites


class TestFaultSites:
    def test_journal_fault_contained(self, tmp_path):
        faults.install(FaultRegistry.parse("journal_raise@times=1"))
        j = _journal(tmp_path)
        j.append_match("a", 1)  # must NOT raise into the request path
        assert j.healthy is False
        assert j.write_errors == 1
        j.append_match("b", 1)  # budget spent: appends work again
        j.abandon()
        j2 = _journal(tmp_path)
        assert set(j2.recovered_ages) == {"b"}
        j2.abandon()

    def test_journal_torn_fault_wedges(self, tmp_path):
        faults.install(FaultRegistry.parse("journal_torn_raise@after=1"))
        j = _journal(tmp_path)
        j.append_match("a", 1)  # clean
        j.append_match("b", 1)  # written torn; journal wedges
        j.append_match("c", 1)  # wedged: ignored, torn frame stays final
        assert j.healthy is False
        j.abandon()

        faults.install(None)
        j2 = _journal(tmp_path)
        assert j2.replayed == 1
        assert j2.torn_tails == 1
        assert set(j2.recovered_ages) == {"a"}
        j2.abandon()

    def test_snapshot_fault_preserves_wal(self, tmp_path):
        import threading

        j = _journal(tmp_path, fsync_ms=10_000)
        j.append_match("a", 3)
        j._source = lambda: {"a": [0.0] * 3}
        j._source_lock = threading.Lock()
        wal_size = os.path.getsize(_wal(tmp_path))
        faults.install(FaultRegistry.parse("snapshot_raise@times=1"))
        assert j.snapshot_now() is False
        assert j.snapshot_errors == 1
        assert j.epoch == 0
        assert os.path.getsize(_wal(tmp_path)) == wal_size  # NOT truncated
        assert not os.path.exists(_snap(tmp_path))
        # budget spent: the next snapshot succeeds and truncates
        assert j.snapshot_now() is True
        assert j.epoch == 1
        assert os.path.getsize(_wal(tmp_path)) == 0
        j.close()


# -------------------------------------------------------------- snapshots


class TestSnapshots:
    def test_rotation_and_recovery(self, tmp_path):
        import threading

        j = _journal(tmp_path, fsync_ms=10_000)
        j._source = lambda: {"a": [1.5, 3.0]}
        j._source_lock = threading.Lock()
        j.append_match("a", 2)
        assert j.snapshot_now() is True
        assert os.path.getsize(_wal(tmp_path)) == 0
        assert os.path.exists(_snap(tmp_path))
        assert os.path.exists(_snap(tmp_path) + ".sum")
        j.append_match("b", 1)  # post-snapshot tail
        j.abandon()

        j2 = _journal(tmp_path)
        assert j2.epoch == 1
        assert j2.replayed == 1
        assert set(j2.recovered_ages) == {"a", "b"}
        assert len(j2.recovered_ages["a"]) == 2
        assert all(a >= 1.5 for a in j2.recovered_ages["a"])
        j2.abandon()

    def test_corrupt_snapshot_quarantined(self, tmp_path):
        import threading

        j = _journal(tmp_path, fsync_ms=10_000)
        j._source = lambda: {"a": [1.0]}
        j._source_lock = threading.Lock()
        assert j.snapshot_now() is True
        j.append_match("b", 1)
        j.abandon()
        with open(_snap(tmp_path), "r+b") as f:
            f.write(b"\x00\x00\x00\x00")

        j2 = _journal(tmp_path)
        assert j2.snapshot_corrupt == 1
        assert os.path.exists(_snap(tmp_path) + ".corrupt")
        # boot survives on the journal tail alone
        assert set(j2.recovered_ages) == {"b"}
        j2.abandon()


# ------------------------------------------------------- durable tracker


class TestDurableTracker:
    def _tracker(self, tmp_path, clock=None):
        j = _journal(tmp_path, fsync_ms=10_000)
        return DurableFrequencyTracker(ScoringConfig(), clock or FakeClock(), j), j

    def test_mutations_survive_crash(self, tmp_path):
        t, j = self._tracker(tmp_path)
        t.record_pattern_matches("oom", 3)
        t.record_pattern_matches("conn", 1)
        t.reset_pattern_frequency("conn")
        j.abandon()

        t2, j2 = self._tracker(tmp_path)
        assert t2.get_frequency_statistics() == {"oom": 3, "conn": 0}
        j2.abandon()

    def test_noop_mutations_not_journaled(self, tmp_path):
        t, j = self._tracker(tmp_path)
        t.record_pattern_matches(None, 5)
        t.record_pattern_matches("", 5)
        t.record_pattern_matches("a", 0)
        assert j.records == 0
        j.abandon()

    def test_restore_barrier_survives_crash(self, tmp_path):
        t, j = self._tracker(tmp_path)
        t.record_pattern_matches("old", 9)
        t.restore({"new": [2.0]})
        j.abandon()
        t2, j2 = self._tracker(tmp_path)
        assert t2.get_frequency_statistics() == {"new": 1}
        j2.abandon()

    def test_rejected_restore_leaves_journal_untouched(self, tmp_path):
        t, j = self._tracker(tmp_path)
        t.record_pattern_matches("a", 2)
        with pytest.raises(SnapshotValidationError):
            t.restore({"bad": [-1.0]})
        j.abandon()
        t2, j2 = self._tracker(tmp_path)
        assert t2.get_frequency_statistics() == {"a": 2}
        j2.abandon()


# ----------------------------------------------- crash-recovery parity


def _sets():
    return [
        make_pattern_set(
            [
                make_pattern(
                    "oom",
                    regex="OutOfMemoryError",
                    confidence=0.9,
                    severity="CRITICAL",
                    secondaries=[("GC overhead", 0.3, 10)],
                    context=(1, 1),
                ),
                make_pattern("conn", regex="Connection refused", confidence=0.7),
                make_pattern("fatal", regex="FATAL", confidence=0.8),
            ]
        )
    ]


REQUESTS = [
    "INFO boot\njava.lang.OutOfMemoryError: heap\nINFO after",
    "WARN x\nConnection refused\nFATAL crash",
    "java.lang.OutOfMemoryError: heap\nGC overhead limit exceeded",
    "Connection refused\njava.lang.OutOfMemoryError: heap\nFATAL boom",
]


def _pod(logs: str) -> PodFailureData:
    return PodFailureData(pod={"metadata": {"name": "crash"}}, logs=logs)


def _events(result) -> list[tuple]:
    return [
        (
            e.line_number,
            e.matched_pattern.id if e.matched_pattern else None,
            e.score,
        )
        for e in result.events
    ]


class TestCrashRecoveryParity:
    """N requests, hard-kill at every phase boundary, restart on the same
    state dir, run the remainder: final scores and frequency stats must
    be bit-identical to one uninterrupted engine taking all N."""

    def _control(self):
        engine = AnalysisEngine(_sets(), ScoringConfig())
        results = [engine.analyze(_pod(logs)) for logs in REQUESTS]
        return _events(results[-1]), engine.frequency.get_frequency_statistics()

    @pytest.mark.parametrize("crash_after", [0, 1, 2, 3])
    def test_kill9_parity_unbatched(self, tmp_path, crash_after):
        want_events, want_stats = self._control()

        first = AnalysisEngine(_sets(), ScoringConfig())
        first.attach_journal(str(tmp_path), fsync_ms=10_000)
        for logs in REQUESTS[:crash_after]:
            first.analyze(_pod(logs))
        first.journal.abandon()  # kill -9: no flush, no final snapshot

        second = AnalysisEngine(_sets(), ScoringConfig())
        second.attach_journal(str(tmp_path), fsync_ms=10_000)
        results = [second.analyze(_pod(logs)) for logs in REQUESTS[crash_after:]]
        assert _events(results[-1]) == want_events
        assert second.frequency.get_frequency_statistics() == want_stats
        second.journal.abandon()

    def test_kill9_parity_batched(self, tmp_path):
        """Same contract with the micro-batcher attached on both sides of
        the crash (sequential submits: deterministic enqueue order)."""
        want_events, want_stats = self._control()

        first = AnalysisEngine(_sets(), ScoringConfig())
        first.attach_journal(str(tmp_path), fsync_ms=10_000)
        first.enable_batching(wait_ms=1.0)
        for logs in REQUESTS[:2]:
            first.analyze_batched(_pod(logs))
        first.batcher.close()
        first.journal.abandon()

        second = AnalysisEngine(_sets(), ScoringConfig())
        second.attach_journal(str(tmp_path), fsync_ms=10_000)
        second.enable_batching(wait_ms=1.0)
        results = [second.analyze_batched(_pod(logs)) for logs in REQUESTS[2:]]
        assert _events(results[-1]) == want_events
        assert second.frequency.get_frequency_statistics() == want_stats
        second.batcher.close()
        second.journal.abandon()

    def test_torn_final_record_parity(self, tmp_path):
        """A crash that tears the last record loses ONLY that request's
        frequency contribution — and the torn bytes are quarantined, not
        fatal. (The chaos sweep drives the same path through a live
        server; this pins the arithmetic.)"""
        first = AnalysisEngine(_sets(), ScoringConfig())
        first.attach_journal(str(tmp_path), fsync_ms=10_000)
        first.analyze(_pod(REQUESTS[0]))
        # request 2's (single) match record is written torn
        faults.install(FaultRegistry.parse("journal_torn_raise@times=1"))
        first.analyze(_pod(REQUESTS[0]))
        faults.install(None)
        first.journal.abandon()

        second = AnalysisEngine(_sets(), ScoringConfig())
        second.attach_journal(str(tmp_path), fsync_ms=10_000)
        assert second.journal.torn_tails == 1
        assert os.path.exists(_wal(tmp_path) + ".torn")
        # only request 1's record survived — the control is a single run
        control = AnalysisEngine(_sets(), ScoringConfig())
        control.analyze(_pod(REQUESTS[0]))
        assert (
            second.frequency.get_frequency_statistics()
            == control.frequency.get_frequency_statistics()
        )
        second.journal.abandon()


@pytest.mark.slow
class TestSubprocessSigkill:
    """The genuine article: a separate interpreter hard-killed by SIGKILL
    mid-stream, recovered by this process from the same state dir."""

    def test_sigkill_replay_parity(self, tmp_path):
        state = str(tmp_path / "state")
        child = textwrap.dedent(
            f"""
            import os, signal
            os.environ["JAX_PLATFORMS"] = "cpu"
            import sys
            sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
            sys.path.insert(0, {os.path.dirname(os.path.abspath(__file__))!r})
            from log_parser_tpu.config import ScoringConfig
            from log_parser_tpu.models.pod import PodFailureData
            from log_parser_tpu.runtime import AnalysisEngine
            from tests.test_journal import REQUESTS, _sets
            engine = AnalysisEngine(_sets(), ScoringConfig())
            engine.attach_journal({state!r}, fsync_ms=10000)
            for logs in REQUESTS[:2]:
                engine.analyze(
                    PodFailureData(pod={{"metadata": {{"name": "crash"}}}}, logs=logs)
                )
            os.kill(os.getpid(), signal.SIGKILL)
            """
        )
        proc = subprocess.run(
            [sys.executable, "-c", child],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            timeout=300,
        )
        assert proc.returncode == -signal.SIGKILL

        engine = AnalysisEngine(_sets(), ScoringConfig())
        engine.attach_journal(state, fsync_ms=10_000)
        results = [engine.analyze(_pod(logs)) for logs in REQUESTS[2:]]

        control = AnalysisEngine(_sets(), ScoringConfig())
        control_results = [control.analyze(_pod(logs)) for logs in REQUESTS]
        assert _events(results[-1]) == _events(control_results[-1])
        assert (
            engine.frequency.get_frequency_statistics()
            == control.frequency.get_frequency_statistics()
        )
        engine.journal.abandon()
