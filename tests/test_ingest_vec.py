"""Differential fuzz for the numpy-vectorized Corpus fallback (ISSUE 10).

The vectorized ingest (native/ingest._split_offsets +
_vectorized_encode) must be bit-identical to BOTH references:

- the scalar fallback it replaced — ``encode_lines(java_split_lines(s))``
  is the parity authority for split semantics, width/rows geometry,
  lengths, and needs_host flags;
- the native scanner, when the shared object loads on this host.

Hostile shapes pinned here: CR/LF/CRLF mixes (a lone ``\\r`` is CONTENT
under Java split semantics, ``\\r\\n`` is one separator), lone
surrogates (cannot strict-encode → the per-line scalar escape hatch),
empty blob, trailing-newline runs (Java drops ALL trailing empty
parts), lines past ``max_line_bytes``, multi-byte UTF-8 straddling the
width cap, and NUL content. Plus: the line-cache keying lane
(``dedup_slots``) against the per-line dict loop it replaced, and
StreamNormalizer chunk-split invariance feeding the vectorized path.
"""

from __future__ import annotations

import random

import numpy as np
import pytest

import log_parser_tpu.native.ingest as ingest_mod
from log_parser_tpu.golden.javacompat import java_split_lines
from log_parser_tpu.native import available
from log_parser_tpu.native.ingest import Corpus, StreamNormalizer
from log_parser_tpu.ops.encode import encode_lines
from log_parser_tpu.runtime.linecache import dedup_slots, line_key

HOSTILE = [
    "",
    "\n",
    "\r",
    "\r\n",
    "\n\n",
    "a",
    "a\n",
    "a\r\nb",
    "a\rb",          # lone \r is content, NOT a separator
    "a\r\r\nb",      # first \r content, second consumed by the CRLF sep
    "a\r\rb",
    "\na",
    "\ra",
    "x\n\n\n",       # ALL trailing empty parts dropped
    "x\r\n\r\n",
    "\n\r\n\r",      # trailing part "\r" is non-empty — kept
    "\r\r\r",
    "€é漢\n字",
    "a\x00b\nc",     # NUL content → needs_host
    "\ud800oops\nok",  # lone surrogate → scalar escape hatch
    "ok\n\ud800",
    "a" * 9000 + "\nshort",  # > max_line_bytes
    ("€" * 40 + "\n") * 5,   # multi-byte UTF-8 at the width cap
    "tail no nl",
    "mél\r\nx",
    "  \n\t\n",
]

KWARG_VARIANTS = [
    {},
    {"max_line_bytes": 16},
    {"pad_to_multiple": 8, "min_rows": 5},
]


@pytest.fixture
def no_native(monkeypatch):
    """Force the vectorized fallback regardless of host toolchain."""
    monkeypatch.setattr(ingest_mod, "get_lib", lambda: None)


def _fuzz_cases(n=250, seed=7):
    rng = random.Random(seed)
    alphabet = "ab\r\n \t€é\x00"
    return [
        "".join(rng.choice(alphabet) for _ in range(rng.randrange(0, 120)))
        for _ in range(n)
    ]


def _assert_corpus_matches_scalar(logs: str, **kw) -> None:
    parts = java_split_lines(logs)
    corpus = Corpus(logs, **kw)
    assert list(corpus) == parts
    try:
        ref = encode_lines(parts, **kw)
    except UnicodeEncodeError:
        # scalar encode raises only where Corpus also took its scalar
        # path; nothing further to compare at the array level
        return
    enc = corpus.encoded
    assert np.array_equal(ref.u8, enc.u8)
    assert np.array_equal(ref.lengths, enc.lengths)
    assert np.array_equal(ref.needs_host, enc.needs_host)
    assert ref.n_lines == enc.n_lines
    for i, part in enumerate(parts):
        assert corpus.line(i) == part
        assert corpus.line_key_bytes(i) == part.encode(
            "utf-8", errors="replace"
        )


class TestVectorizedVsScalar:
    @pytest.mark.parametrize("logs", HOSTILE)
    def test_hostile_cases(self, no_native, logs):
        for kw in KWARG_VARIANTS:
            _assert_corpus_matches_scalar(logs, **kw)

    def test_fuzz(self, no_native):
        for logs in _fuzz_cases():
            _assert_corpus_matches_scalar(logs)

    def test_fuzz_narrow_width(self, no_native):
        for logs in _fuzz_cases(n=80, seed=11):
            _assert_corpus_matches_scalar(logs, max_line_bytes=16)
            _assert_corpus_matches_scalar(
                logs, pad_to_multiple=8, min_rows=5
            )

    def test_surrogate_falls_back_to_scalar_strings(self, no_native):
        corpus = Corpus("ok\n\ud800bad")
        assert corpus._lines is not None  # the escape hatch, not arrays
        assert corpus.key_view() is None
        assert corpus.line(1) == "\ud800bad"  # original str, unreplaced
        assert corpus.line_key_bytes(1) == "\ud800bad".encode(
            "utf-8", errors="replace"
        )

    def test_clean_input_is_blob_backed(self, no_native):
        corpus = Corpus("a\nbb\nccc")
        blob, starts, ends = corpus.key_view()
        n = corpus.n_lines
        got = [
            blob[a:b]
            for a, b in zip(starts[:n].tolist(), ends[:n].tolist())
        ]
        assert got == [b"a", b"bb", b"ccc"]


@pytest.mark.skipif(not available(), reason="native library not loadable")
class TestVectorizedVsNative:
    @pytest.mark.parametrize("logs", HOSTILE)
    def test_hostile_cases(self, logs, monkeypatch):
        native_corpus = Corpus(logs)
        monkeypatch.setattr(ingest_mod, "get_lib", lambda: None)
        vec_corpus = Corpus(logs)
        assert list(native_corpus) == list(vec_corpus)
        a, b = native_corpus.encoded, vec_corpus.encoded
        assert np.array_equal(a.u8, b.u8)
        assert np.array_equal(a.lengths, b.lengths)
        assert np.array_equal(a.needs_host, b.needs_host)
        assert a.n_lines == b.n_lines
        for i in range(a.n_lines):
            assert native_corpus.line_key_bytes(i) == vec_corpus.line_key_bytes(i)


class TestDedupSlots:
    """The lexsort keying lane vs the per-line dict loop it replaced."""

    def _reference(self, corpus):
        slot_of: dict[bytes, int] = {}
        reps: list[int] = []
        line_slot = []
        for i in range(corpus.n_lines):
            lb = corpus.line_key_bytes(i)
            s = slot_of.get(lb)
            if s is None:
                s = len(reps)
                slot_of[lb] = s
                reps.append(i)
            line_slot.append(s)
        keys = [line_key(lb) for lb in slot_of]
        counts = np.bincount(
            np.asarray(line_slot, dtype=np.int64), minlength=len(reps)
        )
        return line_slot, reps, keys, counts

    def test_fuzz_matches_dict_loop(self, no_native):
        rng = random.Random(3)
        pool = (
            ["err %d" % i for i in range(8)]
            + ["x" * 9000 + str(i) for i in range(3)]  # truncated, ambiguous
            + ["", "a\x00b", "€é", "a" * 63, "a" * 64, "a" * 65]
        )
        for _ in range(150):
            lines = [rng.choice(pool) for _ in range(rng.randrange(0, 60))]
            corpus = Corpus("\n".join(lines))
            got = dedup_slots(corpus)
            assert got is not None
            line_slot, reps, keys, counts = got
            ref_slot, ref_reps, ref_keys, ref_counts = self._reference(corpus)
            assert line_slot.tolist() == ref_slot
            assert reps.tolist() == ref_reps
            assert keys == ref_keys
            assert counts.tolist() == ref_counts.tolist()

    def test_long_lines_grouped_exactly(self, no_native):
        # same truncated prefix + same length, different tails: the u8
        # matrix cannot tell them apart — the blob regroup must
        a = "x" * 5000 + "A"
        b = "x" * 5000 + "B"
        corpus = Corpus("\n".join([a, b, a, b, a]))
        line_slot, reps, keys, counts = dedup_slots(corpus)
        assert line_slot.tolist() == [0, 1, 0, 1, 0]
        assert counts.tolist() == [3, 2]
        assert keys[0] == line_key(a.encode())
        assert keys[1] == line_key(b.encode())

    def test_surrogate_corpus_returns_none(self, no_native):
        assert dedup_slots(Corpus("\ud800x\nok")) is None

    def test_empty_string_is_one_empty_line(self, no_native):
        # Java split: "" -> [""] — one (empty) line, one slot
        line_slot, reps, keys, counts = dedup_slots(Corpus(""))
        assert line_slot.tolist() == [0]
        assert keys == [line_key(b"")]

    def test_zero_line_corpus(self, no_native):
        # "\n" -> ["", ""] -> all trailing empties dropped -> no lines
        line_slot, reps, keys, counts = dedup_slots(Corpus("\n"))
        assert line_slot.size == 0 and len(keys) == 0


class TestStreamNormalizerChunkInvariance:
    """Arbitrary chunkings of one byte stream must produce the same
    normalized text — and therefore the same vectorized Corpus — as the
    joined blob."""

    def test_multibyte_splits(self, no_native):
        text = "héllo €uro\n漢字 line\nplain\r\ntail€"
        blob = text.encode("utf-8")
        joined_corpus = Corpus(text)
        rng = random.Random(5)
        for _ in range(50):
            cuts = sorted(
                rng.randrange(0, len(blob) + 1)
                for _ in range(rng.randrange(0, 6))
            )
            norm = StreamNormalizer()
            pieces = []
            lo = 0
            for cut in cuts + [len(blob)]:
                pieces.append(norm.feed(blob[lo:cut]))
                lo = cut
            pieces.append(norm.flush())
            reassembled = "".join(pieces)
            assert reassembled == text
            corpus = Corpus(reassembled)
            assert np.array_equal(
                corpus.encoded.u8, joined_corpus.encoded.u8
            )
            assert list(corpus) == list(joined_corpus)

    def test_truncated_trailing_sequence(self, no_native):
        blob = "ok line\n€".encode("utf-8")[:-1]  # truncated 3-byte seq
        norm = StreamNormalizer()
        out = norm.feed(blob) + norm.flush()
        assert out == blob.decode("utf-8", errors="replace")
        corpus = Corpus(out)
        assert corpus.n_lines == 2
        assert bool(corpus.encoded.needs_host[1])  # U+FFFD is non-ASCII
