"""Fleet front-door (log_parser_tpu/fleet/): consistent-hash ring
semantics, router→backend parity (routed responses bit-identical to a
direct hit), the 307-taught override lifecycle (a hot tenant migrated
mid-traffic costs clients zero errors), backend-death re-mapping, the
framed front, the shim client's bounded forward-follow, and the shared
compiled-pack memo (N identical banks → one pack built, scores
bit-identical with sharing on or off)."""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request

import pytest

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.fleet.ring import HashRing
from log_parser_tpu.fleet.router import (
    FramedRouterFront,
    base_of,
    make_router,
    parse_backends,
)
from log_parser_tpu.runtime import AnalysisEngine
from log_parser_tpu.runtime.migrate import Migrator
from log_parser_tpu.runtime.tenancy import TenantRegistry
from log_parser_tpu.serve import make_server

from helpers import make_pattern, make_pattern_set

ACME_YAML = """
metadata:
  library_id: acme-lib
patterns:
  - id: oom
    name: Out of memory
    severity: CRITICAL
    primary_pattern:
      regex: OutOfMemoryError
      confidence: 0.9
  - id: err
    name: Errors
    severity: LOW
    primary_pattern:
      regex: "\\\\bERROR\\\\b"
      confidence: 0.5
"""

TRAFFIC = [
    "ERROR twice\nERROR again\nOutOfMemoryError",
    "nothing to see",
    "java.lang.OutOfMemoryError: metaspace\nERROR",
]


@pytest.fixture()
def root(tmp_path):
    for tid in ("acme", "globex"):
        d = tmp_path / "tenants" / tid
        d.mkdir(parents=True)
        (d / "lib.yaml").write_text(ACME_YAML.replace("acme-lib",
                                                      f"{tid}-lib"))
    return str(tmp_path / "tenants")


def _default_engine() -> AnalysisEngine:
    return AnalysisEngine(
        [make_pattern_set([make_pattern("base", regex="BASE")], "base-lib")],
        ScoringConfig(),
    )


def _post(url, payload, headers=None, path="/parse"):
    req = urllib.request.Request(
        url + path,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _payload(logs: str) -> dict:
    return {"pod": {"metadata": {"name": "fleet"}}, "logs": logs}


def _scrub(body: dict) -> dict:
    """Drop the per-request nondeterminism (ids, clocks) so parity
    compares what routing could actually change."""
    out = json.loads(json.dumps(body))
    out.pop("analysisId", None)
    meta = out.get("metadata") or {}
    meta.pop("processingTimeMs", None)
    meta.pop("analyzedAt", None)
    return out


# ------------------------------------------------------------------- ring


class TestHashRing:
    def test_owner_is_deterministic_and_a_member(self):
        backends = [f"http://10.0.0.{i}:8080" for i in range(1, 4)]
        ring = HashRing(backends)
        owners = {t: ring.owner(f"tenant-{t}") for t in range(200)}
        assert set(owners.values()) <= set(backends)
        again = HashRing(list(backends))
        assert owners == {t: again.owner(f"tenant-{t}") for t in range(200)}

    def test_spread_is_roughly_fair(self):
        backends = [f"http://10.0.0.{i}:8080" for i in range(1, 4)]
        spread = HashRing(backends).spread()
        total = sum(spread.values())
        # 64 vnodes x 3 backends: nobody owns the ring, nobody starves
        assert all(0.15 < n / total < 0.55 for n in spread.values()), spread

    def test_removal_remaps_only_the_dead_arcs(self):
        backends = [f"http://10.0.0.{i}:8080" for i in range(1, 4)]
        ring = HashRing(backends)
        keys = [f"tenant-{i}" for i in range(300)]
        before = {k: ring.owner(k) for k in keys}
        dead = backends[0]
        ring.remove(dead)
        for k in keys:
            if before[k] != dead:
                assert ring.owner(k) == before[k], k  # survivors keep theirs
            else:
                assert ring.owner(k) != dead
        ring.add(dead)
        assert {k: ring.owner(k) for k in keys} == before  # re-join restores

    def test_override_lifecycle(self):
        backends = [f"http://10.0.0.{i}:8080" for i in range(1, 3)]
        ring = HashRing(backends)
        tenant = "acme"
        natural = ring.owner(tenant)
        other = next(b for b in backends if b != natural)
        assert not ring.set_override(tenant, "http://10.9.9.9:1")  # non-member
        assert ring.set_override(tenant, other)
        assert ring.owner(tenant) == other
        assert ring.overrides() == {tenant: other}
        # redundant override (back to the hash owner) self-clears
        assert ring.set_override(tenant, natural)
        assert ring.overrides() == {}
        # an override dies with its backend
        assert ring.set_override(tenant, other)
        ring.remove(other)
        assert ring.overrides() == {}
        assert ring.owner(tenant) == natural

    def test_parse_backends(self):
        assert parse_backends("127.0.0.1:8080, http://h:9") == [
            "http://127.0.0.1:8080", "http://h:9",
        ]
        for bad in ("", "no-port", "https://h:1", "h:1,h:1"):
            with pytest.raises(ValueError):
                parse_backends(bad)

    def test_base_of(self):
        assert base_of("http://h:8080/parse?x=1") == "http://h:8080"
        assert base_of("not a url") is None
        assert base_of("/relative/path") is None


# ------------------------------------------------- router parity over HTTP


class _Backend:
    """One in-process serving backend with tenants + a migrator."""

    def __init__(self, root, state_dir):
        self.registry = TenantRegistry(_default_engine(), root=root)
        self.server = make_server(
            self.registry.default_engine, "127.0.0.1", 0,
            tenants=self.registry,
        )
        self.port = self.server.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self.server.migrator = Migrator(
            self.registry, state_root=str(state_dir), node_url=self.url
        )
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def close(self):
        self.server.shutdown()
        self.server.server_close()
        self.registry.shutdown()


@pytest.fixture()
def fleet(root, tmp_path):
    backends = [_Backend(root, tmp_path / f"state{i}") for i in range(2)]
    router = make_router(
        "127.0.0.1", 0, [b.url for b in backends], down_after=1
    )
    threading.Thread(target=router.serve_forever, daemon=True).start()
    router_url = f"http://127.0.0.1:{router.server_address[1]}"
    try:
        yield router, router_url, backends
    finally:
        router.shutdown()
        router.server_close()
        for b in backends:
            b.close()


class TestRouterParity:
    def test_routed_is_bit_identical_to_direct(self, fleet, root, tmp_path):
        router, url, backends = fleet
        direct = _Backend(root, tmp_path / "direct")
        try:
            for tenant in (None, "acme", "globex"):
                hdr = {"X-Tenant": tenant} if tenant else None
                for blob in TRAFFIC:
                    ds, dbody, _ = _post(direct.url, _payload(blob), hdr)
                    rs, rbody, _ = _post(url, _payload(blob), hdr)
                    assert (ds, _scrub(dbody)) == (rs, _scrub(rbody))
        finally:
            direct.close()

    def test_edge_refuses_invalid_tenant(self, fleet):
        _, url, backends = fleet
        status, body, _ = _post(url, _payload(TRAFFIC[0]),
                                {"X-Tenant": "../evil"})
        assert status == 400 and "invalid tenant id" in body["error"]

    def test_health_and_status_surface(self, fleet):
        router, url, backends = fleet
        with urllib.request.urlopen(url + "/q/health", timeout=30) as resp:
            health = json.loads(resp.read())
        assert health["status"] == "UP" and health["role"] == "router"
        with urllib.request.urlopen(url + "/fleet/status", timeout=30) as r:
            status = json.loads(r.read())
        assert sorted(status["ring"]["backends"]) == sorted(
            b.url for b in backends
        )
        assert status["ring"]["overrides"] == {}


class TestBackendDeath:
    def test_ring_remaps_and_serves_from_survivor(self, fleet):
        router, url, backends = fleet
        for blob in TRAFFIC:
            assert _post(url, _payload(blob), {"X-Tenant": "acme"})[0] == 200
        # kill the backend that owns acme, so the very next acme request
        # finds the corpse (eviction is traffic-driven)
        victim = next(b for b in backends
                      if router.ring.owner("acme") == b.url)
        survivor = next(b for b in backends if b is not victim)
        victim.server.shutdown()
        victim.server.server_close()
        # zero client errors across the detection window: the in-flight
        # request that finds the corpse retries the next ring owner
        for _ in range(4):
            for hdr in (None, {"X-Tenant": "acme"}, {"X-Tenant": "globex"}):
                status, body, _ = _post(url, _payload(TRAFFIC[0]), hdr)
                assert status == 200, body
        assert router.ring.backends() == [survivor.url]
        assert router.backends_up() == [survivor.url]
        with urllib.request.urlopen(url + "/q/health", timeout=30) as resp:
            health = json.loads(resp.read())
        assert health["status"] == "UP"
        down = next(c for c in health["checks"] if victim.url in c["name"])
        assert down["status"] == "DOWN"


class TestHotTenantMove:
    def test_mid_traffic_migration_zero_client_errors(self, fleet):
        """The full fleet story: traffic flows through the router while
        the tenant is live-migrated under it. The client never sees the
        307 (the router follows it and learns the override); responses
        stay 200 and bit-identical in shape before and after."""
        router, url, backends = fleet
        hdr = {"X-Tenant": "acme"}
        # land acme somewhere real
        assert _post(url, _payload(TRAFFIC[0]), hdr)[0] == 200
        source = next(b for b in backends
                      if router.ring.owner("acme") == b.url)
        target = next(b for b in backends if b is not source)

        statuses: list[int] = []
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                statuses.append(_post(url, _payload(TRAFFIC[0]), hdr)[0])

        t = threading.Thread(target=hammer)
        t.start()
        try:
            status, summary, _ = _post(
                source.url, {"tenant": "acme", "target": target.url,
                             "retryAfterS": 1},
                path="/admin/migrate",
            )
        finally:
            # a few post-cutover requests exercise the forward-follow
            for _ in range(3):
                statuses.append(_post(url, _payload(TRAFFIC[0]), hdr)[0])
            stop.set()
            t.join(30)
        assert status == 200 and summary["outcome"] == "completed", summary
        assert statuses and set(statuses) == {200}, statuses
        # the forward taught the router the new owner
        assert router.ring.owner("acme") == target.url
        assert router.ring.overrides() == {"acme": target.url}
        # and the source itself now answers 307 (clients talking to the
        # router never see it)
        status, _, headers = _post(source.url, _payload(TRAFFIC[0]), hdr)
        assert status == 307 and headers["Location"].startswith(target.url)


# ------------------------------------------------------------ framed front


class TestFramedFront:
    def test_framed_parity_and_edge_validation(self, fleet):
        grpc_pb = pytest.importorskip("log_parser_tpu.shim.logparser_pb2")
        from log_parser_tpu.shim.client import ShimClient
        from log_parser_tpu.shim.server import make_shim_server

        router, url, backends = fleet
        shims = []
        shim_addrs = {}
        for b in backends:
            shim = make_shim_server(
                b.registry.default_engine, "127.0.0.1", 0,
                tenants=b.registry,
            )
            threading.Thread(target=shim.serve_forever, daemon=True).start()
            shims.append(shim)
            shim_addrs[b.url] = ("127.0.0.1", shim.server_address[1])
        front = FramedRouterFront(("127.0.0.1", 0), router, shim_addrs)
        threading.Thread(target=front.serve_forever, daemon=True).start()
        try:
            front_port = front.server_address[1]
            with ShimClient("127.0.0.1", front_port) as via_router:
                routed = via_router.parse({"metadata": {"name": "fleet"}},
                                          TRAFFIC[0])
            owner = router.ring.owner("default")
            with ShimClient(*shim_addrs[owner]) as direct:
                expected = direct.parse({"metadata": {"name": "fleet"}},
                                        TRAFFIC[0])
            for resp in (routed, expected):  # drop ids and clocks
                resp.analysis_id = ""
                resp.metadata.processing_time_ms = 0
                resp.metadata.analyzed_at = ""
            assert routed.SerializeToString() == expected.SerializeToString()
            # malformed tenant suffix refused at the front, not proxied
            with ShimClient("127.0.0.1", front_port) as bad:
                env = bad.call(
                    "Parse@../evil",
                    grpc_pb.ParseRequest(pod_json="{}", logs="x"),
                )
            assert "invalid tenant id" in env.error
        finally:
            front.shutdown()
            front.server_close()
            for shim in shims:
                shim.shutdown()
                shim.server_close()


# ------------------------------------- shim client bounded forward-follow


class _ForwardingClient:
    """ShimClient with the transport stubbed: each address answers with
    a scripted envelope, so the hop loop is tested without sockets."""

    def __init__(self, script, **kw):
        from log_parser_tpu.shim.client import ShimClient

        self.script = script  # (host, port) -> error text ('' = success)
        self.calls: list[tuple[str, int]] = []

        outer = self

        class Stubbed(ShimClient):
            def _connect_with_retry(self):
                pass

            def _call_once(self, method, payload):
                from log_parser_tpu.shim import logparser_pb2 as pb

                outer.calls.append((self.host, self.port))
                return pb.Envelope(
                    method=method,
                    error=outer.script[(self.host, self.port)],
                )

        self.client = Stubbed("a", 1, sleep=lambda s: None, **kw)

    def call(self):
        from log_parser_tpu.shim import logparser_pb2 as pb

        return self.client.call("Health", pb.HealthRequest())


class TestShimForwardFollow:
    def test_follows_to_the_new_owner(self):
        fc = _ForwardingClient({
            ("a", 1): "tenant 'acme' migrated to http://b:1; retry after 0s",
            ("b", 1): "",
        })
        env = fc.call()
        assert env.error == ""
        assert fc.calls == [("a", 1), ("b", 1)]
        assert (fc.client.host, fc.client.port) == ("b", 1)  # moved for good
        assert fc.client.last_hops == 1

    def test_loop_is_detected_not_orbited(self):
        fc = _ForwardingClient({
            ("a", 1): "tenant 'acme' migrated to http://b:1",
            ("b", 1): "tenant 'acme' migrated to http://a:1",
        })
        env = fc.call()
        assert "migrated to" in env.error  # surfaced, not retried forever
        assert fc.calls == [("a", 1), ("b", 1)]

    def test_hops_are_bounded(self):
        script = {
            ("a", 1): "tenant 'x' migrated to http://b:1",
            ("b", 1): "tenant 'x' migrated to http://c:1",
            ("c", 1): "tenant 'x' migrated to http://d:1",
            ("d", 1): "tenant 'x' migrated to http://e:1",
            ("e", 1): "",
        }
        fc = _ForwardingClient(script, max_hops=2)
        env = fc.call()
        assert fc.client.last_hops == 2
        assert "migrated to" in env.error
        assert fc.calls == [("a", 1), ("b", 1), ("c", 1)]

    def test_default_resolver_keeps_the_port(self):
        from log_parser_tpu.shim.client import default_forward_resolver

        assert default_forward_resolver("http://new-host:8080/x", 9090) == (
            "new-host", 9090,
        )
        assert default_forward_resolver("nonsense", 9090) is None


# ------------------------------------- load-aware single-process placement


class TestTenantPlacementLoad:
    def _placement(self, load=None):
        from log_parser_tpu.parallel.pattern_sharded import TenantPlacement

        return TenantPlacement(devices=["d0", "d1", "d2"], load=load)

    def test_new_tenants_prefer_the_least_loaded_device(self):
        loads = {"d0": 5.0, "d1": 0.5, "d2": 3.0}
        place = self._placement(load=loads.__getitem__)
        assert place.move("t1") == "d1"
        loads["d1"] = 9.0
        assert place.move("t2") == "d2"
        assert place.assignments == {"t1": "d1", "t2": "d2"}

    def test_broken_load_signal_falls_back_to_round_robin(self):
        def load(_device):
            raise RuntimeError("scrape failed")

        place = self._placement(load=load)
        assert [place.move(f"t{i}") for i in range(4)] == [
            "d0", "d1", "d2", "d0",
        ]

    def test_no_callback_is_round_robin(self):
        place = self._placement()
        assert [place.move(f"t{i}") for i in range(3)] == ["d0", "d1", "d2"]


# ------------------------------------------------- shared compiled packs


class TestPackSharing:
    @pytest.fixture(autouse=True)
    def isolated_cache(self, tmp_path, monkeypatch):
        from log_parser_tpu.patterns import libcache

        monkeypatch.setenv("LOG_PARSER_TPU_CACHE", str(tmp_path))
        libcache.reset_packs()
        yield
        libcache.reset_packs()

    def _sets(self):
        return [
            make_pattern_set(
                [make_pattern("oom", regex="OutOfMemoryError",
                              confidence=0.9)],
                "shared-lib",
            )
        ]

    def test_n_identical_banks_build_one_pack(self):
        from log_parser_tpu.patterns import libcache
        from log_parser_tpu.patterns.bank import PatternBank

        banks = [PatternBank(self._sets()) for _ in range(5)]
        stats = libcache.pack_stats()
        assert stats["built"] == 1, stats
        assert stats["shared"] >= 4, stats
        assert stats["resident"] == 1, stats
        # the shared substructure is literally the same objects
        first = banks[0].columns[0]
        assert all(b.columns[0] is first for b in banks[1:])

    def test_shared_scores_match_unshared(self, monkeypatch):
        from log_parser_tpu.patterns import libcache
        from log_parser_tpu.patterns.bank import PatternBank

        shared = PatternBank(self._sets())
        again = PatternBank(self._sets())
        assert libcache.pack_stats()["shared"] >= 1

        monkeypatch.setenv("LOG_PARSER_TPU_PACK_SHARE", "0")
        libcache.reset_packs()
        unshared = PatternBank(self._sets())
        assert libcache.pack_stats() == {
            "built": 0, "shared": 0, "resident": 0, "residentBytes": 0,
        }
        for warm in (again, unshared):
            assert [p.id for p in warm.patterns] == [
                p.id for p in shared.patterns
            ]
            assert [c.regex for c in warm.columns] == [
                c.regex for c in shared.columns
            ]

    def test_pack_memo_is_lru_bounded(self, monkeypatch):
        from log_parser_tpu.patterns import libcache
        from log_parser_tpu.patterns.bank import PatternBank

        monkeypatch.setenv("LOG_PARSER_TPU_PACK_CACHE", "2")
        for i in range(4):
            PatternBank([
                make_pattern_set(
                    [make_pattern(f"p{i}", regex=f"needle{i}")],
                    f"lib-{i}",
                )
            ])
        stats = libcache.pack_stats()
        assert stats["built"] == 4 and stats["resident"] <= 2, stats
