"""TPU engine vs golden reference: full-pipeline score parity.

The contract under test: for any pattern library and any log,
``AnalysisEngine.analyze`` must produce the same events in the same
discovery order with scores within 1e-9 of ``GoldenAnalyzer.analyze``
(budget is 1e-6; f64 kernels land ~1e-13), including cross-request
frequency-state evolution."""

import random

import numpy as np
import pytest

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.golden import GoldenAnalyzer
from log_parser_tpu.models import PodFailureData
from log_parser_tpu.runtime import AnalysisEngine
from tests.conftest import FakeClock
from tests.helpers import make_pattern, make_pattern_set

TOL = 1e-9

FRAGMENTS = [
    "OutOfMemoryError",
    "Connection refused",
    "GC overhead",
    "dial tcp",
    "segfault",
    "probe failed",
    "disk pressure",
    "CrashLoop",
    "exit code 137",
    "permission denied",
]

NOISE = [
    "INFO all systems nominal",
    "metric cpu=0.3 mem=0.7",
    "GET /healthz 200",
    "reconciling deployment web",
    "",  # interior empty line
    "ERROR upstream timeout",  # context: error
    "WARN retry scheduled",  # context: warn
    "    at com.example.Foo.bar(Foo.java:42)",  # context: stack
    "caught IllegalStateException",  # context: exception
    "naïve UTF-8 line é",  # non-ASCII -> host verify path
    "progress 42%\rdone",  # lone \r inside a line
]


def random_library(rng: random.Random, n_patterns: int):
    severities = ["CRITICAL", "HIGH", "MEDIUM", "LOW", "INFO", "Bogus", ""]
    patterns = []
    for i in range(n_patterns):
        frag = rng.choice(FRAGMENTS)
        regex = rng.choice(
            [
                frag,
                rf"\b{frag.split()[0]}\b",
                rf"(?:{frag}|{rng.choice(FRAGMENTS)})",
                rf"{frag.split()[0]}\s+\w+" if " " in frag else frag,
            ]
        )
        secondaries = None
        if rng.random() < 0.5:
            secondaries = [
                (rng.choice(FRAGMENTS), round(rng.uniform(0.1, 0.9), 2),
                 rng.choice([0, 3, 10, 50, 500]))
                for _ in range(rng.randrange(1, 3))
            ]
        sequences = None
        if rng.random() < 0.4:
            sequences = [
                (round(rng.uniform(0.1, 0.6), 2),
                 [rng.choice(FRAGMENTS) for _ in range(rng.randrange(1, 4))])
            ]
        context = rng.choice([None, (1, 1), (3, 5), (10, 10), (0, 0)])
        # exercise duplicate ids (shared frequency slots) and empty ids
        pid = rng.choice([f"p{i}", f"p{i}", f"p{i % 3}", ""])
        patterns.append(
            make_pattern(
                pid,
                regex=regex,
                confidence=round(rng.uniform(0.1, 1.0), 2),
                severity=rng.choice(severities),
                secondaries=secondaries,
                sequences=sequences,
                context=context,
            )
        )
    # split across two pattern sets to exercise set-major discovery order
    cut = max(1, n_patterns // 2)
    return [
        make_pattern_set(patterns[:cut], "libA"),
        make_pattern_set(patterns[cut:], "libB"),
    ]


def random_logs(rng: random.Random, n_lines: int) -> str:
    lines = []
    for _ in range(n_lines):
        r = rng.random()
        if r < 0.35:
            lines.append(rng.choice(NOISE))
        elif r < 0.7:
            frag = rng.choice(FRAGMENTS)
            lines.append(f"{rng.choice(['', 'ts=123 '])}{frag} happened")
        else:
            lines.append("filler " + "".join(rng.choice("abcdef ") for _ in range(20)))
    trailer = rng.choice(["", "\n", "\n\n"])
    return "\n".join(lines) + trailer


def assert_results_match(r1, r2):
    ev1 = [(e.line_number, e.matched_pattern.id, e.matched_pattern.name) for e in r1.events]
    ev2 = [(e.line_number, e.matched_pattern.id, e.matched_pattern.name) for e in r2.events]
    assert ev1 == ev2
    for a, b in zip(r1.events, r2.events):
        if np.isnan(b.score):
            assert np.isnan(a.score)
        else:
            assert a.score == pytest.approx(b.score, abs=TOL), (
                a.line_number, a.matched_pattern.id)
        assert a.context.to_dict() == b.context.to_dict()
    assert r1.summary.to_dict() == r2.summary.to_dict()
    assert r1.metadata.total_lines == r2.metadata.total_lines
    assert r1.metadata.patterns_used == r2.metadata.patterns_used


@pytest.mark.parametrize("seed", range(8))
def test_random_library_parity(seed):
    rng = random.Random(seed)
    sets = random_library(rng, rng.randrange(2, 8))
    config = ScoringConfig(
        frequency_threshold=rng.choice([2.0, 10.0]),
        proximity_max_window=rng.choice([5, 100]),
    )
    engine = AnalysisEngine(sets, config, clock=FakeClock())
    golden = GoldenAnalyzer(sets, config, clock=FakeClock())
    for _ in range(3):  # frequency state must evolve identically
        logs = random_logs(rng, rng.randrange(5, 120))
        data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=logs)
        assert_results_match(engine.analyze(data), golden.analyze(data))
    assert (
        engine.frequency.get_frequency_statistics()
        == golden.frequency.get_frequency_statistics()
    )


LONG_LITERALS = [
    # >31 positions: truncated on the bit tier (primary and secondary
    # roles), exact via the engine's host verify / distance repair
    "could not connect to server: Connection refused",
    "Back-off restarting failed container in pod sandbox",
    "A fatal error has been detected by the Java Runtime",
    "Liveness probe failed: HTTP probe failed with statuscode: 503",
]


def random_long_library(rng: random.Random, n_patterns: int):
    """Libraries whose primaries/secondaries include >31-char literals
    and literal-bearing alternations — the truncation + repair paths."""
    patterns = []
    for i in range(n_patterns):
        lit = rng.choice(LONG_LITERALS)
        regex = rng.choice(
            [
                lit,
                rf"(?:{lit}|{rng.choice(FRAGMENTS)})",
                rf"^{lit}",
                lit + r"\d*",
            ]
        )
        secondaries = None
        if rng.random() < 0.6:
            secondaries = [
                (rng.choice(LONG_LITERALS + FRAGMENTS),
                 round(rng.uniform(0.1, 0.9), 2),
                 rng.choice([3, 8, 100]))
                for _ in range(rng.randrange(1, 3))
            ]
        patterns.append(
            make_pattern(
                f"p{i}",
                regex=regex,
                confidence=round(rng.uniform(0.1, 1.0), 2),
                severity=rng.choice(["CRITICAL", "HIGH", "LOW"]),
                secondaries=secondaries,
            )
        )
    return [make_pattern_set(patterns, "liblong")]


def random_long_logs(rng: random.Random, n_lines: int) -> str:
    """Corpora that plant full long literals AND their 31-char prefixes
    (device-only false positives the engine must repair away)."""
    lines = []
    for _ in range(n_lines):
        r = rng.random()
        lit = rng.choice(LONG_LITERALS)
        if r < 0.25:
            lines.append(lit + rng.choice(["", " tail", "!"]))
        elif r < 0.5:
            # the poison case: exactly the truncated prefix, not the full
            lines.append(rng.choice(["", "pad "]) + lit[:31])
        elif r < 0.65:
            lines.append(rng.choice(FRAGMENTS) + " happened")
        else:
            lines.append("noise " + "".join(rng.choice("xyz ") for _ in range(12)))
    return "\n".join(lines) + rng.choice(["", "\n"])


def _force_bit_policy(engine: AnalysisEngine) -> None:
    """Build the engine's matcher banks under the TPU tier policy (bit
    tiers on, truncation active) on the CPU test backend. Must run
    before the first ``engine.matchers`` access."""
    from log_parser_tpu.ops.match import MatcherBanks

    engine._matchers = MatcherBanks(
        engine.bank,
        bitglush_max_words=MatcherBanks.BITGLUSH_MAX_WORDS_TPU,
        shiftor_min_columns=MatcherBanks.SHIFTOR_MIN_COLUMNS_TPU,
        prefilter_min_columns=MatcherBanks.PREFILTER_MIN_COLUMNS_TPU,
        shiftor_sinks=False,
    )


@pytest.mark.parametrize("seed", range(6))
def test_random_long_literal_parity_bit_policy(seed):
    """Truncation + host verify/repair fuzz: long-literal libraries under
    the TPU tier policy, corpora salted with prefix-only poison lines,
    engine vs golden over evolving frequency state."""
    rng = random.Random(31000 + seed)
    sets = random_long_library(rng, rng.randrange(2, 6))
    config = ScoringConfig(proximity_max_window=rng.choice([5, 100]))
    engine = AnalysisEngine(sets, config, clock=FakeClock())
    _force_bit_policy(engine)
    assert engine.matchers.bitglush is not None
    golden = GoldenAnalyzer(sets, config, clock=FakeClock())
    for _ in range(3):
        logs = random_long_logs(rng, rng.randrange(5, 80))
        data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=logs)
        assert_results_match(engine.analyze(data), golden.analyze(data))
    assert (
        engine.frequency.get_frequency_statistics()
        == golden.frequency.get_frequency_statistics()
    )


class TestEngineEdgeCases:
    def _pair(self, patterns, config=None):
        sets = [make_pattern_set(patterns)]
        cfg = config or ScoringConfig()
        return (
            AnalysisEngine(sets, cfg, clock=FakeClock()),
            GoldenAnalyzer(sets, cfg, clock=FakeClock()),
        )

    def run_both(self, patterns, logs, config=None):
        engine, golden = self._pair(patterns, config)
        data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=logs)
        r1, r2 = engine.analyze(data), golden.analyze(data)
        assert_results_match(r1, r2)
        return r1

    def test_empty_logs(self):
        r = self.run_both([make_pattern("a", regex="X")], "")
        assert r.metadata.total_lines == 1

    def test_only_newlines(self):
        r = self.run_both([make_pattern("a", regex="X")], "\n\n")
        assert r.metadata.total_lines == 0

    def test_no_patterns(self):
        r = self.run_both([], "ERROR something")
        assert r.events == []

    def test_match_on_empty_interior_line(self):
        # ^$ matches the empty line between content lines
        self.run_both([make_pattern("e", regex="^$")], "a\n\nb")

    def test_non_ascii_lines_host_verified(self):
        # 'a.c' DOES match 'aéc' in Java (é is one char) but the byte-level
        # DFA sees two bytes — the host-verify override must restore line 1
        r = self.run_both([make_pattern("dot", regex="a.c")], "aéc\naxc")
        assert [e.line_number for e in r.events] == [1, 2]

    def test_host_fallback_column(self):
        # state blowup -> DFA rejected -> host matcher column, same results
        engine, golden = self._pair(
            [make_pattern("blow", regex=r"[ab]*a[ab]{12}", confidence=0.5)]
        )
        assert engine.dfa_bank.n_regexes < engine.bank.n_columns
        logs = "\n".join(["ab" * 10, "b" * 30, "a" * 14])
        data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=logs)
        assert_results_match(engine.analyze(data), golden.analyze(data))

    def test_shared_pattern_ids_frequency_order(self):
        # two patterns with the same id interleave one frequency counter
        patterns = [
            make_pattern("dup", regex="AAA", confidence=1.0, severity="INFO"),
            make_pattern("dup", regex="BBB", confidence=1.0, severity="INFO"),
        ]
        config = ScoringConfig(frequency_threshold=1.0)
        logs = "\n".join(["AAA BBB", "AAA", "BBB", "AAA BBB"] + ["x"] * 4)
        self.run_both(patterns, logs, config)

    def test_empty_matching_secondary_ignores_padding_rows(self):
        """A secondary like ^$ matches zero-length padding rows; those are
        beyond n_lines and must not create phantom proximity hits."""
        pattern = make_pattern(
            "p", regex="OOM", confidence=1.0, severity="INFO",
            secondaries=[(r"^$", 0.5, 50)],
        )
        # 3 real lines (padded to 8 device rows), no blank line anywhere
        self.run_both([pattern], "x\nx\nOOM happened")

    def test_primary_less_pattern_with_bad_secondary_is_skipped(self):
        from log_parser_tpu.models.pattern import Pattern, SecondaryPattern
        bad = Pattern(
            id="frag", severity="HIGH",
            secondary_patterns=[SecondaryPattern(regex=r"a*+", weight=0.5)],
        )
        engine, golden = self._pair([bad, make_pattern("ok", regex="ERROR")])
        assert engine.skipped_patterns == golden.skipped_patterns
        assert [pid for pid, _ in engine.skipped_patterns] == ["frag"]

    def test_skipped_pattern_leaves_no_orphan_columns(self):
        patterns = [
            make_pattern("bad", regex="GOODPRIMARY",
                         secondaries=[("fine", 0.5, 10), (r"(?>x)", 0.5, 10)]),
            make_pattern("ok", regex="ERROR"),
        ]
        engine, _ = self._pair(patterns)
        interned = {c.regex for c in engine.bank.columns}
        assert "GOODPRIMARY" not in interned
        assert "fine" not in interned
        assert "ERROR" in interned

    def test_zero_window_hours_first_match_has_no_penalty(self):
        """window=0: the FIRST match of a pattern must take the 'no entry'
        early return (penalty 0), not the NaN formula path — and later
        matches go NaN, matching golden exactly."""
        config = ScoringConfig(frequency_time_window_hours=0)
        self.run_both(
            [make_pattern("oom", regex="OOM", confidence=1.0, severity="INFO")],
            "OOM here\nnothing\nOOM again\nx",
            config,
        )

    def test_negative_threshold_never_matched(self):
        """threshold<0 with no tracker entry: golden early-returns 0."""
        config = ScoringConfig(frequency_threshold=-1.0)
        self.run_both(
            [make_pattern("e", regex="ERR", confidence=1.0, severity="INFO")],
            "ERR one\nx\nERR two\nx",
            config,
        )

    def test_negative_context_windows_are_empty_slices(self):
        """lines_before/after < 0 behave as empty slices (golden Python
        slicing), so the context window is the matched line only."""
        from log_parser_tpu.models.pattern import ContextExtraction
        pattern = make_pattern("c", regex="MATCH", confidence=1.0, severity="INFO")
        pattern.context_extraction = ContextExtraction(lines_before=-5, lines_after=-2)
        self.run_both([pattern], "ERROR a\nERROR b\nMATCH ERROR\nERROR c")

    def test_overlong_line_host_verified(self):
        long_line = "x" * 5000 + " OutOfMemoryError"
        r = self.run_both(
            [make_pattern("oom", regex="OutOfMemoryError")], long_line + "\nshort"
        )
        assert [e.line_number for e in r.events] == [1]
