"""Distributed resilience (parallel/resilience.py + distributed.py):
bounded broadcast dispatch with retry/backoff, follower health tracking,
degrade-to-local entry/exit, follower-side malformed-payload containment,
and the shim client's bounded retry.

Everything here is single-process: a :class:`StubTransport` stands in for
the jax.distributed control plane, so the whole ladder — timeout, retry,
degraded serving, heartbeat readmission — runs deterministically in-proc.
The real 2-process wire is covered by tests/test_distributed.py (and its
slow chaos variant)."""

from __future__ import annotations

import socket
import threading

import numpy as np
import pytest

from helpers import make_pattern, make_pattern_set

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.models.pod import PodFailureData
from log_parser_tpu.parallel import distributed as dist
from log_parser_tpu.parallel.distributed import (
    _PING,
    _SHUTDOWN,
    DistributedShardedEngine,
)
from log_parser_tpu.parallel.resilience import (
    BroadcastTimeout,
    DispatchCancelled,
    MeshHealth,
    MeshUnavailable,
    RetryPolicy,
    bounded_call,
    dispatch_with_retry,
)
from log_parser_tpu.runtime import faults
from log_parser_tpu.runtime.faults import FaultRegistry


@pytest.fixture(autouse=True)
def clean_faults():
    """Every test starts and ends with no fault registry installed;
    clearing lifts hung waiters so abandoned workers cannot linger."""
    faults.install(None)
    yield
    faults.install(None)


class StubTransport:
    """In-process stand-in for the jax.distributed control plane: records
    coordinator broadcasts, replays a scripted inbox to a follower, and
    answers ack allgathers for a fully-responsive follower group."""

    def __init__(self, process_count=2, process_index=0, inbox=()):
        self.n = process_count
        self.i = process_index
        self.sent: list[bytes] = []
        self.inbox = list(inbox)
        self.acks: list[list[int]] = []
        self.follower_errors = {pid: 0 for pid in range(1, process_count)}

    def process_count(self):
        return self.n

    def process_index(self):
        return self.i

    def broadcast(self, payload):
        if payload is None:  # follower side: receive the next script entry
            return self.inbox.pop(0)
        self.sent.append(payload)
        return payload

    def allgather(self, row):
        self.acks.append([int(v) for v in np.asarray(row)])
        rows = {int(np.asarray(row)[0]): np.asarray(row, dtype=np.int64)}
        for pid in range(self.n):
            rows.setdefault(
                pid,
                np.array([pid, self.follower_errors.get(pid, 0)], dtype=np.int64),
            )
        return np.stack([rows[pid] for pid in range(self.n)])


@pytest.fixture()
def stub():
    prev = dist.install_transport(StubTransport())
    yield dist.transport()
    dist.install_transport(prev)


def _sets():
    return [
        make_pattern_set(
            [
                make_pattern(
                    "oom", regex="OutOfMemoryError", confidence=0.8,
                    severity="HIGH", secondaries=[("GC overhead", 0.6, 10)],
                ),
                make_pattern("conn", regex="Connection refused", confidence=0.7,
                             severity="MEDIUM"),
            ]
        )
    ]


def _data():
    logs = "\n".join(
        "GC overhead limit" if i == 7
        else "java.lang.OutOfMemoryError: heap" if i == 9
        else "dial tcp: Connection refused" if i == 3
        else f"INFO tick {i}"
        for i in range(32)
    )
    return PodFailureData(pod={"metadata": {"name": "res"}}, logs=logs)


def _fast_policy(**kw):
    kw.setdefault("timeout_s", 0.2)
    kw.setdefault("retries", 1)
    kw.setdefault("backoff_s", 0.01)
    kw.setdefault("max_backoff_s", 0.02)
    return RetryPolicy(**kw)


# ----------------------------------------------------------- bounded_call


class TestBoundedCall:
    def test_returns_value_within_deadline(self):
        assert bounded_call(lambda ctx: 41 + 1, 5.0) == 42

    def test_unbounded_when_timeout_disabled(self):
        assert bounded_call(lambda ctx: "inline", 0) == "inline"

    def test_timeout_pre_collective(self):
        hang = threading.Event()
        with pytest.raises(BroadcastTimeout) as err:
            bounded_call(lambda ctx: hang.wait(5), 0.05, label="x")
        assert not err.value.entered_collective
        hang.set()

    def test_timeout_inside_collective(self):
        hang = threading.Event()

        def attempt(ctx):
            ctx.enter_collective()
            hang.wait(5)

        with pytest.raises(BroadcastTimeout) as err:
            bounded_call(attempt, 0.05)
        assert err.value.entered_collective
        hang.set()

    def test_abandoned_worker_cannot_enter_collective(self):
        """The watcher's cancel and the worker's enter_collective are
        atomic: once the deadline fires, a late worker aborts instead of
        emitting a stale broadcast."""
        release = threading.Event()
        outcome = {}

        def attempt(ctx):
            release.wait(5)  # deadline fires while we are parked here
            try:
                ctx.enter_collective()
                outcome["entered"] = True
            except DispatchCancelled:
                outcome["cancelled"] = True

        with pytest.raises(BroadcastTimeout):
            bounded_call(attempt, 0.05)
        release.set()
        for _ in range(100):
            if outcome:
                break
            import time

            time.sleep(0.01)
        assert outcome == {"cancelled": True}

    def test_exceptions_propagate(self):
        with pytest.raises(ValueError, match="boom"):
            bounded_call(lambda ctx: (_ for _ in ()).throw(ValueError("boom")), 1.0)


# ----------------------------------------------------- dispatch_with_retry


class TestDispatchRetry:
    def test_retry_succeeds_within_budget(self):
        health = MeshHealth(2)
        hang = threading.Event()
        calls = {"n": 0}

        def attempt(ctx):
            calls["n"] += 1
            if calls["n"] == 1:
                hang.wait(5)  # first attempt blows the deadline
            return "ok"

        out = dispatch_with_retry(attempt, _fast_policy(), health, sleep=lambda s: None)
        hang.set()
        assert out == "ok"
        assert calls["n"] == 2
        assert health.broadcast_timeouts == 1
        assert health.broadcast_retries == 1
        assert not health.degraded

    def test_budget_exhausted_raises_mesh_unavailable(self):
        health = MeshHealth(2, dead_after=99)
        hang = threading.Event()
        with pytest.raises(MeshUnavailable):
            dispatch_with_retry(
                lambda ctx: hang.wait(5), _fast_policy(), health,
                sleep=lambda s: None,
            )
        hang.set()
        assert health.broadcast_timeouts == 2  # initial + 1 retry
        assert not health.degraded  # below dead_after; the caller declares

    def test_in_collective_timeout_wedges_without_retry(self):
        health = MeshHealth(2)
        hang = threading.Event()
        calls = {"n": 0}

        def attempt(ctx):
            calls["n"] += 1
            ctx.enter_collective()
            hang.wait(5)

        with pytest.raises(MeshUnavailable):
            dispatch_with_retry(attempt, _fast_policy(retries=3), health,
                                sleep=lambda s: None)
        hang.set()
        assert calls["n"] == 1  # a torn collective is never retried
        assert health.wedged and health.degraded

    def test_exceptions_are_not_retried(self):
        calls = {"n": 0}

        def attempt(ctx):
            calls["n"] += 1
            raise ValueError("logic bug")

        with pytest.raises(ValueError):
            dispatch_with_retry(attempt, _fast_policy(retries=5), None,
                                sleep=lambda s: None)
        assert calls["n"] == 1

    def test_backoff_grows_exponentially(self):
        policy = RetryPolicy(timeout_s=1, retries=3, backoff_s=0.1,
                             max_backoff_s=10, jitter=0.0)
        assert [policy.delay_for(a) for a in (1, 2, 3)] == [0.1, 0.2, 0.4]
        capped = RetryPolicy(backoff_s=1.0, max_backoff_s=1.5, jitter=0.0)
        assert capped.delay_for(5) == 1.5
        jittered = RetryPolicy(backoff_s=0.1, jitter=0.5)
        assert all(0.1 <= jittered.delay_for(1) <= 0.15 for _ in range(16))


# ------------------------------------------------------------- MeshHealth


class TestMeshHealth:
    def test_threshold_declares_degraded(self):
        health = MeshHealth(3, dead_after=3)
        for _ in range(2):
            health.record_broadcast_timeout()
        assert not health.degraded
        health.record_broadcast_timeout()
        assert health.degraded and "3 consecutive" in health.reason

    def test_ack_resets_consecutive_failures(self):
        health = MeshHealth(2, dead_after=3)
        health.record_broadcast_timeout()
        health.record_broadcast_timeout()
        health.record_ack(1, errors=7)
        health.record_broadcast_timeout()
        assert not health.degraded
        stats = health.stats()
        assert stats["followers"]["1"]["errors"] == 7
        assert stats["followers"]["1"]["lastSeenAgoS"] is not None

    def test_readmit_restores_distributed_mode(self):
        health = MeshHealth(2, dead_after=1)
        health.record_broadcast_timeout()
        assert health.degraded
        assert health.readmit()
        assert not health.degraded
        assert health.stats()["readmissions"] == 1
        assert not health.readmit()  # idempotent: already distributed

    def test_wedged_refuses_readmission(self):
        health = MeshHealth(2)
        health.mark_wedged("torn")
        assert health.degraded and not health.readmit()
        stats = health.stats()
        assert stats["wedged"] and stats["mode"] == "degraded"


# ------------------------------------------------- degrade-to-local ladder


class TestDegradeToLocal:
    def _engine(self, stub):
        engine = DistributedShardedEngine(_sets(), ScoringConfig())
        engine.retry_policy = _fast_policy()
        return engine

    def test_follower_hang_degrades_then_probe_readmits(self, stub):
        """The acceptance scenario, in-process: a seeded follower hang
        exhausts the dispatch budget, the engine flips to degrade-to-local
        (responses marked), the probe re-admits once the fault clears, and
        every response matches the healthy sequence score-for-score."""
        engine = self._engine(stub)
        assert engine._is_multiprocess() and engine._is_coordinator()
        faults.install(FaultRegistry.parse("follower_hang:30@times=2"))

        r1 = engine.analyze(_data())  # both attempts hang -> degraded
        assert engine.mesh_health.degraded
        assert r1.metadata.degraded == "distributed-fallback"
        assert stub.sent == []  # the request never reached the group
        stats = engine.mesh_health.stats()
        assert stats["broadcastTimeouts"] == 2
        assert stats["broadcastRetries"] == 1
        assert stats["degradedRequests"] == 1

        r2 = engine.analyze(_data())  # still degraded: no dispatch attempt
        assert r2.metadata.degraded == "distributed-fallback"

        # fault budget (times=2) is spent: the next probe heals the mesh
        assert engine.probe_mesh()
        assert not engine.mesh_health.degraded
        assert stub.sent == [_PING]
        assert engine.mesh_health.stats()["readmissions"] == 1

        r3 = engine.analyze(_data())  # distributed again, broadcast flows
        assert r3.metadata.degraded is None
        assert len(stub.sent) == 2 and b"OutOfMemoryError" in stub.sent[1]

        # the degraded window served REAL results: identical to a healthy
        # engine fed the same three-request stream
        control = DistributedShardedEngine(_sets(), ScoringConfig())
        expect = [control.analyze(_data()) for _ in range(3)]
        for got, want in zip((r1, r2, r3), expect):
            assert [e.score for e in got.events] == [e.score for e in want.events]
            assert [e.line_number for e in got.events] == [
                e.line_number for e in want.events
            ]

    def test_transient_hang_retries_within_budget(self, stub):
        """One timed-out attempt + one clean retry: the request dispatches
        and the mesh never degrades — the satellite's deadline-budget
        contract."""
        engine = self._engine(stub)
        faults.install(FaultRegistry.parse("follower_hang:30@times=1"))
        result = engine.analyze(_data())
        assert result.metadata.degraded is None
        assert not engine.mesh_health.degraded
        assert len(stub.sent) == 1
        stats = engine.mesh_health.stats()
        assert stats["broadcastTimeouts"] == 1 and stats["broadcastRetries"] == 1

    def test_wedged_skips_shutdown_sentinel(self, stub):
        engine = self._engine(stub)
        engine.mesh_health.mark_wedged("torn collective")
        assert not engine.probe_mesh()
        engine.shutdown_followers()
        assert stub.sent == []  # no sentinel into a torn collective

    def test_shutdown_sentinel_flows_when_healthy(self, stub):
        engine = self._engine(stub)
        engine.shutdown_followers()
        assert stub.sent == [_SHUTDOWN]

    def test_health_loop_probes_and_stops(self, stub):
        engine = self._engine(stub)
        thread = engine.start_health_loop(interval_s=0.02)
        assert thread is not None
        for _ in range(200):
            if engine.mesh_health.stats()["probes"]:
                break
            import time

            time.sleep(0.01)
        engine.stop_health_loop()
        assert engine.mesh_health.stats()["probes"] >= 1
        assert _PING in stub.sent
        assert engine._health_thread is None


# ------------------------------------------------------------- followers


class TestFollowerLoop:
    def test_malformed_payload_counted_not_fatal(self):
        """Satellite: garbage broadcasts are logged with length + process
        id and counted — the follower survives to serve the next request
        and its error counter rides the next heartbeat ack."""
        stub = StubTransport(
            process_index=1,
            inbox=[b"\xff\xfenot json", _PING, _SHUTDOWN],
        )
        prev = dist.install_transport(stub)
        try:
            engine = DistributedShardedEngine(_sets(), ScoringConfig())
            engine.follower_loop()  # returns on the shutdown sentinel
            assert engine.follower_errors == 1
            assert stub.acks == [[1, 1]]  # [process_index, follower_errors]
        finally:
            dist.install_transport(prev)

    def test_follower_loop_refused_on_coordinator(self, stub):
        engine = DistributedShardedEngine(_sets(), ScoringConfig())
        with pytest.raises(RuntimeError, match="coordinator"):
            engine.follower_loop()


# ------------------------------------------------------ shim client retry


class _FakeShimServer:
    """Scripted framed-protocol server: each connection serves from a
    script of per-request actions ('close' drops the connection after
    accept; an Envelope is framed back)."""

    def __init__(self, script):
        self.script = list(script)
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.port = self.sock.getsockname()[1]
        self.thread = threading.Thread(target=self._serve, daemon=True)
        self.thread.start()

    def _serve(self):
        from log_parser_tpu.shim.framing import read_frame, write_frame

        while self.script:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            with conn:
                while self.script:
                    action = self.script.pop(0)
                    if action == "close":
                        break  # drop the connection mid-conversation
                    if read_frame(conn) is None:
                        break
                    write_frame(conn, action.SerializeToString())

    def close(self):
        self.sock.close()


class TestShimClientRetry:
    def _ok_envelope(self):
        from log_parser_tpu.shim import logparser_pb2 as pb

        return pb.Envelope(
            method="Parse", payload=pb.ParseResponse().SerializeToString()
        )

    def test_read_failure_reconnects_and_retries(self):
        from log_parser_tpu.shim.client import ShimClient

        server = _FakeShimServer(["close", self._ok_envelope()])
        try:
            sleeps = []
            with ShimClient(
                "127.0.0.1", server.port, retries=2, backoff_s=0.001,
                sleep=sleeps.append,
            ) as client:
                resp = client.parse({"metadata": {"name": "x"}}, "INFO ok")
            assert resp is not None
            assert client.last_attempts == 2
            assert sleeps  # backed off between attempts
        finally:
            server.close()

    def test_retry_budget_exhausted_raises(self):
        from log_parser_tpu.shim.client import ShimClient

        server = _FakeShimServer(["close", "close", "close"])
        try:
            with pytest.raises((ConnectionError, OSError)):
                with ShimClient(
                    "127.0.0.1", server.port, retries=2, backoff_s=0.001,
                    sleep=lambda s: None,
                ) as client:
                    client.parse({"metadata": {"name": "x"}}, "INFO ok")
        finally:
            server.close()

    def test_overload_envelope_honors_retry_after(self):
        from log_parser_tpu.shim import logparser_pb2 as pb
        from log_parser_tpu.shim.client import ShimClient

        shed = pb.Envelope(
            method="Parse", error="overloaded: queue full; retry after 3s"
        )
        server = _FakeShimServer([shed, self._ok_envelope()])
        try:
            sleeps = []
            with ShimClient(
                "127.0.0.1", server.port, retries=2, backoff_s=0.001,
                retry_after_cap_s=0.5, sleep=sleeps.append,
            ) as client:
                resp = client.parse({"metadata": {"name": "x"}}, "INFO ok")
            assert resp is not None
            assert client.last_attempts == 2
            assert 0.5 in sleeps  # the server's 3s hint, capped
        finally:
            server.close()

    def test_connect_retries_until_listener_responds(self, monkeypatch):
        from log_parser_tpu.shim import client as client_mod
        from log_parser_tpu.shim.client import ShimClient

        server = _FakeShimServer([])
        real_create = socket.create_connection
        calls = {"n": 0}

        def flaky(addr, *a, **kw):
            calls["n"] += 1
            if calls["n"] < 3:
                raise ConnectionRefusedError("listener not up yet")
            return real_create(addr, *a, **kw)

        monkeypatch.setattr(client_mod.socket, "create_connection", flaky)
        try:
            client = ShimClient(
                "127.0.0.1", server.port, retries=3, backoff_s=0.001,
                sleep=lambda s: None,
            )
            client.close()
            assert calls["n"] == 3
        finally:
            server.close()

    def test_connect_budget_exhausted_raises(self, monkeypatch):
        from log_parser_tpu.shim import client as client_mod
        from log_parser_tpu.shim.client import ShimClient

        monkeypatch.setattr(
            client_mod.socket,
            "create_connection",
            lambda *a, **kw: (_ for _ in ()).throw(ConnectionRefusedError()),
        )
        with pytest.raises(OSError):
            ShimClient("127.0.0.1", 1, retries=1, backoff_s=0.001,
                       sleep=lambda s: None)
