"""Differential tests: native batched regex pipeline vs the Python one.

The C++ port (native/log_parser_native.cpp section 4) re-implements the
STRICT mode of patterns/regex/parser.py + nfa.py so a whole library
compiles in one native call.  Its contract: for every regex it either
produces an automaton BEHAVIORALLY equal to the Python pipeline's, or
declines (status != 0) exactly where the Python pipeline raises
RegexUnsupportedError / DfaLimitError — it may never succeed with
different semantics.  These tests hold that contract over a curated
feature corpus, the builtin pattern library, and the synthetic bench
shapes.
"""

from __future__ import annotations

import random

import pytest

from log_parser_tpu.native import get_lib
from log_parser_tpu.native.dfabuild import build_dfas_batch
from log_parser_tpu.patterns.regex.dfa import (
    CompiledDfa,
    DfaLimitError,
    compile_regex_to_dfa,
)
from log_parser_tpu.patterns.regex.parser import RegexUnsupportedError

pytestmark = pytest.mark.skipif(
    get_lib() is None, reason="native library unavailable"
)

# (regex, ci) pairs covering every construct the parser handles, plus the
# unsupported ones (which must decline on BOTH sides)
FEATURE_CORPUS = [
    ("error", False),
    ("Error", True),
    ("time(out|r)+x", False),
    ("^anchored start", False),
    ("trailing end$", False),
    ("\\bword\\b", False),
    ("non\\Bboundary", False),
    ("\\AabsStart and \\z", False),
    ("before final \\Z", False),
    ("a.c", False),
    ("x\\d+y", False),
    ("\\D\\w\\W\\s\\S", False),
    ("[abc]+", False),
    ("[a-f0-9]{2,4}", False),
    ("[^xyz]", False),
    ("[\\d\\s]", False),
    ("[\\x41-\\x5a]", False),
    ("[\\u0041b]", False),
    ("[-a]", False),
    ("[a-]", False),
    ("[]x]", False),  # first ']' is literal
    ("[\\n\\t\\r\\f\\a\\e]", False),
    ("\\x41\\u0042", False),
    ("\\Qliteral.*+?()\\E tail", False),
    ("\\Q unterminated quote", False),
    ("\\n\\t\\r\\f\\a\\e", False),
    ("(?:group)ed", False),
    ("(?<name>named)", False),
    ("(?i)rest insensitive", False),
    ("pre(?i:mid)post", False),
    ("(?i)outer(?-i:inner)", True),
    ("a{3}", False),
    ("a{2,}", False),
    ("a{2,5}", False),
    ("a{,5}", False),  # literal brace in Java
    ("a{}", False),
    ("lazy.*?end", False),
    ("(\\b)*quantified assertion", False),
    ("(\\b)+kept", False),
    ("café utf8", False),
    ("\\u00e9scape", False),
    ("\\p{Alpha}\\p{Digit}\\p{Punct}", False),
    ("\\P{Digit}", False),
    ("[\\p{Upper}]", False),
    ("escaped \\. \\* \\( \\[ \\\\", False),
    ("status=[45]\\d\\d", False),
    ("pod-\\w+-[0-9a-f]{5}", False),
    ("^\\s*at\\s+[\\w\\.\\$]+\\(.*\\)\\s*$", False),
    ("\\b(ERROR|FATAL|CRITICAL|SEVERE)\\b", False),
    ("\\b\\w*Exception\\b|\\b\\w*Error\\b", False),
    ("", False),
    ("()", False),
    ("a|", False),
    ("|b", False),
    # unsupported on both sides
    ("look(?=ahead)", False),
    ("look(?!neg)", False),
    ("(?<=behind)x", False),
    ("(?<!negbehind)x", False),
    ("back(ref)\\1", False),
    ("named(?<g>x)\\k<g>", False),
    ("atomic(?>group)", False),
    ("possessive a*+", False),
    ("class[a&&b]", False),
    ("octal \\0101", False),
    ("control \\cA", False),
    ("\\G anchored", False),
    ("a{100}", True),  # counted rep beyond MAX_COUNTED=64
    ("nested [[a]]", False),
    ("[é]", False),  # non-ASCII in class
    ("bad flag (?m:x)", False),
    ("\\p{IsGreek}", False),
    ("trailing backslash \\", False),
    ("unbalanced (", False),
    ("unbalanced )", False),
    ("dangling *", False),
    ("reversed [z-a]", False),
    ("bad quant a{5,2}", False),
]


def _python_compile(rx: str, ci: bool):
    try:
        return compile_regex_to_dfa(rx, ci)
    except (RegexUnsupportedError, DfaLimitError):
        return None


def _to_dfa(rx: str, item) -> CompiledDfa:
    trans, byte_class, accept, start = item
    return CompiledDfa(
        regex=rx,
        trans=trans,
        byte_class=byte_class,
        accept_end=accept,
        start=start,
        n_states=trans.shape[0],
        n_classes=trans.shape[1],
    )


def _probe_inputs(rx: str) -> list[bytes]:
    """Inputs biased toward the regex's own bytes plus structured noise."""
    rng = random.Random(hash(rx) & 0xFFFF)
    lits = rx.encode("utf-8", "ignore")
    alphabet = (lits.replace(b"\\", b"") or b"ab") + b" aA0_.-\tz\r"
    out = [
        b"",
        lits,
        b" " + lits + b" ",
        lits.lower(),
        lits.upper(),
        b"prefix " + lits,
        lits + b" suffix",
        lits + b"\r",
    ]
    for _ in range(40):
        n = rng.randrange(0, 24)
        out.append(bytes(rng.choice(alphabet) for _ in range(n)))
    return out


def _assert_equivalent(rx: str, ci: bool, py, nat) -> None:
    if py is None:
        assert nat is None, f"{rx!r}: python declines but native compiled"
        return
    assert nat is not None, f"{rx!r}: native declined but python compiles"
    ndfa = _to_dfa(rx, nat)
    for s in _probe_inputs(rx):
        assert py.matches(s) == ndfa.matches(s), (
            f"{rx!r} disagrees on {s!r}: "
            f"python={py.matches(s)} native={ndfa.matches(s)}"
        )


def test_feature_corpus_equivalence():
    batch = build_dfas_batch(FEATURE_CORPUS)
    assert batch is not None and len(batch) == len(FEATURE_CORPUS)
    for (rx, ci), nat in zip(FEATURE_CORPUS, batch):
        _assert_equivalent(rx, ci, _python_compile(rx, ci), nat)


def test_builtin_library_equivalence():
    from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets

    entries: list[tuple[str, bool]] = []
    for ps in load_builtin_pattern_sets():
        for p in ps.patterns:
            if p.primary_pattern:
                entries.append((p.primary_pattern.regex, False))
            for sec in p.secondary_patterns or []:
                entries.append((sec.regex, False))
            for seq in p.sequence_patterns or []:
                for ev in seq.events or []:
                    entries.append((ev.regex, False))
    entries = sorted(set(entries))
    assert len(entries) > 80
    batch = build_dfas_batch(entries)
    assert batch is not None
    n_native = sum(1 for item in batch if item is not None)
    for (rx, ci), nat in zip(entries, batch):
        _assert_equivalent(rx, ci, _python_compile(rx, ci), nat)
    # the whole builtin library must ride the native pipeline (its dialect
    # is the port's floor) — a silent mass-decline would erase the boot win
    assert n_native == len(entries)


def test_synthetic_bench_shapes_equivalence():
    import sys

    sys.path.insert(0, "")  # repo root on path for bench_bank
    import bench_bank

    sets = bench_bank.synth_library(200)
    entries = []
    for ps in sets:
        for p in ps.patterns:
            entries.append((p.primary_pattern.regex, False))
            for sec in p.secondary_patterns or []:
                entries.append((sec.regex, False))
    batch = build_dfas_batch(entries)
    assert batch is not None
    for (rx, ci), nat in zip(entries, batch):
        _assert_equivalent(rx, ci, _python_compile(rx, ci), nat)
    assert all(item is not None for item in batch)


def test_extraction_equivalence():
    """Native literal/exact-sequence extraction must EQUAL the Python
    one — including set contents, ci folding, truncation, sequence
    order (it feeds Shift-Or packing), and the None classifications."""
    from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets
    from log_parser_tpu.patterns.regex.literals import (
        exact_sequences,
        extract_literals,
    )
    from log_parser_tpu.patterns.regex.parser import parse_java_regex

    entries = [e for e in FEATURE_CORPUS]
    for ps in load_builtin_pattern_sets():
        for p in ps.patterns:
            if p.primary_pattern:
                entries.append((p.primary_pattern.regex, False))
            for sec in p.secondary_patterns or []:
                entries.append((sec.regex, False))
    entries = sorted(set(entries))
    batch = build_dfas_batch(entries, with_extraction=True)
    assert batch is not None
    checked = 0
    for (rx, ci), item in zip(entries, batch):
        if item is None:
            continue
        _, nat_lits, nat_seqs = item
        node = parse_java_regex(rx, ci)
        assert nat_lits == extract_literals(node), rx
        assert nat_seqs == exact_sequences(node), rx
        checked += 1
    assert checked > 100


def test_ac_native_matches_python(monkeypatch):
    """The native AC build must produce ARRAY-identical tables to the
    Python BFS (same algorithm, same insertion/class order)."""
    import numpy as np

    import log_parser_tpu.native as native_mod
    from log_parser_tpu.patterns.regex.ac import AhoCorasick

    cases = [
        ([b"error", b"err", b"rror", b"timeout", b"time", b"out", b"x", b"",
          b"status=ok", b"statue"], [0, 0, 1, 2, 3, 1, 4, 5, 2, 3]),
        ([b"a"], None),
        ([], None),
    ]
    rng = random.Random(99)
    for _ in range(5):
        lits = [
            bytes(rng.randrange(97, 123) for _ in range(rng.randrange(1, 12)))
            for _ in range(rng.randrange(2, 60))
        ]
        cases.append((lits, [rng.randrange(0, 8) for _ in lits]))

    for lits, groups in cases:
        nat = AhoCorasick(lits, groups)
        with monkeypatch.context() as m:
            m.setattr(native_mod, "get_lib", lambda: None)
            py = AhoCorasick(lits, groups)
        assert (nat.n_nodes, nat.n_classes, nat.n_words) == (
            py.n_nodes, py.n_classes, py.n_words
        )
        np.testing.assert_array_equal(nat.goto, py.goto)
        np.testing.assert_array_equal(nat.byte_class, py.byte_class)
        np.testing.assert_array_equal(nat.out_words, py.out_words)
        np.testing.assert_array_equal(nat.has_out, py.has_out)


def test_random_library_equivalence():
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).parent))
    from test_engine_parity import random_library

    entries: list[tuple[str, bool]] = []
    for seed in range(40):
        rng = random.Random(10_000 + seed)
        for ps in random_library(rng, rng.randrange(2, 8)):
            for p in ps.patterns:
                if p.primary_pattern:
                    entries.append((p.primary_pattern.regex, False))
                for sec in p.secondary_patterns or []:
                    entries.append((sec.regex, False))
                for seq in p.sequence_patterns or []:
                    for ev in seq.events or []:
                        entries.append((ev.regex, False))
    entries = sorted(set(entries))
    batch = build_dfas_batch(entries)
    assert batch is not None
    for (rx, ci), nat in zip(entries, batch):
        _assert_equivalent(rx, ci, _python_compile(rx, ci), nat)
