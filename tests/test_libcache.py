"""Whole-library bank snapshot (patterns/libcache.py): warm restore
equivalence, skip-decision preservation, lazy host compilation, corrupt
entry containment, and content-keyed invalidation."""

from __future__ import annotations

import pickle

import pytest

from helpers import make_pattern, make_pattern_set


@pytest.fixture()
def cache_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("LOG_PARSER_TPU_CACHE", str(tmp_path))
    # these tests pin the DISK snapshot layer (quarantine, fault
    # injection, lazy restore); the in-process pack memo would answer
    # warm loads before the disk is ever read, so park it — the memo
    # has its own coverage (tests/test_fleet.py TestPackSharing)
    monkeypatch.setenv("LOG_PARSER_TPU_PACK_SHARE", "0")
    from log_parser_tpu.patterns import libcache
    libcache.reset_packs()
    return tmp_path


def _sets():
    return [
        make_pattern_set(
            [
                make_pattern("ok-1", regex="OutOfMemoryError", confidence=0.9),
                make_pattern("ok-2", regex="x(code|status)=[45]\\d\\d",
                             confidence=0.5),
                make_pattern("bad-1", regex="broken(", confidence=0.5),
                make_pattern("ok-3", regex="\\btimeout\\b", confidence=0.7),
            ]
        )
    ]


def _bank_fingerprint(bank):
    return (
        [(c.regex, c.case_insensitive, c.dfa is None, c.exact_seqs,
          c.literals) for c in bank.columns],
        [p.id for p in bank.patterns],
        bank.skipped_patterns,
        bank.primary_columns.tolist(),
        [(s.pattern_idx, s.column, s.weight, s.window)
         for s in bank.secondaries],
        bank.freq_ids,
    )


def test_warm_restore_is_equivalent_and_lazy(cache_dir):
    from log_parser_tpu.patterns.bank import PatternBank

    cold = PatternBank(_sets())
    snaps = list((cache_dir / "bank").glob("*.pkl"))
    assert snaps, "snapshot not written"

    warm = PatternBank(_sets())
    assert _bank_fingerprint(warm) == _bank_fingerprint(cold)
    # warm columns have NOT compiled their golden host patterns yet
    assert all(c._host is None for c in warm.columns)
    # the property compiles on demand and matches
    assert warm.columns[-1].host.search("a timeout b")
    # bad regex skipped identically without any compile on the warm path
    assert warm.skipped_patterns and warm.skipped_patterns[0][0] == "bad-1"


def test_corrupt_snapshot_rebuilds(cache_dir):
    from log_parser_tpu.patterns.bank import PatternBank

    PatternBank(_sets())
    (snap,) = (cache_dir / "bank").glob("*.pkl")
    snap.write_bytes(b"not a pickle")
    bank = PatternBank(_sets())  # must not raise
    assert bank.n_patterns == 3


def test_malformed_snapshot_contents_rebuild(cache_dir):
    from log_parser_tpu.patterns import libcache
    from log_parser_tpu.patterns.bank import PatternBank

    PatternBank(_sets())
    (path,) = (cache_dir / "bank").glob("*.pkl")
    with open(path, "rb") as f:
        snap = pickle.load(f)
    snap["kept"] = [[0]] * 7  # wrong shape: restore must fall back
    with open(path, "wb") as f:
        pickle.dump(snap, f)
    bank = PatternBank(_sets())
    assert bank.n_patterns == 3 and len(bank.columns) >= 7


def test_content_keyed_invalidation(cache_dir):
    from log_parser_tpu.patterns.bank import PatternBank

    PatternBank(_sets())
    changed = _sets()
    changed[0].patterns[0].primary_pattern.regex = "SomethingElse"
    bank = PatternBank(changed)
    assert any(c.regex == "SomethingElse" for c in bank.columns)
    assert len(list((cache_dir / "bank").glob("*.pkl"))) == 2


def test_ac_build_cached_roundtrip(cache_dir):
    import numpy as np

    from log_parser_tpu.patterns.regex.ac import AhoCorasick

    lits = [b"error", b"warn", b"exception in", b"err"]
    groups = [0, 1, 2, 0]
    cold = AhoCorasick.build_cached(lits, groups)
    assert list((cache_dir / "ac").glob("*.npz"))
    warm = AhoCorasick.build_cached(lits, groups)
    for f in ("goto", "byte_class", "out_words", "has_out"):
        np.testing.assert_array_equal(getattr(cold, f), getattr(warm, f))
    assert warm.scan(b"an exception in warnings") == cold.scan(
        b"an exception in warnings"
    )
    # corrupt entry: rebuilt, not crashed
    (entry,) = (cache_dir / "ac").glob("*.npz")
    entry.write_bytes(b"junk")
    again = AhoCorasick.build_cached(lits, groups)
    np.testing.assert_array_equal(again.goto, cold.goto)


def test_warm_engine_end_to_end(cache_dir):
    """A warm-restored bank drives the full engine identically."""
    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.models.pod import PodFailureData
    from log_parser_tpu.runtime import AnalysisEngine

    logs = "ok\njava.lang.OutOfMemoryError: heap\nxstatus=503 now\ntimeout x"
    data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=logs)
    r_cold = AnalysisEngine(_sets(), ScoringConfig()).analyze(data)
    r_warm = AnalysisEngine(_sets(), ScoringConfig()).analyze(data)
    cold_ev = [(e.matched_pattern.id, e.line_number, e.score)
               for e in r_cold.events]
    warm_ev = [(e.matched_pattern.id, e.line_number, e.score)
               for e in r_warm.events]
    assert cold_ev == warm_ev and len(cold_ev) == 3
