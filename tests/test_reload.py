"""Zero-downtime pattern hot reload (runtime/reload.py).

The rollback invariant under test everywhere: any failure at any stage
(parse, build, canary, swap) leaves the live engine byte-for-byte
untouched — same bank OBJECT, same frequency stats, same scores — and
a retry after the failure succeeds. Success swaps atomically under the
quiescence gate: concurrent (batched) requests all complete, none fail.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import pytest
import yaml

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.models.pod import PodFailureData
from log_parser_tpu.runtime import AnalysisEngine, faults
from log_parser_tpu.runtime.faults import FaultRegistry
from log_parser_tpu.runtime.reload import (
    PatternReloader,
    PatternWatcher,
    ReloadError,
    parse_yaml_sets,
)
from log_parser_tpu.serve import make_server
from tests.helpers import make_pattern, make_pattern_set


@pytest.fixture(autouse=True)
def clean_registry():
    faults.install(None)
    yield
    faults.install(None)


def _yaml(sets) -> str:
    return "\n---\n".join(yaml.safe_dump(s.to_dict(drop_none=True)) for s in sets)


def _v1_sets():
    return [
        make_pattern_set(
            [
                make_pattern("oom", regex="OutOfMemoryError", confidence=0.9,
                             severity="CRITICAL"),
                make_pattern("conn", regex="Connection refused", confidence=0.7),
            ],
            "lib-v1",
        )
    ]


def _v2_sets():
    # "oom" survives, "conn" is dropped, "disk" is new
    return [
        make_pattern_set(
            [
                make_pattern("oom", regex="OutOfMemoryError", confidence=0.9,
                             severity="CRITICAL"),
                make_pattern("disk", regex="No space left on device",
                             confidence=0.8, severity="HIGH"),
            ],
            "lib-v2",
        )
    ]


def _pod(logs: str) -> PodFailureData:
    return PodFailureData(pod={"metadata": {"name": "reload"}}, logs=logs)


def _engine() -> AnalysisEngine:
    return AnalysisEngine(_v1_sets(), ScoringConfig())


MIXED = (
    "INFO boot\n"
    "java.lang.OutOfMemoryError: heap\n"
    "Connection refused\n"
    "No space left on device\n"
)


def _matched_ids(result) -> set:
    return {
        e.matched_pattern.id for e in result.events if e.matched_pattern
    }


# --------------------------------------------------------- parse_yaml_sets


class TestParseYamlSets:
    def test_multi_document_and_list_forms(self):
        text = _yaml(_v1_sets() + _v2_sets())
        assert [s.metadata.library_id for s in parse_yaml_sets(text)] == [
            "lib-v1", "lib-v2",
        ]
        as_list = yaml.safe_dump(
            [s.to_dict(drop_none=True) for s in _v1_sets() + _v2_sets()]
        )
        assert len(parse_yaml_sets(as_list)) == 2

    @pytest.mark.parametrize(
        "text,reason_part",
        [
            ("{unclosed: [", "invalid YAML"),
            ("just a scalar", "must be a mapping"),
            ("- 1\n- 2\n", "must be a mapping"),
            ("", "no pattern sets"),
            ("---\n---\n", "no pattern sets"),
            ("metadata: {library_id: x}\npatterns: 7\n", "invalid pattern set"),
        ],
    )
    def test_malformed_body_raises_build_error(self, text, reason_part):
        with pytest.raises(ReloadError) as err:
            parse_yaml_sets(text)
        assert err.value.stage == "build"
        assert reason_part in err.value.reason
        assert err.value.to_json()["error"] == "reload rejected"


# ----------------------------------------------------------- swap contract


class TestReloadSwap:
    def test_swap_replaces_banks_and_bumps_epoch(self):
        engine = _engine()
        before = _matched_ids(engine.analyze(_pod(MIXED)))
        assert before == {"oom", "conn"}

        envelope = PatternReloader(engine).reload(yaml_text=_yaml(_v2_sets()))
        assert envelope["status"] == "reloaded"
        assert envelope["epoch"] == 1 == engine.reload_epoch
        assert envelope["patternSets"] == 1
        assert envelope["patterns"] == 2
        assert envelope["canaryEvents"] > 0
        assert engine.reload_count == 1 and engine.reload_failures == 0

        after = _matched_ids(engine.analyze(_pod(MIXED)))
        assert after == {"oom", "disk"}  # old pattern gone, new one live

    def test_frequency_carries_over_for_survivors_only(self):
        engine = _engine()
        engine.analyze(_pod(MIXED))  # oom: 1, conn: 1
        assert engine.frequency.get_frequency_statistics() == {
            "oom": 1, "conn": 1,
        }
        PatternReloader(engine).reload(yaml_text=_yaml(_v2_sets()))
        # the survivor keeps its history; the dropped id is pruned, the
        # new id starts cold
        assert engine.frequency.get_frequency_statistics() == {"oom": 1}
        engine.analyze(_pod(MIXED))
        assert engine.frequency.get_frequency_statistics() == {
            "oom": 2, "disk": 1,
        }

    @pytest.mark.parametrize("site", ["reload_build", "reload_canary"])
    def test_injected_failure_rolls_back_untouched(self, site):
        engine = _engine()
        before_events = [
            (e.line_number, e.score) for e in engine.analyze(_pod(MIXED)).events
        ]
        bank_before = engine.bank
        stats_before = engine.frequency.get_frequency_statistics()
        reloader = PatternReloader(engine)

        faults.install(FaultRegistry.parse(f"{site}_raise@times=1"))
        with pytest.raises(ReloadError) as err:
            reloader.reload(yaml_text=_yaml(_v2_sets()))
        assert err.value.stage == ("build" if site == "reload_build" else "canary")
        assert engine.bank is bank_before  # the same object: no partial swap
        assert engine.reload_epoch == 0
        assert engine.reload_failures == 1
        assert engine.last_reload_error is not None
        assert engine.frequency.get_frequency_statistics() == stats_before
        # served results are unchanged after the rollback
        again = [
            (e.line_number, e.score) for e in engine.analyze(_pod(MIXED)).events
        ]
        assert again == before_events

        # fault budget spent: the retry goes through
        envelope = reloader.reload(yaml_text=_yaml(_v2_sets()))
        assert envelope["epoch"] == 1
        assert engine.last_reload_error is None

    def test_reload_under_concurrent_batched_load(self):
        """The acceptance gate: a swap while batched requests are in
        flight — every request completes, none fail, and requests that
        entered before the swap score on the OLD banks."""
        engine = _engine()
        engine.enable_batching(wait_ms=2.0, batch_max=4)
        reloader = PatternReloader(engine)
        errors: list = []
        results: list = []
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                try:
                    results.append(engine.analyze_batched(_pod(MIXED)))
                except Exception as exc:  # noqa: BLE001 - any failure fails the test
                    errors.append(exc)
                    return

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            time.sleep(0.1)  # requests genuinely in flight
            envelope = reloader.reload(yaml_text=_yaml(_v2_sets()))
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30.0)
        try:
            assert errors == []
            assert envelope["epoch"] == 1
            assert engine.reload_failures == 0
            assert results  # the hammers did real work
            # after the dust settles the new library serves
            assert _matched_ids(engine.analyze_batched(_pod(MIXED))) == {
                "oom", "disk",
            }
        finally:
            engine.batcher.close()


# ------------------------------------------------------------ HTTP contract


def _post(url: str, body: bytes):
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/x-yaml"}
    )
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestReloadEndpoint:
    @pytest.fixture()
    def server_url(self):
        server = make_server(_engine(), host="127.0.0.1", port=0)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        yield f"http://127.0.0.1:{server.server_address[1]}"
        server.shutdown()
        server.server_close()

    def test_valid_body_swaps_and_answers_envelope(self, server_url):
        status, body = _post(
            server_url + "/patterns/reload", _yaml(_v2_sets()).encode()
        )
        assert status == 200
        assert body["status"] == "reloaded" and body["epoch"] == 1

    def test_invalid_yaml_is_structured_409(self, server_url):
        status, body = _post(server_url + "/patterns/reload", b"{unclosed: [")
        assert status == 409
        assert body["error"] == "reload rejected"
        assert body["stage"] == "build"
        assert "invalid YAML" in body["reason"]

    def test_empty_body_without_pattern_dir_is_409(self, server_url):
        status, body = _post(server_url + "/patterns/reload", b"")
        assert status == 409 and body["stage"] == "build"

    def test_non_utf8_body_is_400(self, server_url):
        status, body = _post(server_url + "/patterns/reload", b"\xff\xfe\x00ok")
        assert status == 400
        assert body == {"error": "body is not UTF-8"}

    def test_oversized_body_is_413(self, server_url):
        """The cap rejects on declared Content-Length BEFORE reading the
        body (a runaway payload must not balloon the process), so speak
        raw HTTP: send only the head and read the immediate 413."""
        import socket

        host, port = server_url[len("http://"):].rsplit(":", 1)
        with socket.create_connection((host, int(port)), timeout=30) as sock:
            sock.sendall(
                b"POST /patterns/reload HTTP/1.1\r\n"
                b"Host: test\r\n"
                b"Content-Length: %d\r\n"
                b"Connection: close\r\n\r\n" % ((4 << 20) + 1)
            )
            raw = b""
            while True:
                chunk = sock.recv(65536)
                if not chunk:
                    break
                raw = raw + chunk
        head, _, payload = raw.partition(b"\r\n\r\n")
        assert b" 413 " in head.split(b"\r\n", 1)[0]
        assert json.loads(payload) == {"error": "payload too large"}


# ----------------------------------------------------------------- watcher


class TestPatternWatcher:
    def test_directory_edit_triggers_reload(self, tmp_path):
        path = tmp_path / "lib.yaml"
        path.write_text(_yaml(_v1_sets()))
        engine = _engine()
        watcher = PatternWatcher(
            PatternReloader(engine, str(tmp_path)), str(tmp_path),
            interval_s=0.05,
        )
        watcher.start()
        try:
            path.write_text(_yaml(_v2_sets()))
            deadline = time.monotonic() + 60.0
            while engine.reload_epoch == 0 and time.monotonic() < deadline:
                time.sleep(0.05)
            assert engine.reload_epoch == 1
            assert watcher.reload_attempts >= 1
            assert _matched_ids(engine.analyze(_pod(MIXED))) == {"oom", "disk"}
        finally:
            watcher.stop()

    def test_broken_edit_keeps_old_banks_until_fixed(self, tmp_path):
        path = tmp_path / "lib.yaml"
        path.write_text(_yaml(_v1_sets()))
        engine = _engine()
        reloader = PatternReloader(engine, str(tmp_path))
        watcher = PatternWatcher(reloader, str(tmp_path), interval_s=0.05)
        watcher.start()
        try:
            path.write_text("{unclosed: [")  # an operator mid-edit
            deadline = time.monotonic() + 60.0
            while watcher.reload_attempts == 0 and time.monotonic() < deadline:
                time.sleep(0.05)
            assert engine.reload_epoch == 0  # old banks still serving
            assert _matched_ids(engine.analyze(_pod(MIXED))) == {"oom", "conn"}
        finally:
            watcher.stop()
