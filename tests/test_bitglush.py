"""Bit-parallel extended Shift-And engine: compile coverage, exactness vs
host ``re`` per feature, fuzz over random lines, and the tier wiring."""

from __future__ import annotations

import random
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from log_parser_tpu.golden.javacompat import compile_java_regex
from log_parser_tpu.ops.bitglush import BitGlushBank
from log_parser_tpu.ops.encode import encode_lines
from log_parser_tpu.ops.match import pack_byte_pairs
from log_parser_tpu.patterns.regex.bitprog import (
    BitUnsupportedError,
    compile_bitprog_regex,
    expand_asserts,
    has_asserts,
)


def run_bank(
    regexes: list[tuple[str, bool]], lines: list[str], deassert: bool = False
) -> np.ndarray:
    entries = [
        (i, compile_bitprog_regex(rx, ci)) for i, (rx, ci) in enumerate(regexes)
    ]
    if deassert:
        entries = [(i, expand_asserts(p)) for i, p in entries]
        assert not any(has_asserts(p) for _, p in entries)
    bank = BitGlushBank(entries)
    enc = encode_lines(lines)
    lines_tb = jnp.asarray(enc.u8.T)
    lens = jnp.asarray(enc.lengths)
    B = enc.u8.shape[0]
    init, step, finish = bank.pair_stepper(B, lens)
    pairs, ts = pack_byte_pairs(lines_tb)
    carry, _ = jax.lax.scan(
        lambda c, xs: (step(c, xs[0][0], xs[0][1], xs[1]), None),
        init,
        (pairs, ts),
    )
    return np.asarray(finish(carry))[: len(lines)]


def check_exact(
    regexes: list[tuple[str, bool]], lines: list[str], deassert: bool = False
):
    got = run_bank(regexes, lines, deassert=deassert)
    for j, (rx, ci) in enumerate(regexes):
        host = compile_java_regex(rx, ci)
        for i, line in enumerate(lines):
            want = host.search(line) is not None
            assert got[i, j] == want, (
                f"regex {rx!r} ci={ci} line {line!r}: got {got[i, j]}, want {want}"
            )


FEATURES = [
    # plain literals, incl. one spanning >32 positions (cross-word shift)
    ("OutOfMemoryError", False),
    ("A fatal error has been detected by the Java Runtime Environment", False),
    # classes and bounded repeats
    ("x[45]\\d\\d", False),
    ("a{3}b", False),
    ("ab{2,4}c", False),
    # plus / star / optional
    ("Port \\d+ in use", False),
    ("Exit Code:\\s*137", False),
    ("colou?r", False),
    # gaps
    ("status.*red", False),
    ("node .* not ready", False),
    # alternation incl. nested group expansion
    ("foo|ba[rz]", False),
    ("liquibase.* (failed|error)", False),
    ("(sorry, )?too many (connections|clients)", False),
    # anchors and boundaries
    ("^startline", False),
    ("endline$", False),
    ("^whole line$", False),
    ("\\btimeout\\b", False),
    ("\\bdial tcp\\b", False),
    ("\\b(WARN|WARNING)\\b", True),
    ("\\b\\w*Exception\\b|\\b\\w*Error\\b", False),
    ("^\\s*at\\s+[\\w\\.\\$]+\\(.*\\)\\s*$", False),
    # case-insensitive
    ("deadlock", True),
    # non-word boundary
    ("\\Bood", False),
]

FEATURE_LINES = [
    "",
    "x",
    "java.lang.OutOfMemoryError: heap",
    "A fatal error has been detected by the Java Runtime Environment:",
    "the Java Runtime Environment",
    "x503 status",
    "x403",
    "x903",
    "aaab",
    "aab",
    "abbc abbbbc",
    "abc",
    "Port 8080 in use",
    "Port  in use",
    "Exit Code:137",
    "Exit Code: 137",
    "Exit Code :137",
    "color colour colouur",
    "status is red",
    "statusred",
    "red status",
    "node web-1 not ready",
    "foo bar baz",
    "liquibase migration error",
    "liquibase ok",
    "too many connections",
    "sorry, too many clients",
    "sorry too many clients",
    "startline here",
    "not startline",
    "an endline",
    "endline not",
    "whole line",
    " whole line",
    "timeout after",
    "timeouts after",
    "xtimeout",
    "dial tcp 10.0.0.7",
    "dials tcp",
    "warn: warning things",
    "WARNED",
    "threw FooException here",
    "Exceptional",
    "plain Error",
    "  at com.example.Service.handle(Service.java:42)",
    "at com.example.run(X.java:1) extra",
    "  at  spaced(Y.scala:2)  ",
    "DEADLOCK found",
    "good wood",
    "oodles",
    "ood start",
]


def test_feature_exactness():
    check_exact(FEATURES, FEATURE_LINES)


def test_feature_exactness_deasserted():
    """The de-assert rewrite (expand_asserts) stays exact on every
    feature, including leading/trailing \\b, \\B, and their ^/$/case
    interactions."""
    check_exact(FEATURES, FEATURE_LINES, deassert=True)


def test_deassert_shapes():
    """Shapes at the edges of the rewrite: single-item \\b\\w+\\b (PLUS
    split both ends), pre-assert on a PLUS, impure trailing byteset
    (split), cascade trailing (uniform), and \\B both ways."""
    regexes = [
        ("\\b\\w+\\b", False),
        ("\\bx+y\\b", False),
        ("x[=a]\\b", False),  # impure final byteset: split
        # cascade [\s*, b] mixes word-ness across accepting positions ->
        # rejected ("word-ness-impure trailing cascade"); asserted below
        ("ab\\s*\\b", False),
        ("\\Bood\\b", False),
        ("\\btag\\B", False),
    ]
    lines = [
        "", "x", "word", " word ", "=word=", "xxy", "xy z", "axy.",
        "x= y", "xa b", "ab  c", "ab", "abc", "good food", "oodles",
        "tag", "tags", "tag s", "a tag", "atag b", "x=", "x=,", "=x",
    ]
    with pytest.raises(BitUnsupportedError):
        expand_asserts(compile_bitprog_regex("ab\\s*\\b", False))
    for rx, ci in regexes:
        try:
            prog = expand_asserts(compile_bitprog_regex(rx, ci))
        except BitUnsupportedError:
            continue  # rejected shapes stay on gated tiers — fine
        assert not has_asserts(prog)
        check_exact([(rx, ci)], lines, deassert=True)


def test_generative_fuzz_deasserted():
    """Random regexes over the assert-bearing fragment, run through
    expand_asserts, must match host re exactly."""
    rng = random.Random(424242)
    regexes: list[tuple[str, bool]] = []
    attempts = 0
    while len(regexes) < 60 and attempts < 1500:
        attempts += 1
        rx = _gen_regex(rng)
        if "\\b" not in rx and rng.random() < 0.8:
            continue  # bias toward assert-bearing shapes
        ci = rng.random() < 0.2
        try:
            prog = expand_asserts(compile_bitprog_regex(rx, ci))
        except BitUnsupportedError:
            continue
        assert not has_asserts(prog)
        try:
            compile_java_regex(rx, ci)
        except Exception:
            continue
        regexes.append((rx, ci))
    assert len(regexes) >= 40, f"generator too restrictive: {len(regexes)}"
    alphabet = "abcxyz05 _-:AB9\t."
    lines = [
        "".join(rng.choice(alphabet) for _ in range(rng.randrange(0, 50)))
        for _ in range(250)
    ]
    lines += ["", "a", " ", "foo", "bar:", "x0 x0 x0", "abc05xyz", "a" * 120]
    check_exact(regexes, lines, deassert=True)


def test_builtin_bank_fully_deasserted():
    """The builtin library's bit bank must come out of the de-assert
    rewrite with every word-ness op group off (that is the point: ~8 of
    ~18 ops leave the scan body)."""
    from log_parser_tpu.ops.match import MatcherBanks
    from log_parser_tpu.patterns.bank import PatternBank
    from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets

    bank = PatternBank(load_builtin_pattern_sets())
    mb = MatcherBanks(bank, bitglush_max_words=192)
    g = mb.bitglush
    assert g is not None
    assert not g.has_preassert and not g.has_tb and not g.needs_wordness


def test_builtin_union_columns_exact_on_corpus():
    """Every builtin dense-eligible regex that compiles to a bit program
    matches the host `re` exactly over a mixed corpus."""
    from log_parser_tpu.patterns.bank import PatternBank
    from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets

    bank = PatternBank(load_builtin_pattern_sets())
    regexes = []
    for col in bank.columns:
        if col.dfa is None or col.exact_seqs is not None:
            continue
        try:
            compile_bitprog_regex(col.regex, col.case_insensitive)
        except BitUnsupportedError:
            continue
        regexes.append((col.regex, col.case_insensitive))
    # MAX_EXACT_LEN=64 routes long literal alternations to Shift-Or
    # chains, so ~32 dense-eligible columns remain for the bit tier
    assert len(regexes) >= 25

    rng = random.Random(7)
    words = [
        "ERROR", "error", "timeout", "dial", "tcp", "OOMKilled", "status",
        "red", "node", "not", "ready", "at", "failed", "Migration", "x",
        "Exception", "Error", "deadlock", "FATAL:", "too", "many",
        "connections", "goroutine", "137", "Exit", "Code:", "segfault",
        "0af3", "(", ")", "running", "[running]", "upstream", "Full", "GC",
    ]
    lines = [
        " ".join(rng.choice(words) for _ in range(rng.randrange(0, 12)))
        for _ in range(300)
    ]
    lines += [
        "java.lang.OutOfMemoryError: Java heap space",
        "  at com.example.Service.handle(Service.java:42)",
        "goroutine 42 [running]",
        "FATAL:  too many connections",
        "Exit Code:  137",
        "segfault at deadbeef",
        "upstream connect error or disconnect",
        "node web-1 not ready",
        "liquibase update failed",
    ]
    check_exact(regexes, lines)


def test_fuzz_random_ascii():
    regexes = FEATURES
    rng = random.Random(1234)
    alphabet = (
        "abcdefgxyz XYZ0123459_().:-\t"
        "ABCDE"
    )
    lines = [
        "".join(rng.choice(alphabet) for _ in range(rng.randrange(0, 70)))
        for _ in range(400)
    ]
    check_exact(regexes, lines)


def test_sink_mode_full_width_lines():
    """Sink-mode acceptance at the scan's last byte: a line that fills
    every scanned byte (length == T, no padding inside the scan) relies
    on finish()'s virtual padding step to sweep last-byte finals into
    their sinks — both the plain and the ``$`` kind."""
    regexes = [
        ("Error$", False),
        ("Error", False),
        ("fail(ed)?$", False),
        ("x[45]\\d$", False),
    ]
    # encode_lines pads T to a multiple of 32: 32-char lines are
    # full-width rows, shorter ones see real in-scan padding
    lines = [
        "x" * 27 + "Error",          # 32 chars, Error at the very end
        "Error" + "y" * 27,          # Error mid-line, 32 chars
        "z" * 25 + "failed",         # 31 chars: one padding byte in scan
        "q" * 26 + "failed",         # 32 chars, ends at T
        "w" * 28 + "fail",           # optional group empty at line end
        "v" * 29 + "x45",            # $ after class item, full width
        "v" * 20 + "x45" + "z" * 9,  # same match mid-line: $ must miss
        "",
        "Error",
    ]
    entries = [
        (i, compile_bitprog_regex(rx, ci)) for i, (rx, ci) in enumerate(regexes)
    ]
    bank = BitGlushBank(entries)
    assert bank.use_sinks
    check_exact(regexes, lines)


def test_sink_mode_skippable_cascade_into_sink():
    """Finals that cascade back through a trailing skippable suffix all
    reach the sink via the existing closure unrolling."""
    regexes = [("ab?c?", False), ("de*", False), ("fg?$", False)]
    lines = ["za", "zab", "zabc", "zd", "zdee", "zf", "zfg", "zfgh", "q"]
    entries = [
        (i, compile_bitprog_regex(rx, ci)) for i, (rx, ci) in enumerate(regexes)
    ]
    assert BitGlushBank(entries).use_sinks
    check_exact(regexes, lines)


def test_trailing_boundary_bank_keeps_hits_path():
    """A trailing \\b final is sink-ineligible: the bank keeps the
    per-byte hit path and stays exact."""
    regexes = [("Error\\b", False), ("plain", False)]
    entries = [
        (i, compile_bitprog_regex(rx, ci)) for i, (rx, ci) in enumerate(regexes)
    ]
    bank = BitGlushBank(entries)
    assert not bank.use_sinks
    check_exact(regexes, ["Error", "Errors", "xError", "plainly", "no"])


def test_unsupported_shapes_rejected():
    for rx in [
        "(ab)+c",  # unbounded group repeat
        "a{40}",  # oversized bound
        "\\bx?y",  # assertion before optional item
        "^$",  # assertion-only
        "(a|b)(c|d)(e|f)(g|h)(i|j)(k|l)(m|n)",  # 128 alts > 64 cap
        "abc^",  # trailing anchor (legal regex, never matches)
        "x*^ab",  # mid-pattern anchor, satisfiable via empty prefix
    ]:
        with pytest.raises(BitUnsupportedError):
            compile_bitprog_regex(rx, False)


def test_boundary_rewrite_requires_consuming_next_item():
    """'\\b\\w*x?-' must NOT take the \\b\\w* drop rewrite: x? can match
    empty, leaving the non-word '-' as the first consumed byte, so the
    boundary requirement survives. The shape is rejected (it routes to
    the union tier) instead of compiling to a false-positive program;
    the consuming-next variant still compiles and stays exact."""
    with pytest.raises(BitUnsupportedError):
        compile_bitprog_regex("\\b\\w*x?-", False)
    check_exact(
        [("\\b\\w*x-", False)],
        ["a -", "a x-", "ax-", "a-", " -", "x-", "-", "yx-", " yx-"],
    )


def test_boundary_rewrite_leading_unanchored_only():
    """The \\b\\w* drop rewrite is sound only when \\b\\w* is the leading
    consuming element of an unanchored alternative — a preceding consumed
    item or a ^ pins the left edge the containment argument needs free.
    The advisor's counterexamples: '=\\b\\w*Exception' would miss
    '=FooException', 'a\\b\\w*Exception' would falsely match 'aException',
    '^\\b\\w*Exception' would miss 'FooException'. All three must be
    rejected (routing the column to an exact automaton tier); the leading
    unanchored shape still compiles and stays exact."""
    for rx in ["=\\b\\w*Exception", "a\\b\\w*Exception", "^\\b\\w*Exception"]:
        with pytest.raises(BitUnsupportedError):
            compile_bitprog_regex(rx, False)
    check_exact(
        [("\\b\\w*Exception\\b", False)],
        ["=FooException", "aException", "FooException", "threw FooException x"],
    )


def _gen_regex(rng: random.Random) -> str:
    """Random regex over (a superset of) the bit-parallel fragment."""
    def atom() -> str:
        r = rng.random()
        if r < 0.35:
            return rng.choice("abcxyz05 _-:")  # literal (incl. specials-free)
        if r < 0.5:
            return rng.choice(["[abc]", "[0-9]", "[a-cx-z]", "[^a-y]"])
        if r < 0.65:
            return rng.choice(["\\d", "\\w", "\\s", "."])
        return rng.choice(["foo", "bar:", "x0 "])  # short literal run

    def item() -> str:
        a = atom()
        r = rng.random()
        if r < 0.55:
            return a
        if r < 0.7:
            return a + "+"
        if r < 0.8:
            return a + "*"
        if r < 0.9:
            return a + "?"
        lo = rng.randrange(0, 3)
        return a + "{%d,%d}" % (lo, lo + rng.randrange(0, 3))

    def branch() -> str:
        n = rng.randrange(1, 7)
        s = "".join(item() for _ in range(n))
        if rng.random() < 0.15:
            s = "\\b" + s
        if rng.random() < 0.1:
            s = "^" + s
        if rng.random() < 0.15:
            s = s + "\\b"
        if rng.random() < 0.1:
            s = s + "$"
        return s

    return "|".join(branch() for _ in range(rng.randrange(1, 4)))


def test_generative_fuzz_vs_host_re():
    """Generate random regexes across the whole supported fragment, keep
    those the compiler accepts, and check device-vs-host exactness over
    random and adversarial lines — one shared bank so the scan compiles
    once."""
    rng = random.Random(20260730)
    regexes: list[tuple[str, bool]] = []
    attempts = 0
    while len(regexes) < 120 and attempts < 1200:
        attempts += 1
        rx = _gen_regex(rng)
        ci = rng.random() < 0.2
        try:
            compile_bitprog_regex(rx, ci)
        except BitUnsupportedError:
            continue
        try:  # the golden compiler must accept it too
            compile_java_regex(rx, ci)
        except Exception:
            continue
        regexes.append((rx, ci))
    assert len(regexes) >= 80, f"generator too restrictive: {len(regexes)}"

    alphabet = "abcxyz05 _-:AB9\t."
    lines = [
        "".join(rng.choice(alphabet) for _ in range(rng.randrange(0, 50)))
        for _ in range(250)
    ]
    lines += ["", "a", " ", "foo", "bar:", "x0 x0 x0", "foofoofoo",
              "abc05xyz", ":::", "a" * 120]
    check_exact(regexes, lines)


def test_matcher_banks_bit_tier_cube_parity():
    """MatcherBanks with the bit tier forced on (it is TPU-only by
    default) produces the identical cube to the default CPU tiering over
    the builtin library."""
    from log_parser_tpu.ops.match import MatcherBanks
    from log_parser_tpu.patterns.bank import PatternBank
    from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets

    bank = PatternBank(load_builtin_pattern_sets())
    bit = MatcherBanks(bank, bitglush_max_words=192)
    base = MatcherBanks(bank, bitglush_max_words=0)
    # long literal alternations ride Shift-Or chains (MAX_EXACT_LEN=64);
    # the bit tier keeps the ~32 genuinely non-literal columns
    assert len(bit.bitglush_cols) >= 25
    assert not base.bitglush_cols

    lines = [
        "java.lang.OutOfMemoryError: Java heap space",
        "[Full GC (Ergonomics) 255M->250M(256M), 0.41 secs]",
        "dial tcp 10.0.0.7:5432: Connection refused",
        "  at com.example.Service.handle(Service.java:42)",
        "ERROR request failed with IllegalStateException",
        "goroutine 42 [running]",
        "FATAL:  too many connections",
        "liquibase update failed",
        "node web-1 not ready",
        "2026-07-29T07:00:00Z INFO reconcile tick 1 status=ok",
        "",
    ]
    enc = encode_lines(lines)
    lt = jnp.asarray(enc.u8.T)
    ln = jnp.asarray(enc.lengths)
    np.testing.assert_array_equal(
        np.asarray(bit.cube(lt, ln))[: len(lines)],
        np.asarray(base.cube(lt, ln))[: len(lines)],
    )


def test_pallas_kernel_parity_interpret():
    """The Pallas kernel (interpreter mode — no TPU needed) produces the
    identical hit words / columns as the scan-path stepper. Small bank +
    short lines keep the interpreted loop fast."""
    from log_parser_tpu.ops.bitglush_pallas import bitglush_hits_pallas

    regexes = [
        ("OutOfMemoryError", False),
        ("Exit Code:\\s*137", False),
        ("status.*red", False),
        ("\\btimeout\\b", True),
        ("^\\s*at .*\\)$", False),
        ("colou?r|Port \\d+", False),
    ]
    entries = [
        (i, compile_bitprog_regex(rx, ci)) for i, (rx, ci) in enumerate(regexes)
    ]
    bank = BitGlushBank(entries)
    lines = [
        "java OutOfMemoryError x",
        "Exit Code: 137",
        "status went red",
        "TIMEOUT after",
        "xtimeout",
        "  at com.x(Y.java:1)",
        "color Port 80",
        "",
    ]
    enc = encode_lines(lines)
    hits = bitglush_hits_pallas(
        bank, jnp.asarray(enc.u8.T), jnp.asarray(enc.lengths), interpret=True
    )
    got = np.asarray(bank.columns_from_hits(hits))[: len(lines)]
    want = run_bank(regexes, lines)
    np.testing.assert_array_equal(got, want)


def test_pallas_engine_integration(monkeypatch):
    """LOG_PARSER_TPU_PALLAS=1 routes the bit tier through the kernel in
    MatcherBanks.cube (interpreter mode off-TPU) — including when the bit
    tier is the only populated tier — and the cube matches the default
    path."""
    from log_parser_tpu.ops.match import MatcherBanks
    from log_parser_tpu.patterns.bank import PatternBank
    from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets

    monkeypatch.setenv("LOG_PARSER_TPU_PALLAS", "1")
    bank = PatternBank(load_builtin_pattern_sets())
    pal = MatcherBanks(bank, bitglush_max_words=192)
    assert pal.bitglush_use_pallas and pal.bitglush_cols
    monkeypatch.delenv("LOG_PARSER_TPU_PALLAS")
    base = MatcherBanks(bank, bitglush_max_words=192)
    assert not base.bitglush_use_pallas

    lines = [
        "java.lang.OutOfMemoryError: Java heap space",
        "goroutine 42 [running]",
        "  at com.example.Service.handle(Service.java:42)",
        "plain INFO line",
        "",
    ]
    enc = encode_lines(lines)
    lt, ln = jnp.asarray(enc.u8.T), jnp.asarray(enc.lengths)
    np.testing.assert_array_equal(
        np.asarray(pal.cube(lt, ln))[: len(lines)],
        np.asarray(base.cube(lt, ln))[: len(lines)],
    )


def test_word_count():
    progs = [
        compile_bitprog_regex(rx, ci) for rx, ci in FEATURES
    ]
    assert BitGlushBank.count_packed_words(progs) == BitGlushBank(
        list(enumerate(progs))
    ).n_words


# ---------------------------------------------------- truncation + verify


def test_first_fit_packing_never_straddles():
    """The packing invariant the chainless shift relies on: every ≤32-bit
    allocation is placed INSIDE one word (start%32 + alloc ≤ 32), and
    >32-bit allocations start word-aligned."""
    progs = [compile_bitprog_regex(rx, ci) for rx, ci in FEATURES]
    allocs = BitGlushBank._alt_allocs(progs)
    starts, n_words = BitGlushBank._plan(allocs)
    assert len(starts) == len(allocs)
    for s, a in zip(starts, allocs):
        if a <= 32:
            assert s % 32 + a <= 32, (s, a)
        else:
            assert s % 32 == 0, (s, a)
        assert s + a <= n_words * 32


def test_chained_bank_exact_vs_host_re():
    """A bank holding a >32-position alternative (word-straddling
    allocation → has_chains → conditional carry) must stay exact —
    including co-packed short, caret, and skip programs sharing the
    bank, and matches crossing both word boundaries of the chain."""
    long_rx = "could not connect to server: Connection refused no retry"
    regexes = [
        (long_rx, False),
        ("^anchored", False),
        ("time.?out", False),
        ("x\\d+y", False),
    ]
    progs = [compile_bitprog_regex(rx, ci) for rx, ci in regexes]
    bank = BitGlushBank(list(enumerate(progs)))
    assert bank.has_chains and bank.n_words >= 3
    rng = random.Random(7)
    alphabet = "cold nt sever:Cfu Retry anhItime-outx123y "
    lines = [
        "".join(rng.choice(alphabet) for _ in range(rng.randrange(0, 70)))
        for _ in range(200)
    ] + [
        long_rx,                       # exact
        "zz " + long_rx + " tail",     # offset: chain restart mid-line
        long_rx[:-1],                  # one short: no match
        long_rx[:33] + "X" + long_rx[34:],  # broken at the word boundary
        "anchored here",               # caret at start
        "not anchored here",           # caret unmet
        "a timeout b",
        "x42y",
        "",
    ]
    check_exact(regexes, lines)


def test_bitglush_budget_holds_after_truncation():
    """The constructed bank must NEVER exceed bitglush_max_words, however
    post-admission truncation reshapes the packing (r5 code review).
    Two ways the admission-time price can go stale: dropping \\b/\\B
    post-asserts can flip the bank sink-eligible (+1 bit per alternative
    bank-wide — in engine banks the never-truncated context columns keep
    their own \\b finals, so the flip needs every remaining b-final to
    sit on truncated alternatives), and first-fit packing is non-monotone
    (a SHRUNK allocation can reshuffle the plan into more words).  The
    post-truncation re-price/shed loop in MatcherBanks is the invariant's
    single enforcement point; this pins it across budgets on a bank mixing
    exactly-one-word sequence columns with a truncated long primary."""
    from helpers import make_pattern, make_pattern_set
    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.ops.match import MatcherBanks
    from log_parser_tpu.runtime import AnalysisEngine

    # six distinct 32-position non-literal SEQUENCE-EVENT regexes:
    # non-truncatable (no cheap repair for the temporal chain), exactly
    # one word each while the bank is sink-less, two words once sinks
    # flip on (32 + 1 sink = 33 bits straddles a word boundary)
    seq_rx = [f"stage {k} failed with retcode n" + "\\d\\d\\d" for k in range(6)]
    assert all(
        len(s) - 3 * len("\\d") + 3 == 32 for s in seq_rx
    )  # 29 literal chars + 3 class positions
    long_b = "Connection is not available, request timed out after\\b"
    sets = [
        make_pattern_set(
            [
                make_pattern(
                    "plong",
                    regex=long_b,
                    confidence=0.9,
                    sequences=[(1.5, seq_rx)],
                )
            ]
        )
    ]
    engine = AnalysisEngine(sets, ScoringConfig())
    for budget in (2, 4, 8, 12):
        mb = MatcherBanks(
            engine.bank,
            bitglush_max_words=budget,
            shiftor_min_columns=10**9,
            prefilter_min_columns=10**9,
            multi_min_columns=10**9,
        )
        if mb.bitglush is not None:
            assert mb.bitglush.n_words <= budget, (budget, mb.bitglush.n_words)


def test_approx_caches_invalidate_on_matcher_swap():
    """ADVICE r4: the lazily-built approx caches are keyed on matcher
    object identity — rebuilding/replacing ``engine._matchers`` after a
    first analyze must refresh ``_approx_patterns``/``_approx_secondaries``,
    or stale (empty) repair sets would skip the host re-verification of
    truncated columns."""
    from helpers import make_pattern, make_pattern_set
    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.ops.match import MatcherBanks
    from log_parser_tpu.runtime import AnalysisEngine

    long_lit = "Connection is not available, request timed out after"
    sets = [
        make_pattern_set(
            [
                make_pattern("plong", regex=long_lit, confidence=0.9),
                make_pattern("pshort", regex="timed out", confidence=0.5),
            ]
        )
    ]
    engine = AnalysisEngine(sets, ScoringConfig())
    # default CPU-policy matchers: nothing truncated, caches built empty
    assert not engine._approx_patterns().any()
    assert engine._approx_secondaries() == []
    # swap in the TPU-style tier build that truncates the long literal
    engine._matchers = MatcherBanks(
        engine.bank,
        bitglush_max_words=192,
        shiftor_min_columns=10**9,
        prefilter_min_columns=10**9,
        multi_min_columns=10**9,
    )
    assert engine.matchers.approx_cols
    # the caches must follow the swap, not serve the stale empty sets
    assert engine._approx_patterns().any()


def test_truncated_primary_column_engine_exact():
    """End-to-end: a primary-only column whose long alternative is
    truncated on device must still produce EXACTLY the reference's
    events — the engine re-verifies flagged lines with the host regex
    and drops prefix-only false positives before scoring, frequency
    recording, and assembly."""
    from helpers import make_pattern, make_pattern_set
    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.golden.engine import GoldenAnalyzer
    from log_parser_tpu.models.pod import PodFailureData
    from log_parser_tpu.ops.match import MatcherBanks
    from log_parser_tpu.patterns.bank import PatternBank
    from log_parser_tpu.runtime import AnalysisEngine

    long_lit = "Connection is not available, request timed out after"
    sets = [
        make_pattern_set(
            [
                make_pattern("plong", regex=long_lit, confidence=0.9),
                make_pattern("pshort", regex="timed out", confidence=0.5),
            ]
        )
    ]
    logs = "\n".join(
        [
            f"{long_lit} 30000ms",        # true match (both patterns)
            "Connection is not available, request timed out",  # prefix only
            "request timed out again",     # short pattern only
            "clean line",
        ]
    )
    data = PodFailureData(logs=logs)

    engine = AnalysisEngine(sets, ScoringConfig())
    # force the TPU tier policy on the CPU test backend so the long
    # alternative actually rides (truncated) bitglush
    engine._matchers = MatcherBanks(
        engine.bank,
        bitglush_max_words=192,
        shiftor_min_columns=10**9,
        prefilter_min_columns=10**9,
        multi_min_columns=10**9,
    )
    mb = engine.matchers
    long_col = next(
        i for i, c in enumerate(engine.bank.columns) if c.regex == long_lit
    )
    assert long_col in mb.approx_cols

    got = engine.analyze(data)
    want = GoldenAnalyzer(sets, ScoringConfig()).analyze(data)
    assert [e.line_number for e in got.events] == [
        e.line_number for e in want.events
    ]
    assert [e.matched_pattern.id for e in got.events] == [
        e.matched_pattern.id for e in want.events
    ]
    for g, w in zip(got.events, want.events):
        assert abs(g.score - w.score) < 1e-9
    # the false positive line (prefix only) produced no plong event
    assert all(
        not (e.matched_pattern.id == "plong" and e.line_number == 2)
        for e in got.events
    )


def test_truncation_roles():
    """Secondary-role long columns truncate (their distances get the
    exact host repair in the engine); sequence-event-role long columns
    never truncate — they ride Shift-Or's chain path and stay exact in
    the cube."""
    from helpers import make_pattern, make_pattern_set
    from log_parser_tpu.models.pattern import (
        SecondaryPattern,
        SequenceEvent,
        SequencePattern,
    )
    from log_parser_tpu.ops.match import MatcherBanks
    from log_parser_tpu.patterns.bank import PatternBank

    sec_lit = "Back-off restarting failed container"
    seq_lit = "Liveness probe failed repeatedly for main container"
    p1 = make_pattern("p1", regex="primary thing", confidence=0.5)
    p1.secondary_patterns = [
        SecondaryPattern(regex=sec_lit, weight=0.5, proximity_window=5)
    ]
    p1.sequence_patterns = [
        SequencePattern(
            description="d",
            bonus_multiplier=0.4,
            events=[SequenceEvent(regex=seq_lit)],
        )
    ]
    p2 = make_pattern("p2", regex=sec_lit, confidence=0.5)
    bank = PatternBank([make_pattern_set([p1, p2])])
    mb = MatcherBanks(
        bank,
        bitglush_max_words=192,
        shiftor_min_columns=1,
    )
    sec_col = next(i for i, c in enumerate(bank.columns) if c.regex == sec_lit)
    seq_col = next(i for i, c in enumerate(bank.columns) if c.regex == seq_lit)
    # secondary-role long column: truncated on device, flagged approx
    assert sec_col in mb.approx_cols
    assert sec_col not in mb.shiftor_cols
    # sequence-event-role long column: exact, on the Shift-Or chain path
    assert seq_col not in mb.approx_cols
    assert seq_col in mb.shiftor_cols
    assert mb.shiftor.has_chains
    lines = [seq_lit, seq_lit[:-1], "x " + seq_lit + " y", ""]
    enc = encode_lines(lines)
    got = np.asarray(
        mb.cube(jnp.asarray(enc.u8.T), jnp.asarray(enc.lengths))
    )[: len(lines), seq_col]
    np.testing.assert_array_equal(got, [True, False, True, False])


def test_truncated_secondary_distance_repair():
    """End-to-end: a pattern whose long SECONDARY is truncated on device
    must still score exactly — the engine verifies the claimed nearest
    lines and, when both were prefix-only false positives, recovers the
    true distance (or its absence) by the bounded host scan."""
    from helpers import make_pattern, make_pattern_set
    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.golden.engine import GoldenAnalyzer
    from log_parser_tpu.models.pattern import SecondaryPattern
    from log_parser_tpu.models.pod import PodFailureData
    from log_parser_tpu.ops.match import MatcherBanks
    from log_parser_tpu.runtime import AnalysisEngine

    sec_lit = "Back-off restarting failed container"
    prefix_only = sec_lit[:31]  # matches the truncated program only
    p = make_pattern("pp", regex="primary thing", confidence=0.8)
    p.secondary_patterns = [
        SecondaryPattern(regex=sec_lit, weight=0.5, proximity_window=8)
    ]
    sets = [make_pattern_set([p])]

    def build_engine():
        e = AnalysisEngine(sets, ScoringConfig())
        e._matchers = MatcherBanks(
            e.bank,
            bitglush_max_words=192,
            shiftor_min_columns=10**9,
            prefilter_min_columns=10**9,
            multi_min_columns=10**9,
        )
        sec_col = next(
            i for i, c in enumerate(e.bank.columns) if c.regex == sec_lit
        )
        assert sec_col in e.matchers.approx_cols
        return e

    cases = [
        # (log lines, label)
        (
            [
                "primary thing here",
                prefix_only,          # false positive at distance 1
                "filler",
                sec_lit,              # true hit at distance 3
            ],
            "false-then-true",
        ),
        (
            ["x", "primary thing here", sec_lit + " tail"],
            "true-adjacent",
        ),
        (
            ["primary thing here", prefix_only, "y"],
            "false-only",
        ),
        (
            [prefix_only, "a", "primary thing here", "b", prefix_only],
            "false-both-sides",
        ),
    ]
    for lines, label in cases:
        data = PodFailureData(logs="\n".join(lines))
        got = build_engine().analyze(data)
        want = GoldenAnalyzer(sets, ScoringConfig()).analyze(data)
        assert len(got.events) == len(want.events), label
        for a, b in zip(got.events, want.events):
            assert a.line_number == b.line_number, label
            assert abs(a.score - b.score) < 1e-9, (
                label,
                a.score,
                b.score,
            )


def test_truncated_caret_alternative_stays_chainless():
    """Regression (r4 review): the truncation budget must reserve the
    caret guard bit — a ^-anchored >31-position primary-only column
    must truncate to an allocation that fits one word, keeping the
    bank chainless, and stay exact end-to-end via host re-verify."""
    from helpers import make_pattern, make_pattern_set
    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.golden.engine import GoldenAnalyzer
    from log_parser_tpu.models.pod import PodFailureData
    from log_parser_tpu.ops.match import MatcherBanks
    from log_parser_tpu.runtime import AnalysisEngine

    long_anchored = "^FATAL: unrecoverable disk failure on device"  # 43 items
    sets = [
        make_pattern_set(
            [make_pattern("pa", regex=long_anchored, confidence=0.9)]
        )
    ]
    engine = AnalysisEngine(sets, ScoringConfig())
    engine._matchers = MatcherBanks(
        engine.bank,
        bitglush_max_words=192,
        shiftor_min_columns=10**9,
        prefilter_min_columns=10**9,
        multi_min_columns=10**9,
    )
    mb = engine.matchers
    assert mb.bitglush is not None
    assert not mb.bitglush.has_chains  # the budget reserved the guard bit
    assert mb.approx_cols  # truncated -> engine verifies

    body = "FATAL: unrecoverable disk failure on device sda"
    logs = "\n".join(
        [
            body,                        # anchored true match
            "x " + body,                 # caret unmet
            body[:40],                   # prefix of the TRUNCATED region only
            "clean",
        ]
    )
    data = PodFailureData(logs=logs)
    got = engine.analyze(data)
    want = GoldenAnalyzer(sets, ScoringConfig()).analyze(data)
    assert [(e.line_number, e.matched_pattern.id) for e in got.events] == [
        (e.line_number, e.matched_pattern.id) for e in want.events
    ]
    for a, b in zip(got.events, want.events):
        assert abs(a.score - b.score) < 1e-9


def test_all_poison_corpus_zero_events():
    """Worst case for truncation: EVERY line is the 31-char prefix of a
    long primary literal. The device flags every line (K ladder may
    climb), the engine's host verify drops every record, and the result
    is exactly golden's: zero events, NONE summary, zero frequency."""
    from helpers import make_pattern, make_pattern_set
    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.golden.engine import GoldenAnalyzer
    from log_parser_tpu.models.pod import PodFailureData
    from log_parser_tpu.ops.match import MatcherBanks
    from log_parser_tpu.runtime import AnalysisEngine

    lit = "Connection is not available, request timed out after"
    sets = [make_pattern_set([make_pattern("pl", regex=lit, confidence=0.9)])]
    engine = AnalysisEngine(sets, ScoringConfig())
    engine._matchers = MatcherBanks(
        engine.bank,
        bitglush_max_words=MatcherBanks.BITGLUSH_MAX_WORDS_TPU,
        shiftor_min_columns=10**9,
        prefilter_min_columns=10**9,
        multi_min_columns=10**9,
    )
    assert engine.matchers.approx_cols
    logs = "\n".join([lit[:31]] * 5000)
    data = PodFailureData(logs=logs)
    golden = GoldenAnalyzer(sets, ScoringConfig())
    got = engine.analyze(data)
    want = golden.analyze(data)
    assert got.events == [] and want.events == []
    assert got.summary.to_dict() == want.summary.to_dict()
    assert (
        engine.frequency.get_frequency_statistics()
        == golden.frequency.get_frequency_statistics()
    )
