"""Bench harness regression tests (bench_common.py).

The platform-probe contract burned a whole TPU session once: requesting
``LOG_PARSER_TPU_PLATFORM=tpu`` pinned ``jax_platforms="tpu"``, which
fails on plugin-registered devices (the axon tunnel registers platform
"axon" whose devices *report* ``platform == "tpu"``).  The rule under
test: "tpu" is never pinned directly — auto-select, then verify the
device platform; every other explicit platform is pinned verbatim.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time

import pytest

import bench_common


@pytest.fixture(autouse=True)
def isolated_probe_cache(monkeypatch, tmp_path):
    """Every test gets its own (empty) probe-outcome cache file: a cache
    entry left by a real bench run on this host must not let
    probe_backend skip the campaign a test is asserting on."""
    monkeypatch.setattr(
        bench_common, "_PROBE_CACHE_PATH", str(tmp_path / "probe_cache.json")
    )


def _run_probe(platform: str | None) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env.pop("LOG_PARSER_TPU_PLATFORM", None)
    if platform is not None:
        env["LOG_PARSER_TPU_PLATFORM"] = platform
    # the suite's CPU pin must not leak into the probe subprocess — the
    # probe's own platform logic is exactly what is under test
    env.pop("JAX_PLATFORMS", None)
    # drop the axon plugin (it rides in via PYTHONPATH=/root/.axon_site):
    # the probe must never touch the single-session TPU tunnel from the
    # unit suite, and a plugin-free host gives the deterministic
    # auto-select-lands-on-cpu outcome both locally and in CI
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        return subprocess.run(
            [sys.executable, "-c", bench_common._PROBE_SRC],
            capture_output=True,
            text=True,
            timeout=60,
            env=env,
        )
    except subprocess.TimeoutExpired:
        # plugin-free auto-select can block inside libtpu when another
        # process holds the (single-session) TPU device — the probe's
        # outcome is unobservable on such a host, and the production
        # path has its own abandon-on-timeout handling
        # (test_probe_timeout_abandons_never_kills)
        pytest.skip("device auto-select blocked (TPU held elsewhere); "
                    "probe outcome unobservable on this host")


def test_probe_src_explicit_cpu():
    r = _run_probe("cpu")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "PROBE_OK cpu" in r.stdout


def test_probe_src_tpu_does_not_pin_literally():
    """With no TPU plugin on the path, requesting "tpu" must FAIL by
    platform verification after auto-select (exit != 0, our SystemExit
    message), never by pinning ``jax_platforms="tpu"`` (whose "Unable to
    initialize backend" error is what masked a live tunneled chip)."""
    r = _run_probe("tpu")
    assert r.returncode != 0
    assert "auto-select landed on" in (r.stderr + r.stdout)
    assert "Unable to initialize backend" not in r.stderr


def test_pin_platform_tpu_never_pins_and_verifies(monkeypatch):
    """pin_platform("tpu") must not touch jax_platforms; it re-checks the
    device platform in-process. The suite runs CPU-pinned, so the check
    must refuse (the mislabeled-artifact guard) while leaving the config
    untouched."""
    import jax

    monkeypatch.setenv("LOG_PARSER_TPU_PLATFORM", "tpu")
    before = jax.config.jax_platforms
    try:
        bench_common.pin_platform()
    except RuntimeError as exc:
        assert "mislabeled" in str(exc)
    else:  # pragma: no cover - only on a real TPU host without the pin
        assert jax.devices()[0].platform == "tpu"
    assert jax.config.jax_platforms == before


def test_last_fell_back_set_on_floor_fallback(monkeypatch):
    """The fallback-floor signal is the explicit flag, not diagnostics
    truthiness — bench.py's short-dwell policy keys on it."""
    # pin_platform writes os.environ directly on the fallback path;
    # delenv of an ABSENT key registers no undo in pytest, so the
    # setenv-then-delenv pair records state to restore — otherwise the
    # var leaks into every later test and subprocess
    monkeypatch.setenv("LOG_PARSER_TPU_PLATFORM", "")
    monkeypatch.delenv("LOG_PARSER_TPU_PLATFORM")
    monkeypatch.setattr(bench_common, "PROBE_TIMEOUT_S", 2.0)
    # small but NONZERO pause: a 0.0 pause turns the retry loop into a
    # hot loop (~13k no-op attempts/second into the diagnostics list)
    monkeypatch.setattr(bench_common, "_RETRY_PAUSE_S", 0.2)
    monkeypatch.setattr(
        bench_common,
        "_one_attempt",
        lambda timeout_s: (None, {"outcome": "error", "rc": 1}),
    )
    assert bench_common.probe_backend("m", "u") == "cpu"
    assert bench_common.last_fell_back is True
    assert bench_common.last_probe_diagnostics  # embedded in the artifact


def test_last_fell_back_cleared_on_success(monkeypatch):
    monkeypatch.setenv("LOG_PARSER_TPU_PLATFORM", "cpu")
    bench_common.last_fell_back = True  # stale state from a prior call
    assert bench_common.probe_backend("m", "u") == "cpu"
    assert bench_common.last_fell_back is False
    assert bench_common.last_probe_diagnostics == []


def test_run_campaign_measures_levels():
    curve, err = bench_common.run_campaign(
        lambda: time.sleep(0.001), n_lines=100, campaign_s=0.2, levels=(2, 1)
    )
    assert err is None
    assert [p["concurrency"] for p in curve] == [1, 2]  # sorted output
    assert all(p["requests"] > 0 and p["lines_per_sec"] > 0 for p in curve)


def test_run_campaign_degrades_on_error():
    """A failing level is recorded and ends the campaign instead of
    destroying it (the pre-round-4 behavior was raise-on-first-error)."""

    def analyze():
        raise ValueError("backend died")

    curve, err = bench_common.run_campaign(analyze, 100, campaign_s=0.2, levels=(2, 1))
    assert err is not None and err.startswith("concurrency 2:")
    assert "backend died" in err
    assert [p["concurrency"] for p in curve] == [2]
    assert "backend died" in curve[0]["error"]
    assert len(curve[0]["error"]) <= 300


def test_run_campaign_detects_wedged_level(monkeypatch):
    """Requests that never return must trip the bounded drain and degrade
    the level, not hang the bench forever."""
    monkeypatch.setattr(bench_common, "DRAIN_FLOOR_S", 0.3)
    release = threading.Event()
    try:
        curve, err = bench_common.run_campaign(
            release.wait, 100, campaign_s=0.1, levels=(1, 2)
        )
        assert err is not None and "wedged" in err
        assert curve[0]["concurrency"] == 1 and "wedged" in curve[0]["error"]
        assert len(curve) == 1  # nothing after the wedged level ran
    finally:
        release.set()  # let the leaked daemon client threads exit


def test_run_bounded_returns_results_in_order():
    out = bench_common.run_bounded(
        [lambda: 1, lambda: 2, lambda: 3], 10.0, "m", "u", "p", "phase"
    )
    assert out == [1, 2, 3]


def test_run_bounded_reraises_worker_error():
    def boom():
        raise ValueError("backend died")

    with pytest.raises(ValueError, match="backend died"):
        bench_common.run_bounded([boom], 10.0, "m", "u", "p", "phase")


def test_run_bounded_wedge_exits_with_null_artifact(capsys):
    """A worker that never returns must produce the exit-3 diagnostics
    line, never an unbounded hang — the harness contract every bench
    (latency, mesh) now rides on."""
    release = threading.Event()
    try:
        with pytest.raises(SystemExit) as exc_info:
            bench_common.run_bounded([release.wait], 0.2, "m", "u", "p", "phase")
        assert exc_info.value.code == 3
        out = capsys.readouterr().out
        assert '"value": null' in out
        assert "wedged" in out
    finally:
        release.set()


def test_bench_mesh_smoke():
    """bench_mesh end-to-end at tiny shapes on a 2-device virtual mesh.
    The suite env carries an 8-device XLA_FLAGS count from conftest, so
    this also exercises the stale-flag replacement (--devices must win).
    """
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo  # hermetic: drops any device plugin
    # a stall anywhere must surface as the bench's own diagnostics exit,
    # not a bare TimeoutExpired: the subprocess kill must exceed the SUM
    # of the worst-case stage budgets. With PROBE_TIMEOUT_S=60: device
    # init <= 60, warmup <= 60, measure <= measure_budget(60) =
    # 3*max(60, 300) = 900 -> total <= 1020 (+ script overhead); the
    # healthy path finishes in ~30s
    env["LOG_PARSER_TPU_PROBE_TIMEOUT"] = "60"
    r = subprocess.run(
        [sys.executable, "bench_mesh.py", "--devices", "2", "--lines", "200"],
        capture_output=True,
        text=True,
        timeout=1100,
        cwd=repo,
        env=env,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    import json

    doc = json.loads(r.stdout.strip().splitlines()[-1])
    assert doc["platform"] == "cpu-virtual-mesh2"
    assert doc["n_devices"] == 2 and doc["value"] > 0 and doc["n_events"] > 0
    # OBSERVED device count, not an echo of --devices: proves the
    # stale 8-device flag from conftest was actually replaced
    assert doc["visible_devices"] == 2


def test_probe_timeout_abandons_never_kills(monkeypatch, tmp_path):
    """The round-4 relay wedge rule, code-enforced: an attempt window that
    expires must leave the probe RUNNING (abandoned, never signaled), and
    the next attempt must resume polling the SAME process instead of
    spawning a second one against the single-session relay."""
    release = tmp_path / "release"
    src = (
        "import os, time\n"
        f"while not os.path.exists({str(release)!r}):\n"
        "    time.sleep(0.05)\n"
        "print('PROBE_OK cpu 1', flush=True)\n"
    )
    monkeypatch.setattr(bench_common, "_PROBE_SRC", src)
    monkeypatch.setattr(bench_common, "_live_probe", None)
    monkeypatch.setattr(
        bench_common, "_PROBE_STATE_PATH", str(tmp_path / "state.json")
    )
    try:
        p, diag = bench_common._one_attempt(0.5)
        assert p is None
        assert diag["outcome"] == "timeout" and diag["abandoned_running"]
        proc = bench_common._live_probe["proc"]
        assert proc.poll() is None  # alive: abandoned, not killed
        pid1 = proc.pid
        p2, diag2 = bench_common._one_attempt(0.3)
        assert p2 is None and diag2["outcome"] == "timeout"
        assert bench_common._live_probe["proc"].pid == pid1  # resumed
        release.touch()
        p3, diag3 = bench_common._one_attempt(15.0)
        assert p3 == "cpu" and diag3["outcome"] == "ok"
        assert bench_common._live_probe is None  # slot cleared on exit
    finally:
        lp = bench_common._live_probe
        if lp is not None:  # only on assertion failure above
            release.touch()
            lp["proc"].wait(15)
            bench_common._live_probe = None


def test_probe_orphan_adopted_not_doubled(monkeypatch, tmp_path):
    """A probe abandoned by a PREVIOUS bench process (handoff record left
    on disk) must be ADOPTED — polled to completion via /proc — instead
    of a second probe being spawned against the single-session relay
    (two concurrent clients is the round-4 wedge condition)."""
    import json

    release = tmp_path / "release"
    out, err = tmp_path / "probe.out", tmp_path / "probe.err"
    src = (
        "import os, time\n"
        f"while not os.path.exists({str(release)!r}):\n"
        "    time.sleep(0.05)\n"
        "print('PROBE_OK cpu 1', flush=True)\n"
    )
    with open(out, "w") as fo, open(err, "w") as fe:
        proc = subprocess.Popen(
            [sys.executable, "-c", src],
            stdout=fo,
            stderr=fe,
            start_new_session=True,
        )
    state = tmp_path / "state.json"
    state.write_text(
        json.dumps({"pid": proc.pid, "out": str(out), "err": str(err)})
    )
    monkeypatch.setattr(bench_common, "_PROBE_STATE_PATH", str(state))
    monkeypatch.setattr(bench_common, "_live_probe", None)
    # any spawn would be a double-up: make it unmistakable in the diag
    monkeypatch.setattr(bench_common, "_PROBE_SRC", "raise SystemExit(99)")
    try:
        p, diag = bench_common._one_attempt(0.4)
        assert p is None and diag["outcome"] == "timeout"
        assert bench_common._live_probe["pid"] == proc.pid  # adopted
        assert bench_common._live_probe["proc"] is None
        release.touch()
        p2, diag2 = bench_common._one_attempt(15.0)
        assert p2 == "cpu" and diag2["outcome"] == "ok"
        assert diag2.get("adopted_orphan") is True
        assert not state.exists()  # handoff record cleared at completion
    finally:
        release.touch()
        proc.wait(15)
        bench_common._live_probe = None


def test_probe_dead_orphan_discarded(monkeypatch, tmp_path):
    """A DEAD orphan's result is stale (its bench already fell back);
    the record and its probe-output files are discarded, not trusted —
    but only paths that LOOK like our probe files are unlinked (the
    record sits in a world-writable tempdir; a forged record must not
    turn the cleaner into arbitrary file deletion)."""
    import json
    import tempfile

    fd_out, out = tempfile.mkstemp(prefix="lpt_probe_", suffix=".out")
    fd_err, err = tempfile.mkstemp(prefix="lpt_probe_", suffix=".err")
    with os.fdopen(fd_out, "w") as f:
        f.write("PROBE_OK cpu 1")  # stale success from a prior bench
    os.close(fd_err)
    victim = tmp_path / "victim.txt"  # forged-path target
    victim.write_text("do not delete")
    proc = subprocess.Popen([sys.executable, "-c", "pass"])
    proc.wait(15)
    state = tmp_path / "state.json"
    state.write_text(
        json.dumps({"pid": proc.pid, "out": out, "err": str(victim)})
    )
    monkeypatch.setattr(bench_common, "_PROBE_STATE_PATH", str(state))
    assert bench_common._adopt_orphan() is None
    assert not state.exists() and not os.path.exists(out)
    assert victim.exists()  # forged path survived
    os.unlink(err)


def test_emit_includes_relay_health(monkeypatch, capsys):
    import json

    monkeypatch.setattr(
        bench_common, "last_relay_health", {"tiny_dispatch_ms_p50": 1.2}
    )
    monkeypatch.setattr(bench_common, "last_probe_diagnostics", [])
    bench_common.emit("m", 1.0, "u", None, "tpu")
    doc = json.loads(capsys.readouterr().out)
    assert doc["relay_health"] == {"tiny_dispatch_ms_p50": 1.2}


def test_emit_omits_relay_health_when_unset(monkeypatch, capsys):
    import json

    monkeypatch.setattr(bench_common, "last_relay_health", None)
    monkeypatch.setattr(bench_common, "last_probe_diagnostics", [])
    bench_common.emit("m", 1.0, "u", None, "cpu")
    assert "relay_health" not in json.loads(capsys.readouterr().out)


def test_emit_stamps_host_load(monkeypatch, capsys):
    import json

    monkeypatch.setattr(bench_common, "last_relay_health", None)
    monkeypatch.setattr(bench_common, "last_probe_diagnostics", [])
    bench_common.emit("m", 1.0, "u", None, "cpu")
    doc = json.loads(capsys.readouterr().out)
    # bench honesty: every artifact records what else the box was doing
    load = doc["host_load"]
    assert len(load["loadavg"]) == 3
    assert all(x >= 0 for x in load["loadavg"])
    assert load["cpus"] == os.cpu_count()


def test_bench_diff_marks_unequal_load_advisory(tmp_path):
    import json

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    try:
        import bench_diff
    finally:
        sys.path.pop(0)
    busy = {"metric": "lines_per_sec", "value": 100.0,
            "host_load": {"loadavg": [12.0, 10.0, 8.0], "cpus": 8}}
    quiet = {"metric": "lines_per_sec", "value": 50.0,
             "host_load": {"loadavg": [0.2, 0.2, 0.2], "cpus": 8}}
    adv = bench_diff.load_advisory(busy, quiet)
    assert adv is not None and adv["ratio"] > 2.0
    # comparable load (or a pre-stamp artifact) stays trustworthy
    assert bench_diff.load_advisory(quiet, dict(quiet)) is None
    assert bench_diff.load_advisory({}, quiet) is None
    # end-to-end: --strict must NOT fail a 2x "regression" measured on
    # a loaded box, and the JSON summary carries the advisory
    a, b = tmp_path / "old.json", tmp_path / "new.json"
    a.write_text(json.dumps(busy))
    b.write_text(json.dumps(quiet))
    assert bench_diff.main([str(a), str(b), "--strict"]) == 0


def test_stamp_relay_health_timeout_records_error(monkeypatch):
    """A wedged tiny-dispatch must degrade to an error field, never hang
    or fail the bench — the bench's own bounded phases own wedge exits."""
    monkeypatch.setattr(
        bench_common, "_measure_relay_health", lambda: time.sleep(30)
    )
    bench_common._stamp_relay_health(budget_s=0.2)
    assert "error" in bench_common.last_relay_health
    bench_common.last_relay_health = None


def test_pin_platform_cpu_pins(monkeypatch):
    import jax

    monkeypatch.setenv("LOG_PARSER_TPU_PLATFORM", "cpu")
    before = jax.config.jax_platforms
    try:
        bench_common.pin_platform()
        assert jax.config.jax_platforms == "cpu"
    finally:
        jax.config.update("jax_platforms", before)


def _probe_success_env(monkeypatch):
    """A probe_backend call whose campaign and pin are both stubbed to
    instant success on "cpu" — the cache tests exercise the control
    flow, not the subprocess dial."""
    monkeypatch.setenv("LOG_PARSER_TPU_PLATFORM", "cpu")
    monkeypatch.setattr(
        bench_common,
        "_one_attempt",
        lambda timeout_s: ("cpu", {"outcome": "ok"}),
    )
    monkeypatch.setattr(
        bench_common, "_pin_and_verify", lambda platform, timeout_s: None
    )
    monkeypatch.setattr(bench_common, "_device_platform", lambda: "cpu")


def test_probe_cache_hit_skips_campaign(monkeypatch):
    _probe_success_env(monkeypatch)
    assert bench_common.probe_backend("m", "u") == "cpu"
    assert bench_common.last_probe_cached is False
    assert bench_common.last_backend == "cpu"

    def boom(timeout_s):
        raise AssertionError("campaign must not re-dial on a cache hit")

    monkeypatch.setattr(bench_common, "_one_attempt", boom)
    assert bench_common.probe_backend("m", "u") == "cpu"
    assert bench_common.last_probe_cached is True
    assert bench_common.last_backend == "cpu"


def test_probe_cache_hit_still_verifies_in_process(monkeypatch):
    """The cache skips only the subprocess campaign — a pin failure on
    the cached platform invalidates the entry and re-runs the full
    campaign (the mislabel guard is never skippable)."""
    _probe_success_env(monkeypatch)
    assert bench_common.probe_backend("m", "u") == "cpu"

    pins: list[str] = []

    def pin(platform, timeout_s):
        pins.append(platform)
        if len(pins) == 1:
            raise RuntimeError("tunnel died since the cached probe")

    dialed: list[int] = []

    def attempt(timeout_s):
        dialed.append(1)
        return "cpu", {"outcome": "ok"}

    monkeypatch.setattr(bench_common, "_pin_and_verify", pin)
    monkeypatch.setattr(bench_common, "_one_attempt", attempt)
    assert bench_common.probe_backend("m", "u") == "cpu"
    assert dialed, "stale cache entry must re-run the campaign"
    assert bench_common.last_probe_cached is False
    assert not os.path.exists(bench_common._PROBE_CACHE_PATH) or (
        bench_common._probe_cache_load("cpu") == "cpu"
    )


def test_probe_cache_ttl_bounds_staleness(monkeypatch):
    bench_common._probe_cache_store("cpu", "cpu")
    assert bench_common._probe_cache_load("cpu") == "cpu"
    assert bench_common._probe_cache_load("auto") is None  # key mismatch
    monkeypatch.setattr(bench_common, "PROBE_CACHE_TTL_S", 0.0)
    assert bench_common._probe_cache_load("cpu") is None  # disabled
    monkeypatch.setattr(bench_common, "PROBE_CACHE_TTL_S", 1e-9)
    time.sleep(0.01)
    assert bench_common._probe_cache_load("cpu") is None  # expired


def test_emit_stamps_backend(monkeypatch, capsys):
    import json

    monkeypatch.setattr(bench_common, "last_backend", "cpu")
    monkeypatch.setattr(bench_common, "last_probe_cached", True)
    monkeypatch.setattr(bench_common, "last_relay_health", None)
    monkeypatch.setattr(bench_common, "last_probe_diagnostics", [])
    bench_common.emit("m", 1.0, "u", None, "cpu")
    doc = json.loads(capsys.readouterr().out)
    assert doc["backend"] == "cpu"
    assert doc["probe_cached"] is True

    monkeypatch.setattr(bench_common, "last_backend", None)
    bench_common.emit("m", 1.0, "u", None, "cpu")
    assert "backend" not in json.loads(capsys.readouterr().out)
