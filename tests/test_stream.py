"""Streaming follow-mode (runtime/stream.py): the replay theorem and its
reliability wiring.

The correctness anchor is chunked-vs-oneshot bit parity — feeding a blob
in N chunks of ANY split must close with final scores bit-identical to
one-shot ``analyze()`` on the concatenated blob, across batched/unbatched
engines and line cache on/off. Around it: the carried-scan-state tiers
(``CubeHostCarry``) pinned bit-identical to ``MatcherBanks.cube`` per
prefix, the monotone-refinement frame contract (emit, then explicit
``revised`` — never a silent retraction), frequency serial-equivalence
under 8 concurrent sessions, TTL reaping through the shared admission
gate, hot-reload re-basing, the chunk-boundary UTF-8 normalizer
(native/ingest.py ``StreamNormalizer``), and the gRPC twin transport.
"""

from __future__ import annotations

import json
import random
import threading

import numpy as np
import pytest

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.models.pod import PodFailureData
from log_parser_tpu.native.ingest import StreamNormalizer
from log_parser_tpu.ops.encode import encode_lines
from log_parser_tpu.ops.match import MatcherBanks
from log_parser_tpu.patterns.bank import PatternBank
from log_parser_tpu.runtime import AnalysisEngine
from log_parser_tpu.runtime.reload import VALIDATION_LOGS
from log_parser_tpu.runtime.stream import FRAME_TYPES, StreamManager
from log_parser_tpu.serve.admission import shared_gate

from conftest import FakeClock
from helpers import make_pattern, make_pattern_set


def _sets():
    return [
        make_pattern_set(
            [
                make_pattern(
                    "oom", regex="OutOfMemoryError", confidence=0.8,
                    severity="HIGH",
                    secondaries=[("GC overhead", 0.6, 10)], context=(1, 1),
                ),
                make_pattern(
                    "crash", regex="CrashLoopBackOff", confidence=0.7,
                    severity="MEDIUM",
                ),
                make_pattern(
                    "refused", regex="connection refused", confidence=0.6,
                    severity="LOW",
                ),
            ]
        )
    ]


def _engine() -> AnalysisEngine:
    return AnalysisEngine(_sets(), ScoringConfig(), clock=FakeClock())


def _events(result_dict: dict) -> list[tuple]:
    return [
        (e["lineNumber"], e["matchedPattern"]["id"], e["score"])
        for e in result_dict.get("events", [])
    ]


def _oneshot(engine: AnalysisEngine, blob: str, batched: bool) -> list[tuple]:
    data = PodFailureData(logs=blob)
    result = engine.analyze_batched(data) if batched else engine.analyze(data)
    return _events(result.to_dict(drop_none=True))


def _splits(rng: random.Random, data: bytes) -> list[bytes]:
    cuts = sorted(
        rng.randrange(len(data) + 1) for _ in range(rng.randrange(0, 9))
    )
    bounds = [0, *cuts, len(data)]
    return [data[a:b] for a, b in zip(bounds, bounds[1:])]


def _stream(mgr: StreamManager, chunks: list[bytes]) -> list[dict]:
    sess = mgr.open()
    frames: list[dict] = []
    for c in chunks:
        frames += sess.feed(c)
        assert not sess.closed, frames[-1]
    frames += sess.close()
    assert sess.closed
    return frames


def _final_of(frames: list[dict]) -> dict:
    assert all(f["type"] in FRAME_TYPES for f in frames)
    finals = [f for f in frames if f["type"] == "final"]
    assert len(finals) == 1 and frames[-1] is finals[0], [
        f["type"] for f in frames
    ]
    return finals[0]


# ------------------------------------------------------- replay theorem


@pytest.mark.parametrize("cache", [False, True], ids=["nocache", "cache"])
@pytest.mark.parametrize("batched", [False, True], ids=["unbatched", "batched"])
def test_replay_theorem_randomized_splits(cache, batched):
    """Any split of VALIDATION_LOGS (and a repeat of it — carried
    frequency state) closes bit-identical to one-shot analyze() on the
    reassembled blob, with the two engines' frequency trackers staying
    serially equivalent request-for-request."""
    engine, ref = _engine(), _engine()
    for e in (engine, ref):
        if cache:
            e.enable_line_cache(8)
        if batched:
            e.enable_batching(wait_ms=1.0)
    try:
        mgr = StreamManager(engine, ttl_s=0, start_reaper=False)
        rng = random.Random(901)
        blob = VALIDATION_LOGS
        for round_no in range(3):  # repeats: cache hits + frequency history
            frames = _stream(mgr, _splits(rng, blob.encode()))
            got = _events(_final_of(frames)["result"])
            want = _oneshot(ref, blob, batched)
            assert got == want, f"round {round_no}: {got} != {want}"
            assert json.dumps(
                engine.frequency.get_frequency_statistics(), sort_keys=True
            ) == json.dumps(
                ref.frequency.get_frequency_statistics(), sort_keys=True
            )
        assert shared_gate(engine).stats()["inflight"] == 0
    finally:
        for e in (engine, ref):
            if e.batcher is not None:
                e.batcher.close()


def test_replay_theorem_hostile_bytes():
    """Splits that land inside multi-byte UTF-8 sequences, on CRLF
    boundaries, and inside invalid bytes still close identical to the
    blob path (errors="replace" end to end)."""
    engine, ref = _engine(), _engine()
    mgr = StreamManager(engine, ttl_s=0, start_reaper=False)
    blob_bytes = (
        "café OutOfMemoryError 你好\r\n".encode()
        + b"\xff\xfe connection refused\n"
        + "tail CrashLoopBackOff \U0001f600".encode()[:-2]  # truncated emoji
    )
    blob = blob_bytes.decode("utf-8", errors="replace")
    rng = random.Random(77)
    for _ in range(3):
        frames = _stream(mgr, _splits(rng, blob_bytes))
        assert _events(_final_of(frames)["result"]) == _oneshot(ref, blob, False)
    # byte-at-a-time is the worst split of all
    frames = _stream(mgr, [bytes([b]) for b in blob_bytes])
    assert _events(_final_of(frames)["result"]) == _oneshot(ref, blob, False)


# ------------------------------------------------- carry == cube parity


@pytest.fixture
def multi_engaged(monkeypatch):
    """Force the multi tier on hosts without the native library: the
    MatcherBanks gate sees a library while the union builder takes the
    Python construction (tests/test_matchdfa_pallas.py idiom)."""
    import log_parser_tpu.native as native
    import log_parser_tpu.native.dfabuild as dfabuild

    monkeypatch.setattr(native, "get_lib", lambda: object())
    monkeypatch.setattr(dfabuild, "get_lib", lambda: None)


_CARRY_REGEXES = [
    "OutOfMemoryError",
    "exit code 137|Exit Code:\\s*137",
    "segfault at [0-9a-f]+|Segmentation fault",
    "a{2,4}b",
    "status.*red",
    "^start",
    "foo$",
]

_CARRY_LINES = [
    "",
    "java.lang.OutOfMemoryError: heap",
    "Exit Code:   137",
    "segfault at deadbeef",
    "aaaab",
    "status is red",
    "start here",
    "restart",
    "foox",
    "xfoo",
    "status red herring status is red",
]


def _carry_bank() -> PatternBank:
    patterns = [
        make_pattern(f"p{j}", regex=rx, confidence=0.5, severity="LOW")
        for j, rx in enumerate(_CARRY_REGEXES)
    ]
    return PatternBank([make_pattern_set(patterns)])


_TIER_KW = {
    "dense": dict(
        shiftor_min_columns=10**9, prefilter_min_columns=10**9,
        multi_min_columns=10**9, bitglush_max_words=0,
    ),
    "shiftor": dict(
        shiftor_min_columns=1, prefilter_min_columns=10**9,
        multi_min_columns=10**9, bitglush_max_words=0,
    ),
    "multi": dict(
        shiftor_min_columns=10**9, prefilter_min_columns=10**9,
        multi_min_columns=2, bitglush_max_words=0,
    ),
}


@pytest.mark.parametrize("tier", ["dense", "shiftor", "multi"])
def test_carry_snapshot_matches_cube(tier, multi_engaged):
    """CubeHostCarry fed any split of a line reports the same cube row
    as the device scan — per PREFIX, not just at end of line: this is
    the resumability property the streaming tail rides on."""
    banks = MatcherBanks(_carry_bank(), **_TIER_KW[tier])
    if tier == "multi":
        assert banks.multi_groups, "multi tier must engage"
    if tier == "shiftor":
        assert banks.shiftor is not None, "shiftor tier must engage"
    carry = banks.host_carry()
    assert carry is not None

    import jax.numpy as jnp

    rng = random.Random(5)
    for line in _CARRY_LINES:
        data = line.encode()
        prefixes = [data[:i] for i in range(len(data) + 1)]
        enc = encode_lines([p.decode() for p in prefixes], 4096, 128, 8)
        rows = np.asarray(
            banks.cube(jnp.asarray(enc.u8.T), jnp.asarray(enc.lengths))
        )[: len(prefixes)]
        for _ in range(3):
            carry.reset()
            fed = 0
            np.testing.assert_array_equal(
                carry.snapshot_bits(), rows[0], err_msg=f"{line!r} empty"
            )
            while fed < len(data):
                step = rng.randrange(1, len(data) - fed + 1)
                carry.feed(data[fed : fed + step])
                fed += step
                np.testing.assert_array_equal(
                    carry.snapshot_bits(), rows[fed],
                    err_msg=f"{line!r} prefix {fed} ({tier})",
                )


# ------------------------------------------- monotone-refinement frames


def test_monotone_refinement_contract():
    """Every score an event ever shows is announced: the first report is
    an ``emit`` at/above the threshold, every change afterwards is a
    ``revised`` frame whose previousScore chains exactly, retractions
    are explicit, and the ledger's end state equals the final result —
    a silent retraction or jump is impossible by construction."""
    engine = _engine()
    threshold = 0.3
    mgr = StreamManager(
        engine, emit_threshold=threshold, ttl_s=0, start_reaper=False
    )
    sess = mgr.open()
    frames: list[dict] = []
    for piece in [
        b"INFO boot\n",
        b"java.lang.OutOfMemoryError: heap\n",
        b"INFO filler\n",
        b"GC overhead limit exceeded\n",  # secondary: firms up the oom score
        b"connection refused\n",
    ]:
        frames += sess.feed(piece)
    frames += sess.close()
    final = _final_of(frames)

    trail: dict[tuple, float | None] = {}
    for f in frames:
        if f["type"] == "emit":
            key = (f["line"], f["patternId"])
            assert key not in trail, f"re-emit of {key}"
            assert f["score"] >= threshold, f
            trail[key] = f["score"]
        elif f["type"] == "revised":
            key = (f["line"], f["patternId"])
            assert key in trail, f"revision of never-emitted {key}"
            assert f["previousScore"] == trail[key], f
            if f["score"] is None or f["score"] < threshold:
                assert f["retracted"] is True, f
            trail[key] = f["score"]
    # seq numbers are strictly increasing: frame order is reconstructable
    seqs = [f["seq"] for f in frames]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)

    # the ledger's last word per event == the final result, exactly
    final_scores = {
        (e["lineNumber"], e["matchedPattern"]["id"]): e["score"]
        for e in final["result"].get("events", [])
        if e["score"] >= threshold
    }
    live = {
        k: v for k, v in trail.items() if v is not None and v >= threshold
    }
    assert live == final_scores
    # the proximity secondary landed after the emit: a revision happened
    assert any(
        f["type"] == "revised" and f["patternId"] == "oom" for f in frames
    ), [f["type"] for f in frames]


# ------------------------------------- concurrent frequency equivalence


def test_eight_concurrent_sessions_frequency_serial_equivalence():
    """8 sessions feeding interleaved chunks on ONE engine: each final
    matches a serial replay of the same blobs in close order on a fresh
    engine, and the shared frequency tracker ends in exactly the serial
    replay's state — streamed sessions commit once, at close, in their
    close order."""
    engine = _engine()
    mgr = StreamManager(engine, ttl_s=0, start_reaper=False)
    blobs = [
        (
            f"INFO pod-{i} boot\n"
            + ("java.lang.OutOfMemoryError: heap\n" * (1 + i % 3))
            + ("connection refused\n" if i % 2 else "CrashLoopBackOff\n")
            + f"INFO pod-{i} done\n"
        )
        for i in range(8)
    ]
    order: list[int] = []
    results: dict[int, list[tuple]] = {}
    errors: list[BaseException] = []
    close_lock = threading.Lock()  # close order == frequency commit order

    def run(i: int) -> None:
        try:
            rng = random.Random(1000 + i)
            sess = mgr.open()
            frames: list[dict] = []
            for c in _splits(rng, blobs[i].encode()):
                frames += sess.feed(c)
            with close_lock:
                frames += sess.close()
                order.append(i)
            results[i] = _events(_final_of(frames)["result"])
        except BaseException as exc:  # surface into the main thread
            errors.append(exc)

    threads = [threading.Thread(target=run, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    assert not errors, errors
    assert sorted(order) == list(range(8))

    ref = _engine()
    for i in order:
        want = _oneshot(ref, blobs[i], False)
        assert results[i] == want, f"session {i} (closed #{order.index(i)})"
    assert json.dumps(
        engine.frequency.get_frequency_statistics(), sort_keys=True
    ) == json.dumps(ref.frequency.get_frequency_statistics(), sort_keys=True)
    assert shared_gate(engine).stats()["inflight"] == 0
    assert mgr.stats()["openSessions"] == 0


# ------------------------------------------------- reliability wiring


def test_ttl_reap_releases_admission_slot():
    engine = _engine()
    clk = FakeClock()
    mgr = StreamManager(engine, ttl_s=30.0, clock=clk, start_reaper=False)
    sess = mgr.open()
    sess.feed(b"INFO dangling tail with no newline")
    assert shared_gate(engine).stats()["inflight"] == 1
    clk.advance(29.0)
    assert mgr.reap_now() == 0  # not stale yet
    clk.advance(2.0)
    assert mgr.reap_now() == 1
    assert sess.closed and sess.kill_reason == "ttl"
    assert shared_gate(engine).stats()["inflight"] == 0
    frames = sess.feed(b"more")  # dead sessions answer with an error frame
    assert frames[-1]["type"] == "error" and frames[-1]["reason"] == "ttl"
    st = mgr.stats()
    assert st["sessionsReaped"] == 1 and st["openSessions"] == 0


def test_hot_reload_rebases_open_session():
    """apply_library landing between chunks: the session re-bases onto
    the swapped banks on its next feed and still closes with a final
    identical to one-shot analyze on the post-swap engine."""
    engine = _engine()
    mgr = StreamManager(engine, ttl_s=0, start_reaper=False)
    sess = mgr.open()
    sess.feed(b"java.lang.OutOfMemoryError: heap\n")
    engine.apply_library(_engine())
    sess.feed(b"connection refused\n")
    frames = sess.close()
    got = _events(_final_of(frames)["result"])
    assert mgr.stats()["sessionsRebased"] == 1
    ref = _engine()
    want = _oneshot(
        ref, "java.lang.OutOfMemoryError: heap\nconnection refused\n", False
    )
    assert got == want


def test_manager_stats_keys_are_stable():
    """The /trace/last ``stream`` block contract (docs/OPS.md table)."""
    mgr = StreamManager(_engine(), ttl_s=0, start_reaper=False)
    assert sorted(mgr.stats()) == sorted(
        [
            "openSessions", "sessionsOpened", "sessionsClosed",
            "sessionsKilled", "sessionsReaped", "sessionsRebased",
            "sessionsMigrated", "sessionsAdopted",
            "chunksIngested", "bytesIngested", "framesEmitted",
            "framesRevised", "goldenContinuations", "poisonKills",
        ]
    )


# ------------------------------------------- chunk-boundary normalizer


def test_normalizer_split_invariance():
    """Decoding chunk-by-chunk through StreamNormalizer equals decoding
    the joined blob, for ANY split point — including splits inside
    multi-byte sequences and inside invalid bytes."""
    rng = random.Random(11)
    samples = [
        "café über 你好 \U0001f600\nplain\n".encode(),
        b"\xff\xfe broken \xc3( mid\n",
        "tail€".encode()[:-1],  # truncated trailing multi-byte
        bytes(range(1, 256)),
        b"",
    ]
    for data in samples:
        want = data.decode("utf-8", errors="replace")
        for _ in range(25):
            chunks = _splits(rng, data)
            norm = StreamNormalizer()
            got = "".join(norm.feed(c) for c in chunks) + norm.flush()
            assert got == want, (data, chunks)


def test_normalizer_holds_dangling_prefix():
    """The dangling half of a split sequence is HELD, not replaced — the
    naive per-chunk decode would emit two U+FFFD here instead of the
    blob path's single character."""
    euro = "€".encode()  # 3 bytes
    norm = StreamNormalizer()
    assert norm.feed(b"x" + euro[:1]) == "x"
    assert norm.feed(euro[1:]) == "€"
    assert norm.flush() == ""


def test_normalizer_truncated_trailing_multibyte_flush():
    norm = StreamNormalizer()
    assert norm.feed(b"caf\xc3") == "caf"
    assert norm.flush() == "�"  # same replacement the blob path makes
    assert norm.feed(b"ok") == "ok"  # reset: reusable after flush


# ----------------------------------------------------- gRPC twin transport


def test_grpc_stream_parity():
    from log_parser_tpu.shim.grpc_server import HAVE_GRPC

    if not HAVE_GRPC:
        pytest.skip("grpcio not installed")
    import grpc

    from log_parser_tpu.shim import logparser_stream_pb2 as spb
    from log_parser_tpu.shim import make_stream_stub
    from log_parser_tpu.shim.grpc_server import make_grpc_server

    engine = _engine()
    server, port = make_grpc_server(engine, host="127.0.0.1", port=0)
    server.start()
    try:
        channel = grpc.insecure_channel(f"127.0.0.1:{port}")
        stub = make_stream_stub(channel)
        blob = (
            "INFO boot\njava.lang.OutOfMemoryError: heap\n"
            "GC overhead limit exceeded\nCrashLoopBackOff seen\n"
        )
        data = blob.encode()

        def chunks():
            for i in range(0, len(data), 7):
                yield spb.StreamChunk(data=data[i : i + 7])
            yield spb.StreamChunk(close=True)

        frames = [json.loads(f.json) for f in stub(chunks())]
        final = _final_of(frames)
        want = _oneshot(_engine(), blob, False)
        assert _events(final["result"]) == want
        channel.close()
    finally:
        server.stop(grace=None)
