"""Crash-safe on-disk caches: checksum sidecars, quarantine of corrupt
or truncated entries, rebuild-not-raise, and the ``cache`` fault site
(patterns/libcache.py sidecars + utils/xlacache.py integrity sweep)."""

from __future__ import annotations

import hashlib
import os

import pytest

from helpers import make_pattern, make_pattern_set

from log_parser_tpu.runtime import faults
from log_parser_tpu.runtime.faults import FaultRegistry


@pytest.fixture(autouse=True)
def clean_faults():
    faults.install(None)
    yield
    faults.install(None)


@pytest.fixture()
def cache_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("LOG_PARSER_TPU_CACHE", str(tmp_path))
    # these tests pin the DISK snapshot layer (quarantine, fault
    # injection, lazy restore); the in-process pack memo would answer
    # warm loads before the disk is ever read, so park it — the memo
    # has its own coverage (tests/test_fleet.py TestPackSharing)
    monkeypatch.setenv("LOG_PARSER_TPU_PACK_SHARE", "0")
    from log_parser_tpu.patterns import libcache
    libcache.reset_packs()
    return tmp_path


def _sets():
    return [
        make_pattern_set(
            [
                make_pattern("oom", regex="OutOfMemoryError", confidence=0.9),
                make_pattern("to", regex="\\btimeout\\b", confidence=0.7,
                             severity="MEDIUM"),
            ]
        )
    ]


def _snapshot(cache_dir):
    (path,) = (cache_dir / "bank").glob("*.pkl")
    return path


# ----------------------------------------------------------- libcache


class TestLibcacheCrashSafety:
    def test_save_publishes_checksum_sidecar(self, cache_dir):
        from log_parser_tpu.patterns.bank import PatternBank

        PatternBank(_sets())
        path = _snapshot(cache_dir)
        sidecar = path.with_name(path.name + ".sum")
        assert sidecar.exists()
        digest, size = sidecar.read_text().split()
        blob = path.read_bytes()
        assert digest == hashlib.sha256(blob).hexdigest()
        assert int(size) == len(blob)

    def test_flipped_byte_quarantined_and_rebuilt(self, cache_dir):
        """A single flipped byte mid-file — the torn-write/bit-rot case a
        bare ``pickle.load`` may well decode into silent garbage — is
        caught by the checksum, quarantined, and rebuilt cold."""
        from log_parser_tpu.patterns.bank import PatternBank

        PatternBank(_sets())
        path = _snapshot(cache_dir)
        blob = bytearray(path.read_bytes())
        blob[len(blob) // 2] ^= 0xFF
        path.write_bytes(bytes(blob))

        bank = PatternBank(_sets())  # must not raise
        assert bank.n_patterns == 2
        corrupt = list((cache_dir / "bank").glob("*.pkl.corrupt"))
        assert len(corrupt) == 1
        # the rebuild republished a healthy snapshot + fresh sidecar
        path = _snapshot(cache_dir)
        assert (
            path.with_name(path.name + ".sum").read_text().split()[0]
            == hashlib.sha256(path.read_bytes()).hexdigest()
        )

    def test_truncated_entry_quarantined_and_rebuilt(self, cache_dir):
        from log_parser_tpu.patterns.bank import PatternBank

        PatternBank(_sets())
        path = _snapshot(cache_dir)
        blob = path.read_bytes()
        path.write_bytes(blob[: len(blob) // 2])

        bank = PatternBank(_sets())
        assert bank.n_patterns == 2
        assert list((cache_dir / "bank").glob("*.pkl.corrupt"))

    def test_sidecarless_legacy_entry_still_loads(self, cache_dir):
        from log_parser_tpu.patterns import libcache
        from log_parser_tpu.patterns.bank import PatternBank

        PatternBank(_sets())
        path = _snapshot(cache_dir)
        path.with_name(path.name + ".sum").unlink()
        key = path.stem
        assert libcache.load(key) is not None  # trusted, like before

    def test_corrupt_rebuild_scores_match_cold_build(self, cache_dir):
        """Acceptance: startup over a corrupted entry succeeds AND the
        rebuilt bank scores identically to a cold build."""
        from log_parser_tpu.config import ScoringConfig
        from log_parser_tpu.models.pod import PodFailureData
        from log_parser_tpu.runtime import AnalysisEngine

        logs = "ok\njava.lang.OutOfMemoryError: heap\na timeout b"
        data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=logs)
        r_cold = AnalysisEngine(_sets(), ScoringConfig()).analyze(data)

        path = _snapshot(cache_dir)
        blob = bytearray(path.read_bytes())
        blob[10] ^= 0x55
        path.write_bytes(bytes(blob))

        r_rebuilt = AnalysisEngine(_sets(), ScoringConfig()).analyze(data)
        assert [(e.matched_pattern.id, e.line_number, e.score)
                for e in r_rebuilt.events] == [
            (e.matched_pattern.id, e.line_number, e.score)
            for e in r_cold.events
        ]
        assert len(r_cold.events) == 2

    def test_injected_cache_fault_is_a_miss_not_a_quarantine(self, cache_dir):
        from log_parser_tpu.patterns import libcache
        from log_parser_tpu.patterns.bank import PatternBank

        PatternBank(_sets())
        path = _snapshot(cache_dir)
        key = path.stem

        faults.install(FaultRegistry.parse("cache_raise@times=1"))
        assert libcache.load(key) is None  # injected read failure: a miss
        assert path.exists()  # the healthy entry was NOT quarantined
        assert not list((cache_dir / "bank").glob("*.pkl.corrupt"))
        assert libcache.load(key) is not None  # budget spent: loads again


# ----------------------------------------------------------- xlacache


class TestXlaCacheIntegrity:
    def _entry(self, d, name, content):
        path = os.path.join(d, name)
        with open(path, "wb") as f:
            f.write(content)
        return path

    def test_sweep_records_then_detects_corruption(self, tmp_path):
        from log_parser_tpu.utils.xlacache import verify_cache_integrity

        d = str(tmp_path)
        self._entry(d, "exec-a", b"compiled-bytes-a" * 100)
        self._entry(d, "exec-b", b"compiled-bytes-b" * 100)

        first = verify_cache_integrity(d)
        assert first == {"checked": 2, "recorded": 2, "quarantined": 0}
        assert sorted(os.listdir(os.path.join(d, ".integrity"))) == [
            "exec-a.sum", "exec-b.sum",
        ]

        # truncate one entry the way a crashed writer would
        with open(os.path.join(d, "exec-a"), "wb") as f:
            f.write(b"compiled")
        second = verify_cache_integrity(d)
        assert second["quarantined"] == 1
        assert not os.path.exists(os.path.join(d, "exec-a"))
        assert os.path.exists(os.path.join(d, "exec-a.corrupt"))
        assert os.path.exists(os.path.join(d, "exec-b"))

        # the quarantined name is now a plain miss: sweeps stay stable
        third = verify_cache_integrity(d)
        assert third == {"checked": 1, "recorded": 0, "quarantined": 0}

    def test_unmodified_entries_pass_repeated_sweeps(self, tmp_path):
        from log_parser_tpu.utils.xlacache import verify_cache_integrity

        d = str(tmp_path)
        self._entry(d, "exec-a", b"stable" * 1000)
        verify_cache_integrity(d)
        for _ in range(3):
            counts = verify_cache_integrity(d)
            assert counts == {"checked": 1, "recorded": 0, "quarantined": 0}

    def test_mutable_atime_markers_are_never_checksummed(self, tmp_path):
        from log_parser_tpu.utils.xlacache import verify_cache_integrity

        d = str(tmp_path)
        self._entry(d, "jit_f-abc123-cache", b"payload" * 100)
        self._entry(d, "jit_f-abc123-atime", b"\x00" * 8)

        first = verify_cache_integrity(d)
        assert first == {"checked": 1, "recorded": 1, "quarantined": 0}

        # JAX rewrites the atime marker on every cache hit; the sweep
        # must not mistake that for corruption
        self._entry(d, "jit_f-abc123-atime", b"\x01" * 8)
        second = verify_cache_integrity(d)
        assert second == {"checked": 1, "recorded": 0, "quarantined": 0}
        assert os.path.exists(os.path.join(d, "jit_f-abc123-atime"))

    def test_orphan_sidecars_are_dropped(self, tmp_path):
        from log_parser_tpu.utils.xlacache import verify_cache_integrity

        d = str(tmp_path)
        path = self._entry(d, "exec-a", b"bytes")
        verify_cache_integrity(d)
        os.unlink(path)  # operator cleanup (find -atime +30 -delete)
        verify_cache_integrity(d)
        assert os.listdir(os.path.join(d, ".integrity")) == []

    def test_missing_directory_is_a_noop(self, tmp_path):
        from log_parser_tpu.utils.xlacache import verify_cache_integrity

        counts = verify_cache_integrity(str(tmp_path / "never-created"))
        assert counts == {"checked": 0, "recorded": 0, "quarantined": 0}

    def test_injected_cache_fault_aborts_sweep_quietly(self, tmp_path):
        from log_parser_tpu.utils.xlacache import verify_cache_integrity

        d = str(tmp_path)
        self._entry(d, "exec-a", b"bytes")
        faults.install(FaultRegistry.parse("cache_raise@times=1"))
        counts = verify_cache_integrity(d)  # must not raise into boot
        assert counts == {"checked": 0, "recorded": 0, "quarantined": 0}
        assert os.path.exists(os.path.join(d, "exec-a"))
