"""Fused match+factor pipeline: record compaction and overflow handling."""

from __future__ import annotations

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.models.pod import PodFailureData
from log_parser_tpu.runtime import AnalysisEngine

from helpers import make_pattern, make_pattern_set


def test_k_ladder_overflow_grows_to_cap(monkeypatch):
    """A batch with more matches than every ladder rung must still return
    complete records (the final rung is B*P, the true cap)."""
    import log_parser_tpu.ops.fused as fused

    monkeypatch.setattr(fused, "K_LADDER", (4, 8))
    ps = make_pattern_set(
        [make_pattern("every", regex="line", confidence=0.5, severity="LOW")]
    )
    engine = AnalysisEngine([ps], ScoringConfig())
    logs = "\n".join(f"line {i}" for i in range(32))
    result = engine.analyze(
        PodFailureData(pod={"metadata": {"name": "p"}}, logs=logs)
    )
    assert len(result.events) == 32
    assert [e.line_number for e in result.events] == list(range(1, 33))


def test_records_in_discovery_order_multi_pattern():
    """Line-major then pattern order (AnalysisService.java:89-113)."""
    ps = make_pattern_set(
        [
            make_pattern("a", regex="both|only_a", confidence=0.5, severity="LOW"),
            make_pattern("b", regex="both|only_b", confidence=0.5, severity="LOW"),
        ]
    )
    engine = AnalysisEngine([ps], ScoringConfig())
    logs = "only_b\nnothing\nboth\nonly_a"
    result = engine.analyze(
        PodFailureData(pod={"metadata": {"name": "p"}}, logs=logs)
    )
    got = [(e.line_number, e.matched_pattern.id) for e in result.events]
    assert got == [(1, "b"), (3, "a"), (3, "b"), (4, "a")]


def test_encode_rows_divisible_by_non_pow2_min_rows():
    """A sharded engine passes the mesh size as min_rows; on a 6-device
    mesh the row count must stay divisible by 6 even though rows are
    otherwise padded to powers of two (round-1 advisor finding)."""
    from log_parser_tpu.ops.encode import encode_lines

    for n in (1, 5, 6, 7, 48, 100):
        enc = encode_lines([f"line {i}" for i in range(n)], min_rows=6)
        assert enc.u8.shape[0] % 6 == 0, (n, enc.u8.shape)
        assert enc.u8.shape[0] >= n
