"""Pattern-library static analyzer (log_parser_tpu/analysis/).

The contract under test, per ISSUE: the ReDoS rules flag every seeded
pathological shape and stay quiet on the builtin-style regexes; the
tier classifier's prediction matches the ACTUAL bank build column for
column, with the same reason codes (same exceptions, same registry);
subsumption answers containment exactly on known pairs; schema rules
fire on seeded YAML defects; and the reload ladder's lint stage
rejects under ``block`` while leaving the engine object-identical.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path

import pytest
import yaml

from log_parser_tpu.analysis import classify_regex, lint_pattern_sets
from log_parser_tpu.analysis import subsumption, tiers
from log_parser_tpu.analysis.redos import scan_redos
from log_parser_tpu.analysis.rules import RULES, VALID_RULE_SEVERITIES
from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.ops.match import MatcherBanks
from log_parser_tpu.patterns.bank import PatternBank
from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets
from log_parser_tpu.patterns.loader import (
    PatternValidationError,
    validate_pattern_set,
)
from log_parser_tpu.patterns.regex import reasons
from log_parser_tpu.patterns.regex.dfa import DfaLimitError
from log_parser_tpu.patterns.regex.parser import parse_java_regex
from log_parser_tpu.runtime import AnalysisEngine
from log_parser_tpu.runtime.reload import PatternReloader, ReloadError
from tests.helpers import make_pattern, make_pattern_set


def _rules_of(findings) -> set:
    return {f.rule for f in findings}


def _yaml(sets) -> str:
    return "\n---\n".join(
        yaml.safe_dump(s.to_dict(drop_none=True)) for s in sets
    )


# ------------------------------------------------------------ registries


class TestRegistries:
    def test_every_rule_has_a_valid_severity(self):
        for rule, (severity, description) in RULES.items():
            assert severity in VALID_RULE_SEVERITIES, rule
            assert description

    def test_dfa_limit_code_matches_registry(self):
        # dfa.py cannot import reasons (layering); the literal is pinned
        assert DfaLimitError.code == reasons.DFA_TOO_LARGE

    def test_bit_position_cap_mirrors_matcher_banks(self):
        assert (
            tiers.BIT_MAX_COLUMN_POSITIONS
            == MatcherBanks.BITGLUSH_MAX_COLUMN_POSITIONS
        )

    def test_describe_known_and_unknown(self):
        assert reasons.describe(reasons.RX_LOOKAROUND)
        assert reasons.describe("no-such-code") == "unknown reason code"


# ------------------------------------------------------- ReDoS detection


REDOS_FLAGGED = [
    ("(a+)+", "redos-nested-quantifier"),
    ("(a*)*", "redos-nested-quantifier"),
    ("([a-z]+)*X", "redos-nested-quantifier"),
    ("(x*y?)*z", "redos-nested-quantifier"),
    ("(a|ab)*c", "redos-overlapping-alternation"),
    ("(a|a)*", "redos-overlapping-alternation"),
    (".*.*x", "redos-adjacent-overlap"),
    (r"\w+\w+", "redos-adjacent-overlap"),
]

REDOS_CLEAN = [
    "(ab+c)+",
    "(ERROR|FATAL|CRITICAL)",
    r"^\s*at\s+[\w\.\$]+\(.*\)\s*$",
    r"\b\w*Exception\b|\b\w*Error\b",
    "OutOfMemoryError",
    "a{2,5}",
]


class TestRedos:
    @pytest.mark.parametrize("regex,rule", REDOS_FLAGGED)
    def test_adversarial_corpus_is_flagged(self, regex, rule):
        found = scan_redos(parse_java_regex(regex, False))
        assert rule in {r for r, _ in found}, (regex, found)

    @pytest.mark.parametrize("regex", REDOS_CLEAN)
    def test_builtin_style_regexes_are_clean(self, regex):
        assert scan_redos(parse_java_regex(regex, False)) == []

    def test_gating_redos_rules_gate_through_lint(self):
        sets = [
            make_pattern_set([make_pattern("bad", regex="(a+)+!")], "lib")
        ]
        report = lint_pattern_sets(sets, check_subsumption=False)
        assert report.gating
        assert "redos-nested-quantifier" in _rules_of(report.gating_findings)


class TestHostPathTimeBudget:
    """Every regex that actually serves on the host ``re`` path must
    finish a pathological line inside the budget. The builtin library
    currently has zero host-tier columns — the loop must stay, so the
    first PR that adds one inherits the budget check automatically."""

    PATHOLOGICAL = "a" * 4096 + " " + "b" * 4096

    def test_builtin_host_columns_within_budget(self):
        bank = PatternBank(load_builtin_pattern_sets())
        host_cols = [
            c for c in bank.columns
            if c.exact_seqs is None and c.dfa is None
        ]
        for col in host_cols:
            start = time.monotonic()
            col.host.search(self.PATHOLOGICAL)
            assert time.monotonic() - start < 1.0, col.regex


# -------------------------------------------------------- tier classifier


class TestTierParity:
    def test_prediction_matches_built_bank_column_for_column(self):
        bank = PatternBank(load_builtin_pattern_sets())
        assert bank.columns, "builtin bank built no columns"
        mismatches = []
        for col in bank.columns:
            pred = classify_regex(col.regex, col.case_insensitive)
            actual = (
                tiers.SHIFTOR if col.exact_seqs is not None
                else tiers.DFA if col.dfa is not None
                else tiers.HOST
            )
            if pred.tier != actual:
                mismatches.append((col.regex, pred.tier, actual))
        assert mismatches == []

    def test_supported_tiers_carry_supported_code(self):
        pred = classify_regex("OutOfMemoryError")
        assert pred.tier == tiers.SHIFTOR
        assert pred.reason_code == reasons.SUPPORTED
        assert pred.bit_capable

    def test_host_reason_code_is_the_exceptions_code(self):
        pred = classify_regex(r"(?<=foo)bar")
        assert pred.tier == tiers.HOST
        assert pred.reason_code == reasons.RX_LOOKAROUND
        backref = classify_regex(r"(a)\1")
        assert backref.tier == tiers.HOST
        assert backref.reason_code == reasons.RX_BACKREFERENCE

    def test_skipped_on_uncompilable(self):
        pred = classify_regex("(unclosed")
        assert pred.tier == tiers.SKIPPED
        assert pred.reason_code == reasons.RX_SYNTAX

    def test_prediction_json_shape(self):
        out = classify_regex("ERROR|FATAL").to_json()
        assert out["tier"] in (tiers.SHIFTOR, tiers.DFA)
        assert set(out) >= {"regex", "tier", "reason", "bitCapable",
                            "literals"}


# ----------------------------------------------------------- subsumption


def _dfa_of(regex: str):
    pred = classify_regex(regex)
    assert pred.dfa is not None, regex
    return pred.dfa


class TestSubsumption:
    def test_equal_languages(self):
        rel = subsumption.compare_dfas(_dfa_of("abc"), _dfa_of("ab[c]"))
        assert rel == subsumption.EQUAL

    def test_strict_containment_real_builtin_pair(self):
        # any line containing OutOfMemoryError contains MemoryError
        rel = subsumption.compare_dfas(
            _dfa_of("OutOfMemoryError"), _dfa_of("MemoryError")
        )
        assert rel == subsumption.A_IN_B
        assert subsumption.compare_dfas(
            _dfa_of("MemoryError"), _dfa_of("OutOfMemoryError")
        ) == subsumption.B_IN_A

    def test_incomparable(self):
        rel = subsumption.compare_dfas(_dfa_of("ERROR"), _dfa_of("WARN"))
        assert rel == subsumption.INCOMPARABLE

    def test_budget_exhaustion_is_undecided_not_wrong(self):
        rel = subsumption.compare_dfas(
            _dfa_of("ERROR"), _dfa_of("WARN"), max_product_states=1
        )
        assert rel == subsumption.UNDECIDED

    def test_lint_reports_duplicate_and_shadow(self):
        sets = [
            make_pattern_set(
                [
                    make_pattern("jvm-oom", regex="OutOfMemoryError"),
                    make_pattern("py-mem", regex="MemoryError"),
                    make_pattern("oom-again", regex="OutOfMemoryError"),
                ],
                "lib",
            )
        ]
        report = lint_pattern_sets(sets)
        rules = _rules_of(report.findings)
        assert "subsume-duplicate" in rules  # identical regex pair
        assert "subsume-shadowed" in rules  # strict containment pair
        assert report.stats["subsumptionUndecided"] == 0


# --------------------------------------------------------- schema rules


class TestSchemaRules:
    def test_cross_set_duplicate_id_gates(self):
        sets = [
            make_pattern_set([make_pattern("dup", regex="AAA")], "lib-a"),
            make_pattern_set([make_pattern("dup", regex="BBB")], "lib-b"),
        ]
        report = lint_pattern_sets(sets, check_subsumption=False)
        assert "schema-duplicate-id" in _rules_of(report.gating_findings)

    def test_unknown_severity_gates_lowercase_known_does_not(self):
        bad = [make_pattern_set(
            [make_pattern("p", severity="URGENT")], "lib")]
        ok = [make_pattern_set(
            [make_pattern("p", severity="high")], "lib")]
        assert "schema-unknown-severity" in _rules_of(
            lint_pattern_sets(bad, check_subsumption=False).gating_findings
        )
        assert "schema-unknown-severity" not in _rules_of(
            lint_pattern_sets(ok, check_subsumption=False).findings
        )

    def test_empty_regex_and_invalid_regex_gate(self):
        sets = [
            make_pattern_set(
                [
                    make_pattern("empty", regex=""),
                    make_pattern("broken", regex="(unclosed"),
                ],
                "lib",
            )
        ]
        rules = _rules_of(
            lint_pattern_sets(sets, check_subsumption=False).gating_findings
        )
        assert {"schema-empty-regex", "schema-invalid-regex"} <= rules

    def test_bad_confidence_warns(self):
        sets = [make_pattern_set(
            [make_pattern("p", confidence=1.5)], "lib")]
        report = lint_pattern_sets(sets, check_subsumption=False)
        assert "schema-bad-confidence" in _rules_of(report.gating_findings)

    def test_summary_counts(self):
        sets = [make_pattern_set([make_pattern("p")], "lib")]
        summary = lint_pattern_sets(sets, check_subsumption=False).summary()
        assert set(summary) == {"findings", "error", "warn", "info",
                                "gating"}
        assert summary["gating"] is False


class TestLoaderValidation:
    def test_within_set_duplicate_id_is_a_parse_error(self):
        ps = make_pattern_set(
            [make_pattern("dup"), make_pattern("dup")], "lib"
        )
        with pytest.raises(PatternValidationError) as err:
            validate_pattern_set(ps, source="lib.yaml")
        assert err.value.source == "lib.yaml"
        assert [f["rule"] for f in err.value.findings] == ["duplicate-id"]

    def test_unknown_severity_is_a_parse_error(self):
        ps = make_pattern_set([make_pattern("p", severity="WAT")], "lib")
        with pytest.raises(PatternValidationError) as err:
            validate_pattern_set(ps)
        assert [f["rule"] for f in err.value.findings] == [
            "unknown-severity"
        ]

    def test_case_insensitive_severity_accepted(self):
        validate_pattern_set(
            make_pattern_set([make_pattern("p", severity="critical")], "l")
        )


# ------------------------------------------------------- builtin library


class TestBuiltinLibrary:
    def test_builtin_is_gating_clean(self):
        report = lint_pattern_sets(load_builtin_pattern_sets())
        assert report.gating_findings == []
        # and has real coverage: tiers were classified for every pattern
        assert report.stats["patterns"] > 50
        assert len(report.tiers) == report.stats["patterns"]
        assert all(
            t["tier"] in (tiers.SHIFTOR, tiers.DFA)
            for t in report.tiers.values()
        )


# ------------------------------------------------- reload ladder gating


def _sets_v1():
    return [make_pattern_set(
        [make_pattern("oom", regex="OutOfMemoryError")], "lib-v1")]


def _sets_redos():
    return [make_pattern_set(
        [make_pattern("evil", regex="(a+)+!")], "lib-evil")]


def _engine() -> AnalysisEngine:
    return AnalysisEngine(_sets_v1(), ScoringConfig())


class TestReloadLintGate:
    def test_block_mode_rejects_and_engine_is_object_identical(self):
        engine = _engine()
        bank_before = engine.bank
        epoch_before = engine.reload_epoch
        reloader = PatternReloader(engine, lint_mode="block")
        with pytest.raises(ReloadError) as err:
            reloader.reload(yaml_text=_yaml(_sets_redos()))
        assert err.value.stage == "lint"
        body = err.value.to_json()
        assert body["error"] == "reload rejected"
        assert any(
            f["rule"] == "redos-nested-quantifier" for f in body["findings"]
        )
        assert engine.bank is bank_before
        assert engine.reload_epoch == epoch_before
        # the attempt's lint summary is still exposed for /trace/last
        assert engine.last_lint is not None
        assert engine.last_lint["gating"] is True

    def test_warn_mode_proceeds_and_reports(self):
        engine = _engine()
        envelope = PatternReloader(engine, lint_mode="warn").reload(
            yaml_text=_yaml(_sets_redos())
        )
        assert envelope["status"] == "reloaded"
        assert envelope["lint"]["gating"] is True
        assert engine.last_lint == envelope["lint"]

    def test_off_mode_has_no_lint_envelope(self):
        engine = _engine()
        envelope = PatternReloader(engine, lint_mode="off").reload(
            yaml_text=_yaml(_sets_v1())
        )
        assert envelope["status"] == "reloaded"
        assert "lint" not in envelope
        assert engine.last_lint is None

    def test_clean_reload_in_block_mode_succeeds(self):
        engine = _engine()
        envelope = PatternReloader(engine, lint_mode="block").reload(
            yaml_text=_yaml(_sets_v1())
        )
        assert envelope["status"] == "reloaded"
        assert envelope["lint"]["gating"] is False

    def test_loader_schema_errors_reject_with_findings(self):
        engine = _engine()
        dup = [make_pattern_set(
            [make_pattern("d", regex="A"), make_pattern("d", regex="B")],
            "lib-dup",
        )]
        with pytest.raises(ReloadError) as err:
            PatternReloader(engine, lint_mode="off").reload(
                yaml_text=_yaml(dup)
            )
        assert err.value.stage == "build"
        assert err.value.findings
        assert err.value.findings[0]["rule"] == "duplicate-id"
        assert engine.reload_epoch == 0

    def test_trace_last_reports_lint_summary(self):
        import json
        import threading
        import urllib.request

        from log_parser_tpu.serve import make_server

        engine = _engine()
        PatternReloader(engine, lint_mode="warn").reload(
            yaml_text=_yaml(_sets_redos())
        )
        server = make_server(engine, host="127.0.0.1", port=0)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        try:
            url = f"http://127.0.0.1:{server.server_address[1]}/trace/last"
            with urllib.request.urlopen(url) as resp:
                payload = json.loads(resp.read())
        finally:
            server.shutdown()
        assert payload["lint"]["gating"] is True
        assert payload["lint"]["findings"] >= 1


# ------------------------------------------------------------------ CLI


class TestPatternLintCli:
    def test_seeded_fixtures_flagged_with_exit_codes(self, tmp_path):
        import subprocess
        import sys as _sys

        bad = tmp_path / "bad.yaml"
        bad.write_text(
            yaml.safe_dump(
                make_pattern_set(
                    [
                        make_pattern("evil", regex="(a+)+!"),
                        make_pattern("dup", regex="OutOfMemoryError"),
                        make_pattern("dup", regex="Urgent",
                                     severity="URGENT"),
                        make_pattern("oom2", regex="OutOfMemoryError"),
                    ],
                    "lib-bad",
                ).to_dict(drop_none=True)
            )
        )
        cli = str(Path(__file__).resolve().parents[1]
                  / "tools" / "pattern_lint.py")
        proc = subprocess.run(
            [_sys.executable, cli, "--json", str(bad)],
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        report = json.loads(proc.stdout)
        rules = {f["rule"] for f in report["findings"]}
        assert {
            "redos-nested-quantifier",
            "schema-duplicate-id",
            "schema-unknown-severity",
            "subsume-duplicate",
        } <= rules

        missing = subprocess.run(
            [_sys.executable, cli, str(tmp_path / "nope.yaml")],
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert missing.returncode == 2
