"""Native library parity: ingest vs java_split_lines/encode_lines, and the
C++ DFA builder vs the Python subset construction and Python ``re``."""

from __future__ import annotations

import random

import numpy as np
import pytest

from log_parser_tpu.golden.javacompat import compile_java_regex, java_split_lines
from log_parser_tpu.native import available
from log_parser_tpu.native.ingest import Corpus
from log_parser_tpu.ops.encode import encode_lines

pytestmark = pytest.mark.skipif(
    not available(), reason="native library unavailable"
)


SPLIT_CASES = [
    "",
    "a",
    "a\nb",
    "a\r\nb",
    "a\n",
    "a\r\n",
    "\n",
    "\r\n",
    "\n\n",
    "a\n\nb\n\n",
    "a\r\rb",          # lone \r is not a separator
    "a\r\r\nb",        # only one \r consumed by the separator
    "\r",
    "x" * 5000 + "\nshort",
    "héllo\nwörld\n",
    "tail no newline",
    "\nleading",
]


@pytest.mark.parametrize("logs", SPLIT_CASES)
def test_corpus_split_matches_java(logs):
    corpus = Corpus(logs)
    expect = java_split_lines(logs)
    assert len(corpus) == len(expect)
    assert list(corpus) == expect


@pytest.mark.parametrize("logs", SPLIT_CASES)
def test_corpus_encode_matches_python(logs):
    corpus = Corpus(logs)
    expect = encode_lines(java_split_lines(logs))
    enc = corpus.encoded
    assert enc.n_lines == expect.n_lines
    assert enc.u8.shape == expect.u8.shape
    np.testing.assert_array_equal(enc.u8, expect.u8)
    np.testing.assert_array_equal(enc.lengths, expect.lengths)
    np.testing.assert_array_equal(enc.needs_host, expect.needs_host)


def test_corpus_random_fuzz():
    rng = random.Random(7)
    alphabet = "ab\r\n \t€é"
    for _ in range(200):
        logs = "".join(rng.choice(alphabet) for _ in range(rng.randrange(0, 60)))
        corpus = Corpus(logs)
        expect = java_split_lines(logs)
        assert list(corpus) == expect, repr(logs)
        enc = corpus.encoded
        pe = encode_lines(expect)
        np.testing.assert_array_equal(enc.u8, pe.u8, err_msg=repr(logs))
        np.testing.assert_array_equal(enc.lengths, pe.lengths, err_msg=repr(logs))
        np.testing.assert_array_equal(
            enc.needs_host, pe.needs_host, err_msg=repr(logs)
        )


def test_corpus_slicing():
    corpus = Corpus("a\nbb\nccc\ndddd")
    assert corpus[1] == "bb"
    assert corpus[-1] == "dddd"
    assert corpus[1:3] == ["bb", "ccc"]
    assert corpus[:] == ["a", "bb", "ccc", "dddd"]
    assert corpus.materialize() == ["a", "bb", "ccc", "dddd"]


# ---------------------------------------------------------------------------
# DFA builder
# ---------------------------------------------------------------------------

REGEXES = [
    "ERROR",
    "(?i)out of memory",
    r"\bOOM\b",
    r"^\s*at\s+[\w.$]+",
    r"(ERROR|FATAL|CRITICAL|SEVERE)",
    r"\w+Exception",
    r"Connection refused.*:\d+",
    r"x{2,4}y",
    r"[A-Za-z_][A-Za-z0-9_]*Error$",
    r"a|b|c|abc",
    r"probe (failed|timed out)",
    r"GC \(.*\) \d+M->\d+M",
]

LINES = [
    "",
    "ERROR something broke",
    "error lowercase",
    "Out Of Memory detected",
    "OOM",
    "xOOMy",
    "    at com.example.Main.run(Main.java:1)",
    "java.lang.IllegalStateException: boom",
    "Connection refused to host:5432",
    "xxy xxxy xxxxy",
    "MyError",
    "MyError trailing",
    "abc",
    "probe failed",
    "probe timed out",
    "[Full GC (Ergonomics) 255M->250M(256M)]",
    "benign INFO line",
]


@pytest.mark.parametrize("regex", REGEXES)
def test_native_dfa_matches_python_builders(regex):
    from log_parser_tpu.patterns.regex.dfa import compile_nfa_to_dfa
    from log_parser_tpu.patterns.regex.nfa import build_nfa
    from log_parser_tpu.patterns.regex.parser import parse_java_regex
    from log_parser_tpu.native.dfabuild import build_dfa_native

    ci = regex.startswith("(?i)")
    body = regex[4:] if ci else regex
    node = parse_java_regex(body, ci)
    nfa = build_nfa(node, unanchored_prefix=True)
    py = compile_nfa_to_dfa(nfa, regex=body)
    built = build_dfa_native(nfa)
    assert built is not None
    trans, byte_class, accept, start = built
    host = compile_java_regex(body, ci)

    # native minimizes: state count must not exceed the Python builder's
    assert trans.shape[0] <= py.n_states

    def native_match(data: bytes) -> bool:
        st = start
        for b in data:
            st = trans[st, byte_class[b]]
        return bool(accept[st])

    for line in LINES:
        data = line.encode()
        expect = bool(host.search(line))
        assert py.matches(data) == expect, (regex, line)
        assert native_match(data) == expect, (regex, line)


def test_native_dfa_limit():
    from log_parser_tpu.patterns.regex.nfa import build_nfa
    from log_parser_tpu.patterns.regex.parser import parse_java_regex
    from log_parser_tpu.native.dfabuild import DfaLimitExceeded, build_dfa_native

    node = parse_java_regex(r"a.{10,20}b.{10,20}c", False)
    nfa = build_nfa(node, unanchored_prefix=True)
    with pytest.raises(DfaLimitExceeded):
        build_dfa_native(nfa, max_states=8)


def test_compile_regex_to_dfa_uses_native_and_matches():
    from log_parser_tpu.patterns.regex.dfa import compile_regex_to_dfa

    dfa = compile_regex_to_dfa(r"(ERROR|WARN)\s+\w+")
    host = compile_java_regex(r"(ERROR|WARN)\s+\w+")
    for line in LINES + ["ERROR x", "WARN  yz", "WARNx"]:
        assert dfa.matches(line.encode()) == bool(host.search(line)), line


def test_native_dfa_zero_state_cap():
    from log_parser_tpu.patterns.regex.dfa import DfaLimitError, compile_regex_to_dfa

    with pytest.raises(DfaLimitError):
        compile_regex_to_dfa("a", max_states=0)
