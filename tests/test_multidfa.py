"""Union multi-pattern DFA (patterns/regex/multidfa.py + MultiDfaBank).

The union automaton must be bit-for-bit equivalent to running each regex
alone — per pattern, per line — including anchors, word boundaries,
case-insensitive branches, counted repetitions, end-of-line completions,
and empty-match regexes. The packing must respect the state budget and
keep every entry accounted for (grouped or rejected).
"""

from __future__ import annotations

import random
import re

import numpy as np
import pytest

from log_parser_tpu.ops.encode import encode_lines
from log_parser_tpu.ops.match import MatcherBanks
from log_parser_tpu.patterns.bank import PatternBank
from log_parser_tpu.patterns.regex.multidfa import (
    MultiDfaLimitError,
    _compile_union_python,
    _merge_nfas,
    compile_union_regexes,
    pack_union_groups,
)
from log_parser_tpu.patterns.regex.nfa import build_nfa
from log_parser_tpu.patterns.regex.parser import parse_java_regex
from tests.helpers import make_pattern, make_pattern_set

REGEXES: list[tuple[str, bool]] = [
    ("OutOfMemoryError", False),
    ("(Liveness|Readiness) probe failed", False),
    ("exit code 137|Exit Code:\\s*137", False),
    ("segfault at [0-9a-f]+|Segmentation fault", False),
    ("\\bFull GC\\b", False),
    ("panic: ", False),
    ("foo$", False),
    ("^start", False),
    ("a{2,4}b", False),
    ("status.*red", False),
    ("no such host|could not resolve|NXDOMAIN", True),
    ("ERROR|FATAL", False),
    ("x?", False),  # matches the empty string on every line
]

LINES = [
    "",
    "foo",
    "xfoo",
    "foox",
    "start here",
    "restart",
    "aab",
    "aaaab",
    "ab",
    "aaaaab",
    "java.lang.OutOfMemoryError: heap",
    "Liveness probe failed",
    "probe failed",
    "exit code 137",
    "Exit Code:   137",
    "segfault at deadbeef",
    "Segmentation fault",
    "a Full GC pause",
    "FullGC",
    "panic: oops",
    "status is red",
    "red before status",
    "NO SUCH HOST",
    "nxdomain lookup",
    "Could Not Resolve",
    "ERROR and FATAL",
]


def _want(lines: list[str]) -> np.ndarray:
    out = np.zeros((len(lines), len(REGEXES)), dtype=bool)
    for j, (rx, ci) in enumerate(REGEXES):
        pat = re.compile(rx, re.IGNORECASE if ci else 0)
        for i, ln in enumerate(lines):
            out[i, j] = bool(pat.search(ln))
    return out


def test_union_matches_re_native_and_python():
    md_native = compile_union_regexes(REGEXES)
    nfas = [
        build_nfa(parse_java_regex(rx, ci), unanchored_prefix=False)
        for rx, ci in REGEXES
    ]
    merged, finals = _merge_nfas(nfas)
    md_py = _compile_union_python(merged, finals, len(REGEXES), 8192)

    want = _want(LINES)
    for i, ln in enumerate(LINES):
        data = ln.encode()
        np.testing.assert_array_equal(md_native.matches(data), want[i], err_msg=ln)
        np.testing.assert_array_equal(md_py.matches(data), want[i], err_msg=ln)


def test_union_random_fuzz_vs_re():
    rng = random.Random(7)
    alphabet = "abE R:137fostdx"
    lines = [
        "".join(rng.choice(alphabet) for _ in range(rng.randrange(0, 40)))
        for _ in range(200)
    ]
    md = compile_union_regexes(REGEXES)
    want = _want(lines)
    for i, ln in enumerate(lines):
        np.testing.assert_array_equal(md.matches(ln.encode()), want[i], err_msg=ln)


def test_budget_raises():
    with pytest.raises(MultiDfaLimitError):
        compile_union_regexes(REGEXES, max_states=8)


def test_pack_union_groups_accounts_for_every_entry():
    entries = [(f"k{j}", rx, ci) for j, (rx, ci) in enumerate(REGEXES)]
    groups, rejected = pack_union_groups(entries, max_states=300, max_group=8)
    keys = [k for ks, _ in groups for k in ks] + [k for k, _, _ in rejected]
    assert sorted(keys) == sorted(k for k, _, _ in entries)
    for ks, md in groups:
        assert md.n_patterns == len(ks)
        assert md.n_states <= 300


def test_matcher_bank_multi_tier_cube_parity():
    """MatcherBanks with the multi tier vs pure dense — identical cubes."""
    patterns = [
        make_pattern(f"p{j}", regex=rx, confidence=0.5, severity="LOW")
        for j, (rx, ci) in enumerate(REGEXES)
        if not ci and rx != "x?"  # bank-level: keep deterministic columns
    ]
    bank = PatternBank([make_pattern_set(patterns)])
    multi = MatcherBanks(
        bank, shiftor_min_columns=10**9, prefilter_min_columns=10**9,
        multi_min_columns=2, bitglush_max_words=0,
    )
    dense = MatcherBanks(
        bank, shiftor_min_columns=10**9, prefilter_min_columns=10**9,
        multi_min_columns=10**9, bitglush_max_words=0,
    )
    assert multi.multi_groups, "multi tier must engage"
    assert not multi.dfa_cols, "every dense column should ride the union"

    import jax.numpy as jnp

    enc = encode_lines(LINES, 4096, 128, 8)
    lt = jnp.asarray(enc.u8.T)
    ln = jnp.asarray(enc.lengths)
    np.testing.assert_array_equal(
        np.asarray(multi.cube(lt, ln))[: len(LINES)],
        np.asarray(dense.cube(lt, ln))[: len(LINES)],
    )


def test_engine_parity_with_multi_tier():
    from log_parser_tpu.config import ScoringConfig
    from log_parser_tpu.golden import GoldenAnalyzer
    from log_parser_tpu.models import PodFailureData
    from log_parser_tpu.runtime import AnalysisEngine

    from tests.test_engine_parity import assert_results_match

    patterns = [
        make_pattern(
            f"p{j}",
            regex=rx,
            confidence=0.6,
            severity="MEDIUM",
            secondaries=[("panic: ", 0.5, 10)],
        )
        for j, (rx, ci) in enumerate(REGEXES[:6])
    ]
    sets = [make_pattern_set(patterns)]
    engine = AnalysisEngine(sets, ScoringConfig())
    # the bit-parallel tier may absorb compilable columns first; the union
    # must hold whatever is left on an automaton tier
    assert engine.matchers.multi_groups or engine.matchers.bitglush_cols
    logs = "\n".join(LINES)
    data = PodFailureData(pod={"metadata": {"name": "m"}}, logs=logs)
    assert_results_match(
        engine.analyze(data), GoldenAnalyzer(sets, ScoringConfig()).analyze(data)
    )
