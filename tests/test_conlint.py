"""Concurrency-invariant linter (tools/conlint.py, hygiene check 10).

Two-sided contract: the checker is CLEAN over the real tree (every
waiver present and justified), and it FLAGS every violation seeded in
``tests/fixtures/conlint_bad_fixture.py`` — inverted lock order,
blocking calls under ``state_lock``, an uncontained ``faults.fire`` —
while staying quiet on the fixture's near-miss ``ok_*`` functions.
The checker imports nothing from the package, so these tests load it
straight from its file path.
"""

from __future__ import annotations

import importlib.util
import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
CONLINT = REPO / "tools" / "conlint.py"
FIXTURE = REPO / "tests" / "fixtures" / "conlint_bad_fixture.py"

_spec = importlib.util.spec_from_file_location("conlint", CONLINT)
conlint = importlib.util.module_from_spec(_spec)
sys.modules["conlint"] = conlint  # dataclasses resolves hints via sys.modules
_spec.loader.exec_module(conlint)


def _fixture_findings():
    return conlint.check_file(str(FIXTURE))


class TestRepoIsClean:
    def test_default_scope_has_no_findings(self):
        findings = conlint.check_paths(
            [str(REPO / "log_parser_tpu" / d)
             for d in ("runtime", "serve", "parallel")]
        )
        assert findings == [], [f"{f.file}:{f.line} {f.rule}" for f in findings]

    def test_cli_exit_codes_and_json(self):
        clean = subprocess.run(
            [sys.executable, str(CONLINT), "--json"],
            cwd=REPO, capture_output=True, text=True,
        )
        assert clean.returncode == 0, clean.stdout + clean.stderr
        assert json.loads(clean.stdout) == []

        bad = subprocess.run(
            [sys.executable, str(CONLINT), "--json", str(FIXTURE)],
            cwd=REPO, capture_output=True, text=True,
        )
        assert bad.returncode == 1
        findings = json.loads(bad.stdout)
        assert findings and all(
            set(f) == {"file", "line", "rule", "detail"} for f in findings
        )


class TestBadFixtureIsFlagged:
    def test_every_seeded_violation_found(self):
        by_rule: dict[str, list[int]] = {}
        for f in _fixture_findings():
            by_rule.setdefault(f.rule, []).append(f.line)
        assert len(by_rule.get("conlint-lock-order", [])) == 2
        assert len(by_rule.get("conlint-blocking-under-lock", [])) == 4
        assert len(by_rule.get("conlint-uncontained-fire", [])) == 1

    def test_findings_point_into_bad_functions_only(self):
        source = FIXTURE.read_text().splitlines()
        current = ""
        owner_of: dict[int, str] = {}
        for i, line in enumerate(source, 1):
            if line.startswith("def "):
                current = line.split("(")[0][4:]
            owner_of[i] = current
        for f in _fixture_findings():
            assert owner_of[f.line].startswith("bad_"), (
                f"{f.rule} at line {f.line} is inside "
                f"{owner_of[f.line]!r}, expected a bad_* function"
            )

    @pytest.mark.parametrize(
        "rule,detail_part",
        [
            ("conlint-lock-order", "while state_lock is held"),
            ("conlint-blocking-under-lock", "time.sleep"),
            ("conlint-blocking-under-lock", ".join(timeout=...)"),
            ("conlint-blocking-under-lock", ".wait()"),
            ("conlint-blocking-under-lock", "subprocess.run"),
            ("conlint-uncontained-fire", "no containing try"),
        ],
    )
    def test_details_name_the_operation(self, rule, detail_part):
        assert any(
            f.rule == rule and detail_part in f.detail
            for f in _fixture_findings()
        )


class TestWaiverMechanism:
    def test_waived_fire_site_is_suppressed(self):
        # ok_waived_fire carries the waiver comment; the same call
        # without it must be flagged — prove both directions
        waived = FIXTURE.read_text()
        assert "conlint: contained-by-caller" in waived
        fire_lines = [
            f.line for f in _fixture_findings()
            if f.rule == "conlint-uncontained-fire"
        ]
        waiver_line = next(
            i for i, ln in enumerate(waived.splitlines(), 1)
            if "conlint: contained-by-caller" in ln
        )
        assert waiver_line not in fire_lines

    def test_real_tree_waivers_name_their_container(self, tmp_path):
        # every in-tree waiver must say where the containment lives
        out = subprocess.run(
            ["grep", "-rn", "conlint: contained-by-caller",
             "log_parser_tpu"],
            cwd=REPO, capture_output=True, text=True,
        )
        lines = [l for l in out.stdout.splitlines() if l]
        assert lines, "expected waivered fire sites in the tree"
        for line in lines:
            assert "(" in line.split("contained-by-caller", 1)[1], line
