"""Row-padding rung properties (``ops/encode._pad_rows``).

The rung ladder bounds both padding waste and the compile-shape set:
plain pow2 ≤ 8k rows, quarter rungs to 64k (≤25% waste), eighth rungs
above (≤12.5% waste). The large-batch branch is otherwise exercised only
by 1M-line bench runs on hardware, so its arithmetic is pinned here.
"""

from __future__ import annotations

import pytest

from log_parser_tpu.ops.encode import (
    _EIGHTH_RUNG_FLOOR,
    _QUARTER_RUNG_FLOOR,
    _pad_rows,
)


@pytest.mark.parametrize(
    "n,expected",
    [
        (1, 1),
        (100, 128),
        (8192, 8192),  # pow2 branch, exact at the floor
        (8193, 10240),  # first quarter rung: 8192 + 2048
        (65536, 65536),  # quarter branch, exact at the octave edge
        (65537, 73728),  # first eighth rung: 65536 + 8192
        (200000, 212992),  # 131072 + 5 * 16384 (was 229376 on quarters)
        (1000000, 1048576),  # lands on the pow2 edge either way
    ],
)
def test_pad_rows_values(n, expected):
    assert _pad_rows(n, 1) == expected


def test_pad_rows_properties():
    prev = 0
    for n in range(1, 300000, 997):
        rows = _pad_rows(n, 1)
        assert rows >= n
        assert rows >= prev  # monotonic in n
        prev = rows
        if n > _EIGHTH_RUNG_FLOOR:
            assert (rows - n) / n <= 0.125
            # eighth rungs above 64k are multiples of 8192: keeps every
            # batch-axis alignment downstream (128 lanes, 8 sublanes,
            # bitglush_pallas tile divisibility) trivially satisfied
            assert rows % 8192 == 0
        elif n > _QUARTER_RUNG_FLOOR:
            assert (rows - n) / n <= 0.25
            assert rows % 1024 == 0


def test_pad_rows_min_rows_divisibility():
    for min_rows in (1, 3, 7, 8, 48):
        for n in (1, 5000, 70000, 200001):
            rows = _pad_rows(n, min_rows)
            assert rows % min_rows == 0
            assert rows >= n


def test_nul_line_routes_to_host():
    from log_parser_tpu.ops.encode import encode_lines

    enc = encode_lines(["plain ok", "has\x00nul", "also fine"])
    assert not enc.needs_host[0]
    assert enc.needs_host[1]  # content NUL -> host re-match
    assert not enc.needs_host[2]


def test_nul_line_routes_to_host_native():
    from log_parser_tpu.native import available
    from log_parser_tpu.native.ingest import Corpus

    if not available():
        pytest.skip("native library unavailable")
    enc = Corpus("plain ok\nhas\x00nul\nalso fine").encoded
    assert not enc.needs_host[0]
    assert enc.needs_host[1]
    assert not enc.needs_host[2]


def test_bit_tiers_pad0_transparent_for_builtin_bank():
    """Byte 0 is stripped from every device byteset (NUL lines are
    needs_host), so both bit tiers must take the gate-free stepper —
    a regression here silently re-adds two [B, W] selects per byte."""
    from log_parser_tpu.ops.match import MatcherBanks
    from log_parser_tpu.patterns.bank import PatternBank
    from log_parser_tpu.patterns.builtin import load_builtin_pattern_sets

    # force the TPU tier shape on the CPU test backend: both bit tiers
    # are TPU-policy tiers now (CPU routes literals through the union)
    mb = MatcherBanks(
        PatternBank(load_builtin_pattern_sets()),
        bitglush_max_words=192,
        shiftor_min_columns=1,
    )
    assert mb.shiftor is not None and mb.shiftor.pad0_transparent
    assert mb.bitglush is not None and mb.bitglush.pad0_transparent
