"""Host-only column handling: the lenient literal prefilter (engine
runs host re only over AC-candidate lines), the literal-free slow path,
and lenient-parse widening semantics."""

from __future__ import annotations

import numpy as np
import pytest

from helpers import make_pattern, make_pattern_set
from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.golden import GoldenAnalyzer
from log_parser_tpu.models.pod import PodFailureData
from log_parser_tpu.patterns.regex.literals import extract_literals
from log_parser_tpu.patterns.regex.parser import (
    RegexUnsupportedError,
    parse_java_regex,
)
from log_parser_tpu.runtime import AnalysisEngine
from tests.test_engine_parity import assert_results_match


def _pair(patterns):
    from conftest import FakeClock

    sets = [make_pattern_set(patterns)]
    return (
        AnalysisEngine(sets, ScoringConfig(), clock=FakeClock()),
        GoldenAnalyzer(sets, ScoringConfig(), clock=FakeClock()),
    )


def test_lookbehind_column_prefiltered_and_exact():
    engine, golden = _pair(
        [
            make_pattern("lb", regex=r"(?<=refused )connection",
                         confidence=0.8, severity="HIGH"),
            make_pattern("ok", regex="OutOfMemoryError", confidence=0.9),
        ]
    )
    # the lookbehind column is host-only but literal-prefiltered
    assert engine._host_cols and engine._host_prefilter is not None
    assert engine._host_pref_cols and not engine._host_slow_cols
    logs = "\n".join(
        [
            "x refused connection now",   # matches
            "connection only",            # literal hit, lookbehind fails
            "refused connection",         # matches
            "nothing here",
            "java.lang.OutOfMemoryError",
        ]
        + ["filler %d ok" % i for i in range(40)]
    )
    data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=logs)
    assert_results_match(engine.analyze(data), golden.analyze(data))
    assert engine.fallback_count == 0


def test_backreference_column_prefiltered_and_exact():
    engine, golden = _pair(
        [make_pattern("br", regex=r"fatal (\w+) \1 loop", confidence=0.7)]
    )
    assert engine._host_pref_cols
    logs = "\n".join(
        [
            "fatal spin spin loop",   # matches
            "fatal spin whirl loop",  # literal hits, backref fails
            "benign line",
        ]
        + ["pad %d" % i for i in range(20)]
    )
    data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=logs)
    assert_results_match(engine.analyze(data), golden.analyze(data))


def test_literal_free_host_column_slow_path_exact():
    engine, golden = _pair(
        [make_pattern("dup", regex=r"(.)\1\1\1", confidence=0.6)]
    )
    assert engine._host_slow_cols and not engine._host_pref_cols
    logs = "aaaa here\nabab abab\nzzzz\nplain"
    data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=logs)
    assert_results_match(engine.analyze(data), golden.analyze(data))


def test_prefiltered_column_with_non_ascii_line():
    """needs_host lines are always candidates: a non-ASCII line whose
    device encoding could hide the literal still gets host-verified."""
    engine, golden = _pair(
        [make_pattern("lb", regex=r"(?<=é )connection", confidence=0.8)]
    )
    logs = "é connection\nplain connection\nx é connection y"
    data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=logs)
    assert_results_match(engine.analyze(data), golden.analyze(data))


def test_mixed_prefiltered_and_slow_host_columns():
    engine, golden = _pair(
        [
            make_pattern("lb", regex=r"(?<=at )FooService", confidence=0.8),
            make_pattern("dup", regex=r"(.)\1\1\1\1\1", confidence=0.6),
        ]
    )
    assert engine._host_pref_cols and engine._host_slow_cols
    logs = "\n".join(
        ["at FooService.run", "FooService alone", "xxxxxx run", "ok"]
    )
    data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=logs)
    assert_results_match(engine.analyze(data), golden.analyze(data))


# ---------------------------------------------------------- lenient parse


def test_lenient_parse_widens_and_extracts_literals():
    cases = {
        r"(?<=refused )connection": b"connection",
        r"(?=.*fatal)error": b"error",
        r"fatal (\w+) \1 loop": b"fatal ",
        r"a*+bcde": b"bcde",
        r"(?>abc)def": b"abcdef",
        r"\GFooBar": b"foobar",  # folded form
    }
    for rx, expected in cases.items():
        with pytest.raises(RegexUnsupportedError):
            parse_java_regex(rx, False)
        lits = extract_literals(parse_java_regex(rx, False, lenient=True))
        assert lits, rx
        folded = {lit.fold().text for lit in lits}
        assert any(expected in t or t in expected for t in folded), (rx, folded)


def test_lenient_parse_still_rejects_language_reshaping():
    for rx in [
        "(?x)a b  # comment",  # free-spacing retokenizes
        "(?iu)straße",         # unicode case folding
        "[a&&[b]]",            # class intersection
    ]:
        with pytest.raises(RegexUnsupportedError):
            parse_java_regex(rx, False, lenient=True)


def test_lenient_backref_is_widest():
    """The backref approximation must not constrain length or content."""
    node = parse_java_regex(r"x(\d+)y\1z", False, lenient=True)
    lits = extract_literals(node)
    texts = {lit.text for lit in lits} if lits else set()
    # x/y/z single-char runs; none may claim the backref's content
    assert texts and all(len(t) <= 2 for t in texts)


def test_nul_line_matches_exactly_via_host_override():
    """A content NUL routes the line to host re-match (encode flags it
    needs_host) so stripping byte 0 from device bytesets is invisible:
    engine results stay event-for-event equal to golden."""
    engine, golden = _pair(
        [
            make_pattern(
                "nul-neg", severity="HIGH",
                regex="fail[^ ]*ure", confidence=0.8,
            ),
            make_pattern(
                "nul-lit", severity="LOW", regex="tick", confidence=0.5,
            ),
        ]
    )
    logs = "\n".join(
        ["tick ok", "fail\x00ure mid-line nul", "failhardure", "tick end"]
    )
    data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=logs)
    assert_results_match(engine.analyze(data), golden.analyze(data))
