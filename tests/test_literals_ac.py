"""Literal-factor extraction soundness + Aho-Corasick correctness.

The prefilter contract: for every factorable regex and every line it
matches, at least one extracted literal must occur in the line (after case
folding for ci literals). Violations would silently drop matches."""

import random
import re

import numpy as np
import pytest

from log_parser_tpu.patterns.regex import extract_literals, parse_java_regex
from log_parser_tpu.patterns.regex.ac import AhoCorasick, fold_lines_u8
from tests.test_regex_dfa import REGEXES, random_lines


def get_literals(rx: str, ci: bool = False):
    return extract_literals(parse_java_regex(rx, ci))


class TestExtraction:
    def test_plain_literal(self):
        lits = get_literals(r"OutOfMemoryError")
        assert {l.text for l in lits} == {b"OutOfMemoryError"}

    def test_alternation_unions(self):
        lits = get_literals(r"\b(ERROR|FATAL|CRITICAL|SEVERE)\b")
        assert {l.text for l in lits} == {b"ERROR", b"FATAL", b"CRITICAL", b"SEVERE"}

    def test_star_prefix_keeps_suffix(self):
        lits = get_literals(r"\w*Exception")
        assert {l.text for l in lits} == {b"Exception"}

    def test_picks_longest_run(self):
        lits = get_literals(r"\d+ Connection refused \d+")
        assert {l.text for l in lits} == {b" Connection refused "}

    def test_ci_literal_folded(self):
        lits = get_literals(r"WARN", ci=True)
        (lit,) = lits
        assert lit.text == b"warn" and lit.ci

    def test_unfactorable(self):
        assert get_literals(r"\d+") is None
        assert get_literals(r"[a-z]+") is None
        assert get_literals(r".*") is None
        assert get_literals(r"(\d+|x)") is None  # one branch unfactorable

    def test_optional_contributes_nothing(self):
        # x? can be absent: 'abc' must come from the mandatory part
        lits = get_literals(r"x?abc")
        assert {l.text for l in lits} == {b"abc"}

    def test_soundness_on_corpus(self):
        """Every line matched by the regex contains an extracted literal."""
        for rx in REGEXES:
            lits = get_literals(rx)
            if lits is None:
                continue
            py = re.compile(rx, re.ASCII)
            for line in random_lines(hash(rx) % 2**32):
                if py.search(line):
                    blob = line.encode()
                    folded = blob.lower()
                    assert any(
                        (l.text in folded) if l.ci else (l.text in blob)
                        for l in lits
                    ), f"{rx!r} matched {line!r} but no literal present"


class TestAhoCorasick:
    def test_basic_hits(self):
        ac = AhoCorasick([b"ERROR", b"WARN", b"Exception"])
        assert ac.scan(b"an ERROR and an Exception") == {0, 2}
        assert ac.scan(b"nothing") == set()

    def test_overlapping_and_nested(self):
        ac = AhoCorasick([b"he", b"she", b"hers", b"her"])
        assert ac.scan(b"ushers") == {0, 1, 2, 3}

    def test_substring_literal(self):
        ac = AhoCorasick([b"abcd", b"bc"])
        assert ac.scan(b"xabcdy") == {0, 1}

    def test_vectorized_matches_scalar(self):
        rng = random.Random(7)
        lits = [b"err", b"warning", b"at ", b"OOM", b"refused", b"a"]
        ac = AhoCorasick(lits)
        lines = [
            bytes(rng.choice(b"aerwOMt niofug") for _ in range(rng.randrange(30)))
            for _ in range(100)
        ]
        T = max((len(l) for l in lines), default=1) or 1
        mat = np.zeros((len(lines), T), dtype=np.uint8)
        lengths = np.zeros(len(lines), dtype=np.int32)
        for i, l in enumerate(lines):
            mat[i, : len(l)] = np.frombuffer(l, dtype=np.uint8)
            lengths[i] = len(l)
        masks = ac.scan_lines(mat, lengths)
        for i, l in enumerate(lines):
            want = ac.scan(l)
            got = {
                w * 32 + b
                for w in range(ac.n_words)
                for b in range(32)
                if int(masks[i, w]) >> b & 1
            }
            assert got == want, f"line {i}: {l!r}"

    def test_padding_never_hits(self):
        ac = AhoCorasick([b"\x00\x00"])  # pathological: NUL literal
        mat = np.zeros((1, 8), dtype=np.uint8)
        lengths = np.array([0], dtype=np.int32)
        assert ac.scan_lines(mat, lengths)[0, 0] == 0

    def test_fold_lines_u8(self):
        raw = np.frombuffer(b"MiXeD 42!", dtype=np.uint8)[None, :]
        folded = fold_lines_u8(raw)
        assert bytes(folded[0]) == b"mixed 42!"

    def test_many_literals_multiword_masks(self):
        lits = [f"lit{i:04d}".encode() for i in range(100)]
        ac = AhoCorasick(lits)
        assert ac.n_words == 4
        assert ac.scan(b"xx lit0042 yy lit0099") == {42, 99}
