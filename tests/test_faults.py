"""Fault-injection subsystem (runtime/faults.py): DSL parsing, seeded
determinism, trigger bookkeeping, and the behavior of injected faults at
each pipeline point — device faults degrade to the golden host path, every
other site propagates like the logic bug it simulates."""

from __future__ import annotations

import pytest

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.models import PodFailureData
from log_parser_tpu.runtime import AnalysisEngine, faults
from log_parser_tpu.runtime.engine import is_device_error
from log_parser_tpu.runtime.faults import (
    FaultRegistry,
    FaultSpecError,
    InjectedDeviceFault,
    InjectedFault,
    parse_spec,
)

from conftest import FakeClock
from helpers import make_pattern, make_pattern_set

pytestmark = pytest.mark.chaos

LOGS = "ok\nERROR boom\nok\nERROR again"


def _sets():
    return [make_pattern_set([make_pattern("e", regex="ERROR", confidence=0.7)])]


@pytest.fixture(autouse=True)
def clean_registry():
    """Every test starts and ends with no registry installed; teardown
    lifts any hangs so no injected waiter outlives its test."""
    faults.install(None)
    yield
    faults.install(None)


class TestDSL:
    def test_parse_full_grammar(self):
        spec = parse_spec("device_hang:2.5@after=3@times=1@p=0.5")
        assert spec.site == "device" and spec.action == "hang"
        assert spec.arg == 2.5 and spec.after == 3 and spec.times == 1
        assert spec.p == 0.5

    def test_raise_arg_is_probability(self):
        assert parse_spec("ingest_raise:0.25").p == 0.25
        assert parse_spec("ingest_raise").p == 1.0

    def test_multi_underscore_site(self):
        spec = parse_spec("http_body_raise")
        assert spec.site == "http_body" and spec.action == "raise"

    @pytest.mark.parametrize(
        "bad",
        [
            "device",  # no action
            "device_explode",  # unknown action
            "_raise",  # empty site
            "device_raise:2.0",  # probability out of range
            "device_hang:-1",  # negative delay
            "device_hang:2@nope=1",  # unknown modifier
            "device_hang:2@after=x",  # non-integer modifier
            "device_raise@p=0",  # p out of range
        ],
    )
    def test_rejects_malformed(self, bad):
        with pytest.raises(FaultSpecError):
            parse_spec(bad)

    def test_from_env(self):
        reg = FaultRegistry.from_env(
            {faults.ENV_SPECS: "device_raise, shim_raise:0.5", faults.ENV_SEED: "11"}
        )
        assert [s.point for s in reg.specs] == ["device_raise", "shim_raise"]
        assert reg.seed == 11
        assert FaultRegistry.from_env({}) is None


class TestRegistry:
    def test_after_and_times_window(self):
        reg = FaultRegistry.parse("device_raise@after=2@times=2")
        outcomes = []
        for _ in range(6):
            try:
                reg.fire("device")
                outcomes.append("ok")
            except InjectedDeviceFault:
                outcomes.append("boom")
        # evaluations 1-2 skipped, 3-4 injected, 5-6 exhausted
        assert outcomes == ["ok", "ok", "boom", "boom", "ok", "ok"]
        assert reg.counts() == {"device_raise": 2}
        assert reg.stats()["calls"] == {"device_raise": 6}

    def test_seeded_probability_is_reproducible(self):
        def run(seed):
            reg = FaultRegistry.parse("shim_raise:0.5", seed=seed)
            out = []
            for _ in range(32):
                try:
                    reg.fire("shim")
                    out.append(0)
                except InjectedFault:
                    out.append(1)
            return out

        a, b = run(7), run(7)
        assert a == b and 0 < sum(a) < 32  # same seed, same decisions
        assert run(8) != a  # different seed, different sequence

    def test_lift_releases_hangs_and_disables(self):
        import threading
        import time

        reg = FaultRegistry.parse("device_hang:inf")
        t0 = time.monotonic()
        hung = threading.Thread(target=lambda: reg.fire("device"))
        hung.start()
        hung.join(0.05)
        assert hung.is_alive()  # parked on the release event
        reg.lift("device_hang")
        hung.join(5)
        assert not hung.is_alive()
        reg.fire("device")  # lifted: no longer injects
        assert time.monotonic() - t0 < 5
        assert reg.counts() == {"device_hang": 1}

    def test_unknown_site_is_noop(self):
        reg = FaultRegistry.parse("device_raise")
        reg.fire("ingest")
        assert reg.counts() == {"device_raise": 0}

    def test_module_fire_without_registry_is_noop(self):
        faults.fire("device")
        assert faults.stats() is None


class TestEngineIntegration:
    def test_injected_device_fault_degrades_to_golden(self):
        """A device_raise fault is a device error: the golden host path
        serves the request, the fallback counter moves."""
        faults.install(FaultRegistry.parse("device_raise@times=1"))
        engine = AnalysisEngine(_sets(), ScoringConfig(), clock=FakeClock())
        engine.fallback_to_golden = True
        data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=LOGS)
        result = engine.analyze(data)
        assert len(result.events) == 2
        assert engine.fallback_count == 1
        # injection exhausted: the next request runs on the device
        engine.analyze(data)
        assert engine.fallback_count == 1
        assert faults.active().counts() == {"device_raise": 1}

    def test_injected_ingest_fault_propagates(self):
        """Non-device faults simulate logic bugs: never masked by the
        fallback, exactly like is_device_error demands of the real thing."""
        faults.install(FaultRegistry.parse("ingest_raise@times=1"))
        engine = AnalysisEngine(_sets(), ScoringConfig(), clock=FakeClock())
        engine.fallback_to_golden = True
        data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=LOGS)
        with pytest.raises(InjectedFault):
            engine.analyze(data)
        assert engine.fallback_count == 0

    def test_injected_finalize_fault_rolls_back_frequency(self):
        faults.install(FaultRegistry.parse("finalize_raise@times=1"))
        engine = AnalysisEngine(_sets(), ScoringConfig(), clock=FakeClock())
        data = PodFailureData(pod={"metadata": {"name": "p"}}, logs=LOGS)
        with pytest.raises(InjectedFault):
            engine.analyze(data)
        assert engine.frequency.get_frequency_statistics() == {}
        engine.analyze(data)  # exhausted: clean request works
        assert engine.frequency.get_frequency_statistics() == {"e": 2}

    def test_classification(self):
        assert is_device_error(InjectedDeviceFault("device_raise", 1))
        assert not is_device_error(InjectedFault("ingest_raise", 1))

    def test_injected_broadcast_fault(self):
        """The distributed broadcast fires its chaos point before the
        first collective, so a single-process call trips it too."""
        from log_parser_tpu.parallel.distributed import broadcast_bytes

        faults.install(FaultRegistry.parse("broadcast_raise@times=1"))
        with pytest.raises(InjectedFault):
            broadcast_bytes(b"payload")
