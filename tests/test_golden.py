"""Golden reference engine: hand-computed scores and reference quirks.

These tests pin the exact JVM semantics (SURVEY.md §3) that every TPU kernel
is later property-tested against.
"""

import math

import pytest

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.golden import GoldenAnalyzer
from log_parser_tpu.models.pod import PodFailureData
from tests.conftest import FakeClock
from tests.helpers import make_pattern, make_pattern_set


def analyze(patterns, logs, config=None, clock=None, library_id="lib1"):
    analyzer = GoldenAnalyzer(
        [make_pattern_set(patterns, library_id)],
        config or ScoringConfig(),
        clock=clock or FakeClock(),
    )
    return analyzer.analyze(PodFailureData(pod={"metadata": {"name": "p"}}, logs=logs))


class TestHandComputedScore:
    def test_full_formula(self):
        """Hand-computed end-to-end score on a 20-line log.

        Primary at index 2 (pos 0.1 -> chrono 2.0), HIGH (3.0), confidence
        0.8, one secondary at distance 3 (weight 0.6, decay 10), context
        window +-1 line containing one WARN (+0.2) and the OOM line itself
        (\\w*Error -> +0.3), no sequences, fresh frequency state.
        """
        lines = ["line %d ok" % i for i in range(20)]
        lines[1] = "INFO starting app"
        lines[2] = "java.lang.OutOfMemoryError: Java heap space"
        lines[3] = "WARN low memory"
        lines[5] = "detected memory pressure in cgroup"
        pattern = make_pattern(
            "oom",
            regex="OutOfMemoryError",
            confidence=0.8,
            severity="HIGH",
            secondaries=[("memory pressure", 0.6, 10)],
            context=(1, 1),
        )
        result = analyze([pattern], "\n".join(lines))
        assert len(result.events) == 1
        event = result.events[0]
        assert event.line_number == 3
        expected = 0.8 * 3.0 * 2.0 * (1.0 + 0.6 * math.exp(-0.3)) * 1.0 * 1.5 * 1.0
        assert event.score == pytest.approx(expected, abs=1e-12)

    def test_no_factors(self):
        """One INFO match mid-log, no secondaries/sequences/context rules.

        Context still includes the matched line itself (EventContext always
        carries matchedLine, AnalysisService.java:135)."""
        lines = ["x"] * 10
        lines[6] = "some ERROR here"
        pattern = make_pattern("e", regex="ERROR", confidence=0.5, severity="INFO")
        result = analyze([pattern], "\n".join(lines))
        event = result.events[0]
        # pos = 6/10 = 0.6 > 0.5 -> late zone: 0.5 + (1 - 0.6) = 0.9
        # context: matched line has "ERROR" -> +0.4 -> factor 1.4
        expected = 0.5 * 1.0 * 0.9 * 1.0 * 1.0 * 1.4 * 1.0
        assert event.score == pytest.approx(expected, abs=1e-12)


class TestChronologicalZones:
    @pytest.mark.parametrize(
        "idx,total,expected",
        [
            (0, 100, 1.5 + 0.2 * (1.0 / 0.2)),  # pos 0 -> max early bonus 2.5
            (20, 100, 1.5),  # pos exactly 0.2 -> boundary of early zone
            (35, 100, 1.0 + 0.15 * (0.5 / 0.3)),  # middle zone
            (50, 100, 1.0),  # pos exactly 0.5 -> boundary of middle zone
            (75, 100, 0.5 + 0.25),  # late zone
            (99, 100, 0.5 + 0.01),
        ],
    )
    def test_zone(self, idx, total, expected):
        lines = ["x"] * total
        lines[idx] = "MATCHME"
        result = analyze([make_pattern("c", regex="MATCHME", confidence=1.0, severity="INFO")],
                         "\n".join(lines))
        # isolate chronological: no context rules -> context factor from the
        # matched line only ("MATCHME" hits nothing) -> 1.0
        assert result.events[0].score == pytest.approx(expected, abs=1e-12)


class TestProximity:
    def test_window_clamped_by_max_window(self):
        """Secondary just outside min(max_window, proximity_window) is ignored."""
        lines = ["x"] * 300
        lines[0] = "PRIMARY"
        lines[150] = "SECONDARY"
        pattern = make_pattern(
            "p", regex="PRIMARY", confidence=1.0, severity="INFO",
            secondaries=[("SECONDARY", 1.0, 500)],
        )
        result = analyze([pattern], "\n".join(lines))
        assert result.events[0].score == pytest.approx(2.5 * 1.0, abs=1e-12)  # no bonus

    def test_closest_of_multiple(self):
        lines = ["x"] * 50
        lines[10] = "PRIMARY"
        lines[5] = "SEC"
        lines[12] = "SEC"
        pattern = make_pattern(
            "p", regex="PRIMARY", confidence=1.0, severity="INFO",
            secondaries=[("SEC", 0.5, 30)],
        )
        result = analyze([pattern], "\n".join(lines))
        chrono = 1.5 + (0.2 - 0.2) * (1.0 / 0.2)  # pos = 10/50 = 0.2 exactly
        expected = chrono * (1.0 + 0.5 * math.exp(-2 / 10.0))
        assert result.events[0].score == pytest.approx(expected, abs=1e-12)

    def test_primary_line_excluded(self):
        """A secondary that only matches the primary line itself is not found
        (ScoringService.java:326-328)."""
        lines = ["x"] * 10
        lines[2] = "PRIMARY with SEC embedded"
        pattern = make_pattern(
            "p", regex="PRIMARY", confidence=1.0, severity="INFO",
            secondaries=[("SEC", 1.0, 5)],
        )
        result = analyze([pattern], "\n".join(lines))
        chrono = 1.5 + (0.2 - 0.2) * (1.0 / 0.2)
        assert result.events[0].score == pytest.approx(chrono * 1.0, abs=1e-12)


class TestTemporal:
    def test_sequence_matched_backward(self):
        lines = ["x"] * 40
        lines[5] = "connection lost"
        lines[12] = "retry attempt"
        lines[20] = "FAILURE final"
        pattern = make_pattern(
            "s", regex="FAILURE", confidence=1.0, severity="INFO",
            sequences=[(0.5, ["connection lost", "retry attempt", "FAILURE"])],
        )
        result = analyze([pattern], "\n".join(lines))
        # pos 20/40 = 0.5 -> middle-zone boundary -> 1.0; temporal 1.5
        assert result.events[0].score == pytest.approx(1.0 * 1.5, abs=1e-12)

    def test_sequence_order_violated(self):
        lines = ["x"] * 40
        lines[12] = "connection lost"  # events out of order
        lines[5] = "retry attempt"
        lines[20] = "FAILURE final"
        pattern = make_pattern(
            "s", regex="FAILURE", confidence=1.0, severity="INFO",
            sequences=[(0.5, ["connection lost", "retry attempt", "FAILURE"])],
        )
        result = analyze([pattern], "\n".join(lines))
        assert result.events[0].score == pytest.approx(1.0, abs=1e-12)

    def test_last_event_must_be_near_primary(self):
        lines = ["x"] * 40
        lines[2] = "first thing"
        lines[30] = "last thing"  # > 5 lines from primary at 20
        lines[20] = "FAILURE"
        pattern = make_pattern(
            "s", regex="FAILURE", confidence=1.0, severity="INFO",
            sequences=[(0.5, ["first thing", "last thing"])],
        )
        result = analyze([pattern], "\n".join(lines))
        assert result.events[0].score == pytest.approx(1.0, abs=1e-12)

    def test_search_resets_to_primary_not_match_site(self):
        """Quirk: after the near-window check, the backward search starts at
        the *primary* line, not where the last event matched
        (ScoringService.java:250). An earlier event between the last event's
        match site and the primary still counts."""
        lines = ["x"] * 40
        lines[20] = "FAILURE"
        lines[23] = "last thing"  # within +5 of primary
        lines[19] = "first thing"  # before primary (the search start), after nothing
        pattern = make_pattern(
            "s", regex="FAILURE", confidence=1.0, severity="INFO",
            sequences=[(0.5, ["first thing", "last thing"])],
        )
        result = analyze([pattern], "\n".join(lines))
        assert result.events[0].score == pytest.approx(1.5, abs=1e-12)


class TestContextFactor:
    def test_else_if_warn_shadowed_by_error(self):
        """A line matching ERROR and WARN counts only the error branch."""
        lines = ["x"] * 10
        lines[5] = "MATCHME"
        lines[4] = "ERROR and WARN together"
        pattern = make_pattern("c", regex="MATCHME", confidence=1.0, severity="INFO",
                               context=(1, 0))
        result = analyze([pattern], "\n".join(lines))
        # pos 0.5 -> chrono 1.0; context: line4 -> error +0.4 only
        assert result.events[0].score == pytest.approx(1.4, abs=1e-12)

    def test_stack_trace_double_bonus_capped(self):
        lines = ["x"] * 30
        lines[15] = "MATCHME"
        for i in range(16, 24):
            lines[i] = "    at com.example.Foo$Bar.baz(Foo.java:42)"
        pattern = make_pattern("c", regex="MATCHME", confidence=1.0, severity="INFO",
                               context=(0, 8))
        config = ScoringConfig(context_max_context_factor=10.0)  # uncap to see raw score
        result = analyze([pattern], "\n".join(lines), config=config)
        # 8 stack lines: 8*0.1 per-line + min(8*0.1, 0.5) bonus = 0.8 + 0.5
        # pos 0.5 -> chrono 1.0; 9 context lines -> no density penalty (needs >10)
        assert result.events[0].score == pytest.approx(1.0 + 1.3, abs=1e-9)

    def test_density_penalty(self):
        lines = ["x"] * 40
        lines[20] = "MATCHME ERROR"
        for i in range(10, 20):
            lines[i] = "ERROR cascading failure"
        pattern = make_pattern("c", regex="MATCHME", confidence=1.0, severity="INFO",
                               context=(10, 0))
        config = ScoringConfig(context_max_context_factor=100.0)
        result = analyze([pattern], "\n".join(lines), config=config)
        # 11 context lines, 11 error lines -> 11*0.4 = 4.4, dense -> *0.8 = 3.52
        assert result.events[0].score == pytest.approx(1.0 * (1.0 + 3.52), abs=1e-9)

    def test_cap(self):
        lines = ["x"] * 40
        lines[20] = "MATCHME ERROR"
        for i in range(15, 20):
            lines[i] = "ERROR bad"
        pattern = make_pattern("c", regex="MATCHME", confidence=1.0, severity="INFO",
                               context=(5, 0))
        result = analyze([pattern], "\n".join(lines))
        # raw context = 6*0.4 = 2.4 -> factor 3.4 capped at 2.5
        assert result.events[0].score == pytest.approx(2.5, abs=1e-12)


class TestFrequencyPenalty:
    def test_read_before_record_within_request(self):
        """With threshold 2/hour, the Nth match of a pattern sees N-1 prior
        counts: matches 1-3 get no penalty (rates 0,1,2), match 4 sees rate 3
        -> penalty min(0.8, (3-2)/2) = 0.5."""
        config = ScoringConfig(frequency_threshold=2.0)
        lines = ["REPEAT oops"] * 4 + ["x"] * 4
        pattern = make_pattern("r", regex="REPEAT", confidence=1.0, severity="INFO")
        result = analyze([pattern], "\n".join(lines), config=config)
        scores = [e.score for e in result.events]

        def chrono_at(pos):
            if pos <= 0.2:
                return 1.5 + (0.2 - pos) * (1.0 / 0.2)
            return 1.0 + (0.5 - pos) * (0.5 / 0.3)

        chrono = [chrono_at(i / 8) for i in range(4)]
        penalties = [0.0, 0.0, 0.0, 0.5]
        for s, c, p in zip(scores, chrono, penalties):
            assert s == pytest.approx(c * (1.0 - p), abs=1e-12)

    def test_state_persists_across_requests(self, fake_clock):
        config = ScoringConfig(frequency_threshold=1.0, frequency_max_penalty=0.8)
        pattern = make_pattern("r", regex="REPEAT", confidence=1.0, severity="INFO")
        analyzer = GoldenAnalyzer([make_pattern_set([pattern])], config, clock=fake_clock)
        data = PodFailureData(pod={"metadata": {"name": "p"}}, logs="REPEAT\nfiller")
        first = analyzer.analyze(data).events[0].score
        second = analyzer.analyze(data).events[0].score
        third = analyzer.analyze(data).events[0].score
        # request 2 sees count 1 -> rate 1.0 <= threshold 1.0 -> penalty 0;
        # request 3 sees count 2 -> rate 2.0 -> penalty min(0.8, 1.0) = 0.8
        assert second == pytest.approx(first, abs=1e-12)
        assert third == pytest.approx(first * (1.0 - 0.8), rel=1e-9)

    def test_window_expiry(self, fake_clock):
        config = ScoringConfig(frequency_threshold=1.0)
        pattern = make_pattern("r", regex="REPEAT", confidence=1.0, severity="INFO")
        analyzer = GoldenAnalyzer([make_pattern_set([pattern])], config, clock=fake_clock)
        data = PodFailureData(pod={"metadata": {"name": "p"}}, logs="REPEAT\nfiller")
        for _ in range(5):
            analyzer.analyze(data)
        fake_clock.advance(3601.0)
        result = analyzer.analyze(data)
        # all prior timestamps expired -> same score as a fresh analyzer
        fresh = GoldenAnalyzer([make_pattern_set([pattern])], config, clock=FakeClock())
        assert result.events[0].score == pytest.approx(
            fresh.analyze(data).events[0].score, abs=1e-12
        )


class TestJavaFloatCorners:
    def test_zero_window_hours_is_max_penalty_not_crash(self):
        """frequency_time_window_hours=0: Java computes count/0.0 = Infinity
        -> rate > threshold -> penalty = min(maxPenalty, Inf) = maxPenalty.
        Must not raise ZeroDivisionError."""
        config = ScoringConfig(frequency_time_window_hours=0)
        lines = ["REPEAT a", "REPEAT b", "filler", "filler"]
        pattern = make_pattern("r", regex="REPEAT", confidence=1.0, severity="INFO")
        result = analyze([pattern], "\n".join(lines), config=config)
        # NOTE: with a zero window every timestamp expires instantly, so the
        # second match sees count 0 -> 0/0.0 = NaN in Java -> NaN comparisons
        # false -> penalty Math.min(maxPenalty, NaN) = NaN -> score NaN.
        assert len(result.events) == 2
        assert math.isnan(result.events[1].score)

    def test_zero_early_bonus_threshold(self):
        """chronological_early_bonus_threshold=0 with a match at position 0:
        Java computes bonusRange/0.0 = Infinity -> 0*Inf = NaN score, and
        keeps serving — must not raise ZeroDivisionError."""
        config = ScoringConfig(chronological_early_bonus_threshold=0.0)
        pattern = make_pattern("p", regex="X", confidence=1.0, severity="INFO")
        result = analyze([pattern], "X\nfiller\nfiller\nfiller", config=config)
        # position 0.0 <= 0.0 -> early branch -> 1.5 + (0-0)*Inf = 1.5 + NaN? No:
        # (0.0 - 0.0) * Inf = NaN in Java -> score NaN
        assert math.isnan(result.events[0].score)

    def test_zero_threshold(self):
        """threshold=0: rate > 0 -> excess/0.0 = Infinity -> penalty capped."""
        config = ScoringConfig(frequency_threshold=0.0)
        lines = ["REPEAT a", "REPEAT b", "filler", "filler"]
        pattern = make_pattern("r", regex="REPEAT", confidence=1.0, severity="INFO")
        result = analyze([pattern], "\n".join(lines), config=config)
        first, second = (e.score for e in result.events)
        # first match: no frequency entry yet -> penalty 0
        assert first == pytest.approx(2.5, abs=1e-12)
        # second match: rate 1 > 0 -> penalty min(0.8, inf) = 0.8;
        # pos 1/4 -> middle zone chrono 1 + 0.25*(0.5/0.3)
        assert second == pytest.approx((1.0 + (0.5 - 0.25) * (0.5 / 0.3)) * 0.2, rel=1e-9)


class TestSummaryAndMetadata:
    def test_discovery_order_not_sorted(self):
        """Events come back line-major then pattern order — never score-sorted
        (docs claim sorted, code does not: SURVEY.md §3.4)."""
        lines = ["x"] * 10
        lines[1] = "LOWSEV"   # early -> high chrono factor
        lines[8] = "HIGHSEV"  # late -> low chrono factor
        patterns = [
            make_pattern("a", regex="HIGHSEV", confidence=1.0, severity="CRITICAL"),
            make_pattern("b", regex="LOWSEV", confidence=0.1, severity="INFO"),
        ]
        result = analyze(patterns, "\n".join(lines))
        assert [e.matched_pattern.id for e in result.events] == ["b", "a"]
        assert result.events[0].score < result.events[1].score  # proves unsorted

    def test_severity_distribution_and_highest(self):
        lines = ["CRIT_A", "HIGH_B", "HIGH_B", "x"]
        patterns = [
            make_pattern("a", regex="CRIT_A", severity="critical"),
            make_pattern("b", regex="HIGH_B", severity="High"),
        ]
        result = analyze(patterns, "\n".join(lines))
        assert result.summary.severity_distribution == {"CRITICAL": 1, "HIGH": 2}
        assert result.summary.highest_severity == "CRITICAL"
        assert result.summary.significant_events == 3

    def test_unknown_severity_ranks_below_info(self):
        lines = ["WEIRD_X", "INFO_Y"]
        patterns = [
            make_pattern("w", regex="WEIRD_X", severity="BOGUS"),
            make_pattern("i", regex="INFO_Y", severity="INFO"),
        ]
        result = analyze(patterns, "\n".join(lines))
        assert result.summary.highest_severity == "INFO"

    def test_empty_events(self):
        result = analyze([make_pattern("a", regex="NOPE")], "nothing here")
        assert result.summary.significant_events == 0
        assert result.summary.highest_severity == "NONE"
        assert result.summary.severity_distribution == {}

    def test_metadata(self):
        result = analyze([make_pattern("a", regex="NOPE")], "a\nb\nc\n",
                         library_id="mylib")
        assert result.metadata.total_lines == 3
        assert result.metadata.patterns_used == ["mylib"]
        assert result.analysis_id


class TestPatternContainment:
    def test_untranslatable_pattern_skipped_not_fatal(self):
        """One possessive-quantifier pattern must not take down the library."""
        patterns = [
            make_pattern("bad", regex=r"a*+b", confidence=1.0, severity="HIGH"),
            make_pattern("good", regex="ERROR", confidence=1.0, severity="INFO"),
        ]
        analyzer = GoldenAnalyzer([make_pattern_set(patterns)], ScoringConfig(),
                                  clock=FakeClock())
        assert [pid for pid, _ in analyzer.skipped_patterns] == ["bad"]
        result = analyzer.analyze(
            PodFailureData(pod={"metadata": {"name": "p"}}, logs="an ERROR here")
        )
        assert [e.matched_pattern.id for e in result.events] == ["good"]

    def test_bad_secondary_skips_whole_pattern(self):
        patterns = [
            make_pattern("p", regex="ERROR", secondaries=[(r"(?>x)", 0.5, 10)]),
        ]
        analyzer = GoldenAnalyzer([make_pattern_set(patterns)], ScoringConfig())
        assert len(analyzer.skipped_patterns) == 1
        result = analyzer.analyze(
            PodFailureData(pod={"metadata": {"name": "p"}}, logs="an ERROR here")
        )
        assert result.events == []
