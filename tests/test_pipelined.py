"""Concurrency correctness of the pipelined serving path.

``analyze_pipelined`` runs ingest + device execution outside the engine
``state_lock`` so consecutive requests overlap; only the
frequency-coupled finish phase serializes. These tests pin the two
invariants that split makes fragile: no lost frequency updates under
concurrent clients, and per-request results identical to the serial
path (the reference instead data-races its shared frequency map —
FrequencyTrackingService.java:25 — and mutates shared compiled-pattern
slots per request, SURVEY.md §5.2)."""

from __future__ import annotations

import threading

from helpers import make_pattern, make_pattern_set

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.models import PodFailureData
from log_parser_tpu.runtime import AnalysisEngine


def _engine() -> AnalysisEngine:
    patterns = [
        make_pattern("oom", regex="OutOfMemoryError", confidence=0.9,
                     severity="CRITICAL"),
        make_pattern("conn", regex="Connection refused", confidence=0.7,
                     severity="HIGH"),
    ]
    return AnalysisEngine([make_pattern_set(patterns)], ScoringConfig())


def _req(i: int) -> PodFailureData:
    logs = "\n".join(
        ["INFO tick ok"] * 3
        + ["java.lang.OutOfMemoryError: heap", "dial: Connection refused"]
    )
    return PodFailureData(pod={"metadata": {"name": f"p{i}"}}, logs=logs)


def test_no_lost_frequency_updates_under_concurrency():
    engine = _engine()
    n_threads, per_thread = 8, 6
    errors: list[BaseException] = []

    def client(t: int) -> None:
        try:
            for j in range(per_thread):
                r = engine.analyze_pipelined(_req(t * per_thread + j))
                assert r.summary.significant_events == 2
        except BaseException as exc:  # pragma: no cover - surfaced below
            errors.append(exc)

    threads = [threading.Thread(target=client, args=(t,)) for t in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errors, errors

    # every request recorded exactly one match per pattern: any lost
    # update (torn read-modify-write across the lock split) shows here
    total = n_threads * per_thread
    counts = engine.frequency.get_frequency_statistics()
    assert counts == {"oom": total, "conn": total}


def test_pipelined_result_matches_serial_engine():
    """A pipelined request stream produces the same per-request events
    and scores as the plain serial path on a fresh engine."""
    pipelined, serial = _engine(), _engine()
    for i in range(5):
        a = pipelined.analyze_pipelined(_req(i))
        b = serial.analyze(_req(i))
        assert [
            (e.line_number, e.matched_pattern.id, e.score) for e in a.events
        ] == [(e.line_number, e.matched_pattern.id, e.score) for e in b.events]
