"""Cross-request micro-batching (runtime/batcher.py).

The contract under test: coalescing concurrent requests into one padded
device batch changes THROUGHPUT, never semantics — batched scores equal
unbatched scores exactly (integer device math + vmap adds no arithmetic),
the frequency stream evolves as if the requests had arrived serially in
enqueue order, and failures stay contained to their own demux slot.

Tests drive the batcher through ``_enqueue`` (non-blocking) so enqueue
order — and therefore batch composition — is deterministic; ``submit``
is the same path plus a blocking wait.
"""

from __future__ import annotations

import threading
import time

import pytest

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.models.pod import PodFailureData
from log_parser_tpu.runtime import AnalysisEngine, faults
from log_parser_tpu.runtime.faults import FaultRegistry, InjectedFault
from log_parser_tpu.serve.admission import AdmissionController
from log_parser_tpu.utils.trace import PhaseTrace

from helpers import make_pattern, make_pattern_set


@pytest.fixture(autouse=True)
def clean_registry():
    faults.install(None)
    yield
    faults.install(None)


def _sets():
    return [
        make_pattern_set(
            [
                make_pattern(
                    "oom",
                    regex="OutOfMemoryError",
                    confidence=0.9,
                    severity="CRITICAL",
                    secondaries=[("GC overhead", 0.3, 10)],
                    sequences=[(1.5, ["Full GC", "OutOfMemoryError"])],
                    context=(1, 1),
                ),
                make_pattern("conn", regex="Connection refused", confidence=0.7),
                make_pattern("fatal", regex="FATAL", confidence=0.8),
            ]
        )
    ]


def _pod(lines: list[str]) -> PodFailureData:
    return PodFailureData(
        pod={"metadata": {"name": "batch"}}, logs="\n".join(lines)
    )


# four corpora with DIFFERENT line counts that share one row bucket
# (3-7 lines all pad to the same min-rows floor), exercising per-request
# n_lines masks inside one batch
MIXED = [
    _pod(["INFO a", "Full GC", "java OutOfMemoryError here"]),
    _pod(["GC overhead", "INFO b", "OutOfMemoryError", "INFO c", "INFO d"]),
    _pod(["dial tcp: Connection refused", "INFO", "INFO", "INFO", "INFO", "INFO"]),
    _pod(
        ["INFO"] * 5
        + ["Full GC", "OutOfMemoryError boom"]
    ),
]


def _events(result):
    return [
        (e.line_number, e.matched_pattern.id, e.score) for e in result.events
    ]


def _batched_engine(wait_ms=5000.0, batch_max=4):
    engine = AnalysisEngine(_sets(), ScoringConfig())
    engine.enable_batching(wait_ms=wait_ms, batch_max=batch_max)
    return engine


def _drain(pendings, timeout=60.0):
    for p in pendings:
        assert p.done.wait(timeout), "batched request never resolved"


def test_batched_parity_mixed_sizes():
    """One full batch of mixed-size corpora == the same stream served
    serially by an unbatched engine — exact equality, not a tolerance."""
    serial = AnalysisEngine(_sets(), ScoringConfig())
    expected = [_events(serial.analyze_pipelined(d)) for d in MIXED]

    engine = _batched_engine(batch_max=len(MIXED))
    try:
        pend = [engine.batcher._enqueue(d, None) for d in MIXED]
        _drain(pend)
        for p, want in zip(pend, expected):
            assert p.error is None
            assert _events(p.result) == want  # scores bit-identical
        stats = engine.batcher.stats()
        assert stats["batchesFlushed"] == 1
        assert stats["lastBatchSize"] == len(MIXED)
        assert stats["flushFull"] == 1
        assert engine.fallback_count == 0
    finally:
        engine.batcher.close()


def test_bucket_selection_separates_row_buckets():
    """Corpora whose line counts pad to different row rungs never share a
    batch; each bucket fills and flushes independently."""
    small = [_pod(["ERROR", "Connection refused x"]), _pod(["Connection refused y"])]
    large = [
        _pod(["INFO"] * 79 + ["FATAL disk"]),
        _pod(["FATAL net"] + ["INFO"] * 79),
    ]
    engine = _batched_engine(batch_max=2)
    try:
        # interleave buckets on purpose: small, large, small, large
        pend = [
            engine.batcher._enqueue(d, None)
            for d in (small[0], large[0], small[1], large[1])
        ]
        _drain(pend)
        for p in pend:
            assert p.error is None
        assert [e[1] for e in _events(pend[0].result)] == ["conn"]
        assert [e[1] for e in _events(pend[1].result)] == ["fatal"]
        stats = engine.batcher.stats()
        # two FULL flushes of size 2 — never one batch of four
        assert stats["batchesFlushed"] == 2
        assert stats["maxBatchSeen"] == 2
        assert stats["flushFull"] == 2
    finally:
        engine.batcher.close()


def test_deadline_triggered_flush():
    """An admission deadline pulls the flush long before the coalescing
    window (wait_ms=5000) would close."""
    engine = _batched_engine(wait_ms=5000.0, batch_max=8)
    try:
        t0 = time.monotonic()
        p = engine.batcher._enqueue(MIXED[0], 80.0)
        assert p.done.wait(30)
        assert p.error is None and p.result is not None
        assert time.monotonic() - t0 < 5.0, "flush waited out the window"
        assert engine.batcher.stats()["flushDeadline"] >= 1
    finally:
        engine.batcher.close()


def test_wait_triggered_flush():
    """No batchmates and no deadline: the bucket flushes when the oldest
    entry has waited wait_ms."""
    engine = _batched_engine(wait_ms=30.0, batch_max=8)
    try:
        p = engine.batcher._enqueue(MIXED[0], None)
        assert p.done.wait(30)
        assert p.error is None
        stats = engine.batcher.stats()
        assert stats["flushWait"] >= 1
        assert stats["lastBatchSize"] == 1
    finally:
        engine.batcher.close()


def test_demux_fault_isolated_per_request():
    """A dropped demux slot fails exactly ONE request; its batchmates
    resolve normally (per-request containment)."""
    engine = _batched_engine(batch_max=len(MIXED))
    try:
        faults.install(FaultRegistry.parse("batcher_demux_raise@times=1"))
        pend = [engine.batcher._enqueue(d, None) for d in MIXED]
        _drain(pend)
        assert isinstance(pend[0].error, InjectedFault)
        for p in pend[1:]:
            assert p.error is None and p.result is not None
        assert engine.batcher.stats()["demuxErrors"] == 1
    finally:
        engine.batcher.close()


def test_transient_batch_device_fault_recovers_on_device():
    """A TRANSIENT device fault on the fused step (one injected raise)
    no longer sinks the flush to golden: bisection retries the halves,
    which succeed, and every member is served on-device."""
    engine = _batched_engine(batch_max=len(MIXED))
    engine.fallback_to_golden = True  # conftest disables it via env
    try:
        faults.install(FaultRegistry.parse("device_raise@times=1"))
        pend = [engine.batcher._enqueue(d, None) for d in MIXED]
        _drain(pend)
        for p in pend:
            assert p.error is None
            assert p.result is not None and p.result.events
        assert engine.fallback_count == 0
        stats = engine.batcher.stats()
        assert stats["bisects"] >= 1
    finally:
        engine.batcher.close()


def test_persistent_batch_device_fault_falls_back_per_request():
    """A PERSISTENT device fault (fires on every retry) bisects all the
    way down and every member takes the golden host path individually —
    one fallback per request, no errors, log₂ structure visible in the
    counters (len-1 sub-batches each isolate)."""
    engine = _batched_engine(batch_max=len(MIXED))
    engine.fallback_to_golden = True  # conftest disables it via env
    try:
        faults.install(FaultRegistry.parse("device_raise"))
        pend = [engine.batcher._enqueue(d, None) for d in MIXED]
        _drain(pend)
        for p in pend:
            assert p.error is None
            assert p.result is not None and p.result.events
        assert engine.fallback_count == len(MIXED)
        stats = engine.batcher.stats()
        assert stats["bisects"] >= 1
        assert stats["bisectIsolated"] == len(MIXED)
    finally:
        engine.batcher.close()


def test_poison_row_isolated_and_quarantined():
    """ONE poison row in a fused flush: bisection isolates it, the three
    healthy batchmates serve on-device with scores identical to a clean
    serial stream, the culprit serves from golden and its fingerprint is
    quarantined — a repeat submit never reaches the device step."""
    poison = _pod(["INFO boot", "POISON-PILL marker", "OutOfMemoryError x"])
    stream = [MIXED[0], poison, MIXED[1], MIXED[2]]
    serial = AnalysisEngine(_sets(), ScoringConfig())
    expected = [_events(serial.analyze_pipelined(d)) for d in stream]

    from log_parser_tpu.runtime.quarantine import QuarantineTable

    reg = FaultRegistry.parse("quarantine_raise@match=POISON-PILL")
    faults.install(reg)
    engine = _batched_engine(batch_max=len(stream))
    engine.fallback_to_golden = True  # conftest disables it via env
    engine.quarantine = QuarantineTable(strikes=1, ttl_s=600.0)
    try:
        pend = [engine.batcher._enqueue(d, None) for d in stream]
        _drain(pend)
        for p, want in zip(pend, expected):
            # the healthy majority AND the golden-served culprit all match
            # the clean serial stream exactly (device/golden parity)
            assert p.error is None
            assert _events(p.result) == want
        stats = engine.batcher.stats()
        assert engine.fallback_count == 1  # only the poison row fell back
        assert stats["bisects"] >= 1
        assert stats["bisectIsolated"] == 1
        assert stats["demuxErrors"] == 0
        assert engine.quarantine.stats()["active"] == 1

        # the repeat is intercepted in submit(): served from golden with
        # the keyed fault's fired counter pinned — proof the fingerprint
        # never re-entered a shared batch or the device step
        fired = reg.specs[0].fired
        batched_before = stats["requestsBatched"]
        repeat = engine.batcher.submit(poison)
        assert _events(repeat) == expected[1]
        assert reg.specs[0].fired == fired
        assert engine.quarantine.stats()["servedGolden"] == 1
        assert engine.batcher.stats()["requestsBatched"] == batched_before
    finally:
        engine.batcher.close()


def test_bisect_abort_fault_degrades_to_whole_batch_fallback():
    """An armed ``bisect`` fault vetoes the split: the flush degrades to
    the pre-bisection behaviour (every member's fallback decision made
    individually) — the chaos knob that measures what bisection buys."""
    engine = _batched_engine(batch_max=len(MIXED))
    engine.fallback_to_golden = True  # conftest disables it via env
    try:
        faults.install(
            FaultRegistry.parse("device_raise@times=1,bisect_raise@times=1")
        )
        pend = [engine.batcher._enqueue(d, None) for d in MIXED]
        _drain(pend)
        for p in pend:
            assert p.error is None and p.result is not None
        assert engine.fallback_count == len(MIXED)
        stats = engine.batcher.stats()
        assert stats["bisects"] == 0
        assert stats["bisectAborts"] == 1
    finally:
        engine.batcher.close()


def test_logic_fault_propagates_to_every_caller():
    """A non-device batch failure (a logic bug) must propagate to each
    caller, exactly like the unbatched path — never silently fall back."""
    engine = _batched_engine(batch_max=2)
    try:
        faults.install(FaultRegistry.parse("batcher_raise@times=1"))
        pend = [engine.batcher._enqueue(d, None) for d in MIXED[:2]]
        _drain(pend)
        for p in pend:
            assert isinstance(p.error, InjectedFault)
        assert engine.fallback_count == 0
    finally:
        engine.batcher.close()


def test_oversize_fault_takes_whole_bucket():
    """An armed batcher_oversize fault widens one flush past batch_max —
    the oversized batch still serves every request correctly."""
    engine = _batched_engine(wait_ms=5000.0, batch_max=2)
    try:
        faults.install(FaultRegistry.parse("batcher_oversize_raise@times=1"))
        # hold the scheduler out (its flush pick needs _cv) until all five
        # are enqueued, so the oversize take is deterministic
        with engine.batcher._cv:
            pend = [
                engine.batcher._enqueue(MIXED[i % len(MIXED)], None)
                for i in range(5)
            ]
        _drain(pend)
        for p in pend:
            assert p.error is None and p.result is not None
        stats = engine.batcher.stats()
        assert stats["batchesFlushed"] == 1
        assert stats["maxBatchSeen"] == 5
    finally:
        engine.batcher.close()


def test_submit_after_close_serves_unbatched():
    engine = _batched_engine()
    engine.batcher.close()
    result = engine.batcher.submit(MIXED[0])
    assert result is not None and result.events
    assert engine.batcher.stats()["requestsBatched"] == 0


def test_admission_batched_route_is_first_class():
    """A queued request on a batching engine admits as "batched" — full
    device service, counted as admission rather than host degradation."""
    gate = AdmissionController(max_inflight=1, max_queue=4)
    assert gate.acquire(batchable=True) == "device"
    routes = []
    t = threading.Thread(
        target=lambda: routes.append(gate.acquire(batchable=True)),
        daemon=True,
    )
    t.start()
    time.sleep(0.05)
    gate.release()  # frees the slot the queued waiter is blocked on
    t.join(5)
    assert routes == ["batched"]
    gate.release()
    stats = gate.stats()
    assert stats["admittedBatched"] == 1
    assert stats["admittedHost"] == 0


def test_phase_trace_thread_safe():
    """The batcher accumulates phases into one trace from the submitting
    thread AND the scheduler thread; concurrent adds must not lose time."""
    trace = PhaseTrace()
    n_threads, n_adds = 8, 500

    def worker():
        for _ in range(n_adds):
            trace.add("x", 0.001)
            with trace.phase("y"):
                pass

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    phases = trace.as_dict()
    assert phases["x"] == pytest.approx(n_threads * n_adds * 0.001)
    assert trace.total == pytest.approx(phases["x"] + phases["y"])
