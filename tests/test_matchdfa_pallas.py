"""Pallas union-DFA kernel (ops/matchdfa_pallas.py) vs the XLA scan tier.

Bit-identical semantics are the kernel's contract: every test pins the
kernel's reported flags (interpreter mode — the same kernel semantics
Mosaic lowers on TPU) against the scan tier's pair_stepper carry and an
independent numpy byte-walk of the packed table, over the union fixture
set plus adversarial shapes: pair-stride odd-length tails, padding-class
rows, the dense re-scan ``lax.cond`` recovery path, zero-match batches,
and the oversized-table / no-tile admission fallbacks — batched (the
micro-batcher's vmapped program) and unbatched.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from log_parser_tpu.ops import matchdfa_pallas as mdp
from log_parser_tpu.ops.encode import encode_lines
from log_parser_tpu.ops.match import (
    MatcherBanks,
    MultiDfaBank,
    pack_byte_pairs,
)
from log_parser_tpu.patterns.bank import PatternBank
from log_parser_tpu.patterns.regex.multidfa import pack_union_groups
from log_parser_tpu.runtime import faults
from log_parser_tpu.runtime.faults import FaultRegistry
from tests.helpers import make_pattern, make_pattern_set
from tests.test_multidfa import LINES, REGEXES


def _group_banks(max_states: int = 400, max_group: int = 6):
    """Union groups over the shared multidfa fixture regexes, forced into
    SEVERAL groups (small budget) so the kernel's grid dimension is
    exercised; built through the Python union construction."""
    entries = [(j, rx, ci) for j, (rx, ci) in enumerate(REGEXES)]
    groups, rejected = pack_union_groups(
        entries, max_states=max_states, max_group=max_group
    )
    assert groups, "fixture regexes must pack at least one union group"
    return [MultiDfaBank(md, list(range(len(keys)))) for keys, md in groups]


def _encode_tb(lines: list[str]):
    enc = encode_lines(lines)
    return jnp.asarray(enc.u8.T), jnp.asarray(enc.lengths)


def _numpy_reported(groups, arr_tb: np.ndarray) -> np.ndarray:
    """Independent reference: single-byte walk of each group's packed
    table in numpy — no jax, no pairing."""
    T, B = arr_tb.shape
    outs = []
    for g in groups:
        tbl = np.asarray(g._packed_byte_np, dtype=np.int64)
        s = np.full(B, g.start, np.int64)
        rep = np.full(B, g.start_reports, bool)
        for t in range(T):
            v = tbl[s * 256 + arr_tb[t].astype(np.int64)]
            s = v & g._STATE_MASK
            rep |= v >= g._REPORT_BIT
        outs.append(rep)
    return np.stack(outs, axis=1).astype(np.int32)


def _scan_reported(groups, lines_tb: jax.Array) -> np.ndarray:
    """The XLA scan tier's carry, finished: the exact computation cube()
    fuses when the kernel is off (lengths are unused by the gate-free
    pair_stepper)."""
    B = int(lines_tb.shape[1])
    lengths = jnp.zeros((B,), jnp.int32)
    pairs, ts = pack_byte_pairs(lines_tb)
    outs = []
    for g in groups:
        init, step, finish = g.pair_stepper(B, lengths)

        def f(c, xs):
            pair_t, t = xs
            return step(c, pair_t[0], pair_t[1], t), None

        final, _ = jax.lax.scan(f, init, (pairs, ts))
        outs.append(np.asarray(finish(final)[1]))
    return np.stack(outs, axis=1).astype(np.int32)


@pytest.fixture
def multi_engaged(monkeypatch):
    """Force the multi tier on hosts without the native library: the
    MatcherBanks gate sees a library while the union builder takes the
    Python construction."""
    import log_parser_tpu.native as native
    import log_parser_tpu.native.dfabuild as dfabuild

    monkeypatch.setattr(native, "get_lib", lambda: object())
    monkeypatch.setattr(dfabuild, "get_lib", lambda: None)


# ------------------------------------------------------------ kernel parity


def test_kernel_parity_both_strides():
    groups = _group_banks()
    lines_tb, _ = _encode_tb(LINES)
    ref = _scan_reported(groups, lines_tb)
    ref_np = _numpy_reported(groups, np.asarray(lines_tb))
    np.testing.assert_array_equal(ref, ref_np)
    plan, reason = mdp.build_dfa_plan(groups)
    assert reason in mdp.ADMITTED and plan is not None
    for stride in (2, 1):
        out = np.asarray(
            mdp.multidfa_reported_pallas(
                plan, lines_tb, stride=stride, interpret=True
            )
        )
        np.testing.assert_array_equal(out, ref, err_msg=f"stride {stride}")


def test_kernel_pair_stride_odd_length_tail():
    groups = _group_banks()
    lines_tb, _ = _encode_tb(LINES)
    odd_tb = lines_tb[: int(lines_tb.shape[0]) - 1]  # odd T
    assert int(odd_tb.shape[0]) % 2 == 1
    ref = _numpy_reported(groups, np.asarray(odd_tb))
    plan, _ = mdp.build_dfa_plan(groups)
    for stride in (2, 1):
        out = np.asarray(
            mdp.multidfa_reported_pallas(
                plan, odd_tb, stride=stride, interpret=True
            )
        )
        np.testing.assert_array_equal(out, ref, err_msg=f"stride {stride}")


def test_kernel_padding_class_rows():
    """Rows far shorter than T (and empty rows) ride the byte-0
    self-loop identity class; high random bytes exercise every byte
    column of the planes."""
    rng = np.random.default_rng(11)

    def _blob(n: int) -> str:
        raw = rng.integers(1, 256, size=n).astype(np.uint8)
        raw[(raw == 10) | (raw == 13)] = 32  # newlines would split rows
        return bytes(raw).decode("latin-1")

    lines = ["", "a", "panic: ", "x" * 3] + [
        _blob(int(n)) for n in rng.integers(0, 60, size=12)
    ]
    groups = _group_banks()
    lines_tb, _ = _encode_tb(lines)
    ref = _scan_reported(groups, lines_tb)
    np.testing.assert_array_equal(
        ref, _numpy_reported(groups, np.asarray(lines_tb))
    )
    plan, _ = mdp.build_dfa_plan(groups)
    out = np.asarray(mdp.multidfa_reported_pallas(plan, lines_tb, interpret=True))
    np.testing.assert_array_equal(out, ref)


def test_kernel_zero_match_batch():
    entries = [(0, "OutOfMemoryError", False), (1, "panic: ", False)]
    groups, _rej = pack_union_groups(entries, max_states=400)
    banks = [MultiDfaBank(md, list(range(len(keys)))) for keys, md in groups]
    lines_tb, _ = _encode_tb(["nothing here", "all quiet", ""])
    ref = _scan_reported(banks, lines_tb)
    assert not ref.any()
    plan, _ = mdp.build_dfa_plan(banks)
    out = np.asarray(mdp.multidfa_reported_pallas(plan, lines_tb, interpret=True))
    np.testing.assert_array_equal(out, ref)


def test_kernel_under_vmap_batched():
    """The micro-batcher vmaps the fused step over stacked requests; the
    kernel must batch identically."""
    groups = _group_banks()
    lines_tb, _ = _encode_tb(LINES)
    rev_tb = lines_tb[:, ::-1]
    ref0 = _scan_reported(groups, lines_tb)
    ref1 = _scan_reported(groups, rev_tb)
    plan, _ = mdp.build_dfa_plan(groups)
    f = jax.jit(
        jax.vmap(lambda x: mdp.multidfa_reported_pallas(plan, x, interpret=True))
    )
    out = np.asarray(f(jnp.stack([lines_tb, rev_tb])))
    np.testing.assert_array_equal(out[0], ref0)
    np.testing.assert_array_equal(out[1], ref1)


# ------------------------------------------------------------- admission


def _group_banks_with_entries(max_states: int = 400, max_group: int = 6):
    """Like ``_group_banks`` but keeps the GLOBAL entry keys on the banks
    and returns the per-group entries the split planner needs."""
    entries = [(j, rx, ci) for j, (rx, ci) in enumerate(REGEXES)]
    groups, _rej = pack_union_groups(
        entries, max_states=max_states, max_group=max_group
    )
    emap = {e[0]: e for e in entries}
    banks = [MultiDfaBank(md, keys) for keys, md in groups]
    return banks, [[emap[k] for k in keys] for keys, _ in groups]


def test_oversized_table_refused_without_entries():
    groups = _group_banks()
    plan, reason = mdp.build_dfa_plan(groups, budget=64 * 1024)
    assert plan is None and reason == "table_too_large"


def test_oversized_table_refused_when_singletons_inadmissible():
    """Entries enable re-splitting, but no split can beat the per-group
    VMEM floor (~736 KB at the nominal tile) under a 64 KB budget — the
    planner must refuse rather than loop."""
    banks, gents = _group_banks_with_entries()
    plan, reason = mdp.build_dfa_plan(banks, budget=64 * 1024, entries=gents)
    assert plan is None and reason == "table_too_large"


def test_admission_split_repartitions():
    """A budget above the per-group floor but below the packed fixture
    cost forces the admissible re-partition path: more groups, the same
    columns in the same order, and bit parity on the split plan. The
    fixture regexes ride ONE union group here (large ``max_group``) so
    its padded planes overflow 900 KB while the split halves fit."""
    banks, gents = _group_banks_with_entries(max_states=4096, max_group=64)
    assert len(banks) == 1
    plan, reason = mdp.build_dfa_plan(banks, budget=900 * 1024, entries=gents)
    assert plan is not None and reason == "split"
    assert plan.geometry["split"]
    assert len(plan.groups) > len(banks)
    assert [k for b in plan.groups for k in b.cols] == [
        k for b in banks for k in b.cols
    ]
    lines_tb, _ = _encode_tb(LINES)
    ref = _scan_reported(plan.groups, lines_tb)
    np.testing.assert_array_equal(
        ref, _numpy_reported(plan.groups, np.asarray(lines_tb))
    )
    out = np.asarray(mdp.multidfa_reported_pallas(plan, lines_tb, interpret=True))
    np.testing.assert_array_equal(out, ref)


@pytest.mark.slow
def test_builtin_bank_admits_under_production_budget():
    """The PR's acceptance criterion, pinned: the builtin bank's union
    groups (python pack, disk-cached by the tool) admit under the
    production 12 MB VMEM budget. Mirrors hygiene check 15 in-process."""
    import importlib.util
    import pathlib
    import sys as _sys

    tool = (
        pathlib.Path(__file__).resolve().parents[1]
        / "tools"
        / "check_dfa_admission.py"
    )
    spec = importlib.util.spec_from_file_location("check_dfa_admission", tool)
    mod = importlib.util.module_from_spec(spec)
    _sys.modules["check_dfa_admission"] = mod
    spec.loader.exec_module(mod)
    report = mod.run_admission()
    assert report["admitted"], report
    assert report["geometry"]["vmemPerStep"] <= mdp.DFA_VMEM_BUDGET


def test_no_tile_for_unaligned_batch():
    groups = _group_banks()
    plan, _ = mdp.build_dfa_plan(groups)
    assert mdp.dfa_tile(plan, 12) is None  # no multiple-of-8 divisor
    assert mdp.dfa_tile(plan, 256) is not None


def test_vmem_estimate_monotone():
    assert mdp._vmem_estimate(256, 16, 128, 64) < mdp._vmem_estimate(
        512, 16, 128, 64
    )
    assert mdp._vmem_estimate(256, 8, 128, 64) < mdp._vmem_estimate(
        256, 16, 128, 64
    )
    assert mdp._vmem_estimate(256, 16, 64, 64) < mdp._vmem_estimate(
        256, 16, 128, 64
    )


# ------------------------------------------------- MatcherBanks integration

_KW = dict(
    shiftor_min_columns=10**9,
    prefilter_min_columns=10**9,
    multi_min_columns=2,
    bitglush_max_words=0,
)


def _fixture_bank() -> PatternBank:
    patterns = [
        make_pattern(f"p{j}", regex=rx, confidence=0.5, severity="LOW")
        for j, (rx, ci) in enumerate(REGEXES)
        if not ci and rx != "x?"  # bank-level: keep deterministic columns
    ]
    return PatternBank([make_pattern_set(patterns)])


def test_cube_parity_kernel_tier(multi_engaged, monkeypatch):
    bank = _fixture_bank()
    monkeypatch.delenv("LOG_PARSER_TPU_PALLAS_DFA", raising=False)
    off = MatcherBanks(bank, **_KW)
    assert off.multi_groups and not off.multidfa_use_pallas
    assert off.multidfa_pallas_reason == "off"
    monkeypatch.setenv("LOG_PARSER_TPU_PALLAS_DFA", "1")
    on = MatcherBanks(bank, **_KW)
    assert on.multidfa_use_pallas
    assert on.multidfa_pallas_reason in mdp.ADMITTED
    assert on.dfa_kernel_geometry is not None
    assert on.dfa_kernel_geometry["states"] <= on.dfa_kernel_geometry["statesUnmin"]
    enc = encode_lines(LINES, 4096, 128, 8)
    lt, ln = jnp.asarray(enc.u8.T), jnp.asarray(enc.lengths)
    got = np.asarray(on.cube(lt, ln))
    want = np.asarray(off.cube(lt, ln))
    np.testing.assert_array_equal(got, want)
    assert want[: len(LINES)].any()
    assert on.dfa_kernel_active(int(ln.shape[0]))


def test_cube_parity_dense_rescan_cond_path(multi_engaged, monkeypatch):
    """More flagged rows than the sparse recovery capacity K forces the
    in-program ``lax.cond`` dense re-scan — with the kernel feeding the
    flags."""
    bank = _fixture_bank()
    lines = ["ERROR and FATAL", "panic: oops"] * 1024  # every row flagged
    enc = encode_lines(lines)
    lt, ln = jnp.asarray(enc.u8.T), jnp.asarray(enc.lengths)
    B = int(ln.shape[0])
    assert B >= 2048  # K = max(1024, B // 64) < n_flagged
    monkeypatch.delenv("LOG_PARSER_TPU_PALLAS_DFA", raising=False)
    off = MatcherBanks(bank, **_KW)
    monkeypatch.setenv("LOG_PARSER_TPU_PALLAS_DFA", "1")
    on = MatcherBanks(bank, **_KW)
    np.testing.assert_array_equal(
        np.asarray(on.cube(lt, ln)), np.asarray(off.cube(lt, ln))
    )


def test_cube_oversized_table_falls_back(multi_engaged, monkeypatch):
    bank = _fixture_bank()
    monkeypatch.setenv("LOG_PARSER_TPU_PALLAS_DFA", "1")
    monkeypatch.setattr(mdp, "DFA_VMEM_BUDGET", 64 * 1024)
    on = MatcherBanks(bank, **_KW)
    assert not on.multidfa_use_pallas
    assert on.multidfa_pallas_reason == "table_too_large"
    monkeypatch.delenv("LOG_PARSER_TPU_PALLAS_DFA")
    off = MatcherBanks(bank, **_KW)
    enc = encode_lines(LINES, 4096, 128, 8)
    lt, ln = jnp.asarray(enc.u8.T), jnp.asarray(enc.lengths)
    np.testing.assert_array_equal(
        np.asarray(on.cube(lt, ln)), np.asarray(off.cube(lt, ln))
    )


def test_cube_kernel_fault_whole_batch_xla_fallback(multi_engaged, monkeypatch):
    """An injected kernel fault drops the WHOLE batch onto the XLA scan
    tier with identical results — the chaos_sweep --group kernel
    scenario, at unit scope."""
    bank = _fixture_bank()
    monkeypatch.setenv("LOG_PARSER_TPU_PALLAS_DFA", "1")
    on = MatcherBanks(bank, **_KW)
    monkeypatch.delenv("LOG_PARSER_TPU_PALLAS_DFA")
    off = MatcherBanks(bank, **_KW)
    enc = encode_lines(LINES, 4096, 128, 8)
    lt, ln = jnp.asarray(enc.u8.T), jnp.asarray(enc.lengths)
    faults.install(FaultRegistry.parse("kernel_raise:1.0@times=1", seed=1))
    try:
        got = np.asarray(on.cube(lt, ln))
    finally:
        faults.install(None)
    assert on.multidfa_pallas_reason == "fault"
    np.testing.assert_array_equal(got, np.asarray(off.cube(lt, ln)))


def test_engine_kernel_stats_counters():
    from log_parser_tpu.runtime.engine import KernelTierStats

    ks = KernelTierStats()
    assert ks.stats() == {
        "enabled": False,
        "reason": "off",
        "kernelBatches": 0,
        "kernelRows": 0,
        "xlaBatches": 0,
        "geometry": None,
    }
    geom = {"nGroups": 2, "sPad": 128}
    ks.note(128, active=True, enabled=True, reason="byte_classed",
            geometry=geom)
    ks.note(64, active=False, enabled=True, reason="fault", geometry=geom)
    ks.note(32, active=False, enabled=False, reason="off")  # not counted
    s = ks.stats()
    assert s["kernelBatches"] == 1 and s["kernelRows"] == 128
    assert s["xlaBatches"] == 1
    assert s["enabled"] is False and s["reason"] == "off"
    assert s["geometry"] is None  # last note carried no plan geometry


def test_reason_codes_documented():
    """Every runtime reason the tier can report is a REASONS key (the
    hygiene gate pins REASONS keys to docs/OPS.md rows)."""
    assert set(mdp.REASONS) >= {
        "byte_classed",
        "split",
        "off",
        "no_union_groups",
        "table_too_large",
        "no_tile",
        "fault",
    }
    assert "ok" not in mdp.REASONS  # replaced by the admission provenance
    assert mdp.ADMITTED == {"byte_classed", "split"}
