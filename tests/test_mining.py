"""Self-growing pattern library (log_parser_tpu/mining/).

The contracts under test:

- the miss tap is bounded, sampled, and non-blocking — saturation is a
  drop counter, never hot-path latency;
- the clusterer converges repeated miss lines into token templates and
  promotes only supported, stable, probe-worthy ones;
- the synthesizer emits only the bounded dialect (escaped literals,
  ``\\S{1,64}`` wildcards, never ``.*``) flagged ``generated: true``;
- the admission gate rejects — with a structured, pinned reason — any
  candidate whose language equals, strictly contains, or is strictly
  contained by a curated pattern's (BOTH directions pinned), and a
  rejection leaves the serving bank object-identical;
- the closed loop works end to end: novel templates stream through
  miss → cluster → synthesize → vet → canary → quiesced swap in auto
  mode, and the admitted pattern scores bit-identically to its
  hand-authored YAML equivalent (``generated`` is provenance, not
  semantics).
"""

from __future__ import annotations

import json
import os
import threading
import urllib.error
import urllib.request

import pytest
import yaml

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.mining.admit import (
    REJECT_REASONS,
    Rejection,
    vet_candidate,
)
from log_parser_tpu.mining.synthesize import (
    SEPARATOR_RE,
    WILDCARD_RE,
    candidate_yaml,
    synthesize,
    template_regex,
)
from log_parser_tpu.mining.templates import (
    WILDCARD,
    Cluster,
    TemplateClusterer,
    template_id,
    tokenize,
)
from log_parser_tpu.models.pattern import PatternSet
from log_parser_tpu.models.pod import PodFailureData
from log_parser_tpu.runtime import AnalysisEngine, faults
from log_parser_tpu.runtime.faults import FaultRegistry
from log_parser_tpu.runtime.linecache import MissTap

from helpers import make_pattern, make_pattern_set


@pytest.fixture(autouse=True)
def clean_registry():
    faults.install(None)
    yield
    faults.install(None)


def _pod(lines: list[str]) -> PodFailureData:
    return PodFailureData(
        pod={"metadata": {"name": "mine"}}, logs="\n".join(lines)
    )


def _curated_sets(regex: str = "OutOfMemoryError"):
    return [
        make_pattern_set(
            [make_pattern("curated-1", regex=regex, confidence=0.8)],
            library_id="curated",
        )
    ]


def _miner_engine(
    curated_regex: str = "OutOfMemoryError",
    mode: str = "auto",
    **kw,
) -> AnalysisEngine:
    engine = AnalysisEngine(_curated_sets(curated_regex), ScoringConfig())
    engine.enable_line_cache(4)
    engine.enable_miner(
        mode=mode, min_support=3, stability=0, autostart=False, **kw
    )
    return engine


def _cluster(text: str, support: int = 8) -> Cluster:
    c = Cluster(tokenize(text.encode()))
    c.support = support
    return c


# ------------------------------------------------------------------ miss tap


class TestMissTap:
    def test_bounded_and_drop_counted(self):
        tap = MissTap(capacity=3)
        for i in range(5):
            tap.offer(b"line %d" % i)
        s = tap.stats()
        assert s["tapped"] == 3 and s["dropped"] == 2 and s["queued"] == 3
        got = tap.drain(timeout=0)
        assert [c for _, c in got] == [1, 1, 1]
        assert tap.stats()["queued"] == 0

    def test_stride_sampling_is_deterministic(self):
        a, b = MissTap(sample=0.25), MissTap(sample=0.25)
        for tap in (a, b):
            for i in range(100):
                tap.offer(b"x%d" % i)
        assert a.stats() == b.stats()
        assert a.stats()["tapped"] == 25
        assert a.stats()["sampledOut"] == 75
        assert [x for x, _ in a.drain(max_items=100, timeout=0)] == [
            x for x, _ in b.drain(max_items=100, timeout=0)
        ]

    def test_closed_tap_refuses(self):
        tap = MissTap()
        tap.close()
        assert tap.offer(b"late") is False
        assert tap.drain(timeout=0) == []


# ----------------------------------------------------------------- clusterer


class TestClusterer:
    def test_digit_tokens_mask_to_wildcards(self):
        assert tokenize(b"worker 17 started at t=3") == (
            "worker", WILDCARD, "started", "at", WILDCARD,
        )
        assert tokenize(b"") == ()
        assert tokenize(b"t " * 100) == ()  # over the token cap

    def test_merge_widens_and_resets_stability(self):
        cl = TemplateClusterer(min_support=2, stability=1)
        cl.observe(b"conn reset by peer alpha")
        cl.observe(b"conn reset by peer beta")
        cl.observe(b"conn reset by peer beta")
        snap = cl.snapshot()
        assert len(snap) == 1
        assert snap[0]["template"] == "conn reset by peer <*>"
        # the merge that introduced <*> reset the stability clock; the
        # third (template-stable) observation re-earned it
        assert [c.template for c in cl.promotable()] == [
            ("conn", "reset", "by", "peer", WILDCARD)
        ]

    def test_promotable_needs_support_and_fixed_token(self):
        cl = TemplateClusterer(min_support=3, stability=0)
        cl.observe(b"abcd efgh ijkl mnop")  # support 1 < 3
        # all-wildcard (5-token, so it can't absorb the 4-token group):
        # never promotable regardless of support
        cl.observe(b"x1 y2 z3 w4 v5")
        cl.observe(b"x6 y7 z8 w9 v10")
        cl.observe(b"x11 y12 z13 w14 v15")
        assert cl.promotable() == []
        cl.observe(b"abcd efgh ijkl mnop")
        cl.observe(b"abcd efgh ijkl mnop")
        assert [template_id(c.template) for c in cl.promotable()] == [
            template_id(("abcd", "efgh", "ijkl", "mnop"))
        ]
        # promoted exactly once
        assert cl.promotable() == []

    def test_cluster_cap_discards_instead_of_evicting(self):
        cl = TemplateClusterer(min_support=1, stability=0, max_clusters=2)
        cl.observe(b"aaaa bbbb")
        cl.observe(b"cccc dddd")
        cl.observe(b"eeee ffff")  # at cap: discarded, support intact
        s = cl.stats()
        assert s["clusters"] == 2 and s["discarded"] == 1


# --------------------------------------------------------------- synthesizer


class TestSynthesize:
    def test_bounded_dialect_only(self):
        c = _cluster("frobnicate queue q7 depth d9")
        regex = template_regex(c.template)
        assert ".*" not in regex
        assert regex == (
            f"frobnicate{SEPARATOR_RE}queue{SEPARATOR_RE}{WILDCARD_RE}"
            f"{SEPARATOR_RE}depth{SEPARATOR_RE}{WILDCARD_RE}"
        )

    def test_metacharacters_escaped_and_exotics_demoted(self):
        # metachar-bearing fixed tokens are escaped literals
        assert template_regex(("a+b", "(x)")) == (
            rf"a\+b{SEPARATOR_RE}\(x\)"
        )
        # non-printable-ASCII tokens demote to a bounded wildcard
        assert template_regex(("café",)) == WILDCARD_RE

    def test_candidate_shape_and_yaml_round_trip(self):
        cand = synthesize(_cluster("gc pause exceeded budget", support=11))
        pat = cand.patterns[0]
        assert pat.generated is True
        assert pat.severity == "INFO"
        assert pat.remediation["support"] == 11
        assert pat.id == template_id(("gc", "pause", "exceeded", "budget"))
        again = PatternSet.from_dict(yaml.safe_load(candidate_yaml(cand)))
        assert again.patterns[0].generated is True
        assert again.patterns[0].primary_pattern.regex == (
            pat.primary_pattern.regex
        )


# ------------------------------------------------------- the subsumption gate


class TestSubsumptionGate:
    """A mined pattern may never shadow or duplicate a curated one —
    pinned in BOTH containment directions with structured reasons."""

    def test_mined_equal_curated_rejected(self):
        # same language, different bytes (the byte-identity fast path
        # must not be the only thing standing)
        engine = _miner_engine(r"(?:FooBarBazQux)\s{1,8}happened")
        cand = synthesize(_cluster("FooBarBazQux happened"))
        with pytest.raises(Rejection) as exc:
            vet_candidate(engine, cand)
        assert exc.value.reason == "mined-duplicate"
        assert "curated-1" in exc.value.detail

    def test_mined_contains_curated_rejected(self):
        # mined "FooBarBazQux <*>" strictly contains the curated
        # language -> admitting it would shadow the curated pattern
        engine = _miner_engine(r"FooBarBazQux\s{1,8}happened")
        cand = synthesize(_cluster("FooBarBazQux h4ppened"))
        assert cand.patterns[0].primary_pattern.regex == (
            rf"FooBarBazQux{SEPARATOR_RE}{WILDCARD_RE}"
        )
        with pytest.raises(Rejection) as exc:
            vet_candidate(engine, cand)
        assert exc.value.reason == "mined-shadows-curated"

    def test_curated_contains_mined_rejected(self):
        # mined "FooBarBazQux happened" is strictly inside the curated
        # wildcard language -> every mined match already fires curated
        engine = _miner_engine(rf"FooBarBazQux\s{{1,8}}\S{{1,64}}")
        cand = synthesize(_cluster("FooBarBazQux happened"))
        with pytest.raises(Rejection) as exc:
            vet_candidate(engine, cand)
        assert exc.value.reason == "mined-shadowed"

    def test_duplicate_id_and_incomparable_admit(self):
        engine = _miner_engine("OutOfMemoryError")
        cand = synthesize(_cluster("totally unrelated template line"))
        # incomparable languages vet clean...
        vet = vet_candidate(engine, cand)
        assert vet["tier"] in ("shiftor", "dfa")
        # ...but a live id collision rejects
        dup = synthesize(_cluster("totally unrelated template line"))
        dup.patterns[0].id = "curated-1"
        with pytest.raises(Rejection) as exc:
            vet_candidate(engine, dup)
        assert exc.value.reason == "mined-duplicate-id"

    def test_rejection_reasons_are_pinned_vocabulary(self):
        # every raise site uses a code from REJECT_REASONS (the
        # Rejection constructor asserts it); the vocabulary itself is
        # pinned to docs/PATTERNS.md by hygiene check 14
        assert {"mined-duplicate", "mined-shadows-curated",
                "mined-shadowed", "mined-undecided"} <= set(REJECT_REASONS)
        with pytest.raises(AssertionError):
            Rejection("not-a-reason", "nope")

    def test_rejection_leaves_bank_object_identical(self):
        engine = _miner_engine(r"FooBarBazQux\s{1,8}happened")
        bank = engine.bank
        epoch = engine.reload_epoch
        engine.analyze(_pod([f"FooBarBazQux h4ppened{i}" for i in range(3)]))
        engine.miner.pump()
        stats = engine.miner.stats()
        assert stats["rejected"].get("mined-shadows-curated", 0) >= 1, stats
        assert stats["admitted"] == 0 and stats["errors"] == 0, stats
        assert engine.bank is bank
        assert engine.reload_epoch == epoch
        engine.miner.stop()


# ------------------------------------------------------------ the closed loop


NOVEL = [
    "replication backlog drained on shard {i} after {j} entries",
    "checkpoint upload finished for epoch {i} in {j} ms",
    "thermal governor stepped clock domain {i} to {j} mhz",
]


def _novel_lines(r: int) -> list[str]:
    return [
        t.format(i=r * 10 + k, j=r * 7 + k) for t in NOVEL for k in range(3)
    ]


class TestClosedLoop:
    def test_auto_mode_mines_and_admits_three_templates(self):
        engine = _miner_engine(mode="auto")
        engine.analyze(_pod(_novel_lines(0) + ["OutOfMemoryError hit"]))
        engine.miner.pump()
        stats = engine.miner.stats()
        assert stats["admitted"] == 3, stats
        assert stats["errors"] == 0 and not stats["rejected"], stats
        assert engine.reload_epoch == 3
        mined_ids = sorted(
            p.id
            for ps in engine.bank.pattern_sets
            for p in ps.patterns
            if p.generated
        )
        assert len(mined_ids) == 3 and all(
            i.startswith("mined-") for i in mined_ids
        )
        # auto mode forces shadow verification on for the mined ids
        assert engine.shadow is not None
        # the mined library now scores fresh template instances (new
        # slot values -> genuinely novel lines)
        r = engine.analyze(_pod(_novel_lines(9)))
        assert {e.matched_pattern.id for e in r.events} == set(mined_ids)
        # post-admission steady state: repeats of an already-seen
        # corpus are pure cache hits — miss (and tap) traffic ~0
        engine.analyze(_pod(_novel_lines(9)))
        misses = engine.line_cache.stats()["misses"]
        tapped = engine.miner.tap.stats()["tapped"]
        engine.analyze(_pod(_novel_lines(9)))
        assert engine.line_cache.stats()["misses"] == misses
        assert engine.miner.tap.stats()["tapped"] == tapped
        engine.miner.stop()

    def test_admitted_scores_bit_identical_to_hand_authored(self):
        engine = _miner_engine(mode="auto")
        engine.analyze(_pod(_novel_lines(0)))
        engine.miner.pump()
        assert engine.miner.stats()["admitted"] == 3
        # hand-author the YAML equivalents: the exact bytes the miner
        # would park, minus the provenance flag
        hand_sets = []
        for ps in engine.bank.pattern_sets:
            for p in ps.patterns:
                if not p.generated:
                    continue
                d = yaml.safe_load(
                    candidate_yaml(
                        PatternSet(metadata=ps.metadata, patterns=[p])
                    )
                )
                del d["patterns"][0]["generated"]
                hand_sets.append(PatternSet.from_dict(d))
        assert len(hand_sets) == 3
        assert not any(p.generated for hs in hand_sets for p in hs.patterns)
        hand = AnalysisEngine(_curated_sets() + hand_sets, ScoringConfig())
        # neutralize the mined engine's mining-phase frequency history;
        # from identical state, generated-vs-hand-authored must be
        # invisible to scoring
        engine.frequency.reset_all_frequencies()
        probe = _pod(_novel_lines(7) + ["OutOfMemoryError again"])
        r_mined = engine.analyze(probe)
        r_hand = hand.analyze(probe)
        assert [
            (e.line_number, e.matched_pattern.id, e.score)
            for e in r_mined.events
        ] == [
            (e.line_number, e.matched_pattern.id, e.score)
            for e in r_hand.events
        ]
        assert r_mined.summary.to_dict() == r_hand.summary.to_dict()
        engine.miner.stop()

    def test_review_mode_parks_then_approve_admits(self, tmp_path):
        engine = _miner_engine(mode="review", state_dir=str(tmp_path))
        engine.analyze(_pod(_novel_lines(0)))
        engine.miner.pump()
        stats = engine.miner.stats()
        assert stats["pending"] == 3 and stats["admitted"] == 0, stats
        assert engine.reload_epoch == 0  # review never touches the bank
        pending = engine.miner.pending_list()
        assert {e["tier"] for e in pending} <= {"shiftor", "dfa"}
        on_disk = sorted(os.listdir(tmp_path / "mined" / "pending"))
        assert on_disk == sorted(e["id"] + ".yaml" for e in pending)
        # a fresh miner (restart) rehydrates the parked queue
        engine2 = _miner_engine(mode="review", state_dir=str(tmp_path))
        assert {e["id"] for e in engine2.miner.pending_list()} == {
            e["id"] for e in pending
        }
        engine2.miner.stop()
        # approval runs the FULL ladder and the swap
        result = engine.miner.approve(pending[0]["id"])
        assert result["status"] == "admitted" and result["epoch"] == 1
        assert engine.miner.stats()["pending"] == 2
        assert not (tmp_path / "mined" / "pending"
                    / (pending[0]["id"] + ".yaml")).exists()
        with pytest.raises(KeyError):
            engine.miner.approve("mined-nope")
        engine.miner.stop()

    def test_miner_fault_is_contained(self):
        engine = _miner_engine(mode="auto")
        faults.install(FaultRegistry.parse("miner_admit_raise@times=3"))
        engine.analyze(_pod(_novel_lines(0)))
        engine.miner.pump()
        stats = engine.miner.stats()
        assert stats["rejected"].get("mined-fault") == 3, stats
        assert stats["errors"] == 0 and stats["admitted"] == 0, stats
        faults.install(FaultRegistry.parse("miner_raise@times=1"))
        assert engine.miner.pump() == 0  # contained: a counter, no raise
        assert engine.miner.stats()["errors"] == 1
        engine.miner.stop()


# ------------------------------------------------------------ review surface


class TestMinedHTTP:
    def _server(self, engine):
        from log_parser_tpu.serve.http import make_server

        server = make_server(engine, "127.0.0.1", 0)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        return server, f"http://127.0.0.1:{server.server_address[1]}"

    def _req(self, url, path, body=None):
        data = None if body is None else json.dumps(body).encode()
        req = urllib.request.Request(url + path, data=data)
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def test_review_api_roundtrip(self):
        engine = _miner_engine(mode="review")
        engine.analyze(_pod(_novel_lines(0)))
        engine.miner.pump()
        server, url = self._server(engine)
        try:
            status, body = self._req(url, "/patterns/mined")
            assert status == 200 and len(body["pending"]) == 3
            assert body["stats"]["mode"] == "review"
            ids = [e["id"] for e in body["pending"]]
            status, body = self._req(
                url, "/patterns/mined", {"id": ids[0], "action": "approve"}
            )
            assert status == 200 and body["status"] == "admitted"
            status, body = self._req(
                url, "/patterns/mined", {"id": ids[1], "action": "reject"}
            )
            assert status == 200 and body["status"] == "rejected"
            status, body = self._req(url, "/patterns/mined")
            assert status == 200 and [e["id"] for e in body["pending"]] == [
                ids[2]
            ]
            status, body = self._req(
                url, "/patterns/mined", {"id": "mined-nope", "action": "approve"}
            )
            assert status == 404
            status, body = self._req(
                url, "/patterns/mined", {"id": ids[2]}
            )
            assert status == 400
            # /trace/last surfaces the miner block
            status, trace = self._req(url, "/trace/last")
            assert status == 200 and trace["miner"]["admitted"] == 1
        finally:
            server.shutdown()
            server.server_close()
            engine.miner.stop()

    def test_miner_disabled_404(self):
        engine = AnalysisEngine(_curated_sets(), ScoringConfig())
        server, url = self._server(engine)
        try:
            assert self._req(url, "/patterns/mined")[0] == 404
            assert self._req(
                url, "/patterns/mined", {"id": "x", "action": "reject"}
            )[0] == 404
        finally:
            server.shutdown()
            server.server_close()
