"""Standard-gRPC transport of the LogParser service — the same contract
test_shim.py runs over the framed socket (proto/logparser.proto
``service LogParser``; VERDICT.md round-1 missing #5)."""

from __future__ import annotations

import json

import pytest

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.runtime import AnalysisEngine
from log_parser_tpu.shim import logparser_pb2 as pb
from log_parser_tpu.shim.grpc_server import (
    HAVE_GRPC,
    make_channel_stubs,
    make_grpc_server,
)

from helpers import make_pattern, make_pattern_set

pytestmark = pytest.mark.skipif(not HAVE_GRPC, reason="grpcio not installed")


@pytest.fixture(scope="module")
def stubs():
    import grpc

    sets = [
        make_pattern_set(
            [
                make_pattern(
                    "oom", regex="OutOfMemoryError", confidence=0.8, severity="HIGH",
                    secondaries=[("GC overhead", 0.6, 10)], context=(1, 1),
                )
            ]
        )
    ]
    engine = AnalysisEngine(sets, ScoringConfig())
    server, port = make_grpc_server(engine, host="127.0.0.1", port=0)
    server.start()
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    yield make_channel_stubs(channel)
    channel.close()
    server.stop(grace=None)


def test_health(stubs):
    assert stubs["Health"](pb.HealthRequest()).status == "UP"


def test_parse_roundtrip(stubs):
    resp = stubs["Parse"](
        pb.ParseRequest(
            pod_json=json.dumps({"metadata": {"name": "web-1"}}),
            logs="boot\nGC overhead limit\njava.lang.OutOfMemoryError: heap\ndone",
        )
    )
    assert resp.analysis_id
    assert resp.summary.highest_severity == "HIGH"
    [event] = resp.events
    assert event.line_number == 3
    assert list(event.context.lines_before) == ["GC overhead limit"]
    assert json.loads(event.pattern_json)["id"] == "oom"
    assert event.score > 0
    assert resp.metadata.total_lines == 4


def test_null_pod_is_invalid_argument(stubs):
    import grpc

    with pytest.raises(grpc.RpcError) as err:
        stubs["Parse"](pb.ParseRequest(pod_json="", logs="x"))
    assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    assert "Invalid PodFailureData" in err.value.details()


def test_unknown_tenant_is_not_found(stubs):
    """404-class tenant errors keep their identity on the wire: an
    unknown tenant is NOT_FOUND (a typo or a not-yet-provisioned
    tenant), not INVALID_ARGUMENT — the same split the HTTP transport
    answers with 404 vs 400."""
    import grpc

    with pytest.raises(grpc.RpcError) as err:
        stubs["Parse"](
            pb.ParseRequest(
                pod_json=json.dumps({"metadata": {"name": "w"}}), logs="x"
            ),
            metadata=(("x-tenant", "ghost"),),
        )
    assert err.value.code() == grpc.StatusCode.NOT_FOUND
    assert "ghost" in err.value.details()


def test_malformed_tenant_id_is_invalid_argument(stubs):
    import grpc

    with pytest.raises(grpc.RpcError) as err:
        stubs["FrequencyStats"](
            pb.FrequencyStatsRequest(), metadata=(("x-tenant", "../evil"),)
        )
    assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT


def test_stream_unknown_tenant_is_not_found():
    from log_parser_tpu.shim import logparser_stream_pb2 as spb
    from log_parser_tpu.shim import make_stream_stub

    import grpc

    sets = [make_pattern_set([make_pattern("e", regex="ERROR")])]
    engine = AnalysisEngine(sets, ScoringConfig())
    server, port = make_grpc_server(engine, host="127.0.0.1", port=0)
    server.start()
    try:
        with grpc.insecure_channel(f"127.0.0.1:{port}") as ch:
            stub = make_stream_stub(ch)
            with pytest.raises(grpc.RpcError) as err:
                list(
                    stub(
                        iter([spb.StreamChunk(close=True)]),
                        metadata=(("x-tenant", "ghost"),),
                    )
                )
            assert err.value.code() == grpc.StatusCode.NOT_FOUND
    finally:
        server.stop(grace=None)


def test_frequency_surface(stubs):
    stubs["Parse"](
        pb.ParseRequest(
            pod_json=json.dumps({"metadata": {"name": "w"}}),
            logs="java.lang.OutOfMemoryError",
        )
    )
    stats = stubs["FrequencyStats"](pb.FrequencyStatsRequest())
    assert stats.windowed_counts["oom"] >= 1

    snap = stubs["FrequencySnapshot"](pb.FrequencySnapshotRequest())
    assert len(snap.ages["oom"].ages_seconds) >= 1

    stubs["FrequencyReset"](pb.FrequencyResetRequest())
    stats = stubs["FrequencyStats"](pb.FrequencyStatsRequest())
    assert len(stats.windowed_counts) == 0

    restore = pb.FrequencyRestoreRequest()
    restore.ages["oom"].ages_seconds.extend(snap.ages["oom"].ages_seconds)
    stubs["FrequencyRestore"](restore)
    stats = stubs["FrequencyStats"](pb.FrequencyStatsRequest())
    assert stats.windowed_counts["oom"] >= 1


def test_shared_service_single_lock():
    """--grpc-port shares the framed server's LogParserService so both
    transports serialize on ONE lock (round-2 review finding)."""
    import threading

    from log_parser_tpu.shim import make_shim_server
    from log_parser_tpu.shim.grpc_server import make_grpc_server

    sets = [make_pattern_set([make_pattern("e", regex="ERROR")])]
    engine = AnalysisEngine(sets, ScoringConfig())
    framed = make_shim_server(engine, host="127.0.0.1", port=0)
    server, port = make_grpc_server(
        engine, host="127.0.0.1", port=0, service=framed.service
    )
    try:
        assert framed.analyze_lock is framed.service.lock
        # both transports answer through the same service instance
        threading.Thread(target=framed.serve_forever, daemon=True).start()
        server.start()
        import grpc

        with grpc.insecure_channel(f"127.0.0.1:{port}") as ch:
            stubs = make_channel_stubs(ch)
            assert stubs["Health"](pb.HealthRequest()).status == "UP"
    finally:
        server.stop(grace=None)
        framed.shutdown()


def test_restore_nan_age_rejected(stubs):
    import grpc

    req = pb.FrequencyRestoreRequest()
    req.ages["e"].ages_seconds.append(float("nan"))
    with pytest.raises(grpc.RpcError) as err:
        stubs["FrequencyRestore"](req)
    assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT


def test_internal_valueerror_is_internal_not_client_error():
    """An internal bug surfacing as a plain ValueError must be INTERNAL,
    not INVALID_ARGUMENT — the client-error clause is a closed set
    (ADVICE.md r2)."""
    import grpc

    sets = [make_pattern_set([make_pattern("e", regex="ERROR")])]
    engine = AnalysisEngine(sets, ScoringConfig())
    server, port = make_grpc_server(engine, host="127.0.0.1", port=0)
    server.start()
    try:
        engine.analyze_pipelined = lambda data, **kw: (_ for _ in ()).throw(
            ValueError("internal shape mismatch")
        )
        with grpc.insecure_channel(f"127.0.0.1:{port}") as ch:
            local = make_channel_stubs(ch)
            with pytest.raises(grpc.RpcError) as err:
                local["Parse"](
                    pb.ParseRequest(
                        pod_json=json.dumps({"metadata": {"name": "w"}}), logs="x"
                    )
                )
            assert err.value.code() == grpc.StatusCode.INTERNAL
            assert "internal shape mismatch" in err.value.details()
    finally:
        server.stop(grace=None)
