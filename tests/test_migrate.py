"""Crash-safe tenant live migration (runtime/migrate.py): the
single-owner contract.

The anchor is the crash matrix: a simulated ``kill -9`` (the
``crash_after`` hook — fsync'd record, no cleanup) at EVERY protocol
record boundary, on both sides, must recover to exactly one owner, and
the surviving owner's responses and frequency state must stay
bit-identical to an unmigrated control run of the same traffic under
the same (fake) clock. Around it: happy-path parity (unbatched,
batched, streaming, line cache on/off), the forward envelope
(TenantForwarded 307 with location + Retry-After), live stream-session
adoption vs bounded error-frame close, bundle integrity (sha sidecar,
version gate, bank content-hash mismatch), the CRC-framed journal's
torn-tail quarantine, and the DrainSupervisor: migrate-everything-out
under a bounded deadline, the no-target/past-deadline bounded close
(a stream-pinned tenant never hangs SIGTERM), multi-tenant
finalization, and the health-driven trigger.
"""

from __future__ import annotations

import hashlib
import os
import time

import pytest

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.models.pod import PodFailureData
from log_parser_tpu.patterns import load_pattern_directory
from log_parser_tpu.runtime import AnalysisEngine
from log_parser_tpu.runtime.migrate import (
    BUNDLE_VERSION,
    DrainSupervisor,
    LocalTarget,
    MigrationCrash,
    MigrationError,
    MigrationJournal,
    Migrator,
    SOURCE_RECORDS,
    TARGET_RECORDS,
    canonical_bundle_bytes,
)
from log_parser_tpu.runtime.stream import shared_manager
from log_parser_tpu.runtime.tenancy import (
    TenantError,
    TenantForwarded,
    TenantRegistry,
)
from log_parser_tpu.serve.admission import shared_gate

from helpers import make_pattern, make_pattern_set

ACME_YAML = """
metadata:
  library_id: acme-lib
patterns:
  - id: oom
    name: Out of memory
    severity: CRITICAL
    primary_pattern:
      regex: OutOfMemoryError
      confidence: 0.9
  - id: err
    name: Errors
    severity: LOW
    primary_pattern:
      regex: "\\\\bERROR\\\\b"
      confidence: 0.5
"""

GLOBEX_YAML = """
metadata:
  library_id: globex-lib
patterns:
  - id: conn
    name: Connection refused
    severity: HIGH
    primary_pattern:
      regex: "Connection refused"
      confidence: 0.7
"""

# a DIFFERENT acme library (extra pattern): staging against it must fail
# the bank content-hash check, not silently change scores
ACME_DRIFTED_YAML = ACME_YAML + """\
  - id: extra
    name: Drifted
    severity: LOW
    primary_pattern:
      regex: DRIFT
      confidence: 0.4
"""

TRAFFIC = [
    "INFO boot\njava.lang.OutOfMemoryError: heap\nan ERROR here",
    "Connection refused by peer\nINFO ok",
    "ERROR twice\nERROR again\nOutOfMemoryError",
    "nothing to see",
    "Connection refused\njava.lang.OutOfMemoryError: metaspace\nERROR",
    "INFO a\nINFO b\nan ERROR here",
]

PREFIX, SUFFIX = TRAFFIC[:3], TRAFFIC[3:]


class FakeClock:
    """Shared, manually-stepped monotonic clock: frequency ages are
    clock-relative, so bit-identical parity needs every engine — the
    migrating pair AND the unmigrated control — to observe the same
    request at the same instant."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


@pytest.fixture()
def root(tmp_path):
    for tid, text in (("acme", ACME_YAML), ("globex", GLOBEX_YAML)):
        d = tmp_path / "tenants" / tid
        d.mkdir(parents=True)
        (d / "lib.yaml").write_text(text)
    return str(tmp_path / "tenants")


def _default_engine(clk=None) -> AnalysisEngine:
    return AnalysisEngine(
        [make_pattern_set([make_pattern("base", regex="BASE")], "base-lib")],
        ScoringConfig(),
        clock=clk or time.monotonic,
    )


def _data(blob: str) -> PodFailureData:
    return PodFailureData(pod={"metadata": {"name": "t"}}, logs=blob)


def _events(result) -> list[tuple]:
    d = result.to_dict(drop_none=True)
    return [
        (e["lineNumber"], e["matchedPattern"]["id"], e["score"])
        for e in d.get("events", [])
    ] + [
        (d["summary"]["significantEvents"], d["summary"]["highestSeverity"])
    ]


def _side(tmp_path, root, name, clk, crash_after=None, journaled=False,
          engine_setup=None):
    """One 'process': a registry over the shared tenant root + its
    Migrator over a per-side state dir. Re-calling with the same name
    over the same dirs is the restart half of a kill -9 simulation."""
    state = tmp_path / name
    state.mkdir(exist_ok=True)
    setup = engine_setup
    if journaled:
        def setup(eng, tid):  # noqa: F811 - deliberate override
            # the journal stamps records with wall time; parity across a
            # simulated restart needs that clock frozen too
            eng.attach_journal(str(state / "wal" / tid), wall=clk)

    reg = TenantRegistry(
        _default_engine(clk), root=root, clock=clk or time.monotonic,
        engine_setup=setup,
    )
    mig = Migrator(
        reg, state_root=str(state), node_url=f"local://{name}",
        crash_after=crash_after,
    )
    return reg, mig


def _control(tmp_path, root, clk, journaled=False):
    """The unmigrated control: a dedicated acme engine fed the whole
    traffic sequence on one node. Rebuilding it over the same WAL dir is
    the control's matching 'restart'."""
    eng = AnalysisEngine(
        load_pattern_directory(f"{root}/acme"), ScoringConfig(), clock=clk
    )
    if journaled:
        eng.attach_journal(str(tmp_path / "control" / "acme"), wall=clk)
    return eng


# -------------------------------------------------------- happy path


class TestHappyPath:
    def test_completed_migration_moves_ownership(self, root, tmp_path):
        reg_a, mig_a = _side(tmp_path, root, "a", None)
        reg_b, mig_b = _side(tmp_path, root, "b", None)
        try:
            reg_a.resolve("acme").engine.analyze(_data(TRAFFIC[0]))
            res = mig_a.migrate(
                "acme", LocalTarget(mig_b, url="local://b"), retry_after_s=7
            )
            assert res["outcome"] == "completed"
            assert res["tenant"] == "acme" and res["target"] == "local://b"
            # the forward envelope: 307 + location + Retry-After
            assert reg_a.forward_for("acme") == ("local://b", 7)
            with pytest.raises(TenantForwarded) as ei:
                reg_a.resolve("acme")
            assert ei.value.status == 307
            assert ei.value.location == "local://b"
            assert ei.value.retry_after_s == 7
            # the target serves; the source's other tenants are untouched
            assert reg_b.resolve("acme").engine.bank.n_patterns == 2
            assert reg_a.resolve(None) is reg_a.default_context
            assert mig_a.stats()["completed"] == 1
            assert mig_a.stats()["forwards"] == 1
            assert mig_b.stats()["staged"] == 1
            assert mig_b.stats()["activated"] == 1
            # a second attempt is refused: the tenant already left
            with pytest.raises(MigrationError) as mei:
                mig_a.migrate("acme", LocalTarget(mig_b, url="local://b"))
            assert mei.value.status == 409
        finally:
            reg_a.shutdown()
            reg_b.shutdown()

    def test_default_tenant_is_not_migratable(self, root, tmp_path):
        reg_a, mig_a = _side(tmp_path, root, "a", None)
        reg_b, mig_b = _side(tmp_path, root, "b", None)
        try:
            with pytest.raises(MigrationError) as ei:
                mig_a.migrate("default", LocalTarget(mig_b))
            assert ei.value.status == 400
            with pytest.raises((MigrationError, TenantError)):
                mig_a.migrate("no-such-tenant", LocalTarget(mig_b))
        finally:
            reg_a.shutdown()
            reg_b.shutdown()

    @pytest.mark.parametrize("cache", [False, True], ids=["nocache", "cache"])
    def test_parity_unbatched(self, root, tmp_path, cache):
        clk = FakeClock()
        setup = (
            (lambda eng, tid: eng.enable_line_cache(8)) if cache else None
        )
        reg_a, mig_a = _side(tmp_path, root, "a", clk, engine_setup=setup)
        reg_b, mig_b = _side(tmp_path, root, "b", clk, engine_setup=setup)
        ctl = _control(tmp_path, root, clk)
        if cache:
            ctl.enable_line_cache(8)
        try:
            for i, blob in enumerate(PREFIX):
                clk.t = float(i + 1)
                got = _events(reg_a.resolve("acme").engine.analyze(_data(blob)))
                assert got == _events(ctl.analyze(_data(blob))), blob
            clk.t = 10.0
            mig_a.migrate("acme", LocalTarget(mig_b, url="local://b"))
            for i, blob in enumerate(SUFFIX):
                clk.t = float(20 + i)
                got = _events(reg_b.resolve("acme").engine.analyze(_data(blob)))
                assert got == _events(ctl.analyze(_data(blob))), blob
            clk.t = 40.0
            snap = reg_b.resolve("acme").engine.frequency.snapshot()
            assert snap == ctl.frequency.snapshot()
        finally:
            reg_a.shutdown()
            reg_b.shutdown()

    def test_parity_batched(self, root, tmp_path):
        clk = FakeClock()

        def setup(eng, tid):
            eng.enable_batching(wait_ms=1.0, batch_max=4)

        reg_a, mig_a = _side(tmp_path, root, "a", clk, engine_setup=setup)
        reg_b, mig_b = _side(tmp_path, root, "b", clk, engine_setup=setup)
        ctl = _control(tmp_path, root, clk)
        ctl.enable_batching(wait_ms=1.0, batch_max=4)
        try:
            for i, blob in enumerate(PREFIX):
                clk.t = float(i + 1)
                got = _events(
                    reg_a.resolve("acme").engine.analyze_batched(_data(blob))
                )
                assert got == _events(ctl.analyze_batched(_data(blob))), blob
            clk.t = 10.0
            mig_a.migrate("acme", LocalTarget(mig_b, url="local://b"))
            for i, blob in enumerate(SUFFIX):
                clk.t = float(20 + i)
                got = _events(
                    reg_b.resolve("acme").engine.analyze_batched(_data(blob))
                )
                assert got == _events(ctl.analyze_batched(_data(blob))), blob
            clk.t = 40.0
            snap = reg_b.resolve("acme").engine.frequency.snapshot()
            assert snap == ctl.frequency.snapshot()
        finally:
            ctl.batcher.close()
            reg_a.shutdown()
            reg_b.shutdown()


# ------------------------------------------------- live stream sessions


class TestStreamHandoff:
    def test_local_target_adopts_live_session(self, root, tmp_path):
        clk = FakeClock()
        reg_a, mig_a = _side(tmp_path, root, "a", clk)
        reg_b, mig_b = _side(tmp_path, root, "b", clk)
        ctl = _control(tmp_path, root, clk)
        try:
            mgr_a = shared_manager(reg_a.resolve("acme").engine)
            sess = mgr_a.open()
            csess = shared_manager(ctl).open()
            blob = ("\n".join(TRAFFIC) + "\n").encode()
            chunks = [blob[i:i + 37] for i in range(0, len(blob), 37)]
            half = len(chunks) // 2
            for i, c in enumerate(chunks[:half]):
                clk.t = float(i + 1)
                assert [f["type"] for f in sess.feed(c)] == [
                    f["type"] for f in csess.feed(c)
                ]
            clk.t = 100.0
            res = mig_a.migrate("acme", LocalTarget(mig_b, url="local://b"))
            # the session MOVED: same object, re-based onto b's engine,
            # no error frame ever reached the client
            assert res["sessionsMoved"] == 1 and res["sessionsClosed"] == 0
            mgr_b = reg_b.resolve("acme").engine.stream_manager
            assert sess.manager is mgr_b
            assert mgr_a.stats()["sessionsMigrated"] == 1
            assert mgr_b.stats()["sessionsAdopted"] == 1
            for i, c in enumerate(chunks[half:]):
                clk.t = float(101 + i)
                assert [f["type"] for f in sess.feed(c)] == [
                    f["type"] for f in csess.feed(c)
                ]
            clk.t = 200.0
            assert [f["type"] for f in sess.close()] == [
                f["type"] for f in csess.close()
            ]
            # streaming frequency commits exactly once, at close: the
            # adopted session's history matches the unmigrated control
            snap = reg_b.resolve("acme").engine.frequency.snapshot()
            assert snap == ctl.frequency.snapshot()
        finally:
            reg_a.shutdown()
            reg_b.shutdown()

    def test_unadoptable_session_closes_with_error_frame(self, root,
                                                         tmp_path):
        # an HttpTarget cannot carry a live socket; the session must be
        # closed with an explicit error frame naming the new owner —
        # never left to hang (satellite: bounded drain of pinned streams)
        class NoAdopt(LocalTarget):
            can_adopt_sessions = False

        reg_a, mig_a = _side(tmp_path, root, "a", None)
        reg_b, mig_b = _side(tmp_path, root, "b", None)
        try:
            sess = shared_manager(reg_a.resolve("acme").engine).open()
            sess.feed(b"an ERROR here\n")
            res = mig_a.migrate("acme", NoAdopt(mig_b, url="local://b"))
            assert res["sessionsClosed"] == 1 and res["sessionsMoved"] == 0
            frames = sess.feed(b"more\n")
            assert frames[-1]["type"] == "error"
            assert frames[-1]["reason"] == "migrated"
            assert "local://b" in frames[-1]["message"]
        finally:
            reg_a.shutdown()
            reg_b.shutdown()


# ----------------------------------------------------- the crash matrix

# every record boundary where the crash_after hook can fire: the two
# terminal records (complete/applied) have nothing after them to lose
CRASH_KINDS = [
    k for k in SOURCE_RECORDS + TARGET_RECORDS
    if k not in ("complete", "applied")
]
# boundaries past the commit point: ownership has moved, recovery must
# finish the handoff; everything earlier recovers to source-owned
POST_CUTOVER = ("cutover", "activate")


class TestCrashMatrix:
    @pytest.mark.parametrize("kind", CRASH_KINDS)
    def test_kill_at_boundary_recovers_single_owner(self, root, tmp_path,
                                                    kind):
        clk = FakeClock()
        reg_a, mig_a = _side(tmp_path, root, "a", clk,
                             crash_after={kind}, journaled=True)
        reg_b, mig_b = _side(tmp_path, root, "b", clk,
                             crash_after={kind}, journaled=True)
        ctl = _control(tmp_path, root, clk, journaled=True)
        for i, blob in enumerate(PREFIX):
            clk.t = float(i + 1)
            got = _events(reg_a.resolve("acme").engine.analyze(_data(blob)))
            assert got == _events(ctl.analyze(_data(blob))), blob
        # pre-migration traffic is durable on both sides; the crash under
        # test is the migration boundary, not the WAL's group fsync
        clk.t = 10.0
        reg_a.resolve("acme").engine.journal.flush()
        ctl.journal.flush()
        with pytest.raises(MigrationCrash):
            mig_a.migrate("acme", LocalTarget(mig_b, url="local://b"))
        # kill -9 both nodes: no shutdown, no flush — fresh registries and
        # Migrators over the same state dirs are the restarted processes
        reg_a2, mig_a2 = _side(tmp_path, root, "a", clk, journaled=True)
        reg_b2, mig_b2 = _side(tmp_path, root, "b", clk, journaled=True)
        ctl2 = _control(tmp_path, root, clk, journaled=True)
        try:
            sum_b = mig_b2.recover()
            sum_a = mig_a2.recover(
                {"local://b": LocalTarget(mig_b2, url="local://b")}
            )
            if kind in POST_CUTOVER:
                # the commit record is durable: ownership moved; recovery
                # re-installs the forward and finishes the handoff
                assert reg_a2.forward_for("acme") == ("local://b", 5)
                assert sum_a["forwards"] == ["acme"]
                assert sum_a["resumed"] or sum_b["resumed"]
                with pytest.raises(TenantForwarded) as ei:
                    reg_a2.resolve("acme")
                assert ei.value.location == "local://b"
                owner = reg_b2
            else:
                # no commit record: the tenant never left; the source
                # journal seals to ABORT and any staged copy is discarded
                assert reg_a2.forward_for("acme") is None
                assert len(sum_a["discarded"]) == 1
                assert not sum_a["forwards"] and not sum_a["resumed"]
                if kind in ("import_ack", "stage", "staged"):
                    assert len(sum_b["discarded"]) == 1
                assert mig_b2.stats()["stagedNow"] == 0
                owner = reg_a2
            for i, blob in enumerate(SUFFIX):
                clk.t = float(20 + i)
                got = _events(
                    owner.resolve("acme").engine.analyze(_data(blob))
                )
                assert got == _events(ctl2.analyze(_data(blob))), (kind, blob)
            # the single owner's frequency history is bit-identical to a
            # run that never migrated (and never crashed mid-protocol)
            clk.t = 40.0
            snap = owner.resolve("acme").engine.frequency.snapshot()
            assert snap == ctl2.frequency.snapshot()
            # exactly ONE owner: the other side either forwards (raises
            # 307) or never had the tenant staged
            loser = reg_a2 if owner is reg_b2 else reg_b2
            if owner is reg_b2:
                with pytest.raises(TenantForwarded):
                    loser.resolve("acme")
            else:
                assert "acme" not in loser.resident()
                assert loser.forward_for("acme") is None
        finally:
            reg_a2.shutdown()
            reg_b2.shutdown()


# ------------------------------------------------ recover idempotency


def _journal_records(state_dir) -> dict[str, int]:
    mdir = state_dir / "_migrate"
    if not mdir.exists():
        return {}
    return {
        p.name: len(MigrationJournal.replay(str(p)))
        for p in sorted(mdir.iterdir())
        if p.suffix == ".wal"
    }


class TestRecoverIdempotency:
    """recover() is a convergence, not a transition: running it twice —
    same process or a double boot — must land on the same forwards and
    append nothing new to an already-sealed journal."""

    def test_double_boot_after_precutover_crash(self, root, tmp_path):
        clk = FakeClock()
        reg_a, mig_a = _side(tmp_path, root, "a", clk,
                             crash_after={"export"}, journaled=True)
        reg_b, mig_b = _side(tmp_path, root, "b", clk, journaled=True)
        reg_a.resolve("acme").engine.analyze(_data(TRAFFIC[0]))
        with pytest.raises(MigrationCrash):
            mig_a.migrate("acme", LocalTarget(mig_b, url="local://b"))
        reg_a.shutdown()
        reg_b.shutdown()
        # first boot seals the journal with ABORT
        reg_a2, mig_a2 = _side(tmp_path, root, "a", clk, journaled=True)
        sum1 = mig_a2.recover()
        assert len(sum1["discarded"]) == 1
        sealed = _journal_records(tmp_path / "a")
        # second boot: already aborted — nothing appended, same answer
        reg_a3, mig_a3 = _side(tmp_path, root, "a", clk, journaled=True)
        sum2 = mig_a3.recover()
        assert sum2 == {"forwards": [], "resumed": [], "discarded": [],
                        "pending": [], "owned": []}
        assert _journal_records(tmp_path / "a") == sealed
        assert mig_a3.recover() == sum2  # and a third pass in-process
        assert reg_a3.forward_for("acme") is None
        reg_a3.resolve("acme")  # still owned here
        reg_a2.shutdown()
        reg_a3.shutdown()

    def test_round_trip_reboot_does_not_resurrect_stale_forward(
        self, root, tmp_path
    ):
        clk = FakeClock()
        reg_a, mig_a = _side(tmp_path, root, "a", clk, journaled=True)
        reg_b, mig_b = _side(tmp_path, root, "b", clk, journaled=True)
        reg_a.resolve("acme").engine.analyze(_data(TRAFFIC[0]))
        # out and back: A -> B, then B -> A. Live round trips work
        # (activate clears the stale forward); the regression was the
        # REBOOT — replaying the old outbound cutover re-installed the
        # forward and the owner 307'd its own tenant forever.
        mig_a.migrate("acme", LocalTarget(mig_b, url="local://b"))
        mig_b.migrate("acme", LocalTarget(mig_a, url="local://a"))
        assert reg_a.forward_for("acme") is None
        reg_a.shutdown()
        reg_b.shutdown()
        reg_a2, mig_a2 = _side(tmp_path, root, "a", clk, journaled=True)
        sum1 = mig_a2.recover()
        assert "acme" not in sum1["forwards"]
        assert reg_a2.forward_for("acme") is None
        reg_a2.resolve("acme")  # A serves: no TenantForwarded
        # double boot converges identically
        sealed = _journal_records(tmp_path / "a")
        assert mig_a2.recover() == sum1
        assert _journal_records(tmp_path / "a") == sealed
        # B's reboot still forwards to A — exactly one owner either side
        reg_b2, mig_b2 = _side(tmp_path, root, "b", clk, journaled=True)
        assert mig_b2.recover()["forwards"] == ["acme"]
        with pytest.raises(TenantForwarded) as ei:
            reg_b2.resolve("acme")
        assert ei.value.location == "local://a"
        reg_a2.shutdown()
        reg_b2.shutdown()


# --------------------------------------------------- bundle integrity


def _bare_bundle(mid="mX", tenant="acme"):
    return {
        "version": BUNDLE_VERSION,
        "mid": mid,
        "tenant": tenant,
        "libraryKey": None,
        "frequency": {"ages": {}, "epoch": 0},
        "pending": [],
        "sessions": [],
    }


class TestBundleIntegrity:
    def test_canonical_bytes_are_key_order_independent(self, root):
        a = canonical_bundle_bytes({"b": 1, "a": [2, {"z": 0, "y": 1}]})
        b = canonical_bundle_bytes({"a": [2, {"y": 1, "z": 0}], "b": 1})
        assert a == b

    def test_stage_rejects_bad_sha_and_version(self, root, tmp_path):
        reg_b, mig_b = _side(tmp_path, root, "b", None)
        try:
            bundle = _bare_bundle()
            sha = hashlib.sha256(canonical_bundle_bytes(bundle)).hexdigest()
            with pytest.raises(MigrationError):
                mig_b.stage_import(bundle, "0" * 64)
            bad = dict(bundle, version=99)
            bad_sha = hashlib.sha256(
                canonical_bundle_bytes(bad)
            ).hexdigest()
            with pytest.raises(MigrationError) as ei:
                mig_b.stage_import(bad, bad_sha)
            assert ei.value.status == 400
            assert mig_b.stats()["stagedNow"] == 0
            # and the well-formed bundle stages + activates
            ack = mig_b.stage_import(bundle, sha)
            assert ack["sha"] == sha
            assert mig_b.stats()["stagedNow"] == 1
            out = mig_b.activate("mX")
            assert out["outcome"] == "activated"
            # a re-sent activate for an already-applied mid acks
            # idempotently — a revived source resuming a post-cutover
            # handoff re-sends it, and re-applying the stale bundle
            # would clobber live served state
            again = mig_b.activate("mX")
            assert again.get("alreadyApplied") is True
            assert again["tenant"] == "acme"
            with pytest.raises(MigrationError) as nf:
                mig_b.activate("mZ")
            assert nf.value.status == 404
        finally:
            reg_b.shutdown()

    def test_bank_content_hash_mismatch_aborts(self, root, tmp_path):
        # the target's acme library drifted: staging must fail and the
        # SOURCE must keep the tenant (scores never silently change)
        drift_root = tmp_path / "drift-tenants"
        d = drift_root / "acme"
        d.mkdir(parents=True)
        (d / "lib.yaml").write_text(ACME_DRIFTED_YAML)
        reg_a, mig_a = _side(tmp_path, root, "a", None)
        reg_b, mig_b = _side(tmp_path, str(drift_root), "b", None)
        try:
            reg_a.resolve("acme").engine.analyze(_data(TRAFFIC[0]))
            with pytest.raises(MigrationError) as ei:
                mig_a.migrate("acme", LocalTarget(mig_b, url="local://b"))
            assert "mismatch" in str(ei.value)
            assert mig_a.stats()["aborted"] == 1
            assert reg_a.forward_for("acme") is None
            assert reg_a.resolve("acme").engine.bank.n_patterns == 2
        finally:
            reg_a.shutdown()
            reg_b.shutdown()


class TestJournal:
    def test_torn_tail_is_quarantined(self, tmp_path):
        path = str(tmp_path / "m.src.wal")
        jr = MigrationJournal(path)
        jr.append("begin", mid="m1", tenant="acme")
        jr.append("quiesce")
        jr.close()
        with open(path, "ab") as f:
            f.write(b"\xff\x00\x00\x00torn-mid-append")
        recs = MigrationJournal.replay(path)
        assert [r["k"] for r in recs] == ["begin", "quiesce"]
        assert os.path.exists(path + ".torn")
        # the torn bytes were truncated away: replay is now clean and a
        # reopened journal appends from the last whole record
        jr2 = MigrationJournal(path)
        jr2.append("abort", reason="test")
        jr2.close()
        assert [r["k"] for r in MigrationJournal.replay(path)] == [
            "begin", "quiesce", "abort",
        ]


# ------------------------------------------------------------- drain


class TestDrain:
    def test_drain_migrates_every_tenant_out(self, root, tmp_path):
        reg_a, mig_a = _side(tmp_path, root, "a", None)
        reg_b, mig_b = _side(tmp_path, root, "b", None)
        try:
            reg_a.resolve("acme").engine.analyze(_data(TRAFFIC[0]))
            reg_a.resolve("globex").engine.analyze(_data(TRAFFIC[1]))
            gate = shared_gate(reg_a.default_engine)
            ds = DrainSupervisor(
                reg_a, mig_a, gate=gate,
                target=LocalTarget(mig_b, url="local://b"), deadline_s=30.0,
            )
            res = ds.drain(reason="test")
            assert sorted(res["migrated"]) == ["acme", "globex"]
            assert res["closed"] == []
            assert ds.draining and gate.draining
            # both tenants now live on b; a forwards both
            assert reg_b.resolve("acme").engine.bank.n_patterns == 2
            assert reg_b.resolve("globex").engine.bank.n_patterns == 1
            for tid in ("acme", "globex"):
                with pytest.raises(TenantForwarded):
                    reg_a.resolve(tid)
            # idempotent: a second drain is a no-op, not a second pass
            assert ds.drain() == {"alreadyDraining": True}
            s = ds.stats()
            assert s["drains"] == 1 and s["tenantsMigrated"] == 2
        finally:
            reg_a.shutdown()
            reg_b.shutdown()

    def test_drain_without_target_bounded_close(self, root, tmp_path):
        # no handoff target AND a live stream session pinning the
        # tenant: drain must still complete, closing the session with an
        # explicit error frame — never an indefinite hang
        reg_a, mig_a = _side(tmp_path, root, "a", None)
        try:
            sess = shared_manager(reg_a.resolve("acme").engine).open()
            sess.feed(b"an ERROR here\n")
            ds = DrainSupervisor(reg_a, mig_a, deadline_s=5.0)
            res = ds.drain(reason="sigterm")
            assert res["closed"] == ["acme"] and res["migrated"] == []
            assert res["elapsedS"] <= 5.0
            frames = sess.feed(b"more\n")
            assert frames[-1]["type"] == "error"
            assert frames[-1]["reason"] == "draining"
            assert ds.stats()["sessionsClosed"] == 1
            assert "acme" not in reg_a.resident()
        finally:
            reg_a.shutdown()

    def test_expired_deadline_forces_close_over_migrate(self, root,
                                                        tmp_path):
        # a target exists, but the deadline is already gone: the bounded
        # local close wins — a stream-pinned tenant cannot hold SIGTERM
        # past --drain-deadline-s
        reg_a, mig_a = _side(tmp_path, root, "a", None)
        reg_b, mig_b = _side(tmp_path, root, "b", None)
        try:
            sess = shared_manager(reg_a.resolve("acme").engine).open()
            sess.feed(b"an ERROR here\n")
            ds = DrainSupervisor(
                reg_a, mig_a, target=LocalTarget(mig_b, url="local://b"),
                deadline_s=0.0,
            )
            res = ds.drain(reason="deadline")
            assert res["closed"] == ["acme"] and res["migrated"] == []
            assert mig_b.stats()["staged"] == 0
            assert sess.feed(b"more\n")[-1]["type"] == "error"
        finally:
            reg_a.shutdown()
            reg_b.shutdown()

    def test_finalize_all_folds_every_resident_tenant(self, root, tmp_path):
        # the satellite-2 pin: shutdown finalization covers EVERY
        # resident tenant's WAL, not just the default engine's
        clk = FakeClock()
        reg_a, mig_a = _side(tmp_path, root, "a", clk, journaled=True)
        try:
            clk.t = 1.0
            reg_a.resolve("acme").engine.analyze(_data(TRAFFIC[0]))
            clk.t = 2.0
            reg_a.resolve("globex").engine.analyze(_data(TRAFFIC[1]))
            clk.t = 5.0
            snaps = {
                tid: reg_a.resolve(tid).engine.frequency.snapshot()
                for tid in ("acme", "globex")
            }
            span_path = str(tmp_path / "spans.jsonl")
            ds = DrainSupervisor(reg_a, mig_a, span_dump_path=span_path)
            out = ds.finalize_all()
            assert sorted(out["folded"]) == ["acme", "globex"]
            assert os.path.exists(span_path)
            # the fold is durable: a restarted side (no clean shutdown)
            # rebuilds both tenants to exactly the finalized state
            reg_a2, _ = _side(tmp_path, root, "a", clk, journaled=True)
            try:
                for tid in ("acme", "globex"):
                    got = reg_a2.resolve(tid).engine.frequency.snapshot()
                    assert got == snaps[tid], tid
            finally:
                reg_a2.shutdown()
        finally:
            reg_a.shutdown()

    def test_health_watch_triggers_one_drain(self, root, tmp_path):
        reg_a, mig_a = _side(tmp_path, root, "a", None)
        try:
            reg_a.resolve("acme")
            verdicts = iter([None, None, "slo-burn"])
            ds = DrainSupervisor(reg_a, mig_a, deadline_s=5.0)
            ds.watch_health(lambda: next(verdicts, "slo-burn"), poll_s=0.01)
            deadline = time.monotonic() + 10.0
            while not ds.draining and time.monotonic() < deadline:
                time.sleep(0.01)
            ds.stop_watch()
            assert ds.draining
            s = ds.stats()
            assert s["drains"] == 1 and s["tenantsClosed"] == 1
        finally:
            reg_a.shutdown()
