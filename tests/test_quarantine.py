"""Poison-request quarantine, per-pattern breakers, and online shadow
verification (runtime/quarantine.py + the engine wiring).

The contract under test: ONE pathological request must not degrade the
rest of the fleet. A fingerprint that keeps killing its device step is
routed straight to the golden host path (never re-entering the device or
a shared batch) until its TTL expires; a device-vs-golden score
divergence surfaced by the shadow verifier contains itself to the
divergent pattern's columns (host-regex override) instead of degrading
the whole engine; and shadow sampling itself adds ZERO frequency drift —
a rate-1.0 run is bit-identical to a no-shadow run.
"""

from __future__ import annotations

import types

import pytest

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.models.pod import PodFailureData
from log_parser_tpu.runtime import AnalysisEngine, faults
from log_parser_tpu.runtime.engine import ShadowVerifier
from log_parser_tpu.runtime.faults import FaultRegistry
from log_parser_tpu.runtime.quarantine import (
    PatternBreakerBoard,
    QuarantineRejected,
    QuarantineTable,
    fingerprint,
)

from conftest import FakeClock
from helpers import make_pattern, make_pattern_set


@pytest.fixture(autouse=True)
def clean_registry():
    faults.install(None)
    yield
    faults.install(None)


def _sets():
    return [
        make_pattern_set(
            [
                make_pattern(
                    "oom", regex="OutOfMemoryError", confidence=0.9
                ),
                make_pattern("conn", regex="Connection refused", confidence=0.7),
            ]
        )
    ]


def _pod(logs: str) -> PodFailureData:
    return PodFailureData(pod={"metadata": {"name": "q"}}, logs=logs)


POISON = "INFO boot\nPOISON-PILL marker\njava OutOfMemoryError"
HEALTHY = "INFO fine\ndial tcp: Connection refused\nINFO done"


def _events(result):
    return [
        (e.line_number, e.matched_pattern.id, e.score) for e in result.events
    ]


# ----------------------------------------------------------- fingerprint


class TestFingerprint:
    def test_stable_and_content_sensitive(self):
        assert fingerprint(POISON) == fingerprint(POISON)
        assert fingerprint(POISON) != fingerprint(HEALTHY)
        assert fingerprint("") == fingerprint("")

    def test_normalization_matches_ingest(self):
        # two different lone surrogates encode (errors="replace") to the
        # same device batch, so they must share one fingerprint — the
        # quarantine keys on what the DEVICE saw, like native/ingest.py
        assert fingerprint("a\ud800b") == fingerprint("a\udfffb")

    def test_shape_bucket_separates_padding_rungs(self):
        # same leading bytes, line counts on different power-of-two rungs
        # → different compiled program → different fingerprint identity
        four = "\n".join(["x"] * 4)
        five = "\n".join(["x"] * 5)
        assert fingerprint(four) != fingerprint(five)


# ------------------------------------------------------- QuarantineTable


class TestQuarantineTable:
    def test_strike_threshold_and_check(self):
        t = QuarantineTable(strikes=2, ttl_s=300.0, clock=FakeClock())
        fp = fingerprint(POISON)
        assert t.strike(fp) is False  # first strike: tracked, not active
        assert t.check(fp) is False
        assert t.strike(fp) is True  # threshold crossed
        assert t.check(fp) is True
        s = t.stats()
        assert s["strikes"] == 2
        assert s["quarantined"] == 1
        assert s["active"] == 1

    def test_ttl_expiry_readmits_with_clean_slate(self):
        clock = FakeClock()
        t = QuarantineTable(strikes=2, ttl_s=10.0, clock=clock)
        fp = fingerprint(POISON)
        t.strike(fp)
        t.strike(fp)
        clock.advance(9.9)
        assert t.check(fp) is True  # still inside the TTL
        clock.advance(0.2)
        assert t.check(fp) is False  # expired: dropped entirely
        assert t.stats()["readmitted"] == 1
        assert t.stats()["tracked"] == 0
        # the slate is clean — one fresh strike must NOT re-quarantine
        assert t.strike(fp) is False

    def test_retry_after_counts_down(self):
        clock = FakeClock()
        t = QuarantineTable(strikes=1, ttl_s=300.0, clock=clock)
        fp = fingerprint(POISON)
        t.strike(fp)
        clock.advance(100.0)
        assert t.retry_after(fp) == pytest.approx(200.0)
        assert t.retry_after("unknown") == 1.0  # floor for untracked fps

    def test_lru_eviction_bounds_memory(self):
        t = QuarantineTable(strikes=2, ttl_s=300.0, capacity=2, clock=FakeClock())
        t.strike("fp-a")
        t.strike("fp-b")
        t.strike("fp-c")  # evicts fp-a (least recently struck)
        s = t.stats()
        assert s["tracked"] == 2
        assert s["evicted"] == 1
        # fp-a's strike history is gone: striking it again starts over
        assert t.strike("fp-a") is False
        # fp-c kept its first strike, so its second crosses the threshold
        assert t.strike("fp-c") is True


# --------------------------------------------------- PatternBreakerBoard


class TestPatternBreakerBoard:
    def test_trip_halfopen_close_cycle(self):
        clock = FakeClock()
        b = PatternBreakerBoard(cooldown_s=5.0, clock=clock)
        assert b.trip("oom") is True
        assert b.overridden_patterns() == {"oom"}
        assert b.any_active() is True
        assert b.probe_pending() is False
        clock.advance(5.1)
        # cool-down expiry: open → half-open, override lifts, probe forced
        assert b.overridden_patterns() == set()
        assert b.probe_pending() is True
        # a clean comparison that SAW the pattern closes it
        b.resolve(seen={"oom", "conn"}, diverged=set())
        assert b.any_active() is False
        s = b.stats()
        assert (s["trips"], s["reopens"], s["closes"]) == (1, 0, 1)

    def test_reopen_from_half_open(self):
        clock = FakeClock()
        b = PatternBreakerBoard(cooldown_s=5.0, clock=clock)
        b.trip("oom")
        clock.advance(5.1)
        b.overridden_patterns()  # transitions to half-open
        assert b.trip("oom") is True  # probe diverged again
        s = b.stats()
        assert s["reopens"] == 1
        assert b.overridden_patterns() == {"oom"}

    def test_resolve_ignores_unseen_patterns(self):
        clock = FakeClock()
        b = PatternBreakerBoard(cooldown_s=5.0, clock=clock)
        b.trip("oom")
        clock.advance(5.1)
        b.overridden_patterns()
        # a corpus that never exercises the pattern proves nothing
        b.resolve(seen={"conn"}, diverged=set())
        assert b.probe_pending() is True
        assert b.stats()["closes"] == 0

    def test_trip_while_open_is_idempotent(self):
        b = PatternBreakerBoard(cooldown_s=5.0, clock=FakeClock())
        assert b.trip("oom") is True
        assert b.trip("oom") is False  # already open: refreshes, no count
        assert b.stats()["trips"] == 1


# ----------------------------------------------------- engine integration


class TestEngineQuarantine:
    def _engine(self, strikes=1, ttl_s=600.0):
        engine = AnalysisEngine(_sets(), ScoringConfig(), clock=FakeClock())
        engine.fallback_to_golden = True
        engine.quarantine = QuarantineTable(
            strikes=strikes, ttl_s=ttl_s, clock=FakeClock()
        )
        return engine

    def test_poison_strikes_then_repeat_never_reaches_device(self):
        serial = AnalysisEngine(_sets(), ScoringConfig(), clock=FakeClock())
        want = _events(serial.analyze_pipelined(_pod(POISON)))
        reg = FaultRegistry.parse("quarantine_raise@match=POISON-PILL")
        faults.install(reg)
        engine = self._engine(strikes=1)

        r1 = engine.analyze_pipelined(_pod(POISON))
        assert _events(r1) == want  # fallback result == device parity
        assert engine.fallback_count == 1
        assert engine.quarantine.stats()["active"] == 1
        fired_after_strike = reg.specs[0].fired

        # the repeat serves from golden WITHOUT touching the device step:
        # the keyed fault sits at the device-step boundary, so its fired
        # counter pinning is proof the request never got there
        r2 = engine.analyze_pipelined(_pod(POISON))
        assert _events(r2) == want
        assert reg.specs[0].fired == fired_after_strike
        assert engine.quarantine.stats()["servedGolden"] == 1
        assert engine.fallback_count == 1  # no second fallback

        # innocent traffic is untouched throughout
        healthy = engine.analyze_pipelined(_pod(HEALTHY))
        assert [e[1] for e in _events(healthy)] == ["conn"]
        assert engine.fallback_count == 1

    def test_ttl_expiry_readmits_to_device(self):
        reg = FaultRegistry.parse("quarantine_raise@match=POISON-PILL@times=1")
        faults.install(reg)
        engine = self._engine(strikes=1, ttl_s=10.0)
        engine.analyze_pipelined(_pod(POISON))  # strike → quarantined
        assert engine.quarantine.stats()["active"] == 1
        calls_quarantined = reg.specs[0].calls

        engine.quarantine.clock.advance(11.0)
        r = engine.analyze_pipelined(_pod(POISON))  # re-admitted: device path
        assert r.events  # fault budget spent (times=1), device serves it
        assert reg.specs[0].calls > calls_quarantined
        assert engine.quarantine.stats()["readmitted"] == 1
        assert engine.quarantine.stats()["active"] == 0

    def test_below_threshold_stays_on_device(self):
        faults.install(
            FaultRegistry.parse("quarantine_raise@match=POISON-PILL@times=1")
        )
        engine = self._engine(strikes=2)
        engine.analyze_pipelined(_pod(POISON))  # one strike of two
        s = engine.quarantine.stats()
        assert s["strikes"] == 1
        assert s["active"] == 0
        assert engine.quarantine.stats()["servedGolden"] == 0

    def test_injected_backend_chaos_never_strikes(self):
        # device_raise simulates BACKEND failure — quarantining the
        # innocent request that happened to be in flight would be wrong
        faults.install(FaultRegistry.parse("device_raise@times=1"))
        engine = self._engine(strikes=1)
        engine.analyze_pipelined(_pod(HEALTHY))
        assert engine.fallback_count == 1
        assert engine.quarantine.stats()["tracked"] == 0

    def test_rejected_429_when_golden_also_fails(self, monkeypatch):
        engine = self._engine(strikes=1, ttl_s=300.0)
        fp = fingerprint(POISON)
        engine.quarantine.strike(fp)

        def _golden_down(data):
            raise RuntimeError("golden down")

        monkeypatch.setattr(engine, "_golden_serve", _golden_down)
        with pytest.raises(QuarantineRejected) as ei:
            engine.analyze_pipelined(_pod(POISON))
        exc = ei.value
        assert exc.status == 429
        assert exc.fingerprint == fp
        assert exc.retry_after_s >= 1.0
        assert engine.quarantine.stats()["rejected"] == 1


# ------------------------------------------------------ shadow verifier


class TestShadowVerifier:
    def test_rate_one_zero_divergence_zero_drift(self):
        stream = [_pod(POISON), _pod(HEALTHY), _pod(POISON), _pod(HEALTHY)]
        plain = AnalysisEngine(_sets(), ScoringConfig(), clock=FakeClock())
        want = [_events(plain.analyze_pipelined(d)) for d in stream]

        engine = AnalysisEngine(_sets(), ScoringConfig(), clock=FakeClock())
        engine.enable_shadow(1.0, seed=0)
        try:
            got = [_events(engine.analyze_pipelined(d)) for d in stream]
            assert engine.shadow.drain(timeout_s=60.0)
            assert got == want  # shadowing never perturbs served scores
            s = engine.shadow.stats()
            assert s["sampled"] == len(stream)
            assert s["compared"] == len(stream)
            assert s["divergences"] == 0
            assert s["errors"] == 0
            # zero frequency drift: the cloned tracker never leaks a
            # record back — both engines hold identical windowed state
            assert (
                engine.frequency._save_state() == plain.frequency._save_state()
            )
        finally:
            engine.shadow.close()

    def test_sampling_is_seed_deterministic(self):
        def decisions(seed):
            engine = AnalysisEngine(_sets(), ScoringConfig(), clock=FakeClock())
            v = ShadowVerifier(engine, rate=0.5, seed=seed)
            return [v.should_sample() for _ in range(64)]

        assert decisions(7) == decisions(7)  # replayable under one seed
        assert decisions(7) != decisions(8)  # and actually seed-driven
        assert 0 < sum(decisions(7)) < 64  # a real Bernoulli stream

    def test_full_queue_drops_instead_of_stalling(self):
        engine = AnalysisEngine(_sets(), ScoringConfig(), clock=FakeClock())
        v = ShadowVerifier(engine, rate=1.0, queue_max=4)  # never started
        result = types.SimpleNamespace(events=[])
        for _ in range(5):
            v.submit(_pod(HEALTHY), {}, result)
        assert v.stats()["dropped"] == 1
        assert v.stats()["queueDepth"] == 4

    def test_synthetic_divergence_breaker_ladder(self):
        clock = FakeClock()
        engine = AnalysisEngine(_sets(), ScoringConfig(), clock=FakeClock())
        engine.breakers = PatternBreakerBoard(cooldown_s=5.0, clock=clock)
        faults.install(FaultRegistry.parse("shadow_raise@times=1"))
        engine.enable_shadow(1.0, seed=0)
        try:
            r1 = engine.analyze_pipelined(_pod(POISON))
            assert engine.shadow.drain(timeout_s=60.0)
            s = engine.shadow.stats()
            assert s["divergences"] == 1
            assert s["lastDivergence"]["synthetic"] is True
            tripped = s["lastDivergence"]["patterns"]
            assert tripped == ["oom"]  # first matched pattern of the request
            assert s["breakers"]["open"] == ["oom"]
            assert engine.breakers.any_active()

            # while OPEN the pattern serves from the exact host regex —
            # scores must be indistinguishable from the device run
            r2 = engine.analyze_pipelined(_pod(POISON))
            assert engine.shadow.drain(timeout_s=60.0)
            assert _events(r2) == _events(r1)
            assert engine.shadow.stats()["divergences"] == 1  # no new ones

            # cool-down: open → half-open; the forced probe runs clean on
            # a request that exercises the pattern, closing the breaker
            clock.advance(5.1)
            r3 = engine.analyze_pipelined(_pod(POISON))
            assert engine.shadow.drain(timeout_s=60.0)
            assert _events(r3) == _events(r1)
            s = engine.shadow.stats()
            assert s["breakers"]["open"] == []
            assert s["breakers"]["halfOpen"] == []
            assert s["breakers"]["closes"] == 1
            assert not engine.breakers.any_active()
        finally:
            engine.shadow.close()
