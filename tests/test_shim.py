"""JVM↔TPU shim: framed-protobuf contract over a socket (the north star's
process boundary — the Quarkus front-end delegates the hot loop here)."""

from __future__ import annotations

import json
import threading

import pytest

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.runtime import AnalysisEngine
from log_parser_tpu.shim import ShimClient, make_shim_server
from log_parser_tpu.shim import logparser_pb2 as pb

from helpers import make_pattern, make_pattern_set


@pytest.fixture(scope="module")
def shim():
    sets = [
        make_pattern_set(
            [
                make_pattern(
                    "oom", regex="OutOfMemoryError", confidence=0.8, severity="HIGH",
                    secondaries=[("GC overhead", 0.6, 10)], context=(1, 1),
                )
            ]
        )
    ]
    engine = AnalysisEngine(sets, ScoringConfig())
    server = make_shim_server(engine, host="127.0.0.1", port=0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield server
    server.shutdown()


def _client(shim) -> ShimClient:
    return ShimClient("127.0.0.1", shim.server_address[1])


def test_parse_roundtrip(shim):
    with _client(shim) as c:
        assert c.health() == "UP"
        resp = c.parse(
            {"metadata": {"name": "web-1"}},
            "boot\nGC overhead limit\njava.lang.OutOfMemoryError: heap\ndone",
        )
        assert resp.analysis_id
        assert resp.summary.highest_severity == "HIGH"
        assert resp.summary.severity_distribution["HIGH"] == 1
        [event] = resp.events
        assert event.line_number == 3
        assert event.context.matched_line.startswith("java.lang.OutOfMemoryError")
        assert list(event.context.lines_before) == ["GC overhead limit"]
        assert event.context.has_lines_before
        pattern = json.loads(event.pattern_json)
        assert pattern["id"] == "oom"
        assert event.score > 0
        assert resp.metadata.total_lines == 4


def test_null_pod_is_client_error(shim):
    with _client(shim) as c:
        env = c.call("Parse", pb.ParseRequest(pod_json="", logs="x"))
        assert env.error == "Invalid PodFailureData provided"


def test_unknown_method(shim):
    with _client(shim) as c:
        env = c.call("Nope", pb.HealthRequest())
        assert "unknown method" in env.error


def test_frequency_surface_and_snapshot(shim):
    with _client(shim) as c:
        c.parse({"metadata": {"name": "w"}}, "java.lang.OutOfMemoryError")
        env = c.call("FrequencyStats", pb.FrequencyStatsRequest())
        stats = pb.FrequencyStatsResponse()
        stats.ParseFromString(env.payload)
        assert stats.windowed_counts["oom"] >= 1

        env = c.call("FrequencySnapshot", pb.FrequencySnapshotRequest())
        snap = pb.FrequencySnapshotResponse()
        snap.ParseFromString(env.payload)
        assert len(snap.ages["oom"].ages_seconds) >= 1

        c.call("FrequencyReset", pb.FrequencyResetRequest())
        env = c.call("FrequencyStats", pb.FrequencyStatsRequest())
        stats = pb.FrequencyStatsResponse()
        stats.ParseFromString(env.payload)
        assert len(stats.windowed_counts) == 0

        restore = pb.FrequencyRestoreRequest()
        restore.ages["oom"].ages_seconds.extend(snap.ages["oom"].ages_seconds)
        c.call("FrequencyRestore", restore)
        env = c.call("FrequencyStats", pb.FrequencyStatsRequest())
        stats = pb.FrequencyStatsResponse()
        stats.ParseFromString(env.payload)
        assert stats.windowed_counts["oom"] >= 1
