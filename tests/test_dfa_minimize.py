"""Differential pin for the DFA minimizer (patterns/regex/minimize.py).

Minimization must be a pure size optimization: the language (single
DFA) and the pointwise per-pattern output behaviour (union multi-DFA)
of every automaton are IDENTICAL before and after the shrink. Both
directions are pinned differentially — exact equivalence through the
product-automaton walkers in analysis/subsumption.py on small automata,
plus byte-walk sampling through the reference executors on everything
(including randomized fuzz libraries), so a bad merge is caught at the
first reachable witness rather than in a kernel parity failure three
layers up. Structural invariants ride along: the single-DFA MATCHED
sink stays state 0 (match.py packs bit 30 off its absorbing row),
renumbering is deterministic, minimization is idempotent, and
``n_states_unmin`` provenance survives for the kernel-plan geometry.
"""

from __future__ import annotations

import random

import numpy as np

from log_parser_tpu.analysis.subsumption import (
    EQUAL,
    UNDECIDED,
    compare_dfas,
    compare_multi_dfas,
)
from log_parser_tpu.patterns.regex.dfa import compile_nfa_to_dfa
from log_parser_tpu.patterns.regex.minimize import (
    minimize_dfa,
    minimize_multi_dfa,
)
from log_parser_tpu.patterns.regex.multidfa import compile_union_regexes
from log_parser_tpu.patterns.regex.nfa import build_nfa
from log_parser_tpu.patterns.regex.parser import parse_java_regex
from tests.test_multidfa import LINES, REGEXES


def _raw_single(rx: str, ci: bool = False):
    """Unminimized single DFA with find() semantics (the exact automaton
    compile_regex_to_dfa minimizes on the Python path)."""
    nfa = build_nfa(parse_java_regex(rx, ci), unanchored_prefix=True)
    return compile_nfa_to_dfa(nfa, regex=rx)


def _sample_lines(rng: random.Random, n: int = 120) -> list[bytes]:
    alphabet = "abE R:137fostdx.FGCpnic"
    return [
        "".join(
            rng.choice(alphabet) for _ in range(rng.randrange(0, 48))
        ).encode()
        for _ in range(n)
    ] + [ln.encode() for ln in LINES]


# ------------------------------------------------------------- union DFAs


def test_union_minimize_output_bisimulation_equal():
    raw = compile_union_regexes(REGEXES, minimize=False)
    mini = minimize_multi_dfa(raw)
    assert mini.n_states <= raw.n_states
    assert mini.n_classes <= raw.n_classes
    assert mini.n_states_unmin == raw.n_states
    assert compare_multi_dfas(raw, mini) == EQUAL


def test_union_minimize_byte_walk_parity():
    raw = compile_union_regexes(REGEXES, minimize=False)
    mini = minimize_multi_dfa(raw)
    for data in _sample_lines(random.Random(3)):
        np.testing.assert_array_equal(
            raw.matches(data), mini.matches(data), err_msg=repr(data)
        )


def test_union_minimize_shrinks_shared_suffixes(monkeypatch):
    """Distinct alternation branches with a common tail are exactly what
    subset construction duplicates and minimization merges — the shrink
    must be real, not a no-op rename. Forces the Python construction
    path: the native union builder Moore-minimizes as it packs, so its
    output has no duplicated suffix states left to shrink."""
    import log_parser_tpu.native.dfabuild as dfabuild

    monkeypatch.setattr(dfabuild, "get_lib", lambda: None)
    regexes = [("abcdefgh|xbcdefgh|ybcdefgh", False), ("zzcdefgh", False)]
    raw = compile_union_regexes(regexes, minimize=False)
    mini = minimize_multi_dfa(raw)
    assert mini.n_states < raw.n_states
    assert compare_multi_dfas(raw, mini) == EQUAL


def test_union_minimize_deterministic_and_idempotent():
    raw = compile_union_regexes(REGEXES, minimize=False)
    a = minimize_multi_dfa(raw)
    b = minimize_multi_dfa(raw)
    np.testing.assert_array_equal(a.trans, b.trans)
    np.testing.assert_array_equal(a.byte_class, b.byte_class)
    np.testing.assert_array_equal(a.out2, b.out2)
    np.testing.assert_array_equal(a.accept_words, b.accept_words)
    assert a.start == b.start
    again = minimize_multi_dfa(a)
    assert again.n_states == a.n_states
    assert again.n_classes == a.n_classes
    np.testing.assert_array_equal(again.trans, a.trans)


def test_union_fuzz_libraries():
    """Randomized regex libraries over the supported dialect: every
    library's union automaton must survive minimization with byte-walk
    parity, and with product-walk equality whenever the product fits."""
    frags = [
        "ERROR", "FATAL", "panic: ", "a{2,4}b", "st[aeiou]rt", "foo$",
        "^start", "exit code 137", "x?", "no such host", "\\bGC\\b",
        "s.gfault", "re(d|try)", "[0-9a-f]{4}",
    ]
    rng = random.Random(17)
    lines = _sample_lines(rng)
    for _ in range(8):
        k = rng.randrange(2, 7)
        lib = [(rng.choice(frags), rng.random() < 0.3) for _ in range(k)]
        raw = compile_union_regexes(lib, minimize=False)
        mini = minimize_multi_dfa(raw)
        verdict = compare_multi_dfas(raw, mini)
        assert verdict in (EQUAL, UNDECIDED), (lib, verdict)
        for data in lines:
            np.testing.assert_array_equal(
                raw.matches(data), mini.matches(data),
                err_msg=f"{lib} on {data!r}",
            )


# ------------------------------------------------------------ single DFAs


def test_single_minimize_language_equal():
    for rx, ci in REGEXES:
        raw = _raw_single(rx, ci)
        mini = minimize_dfa(raw)
        assert mini.n_states <= raw.n_states, rx
        assert compare_dfas(raw, mini) == EQUAL, rx


def test_single_minimize_matches_parity():
    rng = random.Random(5)
    lines = _sample_lines(rng)
    for rx, ci in REGEXES:
        raw = _raw_single(rx, ci)
        mini = minimize_dfa(raw)
        for data in lines:
            assert raw.matches(data) == mini.matches(data), (rx, data)


def test_single_minimize_keeps_matched_sink_at_zero():
    """match.py's packed-word layout and the sticky-report invariant both
    lean on state 0 being the absorbing accepting sink; minimization must
    renumber around it, never through it."""
    for rx in ("ERROR", "status.*red", "a{2,4}b"):
        mini = minimize_dfa(_raw_single(rx))
        assert bool(mini.accept_end[0])
        assert (np.asarray(mini.trans[0]) == 0).all()
