"""Known-bad input for tools/conlint.py (tests/test_conlint.py).

Each ``bad_*`` function violates exactly one concurrency invariant the
checker enforces; each ``ok_*`` function is a near-miss the checker must
NOT flag. This file lives outside conlint's default scan scope
(runtime/, serve/, parallel/) and is never imported by the runtime —
it only needs to parse.
"""

import subprocess
import threading
import time


class _FakeEngine:
    def __init__(self):
        self.state_lock = threading.Lock()
        self._scopes = threading.Semaphore(64)
        self.faults = _FakeFaults()

    def _request_scope(self):
        return self._scopes


class _FakeFaults:
    def fire(self, site, key=""):
        pass


def bad_lock_order_nested(engine):
    # request-scope entered while state_lock held -> conlint-lock-order
    with engine.state_lock:
        with engine._request_scope():
            return 1


def bad_lock_order_single_with(engine):
    # same inversion in one with statement (items enter left-to-right)
    with engine.state_lock, engine._request_scope():
        return 1


def bad_sleep_under_lock(engine):
    with engine.state_lock:
        time.sleep(0.1)  # -> conlint-blocking-under-lock


def bad_join_under_lock(engine, worker):
    engine.state_lock.acquire()
    try:
        worker.join(timeout=5.0)  # -> conlint-blocking-under-lock
    finally:
        engine.state_lock.release()


def bad_wait_under_lock(engine, event):
    with engine.state_lock:
        event.wait()  # -> conlint-blocking-under-lock


def bad_subprocess_under_lock(engine):
    with engine.state_lock:
        subprocess.run(["true"])  # -> conlint-blocking-under-lock


def bad_uncontained_fire(engine):
    engine.faults.fire("device")  # -> conlint-uncontained-fire
    return 2


def ok_scope_then_lock(engine):
    # the documented order: quiesce gate first, then the lock
    with engine._request_scope(), engine.state_lock:
        return 1


def ok_str_join_under_lock(engine, parts):
    # str.join takes one iterable positional: not a thread join
    with engine.state_lock:
        return ",".join(parts)


def ok_sleep_after_release(engine, worker):
    engine.state_lock.acquire()
    try:
        pass
    finally:
        engine.state_lock.release()
    time.sleep(0.01)
    worker.join()


def ok_contained_fire(engine):
    try:
        engine.faults.fire("device")
    except RuntimeError:
        return None
    return 2


def ok_waived_fire(engine):
    engine.faults.fire("ingest")  # conlint: contained-by-caller (fixture)
    return 3
