"""Test harness configuration.

Forces JAX onto a virtual 8-device CPU backend *before* jax is imported
anywhere, so `shard_map`/mesh tests exercise real multi-device sharding
without TPU hardware (the standard JAX fake-backend idiom — SURVEY.md §4).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon TPU plugin's sitecustomize forces jax_platforms="axon,cpu" at the
# *config* level, overriding the env var — pin it back so the test suite
# never initializes (or blocks on) the single-session TPU tunnel.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# device bugs must never hide behind the golden-host insurance path; the
# fallback itself is tested explicitly with it re-enabled (test_fallback.py)
os.environ["LOG_PARSER_TPU_NO_FALLBACK"] = "1"

# keep the suite hermetic: never read or write the user-level persistent
# XLA executable cache (entries written under different XLA_FLAGS emit
# machine-feature mismatch warnings on load)
os.environ["LOG_PARSER_TPU_XLA_CACHE"] = "0"

# ... and never the user-level DFA/bank/AC caches either: a warm bank
# snapshot would silently bypass the bank-construction code a test run is
# meant to exercise. One shared per-run directory keeps repeat builds
# within the run fast (tests that need cold/warm control, like
# test_libcache.py, monkeypatch LOG_PARSER_TPU_CACHE themselves).
import atexit  # noqa: E402
import shutil  # noqa: E402
import tempfile  # noqa: E402

_cache_root = tempfile.mkdtemp(prefix="lpt-test-cache-")
os.environ["LOG_PARSER_TPU_CACHE"] = _cache_root
atexit.register(shutil.rmtree, _cache_root, ignore_errors=True)

import pytest  # noqa: E402

from log_parser_tpu.config import ScoringConfig  # noqa: E402


@pytest.fixture
def default_config() -> ScoringConfig:
    return ScoringConfig()


class FakeClock:
    """Deterministic, manually-advanced clock for frequency-window tests."""

    def __init__(self, start: float = 0.0):
        self.now = start

    def __call__(self) -> float:
        return self.now

    def advance(self, seconds: float) -> None:
        self.now += seconds


@pytest.fixture
def fake_clock() -> FakeClock:
    return FakeClock()
