"""Admission control + the degradation ladder (serve/admission.py) under
injected chaos (runtime/faults.py).

The acceptance scenario from the robustness issue runs here end-to-end
with a fixed fault seed: device-path 200s → host-path 200s with the
breaker open → 429s with Retry-After once the queue bound is hit → a
drain where in-flight work completes, readiness goes 503, and the serve
loop exits cleanly."""

from __future__ import annotations

import json
import signal
import threading
import time
import urllib.error
import urllib.request

import pytest

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.runtime import AnalysisEngine, faults
from log_parser_tpu.runtime.engine import DeviceWatchdog
from log_parser_tpu.runtime.faults import FaultRegistry
from log_parser_tpu.serve import make_server
from log_parser_tpu.serve.admission import (
    AdmissionController,
    AdmissionRejected,
    install_drain_handlers,
    shared_gate,
)
from log_parser_tpu.shim.client import ShimClient
from log_parser_tpu.shim.server import make_shim_server

from conftest import FakeClock
from helpers import make_pattern, make_pattern_set

pytestmark = pytest.mark.chaos

LOGS = "ok\nERROR boom\nok"
POD = {"pod": {"metadata": {"name": "p"}}, "logs": LOGS}


def _sets():
    return [make_pattern_set([make_pattern("e", regex="ERROR", confidence=0.7)])]


@pytest.fixture(autouse=True)
def clean_registry():
    faults.install(None)
    yield
    faults.install(None)


def _post(url, payload=POD, headers=None):
    """(status, body, response headers) for one POST."""
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _get(url):
    try:
        with urllib.request.urlopen(url) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _await(predicate, timeout=5.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.005)
    raise AssertionError(f"timed out waiting for {what}")


class _Client(threading.Thread):
    """One request on its own thread, result captured for later asserts."""

    def __init__(self, url, payload=POD, headers=None):
        super().__init__(daemon=True)
        self.url, self.payload, self.headers = url, payload, headers
        self.result = None
        self.start()

    def join_result(self, timeout=30):
        self.join(timeout)
        assert not self.is_alive(), "client request never completed"
        return self.result

    def run(self):
        self.result = _post(self.url, self.payload, self.headers)


@pytest.fixture
def served_engine():
    """Engine + HTTP server on an ephemeral port; gate/watchdog are set
    per-test BEFORE the fixture is used via the returned builder."""
    state = {}

    def build(gate=None, watchdog=None, fallback=True):
        engine = AnalysisEngine(_sets(), ScoringConfig(), clock=FakeClock())
        engine.fallback_to_golden = fallback
        if watchdog is not None:
            engine.watchdog = watchdog
        if gate is not None:
            engine.admission_gate = gate  # shared_gate() will find it
        server = make_server(engine, host="127.0.0.1", port=0)
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        state.update(engine=engine, server=server, thread=thread)
        return engine, server, f"http://127.0.0.1:{server.server_address[1]}", thread

    yield build
    if state:
        state["server"].shutdown()
        state["server"].server_close()


# --------------------------------------------------------------- unit level


class TestController:
    def test_routes_and_counters(self):
        gate = AdmissionController(max_inflight=2, max_queue=1)
        assert gate.acquire() == "device"
        assert gate.acquire() == "device"
        # saturated now; a queued acquire on another thread degrades to host
        got = []
        t = threading.Thread(target=lambda: got.append(gate.acquire()))
        t.start()
        _await(lambda: gate.queued == 1, what="waiter to queue")
        # queue full: the next arrival sheds immediately with 429
        with pytest.raises(AdmissionRejected) as exc:
            gate.acquire()
        assert exc.value.status == 429 and exc.value.reason == "queue full"
        assert exc.value.retry_after_s >= 1
        gate.release()
        t.join(5)
        assert got == ["host"]
        stats = gate.stats()
        assert stats["admittedDevice"] == 2
        assert stats["admittedHost"] == 1
        assert stats["shedQueueFull"] == 1

    def test_unbounded_mode_still_counts_inflight(self):
        gate = AdmissionController()  # max_inflight=0: no shedding...
        for _ in range(5):
            assert gate.acquire() == "device"
        assert gate.inflight == 5  # ...but drain can still wait for work
        for _ in range(5):
            gate.release()
        assert gate.wait_idle(0.1)

    def test_deadline_sheds_queued_request(self):
        gate = AdmissionController(max_inflight=1, max_queue=2)
        gate.acquire()
        with pytest.raises(AdmissionRejected) as exc:
            gate.acquire(deadline_ms=50)  # slot never frees within 50ms
        assert exc.value.reason == "deadline"
        assert gate.stats()["shedDeadline"] == 1
        gate.release()

    def test_default_deadline_applies_when_no_header(self):
        gate = AdmissionController(
            max_inflight=1, max_queue=2, default_deadline_ms=50
        )
        gate.acquire()
        with pytest.raises(AdmissionRejected) as exc:
            gate.acquire()  # None -> default 50ms budget
        assert exc.value.reason == "deadline"
        gate.release()

    def test_drain_rejects_and_wakes_waiters(self):
        gate = AdmissionController(max_inflight=1, max_queue=2)
        gate.acquire()
        errors = []

        def waiter():
            try:
                gate.acquire()
            except AdmissionRejected as exc:
                errors.append(exc)

        t = threading.Thread(target=waiter)
        t.start()
        _await(lambda: gate.queued == 1, what="waiter to queue")
        gate.begin_drain()
        t.join(5)
        assert errors and errors[0].status == 503
        with pytest.raises(AdmissionRejected):
            gate.acquire()
        assert not gate.wait_idle(0.05)  # one still in flight
        gate.release()
        assert gate.wait_idle(1.0)

    def test_shared_gate_is_one_per_engine(self):
        engine = AnalysisEngine(_sets(), ScoringConfig(), clock=FakeClock())
        assert shared_gate(engine) is shared_gate(engine)


# ---------------------------------------------------------------- the ladder


class TestDegradationLadder:
    def test_full_ladder_under_injected_hang(self, served_engine):
        """The acceptance scenario, seeded and sequenced deterministically:
        (a) device-path 200s, (b) host-path 200s with the breaker open,
        (c) 429 + Retry-After at the queue bound, (d) drain: in-flight
        completes, readiness 503, serve loop exits cleanly."""
        gate = AdmissionController(max_inflight=1, max_queue=1)
        engine, server, url, serve_thread = served_engine(
            gate=gate,
            # long cooldown: no half-open probe interferes mid-test
            watchdog=DeviceWatchdog(timeout_s=60.0, cooldown_s=60.0),
        )
        # warm-up takes the one-time XLA compile off the watchdog clock,
        # then the deadline drops to something a wedge will overrun
        assert _post(url + "/parse")[0] == 200
        engine.watchdog.timeout_s = 0.3
        faults.install(
            FaultRegistry.parse(
                # device call 3 wedges for good; ingest calls 4-5 are slow
                # (they hold the admission slot so the queue fills)
                "device_hang:inf@after=2@times=1,"
                "ingest_slow:1.0@after=3@times=2",
                seed=42,
            )
        )

        # (a) full service: two requests on the device path
        for _ in range(2):
            status, body, _ = _post(url + "/parse")
            assert status == 200 and body["summary"]["significantEvents"] == 1
        assert engine.fallback_count == 0

        # (b) the injected wedge: watchdog times out, breaker opens, the
        # request is still answered 200 from the host path
        status, body, _ = _post(url + "/parse")
        assert status == 200 and body["summary"]["significantEvents"] == 1
        assert engine.fallback_count == 1
        assert engine.watchdog.circuit_open
        _, health = _get(url + "/health")
        assert health["checks"] == [{"name": "device", "status": "DEGRADED"}]

        # (c) saturate: A holds the one slot (slow ingest), B queues (will
        # degrade to host ROUTING, not fallback), C finds the queue full
        a = _Client(url + "/parse")
        _await(lambda: gate.inflight == 1, what="A to hold the slot")
        b = _Client(url + "/parse")
        _await(lambda: gate.queued == 1, what="B to queue")
        status, body, headers = _post(url + "/parse")  # C
        assert status == 429
        assert body == {"error": "overloaded", "reason": "queue full"}
        assert int(headers["Retry-After"]) >= 1

        status, _, _ = a.join_result()
        assert status == 200  # A: breaker open -> host path serves it
        status, _, _ = b.join_result()
        assert status == 200  # B: routed to the host path by the gate
        assert engine.host_routed_count == 1
        assert engine.fallback_count == 2  # request (b) + A

        _, trace = _get(url + "/trace/last")
        assert trace["admission"]["shedQueueFull"] == 1
        assert trace["admission"]["admittedHost"] == 1
        assert trace["hostRoutedCount"] == 1
        assert trace["faults"]["seed"] == 42
        assert trace["faults"]["fired"]["device_hang"] == 1
        assert trace["faults"]["fired"]["ingest_slow"] >= 1

        # (d) drain: D in flight (slow ingest), then the SIGTERM handler
        old_term = signal.getsignal(signal.SIGTERM)
        old_int = signal.getsignal(signal.SIGINT)
        try:
            import logging

            handler = install_drain_handlers(
                server, gate, logging.getLogger("test-drain")
            )
            d = _Client(url + "/parse")
            _await(lambda: gate.inflight == 1, what="D to hold the slot")
            handler(signal.SIGTERM, None)
            _await(lambda: gate.draining, what="drain to begin")
            status, _ = _get(url + "/health/ready")
            assert status == 503
            status, body, headers = _post(url + "/parse")
            assert status == 503 and body["reason"] == "draining"
            assert "Retry-After" in headers
            status, _, _ = d.join_result()
            assert status == 200  # in-flight work finished during drain
            serve_thread.join(10)
            assert not serve_thread.is_alive()  # serve loop exited cleanly
        finally:
            signal.signal(signal.SIGTERM, old_term)
            signal.signal(signal.SIGINT, old_int)

    def test_deadline_header_sheds_at_queue_head(self, served_engine):
        """A queued request whose X-Request-Deadline-Ms expires before a
        slot frees is shed with 429 instead of doing dead work."""
        faults.install(FaultRegistry.parse("ingest_slow:1.0@times=1", seed=1))
        gate = AdmissionController(max_inflight=1, max_queue=2)
        engine, server, url, _ = served_engine(gate=gate)

        a = _Client(url + "/parse")
        _await(lambda: gate.inflight == 1, what="A to hold the slot")
        status, body, headers = _post(
            url + "/parse", headers={"X-Request-Deadline-Ms": "80"}
        )
        assert status == 429 and body["reason"] == "deadline"
        assert "Retry-After" in headers
        assert a.join_result()[0] == 200
        assert gate.stats()["shedDeadline"] == 1

    def test_bad_deadline_header_is_400(self, served_engine):
        _, _, url, _ = served_engine()
        status, body, _ = _post(
            url + "/parse", headers={"X-Request-Deadline-Ms": "soon"}
        )
        assert status == 400


# ------------------------------------------------------- cross-transport gate


class TestSharedGateAcrossTransports:
    def test_http_saturation_sheds_on_shim(self, served_engine):
        """ONE semaphore guards every transport: filling it over HTTP makes
        the framed shim shed, and vice versa once the slot frees."""
        faults.install(FaultRegistry.parse("ingest_slow:1.2@times=1", seed=3))
        gate = AdmissionController(max_inflight=1, max_queue=0)
        engine, server, url, _ = served_engine(gate=gate)
        shim = make_shim_server(engine, host="127.0.0.1", port=0)
        shim_port = shim.server_address[1]
        assert shim.admission is gate  # same object, not a twin
        shim_thread = threading.Thread(target=shim.serve_forever, daemon=True)
        shim_thread.start()
        try:
            a = _Client(url + "/parse")  # HTTP holds the only slot
            _await(lambda: gate.inflight == 1, what="HTTP to hold the slot")
            # retries=0: observe the raw shed — the client's default
            # Retry-After honoring would wait out the hint and succeed
            # once the HTTP request releases the slot
            with ShimClient("127.0.0.1", shim_port, retries=0) as client:
                with pytest.raises(ValueError, match="overloaded"):
                    client.parse(POD["pod"], POD["logs"])
                assert a.join_result()[0] == 200
                # slot free again: the shim serves
                resp = client.parse(POD["pod"], POD["logs"])
                assert resp.summary.significant_events == 1
            assert gate.stats()["shedQueueFull"] == 1
        finally:
            shim.shutdown()
            shim.server_close()


# ----------------------------------------------------------- half-open probe


class TestHalfOpenProbe:
    def test_probe_restores_device_serving_with_abandoned_workers(
        self, served_engine
    ):
        """Acceptance: a permanent injected hang opens the circuit and its
        workers never respond; once injection stops (times= exhausted),
        the half-open probe closes the circuit again — with the abandoned
        workers STILL outstanding. The old close-on-last-worker rule alone
        would have left the breaker stuck open forever."""
        watchdog = DeviceWatchdog(timeout_s=60.0, cooldown_s=0.35)
        engine, server, url, _ = served_engine(watchdog=watchdog)
        # compile the device path before the tight deadline applies, so
        # the final probe measures the real step, not XLA compilation
        assert _post(url + "/parse")[0] == 200
        watchdog.timeout_s = 0.15
        faults.install(FaultRegistry.parse("device_hang:inf@times=2", seed=5))

        # hang #1: breaker opens, golden answers
        status, _, _ = _post(url + "/parse")
        assert status == 200
        assert engine.fallback_count == 1 and watchdog.circuit_open

        # inside the cool-down: NO probe — instant host path, the wedged
        # backend is not re-entered
        status, _, _ = _post(url + "/parse")
        assert status == 200
        assert engine.fallback_count == 2
        assert faults.active().counts()["device_hang"] == 1

        # cool-down elapsed: the next request is the half-open trial; it
        # meets hang #2, times out, and re-arms the breaker
        time.sleep(0.4)
        status, _, _ = _post(url + "/parse")
        assert status == 200
        assert engine.fallback_count == 3 and watchdog.circuit_open
        assert faults.active().counts()["device_hang"] == 2

        # injection exhausted. After another cool-down the probe reaches
        # the real device, succeeds, and closes the circuit even though
        # both abandoned workers are still parked in their hang.
        time.sleep(0.4)
        status, body, _ = _post(url + "/parse")
        assert status == 200 and body["summary"]["significantEvents"] == 1
        assert not watchdog.circuit_open
        assert engine.fallback_count == 3  # the probe served on-device
        with watchdog._lock:
            assert watchdog._inflight == 2  # abandoned workers outstanding

        # recovered: subsequent requests take the device path directly
        status, _, _ = _post(url + "/parse")
        assert status == 200 and engine.fallback_count == 3
