"""Java-compatibility primitives: split semantics and the regex dialect."""

import pytest

from log_parser_tpu.golden.javacompat import (
    compile_java_regex,
    java_split_lines,
    translate_java_regex,
)


class TestJavaSplitLines:
    """Java String.split("\\r?\\n") semantics — AnalysisService.java:53."""

    def test_plain(self):
        assert java_split_lines("a\nb\nc") == ["a", "b", "c"]

    def test_crlf(self):
        assert java_split_lines("a\r\nb\r\nc") == ["a", "b", "c"]

    def test_trailing_newline_dropped(self):
        # Java drops trailing empty strings
        assert java_split_lines("a\nb\n") == ["a", "b"]
        assert java_split_lines("a\n\n\n") == ["a"]

    def test_interior_empty_kept(self):
        assert java_split_lines("a\n\nb") == ["a", "", "b"]

    def test_empty_string_is_one_line(self):
        # "".split(regex) returns [""] in Java
        assert java_split_lines("") == [""]

    def test_only_newlines_is_empty(self):
        # "\n\n".split returns an empty array in Java
        assert java_split_lines("\n") == []
        assert java_split_lines("\n\n") == []

    def test_leading_empty_kept(self):
        assert java_split_lines("\na") == ["", "a"]

    def test_lone_cr_not_a_separator(self):
        assert java_split_lines("a\rb") == ["a\rb"]


class TestJavaRegex:
    def test_find_semantics_is_substring_search(self):
        # Matcher.find() (AnalysisService.java:95) == re.search
        assert compile_java_regex("Error").search("an Error occurred")

    def test_ascii_word_boundary(self):
        # Java \b is ASCII by default; é must not count as a word char
        pat = compile_java_regex(r"\bERROR\b")
        assert pat.search("éERROR!")  # boundary exists before E in Java (é non-word)
        assert not pat.search("xERRORy")

    def test_case_insensitive(self):
        pat = compile_java_regex(r"\b(WARN|WARNING)\b", case_insensitive=True)
        assert pat.search("2024 warn: disk")
        assert pat.search("warning-free")  # '-' is a boundary after WARNING
        assert not pat.search("warned")  # no boundary after WARN, WARNING absent

    def test_posix_class_translation(self):
        assert translate_java_regex(r"\p{Digit}+") == "[0-9]+"
        assert compile_java_regex(r"\p{Alpha}+").search("abc")

    def test_possessive_quantifier_rejected(self):
        with pytest.raises(ValueError):
            translate_java_regex(r"a*+b")

    def test_atomic_group_rejected(self):
        with pytest.raises(ValueError):
            translate_java_regex(r"(?>ab)c")

    def test_unknown_posix_class_rejected(self):
        with pytest.raises(ValueError):
            translate_java_regex(r"\p{IsGreek}")

    def test_escaped_plus_not_possessive(self):
        # C\++ is a literal '+' quantified — valid Java, not possessive
        assert translate_java_regex(r"C\++") == r"C\++"
        assert compile_java_regex(r"C\++").search("C++ rocks")

    def test_quantifier_chars_in_class_are_literals(self):
        assert translate_java_regex(r"[?+]") == r"[?+]"
        assert compile_java_regex(r"[?+]").search("a+b")

    def test_posix_class_inside_character_class(self):
        # [\p{Alpha}_] must splice contents, not nest brackets
        assert translate_java_regex(r"[\p{Alpha}_]+") == "[a-zA-Z_]+"
        pat = compile_java_regex(r"[\p{Alpha}_]+")
        assert pat.fullmatch("abc_DEF")

    def test_named_group_translated(self):
        pat = compile_java_regex(r"(?<code>\d+) error")
        m = pat.search("status 404 error")
        assert m and m.group("code") == "404"

    def test_named_backref_translated(self):
        pat = compile_java_regex(r"(?<w>\w+) \k<w>")
        assert pat.search("again again")

    def test_lookbehind_untouched(self):
        pat = compile_java_regex(r"(?<=ERROR )\d+")
        assert pat.search("ERROR 42").group(0) == "42"

    def test_lazy_quantifier_kept(self):
        # '.' is rewritten to Java's terminator-excluding class; laziness kept
        translated = translate_java_regex(r"a.*?b")
        assert translated.startswith("a[^") and translated.endswith("]*?b")

    def test_brace_quantifier_possessive_rejected(self):
        with pytest.raises(ValueError):
            translate_java_regex(r"a{2,3}+")

    def test_literal_brace_plus_ok(self):
        # '}' here is a literal, not a quantifier close — '}+' is fine
        assert translate_java_regex(r"x}+") == r"x}+"
        assert compile_java_regex(r"x}+").search("x}}}")

    def test_class_intersection_rejected(self):
        with pytest.raises(ValueError):
            translate_java_regex(r"[a-z&&[^aeiou]]")

    def test_nested_class_rejected(self):
        with pytest.raises(ValueError):
            translate_java_regex(r"[a[b]]")

    def test_mid_pattern_inline_flags_rejected(self):
        with pytest.raises(ValueError):
            translate_java_regex(r"a(?i)b")
        # at position 0 Python accepts global flags — passes through
        assert compile_java_regex(r"(?i)warn").search("WARN")

    def test_dot_excludes_carriage_return(self):
        # Java '.' excludes \r; Python's does not — must be translated
        assert not compile_java_regex(r"a.b").search("a\rb")
        assert compile_java_regex(r"a.b").search("axb")

    def test_dollar_before_trailing_cr(self):
        # Java $ matches before a final line terminator (lone \r included)
        assert compile_java_regex(r"c$").search("abc\r")
        assert compile_java_regex(r"c$").search("abc")
        assert not compile_java_regex(r"c$").search("abc\rx")
        assert not compile_java_regex(r"c$").search("abc\r\r")

    def test_java_z_escapes(self):
        assert not compile_java_regex(r"c\z").search("abc\r")  # absolute end
        assert compile_java_regex(r"c\z").search("abc")
        assert compile_java_regex(r"c\Z").search("abc\r")  # before final term


class TestQuoting:
    def test_quoted_run_is_literal(self):
        # Java \Q...\E quotes metachars; Python re has no \Q — the
        # translation splices the run in escaped (passing \Q through
        # made re.compile reject and the whole pattern skip at boot,
        # a parity gap against the Java engine)
        p = compile_java_regex(r"start \Qa.b*c\E end")
        assert p.search("xx start a.b*c end yy")
        assert not p.search("start aXbYc end")

    def test_unterminated_quote_runs_to_end(self):
        assert compile_java_regex(r"\Qtail.").search("has tail. here")
        assert not compile_java_regex(r"\Qtail.").search("has tailX here")

    def test_quoted_engine_golden_parity(self):
        # end-to-end: a \Q pattern must now produce events (device parser
        # already supported it; the host gate was the blocker) and agree
        # with the golden engine exactly
        from helpers import make_pattern, make_pattern_set

        from log_parser_tpu.config import ScoringConfig
        from log_parser_tpu.golden import GoldenAnalyzer
        from log_parser_tpu.models.pod import PodFailureData
        from log_parser_tpu.runtime import AnalysisEngine

        sets = [make_pattern_set([
            make_pattern("pq", regex=r"err \Qcode[3]\E hit", confidence=0.9),
        ])]
        data = PodFailureData(
            pod={"metadata": {"name": "q"}},
            logs="ok line\nerr code[3] hit now\nerr codeX3Y hit\n",
        )
        got = AnalysisEngine(sets, ScoringConfig()).analyze(data)
        want = GoldenAnalyzer(sets, ScoringConfig()).analyze(data)
        assert [e.line_number for e in got.events] == [2]
        assert [e.line_number for e in want.events] == [2]
        assert abs(got.events[0].score - want.events[0].score) < 1e-9

    def test_quantifier_after_quoted_run_binds_to_last_char(self):
        # Java binds {2} to the last quoted char: "ab{2}" matches "abb"
        p = compile_java_regex(r"x \Qab\E{2} y")
        assert p.search("x abb y")
        assert not p.search("x abab y")
        # the DEVICE parser declines this shape (it holds the run as one
        # atom), so the engine serves it host-side — results must still
        # match golden exactly
        import pytest

        from log_parser_tpu.patterns.regex.parser import (
            RegexUnsupportedError,
            parse_java_regex,
        )

        with pytest.raises(RegexUnsupportedError):
            parse_java_regex(r"x \Qab\E{2} y")
        # single-char runs stay device-parseable (binding is unambiguous)
        parse_java_regex(r"x \Qa\E{2} y")

    def test_quoted_leading_digit_cannot_merge_into_backref(self):
        # (a)\1 then literal "2": a bare splice would produce \12 (a
        # different backreference); Java matches "aa2"
        p = compile_java_regex(r"(a)\1\Q2\E")
        assert p.search("xx aa2 yy")
        assert not p.search("xx a2 yy")

    def test_quoted_run_engine_golden_parity_host_fallback(self):
        from helpers import make_pattern, make_pattern_set

        from log_parser_tpu.config import ScoringConfig
        from log_parser_tpu.golden import GoldenAnalyzer
        from log_parser_tpu.models.pod import PodFailureData
        from log_parser_tpu.runtime import AnalysisEngine

        sets = [make_pattern_set([
            make_pattern("pq2", regex=r"x \Qab\E{2} y", confidence=0.8),
        ])]
        data = PodFailureData(
            pod={"metadata": {"name": "q2"}},
            logs="x abb y\nx abab y\nother\n",
        )
        got = AnalysisEngine(sets, ScoringConfig()).analyze(data)
        want = GoldenAnalyzer(sets, ScoringConfig()).analyze(data)
        assert [e.line_number for e in got.events] == [1]
        assert [e.line_number for e in want.events] == [1]
