"""Deterministic fleet simulation (log_parser_tpu/sim/).

The tentpole contract: a whole fleet — router placement, two backends,
warm standby, migration and failover supervisors — runs in ONE process
under a :class:`VirtualClock` and an in-memory :class:`SimNet`, driven by
seeded multi-fault schedules with the SIM-I1..I5 invariants swept after
every op.  The tests pin the three properties everything else rests on:

* **Determinism** — the same seed always produces the byte-identical
  event log (equal sha256 digests), so a failing seed IS its repro.
* **Rediscovery** — re-introducing a fixed historical bug via its
  ``LOG_PARSER_TPU_SIM_BUG_*`` guard flag makes the sweep find it again
  within 200 seeds, and the minimizer shrinks the failing schedule.
* **Clamps (S1)** — every production site that ages state by wall-clock
  arithmetic survives a backwards step (NTP slew, VM pause): snapshot
  ages clamp at zero, TTL reaping rebases, SLO cells never run
  backwards.  The ``clock_skew`` schedule op drives the same sites
  end-to-end inside the simulator.
"""

from __future__ import annotations

import threading
from types import SimpleNamespace

import pytest

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.golden.engine import (
    GoldenFrequencyTracker,
    SnapshotValidationError,
)
from log_parser_tpu.obs.slo import SloTracker
from log_parser_tpu.runtime.replicate import ReplicationError
from log_parser_tpu.runtime.stream import StreamManager
from log_parser_tpu.sim.clock import VirtualClock
from log_parser_tpu.sim.harness import minimize, run_schedule, run_seed
from log_parser_tpu.sim.invariants import INVARIANTS
from log_parser_tpu.sim.schedule import SCHEDULE_OPS, generate_schedule
from log_parser_tpu.sim.transport import (
    SimNet,
    SimPartitioned,
    SimReplicaTarget,
)

SMOKE_SEEDS = 200
SMOKE_OPS = 40


# --------------------------------------------------------- virtual clock


class TestVirtualClock:
    def test_advance_moves_wall_and_monotonic_together(self):
        clk = VirtualClock(start=1000.0)
        clk.advance(7)
        assert clk.wall() == 1007.0
        assert clk.mono() == 1007.0

    def test_pause_wall_freezes_wall_while_monotonic_runs(self):
        clk = VirtualClock(start=1000.0)
        clk.pause_wall(30)
        assert clk.mono() == 1030.0
        assert clk.wall() == 1000.0  # the VM-pause shape

    def test_skew_wall_steps_wall_only_including_backwards(self):
        clk = VirtualClock(start=1000.0)
        clk.skew_wall(-5)
        assert clk.wall() == 995.0
        assert clk.mono() == 1000.0  # monotonic NEVER moves backwards

    def test_driver_sleep_advances_virtual_time(self):
        clk = VirtualClock(start=1000.0)
        clk.sleep(12)
        assert clk.mono() == 1012.0 and clk.wall() == 1012.0

    def test_background_thread_sleep_never_advances_virtual_time(self):
        clk = VirtualClock(start=1000.0)
        t = threading.Thread(target=clk.sleep, args=(3600,))
        t.start()
        t.join(10)
        assert not t.is_alive()
        assert clk.mono() == 1000.0 and clk.wall() == 1000.0

    def test_driver_wait_advances_by_timeout_and_reports_event(self):
        clk = VirtualClock(start=1000.0)
        ev = threading.Event()
        assert clk.wait(ev, timeout=15) is False
        assert clk.mono() == 1015.0
        ev.set()
        assert clk.wait(ev, timeout=15) is True
        assert clk.mono() == 1015.0  # set event: no time passes


# ----------------------------------------------------- simulated network


class TestSimNet:
    def test_partition_is_symmetric_and_heals(self):
        net = SimNet()
        net.partition("a", "s")
        for src, dst in (("a", "s"), ("s", "a")):
            with pytest.raises(SimPartitioned):
                net.deliver(src, dst, "x", lambda: "ok")
        net.heal()
        assert net.deliver("a", "s", "x", lambda: "ok") == "ok"

    def test_drop_is_one_shot(self):
        net = SimNet()
        net.drop_next.add(("a", "s"))
        with pytest.raises(SimPartitioned):
            net.deliver("a", "s", "x", lambda: "ok")
        assert net.deliver("a", "s", "x", lambda: "ok") == "ok"

    def test_duplicate_applies_twice_caller_sees_second(self):
        net = SimNet()
        calls = []
        net.dup_next.add(("a", "s"))
        out = net.deliver(
            "a", "s", "x", lambda: calls.append(len(calls)) or len(calls)
        )
        assert calls == [0, 1]
        assert out == 2  # the second application's response
        net.deliver("a", "s", "x", lambda: calls.append(len(calls)))
        assert calls == [0, 1, 2]  # one-shot

    def test_defer_queues_then_flush_delivers_out_of_band(self):
        net = SimNet()
        landed = []
        net.defer_next.add(("a", "s"))
        with pytest.raises(SimPartitioned):
            # the ambiguous failure: the sender sees a timeout, but the
            # request is sitting in the queue
            net.deliver("a", "s", "late", lambda: landed.append("late"))
        assert landed == []
        assert net.deliver("a", "s", "now", lambda: landed.append("now"))\
            is None
        assert net.flush() == ["late"]
        assert landed == ["now", "late"]  # late delivery lands after

    def test_flush_swallows_receiver_rejection(self):
        net = SimNet()
        net.defer_next.add(("a", "s"))

        def boom():
            raise ReplicationError("stale", status=409)

        with pytest.raises(SimPartitioned):
            net.deliver("a", "s", "dup", boom)
        labels = net.flush()
        assert labels == ["dup:rejected:ReplicationError"]

    def test_replica_target_surfaces_dead_peer_as_503(self):
        net = SimNet()
        target = SimReplicaTarget(net, "a", "s", lambda: None)
        with pytest.raises(ReplicationError) as exc:
            target.feed({"tenant": "acme"})
        assert exc.value.status == 503


# --------------------------------------------------- schedule generation


class TestScheduleGeneration:
    def test_seed_expansion_is_deterministic(self):
        a = generate_schedule(123, 40)
        b = generate_schedule(123, 40)
        assert a == b
        assert len(a) == 40

    def test_only_documented_ops_are_generated(self):
        for seed in range(20):
            for op in generate_schedule(seed, 40):
                assert op[0] in SCHEDULE_OPS, op

    def test_invariant_ids_are_pinned(self):
        assert [inv.id for inv in INVARIANTS] == [
            "SIM-I1", "SIM-I2", "SIM-I3", "SIM-I4", "SIM-I5",
        ]


# ------------------------------------------------- schedule-driven tests


@pytest.mark.sim
class TestDeterministicReplay:
    def test_same_seed_replays_byte_identically(self):
        first = run_seed(11, n_ops=SMOKE_OPS)
        second = run_seed(11, n_ops=SMOKE_OPS)
        assert first.digest == second.digest
        assert first.events == second.events
        assert first.ok, first.violations

    def test_different_seeds_diverge(self):
        assert run_seed(11, n_ops=20).digest != run_seed(12, n_ops=20).digest

    def test_clock_pause_and_skew_schedule_passes(self):
        # the S1 clamp sites driven end-to-end: traffic, a shipped batch,
        # a VM pause, a backwards NTP step, failover — invariants hold
        res = run_schedule([
            ("serve", "acme", 0),
            ("serve", "globex", 1),
            ("pump", "a"),
            ("clock_pause", 30),
            ("serve", "acme", 2),
            ("clock_skew", -5),
            ("serve", "acme", 3),
            ("pump", "a"),
            ("promote",),
            ("serve", "globex", 2),
        ])
        assert res.ok, res.violations


@pytest.mark.sim
class TestCrossPlaneCrashMatrix:
    def test_pressure_hard_x_migration_cutover_x_promote(self):
        """The S3 acceptance schedule: a migration target crashed at its
        ACTIVATE record, hard disk pressure across the fleet, and a
        standby promotion — three planes interleaved in one schedule —
        must still quiesce to exactly one owner per tenant with clean
        forwards and idempotent recovery."""
        res = run_schedule([
            ("serve", "acme", 0),
            ("serve", "globex", 1),
            ("pump", "a"),
            # migration plane: acme cuts over, the target dies mid-adopt
            ("migrate", "acme", "a", "activate"),
            # pressure plane: every journal diverts to its ring
            ("enospc",),
            ("serve", "globex", 2),
            # replication plane: the standby takes the pair
            ("promote",),
            ("serve", "globex", 3),
            ("disk_recover",),
            ("supervise",),
        ])
        assert res.ok, res.violations
        ops = [ev.get("op") for ev in res.events]
        assert "enospc" in ops and "promote" in ops
        crash = next(ev for ev in res.events if ev.get("op") == "migrate")
        assert crash["outcome"] == "crash" and crash["at"] == "activate"
        promote = next(ev for ev in res.events if ev.get("op") == "promote")
        assert promote["result"]["status"] == "promoted"


@pytest.mark.sim
class TestGuardFlagRediscovery:
    """Re-introduce each fixed historical bug behind its guard flag: the
    sweep must rediscover it within 200 seeds, the failing seed must
    replay byte-identically, and the minimizer must shrink the repro."""

    @pytest.mark.parametrize("flag", [
        "LOG_PARSER_TPU_SIM_BUG_MISALIGNED_WEDGE",
        "LOG_PARSER_TPU_SIM_BUG_FORWARD_RESURRECTION",
    ])
    def test_bug_rediscovered_replayed_and_minimized(self, flag):
        bug_env = {flag: "1"}
        failing = None
        for seed in range(200):
            res = run_seed(seed, n_ops=SMOKE_OPS, bug_env=bug_env)
            if not res.ok:
                failing = res
                break
        assert failing is not None, f"{flag} not rediscovered in 200 seeds"
        replay = run_seed(failing.seed, n_ops=SMOKE_OPS, bug_env=bug_env)
        assert replay.digest == failing.digest
        assert replay.violations == failing.violations
        small = minimize(list(failing.schedule), bug_env=bug_env)
        assert len(small) < len(failing.schedule)
        assert not run_schedule(small, bug_env=bug_env).ok


@pytest.mark.sim
class TestSeedSmoke:
    def test_smoke_sweep_all_seeds_pass(self):
        """The tier-1 campaign: every seed in [0, 200) must pass, and a
        sample must replay to identical digests (the determinism the
        repro workflow rests on)."""
        digests = {}
        failures = []
        for seed in range(SMOKE_SEEDS):
            res = run_seed(seed, n_ops=SMOKE_OPS)
            digests[seed] = res.digest
            if not res.ok:
                failures.append((seed, res.failed_at, res.violations[:1]))
        assert not failures, failures
        for seed in range(0, SMOKE_SEEDS, 40):
            assert run_seed(seed, n_ops=SMOKE_OPS).digest == digests[seed]

    @pytest.mark.slow
    def test_nightly_sweep(self):
        """The slow-marked nightly campaign (docs/OPS.md): a wider seed
        range at the same schedule length."""
        failures = []
        for seed in range(SMOKE_SEEDS, SMOKE_SEEDS + 800):
            res = run_seed(seed, n_ops=SMOKE_OPS)
            if not res.ok:
                failures.append((seed, res.failed_at, res.violations[:1]))
        assert not failures, failures


# ------------------------------------------------ S1: backwards-wall S1


class TestBackwardsWallClamps:
    """Unit regression tests for every production clamp the ``clock_skew``
    schedule op exercises end-to-end."""

    def test_frequency_snapshot_clamps_negative_ages(self):
        t = [1000.0]
        tracker = GoldenFrequencyTracker(ScoringConfig(), clock=lambda: t[0])
        tracker.record_pattern_match("oom")
        t[0] = 990.0  # wall stepped back: recorded timestamp is "future"
        snap = tracker.snapshot()
        assert snap["oom"] == [0.0]  # "matched just now" is the floor

    def test_frequency_restore_rejects_negative_ages(self):
        tracker = GoldenFrequencyTracker(ScoringConfig(), clock=lambda: 0.0)
        with pytest.raises(SnapshotValidationError):
            tracker.restore({"oom": [-1.0]})
        with pytest.raises(SnapshotValidationError):
            tracker.restore({"oom": [float("nan")]})

    def test_snapshot_restore_round_trip_after_backwards_step(self):
        t = [1000.0]
        src = GoldenFrequencyTracker(ScoringConfig(), clock=lambda: t[0])
        src.record_pattern_matches("oom", 3)
        t[0] = 900.0
        dst = GoldenFrequencyTracker(ScoringConfig(), clock=lambda: t[0])
        dst.restore(src.snapshot())  # must not raise: ages were clamped
        assert dst.snapshot()["oom"] == [0.0, 0.0, 0.0]

    def test_stream_reap_rebases_future_sessions(self):
        t = [1000.0]
        mgr = StreamManager(
            engine=None, ttl_s=10, clock=lambda: t[0], start_reaper=False
        )
        killed = []
        sess = SimpleNamespace(
            last_active=1500.0,  # opened before the wall stepped back
            kill=lambda reason: killed.append(reason),
        )
        mgr._sessions["s1"] = sess
        assert mgr.reap_now() == 0
        # the negative idle age no longer shields the session: rebased
        assert sess.last_active == 1000.0
        t[0] = 1011.0  # now the TTL applies from the rebased point
        assert mgr.reap_now() == 1
        assert killed == ["ttl"]

    def test_slo_cells_never_run_backwards(self):
        t = [1000.0]
        slo = SloTracker(availability=0.999, windows_s=(60,),
                         clock=lambda: t[0])
        slo.note(ok=True, duration_ms=1.0)
        t[0] = 900.0  # backwards step mid-stream
        slo.note(ok=False, duration_ms=1.0)
        # the fresh error lands at the high-water mark, inside the
        # window — not in a cell the window filter already passed
        total, errors, _ = slo._window_counts(60)
        assert (total, errors) == (2, 1)
        assert slo.burn_rates()["availability"]["60s"] > 0
