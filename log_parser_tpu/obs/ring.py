"""Bounded request-trace ring: the rolling memory `/trace/last` never
had. Every request — served, fallback, host-routed or shed — lands here
as one small dict keyed by its propagated request id, and requests whose
wall time crosses ``--trace-slow-ms`` are additionally retained in a
separate slow ring so a latency incident survives the next thousand
fast requests. ``GET /trace/recent?n=`` reads both, newest first."""

from __future__ import annotations

import threading
from collections import deque

DEFAULT_CAPACITY = 256
DEFAULT_SLOW_MS = 500.0
SLOW_CAPACITY = 64


class TraceRing:
    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 slow_ms: float = DEFAULT_SLOW_MS,
                 slow_capacity: int = SLOW_CAPACITY):
        self.capacity = max(1, int(capacity))
        self.slow_ms = float(slow_ms)
        self._lock = threading.Lock()
        self._ring: deque[dict] = deque(maxlen=self.capacity)
        self._slow: deque[dict] = deque(maxlen=max(1, int(slow_capacity)))
        self._seq = 0
        self.slow_captured = 0

    def record(self, entry: dict) -> bool:
        """Append one request entry; returns True when it was also
        captured as slow. The caller supplies ``totalMs``."""
        slow = float(entry.get("totalMs") or 0.0) >= self.slow_ms
        with self._lock:
            self._seq += 1
            entry["seq"] = self._seq
            self._ring.append(entry)
            if slow:
                entry["slow"] = True
                self._slow.append(entry)
                self.slow_captured += 1
        return slow

    def recent(self, n: int | None = None) -> list[dict]:
        with self._lock:
            items = list(self._ring)
        items.reverse()
        return items if n is None else items[: max(0, int(n))]

    def slow_recent(self, n: int | None = None) -> list[dict]:
        with self._lock:
            items = list(self._slow)
        items.reverse()
        return items if n is None else items[: max(0, int(n))]

    def stats(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "slowMs": self.slow_ms,
                "retained": len(self._ring),
                "slowRetained": len(self._slow),
                "recorded": self._seq,
                "slowCaptured": self.slow_captured,
            }
