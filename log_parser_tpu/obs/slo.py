"""SLO burn accounting: rolling multi-window availability and latency
objectives in the style of the SRE multi-window multi-burn-rate alert.

The tracker buckets outcomes into one-second cells and answers, per
window, "what fraction of the error budget is this window burning?".
``burn == 1.0`` means the budget is being spent exactly as fast as the
objective allows; a sustained burn above the threshold on EVERY
configured window (short window for recency, long for significance)
flips the ``slo`` condition on ``/q/health`` to DEGRADED. Objectives
are off until ``--slo-p99-ms`` / ``--slo-availability`` set them."""

from __future__ import annotations

import threading
import time
from log_parser_tpu import _clock as pclock

DEFAULT_WINDOWS_S = (60.0, 300.0)
DEFAULT_BURN_THRESHOLD = 2.0
# latency objective is a p99: 1% of requests may run over the target
LATENCY_QUANTILE_BUDGET = 0.01


class SloTracker:
    def __init__(self, p99_ms: float = 0.0, availability: float = 0.0,
                 windows_s=DEFAULT_WINDOWS_S,
                 burn_threshold: float = DEFAULT_BURN_THRESHOLD,
                 clock=pclock.mono):
        self.p99_ms = float(p99_ms)
        self.availability = float(availability)
        self.windows_s = tuple(
            sorted(float(w) for w in windows_s if float(w) > 0)
        ) or DEFAULT_WINDOWS_S
        self.burn_threshold = float(burn_threshold)
        self.clock = clock
        self._lock = threading.Lock()
        # second -> [total, errors, slow]; bounded by the longest window
        self._cells: dict[int, list[int]] = {}
        self._horizon = int(max(self.windows_s)) + 2
        # High-water mark: bucketing must not run backwards when the clock
        # does, or fresh outcomes land in cells the window filter already
        # passed (undercounting burn) and eviction can eat recent cells.
        self._hwm = 0

    @property
    def enabled(self) -> bool:
        return self.p99_ms > 0 or self.availability > 0

    def note(self, ok: bool, duration_ms: float) -> None:
        if not self.enabled:
            return
        now = int(self.clock())
        with self._lock:
            now = self._hwm = max(now, self._hwm)
            cell = self._cells.get(now)
            if cell is None:
                cell = self._cells[now] = [0, 0, 0]
                if len(self._cells) > self._horizon:
                    floor = now - self._horizon
                    for sec in [s for s in self._cells if s < floor]:
                        del self._cells[sec]
            cell[0] += 1
            if not ok:
                cell[1] += 1
            if self.p99_ms > 0 and duration_ms > self.p99_ms:
                cell[2] += 1

    def _window_counts(self, window_s: float) -> tuple[int, int, int]:
        total = errors = slow = 0
        with self._lock:
            now = max(self.clock(), self._hwm)
            floor = now - window_s
            for sec, (t, e, s) in self._cells.items():
                if floor <= sec <= now:
                    total += t
                    errors += e
                    slow += s
        return total, errors, slow

    def burn_rates(self) -> dict[str, dict[str, float]]:
        """{objective: {window-label: burn}} for configured objectives."""
        out: dict[str, dict[str, float]] = {}
        for window in self.windows_s:
            total, errors, slow = self._window_counts(window)
            label = f"{int(window)}s"
            if self.availability > 0:
                budget = max(1e-9, 1.0 - self.availability)
                frac = errors / total if total else 0.0
                out.setdefault("availability", {})[label] = frac / budget
            if self.p99_ms > 0:
                frac = slow / total if total else 0.0
                out.setdefault("latency", {})[label] = (
                    frac / LATENCY_QUANTILE_BUDGET
                )
        return out

    def degraded_objectives(self) -> list[str]:
        """Objectives burning above threshold on EVERY window."""
        return [
            objective
            for objective, rates in self.burn_rates().items()
            if rates and all(
                burn >= self.burn_threshold for burn in rates.values()
            )
        ]

    def health(self) -> dict | None:
        """The ``/q/health`` check row, or None while no objective is
        configured."""
        if not self.enabled:
            return None
        burning = self.degraded_objectives()
        rates = {
            objective: {w: round(b, 3) for w, b in rates.items()}
            for objective, rates in self.burn_rates().items()
        }
        return {
            "name": "slo",
            "status": "DEGRADED" if burning else "UP",
            "burning": burning,
            "burnRates": rates,
            "objectives": {
                **({"p99Ms": self.p99_ms} if self.p99_ms > 0 else {}),
                **(
                    {"availability": self.availability}
                    if self.availability > 0 else {}
                ),
            },
        }

    def samples(self):
        """Registry collector feed: one gauge per objective × window."""
        for objective, rates in self.burn_rates().items():
            for window, burn in rates.items():
                yield (
                    "logparser_slo_burn_rate",
                    {"objective": objective, "window": window},
                    burn,
                )
