"""Process-wide metrics registry: counters, gauges and fixed-bucket
histograms with bounded label cardinality, rendered as Prometheus text
exposition (version 0.0.4).

Two kinds of series feed one scrape:

* **Owned instruments** — hot-path counters/histograms the serving and
  runtime layers increment directly (request totals, per-phase latency,
  dropped responses). These are the single source of truth: the
  `/trace/last` fields that used to keep their own tallies (e.g.
  ``droppedResponses``) now *read* the registry instead of maintaining a
  parallel count.
* **Collector-backed series** — scrape-time callbacks that read the
  subsystems' existing ``stats()`` dicts (admission ladder, batcher,
  line cache, kernel tier, quarantine, shadow, miner, tenancy, streams)
  and re-emit them under stable metric names. No second copy of any
  counter exists, so ``/metrics`` and ``/trace/last`` agree bit-for-bit
  by construction: both are views over the same variables.

Every exported name must appear in :data:`METRICS` — hygiene check 16
pins each one to a backtick-quoted docs/OPS.md row, the same way checks
9/12/14 pin trace counters, tenancy and miner vocabularies.
"""

from __future__ import annotations

import threading

# name -> (type, help). The *only* metric-name vocabulary: instruments
# and collectors both refuse names missing from this table, and hygiene
# check 16 requires a docs/OPS.md row for every key. Keep it a plain
# dict literal — the checker harvests keys with ast, not an import.
METRICS = {
    # -------------------------------------------------- request plane
    "logparser_requests_total": (
        "counter", "Requests by transport, route, status and tenant."),
    "logparser_request_seconds": (
        "histogram", "End-to-end request wall latency by route."),
    "logparser_phase_seconds": (
        "histogram",
        "Per-phase engine latency fed by PhaseTrace, by tenant/phase/route."),
    "logparser_slow_requests_total": (
        "counter",
        "Requests captured in the slow-trace ring (above --trace-slow-ms)."),
    "logparser_dropped_responses_total": (
        "counter",
        "Computed responses the transport failed to write, by transport."),
    "logparser_metric_series_overflow_total": (
        "counter",
        "Label sets folded into _overflow after an instrument's "
        "cardinality bound."),
    "logparser_profile_captures_total": (
        "counter", "Completed on-demand jax.profiler captures."),
    "logparser_slo_burn_rate": (
        "gauge", "SLO error-budget burn rate by objective and window."),
    # ---------------------------------------------- admission ladder
    "logparser_admission_total": (
        "counter", "Admission ladder outcomes (admitted and shed rungs)."),
    "logparser_inflight": (
        "gauge", "Requests currently holding an admission slot."),
    "logparser_admission_queued": (
        "gauge", "Requests parked in the admission queue."),
    # ------------------------------------------------------- engine
    "logparser_fallback_total": (
        "counter", "Requests served by the golden fallback after a "
        "device failure."),
    "logparser_host_routed_total": (
        "counter", "Requests deliberately routed to the vectorized "
        "host path."),
    "logparser_reload_epoch": ("gauge", "Pattern-bank reload epoch."),
    "logparser_device_circuit_open": (
        "gauge", "1 while the device watchdog circuit is open."),
    "logparser_quarantine_active": (
        "gauge", "Request fingerprints currently quarantined."),
    "logparser_quarantine_served_golden_total": (
        "counter", "Quarantined requests served straight from golden."),
    "logparser_shadow_divergences_total": (
        "counter", "Shadow-verification divergences."),
    "logparser_kernel_batches_total": (
        "counter", "Device dispatches by execution tier (kernel vs xla)."),
    "logparser_kernel_rows_total": (
        "counter", "Rows dispatched through the Pallas union-DFA kernel."),
    # ----------------------------------------- line cache + interner
    "logparser_line_cache_hits_total": ("counter", "Line-cache hit lines."),
    "logparser_line_cache_misses_total": ("counter", "Line-cache miss lines."),
    "logparser_line_cache_evictions_total": (
        "counter", "Line-cache entries evicted."),
    "logparser_line_cache_resident_bytes": (
        "gauge", "Line-cache resident bytes."),
    "logparser_interner_probe_hits_total": (
        "counter", "KeyInterner 64-bit probe hits (blake2b skipped)."),
    "logparser_interner_inserts_total": (
        "counter", "KeyInterner first-touch inserts (blake2b paid)."),
    # ------------------------------------------------------ batcher
    "logparser_batch_queue_depth": (
        "gauge", "Requests parked in micro-batcher queues."),
    "logparser_requests_batched_total": (
        "counter", "Requests that rode a micro-batch."),
    "logparser_batches_flushed_total": (
        "counter", "Micro-batches flushed to the device."),
    # -------------------------------------------------------- miner
    "logparser_miner_tapped_total": (
        "counter", "Miss lines tapped into the template miner."),
    "logparser_miner_admitted_total": (
        "counter", "Mined patterns admitted into the serving bank."),
    # ------------------------------------------------------ tenancy
    "logparser_tenants_resident": (
        "gauge", "Tenant engines resident (including default)."),
    "logparser_tenant_builds_total": ("counter", "Tenant engine builds."),
    "logparser_tenant_evictions_total": (
        "counter", "Tenant engines evicted by the residency budget."),
    # ------------------------------------------------------ streams
    "logparser_stream_sessions": ("gauge", "Open streaming sessions."),
    "logparser_stream_chunks_total": (
        "counter", "Chunks ingested across streaming sessions."),
    "logparser_stream_frames_total": (
        "counter", "Frames emitted across streaming sessions."),
    # --------------------------------------------------- span store
    "logparser_trace_spans_total": (
        "counter", "Causal traces committed to the span store."),
    "logparser_trace_spans_dropped_total": (
        "counter", "Traces discarded by span sampling (children cleaned)."),
    # ------------------------------------- device utilization (roofline)
    "logparser_device_dispatches_total": (
        "counter", "Device dispatches by tenant and execution tier."),
    "logparser_device_padded_rows_total": (
        "counter", "Padded line rows shipped to the device (incl. waste)."),
    "logparser_device_dummy_rows_total": (
        "counter", "Dummy pow2-padding request slots dispatched (waste)."),
    "logparser_device_dummy_waste_ratio": (
        "gauge", "Dummy-slot waste fraction of the last batched dispatch."),
    "logparser_device_flops_total": (
        "counter", "XLA cost-analysis FLOPs accumulated over dispatches."),
    "logparser_device_hbm_bytes_total": (
        "counter", "XLA cost-analysis bytes accessed over dispatches."),
    # --------------------------------------- plan geometry + load state
    "logparser_kernel_plan_vmem_bytes": (
        "gauge", "Admitted union-DFA plan VMEM bytes per grid step."),
    "logparser_kernel_plan_groups": (
        "gauge", "Union-DFA groups in the admitted kernel plan."),
    "logparser_kernel_plan_plane_bytes": (
        "gauge", "Transition-plane bytes resident per kernel grid step."),
    "logparser_native_loaded": (
        "gauge", "1 when the native C++ scanner loaded; reason label "
        "carries the bounded load-failure class."),
    "logparser_compile_cache_events_total": (
        "counter", "Persistent XLA compile-cache events by kind (hit/miss)."),
    "logparser_journal_epoch": (
        "gauge", "Frequency-WAL snapshot epoch by tenant."),
    "logparser_lint_findings": (
        "gauge", "Findings in the last pattern-lint run by severity."),
    "logparser_faults_armed": (
        "gauge", "Fault-injection sites armed via LOG_PARSER_TPU_FAULTS."),
    "logparser_mesh_degraded": (
        "gauge", "1 while distributed serving is degraded to local."),
    # ---------------------------------------------- migration + drain
    "logparser_migration_total": (
        "counter",
        "Tenant-migration protocol outcomes by role and disposition "
        "(completed/aborted/staged/activated/recovered_*/session_*/"
        "drain_*)."),
    "logparser_migration_active": (
        "gauge", "Tenant migrations currently running the protocol."),
    "logparser_migration_forwards": (
        "gauge", "Tenants 307-forwarded to another process post-cutover."),
    "logparser_migration_draining": (
        "gauge", "1 while the drain supervisor is evacuating this process."),
    # ------------------------------------------------- replication
    "logparser_replication_lag_records": (
        "gauge", "Whole WAL records fsynced on the primary but not yet "
        "acked by the standby, per tenant."),
    "logparser_replication_lag_bytes": (
        "gauge", "WAL bytes past the standby's acked offset, per tenant."),
    "logparser_replication_lag_seconds": (
        "gauge", "Age of the oldest un-acked WAL record, per tenant."),
    "logparser_replication_acked_offset": (
        "gauge", "Replication byte offset acked per tenant, by side "
        "(sender/receiver)."),
    "logparser_replication_epoch": (
        "gauge", "Ownership epoch this process last journaled; role label "
        "says primary or standby."),
    "logparser_replication_total": (
        "counter", "Replication batch outcomes "
        "(shipped/applied/rejected/reseed/send_error)."),
    "logparser_replication_promotions_total": (
        "counter", "Fenced ownership transitions journaled by this "
        "process (kind=promote/demote)."),
    # ------------------------------------------------------- fleet
    "logparser_fleet_routed_total": (
        "counter", "Router-proxied requests by backend and outcome."),
    "logparser_fleet_reroutes_total": (
        "counter", "Ring re-routes by reason (forward/backend_down)."),
    "logparser_fleet_backends_up": (
        "gauge", "Backends currently on the router's ring."),
    "logparser_fleet_overrides": (
        "gauge", "Per-tenant ring overrides installed on the router."),
    "logparser_fleet_moves_total": (
        "counter", "Placer-initiated live tenant moves by trigger "
        "(quota_shed/slo_burn/residency_thrash)."),
    "logparser_fleet_budget_mb": (
        "gauge", "Fleet-arbitrated budget share by backend and kind "
        "(line_cache/tenant)."),
    # ---------------------------------------------------- pressure
    "logparser_pressure_state": (
        "gauge", "Resource-pressure ladder rung per resource "
        "(0=ok, 1=soft, 2=hard)."),
    "logparser_pressure_transitions_total": (
        "counter", "Pressure ladder transitions by resource and "
        "entered state."),
    "logparser_pressure_degraded_writes_total": (
        "counter", "WAL records absorbed by in-memory rings while disk "
        "durability is degraded."),
    "logparser_pressure_levers_total": (
        "counter", "Memory-pressure lever pulls by lever name."),
    "logparser_pressure_retry_total": (
        "counter", "Retry-budget verdicts by outcome (allowed/shed)."),
}

# /trace/last payload block -> covering /metrics families. Hygiene
# check 16 harvests every ``payload["..."]`` key assigned in
# serve/http.py and fails when a block is missing here or maps to a
# name outside METRICS — so a new trace block cannot ship invisible to
# scrapers again (the PR-10 native block did exactly that).
TRACE_BLOCKS = {
    "phasesMs": ("logparser_phase_seconds",),
    "totalMs": ("logparser_request_seconds",),
    "fallbackCount": ("logparser_fallback_total",),
    "hostRoutedCount": ("logparser_host_routed_total",),
    "deviceCircuitOpen": ("logparser_device_circuit_open",),
    "droppedResponses": ("logparser_dropped_responses_total",),
    "admission": ("logparser_admission_total", "logparser_inflight",
                  "logparser_admission_queued"),
    "traceRing": ("logparser_slow_requests_total",),
    "spans": ("logparser_trace_spans_total",
              "logparser_trace_spans_dropped_total"),
    "batcher": ("logparser_batch_queue_depth",
                "logparser_requests_batched_total",
                "logparser_batches_flushed_total"),
    "lineCache": ("logparser_line_cache_hits_total",
                  "logparser_line_cache_misses_total",
                  "logparser_line_cache_evictions_total",
                  "logparser_line_cache_resident_bytes"),
    "interner": ("logparser_interner_probe_hits_total",
                 "logparser_interner_inserts_total"),
    "kernel": ("logparser_kernel_batches_total",
               "logparser_kernel_rows_total",
               "logparser_kernel_plan_vmem_bytes",
               "logparser_kernel_plan_groups",
               "logparser_kernel_plan_plane_bytes"),
    "distributed": ("logparser_mesh_degraded",),
    "journal": ("logparser_journal_epoch",),
    "stream": ("logparser_stream_sessions",
               "logparser_stream_chunks_total",
               "logparser_stream_frames_total"),
    "native": ("logparser_native_loaded",),
    "compileCache": ("logparser_compile_cache_events_total",),
    "quarantine": ("logparser_quarantine_active",
                   "logparser_quarantine_served_golden_total"),
    "miner": ("logparser_miner_tapped_total",
              "logparser_miner_admitted_total"),
    "shadow": ("logparser_shadow_divergences_total",),
    "reload": ("logparser_reload_epoch",),
    "lint": ("logparser_lint_findings",),
    "tenants": ("logparser_tenants_resident",
                "logparser_tenant_builds_total",
                "logparser_tenant_evictions_total"),
    "faults": ("logparser_faults_armed",),
    "migration": ("logparser_migration_total",
                  "logparser_migration_active",
                  "logparser_migration_forwards",
                  "logparser_migration_draining"),
    "replication": ("logparser_replication_lag_records",
                    "logparser_replication_lag_bytes",
                    "logparser_replication_lag_seconds",
                    "logparser_replication_acked_offset",
                    "logparser_replication_epoch",
                    "logparser_replication_total",
                    "logparser_replication_promotions_total"),
    "pressure": ("logparser_pressure_state",
                 "logparser_pressure_transitions_total",
                 "logparser_pressure_degraded_writes_total",
                 "logparser_pressure_levers_total",
                 "logparser_pressure_retry_total"),
}

# request latency: sub-ms cache hits through multi-second cold compiles
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

# per-instrument child bound; beyond it new label sets fold into a
# single reserved series so a tenant-id flood cannot OOM the registry
DEFAULT_MAX_SERIES = 64
OVERFLOW_LABEL = "_overflow"

_INF = float("inf")


def _escape(value: str) -> str:
    return (
        str(value).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _labels_text(labelnames: tuple, labelvalues: tuple) -> str:
    if not labelnames:
        return ""
    inner = ",".join(
        f'{k}="{_escape(v)}"' for k, v in zip(labelnames, labelvalues)
    )
    return "{" + inner + "}"


class _Instrument:
    """One named metric family: a dict of label-tuple -> child state
    behind one lock. ``inc``/``set``/``observe`` are a lock, a dict
    lookup and an add — cheap enough for the request hot path."""

    kind = "untyped"

    def __init__(self, name: str, labelnames: tuple[str, ...],
                 max_series: int, registry: "Registry"):
        self.name = name
        self.labelnames = tuple(labelnames)
        self.max_series = max_series
        self._registry = registry
        self._lock = threading.Lock()
        self._children: dict[tuple, object] = {}

    def _key(self, labels: dict) -> tuple:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: got labels {sorted(labels)}, "
                f"declared {sorted(self.labelnames)}"
            )
        return tuple(str(labels[k]) for k in self.labelnames)

    def _child(self, key: tuple):
        # caller holds self._lock
        child = self._children.get(key)
        if child is None:
            if len(self._children) >= self.max_series:
                over = (OVERFLOW_LABEL,) * len(self.labelnames)
                child = self._children.get(over)
                if child is None:
                    child = self._new_child()
                    self._children[over] = child
                self._registry.note_overflow()
                return child
            child = self._new_child()
            self._children[key] = child
        return child

    def _new_child(self):  # pragma: no cover - overridden
        raise NotImplementedError

    def series(self) -> list[tuple[tuple, object]]:
        with self._lock:
            return list(self._children.items())


class Counter(_Instrument):
    kind = "counter"

    def _new_child(self):
        return [0.0]

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._child(key)[0] += amount

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            child = self._children.get(key)
            return child[0] if child is not None else 0.0

    def total(self) -> float:
        with self._lock:
            return sum(c[0] for c in self._children.values())


class Gauge(_Instrument):
    kind = "gauge"

    def _new_child(self):
        return [0.0]

    def set(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._child(key)[0] = float(value)

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            child = self._children.get(key)
            return child[0] if child is not None else 0.0


class _HistChild:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets  # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0


class Histogram(_Instrument):
    kind = "histogram"

    def __init__(self, name, labelnames, max_series, registry,
                 buckets=DEFAULT_BUCKETS):
        super().__init__(name, labelnames, max_series, registry)
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError(f"{name}: histogram needs at least one bucket")
        self.bounds = bounds  # +Inf is implicit

    def _new_child(self):
        return _HistChild(len(self.bounds) + 1)

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        # bisect by hand: bounds are short tuples, and `le` is inclusive
        idx = len(self.bounds)
        for i, b in enumerate(self.bounds):
            if value <= b:
                idx = i
                break
        with self._lock:
            child = self._child(key)
            child.counts[idx] += 1
            child.sum += value
            child.count += 1

    def snapshot(self, **labels) -> tuple[list[int], float, int]:
        """(cumulative bucket counts incl. +Inf, sum, count)."""
        key = self._key(labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                return [0] * (len(self.bounds) + 1), 0.0, 0
            cum, running = [], 0
            for c in child.counts:
                running += c
                cum.append(running)
            return cum, child.sum, child.count


class Registry:
    """Instrument factory + scrape renderer. ``counter``/``gauge``/
    ``histogram`` are idempotent by name so independent call sites can
    share a family; collectors are keyed and replaced on re-register so
    server restarts over one engine never double-emit."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: dict[str, _Instrument] = {}
        self._collectors: dict[str, object] = {}
        self._overflow = self.counter("logparser_metric_series_overflow_total")

    # ------------------------------------------------------- factories

    def _make(self, cls, name, labelnames, max_series, **kw):
        if name not in METRICS:
            raise ValueError(f"metric {name!r} is not declared in METRICS")
        if METRICS[name][0] != cls.kind:
            raise ValueError(
                f"metric {name!r} is declared {METRICS[name][0]}, "
                f"not {cls.kind}"
            )
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls(name, tuple(labelnames), max_series, self, **kw)
                self._instruments[name] = inst
            elif not isinstance(inst, cls) or inst.labelnames != tuple(labelnames):
                raise ValueError(f"metric {name!r} re-declared differently")
            return inst

    def counter(self, name, labelnames=(), max_series=DEFAULT_MAX_SERIES):
        return self._make(Counter, name, labelnames, max_series)

    def gauge(self, name, labelnames=(), max_series=DEFAULT_MAX_SERIES):
        return self._make(Gauge, name, labelnames, max_series)

    def histogram(self, name, labelnames=(), buckets=DEFAULT_BUCKETS,
                  max_series=DEFAULT_MAX_SERIES):
        return self._make(Histogram, name, labelnames, max_series,
                          buckets=buckets)

    def note_overflow(self) -> None:
        # called while the overflowing instrument's own lock is held;
        # the overflow counter's lock is distinct and never re-enters
        with self._overflow._lock:
            child = self._overflow._children.get(())
            if child is None:
                child = self._overflow._children[()] = [0.0]
            child[0] += 1

    # ------------------------------------------------------ collectors

    def register_collector(self, key: str, fn) -> None:
        """``fn() -> iterable of (metric_name, labels_dict, value)``.
        Runs at scrape time; replaced when ``key`` re-registers."""
        with self._lock:
            self._collectors[key] = fn

    def unregister_collector(self, key: str) -> None:
        with self._lock:
            self._collectors.pop(key, None)

    def _collected(self) -> dict[str, list[tuple[dict, float]]]:
        with self._lock:
            fns = list(self._collectors.values())
        out: dict[str, list[tuple[dict, float]]] = {}
        for fn in fns:
            try:
                samples = list(fn())
            except Exception:
                continue  # a broken subsystem must not take down /metrics
            for name, labels, value in samples:
                if name not in METRICS:
                    continue
                out.setdefault(name, []).append((dict(labels), float(value)))
        return out

    # --------------------------------------------------------- scrape

    def render(self) -> str:
        """Prometheus text exposition, family order pinned to METRICS."""
        collected = self._collected()
        with self._lock:
            owned = dict(self._instruments)
        lines: list[str] = []
        for name, (kind, help_text) in METRICS.items():
            inst = owned.get(name)
            extra = collected.get(name)
            if inst is None and not extra:
                continue
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {kind}")
            if isinstance(inst, Histogram):
                for key, _child in sorted(inst.series()):
                    labels = dict(zip(inst.labelnames, key))
                    cum, total, count = inst.snapshot(**labels)
                    for bound, c in zip(
                        list(inst.bounds) + [_INF], cum
                    ):
                        le = "+Inf" if bound == _INF else repr(bound)
                        ltext = _labels_text(
                            inst.labelnames + ("le",), key + (le,)
                        )
                        lines.append(f"{name}_bucket{ltext} {c}")
                    ltext = _labels_text(inst.labelnames, key)
                    lines.append(f"{name}_sum{ltext} {_fmt(total)}")
                    lines.append(f"{name}_count{ltext} {count}")
            elif inst is not None:
                for key, child in sorted(inst.series()):
                    ltext = _labels_text(inst.labelnames, key)
                    lines.append(f"{name}{ltext} {_fmt(child[0])}")
            if extra:
                for labels, value in sorted(
                    extra, key=lambda s: sorted(s[0].items())
                ):
                    names = tuple(sorted(labels))
                    ltext = _labels_text(
                        names, tuple(labels[k] for k in names)
                    )
                    lines.append(f"{name}{ltext} {_fmt(value)}")
        return "\n".join(lines) + "\n"

    # ----------------------------------------------------- test/view

    def value(self, name: str, **labels) -> float:
        with self._lock:
            inst = self._instruments.get(name)
        if isinstance(inst, (Counter, Gauge)):
            return inst.value(**labels)
        return 0.0

    def total(self, name: str) -> float:
        with self._lock:
            inst = self._instruments.get(name)
        if isinstance(inst, Counter):
            return inst.total()
        return 0.0

    def collected_value(self, name: str, **labels) -> float | None:
        """Scrape-time value of a collector-backed series (tests)."""
        for got, value in self._collected().get(name, []):
            if got == {k: str(v) for k, v in labels.items()} or got == labels:
                return value
        return None


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def samples_from_stats(stats: dict, spec, labels: dict | None = None):
    """Map a subsystem ``stats()`` dict onto registry samples.

    ``spec`` rows are ``(stats_key, metric_name, extra_labels)``; the
    subsystems keep their spec next to their ``stats()`` method so the
    mapping and the source stay in one diff."""
    base = labels or {}
    out = []
    for stats_key, metric, extra in spec:
        value = stats.get(stats_key)
        if value is None:
            continue
        out.append((metric, {**base, **extra}, float(value)))
    return out
