"""Fleet observability plane: one :class:`Obs` bundle per engine family
ties together the metrics registry (:mod:`.registry`), the request-trace
ring (:mod:`.ring`), SLO burn accounting (:mod:`.slo`) and on-demand
device profiling (:mod:`.profiler`).

The bundle is rooted at the engine (``engine.obs``) rather than being a
process singleton: every transport (HTTP, framed shim, gRPC, streaming)
already holds the engine, tenant engines share the primary's bundle
under their own ``tenant`` label, and each test engine gets fresh
zeroed counters instead of cross-test pollution. Configuration comes
from the same env vars the serve flags mirror, read once per bundle."""

from __future__ import annotations

import os
import time
import uuid

from log_parser_tpu import _clock as pclock
from log_parser_tpu.obs.profiler import (  # noqa: F401  (re-export)
    DeviceProfiler,
    ProfilerBusy,
    ProfilerUnavailable,
)
from log_parser_tpu.obs.registry import (  # noqa: F401  (re-export)
    METRICS,
    Registry,
    samples_from_stats,
)
from log_parser_tpu.obs.ring import DEFAULT_CAPACITY, DEFAULT_SLOW_MS, TraceRing
from log_parser_tpu.obs.slo import (
    DEFAULT_BURN_THRESHOLD,
    DEFAULT_WINDOWS_S,
    SloTracker,
)
from log_parser_tpu.obs.spans import (  # noqa: F401  (re-export)
    DEFAULT_SPAN_CAPACITY,
    SPANS,
    SpanStore,
)

# finer low end than the request histogram: cache-hit phases are sub-ms
PHASE_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
)

# engine-attribute samples every engine collector emits; subsystems with
# their own stats() dicts keep their spec next to that method instead
# (serve/admission.py, runtime/{batcher,linecache,stream,tenancy}.py)
_QUARANTINE_SAMPLES = (
    ("active", "logparser_quarantine_active", {}),
    ("servedGolden", "logparser_quarantine_served_golden_total", {}),
)
_SHADOW_SAMPLES = (
    ("divergences", "logparser_shadow_divergences_total", {}),
)
_MINER_SAMPLES = (
    ("tapped", "logparser_miner_tapped_total", {}),
    ("admitted", "logparser_miner_admitted_total", {}),
)
_JOURNAL_SAMPLES = (
    ("epoch", "logparser_journal_epoch", {}),
)

# bounded reason classes for logparser_native_loaded, matched against
# the load-failure string native.stats() records (native/__init__.py
# sets _load_error exactly once) — the label stays low-cardinality no
# matter what the dlopen error text says
_NATIVE_REASONS = (
    ("disabled", "disabled"),
    ("compile failed", "compile_failed"),
    ("no prebuilt library", "no_library"),
    # before the generic dlopen bucket: native/__init__.py diagnoses the
    # built-on-a-newer-distro case (required GLIBCXX symbol versions the
    # host libstdc++ doesn't export) and prefixes it distinctly, so the
    # scrape can alert on it specifically (tools/check_native.py prints
    # the full required-vs-provided table)
    ("glibcxx mismatch", "glibcxx_mismatch"),
    ("load failed", "load_failed"),
    ("stale library", "stale"),
)


def native_load_reason(stats: dict) -> str:
    """Map native.stats() onto the bounded ``reason`` label vocabulary
    (ok / not_loaded / disabled / compile_failed / no_library /
    glibcxx_mismatch / load_failed / stale / other)."""
    if stats.get("available"):
        return "ok"
    err = stats.get("loadError")
    if not err:
        return "not_loaded"
    for prefix, reason in _NATIVE_REASONS:
        if err.startswith(prefix):
            return reason
    return "other"


def _native_samples():
    """`logparser_native_loaded` — the GLIBCXX triage that used to live
    only on /trace/last, now scrapeable (lazy import: get_lib is warmed
    by boot, a scrape never triggers a compile)."""
    from log_parser_tpu import native

    st = native.stats()
    return [(
        "logparser_native_loaded",
        {"reason": native_load_reason(st)},
        1.0 if st.get("available") else 0.0,
    )]


def _compile_cache_samples():
    from log_parser_tpu.utils import xlacache

    st = xlacache.stats()
    return [
        ("logparser_compile_cache_events_total", {"kind": "hit"},
         st.get("compileHits", 0)),
        ("logparser_compile_cache_events_total", {"kind": "miss"},
         st.get("compileMisses", 0)),
    ]


def _fault_samples():
    from log_parser_tpu.runtime import faults

    st = faults.stats()
    armed = 0 if st is None else len(st.get("fired", {}))
    return [("logparser_faults_armed", {}, armed)]


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class Obs:
    """Registry + trace ring + SLO tracker + profiler for one engine
    family. Cheap to construct (no threads, no jax imports)."""

    def __init__(self, clock=pclock.mono):
        self.registry = Registry()
        self.ring = TraceRing(
            capacity=int(
                _env_float("LOG_PARSER_TPU_TRACE_RING", DEFAULT_CAPACITY)
            ),
            slow_ms=_env_float("LOG_PARSER_TPU_TRACE_SLOW_MS", DEFAULT_SLOW_MS),
        )
        windows = tuple(
            float(w)
            for w in os.environ.get("LOG_PARSER_TPU_SLO_WINDOWS_S", "").split(",")
            if w.strip()
        ) or DEFAULT_WINDOWS_S
        self.slo = SloTracker(
            p99_ms=_env_float("LOG_PARSER_TPU_SLO_P99_MS", 0.0),
            availability=_env_float("LOG_PARSER_TPU_SLO_AVAILABILITY", 0.0),
            windows_s=windows,
            burn_threshold=_env_float(
                "LOG_PARSER_TPU_SLO_BURN", DEFAULT_BURN_THRESHOLD
            ),
            clock=clock,
        )
        self.profiler = DeviceProfiler(on_complete=self._profile_done)
        self.spans = SpanStore(
            capacity=int(
                _env_float("LOG_PARSER_TPU_TRACE_SPANS", DEFAULT_SPAN_CAPACITY)
            ),
            sample=_env_float("LOG_PARSER_TPU_TRACE_SAMPLE", 1.0),
            slow_ms=self.ring.slow_ms,
        )
        self.span_dump_path: str | None = None
        self.clock = clock
        reg = self.registry
        self.requests_total = reg.counter(
            "logparser_requests_total",
            ("transport", "route", "status", "tenant"),
            max_series=256,
        )
        self.request_seconds = reg.histogram(
            "logparser_request_seconds", ("route",)
        )
        self.phase_seconds = reg.histogram(
            "logparser_phase_seconds", ("tenant", "phase", "route"),
            buckets=PHASE_BUCKETS, max_series=256,
        )
        self.slow_requests = reg.counter(
            "logparser_slow_requests_total", ("route",)
        )
        self.dropped = reg.counter(
            "logparser_dropped_responses_total", ("transport",)
        )
        self.profile_captures = reg.counter("logparser_profile_captures_total")
        self.device_dispatches = reg.counter(
            "logparser_device_dispatches_total", ("tenant", "tier"),
            max_series=128,
        )
        self.device_padded_rows = reg.counter(
            "logparser_device_padded_rows_total", ("tenant",)
        )
        self.device_dummy_rows = reg.counter(
            "logparser_device_dummy_rows_total", ("tenant",)
        )
        self.device_waste = reg.gauge(
            "logparser_device_dummy_waste_ratio", ("tenant",)
        )
        self.device_flops = reg.counter(
            "logparser_device_flops_total", ("tenant",)
        )
        self.device_hbm_bytes = reg.counter(
            "logparser_device_hbm_bytes_total", ("tenant",)
        )
        reg.register_collector("slo", self.slo.samples)
        reg.register_collector("spans", self._span_samples)
        reg.register_collector("native", _native_samples)
        reg.register_collector("compilecache", _compile_cache_samples)
        reg.register_collector("faults", _fault_samples)

    def _span_samples(self):
        st = self.spans.stats()
        return [
            ("logparser_trace_spans_total", {}, st["committed"]),
            ("logparser_trace_spans_dropped_total", {}, st["droppedTraces"]),
        ]

    def _profile_done(self) -> None:
        self.profile_captures.inc()

    # ------------------------------------------------------- identity

    @staticmethod
    def new_request_id() -> str:
        return uuid.uuid4().hex[:16]

    @staticmethod
    def clean_request_id(raw: str | None) -> str | None:
        """Sanitize an inbound X-Request-Id: printable, bounded, no
        header/label injection."""
        if not raw:
            return None
        rid = "".join(c for c in raw.strip() if c.isprintable())[:128]
        return rid or None

    # ------------------------------------------------------- hot path

    def note_served(self, trace, start: float, tenant: str,
                    outcome: str = "ok", n_lines: int | None = None,
                    error: str | None = None) -> None:
        """One engine-served request: phase histograms + ring entry.
        Called from ``_finish`` (and the fallback path) with the
        request's :class:`PhaseTrace`."""
        route = getattr(trace, "route", "device") or "device"
        request_id = getattr(trace, "request_id", None) or self.new_request_id()
        total_ms = (self.clock() - start) * 1e3
        phases = trace.as_dict()
        observe = self.phase_seconds.observe
        for phase, seconds in phases.items():
            observe(seconds, tenant=tenant, phase=phase, route=route)
        entry = {
            "requestId": request_id,
            "tenant": tenant,
            "route": route,
            "outcome": outcome,
            "totalMs": round(total_ms, 3),
            "phasesMs": {k: round(v * 1e3, 3) for k, v in phases.items()},
        }
        if n_lines is not None:
            entry["lines"] = n_lines
        if error is not None:
            entry["error"] = error
        if self.ring.record(entry):
            self.slow_requests.inc(route=route)
        # the span root is built from the SAME clock delta and phases
        # dict as the ring entry + phase histograms above, so the three
        # surfaces reconcile exactly, not approximately
        attrs = {"route": route, "outcome": outcome}
        if n_lines is not None:
            attrs["lines"] = n_lines
        if error is not None:
            attrs["error"] = error
        extra = getattr(trace, "span_attrs", None)
        if extra:
            attrs.update(extra)
        self.spans.end_trace(
            request_id, duration_s=total_ms / 1e3, tenant=tenant,
            attrs=attrs, phases=phases,
            links=list(getattr(trace, "links", ()) or ()),
        )

    def note_request(self, transport: str, route: str, status: int,
                     tenant: str, duration_s: float,
                     request_id: str | None = None,
                     detail: str | None = None) -> None:
        """One transport-level request outcome: totals, latency, SLO.
        Ring entries for non-200 outcomes (200s were already recorded by
        the engine with full phase detail)."""
        self.requests_total.inc(
            transport=transport, route=route, status=str(status),
            tenant=tenant,
        )
        self.request_seconds.observe(duration_s, route=route)
        self.slo.note(ok=status < 500, duration_ms=duration_s * 1e3)
        if status != 200:
            entry = {
                "requestId": request_id or self.new_request_id(),
                "tenant": tenant,
                "route": route,
                "outcome": f"http_{status}" if transport == "http"
                else f"{transport}_{status}",
                "totalMs": round(duration_s * 1e3, 3),
                "phasesMs": {},
            }
            if detail:
                entry["error"] = detail
            if self.ring.record(entry):
                self.slow_requests.inc(route=route)
            # non-200s never reach note_served, so their trace (and any
            # staged admission child) must be finished here — otherwise
            # a shed request would orphan its staged spans
            attrs = {"route": route, "outcome": entry["outcome"],
                     "transport": transport, "status": status}
            if detail:
                attrs["error"] = detail
            self.spans.end_trace(
                entry["requestId"], duration_s=duration_s, tenant=tenant,
                attrs=attrs,
            )

    def note_dispatch(self, tenant: str, tier: str, padded_rows: int = 0,
                      dummy_rows: int = 0, waste: float | None = None,
                      flops: float | None = None,
                      hbm_bytes: float | None = None) -> None:
        """Per-dispatch device-utilization accounting: every device
        step (direct, batched flush, line-cache residual) folds its
        cost into the per-tenant ``logparser_device_*`` families so
        roofline math is a scrape, not a bench run."""
        self.device_dispatches.inc(tenant=tenant, tier=tier)
        if padded_rows:
            self.device_padded_rows.inc(padded_rows, tenant=tenant)
        if dummy_rows:
            self.device_dummy_rows.inc(dummy_rows, tenant=tenant)
        if waste is not None:
            self.device_waste.set(waste, tenant=tenant)
        if flops:
            self.device_flops.inc(flops, tenant=tenant)
        if hbm_bytes:
            self.device_hbm_bytes.inc(hbm_bytes, tenant=tenant)

    def note_dropped(self, transport: str) -> None:
        """A computed response the transport could not write back —
        the one counter shared by HTTP, framed shim and gRPC."""
        self.dropped.inc(transport=transport)

    @property
    def dropped_responses(self) -> int:
        return int(self.dropped.total())

    # ----------------------------------------------------- collectors

    def add_engine_collector(self, engine) -> None:
        """Scrape-time view over one engine's counters and its enabled
        subsystems' ``stats()`` dicts (line cache, interner, batcher,
        kernel tier, quarantine, shadow, miner)."""

        def collect():
            tenant = getattr(engine, "obs_tenant", "default")
            labels = {"tenant": tenant}
            out = [
                ("logparser_fallback_total", labels,
                 getattr(engine, "fallback_count", 0)),
                ("logparser_host_routed_total", labels,
                 getattr(engine, "host_routed_count", 0)),
                ("logparser_reload_epoch", labels,
                 getattr(engine, "reload_epoch", 0)),
            ]
            watchdog = getattr(engine, "watchdog", None)
            if watchdog is not None:
                out.append((
                    "logparser_device_circuit_open", labels,
                    1.0 if watchdog.circuit_open else 0.0,
                ))
            kernel = getattr(engine, "kernel_stats", None)
            if kernel is not None:
                ks = kernel.stats()
                out.extend([
                    ("logparser_kernel_batches_total",
                     {**labels, "tier": "kernel"}, ks.get("kernelBatches", 0)),
                    ("logparser_kernel_batches_total",
                     {**labels, "tier": "xla"}, ks.get("xlaBatches", 0)),
                    ("logparser_kernel_rows_total", labels,
                     ks.get("kernelRows", 0)),
                ])
                geometry = ks.get("geometry") or {}
                if geometry:
                    out.extend([
                        ("logparser_kernel_plan_vmem_bytes", labels,
                         geometry.get("vmemPerStep", 0)),
                        ("logparser_kernel_plan_groups", labels,
                         geometry.get("nGroups", 0)),
                        ("logparser_kernel_plan_plane_bytes", labels,
                         geometry.get("planeBytes", 0)),
                    ])
            journal = getattr(engine, "journal", None)
            if journal is not None:
                out.extend(samples_from_stats(
                    journal.stats(), _JOURNAL_SAMPLES, labels
                ))
            last_lint = getattr(engine, "last_lint", None)
            if last_lint:
                for severity in ("error", "warn", "info"):
                    if severity in last_lint:
                        out.append((
                            "logparser_lint_findings",
                            {**labels, "severity": severity},
                            last_lint[severity],
                        ))
            mesh = getattr(engine, "mesh_health", None)
            if mesh is not None:
                out.append((
                    "logparser_mesh_degraded", labels,
                    0.0 if mesh.stats().get("mode") == "distributed" else 1.0,
                ))
            quarantine = getattr(engine, "quarantine", None)
            if quarantine is not None:
                out.extend(samples_from_stats(
                    quarantine.stats(), _QUARANTINE_SAMPLES, labels
                ))
            shadow = getattr(engine, "shadow", None)
            if shadow is not None:
                out.extend(samples_from_stats(
                    shadow.stats(), _SHADOW_SAMPLES, labels
                ))
            miner = getattr(engine, "miner", None)
            if miner is not None:
                out.extend(samples_from_stats(
                    miner.stats(), _MINER_SAMPLES, labels
                ))
            cache = getattr(engine, "line_cache", None)
            if cache is not None:
                from log_parser_tpu.runtime import linecache as lc

                out.extend(samples_from_stats(
                    cache.stats(), lc.CACHE_METRIC_SAMPLES, labels
                ))
            interner = getattr(engine, "key_interner", None)
            if interner is not None:
                from log_parser_tpu.runtime import linecache as lc

                out.extend(samples_from_stats(
                    interner.stats(), lc.INTERNER_METRIC_SAMPLES, labels
                ))
            batcher = getattr(engine, "batcher", None)
            if batcher is not None:
                from log_parser_tpu.runtime import batcher as bt

                out.extend(samples_from_stats(
                    batcher.stats(), bt.METRIC_SAMPLES, labels
                ))
            return out

        self.registry.register_collector(f"engine-{id(engine)}", collect)

    def remove_engine_collector(self, engine) -> None:
        self.registry.unregister_collector(f"engine-{id(engine)}")

    def add_stats_collector(self, key: str, stats_fn, spec,
                            labels: dict | None = None) -> None:
        """Generic scrape-time bridge: ``stats_fn()`` dict through a
        ``(stats_key, metric, extra_labels)`` spec (admission gate,
        stream manager, tenant registry)."""

        def collect():
            return samples_from_stats(stats_fn(), spec, labels)

        self.registry.register_collector(key, collect)
