"""On-demand ``jax.profiler`` capture: ``POST /debug/profile`` arms a
single background capture thread that traces the live process for N
seconds into a state-dir subdirectory — the capture path for the owed
live-TPU re-baseline sessions (ROADMAP item 1) without restarting the
server. Single-flight: a second request while one is armed gets 409."""

from __future__ import annotations

import logging
import os
import threading
import time
from log_parser_tpu import _clock as pclock

log = logging.getLogger("log_parser_tpu.obs")

MAX_CAPTURE_S = 120.0


class ProfilerUnavailable(RuntimeError):
    """No capture directory configured (server started without
    ``--state-dir``)."""


class ProfilerBusy(RuntimeError):
    """A capture is already in flight."""


class DeviceProfiler:
    def __init__(self, base_dir: str | None = None, on_complete=None):
        self.base_dir = base_dir
        self.on_complete = on_complete
        self._lock = threading.Lock()
        self._active: str | None = None
        self.captures = 0
        self.last_dir: str | None = None
        self.last_error: str | None = None

    def configure(self, base_dir: str) -> None:
        self.base_dir = base_dir

    def start(self, seconds: float) -> str:
        """Arm one capture; returns the capture directory immediately
        while the trace runs on a daemon thread."""
        seconds = float(seconds)
        if not (0 < seconds <= MAX_CAPTURE_S):
            raise ValueError(
                f"seconds must be in (0, {MAX_CAPTURE_S:g}], got {seconds!r}"
            )
        if not self.base_dir:
            raise ProfilerUnavailable(
                "profiling requires --state-dir (no capture directory)"
            )
        with self._lock:
            if self._active is not None:
                raise ProfilerBusy(f"capture already running: {self._active}")
            capture_dir = os.path.join(
                self.base_dir, time.strftime("%Y%m%dT%H%M%S")
            )
            os.makedirs(capture_dir, exist_ok=True)
            self._active = capture_dir
        threading.Thread(
            target=self._capture, args=(capture_dir, seconds),
            name="obs-profiler", daemon=True,
        ).start()
        return capture_dir

    def _capture(self, capture_dir: str, seconds: float) -> None:
        try:
            from log_parser_tpu.utils.trace import profiler_trace

            with profiler_trace(capture_dir):
                pclock.sleep(seconds)
            with self._lock:
                self.captures += 1
                self.last_dir = capture_dir
                self.last_error = None
            if self.on_complete is not None:
                self.on_complete()
        except Exception as exc:  # profiler availability is best-effort
            log.exception("profile capture failed: %s", capture_dir)
            with self._lock:
                self.last_error = f"{type(exc).__name__}: {exc}"
        finally:
            with self._lock:
                self._active = None

    def status(self) -> dict:
        with self._lock:
            return {
                "configured": bool(self.base_dir),
                "active": self._active,
                "captures": self.captures,
                "lastDir": self.last_dir,
                "lastError": self.last_error,
            }
