"""Causal span store: the *why* behind the trace ring's *how much*.

PR 14's flat ring records one dict per request, but the fleet's
interesting latency is structural: the micro-batcher coalesces N
requests into ONE fused device flush, stream sessions spread one
request across many chunks, and tenant engines share one admission
gate. :class:`SpanStore` records bounded causal trees — a request span
with admission/enqueue/phase children, a *flush* span carrying
span-links to every coalesced request trace (fan-in the flat ring
cannot express), dispatch spans carrying device-utilization attributes
(tier, plan geometry, padded rows, dummy-slot waste), long-lived
stream-session spans with per-chunk children, and tenancy/broadcast
lifecycle spans.

Design rules:

- **Trace id == request id.** The propagated ``X-Request-Id`` plumbing
  from PR 14 is reused verbatim; stream sessions use their session id;
  flush spans mint their own trace and LINK (not parent) the member
  requests, because a flush belongs to several traces at once.
- **Stage, then commit.** Child spans are staged per trace id in a
  bounded dict; :meth:`end_trace` builds the root, attaches children
  and commits the whole tree iff the trace is sampled
  (``--trace-sample``, deterministic on the trace id), slow
  (``--trace-slow-ms``, always-on), or forced (flush/tenancy spans are
  rare and always kept). A dropped sample pops its staged children too
  — no orphans, ever.
- **Reconcile by construction.** The request root is built inside
  ``Obs.note_served`` from the *same* ``PhaseTrace`` dict and the same
  clock delta the ring entry and the phase histograms use, so
  ``/metrics``, ``/trace/recent`` and ``/trace/spans`` can never
  disagree about a duration.

Export: ``GET /trace/spans`` serves :meth:`traces` (self-contained
JSON) and :meth:`dump` writes an OTLP-compatible JSON file
(``resourceSpans`` shape) to the state dir.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from log_parser_tpu import _clock as pclock
import zlib
from collections import deque

DEFAULT_SPAN_CAPACITY = 256
DEFAULT_STAGING_CAPACITY = 512
DEFAULT_SAMPLE = 1.0

# Span-name vocabulary. hygiene check 17 pins every key to a
# backtick-quoted row of the docs/OPS.md span table; code may only
# record spans under these names (SpanStore rejects unknown ones), so
# the operator table can never drift from what actually serves.
SPANS = {
    "request": "one-shot parse: transport receipt to response handoff",
    "phase": "one PhaseTrace phase replayed as a child span (attr phase)",
    "admission": "admission-gate + tenant-quota acquire verdict",
    "enqueue": "micro-batcher enqueue: submit until flush pickup",
    "flush": "coalesced batch flush; links every member request trace",
    "dispatch": "one device dispatch: tier, plan geometry, utilization",
    "demux": "flush demux: per-request verify + finalize fan-out",
    "session": "stream session lifetime: open to close or kill",
    "chunk": "one stream chunk: bytes fed to frames emitted",
    "rebase": "stream session re-based onto a hot-reloaded library",
    "broadcast": "coordinator-to-follower mesh broadcast for one trace",
    "tenant_build": "tenant bank build (first touch or post-evict)",
    "tenant_evict": "tenant eviction: flush, close streams, fold WAL",
    "migration": "one tenant migration end-to-end (source side)",
    "migrate_export": "quiesce + WAL fold + bundle export (source)",
    "migrate_import": "bundle verify, warm stage and activate (target)",
    "migrate_cutover": "ownership commit: forward install + handoff",
    "drain": "one drain-supervisor pass: migrate-or-close every tenant",
    "replicate": "one replication batch applied on the standby (warm bank)",
    "promote": "fenced failover: PROMOTE journaled, tenants activated",
    "demote": "stale-epoch step-down: DEMOTE journaled, registry fenced",
    "route": "router edge: tenant resolve, ring lookup, backend proxy",
    "pressure": "resource-pressure ladder transition (attrs resource/state)",
}


def _span_id(trace_id: str) -> str:
    """Deterministic 8-byte root span id for a trace id, so a link to
    another trace's root can be minted WITHOUT looking that trace up
    (the linked trace may not even be committed yet)."""
    return hashlib.blake2b(trace_id.encode("utf-8", "replace"),
                           digest_size=8).hexdigest()


def _otlp_trace_id(trace_id: str) -> str:
    """16-byte OTLP trace id derived from the wire trace id (which is
    free-form: inbound X-Request-Id survives cleaning at ≤128 chars)."""
    return hashlib.blake2b(trace_id.encode("utf-8", "replace"),
                           digest_size=16).hexdigest()


def _link(trace_id: str) -> dict:
    """A span-link to another trace's root span."""
    return {"traceId": trace_id, "spanId": _span_id(trace_id)}


class SpanStore:
    """Process-wide bounded causal-span store (one per Obs bundle;
    tenant engines share the primary's, like the ring)."""

    def __init__(self, capacity: int = DEFAULT_SPAN_CAPACITY,
                 sample: float = DEFAULT_SAMPLE,
                 slow_ms: float = 500.0,
                 staging_capacity: int = DEFAULT_STAGING_CAPACITY):
        self.capacity = max(1, int(capacity))
        self.sample = min(1.0, max(0.0, float(sample)))
        self.slow_ms = float(slow_ms)
        self.staging_capacity = max(1, int(staging_capacity))
        self._lock = threading.Lock()
        self._traces: deque[dict] = deque(maxlen=self.capacity)
        self._staging: dict[str, list[dict]] = {}
        self._seq = 0
        self._span_seq = 0
        self.committed = 0
        self.dropped_traces = 0
        self.staging_evicted = 0
        self.span_count = 0

    # ----------------------------------------------------- sampling

    def sampled(self, trace_id: str) -> bool:
        """Deterministic head sampling on the trace id: the same id
        gives the same verdict on every process and every surface, so
        a replayed request is reproducibly kept or reproducibly cheap."""
        if self.sample >= 1.0:
            return True
        if self.sample <= 0.0:
            return False
        h = zlib.crc32(trace_id.encode("utf-8", "replace")) & 0xFFFFFFFF
        return (h % 10000) < int(self.sample * 10000)

    # ------------------------------------------------------ staging

    def _new_span(self, name: str, parent: str | None, t0: float,
                  duration_s: float, attrs: dict | None,
                  links: list | None) -> dict:
        if name not in SPANS:
            raise ValueError(f"unknown span name: {name!r}")
        self._span_seq += 1
        span = {
            "spanId": f"{self._span_seq:016x}",
            "name": name,
            "startUnixNano": int(t0 * 1e9),
            "durationMs": round(duration_s * 1e3, 6),
        }
        if parent:
            span["parentSpanId"] = parent
        if attrs:
            span["attrs"] = dict(attrs)
        if links:
            span["links"] = [
                ln if isinstance(ln, dict) else _link(ln) for ln in links
            ]
        return span

    def annotate(self, trace_id: str, name: str, duration_s: float,
                 attrs: dict | None = None, links: list | None = None,
                 t0: float | None = None) -> None:
        """Stage one completed child span under ``trace_id``; it is
        attached (parented to the root) when the trace ends. Bounded:
        the oldest staged trace is evicted whole when the staging dict
        would exceed its capacity, so an abandoned trace id can never
        grow the store."""
        if t0 is None:
            t0 = pclock.wall() - duration_s
        with self._lock:
            span = self._new_span(name, _span_id(trace_id), t0,
                                  duration_s, attrs, links)
            bucket = self._staging.get(trace_id)
            if bucket is None:
                while len(self._staging) >= self.staging_capacity:
                    self._staging.pop(next(iter(self._staging)))
                    self.staging_evicted += 1
                bucket = self._staging[trace_id] = []
            bucket.append(span)

    # ------------------------------------------------------- commit

    def end_trace(self, trace_id: str, duration_s: float,
                  tenant: str = "default", name: str = "request",
                  attrs: dict | None = None,
                  phases: dict | None = None,
                  links: list | None = None,
                  force: bool = False,
                  t0: float | None = None) -> bool:
        """Finish a trace: build its root span, replay ``phases`` (the
        request's PhaseTrace dict, seconds per phase) as sequential
        ``phase`` children, attach every staged child, and commit the
        tree iff sampled/slow/forced. Staged children are popped
        either way — a dropped sample never orphans a child span.
        Returns True when the trace was committed."""
        if name not in SPANS:
            raise ValueError(f"unknown span name: {name!r}")
        total_ms = duration_s * 1e3
        keep = force or total_ms >= self.slow_ms or self.sampled(trace_id)
        if t0 is None:
            t0 = pclock.wall() - duration_s
        with self._lock:
            staged = self._staging.pop(trace_id, None)
            if not keep:
                self.dropped_traces += 1
                return False
            self._span_seq += 1
            root = {
                "spanId": _span_id(trace_id),
                "name": name,
                "startUnixNano": int(t0 * 1e9),
                "durationMs": round(total_ms, 6),
            }
            if attrs:
                root["attrs"] = dict(attrs)
            if links:
                root["links"] = [
                    ln if isinstance(ln, dict) else _link(ln)
                    for ln in links
                ]
            spans = [root]
            offset = 0.0
            for pname, seconds in (phases or {}).items():
                spans.append(self._new_span(
                    "phase", root["spanId"], t0 + offset, seconds,
                    {"phase": pname}, None,
                ))
                offset += seconds
            if staged:
                spans.extend(staged)
            self._seq += 1
            self._traces.append({
                "traceId": trace_id,
                "otlpTraceId": _otlp_trace_id(trace_id),
                "seq": self._seq,
                "tenant": tenant,
                "name": name,
                "slow": total_ms >= self.slow_ms,
                "totalMs": round(total_ms, 3),
                "spans": spans,
            })
            self.committed += 1
            self.span_count += len(spans)
        return True

    # ------------------------------------------------------- reads

    def traces(self, n: int | None = None) -> list[dict]:
        """Committed traces, newest first (the /trace/spans payload)."""
        with self._lock:
            items = list(self._traces)
        items.reverse()
        return items if n is None else items[: max(0, int(n))]

    def find(self, trace_id: str) -> dict | None:
        with self._lock:
            for tr in reversed(self._traces):
                if tr["traceId"] == trace_id:
                    return tr
        return None

    def stats(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "sample": self.sample,
                "slowMs": self.slow_ms,
                "retained": len(self._traces),
                "committed": self.committed,
                "droppedTraces": self.dropped_traces,
                "staged": len(self._staging),
                "stagingEvicted": self.staging_evicted,
                "spanCount": self.span_count,
            }

    # ------------------------------------------------------- export

    @staticmethod
    def _otlp_value(v) -> dict:
        if isinstance(v, bool):
            return {"boolValue": v}
        if isinstance(v, int):
            return {"intValue": str(v)}
        if isinstance(v, float):
            return {"doubleValue": v}
        return {"stringValue": str(v)}

    def export_otlp(self) -> dict:
        """The committed store as one OTLP/JSON ``resourceSpans``
        document (ExportTraceServiceRequest shape) — importable by any
        OTLP-speaking backend without a collector in the loop."""
        spans_out = []
        for tr in self.traces():
            tid = tr["otlpTraceId"]
            for span in tr["spans"]:
                start = span["startUnixNano"]
                end = start + int(span["durationMs"] * 1e6)
                item = {
                    "traceId": tid,
                    "spanId": span["spanId"],
                    "name": span["name"],
                    "kind": 1,  # SPAN_KIND_INTERNAL
                    "startTimeUnixNano": str(start),
                    "endTimeUnixNano": str(end),
                }
                if span.get("parentSpanId"):
                    item["parentSpanId"] = span["parentSpanId"]
                attrs = dict(span.get("attrs") or {})
                attrs.setdefault("tenant", tr["tenant"])
                attrs.setdefault("trace.wire_id", tr["traceId"])
                item["attributes"] = [
                    {"key": k, "value": self._otlp_value(v)}
                    for k, v in attrs.items()
                ]
                if span.get("links"):
                    item["links"] = [
                        {"traceId": _otlp_trace_id(ln["traceId"]),
                         "spanId": ln["spanId"]}
                        for ln in span["links"]
                    ]
                spans_out.append(item)
        return {
            "resourceSpans": [{
                "resource": {"attributes": [{
                    "key": "service.name",
                    "value": {"stringValue": "log_parser_tpu"},
                }]},
                "scopeSpans": [{
                    "scope": {"name": "log_parser_tpu.obs.spans"},
                    "spans": spans_out,
                }],
            }],
        }

    def dump(self, path: str) -> str | None:
        """Write the OTLP document to ``path`` (tmp + rename so a
        crashed dump never leaves a torn file). Returns the path, or
        None when the write was skipped atomically because the disk
        ladder is hard — a span dump is the least valuable bytes in the
        process and must never raise into a drain (runtime/pressure.py)."""
        from log_parser_tpu.runtime import pressure

        if pressure.writes_paused():
            return None
        doc = self.export_otlp()
        tmp = f"{path}.tmp"
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        try:
            pressure.disk_write_guard("otlp_dump")
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, separators=(",", ":"))
            os.replace(tmp, path)
        except OSError as exc:
            pressure.note_write_error(exc, "otlp_dump")
            raise
        return path

    def trim_staging(self, capacity: int) -> int:
        """Memory-pressure lever (runtime/pressure.py): shrink the
        staging bound and evict oldest staged buckets down to it.
        Evicted buckets count as ``staging_evicted`` — their traces
        commit rootless-children-free, exactly like a staging overflow
        today. Returns how many buckets were evicted."""
        evicted = 0
        with self._lock:
            self.staging_capacity = max(1, int(capacity))
            while len(self._staging) > self.staging_capacity:
                self._staging.pop(next(iter(self._staging)))
                self.staging_evicted += 1
                evicted += 1
        return evicted
