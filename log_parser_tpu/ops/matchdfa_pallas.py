"""Pallas TPU kernel for the union multi-DFA reported-flag scan.

The lax.scan implementation (ops/match.py ``MultiDfaBank`` /
``MultiDfaCluster``) pays one ``[B]`` (or ``[B, G]``) flat-table gather
per byte — and TPU gathers run on the scalar unit at ~9 ns/element
(PERF.md §1/§4), which is the measured binding constraint of the multi
tier. This kernel keeps the byte-precomposed transition table resident
in VMEM and replaces the per-step gather with MXU one-hot matmuls
vectorized across the batch tile:

- the table is re-encoded densely as ``v' = next_state * 2 + reported``
  (``next_state < 8192`` under the union state budget, so ``v' <= 16383``
  fits two exact 8-bit matmul planes — TPU matmuls run at bfloat16
  precision, 8-bit mantissa, the same plane split as bitglush_pallas.py)
  and transposed to ``[256, S]`` so one transposed byte one-hot
  (``[256, TILE]``, iota-over-sublanes compared against the byte row —
  never materialized in HBM) contracts to the per-state transition row
  ``[TILE, S]`` for every lane's byte in one MXU pass;
- the state select is a lane-iota compare against the carried state
  column (``[TILE, 1]``) summed over lanes — a vector select, not a
  gather;
- scan state (state, reported) stays in VMEM across a ``fori_loop`` over
  the byte steps (the unrolled form blew the Mosaic compile past 9
  minutes on the bitglush kernel at T=64; the loop form compiles in
  seconds), with single-stride and pair-stride variants (the pair
  variant mirrors the fused scan's byte-pair steps; both orders visit
  every byte and are bit-identical);
- groups ride the grid: ``grid = (G, B // TILE)`` with each group's
  plane pair streamed per grid step, so one ``pallas_call`` advances the
  whole union cluster.

Padding is gate-free exactly like the scan tier: byte 0 of the packed
table self-loops carrying the state's own report flag (content NULs
never reach the device), so no length gating is needed and the reported
OR past end-of-line is an idempotent re-OR. The exact flagged-row
accept recovery (``_multi_contribution`` — out-word re-scan of flagged
rows with the ``lax.cond`` dense fallback) deliberately stays on the
XLA tier: it touches only the rare flagged rows, so the gather there is
not on the hot path.

Admission: the dense planes cost ``2 * 256 * S_pad * 4`` bytes of VMEM
per group block. ``build_dfa_plan`` refuses banks whose padded state
count blows the scoped-VMEM budget (Mosaic scopes ~16 MB; we budget
12 MB and leave the rest for the byte tile, the one-hot, and the
``[TILE, S_pad]`` temporaries), and ``dfa_tile`` re-checks at call time
against the actual T and shrinks the batch tile before giving up —
callers fall back to the XLA scan tier on ``None``. Mosaic-friendly
dialect throughout: int32 only, logical shifts via
``jax.lax.shift_right_logical``, no bool vectors (compare results are
cast immediately), 128-aligned lane slices (``S_pad`` is rounded up to
a lane multiple).

Semantics are IDENTICAL to the scan tier's reported-flag carry —
verified bit-exactly by tests/test_matchdfa_pallas.py (interpreter
mode) and adjudicated on live TPU by tools/probe_kernels.py.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from log_parser_tpu.ops.bitglush_pallas import _SRL, _dotT, pick_tile

# Smaller than bitglush's 512: the [TILE, S_pad] transition-row
# temporaries scale with the tile, and the planes already claim most of
# the budget at large S.
DFA_TILE_B = 128
DFA_VMEM_BUDGET = 12 * 1024 * 1024
# T used for admission when the batch's padded length is not yet known
# (host-side tier predicates); dfa_tile re-checks with the real T.
_NOMINAL_T = 512

_REPORT_BIT = 1 << 30  # MultiDfaBank._REPORT_BIT
_STATE_MASK = _REPORT_BIT - 1

# Tier reason codes surfaced in /trace/last (kernel block) and pinned to
# docs/OPS.md rows by tools/hygiene.py. Keep keys snake_case words.
REASONS = {
    "ok": "kernel admitted; union groups run through the Pallas scan",
    "off": "LOG_PARSER_TPU_PALLAS_DFA unset (default) — XLA scan tier",
    "no_union_groups": "bank packed no union multi-DFA groups",
    "table_too_large": "dense planes exceed the VMEM budget — XLA scan",
    "no_tile": "no usable batch tile for this batch size — XLA scan",
    "fault": "kernel path raised; whole batch fell back to the XLA scan",
}


@dataclass
class DfaKernelPlan:
    """Host-packed kernel operands for one bank's union groups."""

    p0: np.ndarray  # [256, G * s_pad] float32: (state*2 + rep) & 0xFF
    p1: np.ndarray  # [256, G * s_pad] float32: (state*2 + rep) >> 8
    starts: np.ndarray  # [G, 2] int32: (start state, start reported)
    s_pad: int
    n_groups: int


def _group_planes(group, s_pad: int) -> tuple[np.ndarray, np.ndarray]:
    """Dense 8-bit plane pair [256, s_pad] of one group's precomposed
    table, re-encoded v' = next_state * 2 + reported and transposed to
    byte-major. Padding states carry v' = 0; they are unreachable (the
    carried state never leaves [0, S))."""
    pb = np.asarray(group._packed_byte_np, dtype=np.int64).reshape(-1, 256)
    vp = ((pb & _STATE_MASK) * 2 + ((pb >> 30) & 1)).astype(np.int32)
    p0 = np.zeros((256, s_pad), np.float32)
    p1 = np.zeros((256, s_pad), np.float32)
    p0[:, : vp.shape[0]] = (vp & 0xFF).T
    p1[:, : vp.shape[0]] = (vp >> 8).T
    return p0, p1


def _vmem_estimate(s_pad: int, tile: int, T: int) -> int:
    """Bytes of VMEM one grid step needs: byte tile + both planes + the
    transposed one-hot + ~5 [tile, s_pad] f32/i32 temporaries (two plane
    results, reassembled next, select mask, product) + carries/out."""
    return 4 * (
        T * tile + 2 * 256 * s_pad + 256 * tile + 5 * tile * s_pad + 2 * tile
    )


def build_dfa_plan(
    groups, budget: int | None = None
) -> tuple[DfaKernelPlan | None, str]:
    """Pack a bank's union groups into kernel operands, or refuse with a
    REASONS code. Admission here is table-size only (state counts are
    static); the batch tile is re-admitted per call by dfa_tile."""
    if budget is None:
        budget = DFA_VMEM_BUDGET
    if not groups:
        return None, "no_union_groups"
    s_max = max(g.n_states for g in groups)
    s_pad = max(128, -(-s_max // 128) * 128)  # 128-aligned lane slices
    if _vmem_estimate(s_pad, DFA_TILE_B, _NOMINAL_T) > budget:
        return None, "table_too_large"
    G = len(groups)
    p0 = np.zeros((256, G * s_pad), np.float32)
    p1 = np.zeros((256, G * s_pad), np.float32)
    starts = np.zeros((G, 2), np.int32)
    for gi, g in enumerate(groups):
        a, b = _group_planes(g, s_pad)
        p0[:, gi * s_pad : (gi + 1) * s_pad] = a
        p1[:, gi * s_pad : (gi + 1) * s_pad] = b
        starts[gi] = (g.start, int(g.start_reports))
    return DfaKernelPlan(p0, p1, starts, s_pad, G), "ok"


def dfa_tile(
    plan: DfaKernelPlan,
    B: int,
    T: int | None = None,
    budget: int | None = None,
) -> int | None:
    """Largest admissible batch tile for a B-row batch, shrinking until
    the VMEM estimate fits; None when no tile works (caller falls back
    to the XLA scan)."""
    if budget is None:
        budget = DFA_VMEM_BUDGET
    T = _NOMINAL_T if T is None else T
    limit = DFA_TILE_B
    while True:
        tile = pick_tile(B, limit)
        if tile is None:
            return None
        if _vmem_estimate(plan.s_pad, tile, T) <= budget:
            return tile
        limit = tile - 8


def _kernel(bytes_ref, p0_ref, p1_ref, start_ref, out_ref, *, T, stride):
    tile = out_ref.shape[0]
    s_pad = p0_ref.shape[1]
    row256 = jax.lax.broadcasted_iota(jnp.int32, (256, tile), 0)
    lane_s = jax.lax.broadcasted_iota(jnp.int32, (tile, s_pad), 1)
    one = jnp.int32(1)

    def step(t, s, rep):
        b_row = bytes_ref[pl.ds(t, 1), :]  # [1, TILE]
        ohT = (row256 == b_row).astype(jnp.float32)  # [256, TILE]
        n0 = _dotT(ohT, p0_ref[:])  # [TILE, s_pad]
        n1 = _dotT(ohT, p1_ref[:])
        nxt = n0.astype(jnp.int32) | (n1.astype(jnp.int32) << 8)
        sel = (lane_s == s).astype(jnp.int32)  # state one-hot per lane
        v = jnp.sum(nxt * sel, axis=1, keepdims=True)  # [TILE, 1]
        return _SRL(v, one), rep | (v & one)

    if stride == 2:
        n_steps = T // 2

        def body(i, carry):
            s, rep = step(2 * i, *carry)
            return step(2 * i + 1, s, rep)

    else:
        n_steps = T

        def body(i, carry):
            return step(i, *carry)

    init = (
        jnp.full((tile, 1), start_ref[0, 0], jnp.int32),
        jnp.full((tile, 1), start_ref[0, 1], jnp.int32),
    )
    s, rep = jax.lax.fori_loop(0, n_steps, body, init)
    if stride == 2 and T % 2:
        s, rep = step(T - 1, s, rep)
    out_ref[:] = rep


def multidfa_reported_pallas(
    plan: DfaKernelPlan,
    lines_tb: jax.Array,
    stride: int = 2,
    interpret: bool | None = None,
    tile_b: int | None = None,
    budget: int | None = None,
) -> jax.Array:
    """Run every union group's reported-flag scan in one Pallas call.

    ``lines_tb``: uint8 [T, B]; returns int32 [B, G] 0/1 reported flags
    in group order, bit-equal to finishing the scan tier's pair_stepper
    carry. ``stride`` 2 mirrors the fused scan's byte-pair steps; 1 is
    the single-stride variant (identical results, A/B'd by
    tools/probe_kernels.py)."""
    assert stride in (1, 2)
    T, B = lines_tb.shape
    if interpret is None:
        # Mosaic needs real TPU hardware; everywhere else (CPU test
        # meshes) the interpreter executes the same kernel semantics
        interpret = jax.default_backend() != "tpu"
    tile = dfa_tile(plan, B, T, budget=budget) if tile_b is None else tile_b
    assert tile is not None, f"no usable tile for batch rows {B}"
    G, s_pad = plan.n_groups, plan.s_pad
    kernel = functools.partial(_kernel, T=T, stride=stride)
    return pl.pallas_call(
        kernel,
        grid=(G, B // tile),
        in_specs=[
            pl.BlockSpec(
                (T, tile), lambda g, i: (0, i), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (256, s_pad), lambda g, i: (0, g), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (256, s_pad), lambda g, i: (0, g), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec((1, 2), lambda g, i: (g, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (tile, 1), lambda g, i: (i, g), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((B, G), jnp.int32),
        interpret=interpret,
    )(
        lines_tb.astype(jnp.int32),
        jnp.asarray(plan.p0),
        jnp.asarray(plan.p1),
        jnp.asarray(plan.starts),
    )
