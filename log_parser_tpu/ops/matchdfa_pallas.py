"""Pallas TPU kernel for the union multi-DFA reported-flag scan.

The lax.scan implementation (ops/match.py ``MultiDfaBank`` /
``MultiDfaCluster``) pays one ``[B]`` (or ``[B, G]``) flat-table gather
per byte — and TPU gathers run on the scalar unit at ~9 ns/element
(PERF.md §1/§4), which is the measured binding constraint of the multi
tier. This kernel keeps the transition planes resident in VMEM and
replaces the per-step gather with MXU one-hot matmuls vectorized across
the batch tile:

- the table is re-encoded densely as ``v' = next_state * 2 + reported``
  (``next_state < 8192`` under the union state budget, so ``v' <= 16383``
  fits two exact 8-bit matmul planes — TPU matmuls run at bfloat16
  precision, 8-bit mantissa, the same plane split as bitglush_pallas.py);
- the byte axis is BYTE-CLASS COMPRESSED (PERF.md §16): planes are
  ``[n_classes_pad, S_pad]`` over the group's ~dozens of distinct byte
  classes, not ``[256, S_pad]`` over raw bytes — a tiny per-group
  ``[1, 256]`` class-map row contracts against the transposed byte
  one-hot (``[256, TILE]``, iota-over-sublanes compared against the byte
  row — never materialized in HBM) to yield each lane's class, a second
  one-hot over classes then contracts with the planes. Both the VMEM
  footprint and the MXU contraction shrink by 256/n_classes (~4–10×);
- the state select is a lane-iota compare against the carried state
  column (``[TILE, 1]``) summed over lanes — a vector select, not a
  gather;
- scan state (state, reported) stays in VMEM across a ``fori_loop`` over
  the byte steps (the unrolled form blew the Mosaic compile past 9
  minutes on the bitglush kernel at T=64; the loop form compiles in
  seconds), with single-stride and pair-stride variants (the pair
  variant mirrors the fused scan's byte-pair steps; both orders visit
  every byte and are bit-identical);
- groups ride the grid: ``grid = (G, B // TILE)`` with each group's
  class map + plane pair streamed per grid step, so one ``pallas_call``
  advances the whole union cluster.

Padding is gate-free exactly like the scan tier: the class map routes
byte 0 to a per-group IDENTITY class whose plane row self-loops carrying
the state's own report flag (content NULs never reach the device), so no
length gating is needed and the reported OR past end-of-line is an
idempotent re-OR. The exact flagged-row accept recovery
(``_multi_contribution`` — out-word re-scan of flagged rows with the
``lax.cond`` dense fallback) deliberately stays on the XLA tier: it
touches only the rare flagged rows, so the gather there is not on the
hot path.

Admission: ``build_dfa_plan`` packs each group's minimized automaton
(patterns/regex/minimize.py runs at compile time) into class-compressed
planes and, when the padded geometry still blows the scoped-VMEM budget
(Mosaic scopes ~16 MB; we budget 12 MB and leave the rest for the byte
tile, the one-hots, and the ``[TILE, S_pad]`` temporaries), RE-SPLITS
the offending union group into the cheapest admissible k-way partition
(``entries`` supplies the group's regexes) instead of refusing outright
— refusal (``table_too_large``) remains only for callers that cannot
recompile (no entries) or groups inadmissible even alone. The admitted
plan carries the (possibly re-partitioned) groups and a ``geometry``
report (states before/after minimization, byte classes, plane bytes,
chosen split) surfaced on ``/trace/last`` and tools/probe_kernels.py.
``dfa_tile`` re-checks at call time against the actual T and shrinks the
batch tile before giving up — callers fall back to the XLA scan tier on
``None``. Mosaic-friendly dialect throughout: int32 only, logical
shifts via ``jax.lax.shift_right_logical``, no bool vectors (compare
results are cast immediately), 128-aligned lane slices (``S_pad``) and
8-aligned sublane counts (``nc_pad``).

Semantics are IDENTICAL to the scan tier's reported-flag carry —
verified bit-exactly by tests/test_matchdfa_pallas.py (interpreter
mode) and adjudicated on live TPU by tools/probe_kernels.py.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from log_parser_tpu.ops.bitglush_pallas import _SRL, _dotT, pick_tile

# Smaller than bitglush's 512: the [TILE, S_pad] transition-row
# temporaries scale with the tile, and the planes already claim most of
# the budget at large S.
DFA_TILE_B = 128
DFA_VMEM_BUDGET = 12 * 1024 * 1024
# T used for admission when the batch's padded length is not yet known
# (host-side tier predicates); dfa_tile re-checks with the real T.
_NOMINAL_T = 512

_REPORT_BIT = 1 << 30  # MultiDfaBank._REPORT_BIT
_STATE_MASK = _REPORT_BIT - 1

# Tier reason codes surfaced in /trace/last (kernel block) and pinned to
# docs/OPS.md rows by tools/hygiene.py. Keep keys snake_case words.
REASONS = {
    "byte_classed": "kernel admitted as packed: minimized byte-class "
    "planes fit the VMEM budget without re-partitioning",
    "split": "kernel admitted after re-partitioning: the cheapest "
    "admissible union-group split replaced the packed groups",
    "off": "LOG_PARSER_TPU_PALLAS_DFA unset (default) — XLA scan tier",
    "no_union_groups": "bank packed no union multi-DFA groups",
    "table_too_large": "dense planes exceed the VMEM budget — XLA scan",
    "no_tile": "no usable batch tile for this batch size — XLA scan",
    "fault": "kernel path raised; whole batch fell back to the XLA scan",
}

#: reason codes meaning "an admissible plan exists" (provenance split)
ADMITTED = frozenset({"byte_classed", "split"})


@dataclass
class DfaKernelPlan:
    """Host-packed kernel operands for one bank's union groups."""

    cmap: np.ndarray  # [G, 256] float32 byte→class, byte 0 → identity class
    p0: np.ndarray  # [nc_pad, G * s_pad] float32: (state*2 + rep) & 0xFF
    p1: np.ndarray  # [nc_pad, G * s_pad] float32: (state*2 + rep) >> 8
    starts: np.ndarray  # [G, 2] int32: (start state, start reported)
    s_pad: int
    nc_pad: int
    n_groups: int
    # the (possibly re-partitioned) MultiDfaBank groups this plan serves,
    # in plane order — callers adopt these so scan-tier fallbacks and the
    # kernel agree on group membership
    groups: list = field(default_factory=list)
    # admission report: states before/after minimization, byte classes,
    # plane bytes, chosen split (see build_dfa_plan)
    geometry: dict = field(default_factory=dict)


def _pad_states(n: int) -> int:
    return max(128, -(-n // 128) * 128)  # 128-aligned lane slices


def _pad_classes(n: int) -> int:
    # +1 for the identity padding class; 8-aligned f32 sublanes
    return max(8, -(-(n + 1) // 8) * 8)


def _group_planes(
    group, s_pad: int, nc_pad: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Class-compressed 8-bit plane pair [nc_pad, s_pad] of one group's
    minimized table, re-encoded v' = next_state * 2 + reported and
    transposed class-major, plus the [256] float class map. Class C (the
    group's identity padding class) self-loops carrying each state's own
    report flag and byte 0 maps to it; padding classes past C and padding
    states past S carry v' = 0 — unreachable (the class map only emits
    [0, C] and the carried state never leaves [0, S))."""
    trans = np.asarray(group._trans_np, dtype=np.int64)  # [S, C]
    reports = np.asarray(group._reports_np, dtype=np.int64)  # [S] 0/1
    S, C = trans.shape
    vp = np.zeros((nc_pad, s_pad), np.int32)
    vp[:C, :S] = (trans * 2 + reports[trans]).T
    vp[C, :S] = np.arange(S, dtype=np.int64) * 2 + reports
    cmap = np.asarray(group._byte_class_np, dtype=np.float32).copy()
    cmap[0] = C
    return (vp & 0xFF).astype(np.float32), (vp >> 8).astype(np.float32), cmap


def _vmem_estimate(s_pad: int, nc_pad: int, tile: int, T: int) -> int:
    """Bytes of VMEM one grid step needs: byte tile + both class planes +
    the class map + the byte and class one-hots + ~5 [tile, s_pad]
    f32/i32 temporaries (two plane results, reassembled next, select
    mask, product) + carries/out."""
    return 4 * (
        T * tile
        + 2 * nc_pad * s_pad
        + 256
        + 256 * tile
        + nc_pad * tile
        + tile
        + 5 * tile * s_pad
        + 2 * tile
    )


def _group_cost(group) -> int:
    return _vmem_estimate(
        _pad_states(group.n_states),
        _pad_classes(group.n_classes),
        DFA_TILE_B,
        _NOMINAL_T,
    )


def _plane_bytes(groups) -> int:
    return sum(
        2 * 4 * _pad_classes(g.n_classes) * _pad_states(g.n_states)
        for g in groups
    )


def _chunks(seq: list, k: int) -> list[list]:
    base, rem = divmod(len(seq), k)
    out, i = [], 0
    for j in range(k):
        size = base + (1 if j < rem else 0)
        if size:
            out.append(seq[i : i + size])
            i += size
    return out


def _compile_parts(group_entries: list, k: int, max_states: int):
    """Compile a k-way contiguous split of one group's (key, regex, ci)
    entries into minimized MultiDfaBank parts; None when any chunk blows
    the state budget (caller tries a finer split)."""
    from log_parser_tpu.ops.match import MultiDfaBank
    from log_parser_tpu.patterns.regex.multidfa import (
        MultiDfaLimitError,
        compile_union_regexes,
    )

    parts = []
    for chunk in _chunks(group_entries, k):
        try:
            md = compile_union_regexes(
                [(rx, ci) for _, rx, ci in chunk],
                max_states=max_states,
                minimize=True,
            )
        except MultiDfaLimitError:
            return None
        parts.append(MultiDfaBank(md, [key for key, _, _ in chunk]))
    return parts


def _split_group(group_entries: list, budget: int, max_states: int):
    """Cheapest admissible re-partition of one union group: the first
    k-way contiguous balanced split whose parts each fit the budget at
    the nominal tile, priced against the (k+1)-way alternative by total
    plane bytes. None when even singletons are inadmissible."""
    n = len(group_entries)
    chosen = None
    for k in range(2, n + 1):
        parts = _compile_parts(group_entries, k, max_states)
        if parts is None:
            continue
        if all(_group_cost(p) <= budget for p in parts):
            chosen = (k, parts)
            break
    if chosen is None:
        return None
    k, parts = chosen
    if k < n:
        alt = _compile_parts(group_entries, k + 1, max_states)
        if (
            alt is not None
            and all(_group_cost(p) <= budget for p in alt)
            and _plane_bytes(alt) < _plane_bytes(parts)
        ):
            k, parts = k + 1, alt
    return parts, _chunks(group_entries, k)


def build_dfa_plan(
    groups,
    budget: int | None = None,
    entries: list | None = None,
    max_states: int = 8192,
) -> tuple[DfaKernelPlan | None, str]:
    """Pack a bank's union groups into kernel operands, or refuse with a
    REASONS code.

    ``entries``: per-group ``(key, regex, case_insensitive)`` lists in
    bit order (MatcherBanks keeps them beside ``multi_groups``). When the
    padded geometry exceeds ``budget``, the costliest group is re-split
    via ``entries`` (cheapest admissible k-way partition) until the plan
    admits — callers must then adopt ``plan.groups``. Without entries
    the old refuse-outright behaviour stands. Admission here is
    table-geometry only (state/class counts are static); the batch tile
    is re-admitted per call by dfa_tile. Returns reason ``byte_classed``
    (admitted as packed) or ``split`` (admitted after re-partitioning)."""
    if budget is None:
        budget = DFA_VMEM_BUDGET
    if not groups:
        return None, "no_union_groups"
    groups = list(groups)
    entries = [list(e) for e in entries] if entries is not None else None
    split_desc: list[str] = []
    while True:
        s_pad = _pad_states(max(g.n_states for g in groups))
        nc_pad = _pad_classes(max(g.n_classes for g in groups))
        if _vmem_estimate(s_pad, nc_pad, DFA_TILE_B, _NOMINAL_T) <= budget:
            break
        gi = max(range(len(groups)), key=lambda i: _group_cost(groups[i]))
        if entries is None or len(entries[gi]) < 2:
            return None, "table_too_large"
        split = _split_group(entries[gi], budget, max_states)
        if split is None:
            return None, "table_too_large"
        parts, part_entries = split
        split_desc.append(f"{len(entries[gi])}p->{len(parts)}")
        groups[gi : gi + 1] = parts
        entries[gi : gi + 1] = part_entries
    G = len(groups)
    cmap = np.zeros((G, 256), np.float32)
    p0 = np.zeros((nc_pad, G * s_pad), np.float32)
    p1 = np.zeros((nc_pad, G * s_pad), np.float32)
    starts = np.zeros((G, 2), np.int32)
    for gi, g in enumerate(groups):
        a, b, cm = _group_planes(g, s_pad, nc_pad)
        p0[:, gi * s_pad : (gi + 1) * s_pad] = a
        p1[:, gi * s_pad : (gi + 1) * s_pad] = b
        cmap[gi] = cm
        starts[gi] = (g.start, int(g.start_reports))
    geometry = {
        "nGroups": G,
        "sPad": s_pad,
        "ncPad": nc_pad,
        "planeBytes": 2 * 4 * nc_pad * G * s_pad,
        "vmemPerStep": _vmem_estimate(s_pad, nc_pad, DFA_TILE_B, _NOMINAL_T),
        "statesUnmin": sum(g.n_states_unmin for g in groups),
        "states": sum(g.n_states for g in groups),
        "groupPatterns": [g.n_cols for g in groups],
        "groupStatesUnmin": [g.n_states_unmin for g in groups],
        "groupStates": [g.n_states for g in groups],
        "groupByteClasses": [g.n_classes for g in groups],
        "split": ",".join(split_desc) if split_desc else None,
    }
    plan = DfaKernelPlan(
        cmap, p0, p1, starts, s_pad, nc_pad, G, groups, geometry
    )
    return plan, ("split" if split_desc else "byte_classed")


def dfa_tile(
    plan: DfaKernelPlan,
    B: int,
    T: int | None = None,
    budget: int | None = None,
) -> int | None:
    """Largest admissible batch tile for a B-row batch, shrinking until
    the VMEM estimate fits; None when no tile works (caller falls back
    to the XLA scan)."""
    if budget is None:
        budget = DFA_VMEM_BUDGET
    T = _NOMINAL_T if T is None else T
    limit = DFA_TILE_B
    while True:
        tile = pick_tile(B, limit)
        if tile is None:
            return None
        if _vmem_estimate(plan.s_pad, plan.nc_pad, tile, T) <= budget:
            return tile
        limit = tile - 8


def _kernel(
    bytes_ref, cmap_ref, p0_ref, p1_ref, start_ref, out_ref, *, T, stride
):
    tile = out_ref.shape[0]
    nc_pad, s_pad = p0_ref.shape
    row256 = jax.lax.broadcasted_iota(jnp.int32, (256, tile), 0)
    rowC = jax.lax.broadcasted_iota(jnp.int32, (nc_pad, tile), 0)
    lane_s = jax.lax.broadcasted_iota(jnp.int32, (tile, s_pad), 1)
    one = jnp.int32(1)

    def step(t, s, rep):
        b_row = bytes_ref[pl.ds(t, 1), :]  # [1, TILE]
        ohT = (row256 == b_row).astype(jnp.float32)  # [256, TILE]
        # per-lane byte class: the [1, 256] map row contracted against
        # the byte one-hot — class ids <= 256 are exact at bf16's 8-bit
        # mantissa, same argument as the planes
        cls = jnp.dot(
            cmap_ref[:], ohT, preferred_element_type=jnp.float32
        ).astype(jnp.int32)  # [1, TILE]
        ohC = (rowC == cls).astype(jnp.float32)  # [nc_pad, TILE]
        n0 = _dotT(ohC, p0_ref[:])  # [TILE, s_pad]
        n1 = _dotT(ohC, p1_ref[:])
        nxt = n0.astype(jnp.int32) | (n1.astype(jnp.int32) << 8)
        sel = (lane_s == s).astype(jnp.int32)  # state one-hot per lane
        v = jnp.sum(nxt * sel, axis=1, keepdims=True)  # [TILE, 1]
        return _SRL(v, one), rep | (v & one)

    if stride == 2:
        n_steps = T // 2

        def body(i, carry):
            s, rep = step(2 * i, *carry)
            return step(2 * i + 1, s, rep)

    else:
        n_steps = T

        def body(i, carry):
            return step(i, *carry)

    init = (
        jnp.full((tile, 1), start_ref[0, 0], jnp.int32),
        jnp.full((tile, 1), start_ref[0, 1], jnp.int32),
    )
    s, rep = jax.lax.fori_loop(0, n_steps, body, init)
    if stride == 2 and T % 2:
        s, rep = step(T - 1, s, rep)
    out_ref[:] = rep


def multidfa_reported_pallas(
    plan: DfaKernelPlan,
    lines_tb: jax.Array,
    stride: int = 2,
    interpret: bool | None = None,
    tile_b: int | None = None,
    budget: int | None = None,
) -> jax.Array:
    """Run every union group's reported-flag scan in one Pallas call.

    ``lines_tb``: uint8 [T, B]; returns int32 [B, G] 0/1 reported flags
    in group order, bit-equal to finishing the scan tier's pair_stepper
    carry. ``stride`` 2 mirrors the fused scan's byte-pair steps; 1 is
    the single-stride variant (identical results, A/B'd by
    tools/probe_kernels.py)."""
    assert stride in (1, 2)
    T, B = lines_tb.shape
    if interpret is None:
        # Mosaic needs real TPU hardware; everywhere else (CPU test
        # meshes) the interpreter executes the same kernel semantics
        interpret = jax.default_backend() != "tpu"
    tile = dfa_tile(plan, B, T, budget=budget) if tile_b is None else tile_b
    assert tile is not None, f"no usable tile for batch rows {B}"
    G, s_pad, nc_pad = plan.n_groups, plan.s_pad, plan.nc_pad
    kernel = functools.partial(_kernel, T=T, stride=stride)
    return pl.pallas_call(
        kernel,
        grid=(G, B // tile),
        in_specs=[
            pl.BlockSpec(
                (T, tile), lambda g, i: (0, i), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, 256), lambda g, i: (g, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (nc_pad, s_pad), lambda g, i: (0, g), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (nc_pad, s_pad), lambda g, i: (0, g), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec((1, 2), lambda g, i: (g, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (tile, 1), lambda g, i: (i, g), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((B, G), jnp.int32),
        interpret=interpret,
    )(
        lines_tb.astype(jnp.int32),
        jnp.asarray(plan.cmap),
        jnp.asarray(plan.p0),
        jnp.asarray(plan.p1),
        jnp.asarray(plan.starts),
    )
