"""Fused match + integer-factor extraction: ONE device program per batch.

TPU has no native float64 — XLA emulates it at a large cost, and the
seven-factor formula needs f64 for ≤1e-6 parity with the JVM's double
arithmetic (SURVEY.md §7 hard part 2). The resolution here is that every
scoring factor is a closed-form f64 function of a handful of *integers*:

==============  ======================================================
factor          integer components (exact)
==============  ======================================================
chronological   global line index, total line count
proximity       per-secondary distance to the nearest hit (int lines)
temporal        per-sequence matched flag (bool)
context         window counts: error / shadowed-warn / stack /
                exception lines + window total
frequency       in-batch prior match count per slot (recovered on host
                from the record stream itself) + persisted base count
==============  ======================================================

So the device program (this module) runs the DFA bank and extracts ONLY
those integers, compacted to a K-capped record buffer in discovery order
(line-major then pattern order — AnalysisService.java:89-113), and the
host finalizer (runtime/finalize.py) evaluates the formula in true f64 on
the M ≪ B·P matched records. No f64 ever touches the device, transfers
shrink from O(B·P) score matrices to O(K) integer records, and parity is
*better* than device-side f64 because the host math is the same IEEE
doubles the JVM uses (ScoringService.java:102-109).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.golden.engine import SEQUENCE_NEAR_WINDOW
from log_parser_tpu.ops.match import DfaBank
from log_parser_tpu.patterns.bank import (
    CTX_ERROR,
    CTX_EXCEPTION,
    CTX_STACK,
    CTX_WARN,
    PatternBank,
)

# "no hit" distance sentinel: larger than any window yet far from int32
# overflow when compared/subtracted
NO_HIT = np.int32(1 << 30)

# This jaxlib (0.4.x) ships no batching rule for optimization_barrier,
# which blocks vmap-ing _step over a leading request axis (the
# cross-request micro-batcher, runtime/batcher.py). The barrier is
# identity-shaped — a fusion hint with no data semantics — so the rule is
# the trivial one: bind the primitive on the batched operands and keep the
# batch dims. Registered defensively: if jax internals move, the batched
# program fails loudly at trace time and the serve path simply runs
# unbatched.
try:  # pragma: no cover - exercised implicitly by every vmapped _step
    from jax._src.lax.lax import optimization_barrier_p as _barrier_p
    from jax.interpreters import batching as _batching

    if _barrier_p not in _batching.primitive_batchers:

        def _barrier_batcher(args, dims, **params):
            return _barrier_p.bind(*args, **params), dims

        _batching.primitive_batchers[_barrier_p] = _barrier_batcher
except Exception:  # noqa: BLE001 - jax internals moved; vmap will raise
    pass

# K-capped record buffers: ladder of compiled bucket sizes; a batch whose
# match count overflows the chosen bucket re-runs at the next rung
K_LADDER = (4096, 32768, 262144, 2097152)


@dataclasses.dataclass
class MatchRecords:
    """Device outputs for one batch: integer factor components per match,
    in discovery order. Rows ≥ n_matches are garbage (unfilled buffer)."""

    n_matches: int
    line: np.ndarray  # int32 [K] 0-based global line index
    pattern: np.ndarray  # int32 [K] pattern index into bank.patterns
    sec_dist: np.ndarray  # int32 [K, S_max] distance per pattern secondary (NO_HIT pad)
    seq_ok: np.ndarray  # bool [K, Q_max] per pattern sequence matched
    ctx_counts: np.ndarray  # int32 [K, 5] err, warn-shadowed, stack, exc, total


class FusedStaticTables:
    """Per-bank static structure shared by the single-device and sharded
    fused programs: per-pattern padded index tables mapping each match
    record to its pattern's secondary entries / sequences / context shape."""

    def __init__(self, bank: PatternBank, config: ScoringConfig):
        self.bank = bank
        self.config = config

        # ---- secondaries: flat entry tables + per-pattern padded index ----
        self.sec_cols = np.asarray([e.column for e in bank.secondaries], dtype=np.int32)
        self.sec_weight = np.asarray([e.weight for e in bank.secondaries], dtype=np.float64)
        self.sec_window = np.asarray(
            [min(config.proximity_max_window, e.window) for e in bank.secondaries],
            dtype=np.int64,
        )
        per_pat: list[list[int]] = [[] for _ in range(bank.n_patterns)]
        for entry_idx, e in enumerate(bank.secondaries):
            per_pat[e.pattern_idx].append(entry_idx)
        self.s_max = max((len(v) for v in per_pat), default=0)
        self.pat_sec = np.full((max(1, bank.n_patterns), max(1, self.s_max)), -1, np.int32)
        for p, entries in enumerate(per_pat):
            self.pat_sec[p, : len(entries)] = entries

        # ---- sequences ----------------------------------------------------
        self.seq_bonus = np.asarray([s.bonus for s in bank.sequences], dtype=np.float64)
        self.seq_event_cols = sorted({c for s in bank.sequences for c in s.event_columns})
        self.seq_col_pos = {c: i for i, c in enumerate(self.seq_event_cols)}
        per_pat_q: list[list[int]] = [[] for _ in range(bank.n_patterns)]
        for q_idx, s in enumerate(bank.sequences):
            per_pat_q[s.pattern_idx].append(q_idx)
        self.q_max = max((len(v) for v in per_pat_q), default=0)
        self.pat_seq = np.full((max(1, bank.n_patterns), max(1, self.q_max)), -1, np.int32)
        for p, qs in enumerate(per_pat_q):
            self.pat_seq[p, : len(qs)] = qs

        # ---- context: unique (has_rules, before, after) shapes -------------
        shapes: list[tuple[bool, int, int]] = []
        shape_idx: dict[tuple[bool, int, int], int] = {}
        pattern_shape = []
        for p_idx in range(bank.n_patterns):
            key = (
                bool(bank.has_context_rules[p_idx]),
                int(bank.ctx_before[p_idx]),
                int(bank.ctx_after[p_idx]),
            )
            if key not in shape_idx:
                shape_idx[key] = len(shapes)
                shapes.append(key)
            pattern_shape.append(shape_idx[key])
        self.ctx_shapes = shapes
        self.pat_ctx_shape = np.asarray(pattern_shape, dtype=np.int32)


def _prev_next_dist(hits: jax.Array, row_idx: jax.Array) -> jax.Array:
    """[B, S] bool hit columns -> [B, S] int32 distance to the nearest hit
    on either side, own row excluded (strict prev/next — the primary line
    is skipped at ScoringService.java:326-328). NO_HIT where none."""
    col_idx = row_idx[:, None]
    prev_incl = jax.lax.cummax(jnp.where(hits, col_idx, -1), axis=0)
    prev = jnp.concatenate(
        [jnp.full((1, hits.shape[1]), -1, prev_incl.dtype), prev_incl[:-1]], axis=0
    )
    nxt_incl = jnp.flip(
        jax.lax.cummin(jnp.flip(jnp.where(hits, col_idx, NO_HIT), axis=0), axis=0),
        axis=0,
    )
    nxt = jnp.concatenate(
        [nxt_incl[1:], jnp.full((1, hits.shape[1]), NO_HIT, nxt_incl.dtype)], axis=0
    )
    d_prev = jnp.where(prev >= 0, col_idx - prev, NO_HIT)
    d_next = jnp.where(nxt < NO_HIT, nxt - col_idx, NO_HIT)
    return jnp.minimum(d_prev, d_next)


def _prefix(x: jax.Array) -> jax.Array:
    """[B, ...] -> [B+1, ...] exclusive prefix sums (window sum = 2 gathers)."""
    return jnp.concatenate(
        [jnp.zeros((1,) + x.shape[1:], x.dtype), jnp.cumsum(x, axis=0)]
    )


def sequence_flags_from_events(
    sequences, t: "FusedStaticTables", em: jax.Array, idx: jax.Array, n_lines
) -> jax.Array:
    """[len(idx), n_sequences] bool — sequence fully matched with the primary
    at each ``idx`` row of the (global) event-match matrix ``em`` [B, E]
    (ScoringService.java:230-305): last event within ±5 of the primary via a
    prefix-count range-any (:272-286), earlier events chained strictly
    backwards via inclusive prefix-cummax of last-hit line; the chain
    restarts at the *primary* line, not the near-window hit (:250).

    Shared by the single-device program (em local == global) and the
    sharded program (em all_gathered, idx = the shard's global rows)."""
    B = em.shape[0]
    eidx = jnp.arange(B, dtype=jnp.int32)[:, None]
    prev_incl = jax.lax.cummax(jnp.where(em, eidx, -1), axis=0)  # [B, E]
    prefix_counts = _prefix(em.astype(jnp.int32))  # [B+1, E]

    w = SEQUENCE_NEAR_WINDOW
    outs = []
    for seq in sequences:
        if not seq.event_columns:
            outs.append(jnp.zeros(idx.shape, dtype=bool))
            continue
        last_e = t.seq_col_pos[seq.event_columns[-1]]
        lo = jnp.clip(idx - w, 0, B)
        hi = jnp.clip(jnp.minimum(idx + w + 1, n_lines), 0, B).astype(jnp.int32)
        ok = (prefix_counts[hi, last_e] - prefix_counts[lo, last_e]) > 0
        cur = idx
        for col in reversed(seq.event_columns[:-1]):
            e = t.seq_col_pos[col]
            g = jnp.where(cur >= 1, prev_incl[jnp.clip(cur - 1, 0, B - 1), e], -1)
            ok = ok & (g >= 0)
            cur = jnp.clip(g, 0, B - 1)
        outs.append(ok)
    return jnp.stack(outs, axis=1)


def compact_records(
    K: int,
    pm: jax.Array,
    t: "FusedStaticTables",
    emit_line: jax.Array,
    gather_line: jax.Array,
    sec_dist: jax.Array,
    seq_ok: jax.Array,
    ctx_counts: jax.Array,
):
    """K-capped record compaction in discovery order (line-major then
    pattern order — AnalysisService.java:89-113), shared by the
    single-device and sharded programs.

    ``emit_line``: per-row line index written into the records (global);
    ``gather_line``: per-row index into the dense factor tables (local).
    rank = exclusive match count in flat order == the record's output slot;
    slot K is the trash row for overflow (caller re-runs at a bigger K).

    Two-level: matching ROWS are compacted first (one [B]-sized pass),
    then (row, pattern) pairs rank/scatter over only ``K_rows x P``
    elements — the naive flat [B*P] cumsum + three scatters are
    per-element scalar-unit work on TPU (like the match-cube gathers,
    PERF.md §1) and dominated the extraction phase at 19M elements on
    config-2 shapes. ``K_rows = min(B, K)`` loses nothing: every
    compacted-out row holds >= 1 match, so row overflow implies
    ``n_matches > K`` — and ``n_matches`` is summed over the FULL cube,
    so the caller's ladder re-run triggers exactly as before."""
    from log_parser_tpu.ops.prefilter import _compact

    B, P = pm.shape
    n_matches = jnp.sum(pm.astype(jnp.int32))

    K_rows = min(B, K)
    _n_rows, rows, rows_valid = _compact(pm.any(axis=1), K_rows)
    sub_pm = pm[rows] & rows_valid[:, None]  # [K_rows, P]

    sub32 = sub_pm.astype(jnp.int32)
    flat = sub32.reshape(-1)
    rank = jnp.cumsum(flat) - flat
    out_pos = jnp.where(flat > 0, jnp.minimum(rank, K), K)

    emit_bp = jnp.broadcast_to(emit_line[rows][:, None], (K_rows, P)).reshape(-1)
    gather_bp = jnp.broadcast_to(
        gather_line[rows][:, None], (K_rows, P)
    ).reshape(-1)
    pats_bp = jnp.broadcast_to(
        jnp.arange(P, dtype=jnp.int32)[None, :], (K_rows, P)
    ).reshape(-1)
    rec_line = jnp.zeros((K + 1,), jnp.int32).at[out_pos].set(emit_bp)[:K]
    rec_grow = jnp.zeros((K + 1,), jnp.int32).at[out_pos].set(gather_bp)[:K]
    rec_pat = jnp.zeros((K + 1,), jnp.int32).at[out_pos].set(pats_bp)[:K]

    sec_idx = jnp.asarray(t.pat_sec)[rec_pat]  # [K, S_max]
    rec_dist = jnp.where(
        sec_idx >= 0,
        sec_dist[rec_grow[:, None], jnp.maximum(sec_idx, 0)],
        NO_HIT,
    )
    q_idx = jnp.asarray(t.pat_seq)[rec_pat]  # [K, Q_max]
    rec_seq = jnp.where(
        q_idx >= 0, seq_ok[rec_grow[:, None], jnp.maximum(q_idx, 0)], False
    )
    rec_ctx = ctx_counts[rec_grow, jnp.asarray(t.pat_ctx_shape)[rec_pat]]  # [K, 5]

    return n_matches.astype(jnp.int32), rec_line, rec_pat, rec_dist, rec_seq, rec_ctx


def pack_records(n_matches, rec_line, rec_pat, rec_dist, rec_seq, rec_ctx):
    """Concatenate one batch's record buffers into a single flat int32
    array: [n, line(K), pattern(K), sec_dist(K*S), seq_ok(K*Q), ctx(K*5)].

    One array == ONE device-to-host copy at resolve time. Through the
    tunneled single-chip backend every transfer is a network round-trip,
    and the 6-array layout made each request pay ~6 RTTs — the dominant
    term of the measured 489ms p99 (bench_results/config5_direct_tpu)."""
    return jnp.concatenate(
        [
            n_matches.reshape(1),
            rec_line,
            rec_pat,
            rec_dist.reshape(-1),
            rec_seq.astype(jnp.int32).reshape(-1),
            rec_ctx.reshape(-1),
        ]
    )


def unpack_records(arr: np.ndarray, s_w: int, q_w: int) -> MatchRecords | None:
    """Host-side inverse of :func:`pack_records`; None signals K overflow."""
    width = 2 + s_w + q_w + 5
    K = (arr.shape[0] - 1) // width
    n_matches = int(arr[0])
    if n_matches > K:
        return None
    off = 1
    line = arr[off : off + K]
    off += K
    pattern = arr[off : off + K]
    off += K
    sec_dist = arr[off : off + K * s_w].reshape(K, s_w)
    off += K * s_w
    seq_ok = arr[off : off + K * q_w].reshape(K, q_w).astype(bool)
    off += K * q_w
    ctx_counts = arr[off : off + K * 5].reshape(K, 5)
    return MatchRecords(
        n_matches=n_matches,
        line=line,
        pattern=pattern,
        sec_dist=sec_dist,
        seq_ok=seq_ok,
        ctx_counts=ctx_counts,
    )


class FusedMatchScore:
    """Single-device fused program: bytes → DFA cube → integer match records.

    Compiled once per (batch rows, K bucket, overrides?) combination; the
    engine picks the K bucket adaptively and re-runs on overflow.
    """

    def __init__(self, bank: PatternBank, config: ScoringConfig, matchers):
        self.bank = bank
        self.config = config
        self.matchers = matchers  # MatcherBanks: tiered Shift-Or + DFA cube
        self.t = FusedStaticTables(bank, config)
        # K is a static arg: each bucket size is its own cached executable
        self._jit_ov = jax.jit(
            lambda k, lines, lens, n, om, ov: self._step(k, lines, lens, n, (om, ov)),
            static_argnums=(0,),
        )
        self._jit_plain = jax.jit(
            lambda k, lines, lens, n: self._step(k, lines, lens, n, None),
            static_argnums=(0,),
        )
        # cube-only programs (the line-cache residual path): no extraction,
        # just the post-override bit matrix — extraction happens on the host
        # from cached + fresh rows together (runtime/linecache.py)
        self._jit_cube_ov = jax.jit(
            lambda lines, lens, n, om, ov: self._cube_step(lines, lens, n, (om, ov))
        )
        self._jit_cube_plain = jax.jit(
            lambda lines, lens, n: self._cube_step(lines, lens, n, None)
        )

    # ------------------------------------------------------------- host entry

    def dispatch(
        self,
        k: int,
        lines_u8: np.ndarray,
        lengths: np.ndarray,
        n_lines: int,
        override_mask: np.ndarray | None = None,
        override_val: np.ndarray | None = None,
    ):
        """Launch the fused program asynchronously at record capacity ``k``
        and return the un-synchronized device outputs. Callers fan out
        several dispatches (e.g. one pattern block per device) before the
        first blocking read.

        The batch uploads in its contiguous [B, T] layout and transposes
        ON DEVICE (a free layout op inside the compiled program): a
        host-side ``.T`` copy before upload measured 82 ms vs 9 ms for
        the contiguous config-2 batch — ~10% of a serial request."""
        lines_bt = jnp.asarray(lines_u8)
        lens = jnp.asarray(lengths)
        n = jnp.asarray(n_lines, dtype=jnp.int32)
        if override_mask is not None:
            return self._jit_ov(
                k, lines_bt, lens, n,
                jnp.asarray(override_mask), jnp.asarray(override_val),
            )
        return self._jit_plain(k, lines_bt, lens, n)

    def k_ladder(self, lines_u8: np.ndarray, k_hint: int = 0):
        """The record-capacity buckets to try, smallest viable first."""
        cap = lines_u8.shape[0] * max(1, self.bank.n_patterns)
        start = 0
        while start < len(K_LADDER) - 1 and K_LADDER[start] < k_hint:
            start += 1
        return [min(k, cap) for k in (*K_LADDER[start:], cap)], cap

    def resolve(self, out) -> MatchRecords | None:
        """Synchronize one dispatch — a single packed-array transfer —
        and unpack; None signals K overflow (re-dispatch at the next
        ladder rung)."""
        return unpack_records(
            np.asarray(out), max(1, self.t.s_max), max(1, self.t.q_max)
        )

    def run(
        self,
        lines_u8: np.ndarray,
        lengths: np.ndarray,
        n_lines: int,
        override_mask: np.ndarray | None = None,
        override_val: np.ndarray | None = None,
        k_hint: int = 0,
    ) -> MatchRecords:
        """Executes the fused program, growing the record buffer until the
        batch's matches fit. ``k_hint``: expected match count (e.g. the
        previous request's), used to pick the starting bucket."""
        ladder, cap = self.k_ladder(lines_u8, k_hint)
        for k in ladder:
            out = self.dispatch(k, lines_u8, lengths, n_lines, override_mask, override_val)
            recs = self.resolve(out)
            if recs is not None or k >= cap:
                if recs is None:  # cap rung can never truly overflow
                    raise AssertionError("unreachable: K ladder capped at B*P")
                return recs
        raise AssertionError("unreachable: K ladder capped at B*P")

    def host_carry(self):
        """Carried-scan-state entry point for streaming ingestion: a
        :class:`~log_parser_tpu.ops.match.CubeHostCarry` whose ``feed``/
        ``snapshot_bits`` advance this program's matcher tiers over one
        growing line and return the cube row the device would produce —
        union-DFA states, dense-DFA states, and Shift-Or bit registers
        all resume across chunk boundaries instead of rescanning.  None
        when a populated tier is not host-resumable (bitglush /
        prefilter); callers then rescan the buffered tail per frame."""
        return self.matchers.host_carry()

    def cube_rows(
        self,
        lines_u8: np.ndarray,
        lengths: np.ndarray,
        n_lines: int,
        override_mask: np.ndarray | None = None,
        override_val: np.ndarray | None = None,
    ) -> np.ndarray:
        """Post-override match-bit matrix [B, n_columns] for a (residual)
        batch — the cacheable unit of the routing tier. Everything the
        fused extraction derives is a pure function of these bits plus the
        request's line count, so the line cache memoizes rows of THIS
        matrix and replays extraction on the host."""
        lines_bt = jnp.asarray(lines_u8)
        lens = jnp.asarray(lengths)
        n = jnp.asarray(n_lines, dtype=jnp.int32)
        if override_mask is not None:
            out = self._jit_cube_ov(
                lines_bt, lens, n,
                jnp.asarray(override_mask), jnp.asarray(override_val),
            )
        else:
            out = self._jit_cube_plain(lines_bt, lens, n)
        return np.asarray(out)

    # ---------------------------------------------------------- device program

    def _cube_step(self, lines_bt, lengths, n_lines, overrides):
        """The shared front half of :meth:`_step`: tiered match cube,
        override splice, padding-row mask. Returns bool [B, n_columns]."""
        lines_tb = lines_bt.T  # device-side layout change (see dispatch)
        B = lengths.shape[0]
        row_idx = jnp.arange(B, dtype=jnp.int32)
        valid = row_idx < n_lines
        cube = jax.lax.optimization_barrier(
            self.matchers.cube(lines_tb, lengths)
        )
        if overrides is not None:
            om, ov = overrides
            cube = jnp.where(om, ov, cube)
        return cube & valid[:, None]

    def _step(self, K, lines_bt, lengths, n_lines, overrides):
        bank, t = self.bank, self.t
        B = lengths.shape[0]
        P = bank.n_patterns
        row_idx = jnp.arange(B, dtype=jnp.int32)

        # ---- match cube (tiered: Shift-Or + DFA banks) --------------------
        # the barrier (inside _cube_step) stops XLA from fusing extraction
        # work back into the scan loops: the compiled step alone measured
        # 0.417 → 0.374 s on v5e config-2 shapes (direct _jit_plain timing;
        # the end-to-end headline moves less — tunnel-sync noise is ±5% at
        # that level). Padding rows contribute nothing: empty-matching
        # regexes (^$, \s*) would otherwise produce phantom hits on
        # zero-length padding.
        cube = self._cube_step(lines_bt, lengths, n_lines, overrides)

        if P == 0:
            z32 = jnp.zeros((K,), jnp.int32)
            return pack_records(
                jnp.int32(0),
                z32,
                z32,
                jnp.full((K, max(1, t.s_max)), NO_HIT, jnp.int32),
                jnp.zeros((K, max(1, t.q_max)), bool),
                jnp.zeros((K, 5), jnp.int32),
            )

        pm = cube[:, jnp.asarray(bank.primary_columns)]  # [B, P]

        # ---- dense integer factor components ------------------------------
        sec_dist = self._secondary_distances(cube, row_idx)  # [B, Smax-safe]
        em = (
            cube[:, jnp.asarray(t.seq_event_cols, dtype=np.int32)]
            if bank.sequences
            else jnp.zeros((B, 1), dtype=bool)
        )
        seq_ok = (
            sequence_flags_from_events(bank.sequences, t, em, row_idx, n_lines)
            if bank.sequences
            else jnp.zeros((B, 1), dtype=bool)
        )
        ctx_counts = self._context_counts(cube, row_idx, B, n_lines)  # [B, U, 5]

        # single-device: emit and gather coordinates coincide
        return pack_records(
            *compact_records(K, pm, t, row_idx, row_idx, sec_dist, seq_ok, ctx_counts)
        )

    # ------------------------------------------------------------ dense tables

    def _secondary_distances(self, cube, row_idx):
        """[B, n_sec_entries] int32 nearest-hit distances (NO_HIT if none).
        Exact for any window: the nearest hit overall is the nearest hit
        within the window (ScoringService.java:315-347)."""
        t = self.t
        if len(t.sec_cols) == 0:
            return jnp.full((cube.shape[0], 1), NO_HIT, jnp.int32)
        hits = cube[:, jnp.asarray(t.sec_cols)]  # [B, S_entries]
        return _prev_next_dist(hits, row_idx)

    def _context_counts(self, cube, row_idx, B, n_lines):
        """[B, U, 5] int32 — per unique context shape: error lines,
        shadowed-warn lines (else-if at ContextAnalysisService.java:64-70),
        stack lines, exception lines, window total."""
        t = self.t
        err = cube[:, CTX_ERROR]
        warn = cube[:, CTX_WARN] & ~err
        stack = cube[:, CTX_STACK]
        exc = cube[:, CTX_EXCEPTION]
        flags = jnp.stack(
            [err, warn, stack, exc], axis=1
        ).astype(jnp.int32)  # [B, 4]
        ps = _prefix(flags)  # [B+1, 4]

        per_shape = []
        for has_rules, before, after in t.ctx_shapes:
            if not has_rules:
                # context = the matched line only (AnalysisService.java:135-139)
                counts = flags
                total = jnp.ones((B,), jnp.int32)
            else:
                lo = jnp.clip(row_idx - before, 0, B)
                hi = jnp.clip(jnp.minimum(row_idx + 1 + after, n_lines), 0, B).astype(
                    jnp.int32
                )
                counts = ps[hi] - ps[lo]  # [B, 4]
                total = hi - lo
            per_shape.append(jnp.concatenate([counts, total[:, None]], axis=1))
        return jnp.stack(per_shape, axis=1)  # [B, U, 5]


class FusedBatchMatchScore:
    """Cross-request batched fused program: ``vmap`` of
    :meth:`FusedMatchScore._step` over a leading request axis R.

    One dispatch serves R coalesced requests (runtime/batcher.py): inputs
    are ``lines_u8 [R, B, T]``, ``lengths [R, B]``, ``n_lines [R]`` and
    optionally stacked override cubes ``[R, B, C]``. Each vmapped instance
    sees ONLY its own rows and its own ``n_lines`` valid-mask, so match
    bits, distances, sequence chains, and context windows can never bleed
    across requests — and because the device math is integer-only, vmap
    cannot perturb results: per-request records are bit-identical to the
    unbatched program's (tests/test_batcher.py asserts equality, which
    subsumes the ≤1e-6 score-parity requirement).

    K (the record capacity) is a shared static arg: one rung serves the
    whole batch, sized by the engine's k_hint, and if ANY request
    overflows, the whole batch re-runs at the next rung (per-request caps
    are equal within a bucket — same B, same pattern count).
    """

    def __init__(self, fused: FusedMatchScore):
        self.fused = fused
        self._jit_plain = jax.jit(
            lambda k, lines, lens, n: jax.vmap(
                lambda L, le, nn: fused._step(k, L, le, nn, None)
            )(lines, lens, n),
            static_argnums=(0,),
        )
        self._jit_ov = jax.jit(
            lambda k, lines, lens, n, om, ov: jax.vmap(
                lambda L, le, nn, m, v: fused._step(k, L, le, nn, (m, v))
            )(lines, lens, n, om, ov),
            static_argnums=(0,),
        )

    def run(
        self,
        lines_u8: np.ndarray,  # [R, B, T] uint8
        lengths: np.ndarray,  # [R, B] int
        n_lines: np.ndarray,  # [R] int
        override_mask: np.ndarray | None = None,  # [R, B, C] bool
        override_val: np.ndarray | None = None,
        k_hint: int = 0,
    ) -> list[MatchRecords]:
        """One batched dispatch per K rung; returns per-request records in
        request order. Overflow of any slot climbs the shared ladder."""
        R = lines_u8.shape[0]
        ladder, cap = self.fused.k_ladder(lines_u8[0], k_hint)
        lines = jnp.asarray(lines_u8)
        lens = jnp.asarray(lengths)
        n = jnp.asarray(n_lines, dtype=jnp.int32)
        for k in ladder:
            if override_mask is not None:
                out = self._jit_ov(
                    k, lines, lens, n,
                    jnp.asarray(override_mask), jnp.asarray(override_val),
                )
            else:
                out = self._jit_plain(k, lines, lens, n)
            arr = np.asarray(out)  # [R, packed] — ONE device→host transfer
            recs = [self.fused.resolve(arr[i]) for i in range(R)]
            if all(r is not None for r in recs):
                return recs
            if k >= cap:
                raise AssertionError("unreachable: K ladder capped at B*P")
        raise AssertionError("unreachable: K ladder capped at B*P")
