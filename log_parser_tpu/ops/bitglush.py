"""Gather-free bit-parallel regex engine (extended Shift-And).

Executes :class:`~log_parser_tpu.patterns.regex.bitprog.BitProgram`
columns — classes, ``+``/``*``/``?`` repeats, ``.*`` gaps, alternations,
``^``/``$``/``\\b``/``\\B`` — with NO per-element random gathers: per byte
the whole bank costs one contiguous ``[256, W]`` mask-row take plus
elementwise vector ops on ``[B, W]`` words. This replaces the union
multi-DFA tier's ``[B, G]`` transition gather (scalar-unit bound at ~9ns
per element, PERF.md §1) for every column whose regex fits the
bit-parallel fragment, turning the match cube's dominant cost into pure
VPU work.

Execution model (Glushkov positions, Shift-And active-high): bit ``g`` of
the state word means "some containment attempt has consumed exactly the
items up to and including position ``g``, ending at the current byte".
Per consumed byte:

1. candidates ``C`` = state shifted one position (cross-word carry; entry
   into ``^``-anchored start positions blocked) | start positions (find()
   restart at every byte — AnalysisService.java:93-95's substring
   semantics) | ``^`` starts at t=0 only;
2. ε-closure: a candidate at a skippable (``*``/``?``) position also
   makes the next position a candidate — unrolled ``max_skip_run`` times;
3. gate by the per-position assertion mask selected from the previous /
   current byte word-ness (``\\b``/``\\B``), AND with the byte's class
   mask row; OR with the self-loop survivors (``+``/``*`` positions whose
   class admits the byte);
4. accept: plain finals accumulate into ``hits``; ``$`` finals only at
   each row's last byte; trailing-``\\b`` finals when the NEXT byte
   breaks word-ness (checked one step later from the pre-update state,
   and at end-of-line against the final byte's word-ness).

Alternatives are first-fit word-packed (like Shift-Or): each
alternative's allocation (its positions, plus one sink bit in sink
mode) lives inside ONE 32-bit word whenever it fits, so the
one-position shift needs NO cross-word carry and the whole carry op
group (a concatenate per shift — a fusion breaker that measured 2.5x
the chainless stepper on v5e, tools/probe_chainless.py) disappears
from chain-free banks. Allocations over 32 bits take word-aligned
runs of whole words and turn the bank-wide carry back on
(``has_chains``) — ops/match.py keeps such alternatives out of the
builtin-library bank by truncating primary-only columns (necessity-
preserving, host-verified at assembly) and routing long literal
columns to Shift-Or's chain path.

Stray cross-allocation shifts are harmless by construction: within an
allocation the shift is the intended advance; the bit leaking OUT of an
allocation lands on the NEXT allocation's first bit — a start position
(re-injected every byte anyway for ``find()`` restart semantics, or
explicitly blocked when ``^``-anchored) — or on an unused fragmentation
bit, whose ``bmask`` row is all-zero so the byte-class AND kills it the
same step. A leaked bit therefore never travels more than one position.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from log_parser_tpu.patterns.regex.bitprog import BitProgram


def _is_word(b32: jax.Array) -> jax.Array:
    """Elementwise [0-9A-Za-z_] test — no table lookup needed."""
    return (
        ((b32 >= 48) & (b32 <= 57))
        | ((b32 >= 65) & (b32 <= 90))
        | ((b32 >= 97) & (b32 <= 122))
        | (b32 == 95)
    )


class BitGlushBank:
    """Packed bit programs for a set of (column, BitProgram) entries."""

    @staticmethod
    def sink_eligible(programs) -> bool:
        """Sticky *sink* positions (see ``__init__``) drop the per-byte
        hit accumulation — and its ``[B, W]`` carry — from the stepper.
        A trailing ``\\b``/``\\B`` final would need a sink whose admission
        depends on the FINAL item's word-ness too, so those banks keep
        the per-byte hit path (in practice ``expand_asserts`` removes
        them before packing)."""
        return not any(
            a.post_assert in ("b", "B")
            for p in programs
            for a in p.alternatives
        )

    @staticmethod
    def _plan(allocs, budget: int | None = None):
        """First-fit packing plan over per-alternative allocation sizes
        (:func:`~log_parser_tpu.ops.shiftor.first_fit_plan` — shared
        with the Shift-Or tier; ``count_packed_words`` and ``__init__``
        both route through here so they cannot disagree)."""
        from log_parser_tpu.ops.shiftor import first_fit_plan

        return first_fit_plan(allocs, budget=budget)

    @staticmethod
    def alt_alloc(alt, sink: int) -> int:
        """Bits ONE alternative allocates under bank-wide sink mode
        ``sink`` (0/1): positions, plus the sink, plus one dead *guard*
        bit BEFORE every ``^``-anchored alternative.  THE per-alternative
        sizing formula — the tier admission gate in ops/match.py prices
        candidates through this too, so a new guard-style bit cannot
        silently diverge the gate from the constructor."""
        return alt.n_positions + sink + (1 if alt.caret else 0)

    @classmethod
    def _alt_allocs(cls, programs) -> list[int]:
        """Per-alternative allocation sizes: positions, plus one sink on
        sink-eligible banks, plus one dead *guard* bit BEFORE every
        ``^``-anchored alternative. The guard bit is never admitted by
        any byte row, so nothing can shift or skip-propagate into the
        caret start after t=0 — which lets the steppers drop their two
        per-byte ``& not_caret`` ops entirely."""
        sink = 1 if cls.sink_eligible(programs) else 0
        return [
            cls.alt_alloc(a, sink)
            for p in programs
            for a in p.alternatives
        ]

    @classmethod
    def count_packed_words(cls, programs, budget: int | None = None) -> int:
        """Exact packed word count the constructor would produce for
        ``programs`` — same first-fit plan, same sink/caret-guard
        allocations.  The tier admission gate in ops/match.py prices
        candidates through this (ADVICE r4: a positions/32 floor ignored
        guard bits and fragmentation, so a constructed bank could exceed
        the budget and cross a 128-lane tile).  ``budget`` bails the plan
        early once the count exceeds it."""
        return cls._plan(cls._alt_allocs(programs), budget=budget)[1]

    def __init__(self, column_programs: list[tuple[int, BitProgram]]):
        self.columns = [c for c, _ in column_programs]
        programs = [p for _, p in column_programs]
        # Sink mode: each alternative gets one extra position after its
        # last item. A sink admits every byte (``$``-final sinks admit
        # ONLY the padding byte 0 — i.e. they fire exactly at end of
        # line) and self-loops, so "some final position was ever alive"
        # becomes readable from the FINAL state: arrival rides the
        # existing shift/closure machinery (the trailing skippable
        # cascade that feeds multiple finals propagates into the sink the
        # same way), persistence rides ``s_static``, and the stepper
        # drops both per-byte hit ORs and the whole ``hits`` carry.
        self.use_sinks = self.sink_eligible(programs)
        allocs = self._alt_allocs(programs)
        alt_starts, self.n_words = self._plan(allocs)
        W = self.n_words
        self.n_positions = sum(allocs)
        # any word-straddling allocation turns the bank-wide shift carry
        # on; chain-free banks shift with a bare ``<< 1``
        self.has_chains = any(a > 32 for a in allocs)
        self.max_skip_run = max(
            (p.max_skip_run for _, p in column_programs), default=0
        )

        bmask = np.zeros((256, W), dtype=np.uint32)
        s_static = np.zeros(W, dtype=np.uint32)
        k_skip = np.zeros(W, dtype=np.uint32)
        start = np.zeros(W, dtype=np.uint32)
        caret_start = np.zeros(W, dtype=np.uint32)
        # allow4[pw*2+cw]: positions whose pre-assertion passes
        allow4 = np.zeros((4, W), dtype=np.uint32)
        f_plain = np.zeros(W, dtype=np.uint32)
        f_dollar = np.zeros(W, dtype=np.uint32)
        f_tb = np.zeros(W, dtype=np.uint32)
        f_tB = np.zeros(W, dtype=np.uint32)

        fin_word: list[int] = []
        fin_bit: list[int] = []
        fin_slot: list[int] = []
        snk_word: list[int] = []
        snk_bit: list[int] = []
        snk_slot: list[int] = []

        def setbit(arr, g):
            arr[g // 32] |= np.uint32(1) << np.uint32(g % 32)

        alt_iter = iter(alt_starts)
        for slot, (_col, prog) in enumerate(column_programs):
            for alt in prog.alternatives:
                # the caret guard bit (dead, leak-absorbing) is the
                # allocation's first bit; items start right after it
                base = g = next(alt_iter) + (1 if alt.caret else 0)
                for j, item in enumerate(alt.items):
                    for byte in item.byteset:
                        # NUL never reaches the device scan as content
                        # (NUL-bearing lines are needs_host — encode) so
                        # byte 0 stays padding-only: its bmask row is
                        # empty and the stepper's pad0_transparent fast
                        # path holds for every bank
                        if byte != 0:
                            setbit(bmask[byte], g)
                    if item.self_loop:
                        setbit(s_static, g)
                    if item.skippable:
                        setbit(k_skip, g)
                    if j == 0:
                        setbit(caret_start if alt.caret else start, g)
                    for combo in range(4):
                        pw, cw = combo >> 1, combo & 1
                        a = item.pre_assert
                        okc = (
                            a is None
                            or (a == "b" and pw != cw)
                            or (a == "B" and pw == cw)
                        )
                        if okc:
                            setbit(allow4[combo], g)
                    g += 1
                ftab = {None: f_plain, "$": f_dollar, "b": f_tb, "B": f_tB}[
                    alt.post_assert
                ]
                for j in alt.final_positions():
                    setbit(ftab, base + j)
                    fin_word.append((base + j) // 32)
                    fin_bit.append((base + j) % 32)
                    fin_slot.append(slot)
                if self.use_sinks:
                    # sink: one extra position after the alternative's
                    # last item. Arrival = shift/closure from any final;
                    # a plain final's sink admits every byte (padding
                    # included — completion at the last content byte
                    # still sweeps in), a ``$`` final's sink admits ONLY
                    # byte 0, so it fires exactly when the line ends.
                    # Self-loop makes it sticky; ``finish`` runs one
                    # virtual padding byte so full-width (length == T)
                    # lines sweep their finals in too.
                    bit = np.uint32(1) << np.uint32(g % 32)
                    if alt.post_assert == "$":
                        bmask[0, g // 32] |= bit
                    else:
                        bmask[:, g // 32] |= bit
                    setbit(s_static, g)
                    for combo in range(4):
                        setbit(allow4[combo], g)
                    snk_word.append(g // 32)
                    snk_bit.append(g % 32)
                    snk_slot.append(slot)
                    g += 1

        self.bmask = jnp.asarray(bmask)
        self.s_static = jnp.asarray(s_static)
        self.k_skip = jnp.asarray(k_skip)
        self.start = jnp.asarray(start)
        self.caret_start = jnp.asarray(caret_start)
        self.allow4 = jnp.asarray(allow4)
        self.f_plain = jnp.asarray(f_plain)
        self.f_dollar = jnp.asarray(f_dollar)
        self.f_tb = jnp.asarray(f_tb)
        self.f_tB = jnp.asarray(f_tB)
        self.has_tb = bool(f_tb.any() or f_tB.any())
        self.has_dollar = bool(f_dollar.any())
        # capability flags: the stepper drops whole op groups when no
        # program in the bank uses them. A bank mixing asserted and
        # assert-free programs pays the full path (a measured split into
        # two banks was slower — see the tier-assignment comment in
        # ops/match.py); a fully assert-free bank gets the light stepper
        self.has_caret = bool(caret_start.any())
        self.has_preassert = any(
            it.pre_assert is not None
            for _c, p in column_programs
            for a in p.alternatives
            for it in a.items
        )
        self.needs_wordness = self.has_preassert or self.has_tb
        self.fin_word = np.asarray(fin_word, dtype=np.int32)
        self.fin_bit = np.asarray(fin_bit, dtype=np.int32)
        self.fin_slot = np.asarray(fin_slot, dtype=np.int32)
        self.snk_word = np.asarray(snk_word, dtype=np.int32)
        self.snk_bit = np.asarray(snk_bit, dtype=np.int32)
        self.snk_slot = np.asarray(snk_slot, dtype=np.int32)

        # Assert-partition constants: the per-byte allow mask is the
        # TAKELESS combine ``where(pw != cw, allow_bc, allow_nb)`` —
        # replacing the allow4 row gather with one select between two
        # [W] constants. They ARE rows of allow4: combo pw*2+cw = 1 is a
        # boundary (no-assert ∪ \b positions), combo 0 is not (no-assert
        # ∪ \B); combos 2/1 and 3/0 are the same sets mirrored.
        # (Pair-composed and byte-class table variants of this stepper
        # were measured SLOWER and deleted — ops/shiftor.py docstring,
        # tools/probe_paircompose.py.)
        self.allow_bc = jnp.asarray(allow4[1])  # boundary present
        self.allow_nb = jnp.asarray(allow4[0])  # no boundary
        # fused start injection: one [W] select on the scalar ``pos == 0``
        # feeds a single broadcast OR instead of two (start, then a
        # caret-gated second OR)
        self.start_all = jnp.asarray(start | caret_start)
        # The ungated hit term is ``hits |= d & f_plain``, and after the
        # update ``d[fin] = bmask[byte][fin] & (...)`` — so a padding
        # byte (0) can only contribute a hit if some PLAIN-final
        # position's byteset admits NUL. When none does, the per-byte
        # ``pos < length`` gating of plain-final accumulation is a
        # provable no-op and the stepper drops it (gap/self-loop
        # positions may freely survive padding — they cannot hit).
        # ``$``/trailing-``\b`` finals keep their eol-equality gates,
        # which can never fire at a padding position. The builder above
        # strips byte 0 from every byteset (NUL-bearing lines are
        # needs_host — encode.py), so today this is True for every bank;
        # the flag still computes the sound condition and the gated
        # stepper path is retained as the correctness fallback should a
        # future bank ever admit the padding byte.
        self.pad0_transparent = not bool((bmask[0] & f_plain).any())

    # --------------------------------------------------------------- device

    def _shift1(self, d: jax.Array) -> jax.Array:
        """One-position shift. Chain-free banks (every allocation inside
        one word — the first-fit invariant) shift with a bare ``<< 1``;
        only banks holding a word-straddling allocation pay the carry.
        The carry stays UNCONDITIONAL across all word boundaries (no
        cont-mask): a carry landing outside a chained run hits the next
        allocation's start bit (re-injected / caret-blocked anyway) or an
        unused bit (killed by the ``bmask`` AND) — see module docstring."""
        sh = d << 1
        if self.has_chains and self.n_words > 1:
            carry = jnp.concatenate(
                [jnp.zeros_like(d[:, :1]), d[:, :-1] >> 31], axis=1
            )
            sh = sh | carry
        return sh

    def pair_stepper(self, B: int, lengths: jax.Array):
        """(init, step(carry, b1, b2, t), finish) — composable with the
        other banks into the single fused scan. Sink-mode banks (the
        default whenever no trailing ``\\b``/``\\B`` final exists) carry
        only (state [B, W] uint32, prev_wordness [B] bool) and read hits
        from sticky sink positions at the end; the rest carry (state,
        hits [B, W] uint32, prev_wordness) and accumulate per byte."""
        if self.use_sinks:
            return self._sink_pair_stepper(B, lengths)
        return self._hits_pair_stepper(B, lengths)

    def _sink_pair_stepper(self, B: int, lengths: jax.Array):
        """Sink-mode stepper: no hit terms, no ``hits`` carry, no
        end-of-line gating at all — ``$`` acceptance is the dollar
        sink's padding-byte admission, and plain finals sweep into
        always-admitting sinks. ``finish`` advances one virtual padding
        byte so lines that fill every scanned byte (length == T) sweep
        their last-byte finals in, then reads the sink bits."""
        W = self.n_words
        init = (
            jnp.zeros((B, W), jnp.uint32),
            jnp.zeros((B,), bool),
        )

        def one(d, pw, b, pos):
            b32 = b.astype(jnp.int32)
            c = self._shift1(d)
            # the guard bit before every ^-anchored alternative absorbs
            # shift/skip leaks (it is never admitted by any byte row), so
            # no ``& not_caret`` is needed anywhere — caret starts are
            # only ever injected by the pos==0 select below
            if self.has_caret:
                c = c | jnp.where(pos == 0, self.start_all, self.start)
            else:
                c = c | self.start
            for _ in range(self.max_skip_run):
                c = c | self._shift1(c & self.k_skip)
            brow = jnp.take(self.bmask, b32, axis=0)  # [B, W]
            if self.has_preassert:
                cw = _is_word(b32)
                bc = ((pw != cw))[:, None]
                allow = jnp.where(bc, self.allow_bc, self.allow_nb)
                d = brow & ((c & allow) | (d & self.s_static))
                # no end-of-line freeze: past the line end only sink
                # positions can stay alive (brow is empty elsewhere) and
                # sinks ignore the allow mask, so pw's padding word-ness
                # gates nothing that matters
                pw = cw
            else:
                d = brow & (c | (d & self.s_static))
            return d, pw

        def step(carry, b1, b2, t):
            d, pw = carry
            p0 = 2 * t
            d, pw = one(d, pw, b1, p0)
            d, pw = one(d, pw, b2, p0 + 1)
            return (d, pw)

        def finish(carry):
            d, pw = carry
            pad = jnp.zeros((B,), jnp.uint8)
            d, _ = one(d, pw, pad, jnp.int32(1))
            return self.columns_from_sinks(d)

        return init, step, finish

    def columns_from_sinks(self, d: jax.Array) -> jax.Array:
        """uint32 [N, W] final sink-mode state -> bool [N, n_columns]."""
        N = d.shape[0]
        alive = (
            jnp.take(d, jnp.asarray(self.snk_word), axis=1)
            >> jnp.asarray(self.snk_bit)[None, :]
        ) & 1  # [N, n_sinks]
        out = jnp.zeros((N, max(1, len(self.columns))), dtype=jnp.int32)
        out = out.at[:, jnp.asarray(self.snk_slot)].max(
            alive.astype(jnp.int32)
        )
        return out.astype(bool)

    def _hits_pair_stepper(self, B: int, lengths: jax.Array):
        """Per-byte hit accumulation — the path for banks with trailing
        ``\\b``/``\\B`` finals (no sink encoding). Carry: (state [B, W]
        uint32, hits [B, W] uint32, prev_wordness [B] bool). One
        ``bmask`` row take per byte; the \\b/\\B allow mask is the
        takeless two-constant select built in ``__init__``. The
        post-line-end state freeze is dropped — every hit term is gated
        by its byte's ``pos < length`` (or, on a ``pad0_transparent``
        bank, by the padding byte zeroing ``d`` itself) and positions
        only grow, so a polluted ``d`` past end-of-line can never
        contribute a hit."""
        W = self.n_words
        init = (
            jnp.zeros((B, W), jnp.uint32),
            jnp.zeros((B, W), jnp.uint32),
            jnp.zeros((B,), bool),
        )
        zero = jnp.uint32(0)

        def one(d, hits, pw, b, pos):
            b32 = b.astype(jnp.int32)
            cw = _is_word(b32) if self.needs_wordness else None
            if not self.pad0_transparent or self.needs_wordness:
                ok = pos < lengths
                okc = ok[:, None]
            if self.has_tb or self.has_preassert:
                bc = (pw != cw)[:, None]

            if self.has_tb:
                hits = hits | jnp.where(
                    okc, d & jnp.where(bc, self.f_tb, self.f_tB), zero
                )

            c = self._shift1(d)
            # ^-anchored starts inject only at each line's first byte;
            # the caret guard bit absorbs shift/skip leaks, so no
            # ``& not_caret`` anywhere (see _alt_allocs)
            if self.has_caret:
                c = c | jnp.where(pos == 0, self.start_all, self.start)
            else:
                c = c | self.start
            for _ in range(self.max_skip_run):
                c = c | self._shift1(c & self.k_skip)

            brow = jnp.take(self.bmask, b32, axis=0)  # [B, W]
            # factored: (c & brow) | (d & brow & s) == brow & (c | (d & s))
            # — one fewer [B, W] AND per byte
            if self.has_preassert:
                allow = jnp.where(bc, self.allow_bc, self.allow_nb)
                d = brow & ((c & allow) | (d & self.s_static))
            else:
                d = brow & (c | (d & self.s_static))

            if self.pad0_transparent:
                hits = hits | (d & self.f_plain)
            else:
                hits = hits | jnp.where(okc, d & self.f_plain, zero)
            if self.has_dollar or self.has_tb:
                eol = (pos == lengths - 1)[:, None]
            if self.has_dollar:
                hits = hits | jnp.where(eol, d & self.f_dollar, zero)
            if self.has_tb:
                cwc = cw[:, None]
                hits = hits | jnp.where(
                    eol, d & jnp.where(cwc, self.f_tb, self.f_tB), zero
                )
            if self.needs_wordness:
                pw = jnp.where(ok, cw, pw)
            return d, hits, pw

        def step(carry, b1, b2, t):
            d, hits, pw = carry
            p0 = 2 * t
            d, hits, pw = one(d, hits, pw, b1, p0)
            d, hits, pw = one(d, hits, pw, b2, p0 + 1)
            return (d, hits, pw)

        def finish(carry):
            _, hits, _ = carry
            return self.columns_from_hits(hits)

        return init, step, finish

    def columns_from_hits(self, hits: jax.Array) -> jax.Array:
        """uint32 [N, W] accumulated hit words -> bool [N, n_columns]."""
        N = hits.shape[0]
        fin = (
            jnp.take(hits, jnp.asarray(self.fin_word), axis=1)
            >> jnp.asarray(self.fin_bit)[None, :]
        ) & 1  # [N, n_fins]
        out = jnp.zeros((N, max(1, len(self.columns))), dtype=jnp.int32)
        out = out.at[:, jnp.asarray(self.fin_slot)].max(fin.astype(jnp.int32))
        return out.astype(bool)
