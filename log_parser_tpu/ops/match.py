"""Batched automaton execution on device.

Two kernels, both shaped as a ``lax.scan`` over byte columns with one gather
per step — the TPU-native replacement for the reference's per-line
``Matcher.find()`` hot loop (AnalysisService.java:89-113):

- :class:`DfaBank` runs R independent per-regex DFAs over every line
  simultaneously (state tensor ``[B, R]``), producing the full boolean
  match cube the scoring kernel consumes.
- :class:`AcRunner` runs the single combined Aho-Corasick automaton (state
  tensor ``[B]``), producing literal-hit bitmask words per line — the cheap
  prefilter for large pattern libraries.

Scans carry int32 states only; byte columns are consumed in a transposed
``[T, B]`` layout so each scan step is a contiguous slice.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from log_parser_tpu.patterns.regex.ac import AhoCorasick
from log_parser_tpu.patterns.regex.dfa import CompiledDfa


# pair-stride transition tables beyond this many int32 entries fall back to
# single-stride (the table must stay comfortably HBM/VMEM-resident)
PAIR_TABLE_MAX_ENTRIES = 64 << 20


def unpack_hit_words(h: jax.Array, n_cols: int) -> jax.Array:
    """uint32 [N, W] per-column hit words -> bool [N, n_cols] (shared by
    the union multi-DFA and AC prefilter tiers)."""
    cols = jnp.arange(n_cols, dtype=jnp.int32)
    word = h[:, cols // 32]
    return (word >> (cols % 32).astype(jnp.uint32)) & 1 > 0


def pack_byte_pairs(lines_tb: jax.Array):
    """uint8 [T, B] -> ([T2, 2, B] byte pairs, [T2] step indexes), padding
    T to even so every scan step consumes exactly two bytes."""
    T, B = lines_tb.shape
    if T % 2:
        lines_tb = jnp.concatenate(
            [lines_tb, jnp.zeros((1, B), lines_tb.dtype)], axis=0
        )
        T += 1
    return lines_tb.reshape(T // 2, 2, B), jnp.arange(T // 2, dtype=jnp.int32)


class DfaBank:
    """R packed DFAs executed in lockstep over a line batch.

    The scan is the serial axis of the whole framework, so by default two
    bytes are consumed per step via precomposed pair transition tables
    ``trans2[s, c1, c2] = trans[trans[s, c1], c2]`` over byte classes
    extended with one identity "padding" class (consumed where a position
    is at/past the line end). That halves the sequential scan length for a
    table-size cost of ``(cmax+1)²/cmax`` — gated by
    ``PAIR_TABLE_MAX_ENTRIES`` for very large banks.
    """

    def __init__(self, dfas: list[CompiledDfa], stride: int = 2):
        self.n_regexes = len(dfas)
        r = max(1, self.n_regexes)
        smax = max([d.n_states for d in dfas], default=1)
        cmax = max([d.n_classes for d in dfas], default=1)
        trans = np.zeros((r, smax, cmax), dtype=np.int32)
        byte_class = np.zeros((r, 256), dtype=np.int32)
        accept = np.zeros((r, smax), dtype=bool)
        start = np.zeros(r, dtype=np.int32)
        for i, d in enumerate(dfas):
            trans[i, : d.n_states, : d.n_classes] = d.trans
            byte_class[i] = d.byte_class
            accept[i, : d.n_states] = d.accept_end
            start[i] = d.start
        self.smax, self.cmax = smax, cmax
        # Byte 0 maps to the identity padding class (index cmax): content
        # NULs never reach the device (encode routes them to host), so
        # padding bytes select identity through the class map itself and
        # the pair-stride scan needs no per-step ``pos < length`` selects.
        # The non-pair paths keep their gating; their (clamped,
        # out-of-range) byte-0 lookups only occur at gated padding bytes.
        byte_class[:, 0] = cmax
        # flat layout for a single fused gather per scan step
        self.flat_trans = jnp.asarray(trans.reshape(-1))
        self.byte_class = jnp.asarray(byte_class)
        self.flat_accept = jnp.asarray(accept.reshape(-1))
        self.start = jnp.asarray(start)

        self.pair_stride = (
            stride == 2
            and r * smax * (cmax + 1) * (cmax + 1) <= PAIR_TABLE_MAX_ENTRIES
        )
        if self.pair_stride:
            cpad = cmax + 1  # class cmax = identity padding class
            ext = np.zeros((r, smax, cpad), dtype=np.int32)
            ext[:, :, :cmax] = trans
            ext[:, :, cmax] = np.arange(smax, dtype=np.int32)[None, :]
            # trans2[r, s, c1, c2] = ext[r, ext[r, s, c1], c2]
            trans2 = np.empty((r, smax, cpad, cpad), dtype=np.int32)
            for i in range(r):
                trans2[i] = ext[i][ext[i], :]
            self.cpad = cpad
            self.flat_trans2 = jnp.asarray(trans2.reshape(-1))

        self._jit = jax.jit(self._run)

    def _run(self, lines_tb: jax.Array, lengths: jax.Array) -> jax.Array:
        """lines_tb: uint8 [T, B] (transposed); lengths: int32 [B].
        Returns bool [B, R]."""
        return self._run_pair(lines_tb, lengths)

    def _run_single(self, lines_tb: jax.Array, lengths: jax.Array) -> jax.Array:
        T, B = lines_tb.shape
        R = self.byte_class.shape[0]
        smax, cmax = self.smax, self.cmax
        states0 = jnp.broadcast_to(self.start[None, :], (B, R)).astype(jnp.int32)
        r_off = (jnp.arange(R, dtype=jnp.int32) * smax)[None, :]  # [1, R]

        def step(states, xs):
            bytes_t, t = xs
            cls = jnp.take(self.byte_class, bytes_t.astype(jnp.int32), axis=1)  # [R, B]
            idx = (r_off + states) * cmax + cls.T  # [B, R]
            nxt = jnp.take(self.flat_trans, idx.reshape(-1)).reshape(B, R)
            active = (t < lengths)[:, None]
            return jnp.where(active, nxt, states), None

        ts = jnp.arange(T, dtype=jnp.int32)
        states, _ = jax.lax.scan(step, states0, (lines_tb, ts))
        return jnp.take(self.flat_accept, (r_off + states).reshape(-1)).reshape(B, R)

    def _run_pair(self, lines_tb: jax.Array, lengths: jax.Array) -> jax.Array:
        """Two bytes per scan step through the precomposed pair tables;
        positions at/past each line's end consume the identity class, so no
        per-step boundary branch is needed."""
        T, B = lines_tb.shape
        init, step, finish = self.pair_stepper(B, lengths)
        pairs, ts = pack_byte_pairs(lines_tb)
        states, _ = jax.lax.scan(
            lambda s, xs: (step(s, xs[0][0], xs[0][1], xs[1]), None),
            init,
            (pairs, ts),
        )
        return finish(states)

    def pair_stepper(self, B: int, lengths: jax.Array):
        """(init, step(carry, b1, b2, t), finish) — one pair-consuming scan
        stage, composable with other banks into a single fused scan."""
        R = self.byte_class.shape[0]
        smax = self.smax
        states0 = jnp.broadcast_to(self.start[None, :], (B, R)).astype(jnp.int32)
        r_off = (jnp.arange(R, dtype=jnp.int32) * smax)[None, :]  # [1, R]

        if self.pair_stride:
            cpad = self.cpad

            def step(states, b1, b2, t):
                # gate-free: padding bytes (0) map to the identity class
                # through byte_class itself (see __init__)
                c1 = jnp.take(self.byte_class, b1.astype(jnp.int32), axis=1)  # [R, B]
                c2 = jnp.take(self.byte_class, b2.astype(jnp.int32), axis=1)
                idx = ((r_off + states) * cpad + c1.T) * cpad + c2.T  # [B, R]
                return jnp.take(self.flat_trans2, idx.reshape(-1)).reshape(B, R)

        else:
            cmax = self.cmax

            def one(states, b, pos_ok):
                cls = jnp.take(self.byte_class, b.astype(jnp.int32), axis=1)  # [R, B]
                idx = (r_off + states) * cmax + cls.T
                nxt = jnp.take(self.flat_trans, idx.reshape(-1)).reshape(B, R)
                return jnp.where(pos_ok[:, None], nxt, states)

            def step(states, b1, b2, t):
                p0 = 2 * t
                states = one(states, b1, p0 < lengths)
                return one(states, b2, p0 + 1 < lengths)

        def finish(states):
            return jnp.take(
                self.flat_accept, (r_off + states).reshape(-1)
            ).reshape(B, R)

        return states0, step, finish

    def match(self, lines_u8: np.ndarray, lengths: np.ndarray) -> np.ndarray:
        """Host entry: uint8 [B, T] padded batch → bool [B, R] match cube."""
        if self.n_regexes == 0:
            return np.zeros((lines_u8.shape[0], 0), dtype=bool)
        out = self._jit(jnp.asarray(lines_u8.T), jnp.asarray(lengths))
        return np.asarray(out)[:, : self.n_regexes]


class MultiDfaBank:
    """One union multi-pattern DFA group on device (multidfa.py).

    R patterns ride ONE automaton. The hot scan is ONE ``[B]`` gather per
    byte: the byte-class map is precomposed into the transition table
    (``[S, 256]`` — at most 8 MB under the 8192-state budget), whose
    packed words carry a "this state can report a match" flag in bit 30 —
    cost independent of R, vs the dense tier's ``[B, R]`` gather (measured
    ~150ms/regex/200k lines on TPU v5e, PERF.md; per-element random
    gathers run on the scalar unit, so eliminating the separate
    byte→class gather halves the tier's hot cost). Exact per-pattern hit
    words are recovered after the scan by re-scanning ONLY the flagged
    rows (matching log lines are rare) through the full output-word
    tables, with an in-program ``lax.cond`` dense re-scan when the
    flagged-row capacity overflows — the same robustness shape as the
    prefilter tier.

    Steps one byte at a time: a pair-precomposed table would be S·256²
    per step and the union automaton's S is large.
    """

    _REPORT_BIT = 1 << 30
    _STATE_MASK = _REPORT_BIT - 1

    def __init__(self, md, cols: list[int]):
        self.cols = cols  # global column ids, bit order
        self.n_cols = len(cols)
        self.n_words = md.n_words
        S, C = md.trans.shape
        self.n_states, self.n_classes = S, C
        # word-ness per BYTE (precomposed through the class map): the out2
        # row index is state*2 + word-ness of the incoming byte
        self.byte_rw = jnp.asarray(md.cls_is_word[md.byte_class])
        self.out2 = jnp.asarray(md.out2)  # [S*2, W] uint32
        self.accept_words = jnp.asarray(md.accept_words)  # [S, W] uint32
        self.start = int(md.start)

        # reporting flags: state may emit out bits under either word-ness,
        # or accept at end-of-input — conservative OR so the flag alone
        # decides whether a row needs the exact second pass
        reports = (
            md.out2.reshape(S, 2, md.n_words).any(axis=(1, 2))
            | md.accept_words.any(axis=1)
        )
        # class-level tables kept host-side for the Pallas kernel's
        # byte-class-compressed planes (matchdfa_pallas._group_planes);
        # n_states_unmin feeds the plan's geometry report, and the
        # compiled automaton rides along (arrays shared, not copied) so
        # admission tooling can snapshot re-partitioned groups
        self._md = md
        self._trans_np = md.trans
        self._byte_class_np = md.byte_class
        self._reports_np = reports
        self.n_states_unmin = md.n_states_unmin or S
        packed = md.trans.astype(np.int64) | (
            reports.astype(np.int64)[md.trans] << 30
        )
        # byte-precomposition below spreads classes over the byte axis;
        # byte 0 is then overridden to a SELF-LOOP carrying the state's
        # own report flag: content NULs never reach the device (encode
        # routes them to host), so past a line's end the state freezes
        # itself and the any-hit flag OR is an idempotent re-OR — the
        # pair_stepper runs gate-free. The exact word_stepper keeps its
        # gating (out2 rows are word-ness-dependent, and a padding byte
        # must not re-emit them).
        # byte-precomposed: trans_byte[s, b] = packed[s, byte_class[b]].
        # Host-side until first use: when the group joins a
        # MultiDfaCluster, the cluster's concatenated device buffer is
        # shared back (via _adopt_table) so the table exists on device
        # exactly once however it is reached.
        packed_byte = packed[:, md.byte_class].astype(np.int32)
        s_idx = np.arange(S, dtype=np.int32)
        packed_byte[:, 0] = s_idx | (reports[s_idx].astype(np.int32) << 30)
        self._packed_byte_np = packed_byte.reshape(-1)
        self._flat: jax.Array | None = None
        self._flat_base = 0
        self.start_reports = bool(reports[md.start])

    def _table(self) -> tuple[jax.Array, int]:
        """(device buffer, base offset) of this group's byte-precomposed
        transition table, uploading it standalone on first use.  Never
        caches under an active jit trace (jnp.asarray would yield a
        trace-local constant whose escape poisons every later call) —
        MatcherBanks pre-uploads eagerly on the no-cluster path so the
        guard is a backstop, not the common case."""
        if self._flat is None:
            arr = jnp.asarray(self._packed_byte_np)
            if isinstance(arr, jax.core.Tracer):
                return arr, self._flat_base
            self._flat = arr
        return self._flat, self._flat_base

    def _adopt_table(self, flat: jax.Array, base: int) -> None:
        # the host copy is kept (host RAM, not HBM): a later cluster over
        # the same groups — re-tiering, probe tools — must be able to
        # rebuild the concatenated buffer
        self._flat = flat
        self._flat_base = int(base)

    # ------------------------------------------------------- hot scan stage

    def pair_stepper(self, B: int, lengths: jax.Array):
        """(init, step(carry, b1, b2, t), finish_carry) — carry is
        (state [B] int32, reported [B] bool). The cube slice is produced
        by :meth:`contribution` from the finished carry."""
        flat, base = self._table()
        init = (
            jnp.full((B,), self.start, jnp.int32),
            jnp.full((B,), self.start_reports, bool),
        )

        def one(s, rep, b):
            # gate-free: padding bytes (0) self-loop with the state's own
            # report flag (see the packed-table build)
            v = jnp.take(flat, base + s * 256 + b.astype(jnp.int32))
            return v & self._STATE_MASK, rep | (v >= self._REPORT_BIT)

        def step(carry, b1, b2, t):
            s, rep = carry
            s, rep = one(s, rep, b1)
            s, rep = one(s, rep, b2)
            return (s, rep)

        def finish(carry):
            return carry

        return init, step, finish

    # ------------------------------------------------- exact recovery stage

    def word_stepper(self, N: int, lengths: jax.Array):
        """Composable pair-stepper for the exact out-word pass. Carry:
        (state [N] int32, hit_words [N, W] uint32)."""
        flat, base = self._table()
        init = (
            jnp.full((N,), self.start, jnp.int32),
            jnp.zeros((N, self.n_words), jnp.uint32),
        )

        def one(s, h, b, ok):
            b32 = b.astype(jnp.int32)
            rw = jnp.take(self.byte_rw, b32)
            ow = jnp.take(self.out2, s * 2 + rw, axis=0)  # [N, W]
            h = h | jnp.where(ok[:, None], ow, jnp.uint32(0))
            v = jnp.take(flat, base + s * 256 + b32)
            s = jnp.where(ok, v & self._STATE_MASK, s)
            return s, h

        def step(carry, b1, b2, t):
            s, h = carry
            p0 = 2 * t
            s, h = one(s, h, b1, p0 < lengths)
            s, h = one(s, h, b2, p0 + 1 < lengths)
            return (s, h)

        def finish(carry):
            s, h = carry
            return h | jnp.take(self.accept_words, s, axis=0)

        return init, step, finish

    def unpack(self, h: jax.Array) -> jax.Array:
        """uint32 [N, W] hit words -> bool [N, n_cols]."""
        return unpack_hit_words(h, self.n_cols)


class MultiDfaCluster:
    """All union groups advanced by ONE ``[B, G]`` gather per byte.

    Running each group as its own stepper inside the fused scan measured
    ~2x the sum of the groups run alone (tools/probe_tiers.py: 4 groups at
    0.13-0.15s each alone, 1.03s fused — the scalar-unit gather code
    XLA emits for several independent gathers in one loop body schedules
    worse than one wider gather). Concatenating the groups'
    byte-precomposed tables and carrying states as ``[B, G]`` makes the
    whole tier one take per byte, restoring per-element throughput."""

    def __init__(self, groups: list[MultiDfaBank]):
        self.groups = groups
        sizes = [g.n_states * 256 for g in groups]
        base = np.zeros(len(groups), dtype=np.int64)
        base[1:] = np.cumsum(sizes[:-1])
        if base[-1] + sizes[-1] >= (1 << 31):
            # int32 gather indices would wrap into wrong transitions; this
            # must survive `python -O`, so no bare assert (group_dfa_states
            # caps keep real banks far below this)
            raise ValueError(
                "multi-DFA cluster table exceeds int32 index range: "
                f"{int(base[-1] + sizes[-1])} entries"
            )
        self._base = jnp.asarray(base.astype(np.int32))[None, :]  # [1, G]
        self._flat = jnp.asarray(
            np.concatenate([g._packed_byte_np for g in groups])
        )
        # share the concatenated buffer back so each group's word_stepper
        # reads the same device memory — the table lives on device once
        for g, b in zip(groups, base):
            g._adopt_table(self._flat, b)
        self._start = jnp.asarray(
            np.asarray([g.start for g in groups], np.int32)
        )
        self._start_reports = jnp.asarray(
            np.asarray([g.start_reports for g in groups], bool)
        )

    def pair_stepper(self, B: int, lengths: jax.Array):
        """Carry: (states [B, G] int32, reported [B, G] bool); finish
        returns the per-group reported columns in group order."""
        G = len(self.groups)
        mask = jnp.int32(MultiDfaBank._STATE_MASK)
        init = (
            jnp.broadcast_to(self._start[None, :], (B, G)).astype(jnp.int32),
            jnp.broadcast_to(self._start_reports[None, :], (B, G)),
        )

        def one(s, rep, b):
            # gate-free: each group's byte-0 column self-loops with the
            # state's own report flag (MultiDfaBank packed-table build)
            idx = self._base + s * 256 + b.astype(jnp.int32)[:, None]
            v = jnp.take(self._flat, idx)  # [B, G]
            return v & mask, rep | (v >= MultiDfaBank._REPORT_BIT)

        def step(carry, b1, b2, t):
            s, rep = carry
            s, rep = one(s, rep, b1)
            s, rep = one(s, rep, b2)
            return (s, rep)

        def finish(carry):
            _, rep = carry
            return [rep[:, i] for i in range(G)]

        return init, step, finish


class AcRunner:
    """Combined Aho-Corasick literal prefilter on device."""

    def __init__(self, ac: AhoCorasick):
        self.ac = ac
        self.n_words = ac.n_words
        self.goto = jnp.asarray(ac.goto)
        self.byte_class = jnp.asarray(ac.byte_class)
        self.out_words = jnp.asarray(ac.out_words.astype(np.uint32))
        self._jit = jax.jit(self._run)

    def _run(self, lines_tb: jax.Array, lengths: jax.Array) -> jax.Array:
        T, B = lines_tb.shape

        def step(carry, xs):
            states, hits = carry
            bytes_t, t = xs
            cls = jnp.take(self.byte_class, bytes_t.astype(jnp.int32))  # [B]
            nxt = self.goto[states, cls]  # [B]
            active = t < lengths
            states = jnp.where(active, nxt, states)
            step_hits = jnp.where(
                active[:, None], jnp.take(self.out_words, states, axis=0), jnp.uint32(0)
            )
            return (states, hits | step_hits), None

        states0 = jnp.zeros(B, dtype=jnp.int32)
        hits0 = jnp.zeros((B, self.n_words), dtype=jnp.uint32)
        ts = jnp.arange(T, dtype=jnp.int32)
        (_, hits), _ = jax.lax.scan(step, (states0, hits0), (lines_tb, ts))
        return hits

    def scan(self, lines_u8: np.ndarray, lengths: np.ndarray) -> np.ndarray:
        """Host entry: uint8 [B, T] → uint32 [B, n_words] literal-hit masks."""
        out = self._jit(jnp.asarray(lines_u8.T), jnp.asarray(lengths))
        return np.asarray(out)


class MatcherBanks:
    """Tiered device matchers for one PatternBank's columns.

    Tier selection is static per column (patterns/bank.py) and
    PLATFORM-DEPENDENT: on TPU, literal-shaped
    regexes go to the bit-parallel Shift-Or bank (cost independent of bank
    size), while on CPU hosts they ride the union multi-DFA / prefilter
    instead (XLA:CPU's vectorized gathers beat mask arithmetic — see
    SHIFTOR_MIN_COLUMNS; Shift-Or re-engages only on degraded hosts
    without the native lib, and for DFA-less literal columns always);
    in wide banks, regexes with required literals ride the AC
    prefilter + per-record verify tier (ops/prefilter.py — cost per byte
    independent of library width); the rest go to the packed dense DFA
    bank; automaton-unsupported regexes stay host-side (the engine injects
    them as cube overrides).
    """

    # CPU threshold. Shift-Or is a TPU-shaped tier: on the host, XLA's
    # vectorized gathers beat [B, W] mask arithmetic at EVERY width
    # measured — the 59 builtin literal columns scan 3.3x faster through
    # the union multi-DFA (config-2 cube 1.455 -> 0.445 s, 200k lines,
    # bit-equal; r5 A/B), and the 1008-column synthetic bank ran 4.5x
    # faster through the prefilter (PERF.md §6). So DFA-backed literal
    # columns are NEVER rerouted to Shift-Or on CPU; DFA-less literal
    # columns still ride it everywhere (their only device tier).  On a
    # degraded host WITHOUT the native library the union tier is off, so
    # Shift-Or re-engages at the old threshold rather than stranding
    # literal columns on the dense [B, R] gather.
    SHIFTOR_MIN_COLUMNS = 10**9
    SHIFTOR_MIN_COLUMNS_NO_NATIVE = 64
    # below this many DENSE-DFA columns, the prefilter tier stays off: the
    # dense gather is cheap and the extra scans aren't worth their latency
    PREFILTER_MIN_COLUMNS = 64

    # TPU thresholds. Measured on v5e (tools/profile_fused.py, 229k-row
    # batch, PERF.md): a dense-DFA column costs ~150ms per 200k lines —
    # the [B, R] transition gather is scalar-unit bound — while a Shift-Or
    # column costs ~3ms and the AC words tier has a fixed cost of roughly
    # eight dense columns. Literal-shaped columns therefore ALWAYS ride
    # Shift-Or, and the prefilter engages at 8 eligible columns.
    SHIFTOR_MIN_COLUMNS_TPU = 1
    PREFILTER_MIN_COLUMNS_TPU = 8

    # Shift-Or's per-byte cost is a [B, n_words] mask gather — linear in
    # the packed WORD count (≈ total literal bytes / 32), not the column
    # count. A 1008-literal-column synthetic bank packs 1488 words and its
    # mask gather alone cost 4.5x the whole prefilter-routed cube (PERF.md
    # §6); beyond this word budget, DFA-backed literal columns join the
    # dense-eligible pool and ride the width policy (union / prefilter)
    # instead. Columns with no DFA stay on Shift-Or regardless — it is
    # their only device tier. 128 words keeps the builtin bank (66 words,
    # Shift-Or measured at 0.17s/59 columns on TPU) while rerouting the
    # 1000-word synthetic banks.
    SHIFTOR_MAX_WORDS = 128

    # Union multi-DFA tier (platform-independent: one [B] gather per byte
    # beats a [B, R] gather for R >= 2 everywhere; the native builder makes
    # group packing cheap). Above MULTI_PREFERRED_MAX dense columns the
    # union would need many groups (each ~2 gathers/byte) — wide
    # literal-BEARING sets ride the AC prefilter instead, whose any-hit
    # stage is O(1)/byte in width; literal-free columns stay on the union
    # whatever the width (their only alternative is the dense bank at
    # ~150ms/column/200k lines on TPU).
    MULTI_MIN_COLUMNS = 2
    MULTI_STATE_BUDGET = 8192
    MULTI_MAX_GROUP = 64
    MULTI_PREFERRED_MAX = 128

    # Bit-parallel extended Shift-And tier (ops/bitglush.py): dense-eligible
    # columns whose regex compiles to the bit fragment run with NO random
    # gathers — one [256, W] mask-row take per byte for the whole tier —
    # ahead of every automaton tier. The word budget bounds the [B, W]
    # elementwise cost the same way SHIFTOR_MAX_WORDS does: the builtin
    # 49 dense-eligible columns pack ~74 words, while a 2k-pattern
    # synthetic bank would need ~600 and rides the prefilter instead.
    # TPU only: replacing the union tier with the bit tier measured the
    # config-2 cube 0.62s -> 0.31s on v5e (random gathers are scalar-unit
    # bound there) but 62k -> 23k lines/s on the host CPU, where XLA's
    # vectorized gathers beat the [B, W] mask arithmetic.
    BITGLUSH_MAX_WORDS_TPU = 192
    BITGLUSH_MAX_WORDS_CPU = 0
    BITGLUSH_MAX_COLUMN_POSITIONS = 512

    def __init__(
        self,
        bank,
        stride: int = 2,
        shiftor_min_columns: int | None = None,
        prefilter_min_columns: int | None = None,
        multi_min_columns: int | None = None,
        shiftor_max_words: int | None = None,
        bitglush_max_words: int | None = None,
        shiftor_sinks: bool | None = None,
    ):
        import jax.numpy as jnp

        from log_parser_tpu.native import get_lib
        from log_parser_tpu.ops.prefilter import PrefilterBank
        from log_parser_tpu.ops.shiftor import ShiftOrBank

        self.bank = bank
        on_tpu = jax.default_backend() == "tpu"
        threshold = shiftor_min_columns
        if threshold is None:
            if on_tpu:
                threshold = self.SHIFTOR_MIN_COLUMNS_TPU
            elif get_lib() is not None:
                threshold = self.SHIFTOR_MIN_COLUMNS
            else:
                # degraded host (no native lib -> no union tier): Shift-Or
                # is still far cheaper than stranding literal columns on
                # the dense [B, R] gather — keep the old CPU engagement
                threshold = self.SHIFTOR_MIN_COLUMNS_NO_NATIVE
        pref_threshold = prefilter_min_columns
        if pref_threshold is None:
            pref_threshold = (
                self.PREFILTER_MIN_COLUMNS_TPU
                if on_tpu
                else self.PREFILTER_MIN_COLUMNS
            )
        n_device = sum(
            1
            for c in bank.columns
            if c.dfa is not None or c.exact_seqs is not None
        )
        bit_budget = (
            (self.BITGLUSH_MAX_WORDS_TPU if on_tpu else self.BITGLUSH_MAX_WORDS_CPU)
            if bitglush_max_words is None
            else bitglush_max_words
        )
        # Keep literal columns on Shift-Or even when the bit tier is on:
        # [B, W] arrays pad to 128 LANES, so per-scan-step cost is
        # ceil(W/128) x the stepper's op-chain length. Absorbing the
        # literal columns into bitglush (their regexes are trivially in
        # the bit fragment) was measured: the merged bank needs 140 words
        # — the second lane-tile doubles the heavy ~18-op bitglush chain
        # (cube 0.44s vs 0.27s split, config-2, v5e). Two banks, each one
        # tile, pay 18 + 8 op-tiles; that is the cheap shape (PERF.md §9).
        # Shift-Or layout is platform-dependent (shiftor.py docstring):
        # on TPU the take cost scales with gathered row width, so the
        # bank packs bare (no sink bits) and accumulates hits per byte;
        # on hosts the pair-composed sink stepper's halved serial chain
        # wins, so the bank packs sinks (probe_sink_ab.py, PERF.md §9d)
        self.shiftor_sinks = (
            (not on_tpu) if shiftor_sinks is None else shiftor_sinks
        )
        use_shiftor = n_device >= threshold
        # Word-budget gate (see SHIFTOR_MAX_WORDS): DFA-backed literal
        # columns only ride Shift-Or while the packed word count stays
        # small. Counted with ShiftOrBank's own first-fit fill (a bits/32
        # estimate undercounts fragmentation ~2x), over the REROUTABLE
        # columns only — no-DFA columns stay on Shift-Or either way, so
        # their words are a floor the reroute can't remove.
        word_budget = (
            self.SHIFTOR_MAX_WORDS
            if shiftor_max_words is None
            else shiftor_max_words
        )
        # DFA-backed columns with any sequence over 32 positions stay
        # off Shift-Or BY DEFAULT: chains would widen every Shift-Or
        # take row (take cost ∝ row width — 81→114 words measured
        # 0.088→0.154 s). Two exceptions ride its cont-mask chains
        # anyway: DFA-less literal columns (their only device tier) and
        # _chain_literal columns below (long literals in secondary/
        # sequence/context roles, where bitglush truncation would be
        # unsound — a couple of words of width beats re-chaining the
        # whole bitglush bank, PERF.md §9d).
        def _short_seqs(c) -> bool:
            return all(len(s) <= 32 for s in c.exact_seqs)

        # Column roles. A cube column may serve several patterns and
        # roles; bitglush's truncation of over-long alternatives
        # (over-approximate device match + exact host repair in
        # runtime/engine.py) is sound for PRIMARY roles (flagged events
        # are re-verified with the host regex and dropped) and for
        # SECONDARY roles (a truncated secondary only feeds the
        # proximity distances, which the engine repairs exactly: the
        # device's claimed min-distance names at most two lines, both
        # host-verified, with a host window re-scan in the rare case
        # both were prefix-only false positives). Sequence-event and
        # context columns feed device-side factor extraction with no
        # cheap repair, so they are NEVER truncated: long literal ones
        # ride Shift-Or's cont-mask chain path; anything long,
        # non-literal, and non-truncatable keeps its exact chained
        # bitglush allocation (has_chains — correct, slower, absent
        # from the builtin library).
        from log_parser_tpu.patterns.bank import CTX_EXCEPTION

        exact_role_cols = {
            c for e in bank.sequences for c in e.event_columns
        } | set(range(CTX_EXCEPTION + 1))
        truncatable = (
            set(int(c) for c in bank.primary_columns)
            | {s.column for s in bank.secondaries}
        ) - exact_role_cols

        def _chain_literal(i, c) -> bool:
            # long-literal column that may NOT be truncated: its exact
            # home is the Shift-Or chain path
            return (
                c.exact_seqs is not None
                and not _short_seqs(c)
                and i in exact_role_cols
            )

        if use_shiftor:
            # count the whole candidate bank, INCLUDING the DFA-less
            # floor (those columns stay on Shift-Or either way, and with
            # chains they can be wide): rerouting the DFA-backed columns
            # must keep the combined bank under the budget, not just
            # their own share
            n_words = ShiftOrBank.count_packed_words(
                (
                    len(seq)
                    for i, c in enumerate(bank.columns)
                    if c.exact_seqs is not None
                    and (
                        c.dfa is None
                        or _short_seqs(c)
                        or _chain_literal(i, c)
                    )
                    for seq in c.exact_seqs
                ),
                budget=word_budget,
                sinks=self.shiftor_sinks,
            )
            if n_words > word_budget:
                use_shiftor = False
        self.shiftor_cols = [
            i
            for i, c in enumerate(bank.columns)
            if c.exact_seqs is not None
            and (
                (use_shiftor and (_short_seqs(c) or _chain_literal(i, c)))
                or c.dfa is None
            )
        ]
        shiftor_set = set(self.shiftor_cols)
        dense_cols = [
            i
            for i, c in enumerate(bank.columns)
            if c.dfa is not None and i not in shiftor_set
        ]
        self.host_cols = [
            i
            for i, c in enumerate(bank.columns)
            if c.dfa is None and c.exact_seqs is None
        ]

        # union multi-DFA tier: pack remaining DFA columns into as few
        # union automata as the state budget allows — each group matches
        # its R patterns with ONE [B] gather per byte (multidfa.py). The
        # construction is native C++; without the lib the packing probes
        # would run the Python subset builder at O(seconds) per probe, so
        # the tier stays off and columns keep their prior tiers.
        multi_threshold = (
            self.MULTI_MIN_COLUMNS
            if multi_min_columns is None
            else multi_min_columns
        )
        use_multi = (
            len(dense_cols) >= multi_threshold and get_lib() is not None
        )

        # WIDE banks select the prefilter set FIRST (any-hit is O(1)/byte
        # in width), so that the union tier can absorb everything the
        # selection leaves behind — literal-free columns AND trie-budget
        # rejects — instead of stranding rejects on the dense bank.
        pref_selected: list = []
        if len(dense_cols) > self.MULTI_PREFERRED_MAX or not use_multi:
            if len(dense_cols) >= pref_threshold:
                eligible = [
                    (i, bank.columns[i])
                    for i in dense_cols
                    if bank.columns[i].literals
                ]
                selected, _rejected = PrefilterBank.select(eligible)
                if len(selected) >= pref_threshold:
                    pref_selected = selected
        pref_set = {g for g, _ in pref_selected}

        # bit-parallel tier: gather-free execution for columns in the
        # union pool (everything the prefilter selection left — wide-bank
        # literal-bearing columns stay on the width-independent AC trie)
        # whose regex compiles to the bit fragment, under the word budget
        from log_parser_tpu.ops.bitglush import BitGlushBank
        from log_parser_tpu.patterns.regex.bitprog import (
            BitUnsupportedError,
            compile_bitprog_regex,
            expand_asserts,
            has_asserts,
            truncate_long_alternatives,
        )

        # Admission prices the EXACT packed bank the constructor would
        # build — same first-fit plan, sink + caret-guard bits included
        # (ADVICE r4: the old positions/32 floor under-counted both, so
        # a constructed bank could exceed the budget and cross a
        # 128-lane tile, doubling per-byte scan cost).  Pricing is
        # incremental (one FirstFitPacker carried across candidates);
        # the one event that invalidates it — a candidate with a \b/\B
        # post-assert flipping the bank sink-INELIGIBLE, which strips a
        # bit from every admitted alternative — triggers a full repack,
        # and eligibility can only flip once (off) per admitted set.
        from log_parser_tpu.ops.shiftor import FirstFitPacker

        bit_entries: list[tuple[int, object]] = []
        bit_progs: list = []
        packer = FirstFitPacker()
        sink_on = True  # BitGlushBank.sink_eligible([]) — empty set
        for i in dense_cols if bit_budget > 0 else []:
            if i in pref_set:
                continue
            col = bank.columns[i]
            try:
                prog = compile_bitprog_regex(col.regex, col.case_insensitive)
            except (BitUnsupportedError, ValueError):
                continue
            if prog.n_positions > self.BITGLUSH_MAX_COLUMN_POSITIONS:
                continue
            flips = sink_on and not BitGlushBank.sink_eligible([prog])
            if flips:
                # eligibility flip strips a bit from every admitted
                # alternative: one full repack (at most once per
                # admitted set while ON; a rejected flip candidate
                # leaves eligibility as-is and pays the same one pass)
                trial = FirstFitPacker()
                allocs = BitGlushBank._alt_allocs(bit_progs + [prog])
            else:
                trial = packer.clone()
                allocs = [
                    BitGlushBank.alt_alloc(alt, 1 if sink_on else 0)
                    for alt in prog.alternatives
                ]
            over = False
            for a in allocs:
                trial.add(a)
                if trial.n_words > bit_budget:
                    over = True
                    break
            if over:
                continue
            packer = trial
            sink_on = sink_on and not flips
            bit_progs.append(prog)
            bit_entries.append((i, prog))
        # De-assert rewrite, all-or-nothing: the op-group savings are
        # BANK-wide capability flags, so expansion only pays if every
        # asserted program expands (and the expanded bank stays within
        # budget); one unexpandable column keeps the gated originals.
        if any(has_asserts(p) for _, p in bit_entries):
            try:
                expanded = [(i, expand_asserts(p)) for i, p in bit_entries]
            except BitUnsupportedError:
                expanded = None
            if expanded is not None and all(
                p.n_positions <= self.BITGLUSH_MAX_COLUMN_POSITIONS
                for _, p in expanded
            ) and BitGlushBank.count_packed_words(
                [p for _, p in expanded], budget=bit_budget
            ) <= bit_budget:
                bit_entries = expanded
        # Truncate over-long alternatives of primary/secondary-role
        # columns so their allocations fit one word and the bank stays
        # on the chainless shift (the carry's concat per shift measured 2.5x
        # the chainless stepper on v5e — tools/probe_chainless.py). The
        # per-alternative item budget reserves the sink bit
        # UNCONDITIONALLY (truncation drops \b/\B post-asserts, which
        # can flip a pre-truncation sink-ineligible bank eligible) and
        # the caret guard bit where the alternative is ^-anchored —
        # otherwise a truncated allocation could still straddle a word
        # and re-enable the bank-wide carry the truncation exists to
        # remove. The truncated column OVER-matches; the engine
        # re-verifies its rare flagged events with the exact host regex
        # at assembly (runtime/engine.py, approx_cols).
        # Non-truncatable long programs stay exact and keep the carry.
        def _item_budget(alt) -> int:
            return 31 - (1 if alt.caret else 0)

        approx: list[int] = []
        truncated_entries: list[tuple[int, object]] = []
        for i, p in bit_entries:
            if i in truncatable and any(
                a.n_positions > _item_budget(a) for a in p.alternatives
            ):
                cut = truncate_long_alternatives(p, _item_budget)
                if cut is not None:
                    p = cut[0]
                    approx.append(i)
            truncated_entries.append((i, p))
        bit_entries = truncated_entries
        # Truncation can FLIP the bank sink-eligible (it drops \b/\B
        # post-asserts), adding one sink bit per alternative BANK-wide —
        # so the admission-time price can be stale. Re-price the final
        # set and shed entries until the constructed bank fits again
        # (shed columns fall through to the union/prefilter/dense tiers
        # like any other reject); the loop re-prices every iteration, so
        # eligibility flips caused by the shedding itself are priced too.
        while bit_entries and BitGlushBank.count_packed_words(
            [p for _, p in bit_entries], budget=bit_budget
        ) > bit_budget:
            bit_entries.pop()
        kept = {i for i, _ in bit_entries}
        self.approx_cols = [i for i in approx if i in kept]
        # ONE bank for all bit programs. A measured A/B split the
        # assert-free programs into their own light bank (no word-ness /
        # allow / caret work): cube 0.31 → 0.39s on v5e — the asserted
        # remainder packs only ~5 words, so the extra stepper's scan
        # overhead outweighed the ops saved (same lesson as the union
        # groups: more carries in one fused scan schedule worse). The
        # capability flags still pay off whenever a whole bank is
        # assert-free (BitGlushBank skips those op groups bank-wide).
        self.bitglush = BitGlushBank(bit_entries) if bit_entries else None
        self.bitglush_cols = [i for i, _ in bit_entries]
        bit_set = set(self.bitglush_cols)
        dense_cols = [i for i in dense_cols if i not in bit_set]
        # experimental whole-tier Pallas kernel (bitglush_pallas.py):
        # measured at parity with the scan path on v5e (PERF.md §9), kept
        # opt-in. Read once here — cube() runs under jit, so an env read
        # there would be frozen at first-trace time anyway.
        self.bitglush_use_pallas = (
            self.bitglush is not None
            and os.environ.get("LOG_PARSER_TPU_PALLAS") == "1"
        )

        self.multi_groups: list[MultiDfaBank] = []
        self._multi_entries: list[list[tuple[int, str, bool]]] = []
        if use_multi:
            from log_parser_tpu.patterns.regex.multidfa import pack_union_groups

            take = [i for i in dense_cols if i not in pref_set]
            if take:
                entries = [
                    (i, bank.columns[i].regex, bank.columns[i].case_insensitive)
                    for i in take
                ]
                groups, rejected_entries = pack_union_groups(
                    entries,
                    max_states=self.MULTI_STATE_BUDGET,
                    max_group=self.MULTI_MAX_GROUP,
                )
                self.multi_groups = [
                    MultiDfaBank(md, keys) for keys, md in groups
                ]
                # per-group (key, regex, ci) in bit order — the kernel
                # plan builder re-splits groups from these when the
                # packed geometry exceeds the VMEM budget
                emap = {e[0]: e for e in entries}
                self._multi_entries = [
                    [emap[k] for k in keys] for keys, _ in groups
                ]
                taken = set(take)
                dense_cols = [k for k, _, _ in rejected_entries] + [
                    i for i in dense_cols if i not in taken and i not in pref_set
                ]
            else:
                dense_cols = [i for i in dense_cols if i not in pref_set]
        else:
            dense_cols = [i for i in dense_cols if i not in pref_set]

        # NARROW banks: the union already took everything; offer its
        # rejects (union-hostile regexes) to the prefilter if enough of
        # them carry literals
        if not pref_selected and len(dense_cols) >= pref_threshold:
            eligible = [
                (i, bank.columns[i])
                for i in dense_cols
                if bank.columns[i].literals
            ]
            selected, _rejected = PrefilterBank.select(eligible)
            if len(selected) >= pref_threshold:
                pref_selected = selected
                sel_set = {g for g, _ in pref_selected}
                dense_cols = [i for i in dense_cols if i not in sel_set]

        self.prefilter: PrefilterBank | None = None
        self.prefilter_cols: list[int] = []
        if pref_selected:
            self.prefilter = PrefilterBank(pref_selected)
            self.prefilter_cols = [g for g, _ in pref_selected]

        self.dfa_cols = dense_cols
        # opt-in Pallas union-DFA kernel (matchdfa_pallas.py): admitted
        # BEFORE the cluster build because an admissible plan may
        # RE-PARTITION the union groups (cheapest admissible split under
        # the VMEM budget) — the cluster, the scan-tier fallbacks, and
        # the kernel planes must all see the same group list. Env read
        # once for the same frozen-under-jit reason as
        # bitglush_use_pallas above.
        self._dfa_pallas_plan = None
        self.multidfa_pallas_reason = "off"
        if os.environ.get("LOG_PARSER_TPU_PALLAS_DFA") == "1":
            from log_parser_tpu.ops.matchdfa_pallas import build_dfa_plan

            plan, reason = build_dfa_plan(
                self.multi_groups,
                entries=self._multi_entries or None,
                max_states=self.MULTI_STATE_BUDGET,
            )
            self._dfa_pallas_plan = plan
            self.multidfa_pallas_reason = reason
            if plan is not None:
                self.multi_groups = list(plan.groups)
        self.multidfa_use_pallas = self._dfa_pallas_plan is not None
        # built once: cube() runs under jit, and constructing the cluster
        # there would re-run the table concatenation and bake a duplicate
        # copy of the fused table into every compiled executable.
        # Platform split (r5 A/B, builtin bank, 200k lines): the ONE-wide-
        # gather cluster is how TPU schedules several groups well (PERF.md
        # §7.2: separate steppers cost 1.03 s vs 0.62 s clustered on v5e),
        # but XLA:CPU runs the cluster 2x SLOWER than the same groups as
        # separate scan stages (0.250 vs 0.124 s) — the cluster is a TPU
        # shape; CPU keeps per-group steppers in the fused scan
        self.multi_cluster = (
            MultiDfaCluster(self.multi_groups)
            if self.multi_groups and on_tpu
            else None
        )
        if self.multi_cluster is None:
            for g in self.multi_groups:
                g._table()  # upload now, outside any jit trace (_table)
        self.dfa_bank = DfaBank(
            [bank.columns[i].dfa for i in self.dfa_cols], stride=stride
        )
        self.shiftor = (
            ShiftOrBank(
                [(i, bank.columns[i].exact_seqs) for i in self.shiftor_cols],
                sinks=self.shiftor_sinks,
            )
            if self.shiftor_cols
            else None
        )
        self._jnp = jnp

    @property
    def multi_cols(self) -> list[int]:
        return [c for g in self.multi_groups for c in g.cols]

    def dfa_kernel_active(self, B: int) -> bool:
        """Host-side predicate: will cube() route the union groups
        through the Pallas kernel for a B-row batch (modulo runtime
        faults)? Used by the engine's kernel-tier counters — uses the
        nominal-T admission, same as cube()'s tile re-check for typical
        padded lengths."""
        if not self.multidfa_use_pallas:
            return False
        from log_parser_tpu.ops.matchdfa_pallas import dfa_tile

        return dfa_tile(self._dfa_pallas_plan, B) is not None

    @property
    def dfa_kernel_geometry(self) -> dict | None:
        """The admitted plan's geometry report (states before/after
        minimization, byte classes, plane bytes, chosen split) for the
        engine's /trace/last kernel block; None when no plan."""
        if self._dfa_pallas_plan is None:
            return None
        return self._dfa_pallas_plan.geometry

    @property
    def device_cols(self) -> list[int]:
        return (
            self.shiftor_cols
            + self.dfa_cols
            + self.bitglush_cols
            + self.multi_cols
            + self.prefilter_cols
        )

    def cube(self, lines_tb, lengths):
        """uint8 [T, B] + lengths -> bool [B, n_columns] match cube
        (device-computable columns only; host columns stay False for the
        engine's override pass).

        Both banks advance in ONE fused scan over byte pairs — the scan is
        the serial axis, so composing steppers instead of running two scans
        halves the sequential latency when both tiers are populated."""
        jnp = self._jnp
        B = lengths.shape[0]
        cube = jnp.zeros((B, self.bank.n_columns), dtype=bool)
        steppers = []
        if self.dfa_cols:
            steppers.append(
                (self.dfa_bank.pair_stepper(B, lengths), self.dfa_cols, True)
            )
        if self.shiftor is not None:
            steppers.append(
                (self.shiftor.pair_stepper(B, lengths), self.shiftor_cols, False)
            )
        if self.bitglush is not None:
            use_pallas = False
            if self.bitglush_use_pallas:
                # import only on the opt-in path: the default scan path
                # must not depend on the experimental pallas module
                from log_parser_tpu.ops.bitglush_pallas import (
                    bitglush_hits_pallas,
                    pick_tile,
                )

                use_pallas = pick_tile(B) is not None
            if use_pallas:
                hits = bitglush_hits_pallas(self.bitglush, lines_tb, lengths)
                cube = cube.at[
                    :, jnp.asarray(np.asarray(self.bitglush_cols))
                ].set(self.bitglush.columns_from_hits(hits))
            else:
                steppers.append(
                    (
                        self.bitglush.pair_stepper(B, lengths),
                        self.bitglush_cols,
                        False,
                    )
                )
        multi_pallas: list | None = None
        if self.multi_groups and self.multidfa_use_pallas:
            from log_parser_tpu.ops.matchdfa_pallas import (
                dfa_tile,
                multidfa_reported_pallas,
            )

            if dfa_tile(self._dfa_pallas_plan, B, lines_tb.shape[0]) is not None:
                # any failure on this path — injected kernel fault or a
                # real lowering error — drops the WHOLE batch back onto
                # the XLA scan tier below, parity preserved
                try:
                    from log_parser_tpu.runtime import faults

                    faults.fire("kernel")
                    rep_bg = multidfa_reported_pallas(
                        self._dfa_pallas_plan, lines_tb
                    )
                    multi_pallas = [
                        rep_bg[:, i] != 0
                        for i in range(len(self.multi_groups))
                    ]
                except Exception:
                    self.multidfa_pallas_reason = "fault"
            else:
                self.multidfa_pallas_reason = "no_tile"
        if multi_pallas is not None:
            pass  # reported flags join multi_reps after the fused scan
        elif self.multi_cluster is not None:
            cluster = self.multi_cluster
            steppers.append(
                (cluster.pair_stepper(B, lengths), cluster, False)
            )
        elif self.multi_groups:
            # CPU: per-group steppers in the same fused scan (see the
            # cluster construction note); group order must match
            # self.multi_groups — _multi_contribution zips against it
            for g in self.multi_groups:
                steppers.append(
                    (g.pair_stepper(B, lengths), "multi_group", False)
                )
        if self.prefilter is not None:
            steppers.append(
                (self.prefilter.anyhit_stepper(B, lengths), None, False)
            )
        if not steppers:
            if multi_pallas is not None:
                cube = self._multi_contribution(
                    cube, lines_tb, lengths, multi_pallas
                )
            return cube

        inits = tuple(s[0][0] for s in steppers)
        pairs, ts = pack_byte_pairs(lines_tb)

        def fused_step(carries, xs):
            pair_t, t = xs
            new = tuple(
                s[0][1](c, pair_t[0], pair_t[1], t)
                for s, c in zip(steppers, carries)
            )
            return new, None

        finals, _ = jax.lax.scan(fused_step, inits, (pairs, ts))
        multi_reps: list[jax.Array] = []
        for (stepper, cols, is_dfa), carry in zip(steppers, finals):
            out = stepper[2](carry)
            if cols is None:  # prefilter: hit words -> verify stage
                contrib = self.prefilter.contribution(lines_tb, lengths, out)
                cube = cube.at[
                    :, jnp.asarray(np.asarray(self.prefilter_cols))
                ].set(contrib)
                continue
            if isinstance(cols, MultiDfaCluster):  # per-group reported cols
                multi_reps.extend(out)
                continue
            if isinstance(cols, str):  # "multi_group": one group's carry
                multi_reps.append(out[1])
                continue
            if is_dfa:
                out = out[:, : len(cols)]
            # tier column sets are disjoint today, so .max equals .set;
            # .max keeps the scatter an OR if a column ever lands in two
            # tiers (a round-4 alternative-split experiment did exactly
            # that and was silently masked by .set — PERF.md §9b)
            cube = cube.at[:, jnp.asarray(np.asarray(cols))].max(out)
        if multi_pallas is not None:
            multi_reps.extend(multi_pallas)
        if multi_reps:
            cube = self._multi_contribution(cube, lines_tb, lengths, multi_reps)
        return cube

    def _multi_word_pass(self, lines_tb, lengths, N: int):
        """ONE fused scan advancing every union group's exact out-word
        machinery over ``lines_tb``; returns the per-group hit words."""
        jnp = self._jnp
        steppers = [g.word_stepper(N, lengths) for g in self.multi_groups]
        pairs, ts = pack_byte_pairs(lines_tb)

        def step(carries, xs):
            pair, t = xs
            return tuple(
                st[1](c, pair[0], pair[1], t)
                for st, c in zip(steppers, carries)
            ), None

        finals, _ = jax.lax.scan(
            step, tuple(st[0] for st in steppers), (pairs, ts)
        )
        return [st[2](c) for st, c in zip(steppers, finals)]

    def _multi_contribution(self, cube, lines_tb, lengths, multi_reps):
        """Exact per-pattern bits for every union group via ONE shared
        second pass over the union of flagged rows (matching lines are
        rare), falling back in-program to a full-batch word pass when the
        flagged-row capacity overflows. Sharing one compaction across
        groups keeps the compiled program at two extra scans total,
        whatever the group count."""
        from log_parser_tpu.ops.prefilter import _compact

        jnp = self._jnp
        T, B = lines_tb.shape
        rep_any = multi_reps[0]
        for r in multi_reps[1:]:
            rep_any = rep_any | r
        K = min(B, max(1024, B // 64))
        n_rep, rows, valid = _compact(rep_any, K)

        def scatter(cube, bits_per_group, row_idx, valid_mask):
            safe = jnp.where(valid_mask, row_idx, B)
            for g, bits in zip(self.multi_groups, bits_per_group):
                out = jnp.zeros((B + 1, g.n_cols), bool)
                out = out.at[safe].set(bits & valid_mask[:, None])[:B]
                cube = cube.at[:, jnp.asarray(np.asarray(g.cols))].set(out)
            return cube

        def sparse(cube):
            sub_len = jnp.where(valid, lengths[rows], 0)
            words = self._multi_word_pass(lines_tb[:, rows], sub_len, K)
            bits = [g.unpack(h) for g, h in zip(self.multi_groups, words)]
            return scatter(cube, bits, rows, valid)

        def dense(cube):
            words = self._multi_word_pass(lines_tb, lengths, B)
            for g, h in zip(self.multi_groups, words):
                cube = cube.at[:, jnp.asarray(np.asarray(g.cols))].set(
                    g.unpack(h)
                )
            return cube

        return jax.lax.cond(n_rep <= K, sparse, dense, cube)

    # ------------------------------------------------------------ host carry

    def host_carry(self) -> "CubeHostCarry | None":
        """Resumable host scanner over one growing line, bit-exact with
        :meth:`cube` for device-eligible bytes (streaming follow-mode
        carries it across chunk boundaries instead of rescanning the
        partial tail line per chunk). None when a populated tier has no
        exact host-resumable form — the bit-parallel bitglush chain and
        the AC-prefilter verify stage are pair-scheduled device programs
        whose per-pair state is not byte-resumable; sessions then rescan
        the buffered tail from scratch per frame (exactness of the FINAL
        frame never depends on the carry either way)."""
        if self.bitglush is not None or self.prefilter is not None:
            return None
        if self.shiftor is not None and self.shiftor.host_carry() is None:
            return None
        return CubeHostCarry(self)


class DfaHostCarry:
    """Carried per-regex dense-DFA states for one growing line (host).

    Walks the SAME transition/byte-class/accept tables the device bank
    gathers from (numpy copies, materialized once). The pair-stride
    device path precomposes two single steps through an identity padding
    class, so a byte-at-a-time walk over the true bytes reaches the
    identical final state — padding never moves a dense DFA."""

    def __init__(self, bank: DfaBank):
        r = max(1, bank.n_regexes)
        self.n_regexes = bank.n_regexes
        self._trans = np.asarray(bank.flat_trans).reshape(r, bank.smax, bank.cmax)
        self._accept = np.asarray(bank.flat_accept).reshape(r, bank.smax)
        self._bc = np.asarray(bank.byte_class)
        self._start = np.asarray(bank.start)
        self._r_idx = np.arange(r)
        self.reset()

    def reset(self) -> None:
        self._s = self._start.copy()

    def feed(self, data: bytes) -> None:
        if not self.n_regexes:
            return
        trans, bc, r_idx = self._trans, self._bc, self._r_idx
        s = self._s
        for b in data:
            if b == 0:  # padding-only byte: identity (encode bars content NULs)
                continue
            s = trans[r_idx, s, bc[:, b]]
        self._s = s

    def snapshot_bits(self) -> np.ndarray:
        """bool [n_regexes]: accept-at-end per regex, as of the bytes fed."""
        return self._accept[self._r_idx, self._s][: self.n_regexes]


class MultiDfaHostCarry:
    """Carried union multi-DFA state + exact hit words for one growing
    line (host) — the single-row analogue of the group's ``word_stepper``
    (state, out-word accumulation, accept-at-end OR in snapshot)."""

    def __init__(self, group: MultiDfaBank):
        self.group = group
        self._packed = group._packed_byte_np
        self._byte_rw = np.asarray(group.byte_rw)
        self._out2 = np.asarray(group.out2)
        self._accept_words = np.asarray(group.accept_words)
        self.reset()

    def reset(self) -> None:
        self._s = self.group.start
        self._h = np.zeros(self.group.n_words, dtype=np.uint32)

    def feed(self, data: bytes) -> None:
        s, h = self._s, self._h
        packed, byte_rw, out2 = self._packed, self._byte_rw, self._out2
        for b in data:
            if b == 0:  # padding byte: word_stepper gates it off
                continue
            h = h | out2[s * 2 + int(byte_rw[b])]
            s = int(packed[s * 256 + b]) & MultiDfaBank._STATE_MASK
        self._s, self._h = s, h

    def snapshot_bits(self) -> np.ndarray:
        """bool [n_cols] for this group's columns, in ``group.cols`` order."""
        hw = self._h | self._accept_words[self._s]
        cols = np.arange(self.group.n_cols)
        return ((hw[cols // 32] >> (cols % 32).astype(np.uint32)) & 1).astype(bool)


class CubeHostCarry:
    """Carried scan state for every host-resumable tier of one
    MatcherBanks, over ONE growing line.

    ``feed`` advances the Shift-Or registers, the dense-DFA state
    vector, and each union group's (state, hit-words) carry by the new
    bytes only; ``snapshot_bits`` materializes the cube row the device
    would produce for the line as fed so far — pinned bit-identical to
    ``MatcherBanks.cube`` by tests/test_stream.py. Host-only columns
    stay False (the engine overrides them, same as the device cube)."""

    def __init__(self, matchers):
        self.matchers = matchers
        self.n_columns = matchers.bank.n_columns
        self._shiftor = (
            matchers.shiftor.host_carry() if matchers.shiftor is not None else None
        )
        self._dfa = DfaHostCarry(matchers.dfa_bank) if matchers.dfa_cols else None
        self._multi = [MultiDfaHostCarry(g) for g in matchers.multi_groups]
        self.n_bytes = 0

    def reset(self) -> None:
        if self._shiftor is not None:
            self._shiftor.reset()
        if self._dfa is not None:
            self._dfa.reset()
        for m in self._multi:
            m.reset()
        self.n_bytes = 0

    def feed(self, data: bytes) -> None:
        if not data:
            return
        self.n_bytes += len(data)
        if self._shiftor is not None:
            self._shiftor.feed(data)
        if self._dfa is not None:
            self._dfa.feed(data)
        for m in self._multi:
            m.feed(data)

    def snapshot_bits(self) -> np.ndarray:
        out = np.zeros(self.n_columns, dtype=bool)
        m = self.matchers
        if self._shiftor is not None:
            out[np.asarray(m.shiftor_cols, dtype=np.int64)] = (
                self._shiftor.snapshot_bits()[: len(m.shiftor_cols)]
            )
        if self._dfa is not None:
            out[np.asarray(m.dfa_cols, dtype=np.int64)] = self._dfa.snapshot_bits()
        for g, mc in zip(m.multi_groups, self._multi):
            out[np.asarray(g.cols, dtype=np.int64)] = mc.snapshot_bits()
        return out
