"""Batched automaton execution on device.

Two kernels, both shaped as a ``lax.scan`` over byte columns with one gather
per step — the TPU-native replacement for the reference's per-line
``Matcher.find()`` hot loop (AnalysisService.java:89-113):

- :class:`DfaBank` runs R independent per-regex DFAs over every line
  simultaneously (state tensor ``[B, R]``), producing the full boolean
  match cube the scoring kernel consumes.
- :class:`AcRunner` runs the single combined Aho-Corasick automaton (state
  tensor ``[B]``), producing literal-hit bitmask words per line — the cheap
  prefilter for large pattern libraries.

Scans carry int32 states only; byte columns are consumed in a transposed
``[T, B]`` layout so each scan step is a contiguous slice.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from log_parser_tpu.patterns.regex.ac import AhoCorasick
from log_parser_tpu.patterns.regex.dfa import CompiledDfa


# pair-stride transition tables beyond this many int32 entries fall back to
# single-stride (the table must stay comfortably HBM/VMEM-resident)
PAIR_TABLE_MAX_ENTRIES = 64 << 20


class DfaBank:
    """R packed DFAs executed in lockstep over a line batch.

    The scan is the serial axis of the whole framework, so by default two
    bytes are consumed per step via precomposed pair transition tables
    ``trans2[s, c1, c2] = trans[trans[s, c1], c2]`` over byte classes
    extended with one identity "padding" class (consumed where a position
    is at/past the line end). That halves the sequential scan length for a
    table-size cost of ``(cmax+1)²/cmax`` — gated by
    ``PAIR_TABLE_MAX_ENTRIES`` for very large banks.
    """

    def __init__(self, dfas: list[CompiledDfa], stride: int = 2):
        self.n_regexes = len(dfas)
        r = max(1, self.n_regexes)
        smax = max([d.n_states for d in dfas], default=1)
        cmax = max([d.n_classes for d in dfas], default=1)
        trans = np.zeros((r, smax, cmax), dtype=np.int32)
        byte_class = np.zeros((r, 256), dtype=np.int32)
        accept = np.zeros((r, smax), dtype=bool)
        start = np.zeros(r, dtype=np.int32)
        for i, d in enumerate(dfas):
            trans[i, : d.n_states, : d.n_classes] = d.trans
            byte_class[i] = d.byte_class
            accept[i, : d.n_states] = d.accept_end
            start[i] = d.start
        self.smax, self.cmax = smax, cmax
        # flat layout for a single fused gather per scan step
        self.flat_trans = jnp.asarray(trans.reshape(-1))
        self.byte_class = jnp.asarray(byte_class)
        self.flat_accept = jnp.asarray(accept.reshape(-1))
        self.start = jnp.asarray(start)

        self.pair_stride = (
            stride == 2
            and r * smax * (cmax + 1) * (cmax + 1) <= PAIR_TABLE_MAX_ENTRIES
        )
        if self.pair_stride:
            cpad = cmax + 1  # class cmax = identity padding class
            ext = np.zeros((r, smax, cpad), dtype=np.int32)
            ext[:, :, :cmax] = trans
            ext[:, :, cmax] = np.arange(smax, dtype=np.int32)[None, :]
            # trans2[r, s, c1, c2] = ext[r, ext[r, s, c1], c2]
            trans2 = np.empty((r, smax, cpad, cpad), dtype=np.int32)
            for i in range(r):
                trans2[i] = ext[i][ext[i], :]
            self.cpad = cpad
            self.flat_trans2 = jnp.asarray(trans2.reshape(-1))

        self._jit = jax.jit(self._run)

    def _run(self, lines_tb: jax.Array, lengths: jax.Array) -> jax.Array:
        """lines_tb: uint8 [T, B] (transposed); lengths: int32 [B].
        Returns bool [B, R]."""
        if self.pair_stride:
            return self._run_pair(lines_tb, lengths)
        return self._run_single(lines_tb, lengths)

    def _run_single(self, lines_tb: jax.Array, lengths: jax.Array) -> jax.Array:
        T, B = lines_tb.shape
        R = self.byte_class.shape[0]
        smax, cmax = self.smax, self.cmax
        states0 = jnp.broadcast_to(self.start[None, :], (B, R)).astype(jnp.int32)
        r_off = (jnp.arange(R, dtype=jnp.int32) * smax)[None, :]  # [1, R]

        def step(states, xs):
            bytes_t, t = xs
            cls = jnp.take(self.byte_class, bytes_t.astype(jnp.int32), axis=1)  # [R, B]
            idx = (r_off + states) * cmax + cls.T  # [B, R]
            nxt = jnp.take(self.flat_trans, idx.reshape(-1)).reshape(B, R)
            active = (t < lengths)[:, None]
            return jnp.where(active, nxt, states), None

        ts = jnp.arange(T, dtype=jnp.int32)
        states, _ = jax.lax.scan(step, states0, (lines_tb, ts))
        return jnp.take(self.flat_accept, (r_off + states).reshape(-1)).reshape(B, R)

    def _run_pair(self, lines_tb: jax.Array, lengths: jax.Array) -> jax.Array:
        """Two bytes per scan step through the precomposed pair tables;
        positions at/past each line's end consume the identity class, so no
        per-step boundary branch is needed."""
        T, B = lines_tb.shape
        if T % 2:  # pad to even so every step has a byte pair
            lines_tb = jnp.concatenate(
                [lines_tb, jnp.zeros((1, B), lines_tb.dtype)], axis=0
            )
            T += 1
        R = self.byte_class.shape[0]
        smax, cpad = self.smax, self.cpad
        pad_cls = jnp.int32(self.cmax)
        states0 = jnp.broadcast_to(self.start[None, :], (B, R)).astype(jnp.int32)
        r_off = (jnp.arange(R, dtype=jnp.int32) * smax)[None, :]  # [1, R]

        pairs = lines_tb.reshape(T // 2, 2, B)
        ts = jnp.arange(T // 2, dtype=jnp.int32)

        def step(states, xs):
            pair_t, t = xs  # pair_t: [2, B]
            p0 = 2 * t
            c1 = jnp.take(self.byte_class, pair_t[0].astype(jnp.int32), axis=1)  # [R, B]
            c2 = jnp.take(self.byte_class, pair_t[1].astype(jnp.int32), axis=1)
            c1 = jnp.where((p0 < lengths)[None, :], c1, pad_cls)
            c2 = jnp.where((p0 + 1 < lengths)[None, :], c2, pad_cls)
            idx = ((r_off + states) * cpad + c1.T) * cpad + c2.T  # [B, R]
            states = jnp.take(self.flat_trans2, idx.reshape(-1)).reshape(B, R)
            return states, None

        states, _ = jax.lax.scan(step, states0, (pairs, ts))
        return jnp.take(self.flat_accept, (r_off + states).reshape(-1)).reshape(B, R)

    def match(self, lines_u8: np.ndarray, lengths: np.ndarray) -> np.ndarray:
        """Host entry: uint8 [B, T] padded batch → bool [B, R] match cube."""
        if self.n_regexes == 0:
            return np.zeros((lines_u8.shape[0], 0), dtype=bool)
        out = self._jit(jnp.asarray(lines_u8.T), jnp.asarray(lengths))
        return np.asarray(out)[:, : self.n_regexes]


class AcRunner:
    """Combined Aho-Corasick literal prefilter on device."""

    def __init__(self, ac: AhoCorasick):
        self.ac = ac
        self.n_words = ac.n_words
        self.goto = jnp.asarray(ac.goto)
        self.byte_class = jnp.asarray(ac.byte_class)
        self.out_words = jnp.asarray(ac.out_words.astype(np.uint32))
        self._jit = jax.jit(self._run)

    def _run(self, lines_tb: jax.Array, lengths: jax.Array) -> jax.Array:
        T, B = lines_tb.shape

        def step(carry, xs):
            states, hits = carry
            bytes_t, t = xs
            cls = jnp.take(self.byte_class, bytes_t.astype(jnp.int32))  # [B]
            nxt = self.goto[states, cls]  # [B]
            active = t < lengths
            states = jnp.where(active, nxt, states)
            step_hits = jnp.where(
                active[:, None], jnp.take(self.out_words, states, axis=0), jnp.uint32(0)
            )
            return (states, hits | step_hits), None

        states0 = jnp.zeros(B, dtype=jnp.int32)
        hits0 = jnp.zeros((B, self.n_words), dtype=jnp.uint32)
        ts = jnp.arange(T, dtype=jnp.int32)
        (_, hits), _ = jax.lax.scan(step, (states0, hits0), (lines_tb, ts))
        return hits

    def scan(self, lines_u8: np.ndarray, lengths: np.ndarray) -> np.ndarray:
        """Host entry: uint8 [B, T] → uint32 [B, n_words] literal-hit masks."""
        out = self._jit(jnp.asarray(lines_u8.T), jnp.asarray(lengths))
        return np.asarray(out)
