"""Pallas TPU kernel for the bit-parallel extended Shift-And scan.

The lax.scan implementation (ops/bitglush.py) pays one contiguous
``[256, W]`` mask-row take per byte plus ~15 elementwise ``[B, W]`` ops,
all streaming through HBM — measured ~200 ms for the 74-word builtin
program over the 229k-row config-2 batch. This kernel moves the whole
scan into VMEM:

- the mask-row select becomes MXU one-hot matmuls. The one-hot is built
  TRANSPOSED (``[256, TILE]`` — comparing an iota over sublanes against
  the byte row slice directly, no per-step relayout) and contracted over
  dim 0: ``ohT^T @ M[256, W]``. Mask words ride in four 8-bit planes —
  TPU matmuls run at bfloat16 precision (8-bit mantissa), so 16-bit
  plane values measurably drop bits (0x0101 → 0x0100) while ≤255 values
  are exact. Per-row byte word-ness comes from the same one-hot against
  a ``[256, 1]`` table. The one-hot never exists in HBM — precisely why
  the pre-Pallas one-hot variant was deleted (VERDICT r2 #6: a [B, 256]
  f32 one-hot per scan step is ~235 MB of HBM traffic at this batch);
- the scan state (``D``, ``hits``, previous word-ness) stays in VMEM
  across a ``fori_loop`` over the T byte steps (an unrolled variant
  pushed the Mosaic compile past 9 minutes at T=64; the loop form
  compiles in seconds), so per-tile HBM traffic is the byte tile in and
  the hit words out.

Mosaic-friendly dialect: everything is int32 — no uint32, no bool
vectors, no dynamic lane slicing (each hits an unsupported lowering) —
conditions are 0/1 int32 carried to 0/-1 masks, logical right shifts via
``jax.lax.shift_right_logical``, cross-word shift carry via
``pltpu.roll`` with the lane-0 wraparound masked off.

Semantics are IDENTICAL to BitGlushBank's per-byte *hits* pipeline
(``_hits_pair_stepper``) — same candidate / ε-closure / assertion-gating
/ accept path, verified bit-exactly by tests/test_bitglush.py
(interpreter mode) and the TPU-side parity sweep in
tools/probe_tiers.py. The scan path's default stepper is now the sink
stepper (no hits carry), but on sink-packed banks the hits machinery
(``f_plain``/``f_dollar``/``fin_*``, the ``pos < length`` gates)
remains VALID — sinks only add always-admitting positions that no hit
term reads — and this kernel is its remaining consumer: do not strip
those constants from ``use_sinks`` banks while this path exists.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE_B = 512

_SRL = jax.lax.shift_right_logical

# word bytes: [0-9A-Za-z_]
_WORD_TAB = np.zeros((256, 1), dtype=np.float32)
for _b in range(256):
    _WORD_TAB[_b, 0] = float(
        48 <= _b <= 57 or 65 <= _b <= 90 or 97 <= _b <= 122 or _b == 95
    )


def _build_matmul_masks(bank) -> list[np.ndarray]:
    """Four [256, W] float32 matrices: the mask words split into 8-bit
    planes (exact under the MXU's bf16 mantissa; see module docstring)."""
    bmask = np.asarray(bank.bmask, dtype=np.uint32)  # [256, W]
    return [
        ((bmask >> (8 * p)) & 0xFF).astype(np.float32) for p in range(4)
    ]


def _i32(a) -> np.ndarray:
    return np.asarray(a, dtype=np.uint32).astype(np.int64).astype(np.int32)


def pick_tile(B: int, limit: int | None = None) -> int | None:
    """Largest batch tile ≤ TILE_B that divides ``B`` on a sublane
    multiple (8); None when no usable tile exists. The encoder's
    quarter-pow2 row rungs (640, 896, 1792, ...) are not all multiples
    of 512, so the tile adapts per batch (640 → 320)."""
    tile = min(limit or TILE_B, B)
    while tile >= 8:
        if B % tile == 0 and tile % 8 == 0:
            return tile
        tile -= 8
    return None


def _dotT(ohT: jax.Array, m: jax.Array) -> jax.Array:
    """[256, TILE]^T @ [256, N] -> [TILE, N] on the MXU."""
    return jax.lax.dot_general(
        ohT,
        m,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _kernel(
    bytes_ref,  # [T, TILE] int32 (bytes widened on host)
    lens_ref,  # [TILE, 1] int32
    m0_ref,  # [256, W] float32 mask byte-plane 0 (bits 0-7)
    m1_ref,  # [256, W] float32 mask byte-plane 1
    m2_ref,  # [256, W] float32 mask byte-plane 2
    m3_ref,  # [256, W] float32 mask byte-plane 3
    word_ref,  # [256, 1] float32 word-ness table
    consts_ref,  # [8, W] int32: s_static, k_skip, start, caret_start,
    #              f_plain, f_dollar, f_tb, f_tB
    allow_ref,  # [4, W] int32
    out_ref,  # [TILE, W] int32 hit words
    *,
    T: int,
    W: int,
    skip_run: int,
    has_tb: bool,
    has_dollar: bool,
):
    tile = out_ref.shape[0]
    lens = lens_ref[:]  # [TILE, 1]

    s_static = consts_ref[0, :].reshape(1, W)
    k_skip = consts_ref[1, :].reshape(1, W)
    start = consts_ref[2, :].reshape(1, W)
    caret_start = consts_ref[3, :].reshape(1, W)
    f_plain = consts_ref[4, :].reshape(1, W)
    f_dollar = consts_ref[5, :].reshape(1, W)
    f_tb = consts_ref[6, :].reshape(1, W)
    f_tB = consts_ref[7, :].reshape(1, W)
    a0 = allow_ref[0, :].reshape(1, W)
    a1 = allow_ref[1, :].reshape(1, W)
    a2 = allow_ref[2, :].reshape(1, W)
    a3 = allow_ref[3, :].reshape(1, W)

    row256 = jax.lax.broadcasted_iota(jnp.int32, (256, tile), 0)
    ones31 = jnp.int32(31)
    # -1 everywhere except lane 0: kills pltpu.roll's wraparound so the
    # cross-word shift carry is zero into word 0
    not_lane0 = -jnp.minimum(
        jax.lax.broadcasted_iota(jnp.int32, (tile, W), 1), 1
    )

    def full_mask(cond_i32):
        """0/1 int32 -> 0 / -1 (all-ones) mask."""
        return -cond_i32

    def ge(a, b):  # a >= b as 0/1 int32 (small-magnitude operands)
        return 1 - _SRL(a - b, ones31)

    def shift1(d):
        sh = d << 1
        if W > 1:
            carry = pltpu.roll(_SRL(d, ones31), shift=1, axis=1) & not_lane0
            sh = sh | carry
        return sh

    def body(t, carry):
        d, hits, pw = carry
        b_row = bytes_ref[pl.ds(t, 1), :]  # [1, TILE]
        ohT = (row256 == b_row).astype(jnp.float32)  # [256, TILE]
        cw = _dotT(ohT, word_ref[:]).astype(jnp.int32)  # [TILE, 1] 0/1
        ok = ge(lens, t + 1)  # t < len
        okm = full_mask(ok)

        if has_tb:
            bc = pw ^ cw  # 0/1 boundary
            hits = hits | (d & f_tb & okm & full_mask(bc))
            hits = hits | (d & f_tB & okm & full_mask(1 - bc))

        brow = jnp.zeros((tile, W), jnp.int32)
        for p, mp in enumerate((m0_ref, m1_ref, m2_ref, m3_ref)):
            plane = _dotT(ohT, mp[:])
            brow = brow | (plane.astype(jnp.int32) << (8 * p))

        c = shift1(d) | start
        # ^-anchored starts inject only at the line's first byte; the
        # caret guard bit (bitglush.py _alt_allocs) absorbs shift/skip
        # leaks, so no ``& not_caret`` is needed here either
        c = c | (caret_start & full_mask(ge(jnp.int32(0), t)))
        for _ in range(skip_run):
            c = c | shift1(c & k_skip)

        pwm = full_mask(pw)
        cwm = full_mask(cw)
        allow = (pwm & ((cwm & a3) | (~cwm & a2))) | (
            ~pwm & ((cwm & a1) | (~cwm & a0))
        )
        d_new = (c & allow & brow) | (d & brow & s_static)
        d = (okm & d_new) | (~okm & d)

        hits = hits | (okm & d & f_plain)
        eolm = full_mask(ok * ge(t + 1, lens))  # t == len-1
        if has_dollar:
            hits = hits | (eolm & d & f_dollar)
        if has_tb:
            hits = hits | (eolm & cwm & d & f_tb)
            hits = hits | (eolm & ~cwm & d & f_tB)
        pw = (ok * cw) | ((1 - ok) * pw)
        return d, hits, pw

    carry0 = (
        jnp.zeros((tile, W), jnp.int32),
        jnp.zeros((tile, W), jnp.int32),
        jnp.zeros((tile, 1), jnp.int32),
    )
    _, hits, _ = jax.lax.fori_loop(0, T, body, carry0)
    out_ref[:] = hits


def bitglush_hits_pallas(
    bank,
    lines_tb: jax.Array,
    lengths: jax.Array,
    interpret: bool | None = None,
    tile_b: int | None = None,
) -> jax.Array:
    """Run the bank's whole scan in one Pallas call.

    ``lines_tb``: uint8 [T, B] with B a multiple of TILE_B (the encoder's
    row rungs are); returns uint32 [B, W] accumulated hit words, bit-equal
    to running the pair_stepper scan and keeping its hits carry."""
    T, B = lines_tb.shape
    W = bank.n_words
    if interpret is None:
        # Mosaic needs real TPU hardware; everywhere else (CPU test
        # meshes) the interpreter executes the same kernel semantics
        interpret = jax.default_backend() != "tpu"
    consts = jnp.asarray(
        np.stack(
            [
                _i32(bank.s_static),
                _i32(bank.k_skip),
                _i32(bank.start),
                _i32(bank.caret_start),
                _i32(bank.f_plain),
                _i32(bank.f_dollar),
                _i32(bank.f_tb),
                _i32(bank.f_tB),
            ]
        )
    )
    planes = [jnp.asarray(p) for p in _build_matmul_masks(bank)]
    wordtab = jnp.asarray(_WORD_TAB)
    allow = jnp.asarray(_i32(bank.allow4))
    lens2d = lengths.astype(jnp.int32).reshape(B, 1)

    tile = pick_tile(B, tile_b)
    assert tile is not None, f"no usable tile for batch rows {B}"
    kernel = functools.partial(
        _kernel,
        T=T,
        W=W,
        skip_run=bank.max_skip_run,
        has_tb=bank.has_tb,
        has_dollar=bank.has_dollar,
    )
    lines_i32 = lines_tb.astype(jnp.int32)
    out = pl.pallas_call(
        kernel,
        grid=(B // tile,),
        in_specs=[
            pl.BlockSpec((T, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((256, W), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((256, W), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((256, W), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((256, W), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((256, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((8, W), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((4, W), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (tile, W), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((B, W), jnp.int32),
        interpret=interpret,
    )(lines_i32, lens2d, *planes, wordtab, consts, allow)
    return jax.lax.bitcast_convert_type(out, jnp.uint32)
