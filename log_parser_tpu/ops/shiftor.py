"""Bit-parallel Shift-Or matcher: the exact fast path for literal-shaped
regexes.

Most real failure patterns are literal-bearing ("OutOfMemoryError",
"Connection refused", "status=[45]\\d\\d"): their regexes reduce to a few
fixed-length byte-class sequences (literals.exact_sequences), and substring
search for those needs no DFA at all. Shift-Or packs every sequence of all
such matcher columns into 32-bit bit-planes and advances them with three
vector ops per byte — shift, and, or — plus one 256-row table row-select.
Per step the whole bank costs O(B × W) lane-local integer ops (W = packed
words), independent of how many patterns share the bank: the per-regex
axis of the DFA bank disappears, which is what makes the 10k-pattern
configuration tractable on one chip.

Bit layout per 32-bit word (first-fit packing): a sequence of length m at
offset o uses bits [o, o+m). After each byte: ``D = ((D << 1) & start_clear)
| mask[byte]`` where ``start_clear`` zeroes each sequence's start bit
(fresh shift-in, isolating neighbors) and mask bit (o+j) = 1 iff the byte
CANNOT be position j of the sequence (Shift-Or convention: 0 = still
alive). A sequence has matched at this position iff bit (o+m-1) is 0; hits
accumulate over positions ``t < length``.

The row-select ``mask[byte]`` is a small-table ``jnp.take`` ([256, W]
rows, contiguous — measured 0.17s for the 59-column builtin bank over
200k lines on TPU v5e). A one-hot MXU matmul variant was prototyped and
DELETED (VERDICT r2 #6): with the SHIFTOR_MAX_WORDS gate this tier only
ever runs at <=128 words where the take is already cheap, the matmul
would materialize a [B, 256] f32 one-hot (~235 MB per scan step at the
229k-row config-2 batch — pure HBM traffic), and the very wide banks an
MXU formulation could serve no longer reach Shift-Or at all (they route
to the any-hit prefilter, PERF.md §6).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

ByteSeq = tuple  # tuple[frozenset[int], ...]


@dataclasses.dataclass
class _PackedSeq:
    column: int  # matcher-column this sequence belongs to
    word: int
    offset: int
    length: int


class ShiftOrBank:
    """Packed Shift-Or program for a set of (column, sequences) entries."""

    @staticmethod
    def count_packed_words(
        seq_lengths, budget: int | None = None
    ) -> int:
        """First-fit word count for sequences of these lengths — THE
        packing rule of ``__init__`` (single source: tier gates that
        estimate the word cost must agree with the real packer). With a
        ``budget``, returns early once the count exceeds it."""
        word_fill: list[int] = []
        for m in seq_lengths:
            w = next(
                (i for i, used in enumerate(word_fill) if used + m <= 32),
                None,
            )
            if w is None:
                word_fill.append(0)
                if budget is not None and len(word_fill) > budget:
                    return len(word_fill)
            word_fill[w if w is not None else -1] += m
        return len(word_fill)

    def __init__(self, column_seqs: list[tuple[int, tuple[ByteSeq, ...]]]):
        self.columns = [c for c, _ in column_seqs]
        packed: list[_PackedSeq] = []
        word_fill: list[int] = []
        for col, seqs in column_seqs:
            for seq in seqs:
                m = len(seq)
                w = next(
                    (i for i, used in enumerate(word_fill) if used + m <= 32), None
                )
                if w is None:
                    w = len(word_fill)
                    word_fill.append(0)
                packed.append(_PackedSeq(col, w, word_fill[w], m))
                word_fill[w] += m
        self.n_words = max(1, len(word_fill))
        self.n_seqs = len(packed)
        self._packed = packed

        # mask[c, w]: bit (o+j) = 1 iff byte c not allowed at position j;
        # unused bits are always-1 (inert)
        mask = np.full((256, self.n_words), 0xFFFFFFFF, dtype=np.uint32)
        start_clear = np.full(self.n_words, 0xFFFFFFFF, dtype=np.uint32)
        flat_seqs = [s for _, seqs in column_seqs for s in seqs]
        assert len(flat_seqs) == len(packed)
        for ps, seq in zip(packed, flat_seqs):
            start_clear[ps.word] &= np.uint32(0xFFFFFFFF) ^ np.uint32(1 << ps.offset)
            for j, byteset in enumerate(seq):
                bit = np.uint32(1 << (ps.offset + j))
                for c in byteset:
                    mask[c, ps.word] &= ~bit
        self.mask = jnp.asarray(mask)
        self.start_clear = jnp.asarray(start_clear)

        end_mask = np.zeros(self.n_words, dtype=np.uint32)
        for ps in packed:
            end_mask[ps.word] |= np.uint32(1 << (ps.offset + ps.length - 1))
        self.end_mask = jnp.asarray(end_mask)

        # per-sequence extraction: hits[:, word] >> bit & 1 -> column OR
        self.seq_word = np.asarray([ps.word for ps in packed], dtype=np.int32)
        self.seq_bit = np.asarray(
            [ps.offset + ps.length - 1 for ps in packed], dtype=np.int32
        )
        # map sequences onto output slots (position of column in self.columns)
        slot_of_col = {c: i for i, c in enumerate(self.columns)}
        self.seq_slot = np.asarray(
            [slot_of_col[ps.column] for ps in packed], dtype=np.int32
        )

    # --------------------------------------------------------------- device

    def _row_select(self, bytes_t: jax.Array) -> jax.Array:
        return jnp.take(self.mask, bytes_t.astype(jnp.int32), axis=0)  # [B, W]

    def pair_stepper(self, B: int, lengths: jax.Array):
        """(init, step(carry, b1, b2, t), finish) — composable with the DFA
        bank's stepper into one fused scan over byte pairs."""
        select = self._row_select
        d0 = jnp.full((B, self.n_words), 0xFFFFFFFF, dtype=jnp.uint32)
        hits0 = jnp.zeros((B, self.n_words), dtype=jnp.uint32)

        def one(carry, b, pos_ok):
            d, hits = carry
            m = select(b)
            d_new = ((d << 1) & self.start_clear[None, :]) | m
            active = pos_ok[:, None]
            hits = jnp.where(
                active, hits | ((~d_new) & self.end_mask[None, :]), hits
            )
            return jnp.where(active, d_new, d), hits

        def step(carry, b1, b2, t):
            p0 = 2 * t
            carry = one(carry, b1, p0 < lengths)
            return one(carry, b2, p0 + 1 < lengths)

        def finish(carry):
            _, hits = carry
            seq_hit = (
                jnp.take(hits, jnp.asarray(self.seq_word), axis=1)
                >> jnp.asarray(self.seq_bit)[None, :]
            ) & 1  # [B, n_seqs]
            out = jnp.zeros((B, max(1, len(self.columns))), dtype=jnp.int32)
            out = out.at[:, jnp.asarray(self.seq_slot)].max(
                seq_hit.astype(jnp.int32)
            )
            return out.astype(bool)

        return (d0, hits0), step, finish

    def _run(
        self, lines_tb: jax.Array, lengths: jax.Array
    ) -> jax.Array:
        """lines_tb: uint8 [T, B]; returns bool [B, n_columns_in_bank]."""
        from log_parser_tpu.ops.match import pack_byte_pairs

        T, B = lines_tb.shape
        init, step, finish = self.pair_stepper(B, lengths)
        pairs, ts = pack_byte_pairs(lines_tb)
        carry, _ = jax.lax.scan(
            lambda c, xs: (step(c, xs[0][0], xs[0][1], xs[1]), None),
            init,
            (pairs, ts),
        )
        return finish(carry)
