"""Bit-parallel Shift-Or matcher: the exact fast path for literal-shaped
regexes.

Most real failure patterns are literal-bearing ("OutOfMemoryError",
"Connection refused", "status=[45]\\d\\d"): their regexes reduce to a few
fixed-length byte-class sequences (literals.exact_sequences), and substring
search for those needs no DFA at all. Shift-Or packs every sequence of all
such matcher columns into 32-bit bit-planes and advances them with three
vector ops per byte — shift, and, or — plus one 256-row table row-select.
Per step the whole bank costs O(B × W) lane-local integer ops (W = packed
words), independent of how many patterns share the bank: the per-regex
axis of the DFA bank disappears, which is what makes the 10k-pattern
configuration tractable on one chip.

Bit layout per 32-bit word (first-fit packing): a sequence of length m at
offset o uses bits [o, o+m). After each byte: ``D = ((D << 1) & start_clear)
| mask[byte]`` where ``start_clear`` zeroes each sequence's start bit
(fresh shift-in, isolating neighbors) and mask bit (o+j) = 1 iff the byte
CANNOT be position j of the sequence (Shift-Or convention: 0 = still
alive). A sequence has matched at this position iff bit (o+m-1) is 0; hits
accumulate over positions ``t < length``.

Sequences longer than 32 positions ride *cross-word chains*: they take a
word-aligned run of whole words, and the shift propagates bit 31 of each
chain word into bit 0 of the next (``cont_mask`` marks receiving words —
only there is the shift's incoming 0 replaced by the carry, everywhere
else bit 0 is either a start bit, restarted by ``start_clear``, or inert).
The tail word's remaining bits stay open to first-fit short sequences.
Chains let DFA-less literal columns with >32-position sequences (this
tier is their only device path) stay exact instead of falling to host.

The row-select ``mask[byte]`` is a small-table ``jnp.take`` ([256, W]
rows, contiguous). This exact shape — one-level takes from a 256-row
table indexed by the raw byte, minimal row width — is a measured local
optimum. Two families of "structural" improvements were built, measured
SLOWER on TPU v5e, and deleted (tools/probe_paircompose.py, PERF.md §9):

- *pair-composed recurrences* (``D2 = (D<<2) & SC2 | M2[b1,b2]``,
  halving the serial per-byte chain): every variant lost because take
  cost scales with gathered row width, not take count — the composed
  tables need 1.5-2x the row words (0.130-0.242s vs 0.089s for the
  builtin bank, 229k lines);
- *byte-class indirection* (``[C², 2W]`` tables behind a ``[256]``
  class map, C=62): any dependent two-level gather inside the scan adds
  ~3ms/step at this batch — 0.24-0.29s even with 40KB tables.

A one-hot MXU matmul variant was likewise prototyped and DELETED
(VERDICT r2 #6): with the SHIFTOR_MAX_WORDS gate this tier only ever
runs at <=128 words where the take is already cheap, the matmul would
materialize a [B, 256] f32 one-hot (~235 MB per scan step at the
229k-row config-2 batch — pure HBM traffic), and the very wide banks an
MXU formulation could serve no longer reach Shift-Or at all (they route
to the any-hit prefilter, PERF.md §6).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

ByteSeq = tuple  # tuple[frozenset[int], ...]


class ShiftOrBank:
    """Packed Shift-Or program for a set of (column, sequences) entries."""

    @staticmethod
    def _plan(seq_lengths, budget: int | None = None):
        """Packing plan — THE single source of the packing rule (tier
        gates that estimate word cost must agree with ``__init__``).
        Sequences >32 take fresh word-aligned runs (cross-word chains)
        whose tail remainder stays open to first-fit; sequences <=32
        first-fit within any word. Returns (global start bits, n_words);
        with a ``budget``, bails early once the count exceeds it."""
        starts: list[int] = []
        word_fill: list[int] = []
        for m in seq_lengths:
            if m > 32:
                w0 = len(word_fill)
                nw = (m + 31) // 32
                starts.append(w0 * 32)
                word_fill.extend([32] * (nw - 1))
                word_fill.append(m - 32 * (nw - 1))
            else:
                w = next(
                    (i for i, used in enumerate(word_fill) if used + m <= 32),
                    None,
                )
                if w is None:
                    w = len(word_fill)
                    word_fill.append(0)
                starts.append(w * 32 + word_fill[w])
                word_fill[w] += m
            if budget is not None and len(word_fill) > budget:
                return starts, len(word_fill)
        return starts, max(1, len(word_fill))

    @classmethod
    def count_packed_words(cls, seq_lengths, budget: int | None = None) -> int:
        return cls._plan(seq_lengths, budget)[1]

    def __init__(self, column_seqs: list[tuple[int, tuple[ByteSeq, ...]]]):
        self.columns = [c for c, _ in column_seqs]
        flat = [(col, seq) for col, seqs in column_seqs for seq in seqs]
        starts, self.n_words = self._plan([len(seq) for _, seq in flat])
        self.n_seqs = len(flat)

        # mask[c, w]: bit (o+j) = 1 iff byte c not allowed at position j;
        # unused bits are always-1 (inert)
        mask = np.full((256, self.n_words), 0xFFFFFFFF, dtype=np.uint32)
        start_clear = np.full(self.n_words, 0xFFFFFFFF, dtype=np.uint32)
        cont_mask = np.zeros(self.n_words, dtype=np.uint32)
        end_mask = np.zeros(self.n_words, dtype=np.uint32)
        for (col, seq), g in zip(flat, starts):
            start_clear[g // 32] &= ~np.uint32(1 << (g % 32))
            for j, byteset in enumerate(seq):
                p = g + j
                bit = np.uint32(1 << (p % 32))
                for c in byteset:
                    # byte 0 is padding-only (NUL-bearing lines are
                    # needs_host — encode): keep mask[0] all-ones so
                    # pad0_transparent holds for every bank
                    if c != 0:
                        mask[c, p // 32] &= ~bit
            # chain continuation words receive bit 31 of their predecessor
            for w in range(g // 32 + 1, (g + len(seq) - 1) // 32 + 1):
                cont_mask[w] |= np.uint32(1)
            e = g + len(seq) - 1
            end_mask[e // 32] |= np.uint32(1 << (e % 32))
        self.mask = jnp.asarray(mask)
        self.start_clear = jnp.asarray(start_clear)
        self.end_mask = jnp.asarray(end_mask)
        self.has_chains = bool(cont_mask.any())
        self.cont_mask = jnp.asarray(cont_mask)
        # The hit term is ``hits |= (~d_new) & end_mask`` and
        # ``d_new = sh | mask[byte]`` — so a padding byte (0) can only
        # contribute a hit if some sequence's END position admits NUL
        # (its ``mask[0]`` bit is 0). When every end bit is set in
        # ``mask[0]`` the per-byte ``pos < length`` gating is a provable
        # no-op and the stepper drops it (two [B, W] selects per byte).
        # The builder above strips byte 0 from every byteset (NUL-bearing
        # lines are needs_host — encode.py), so today this is True for
        # every bank; the flag still computes the sound condition and the
        # gated stepper path is retained as the correctness fallback
        # should a future bank ever admit the padding byte.
        self.pad0_transparent = bool(((mask[0] & end_mask) == end_mask).all())

        # host copies for probes/serialization (tools/probe_paircompose.py)
        self._np = {"mask": mask, "start_clear": start_clear,
                    "end_mask": end_mask, "cont_mask": cont_mask}

        # per-sequence extraction: hits[:, word] >> bit & 1 -> column OR
        ends = [g + len(seq) - 1 for (_, seq), g in zip(flat, starts)]
        self.seq_word = np.asarray([e // 32 for e in ends], dtype=np.int32)
        self.seq_bit = np.asarray([e % 32 for e in ends], dtype=np.int32)
        # map sequences onto output slots (position of column in self.columns)
        slot_of_col = {c: i for i, c in enumerate(self.columns)}
        self.seq_slot = np.asarray(
            [slot_of_col[col] for col, _ in flat], dtype=np.int32
        )

    # --------------------------------------------------------------- device

    def _row_select(self, bytes_t: jax.Array) -> jax.Array:
        return jnp.take(self.mask, bytes_t.astype(jnp.int32), axis=0)  # [B, W]

    def pair_stepper(self, B: int, lengths: jax.Array):
        """(init, step(carry, b1, b2, t), finish) — composable with the DFA
        bank's stepper into one fused scan over byte pairs."""
        select = self._row_select
        d0 = jnp.full((B, self.n_words), 0xFFFFFFFF, dtype=jnp.uint32)
        hits0 = jnp.zeros((B, self.n_words), dtype=jnp.uint32)

        def one(carry, b, pos_ok):
            d, hits = carry
            m = select(b)
            sh = (d << 1) & self.start_clear[None, :]
            if self.has_chains:
                # bit 31 of each chain word flows into bit 0 of the next
                cr = jnp.concatenate(
                    [jnp.zeros_like(d[:, :1]), d[:, :-1] >> 31], axis=1
                )
                sh = sh | (cr & self.cont_mask[None, :])
            d_new = sh | m
            if self.pad0_transparent:
                # padding bytes saturate d_new to all-ones (mask[0] is
                # all-ones), so end-bit hits past a line's end are
                # impossible — no gating needed
                hits = hits | ((~d_new) & self.end_mask[None, :])
                return d_new, hits
            active = pos_ok[:, None]
            hits = jnp.where(
                active, hits | ((~d_new) & self.end_mask[None, :]), hits
            )
            return jnp.where(active, d_new, d), hits

        def step(carry, b1, b2, t):
            if self.pad0_transparent:
                carry = one(carry, b1, None)
                return one(carry, b2, None)
            p0 = 2 * t
            carry = one(carry, b1, p0 < lengths)
            return one(carry, b2, p0 + 1 < lengths)

        def finish(carry):
            _, hits = carry
            seq_hit = (
                jnp.take(hits, jnp.asarray(self.seq_word), axis=1)
                >> jnp.asarray(self.seq_bit)[None, :]
            ) & 1  # [B, n_seqs]
            out = jnp.zeros((B, max(1, len(self.columns))), dtype=jnp.int32)
            out = out.at[:, jnp.asarray(self.seq_slot)].max(
                seq_hit.astype(jnp.int32)
            )
            return out.astype(bool)

        return (d0, hits0), step, finish

    def _run(
        self, lines_tb: jax.Array, lengths: jax.Array
    ) -> jax.Array:
        """lines_tb: uint8 [T, B]; returns bool [B, n_columns_in_bank]."""
        from log_parser_tpu.ops.match import pack_byte_pairs

        T, B = lines_tb.shape
        init, step, finish = self.pair_stepper(B, lengths)
        pairs, ts = pack_byte_pairs(lines_tb)
        carry, _ = jax.lax.scan(
            lambda c, xs: (step(c, xs[0][0], xs[0][1], xs[1]), None),
            init,
            (pairs, ts),
        )
        return finish(carry)
