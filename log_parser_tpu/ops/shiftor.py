"""Bit-parallel Shift-Or matcher: the exact fast path for literal-shaped
regexes.

Most real failure patterns are literal-bearing ("OutOfMemoryError",
"Connection refused", "status=[45]\\d\\d"): their regexes reduce to a few
fixed-length byte-class sequences (literals.exact_sequences), and substring
search for those needs no DFA at all. Shift-Or packs every sequence of all
such matcher columns into 32-bit bit-planes and advances them with three
vector ops per byte — shift, and, or — plus one 256-row table row-select.
Per step the whole bank costs O(B × W) lane-local integer ops (W = packed
words), independent of how many patterns share the bank: the per-regex
axis of the DFA bank disappears, which is what makes the 10k-pattern
configuration tractable on one chip.

Bit layout per 32-bit word (first-fit packing): a sequence of length m at
offset o uses bits [o, o+m). After each byte: ``D = ((D << 1) & start_clear)
| mask[byte]`` where ``start_clear`` zeroes each sequence's start bit
(fresh shift-in, isolating neighbors) and mask bit (o+j) = 1 iff the byte
CANNOT be position j of the sequence (Shift-Or convention: 0 = still
alive). A sequence has matched at this position iff bit (o+m-1) is 0; hits
accumulate over positions ``t < length``.

Sequences longer than 32 positions ride *cross-word chains*: they take a
word-aligned run of whole words, and the shift propagates bit 31 of each
chain word into bit 0 of the next (``cont_mask`` marks receiving words —
only there is the shift's incoming 0 replaced by the carry, everywhere
else bit 0 is either a start bit, restarted by ``start_clear``, or inert).
The tail word's remaining bits stay open to first-fit short sequences.
Chains let DFA-less literal columns with >32-position sequences (this
tier is their only device path) stay exact instead of falling to host.

The row-select ``mask[byte]`` is a small-table ``jnp.take`` ([256, W]
rows, contiguous): one-level takes from a 256-row table indexed by the
raw byte, minimal row width. The default stepper is *pair-composed*: two
independent takes from that SAME table feed one shift-by-two update
(``_composed_pair_stepper`` — the m-term is composed with vector ops at
runtime, so no table grows), with sticky *sink* bits replacing the
per-byte hit check. Two families of table-side "improvements" were
built, measured SLOWER on TPU v5e, and deleted
(tools/probe_paircompose.py, PERF.md §9b):

- *m-term-precomposed tables* (``D2 = (D<<2) & SC2 | M2[b1,b2]`` with
  ``M2`` materialized per byte pair): every variant lost because take
  cost scales with gathered row width and table locality, not take
  count — the composed tables need 1.5-2x the row words or 65536 rows
  (0.130-0.242s vs 0.089s for the builtin bank, 229k lines). Composing
  the recurrence is fine (it is exact and now the default); composing
  the TABLE is what loses;
- *byte-class indirection* (``[C², 2W]`` tables behind a ``[256]``
  class map, C=62): any dependent two-level gather inside the scan adds
  ~3ms/step at this batch — 0.24-0.29s even with 40KB tables.

A one-hot MXU matmul variant was likewise prototyped and DELETED
(VERDICT r2 #6): with the SHIFTOR_MAX_WORDS gate this tier only ever
runs at <=128 words where the take is already cheap, the matmul would
materialize a [B, 256] f32 one-hot (~235 MB per scan step at the
229k-row config-2 batch — pure HBM traffic), and the very wide banks an
MXU formulation could serve no longer reach Shift-Or at all (they route
to the any-hit prefilter, PERF.md §6).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

ByteSeq = tuple  # tuple[frozenset[int], ...]


class FirstFitPacker:
    """Incremental first-fit packing state — the same rule as
    :func:`first_fit_plan` (which is implemented on top of it), exposed
    statefully so tier admission gates can price one candidate in
    O(candidate + words) instead of repacking every already-admitted
    allocation per candidate (r5 code review: the full repack made
    MatcherBanks construction quadratic on wide libraries)."""

    __slots__ = ("word_fill",)

    def __init__(self) -> None:
        self.word_fill: list[int] = []

    def add(self, alloc: int) -> int:
        """Pack one allocation; returns its start bit.  Allocations ≤ 32
        bits first-fit within any word, never straddling one (the
        chainless-shift invariant both banks rely on); larger
        allocations take word-aligned runs of whole words whose tail
        remainder stays open to first-fit."""
        word_fill = self.word_fill
        if alloc > 32:
            w0 = len(word_fill)
            nw = (alloc + 31) // 32
            word_fill.extend([32] * (nw - 1))
            word_fill.append(alloc - 32 * (nw - 1))
            return w0 * 32
        w = next(
            (i for i, used in enumerate(word_fill) if used + alloc <= 32),
            None,
        )
        if w is None:
            w = len(word_fill)
            word_fill.append(0)
        start = w * 32 + word_fill[w]
        word_fill[w] += alloc
        return start

    @property
    def n_words(self) -> int:
        return len(self.word_fill)

    def clone(self) -> "FirstFitPacker":
        p = FirstFitPacker()
        p.word_fill = list(self.word_fill)
        return p


def first_fit_plan(allocs, budget: int | None = None):
    """First-fit word-packing plan shared by the two bit tiers — THE
    single source of the packing rule (tier gates that estimate word
    cost must agree with the bank constructors; both route through
    :class:`FirstFitPacker`).  Returns (per-allocation start bits,
    n_words); with a ``budget``, bails early once the word count
    exceeds it."""
    packer = FirstFitPacker()
    starts: list[int] = []
    for alloc in allocs:
        starts.append(packer.add(alloc))
        if budget is not None and packer.n_words > budget:
            return starts, packer.n_words
    return starts, max(1, packer.n_words)


class ShiftOrBank:
    """Packed Shift-Or program for a set of (column, sequences) entries."""

    @staticmethod
    def _plan(seq_lengths, budget: int | None = None, sinks: bool = True):
        """Packing plan via :func:`first_fit_plan`. With ``sinks`` every
        sequence's allocation is its length + 2 *sink* bits (the sticky
        match flags the pair-composed stepper reads at scan end);
        without, exactly its length (the hits-carry steppers need no
        extra state)."""
        return first_fit_plan(
            ((m + 2 if sinks else m) for m in seq_lengths), budget
        )

    @classmethod
    def count_packed_words(
        cls, seq_lengths, budget: int | None = None, sinks: bool = True
    ) -> int:
        return cls._plan(seq_lengths, budget, sinks=sinks)[1]

    def __init__(
        self,
        column_seqs: list[tuple[int, tuple[ByteSeq, ...]]],
        sinks: bool = True,
    ):
        """``sinks`` picks the bank layout AND the stepper family:
        True (the CPU default) packs two sticky sink bits per sequence
        and steps with the pair-composed sink recurrence — the fastest
        form on hosts, where the halved serial chain dominates. False
        (the TPU default, chosen by MatcherBanks) packs sequences
        bare and accumulates hits per byte — on v5e the take cost
        scales with the gathered row width, so the ~12% narrower table
        beats the halved chain (tools/probe_sink_ab.py, PERF.md §9d:
        0.082 s vs 0.112 s at config-2 shapes; on CPU the same A/B
        reads 0.253 s vs 0.151 s the other way)."""
        self.columns = [c for c, _ in column_seqs]
        self.sinks = sinks
        flat = [(col, seq) for col, seqs in column_seqs for seq in seqs]
        starts, self.n_words = self._plan(
            [len(seq) for _, seq in flat], sinks=sinks
        )
        self.n_seqs = len(flat)

        # mask[c, w]: bit (o+j) = 1 iff byte c not allowed at position j;
        # unused bits are always-1 (inert). Each sequence's allocation
        # ends with two *sink* bits that admit EVERY byte (padding
        # included): once the end position goes alive, the following
        # shifts park the match in a sink, where the pair-composed
        # stepper's persistence term keeps it for the rest of the scan —
        # two sinks because a pair step shifts by two, so completions of
        # either parity land in one of them.
        mask = np.full((256, self.n_words), 0xFFFFFFFF, dtype=np.uint32)
        start_clear = np.full(self.n_words, 0xFFFFFFFF, dtype=np.uint32)
        cont_mask = np.zeros(self.n_words, dtype=np.uint32)
        end_mask = np.zeros(self.n_words, dtype=np.uint32)
        sink_mask = np.zeros(self.n_words, dtype=np.uint32)
        snk_word: list[int] = []
        snk_bit: list[int] = []
        for (col, seq), g in zip(flat, starts):
            start_clear[g // 32] &= ~np.uint32(1 << (g % 32))
            for j, byteset in enumerate(seq):
                p = g + j
                bit = np.uint32(1 << (p % 32))
                for c in byteset:
                    # byte 0 is padding-only (NUL-bearing lines are
                    # needs_host — encode): keep mask[0] all-ones so
                    # pad0_transparent holds for every bank
                    if c != 0:
                        mask[c, p // 32] &= ~bit
            if sinks:
                for p in (g + len(seq), g + len(seq) + 1):  # the two sinks
                    bit = np.uint32(1 << (p % 32))
                    mask[:, p // 32] &= ~bit
                    sink_mask[p // 32] |= bit
                    snk_word.append(p // 32)
                    snk_bit.append(p % 32)
            # chain continuation words receive shift carry from their
            # predecessor (the allocation spans the sequence positions
            # plus, in sink layout, the 2 sink bits)
            last_p = g + len(seq) - 1 + (2 if sinks else 0)
            for w in range(g // 32 + 1, last_p // 32 + 1):
                cont_mask[w] |= np.uint32(1)
            e = g + len(seq) - 1
            end_mask[e // 32] |= np.uint32(1 << (e % 32))
        self.mask = jnp.asarray(mask)
        self.start_clear = jnp.asarray(start_clear)
        self.end_mask = jnp.asarray(end_mask)
        self.has_chains = bool(cont_mask.any())
        self.cont_mask = jnp.asarray(cont_mask)
        # pair-composed constants. One Shift-Or step is the affine map
        # f(D) = s1(D) & sc | m[b] (s1 = chain-aware shift); s1 relocates
        # bits, so it distributes over & and |, and two steps compose
        # EXACTLY into one shift-by-two step:
        #   f2(f1(D)) = s2(D) & C2 | (s1(m1) & sc | m2)
        # with C2 = s1(sc) & sc precomputed — same-width rows from the
        # same 256-row table, so take cost is unchanged while the vector
        # chain (and the serial depth) nearly halves. The earlier
        # pair-composition attempts that measured SLOWER (docstring
        # above) precomposed the m-term into 65536-row or wider tables —
        # the loss was table locality / row width, not the algebra.
        np1 = lambda x: (  # noqa: E731 — numpy s1 on [..., W] constants
            (x << 1).astype(np.uint32)
            | (
                np.concatenate(
                    [np.zeros_like(x[..., :1]), x[..., :-1] >> 31], axis=-1
                )
                & cont_mask
            )
        )
        if sinks:
            self.c2 = jnp.asarray(np1(start_clear) & start_clear)
            self.cont2_mask = jnp.asarray(cont_mask * np.uint32(3))
            self.not_sink = jnp.asarray(~sink_mask)
            # the virtual padding pair finish() applies for full-width
            # rows: both bytes are padding, so the m-term is a constant
            self.pad_m12 = jnp.asarray(np1(mask[0]) & start_clear | mask[0])
        self.snk_word = np.asarray(snk_word, dtype=np.int32)
        self.snk_bit = np.asarray(snk_bit, dtype=np.int32)
        # The hit term is ``hits |= (~d_new) & end_mask`` and
        # ``d_new = sh | mask[byte]`` — so a padding byte (0) can only
        # contribute a hit if some sequence's END position admits NUL
        # (its ``mask[0]`` bit is 0). When every end bit is set in
        # ``mask[0]`` the per-byte ``pos < length`` gating is a provable
        # no-op and the stepper drops it (two [B, W] selects per byte).
        # The builder above strips byte 0 from every byteset (NUL-bearing
        # lines are needs_host — encode.py), so today this is True for
        # every bank; the flag still computes the sound condition and the
        # gated stepper path is retained as the correctness fallback
        # should a future bank ever admit the padding byte.
        self.pad0_transparent = bool(((mask[0] & end_mask) == end_mask).all())

        # host copies for probes/serialization (tools/probe_paircompose.py)
        self._np = {"mask": mask, "start_clear": start_clear,
                    "end_mask": end_mask, "cont_mask": cont_mask}

        # per-sequence extraction: hits[:, word] >> bit & 1 -> column OR
        ends = [g + len(seq) - 1 for (_, seq), g in zip(flat, starts)]
        self.seq_word = np.asarray([e // 32 for e in ends], dtype=np.int32)
        self.seq_bit = np.asarray([e % 32 for e in ends], dtype=np.int32)
        # map sequences onto output slots (position of column in self.columns)
        slot_of_col = {c: i for i, c in enumerate(self.columns)}
        self.seq_slot = np.asarray(
            [slot_of_col[col] for col, _ in flat], dtype=np.int32
        )
        # two sink entries per sequence, in the same flat order
        self.snk_slot = np.repeat(self.seq_slot, 2)

    # --------------------------------------------------------------- device

    def _row_select(self, bytes_t: jax.Array) -> jax.Array:
        return jnp.take(self.mask, bytes_t.astype(jnp.int32), axis=0)  # [B, W]

    def _s1(self, x: jax.Array) -> jax.Array:
        """Chain-aware shift by one position (device)."""
        sh = x << 1
        if self.has_chains:
            carry = jnp.concatenate(
                [jnp.zeros_like(x[:, :1]), x[:, :-1] >> 31], axis=1
            )
            sh = sh | (carry & self.cont_mask[None, :])
        return sh

    def _s2(self, x: jax.Array) -> jax.Array:
        """Chain-aware shift by two positions (device)."""
        sh = x << 2
        if self.has_chains:
            carry = jnp.concatenate(
                [jnp.zeros_like(x[:, :1]), x[:, :-1] >> 30], axis=1
            )
            sh = sh | (carry & self.cont2_mask[None, :])
        return sh

    def pair_stepper(self, B: int, lengths: jax.Array):
        """(init, step(carry, b1, b2, t), finish). On the (universal
        today) ``pad0_transparent`` banks, sink layout steps with the
        pair-composed sink recurrence — per byte PAIR, two independent
        row takes and one composed update, no per-byte hit term, half
        the serial depth; matches park in sticky sink bits that
        ``finish`` reads once (after one virtual padding pair, so rows
        that fill every scanned byte sweep their last-byte completions
        in). Bare layout steps per byte with an ungated hits carry
        (sound because a padding byte sets every end bit in ``d``, so
        past-end positions can never contribute a hit). Non-transparent
        banks keep the gated per-byte path."""
        if self.pad0_transparent:
            if self.sinks:
                return self._composed_pair_stepper(B)
            return self._ungated_hits_stepper(B)
        return self._perbyte_pair_stepper(B, lengths)

    def _ungated_hits_stepper(self, B: int):
        """Per-byte hits accumulation with NO length gating — the TPU
        form: one [256, W] row take plus four [B, W] vector ops per
        byte on the narrowest possible rows (no sink bits). The carry
        holds the COMPLEMENT (``nh`` — 1 = never hit): the update
        ``nh & (d | ~e)`` is one op cheaper per byte than
        ``hits | (~d & e)`` because ``~e`` is a precomputed constant;
        one inversion at ``finish`` recovers the hit words."""
        select = self._row_select
        sc = self.start_clear[None, :]
        not_e = (~self.end_mask)[None, :]
        d0 = jnp.full((B, self.n_words), 0xFFFFFFFF, dtype=jnp.uint32)
        nh0 = jnp.full((B, self.n_words), 0xFFFFFFFF, dtype=jnp.uint32)

        def one(carry, b):
            d, nh = carry
            d = (self._s1(d) & sc) | select(b)
            return d, nh & (d | not_e)

        def step(carry, b1, b2, t):
            return one(one(carry, b1), b2)

        def finish(carry):
            _, nh = carry
            return self.columns_from_hits(~nh & self.end_mask[None, :])

        return (d0, nh0), step, finish

    def columns_from_hits(self, hits: jax.Array) -> jax.Array:
        """uint32 [N, W] accumulated hit words -> bool [N, n_columns]."""
        N = hits.shape[0]
        seq_hit = (
            jnp.take(hits, jnp.asarray(self.seq_word), axis=1)
            >> jnp.asarray(self.seq_bit)[None, :]
        ) & 1  # [N, n_seqs]
        out = jnp.zeros((N, max(1, len(self.columns))), dtype=jnp.int32)
        out = out.at[:, jnp.asarray(self.seq_slot)].max(
            seq_hit.astype(jnp.int32)
        )
        return out.astype(bool)

    def _composed_pair_stepper(self, B: int):
        select = self._row_select
        d0 = jnp.full((B, self.n_words), 0xFFFFFFFF, dtype=jnp.uint32)

        def step(d, b1, b2, t):
            m1 = select(b1)
            m2 = select(b2)
            m12 = (self._s1(m1) & self.start_clear[None, :]) | m2
            cand = (self._s2(d) & self.c2[None, :]) | m12
            # sinks (0 = a match parked there) persist; everywhere else
            # the composed update stands
            return cand & (d | self.not_sink[None, :])

        def finish(d):
            cand = (self._s2(d) & self.c2[None, :]) | self.pad_m12[None, :]
            d = cand & (d | self.not_sink[None, :])
            alive = (
                jnp.take(d, jnp.asarray(self.snk_word), axis=1)
                >> jnp.asarray(self.snk_bit)[None, :]
            ) & 1  # [B, n_seqs * 2]; 0 = match parked in this sink
            out = jnp.zeros((B, max(1, len(self.columns))), dtype=jnp.int32)
            out = out.at[:, jnp.asarray(self.snk_slot)].max(
                1 - alive.astype(jnp.int32)
            )
            return out.astype(bool)

        return d0, step, finish

    def _perbyte_pair_stepper(self, B: int, lengths: jax.Array):
        """Per-byte gated fallback for banks whose padding byte is not
        provably transparent (unreachable for banks built by this module
        — the builder strips byte 0 — but kept as the correctness path
        should a future builder admit it)."""
        select = self._row_select
        d0 = jnp.full((B, self.n_words), 0xFFFFFFFF, dtype=jnp.uint32)
        hits0 = jnp.zeros((B, self.n_words), dtype=jnp.uint32)

        def one(carry, b, pos_ok):
            d, hits = carry
            m = select(b)
            d_new = (self._s1(d) & self.start_clear[None, :]) | m
            active = pos_ok[:, None]
            hits = jnp.where(
                active, hits | ((~d_new) & self.end_mask[None, :]), hits
            )
            return jnp.where(active, d_new, d), hits

        def step(carry, b1, b2, t):
            p0 = 2 * t
            carry = one(carry, b1, p0 < lengths)
            return one(carry, b2, p0 + 1 < lengths)

        def finish(carry):
            _, hits = carry
            return self.columns_from_hits(hits)

        return (d0, hits0), step, finish

    def _run(
        self, lines_tb: jax.Array, lengths: jax.Array
    ) -> jax.Array:
        """lines_tb: uint8 [T, B]; returns bool [B, n_columns_in_bank]."""
        from log_parser_tpu.ops.match import pack_byte_pairs

        T, B = lines_tb.shape
        init, step, finish = self.pair_stepper(B, lengths)
        pairs, ts = pack_byte_pairs(lines_tb)
        carry, _ = jax.lax.scan(
            lambda c, xs: (step(c, xs[0][0], xs[0][1], xs[1]), None),
            init,
            (pairs, ts),
        )
        return finish(carry)

    # ----------------------------------------------------------- host carry

    def host_carry(self) -> "ShiftOrHostCarry | None":
        """Resumable host-side scanner over ONE line's bytes, bit-exact
        with the device steppers (streaming follow-mode feeds a partial
        tail line chunk by chunk and snapshots the would-be cube row
        without rescanning from byte 0). None for banks whose padding
        byte is not provably transparent — those would need per-position
        gating that a byte-resumable carry cannot replay."""
        if not self.pad0_transparent:
            return None
        return ShiftOrHostCarry(self)


class ShiftOrHostCarry:
    """Carried Shift-Or registers for one growing line (host, numpy).

    Sink layout advances at byte-PAIR granularity — the sticky-sink
    persistence term ``cand & (d | not_sink)`` is defined per pair, so a
    byte-level replay could kill a candidate the device keeps. An odd
    trailing byte is held in the carry and replayed as the device's
    (byte, 0) pair inside :meth:`snapshot_bits`, which also applies the
    same virtual padding pair as the device ``finish`` (extra padding
    pairs are no-ops on a ``pad0_transparent`` bank, so padded width
    does not matter — the width-independence the line cache relies on).
    Bare layout accumulates complement-hits per byte exactly like
    ``_ungated_hits_stepper``."""

    def __init__(self, bank: ShiftOrBank):
        self.bank = bank
        c = bank._np
        self._mask = c["mask"]
        self._sc = c["start_clear"]
        self._em = c["end_mask"]
        self._cm = c["cont_mask"]
        if bank.sinks:
            self._c2 = np.asarray(bank.c2)
            self._not_sink = np.asarray(bank.not_sink)
            self._pad_m12 = np.asarray(bank.pad_m12)
        self.reset()

    def _s1(self, x: np.ndarray) -> np.ndarray:
        sh = (x << 1).astype(np.uint32)
        if self.bank.has_chains:
            carry = np.concatenate([np.zeros(1, np.uint32), x[:-1] >> 31])
            sh = sh | (carry & self._cm)
        return sh

    def _s2(self, x: np.ndarray) -> np.ndarray:
        sh = (x << 2).astype(np.uint32)
        if self.bank.has_chains:
            carry = np.concatenate([np.zeros(1, np.uint32), x[:-1] >> 30])
            sh = sh | (carry & (self._cm * np.uint32(3)))
        return sh

    def reset(self) -> None:
        W = self.bank.n_words
        self._d = np.full(W, 0xFFFFFFFF, dtype=np.uint32)
        if not self.bank.sinks:
            self._nh = np.full(W, 0xFFFFFFFF, dtype=np.uint32)
        self._odd: int | None = None

    def _pair(self, d: np.ndarray, b1: int, b2: int) -> np.ndarray:
        m1 = self._mask[b1]
        m2 = self._mask[b2]
        m12 = (self._s1(m1) & self._sc) | m2
        cand = (self._s2(d) & self._c2) | m12
        return cand & (d | self._not_sink)

    def feed(self, data: bytes) -> None:
        if not data:
            return
        if self.bank.sinks:
            buf = data
            if self._odd is not None:
                buf = bytes([self._odd]) + buf
                self._odd = None
            if len(buf) % 2:
                self._odd = buf[-1]
                buf = buf[:-1]
            d = self._d
            for i in range(0, len(buf), 2):
                d = self._pair(d, buf[i], buf[i + 1])
            self._d = d
        else:
            d, nh = self._d, self._nh
            not_e = ~self._em
            for b in data:
                d = (self._s1(d) & self._sc) | self._mask[b]
                nh = nh & (d | not_e)
            self._d, self._nh = d, nh

    def snapshot_bits(self) -> np.ndarray:
        """bool [n_bank_columns]: the cube row the device would produce
        if the line ended at the bytes fed so far. Non-destructive."""
        bank = self.bank
        if bank.sinks:
            d = self._d
            if self._odd is not None:
                d = self._pair(d, self._odd, 0)
            # the virtual padding pair the device finish applies
            cand = (self._s2(d) & self._c2) | self._pad_m12
            d = cand & (d | self._not_sink)
            alive = (d[bank.snk_word] >> bank.snk_bit.astype(np.uint32)) & 1
            out = np.zeros(max(1, len(bank.columns)), dtype=bool)
            np.maximum.at(out, bank.snk_slot, alive == 0)
            return out
        hits = ~self._nh & self._em
        seq_hit = (hits[bank.seq_word] >> bank.seq_bit.astype(np.uint32)) & 1
        out = np.zeros(max(1, len(bank.columns)), dtype=bool)
        np.maximum.at(out, bank.seq_slot, seq_hit.astype(bool))
        return out
