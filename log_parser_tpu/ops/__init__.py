"""JAX kernels: line encoding, automaton execution, vectorized scoring.

float64 is enabled process-wide here: the reference computes every factor in
Java ``double`` (ScoringService.java:102-109), and the ≤1e-6 parity target
needs f64 for the factor arithmetic. The heavy work (automaton gathers over
line bytes) is integer/int32 and unaffected; only the per-line factor math —
a vanishingly small fraction of the FLOPs — pays the TPU f64 emulation cost.
"""

import jax

jax.config.update("jax_enable_x64", True)

from log_parser_tpu.ops.encode import encode_lines  # noqa: E402
from log_parser_tpu.ops.match import DfaBank, AcRunner  # noqa: E402
from log_parser_tpu.ops.scoring import ScoringKernel  # noqa: E402

__all__ = ["AcRunner", "DfaBank", "ScoringKernel", "encode_lines"]
