"""Vectorized scoring: the reference's seven-factor formula as one jitted
JAX program over the whole line batch.

The reference scores each match with nested sequential scans
(ScoringService.java:63-112): proximity searches ±window lines per
secondary (:315-347), temporal walks backward per sequence event
(:230-305), context re-runs four regexes over each window
(ContextAnalysisService.java:62-83), frequency reads mutable shared state
(:84-88). Here every factor becomes a closed-form array computation over
the match cube:

- chronological: elementwise piecewise-linear on line position (:123-151);
- proximity: nearest-neighbor distances via prefix/suffix cummax over each
  secondary's match column — exact for any window because the closest hit
  overall is the closest hit within the window (:161-190);
- temporal: per-event inclusive prefix-cummax of "last line where the event
  matched", then the backward chain becomes a static sequence of gathers
  (:230-262), with the ±5 near-primary window as a prefix-sum range-any
  (:272-286);
- context: prefix sums of the four per-line context flags turn every window
  sum into two gathers, with the else-if (error shadows warn), capped stack
  bonus, density penalty, and cap applied exactly
  (ContextAnalysisService.java:62-106);
- frequency: the read-before-record order dependence (:84-88 — match N of a
  pattern sees counts 1..N-1) is an exclusive prefix count over the batch,
  composed with the persisted windowed count carried in from the engine.

All factor math is float64, matching Java double arithmetic to well under
the 1e-6 parity budget, including the inf/NaN corners of zero-valued
tunables (IEEE division semantics match Java's).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from log_parser_tpu.config import ScoringConfig
from log_parser_tpu.golden.engine import (
    DENSITY_MIN_LINES,
    DENSITY_PENALTY,
    DENSITY_RATIO,
    ERROR_WEIGHT,
    EXCEPTION_WEIGHT,
    SEQUENCE_NEAR_WINDOW,
    STACK_BONUS_CAP,
    STACK_WEIGHT,
    WARN_WEIGHT,
)
from log_parser_tpu.javamath import java_div
from log_parser_tpu.patterns.bank import (
    CTX_ERROR,
    CTX_EXCEPTION,
    CTX_STACK,
    CTX_WARN,
    PatternBank,
)

f64 = jnp.float64


@dataclasses.dataclass
class ScoreBatch:
    """Device outputs for one batch."""

    scores: np.ndarray  # float64 [B, P] — 0 where no match
    primary_match: np.ndarray  # bool [B, P]
    slot_batch_counts: np.ndarray  # int64 [n_freq_slots] matches to record


def _excl_cumsum(x: jax.Array, axis: int = 0) -> jax.Array:
    c = jnp.cumsum(x, axis=axis)
    return c - x


def _prefix(x: jax.Array) -> jax.Array:
    """[B] -> [B+1] with leading 0: window sums become two gathers."""
    return jnp.concatenate([jnp.zeros((1,) + x.shape[1:], x.dtype), jnp.cumsum(x, axis=0)])


class ScoringKernel:
    """Jitted scoring program specialized to one PatternBank + config."""

    def __init__(self, bank: PatternBank, config: ScoringConfig):
        self.bank = bank
        self.config = config

        # ---- static structure lifted to numpy / python ---------------------
        self.sec_cols = np.asarray([e.column for e in bank.secondaries], dtype=np.int32)
        self.sec_owner = np.asarray([e.pattern_idx for e in bank.secondaries], dtype=np.int32)
        self.sec_weight = np.asarray([e.weight for e in bank.secondaries], dtype=np.float64)
        self.sec_window = np.asarray(
            [min(config.proximity_max_window, e.window) for e in bank.secondaries],
            dtype=np.int32,
        )
        self.sequences = bank.sequences
        self.seq_event_cols = sorted(
            {c for s in bank.sequences for c in s.event_columns}
        )
        self.seq_col_pos = {c: i for i, c in enumerate(self.seq_event_cols)}

        # unique context window shapes: (has_rules, before, after)
        shapes: list[tuple[bool, int, int]] = []
        shape_idx: dict[tuple[bool, int, int], int] = {}
        pattern_shape = []
        for p_idx in range(bank.n_patterns):
            key = (
                bool(bank.has_context_rules[p_idx]),
                int(bank.ctx_before[p_idx]),
                int(bank.ctx_after[p_idx]),
            )
            if key not in shape_idx:
                shape_idx[key] = len(shapes)
                shapes.append(key)
            pattern_shape.append(shape_idx[key])
        self.ctx_shapes = shapes
        self.pattern_ctx_shape = np.asarray(pattern_shape, dtype=np.int32)

        # frequency: within-line ordering corrections only needed for slots
        # shared by >1 pattern (same pattern id in several patterns)
        slot_members: dict[int, list[int]] = {}
        for p_idx, slot in enumerate(bank.freq_slot):
            if slot >= 0:
                slot_members.setdefault(int(slot), []).append(p_idx)
        self.shared_slots = {s: m for s, m in slot_members.items() if len(m) > 1}

        # config-derived constants with Java double semantics for zeros
        self.chrono_early = float(config.chronological_early_bonus_threshold)
        self.chrono_penalty = float(config.chronological_penalty_threshold)
        self.chrono_bonus_quot = java_div(
            config.chronological_max_early_bonus - 1.5,
            config.chronological_early_bonus_threshold,
        )
        self.chrono_middle_quot = java_div(
            0.5,
            config.chronological_penalty_threshold
            - config.chronological_early_bonus_threshold,
        )
        self.freq_hours = float(config.frequency_time_window_hours)

        self._jit = jax.jit(self._score)

    # ------------------------------------------------------------------ entry

    def score_batch(
        self,
        match_cube: np.ndarray,
        n_lines: int,
        freq_base: np.ndarray,
        freq_exists: np.ndarray,
    ) -> ScoreBatch:
        """``match_cube``: bool [B, n_columns] from the match kernels.
        ``freq_base``: float64 [n_freq_slots] windowed counts at batch start.
        ``freq_exists``: bool [n_freq_slots] — tracker has an entry for the
        slot (distinct from count 0: an expired window still has an entry,
        FrequencyTrackingService.java:69-71 vs :74-83).
        """
        scores, pm, counts = self._jit(
            jnp.asarray(match_cube),
            jnp.asarray(n_lines),
            jnp.asarray(freq_base),
            jnp.asarray(freq_exists),
        )
        return ScoreBatch(
            scores=np.asarray(scores),
            primary_match=np.asarray(pm),
            slot_batch_counts=np.asarray(counts),
        )

    # ------------------------------------------------------------------ jitted

    def _score(
        self,
        cube: jax.Array,
        n_lines: jax.Array,
        freq_base: jax.Array,
        freq_exists: jax.Array,
    ):
        bank, cfg = self.bank, self.config
        B = cube.shape[0]
        P = bank.n_patterns
        idx = jnp.arange(B, dtype=jnp.int32)
        valid = idx < n_lines
        # padding rows must contribute nothing to ANY factor: an
        # empty-matching regex (^$, \s*) accepts zero-length padding rows,
        # which would otherwise produce phantom proximity/sequence hits
        cube = cube & valid[:, None]

        pm = cube[:, jnp.asarray(bank.primary_columns)] if P else jnp.zeros((B, 0), bool)

        chrono = self._chronological(idx, n_lines)  # [B]
        prox = self._proximity(cube, idx, B, P)  # [B, P]
        temp = self._temporal(cube, idx, B, P, n_lines)  # [B, P]
        ctx = self._context(cube, idx, B, n_lines)  # [B, P]
        penalty, counts = self._frequency(pm, freq_base, freq_exists, B, P)  # [B, P]

        conf = jnp.asarray(bank.confidence)[None, :]
        sev = jnp.asarray(bank.severity_multiplier)[None, :]
        scores = (
            conf * sev * chrono[:, None].astype(f64) * prox * temp * ctx * (1.0 - penalty)
        )
        scores = jnp.where(pm, scores, 0.0)
        return scores, pm, counts

    def _chronological(self, idx: jax.Array, n_lines: jax.Array) -> jax.Array:
        """ScoringService.java:123-151."""
        pos = idx.astype(f64) / n_lines.astype(f64)
        early = self.chrono_early
        penalty = self.chrono_penalty
        return jnp.where(
            pos <= early,
            1.5 + (early - pos) * self.chrono_bonus_quot,
            jnp.where(
                pos <= penalty,
                1.0 + (penalty - pos) * self.chrono_middle_quot,
                0.5 + (1.0 - pos),
            ),
        )

    def _proximity(self, cube: jax.Array, idx: jax.Array, B: int, P: int) -> jax.Array:
        """ScoringService.java:161-190,315-347 — nearest hit of each
        secondary column on either side, primary line excluded by
        construction (strict prev/next)."""
        if len(self.sec_cols) == 0:
            return jnp.ones((B, P), dtype=f64)
        sm = cube[:, jnp.asarray(self.sec_cols)]  # [B, S]
        col_idx = idx[:, None]
        # "no hit" sentinel must exceed any configurable window, not just B:
        # with window > B a sentinel of B+1 would pass the window test
        big = jnp.int32(1 << 30)

        prev_incl = jax.lax.cummax(jnp.where(sm, col_idx, -1), axis=0)
        prev = jnp.concatenate(
            [jnp.full((1, sm.shape[1]), -1, prev_incl.dtype), prev_incl[:-1]], axis=0
        )
        nxt_incl = jnp.flip(
            jax.lax.cummin(jnp.flip(jnp.where(sm, col_idx, big), axis=0), axis=0), axis=0
        )
        nxt = jnp.concatenate(
            [nxt_incl[1:], jnp.full((1, sm.shape[1]), big, nxt_incl.dtype)], axis=0
        )

        d_prev = jnp.where(prev >= 0, col_idx - prev, big)
        d_next = jnp.where(nxt < big, nxt - col_idx, big)
        dist = jnp.minimum(d_prev, d_next)  # [B, S]
        window = jnp.asarray(self.sec_window)[None, :]
        found = dist <= window
        decay = jnp.exp(-dist.astype(f64) / self.config.proximity_decay_constant)
        contrib = jnp.where(found, jnp.asarray(self.sec_weight)[None, :] * decay, 0.0)

        prox = jnp.ones((B, P), dtype=f64)
        return prox.at[:, jnp.asarray(self.sec_owner)].add(contrib)

    def _temporal(
        self, cube: jax.Array, idx: jax.Array, B: int, P: int, n_lines: jax.Array
    ) -> jax.Array:
        """ScoringService.java:199-305 — the backward chain becomes gathers
        into per-event inclusive prefix-cummax arrays; the near-primary ±5
        window check is a prefix-sum range-any (:272-286). Note the search
        restarts at the *primary* line, not the near-window hit (:250)."""
        temp = jnp.ones((B, P), dtype=f64)
        if not self.sequences:
            return temp
        em = cube[:, jnp.asarray(self.seq_event_cols, dtype=np.int32)]  # [B, E]
        col_idx = idx[:, None]
        prev_incl = jax.lax.cummax(jnp.where(em, col_idx, -1), axis=0)  # [B, E]
        prefix_counts = _prefix(em.astype(jnp.int32))  # [B+1, E]

        w = SEQUENCE_NEAR_WINDOW
        for seq in self.sequences:
            if not seq.event_columns:
                continue
            last_e = self.seq_col_pos[seq.event_columns[-1]]
            lo = jnp.clip(idx - w, 0, B)
            hi = jnp.minimum(idx + w + 1, n_lines).astype(jnp.int32)
            hi = jnp.clip(hi, 0, B)
            near = (prefix_counts[hi, last_e] - prefix_counts[lo, last_e]) > 0

            ok = near
            cur = idx
            for col in reversed(seq.event_columns[:-1]):
                e = self.seq_col_pos[col]
                g = jnp.where(
                    cur >= 1, prev_incl[jnp.clip(cur - 1, 0, B - 1), e], -1
                )
                ok = ok & (g >= 0)
                cur = jnp.clip(g, 0, B - 1)
            temp = temp.at[:, seq.pattern_idx].add(jnp.where(ok, seq.bonus, 0.0))
        return temp

    def _context(
        self, cube: jax.Array, idx: jax.Array, B: int, n_lines: jax.Array
    ) -> jax.Array:
        """ContextAnalysisService.java:46-117 via prefix sums."""
        if not self.ctx_shapes:
            return jnp.ones((B, 0), dtype=f64)
        err = cube[:, CTX_ERROR]
        warn = cube[:, CTX_WARN] & ~err  # the else-if at :64-70
        stack = cube[:, CTX_STACK]
        exc = cube[:, CTX_EXCEPTION]
        line_score = (
            ERROR_WEIGHT * err.astype(f64)
            + WARN_WEIGHT * warn.astype(f64)
            + STACK_WEIGHT * stack.astype(f64)
            + EXCEPTION_WEIGHT * exc.astype(f64)
        )
        ps_score = _prefix(line_score)
        ps_stack = _prefix(stack.astype(jnp.int32))
        ps_err = _prefix(err.astype(jnp.int32))

        cols = []
        for has_rules, before, after in self.ctx_shapes:
            if not has_rules:
                # context = matched line only (AnalysisService.java:135-139)
                w_score = line_score
                w_stack = stack.astype(jnp.int32)
                w_err = err.astype(jnp.int32)
                total = jnp.ones_like(idx)
            else:
                lo = jnp.clip(idx - before, 0, B)
                hi = jnp.clip(jnp.minimum(idx + 1 + after, n_lines), 0, B).astype(
                    jnp.int32
                )
                w_score = ps_score[hi] - ps_score[lo]
                w_stack = ps_stack[hi] - ps_stack[lo]
                w_err = ps_err[hi] - ps_err[lo]
                total = hi - lo
            score = w_score + jnp.where(
                w_stack > 0,
                jnp.minimum(STACK_WEIGHT * w_stack.astype(f64), STACK_BONUS_CAP),
                0.0,
            )
            dense = (total > DENSITY_MIN_LINES) & (
                (w_stack + w_err).astype(f64) > total.astype(f64) * DENSITY_RATIO
            )
            score = jnp.where(dense, score * DENSITY_PENALTY, score)
            cols.append(
                jnp.minimum(1.0 + score, self.config.context_max_context_factor)
            )
        ctx_u = jnp.stack(cols, axis=1)  # [B, U]
        return ctx_u[:, jnp.asarray(self.pattern_ctx_shape)]

    def _frequency(
        self, pm: jax.Array, freq_base: jax.Array, freq_exists: jax.Array, B: int, P: int
    ):
        """FrequencyTrackingService.java:64-93 with the read-before-record
        order of ScoringService.java:84-88: match N sees N-1 prior counts.

        A slot with no tracker entry AND no prior in-batch match returns 0.0
        unconditionally (the ``frequency == null`` early return at :69-71) —
        this matters for degenerate tunables (window 0 → NaN rate, negative
        threshold → negative penalty) where the formula would otherwise
        produce a different value than the reference's early return."""
        bank, cfg = self.bank, self.config
        n_slots = max(1, bank.n_freq_slots)
        pm_f = pm.astype(jnp.int64)

        slot_ok = jnp.asarray(bank.freq_slot >= 0)
        safe_slot = jnp.asarray(np.maximum(bank.freq_slot, 0))
        # per-line per-slot match counts
        line_slot = jnp.zeros((B, n_slots), dtype=jnp.int64)
        line_slot = line_slot.at[:, safe_slot].add(
            jnp.where(slot_ok[None, :], pm_f, 0)
        )
        before_line = _excl_cumsum(line_slot, axis=0)  # [B, n_slots]

        prior = before_line[:, safe_slot]  # [B, P]
        # within-line ordering for slots shared by multiple patterns:
        # pattern-index order within a line (AnalysisService.java:91-92)
        for slot, members in self.shared_slots.items():
            sub = pm_f[:, jnp.asarray(members, dtype=np.int32)]
            corr = _excl_cumsum(sub, axis=1)
            for j, p_idx in enumerate(members):
                prior = prior.at[:, p_idx].add(corr[:, j])

        # a zero-length window expires every record instantly (the tracker
        # prunes timestamps <= now - window), so the windowed count at read
        # time is always 0 — the formula then yields 0/0 = NaN like Java
        if self.freq_hours == 0.0:
            count_before = jnp.zeros_like(prior, dtype=f64)
        else:
            count_before = freq_base[safe_slot][None, :] + prior.astype(f64)
        rate = count_before / self.freq_hours  # IEEE /0 → inf/nan, like Java
        thr = float(cfg.frequency_threshold)
        raw_penalty = jnp.minimum(
            float(cfg.frequency_max_penalty), (rate - thr) / thr
        )
        penalty = jnp.where(rate <= thr, 0.0, raw_penalty)
        never_tracked = (~freq_exists[safe_slot])[None, :] & (prior == 0)
        penalty = jnp.where(never_tracked, 0.0, penalty)
        penalty = jnp.where(slot_ok[None, :], penalty, 0.0)

        counts = jnp.sum(line_slot, axis=0)  # [n_slots]
        return penalty, counts
