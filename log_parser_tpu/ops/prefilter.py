"""AC prefilter + per-record DFA verify for wide pattern banks.

The Hyperscan decomposition (patterns/regex/literals.py:1-20) adapted to
batched TPU execution — the third matcher tier next to Shift-Or and the
dense DFA bank, replacing the reference's per-line `Matcher.find()` loop
for large libraries (AnalysisService.java:89-113):

1. the main fused byte scan carries ONE extra automaton: a combined
   Aho-Corasick over every prefiltered column's required literals
   (case-folded — folding only widens the filter, never drops a match),
   accumulating only a per-line any-hit bit: 3 ``[B]`` gathers per byte
   REGARDLESS of library width (the per-column hit words are W =
   ceil(n_cols/32) words wide — inlining them into the hot scan is
   O(W·B) per byte, which at 10k columns costs more than the dense bank);
2. hit lines are compacted and re-scanned through the same automaton
   accumulating the full per-column words; when the hit compaction
   overflows (real libraries contain common literals like "status" that
   fire on most lines — measured 200k/200k on the builtin bank), a
   ``lax.cond`` runs the words pass over the whole batch instead —
   graceful O(W·B) degradation, NOT the dense-bank cliff;
3. candidate (line, column) pairs are compacted into records and
   verified exactly: each record advances ITS column's packed DFA over
   its line's bytes — one gather per record per byte pair, independent
   of library width. Candidate capacity 2B; overflow falls back to the
   dense DFA scan inside the same compiled program.

Every path is closed on-device: no host round-trips, no retry ladder.

Soundness: every true match of a prefiltered column contains at least one
of its required literals (literals.py extraction invariant), so the AC
candidate set is a superset of matching (line, column) pairs; verification
is the column's exact DFA — identical to the dense path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from log_parser_tpu.ops.match import DfaBank, pack_byte_pairs
from log_parser_tpu.patterns.regex.ac import AhoCorasick

# Prefilter participation cap: total literal bytes in the trie (columns
# over budget fall to the union/dense tiers). Trie states ~= literal
# bytes, so the cap bounds device memory: the byte-precomposed goto is
# S x 1 KB and the out-words table S x W x 4 B — at the 256 KB cap and a
# 10k-column library (W=313) that is ~0.6 GB, well inside v5e HBM. The
# old 64 KB cap stranded 5,894 of a 10k-regex library's columns on ~92
# union groups (92 [B]-gathers per byte) when the whole point of the
# any-hit stage is width-independence.
MAX_PREFILTER_LITERALS = 1 << 18

_FOLD = np.arange(256, dtype=np.uint8)
_FOLD[ord("A") : ord("Z") + 1] += 32  # ASCII lowercase


def _compact(flags: jax.Array, K: int):
    """[N] bool -> (n, idx[K] int32 flat positions in order, valid[K]).
    Slot K is the trash slot; overflow detected via n > K."""
    f32 = flags.astype(jnp.int32)
    rank = jnp.cumsum(f32) - f32
    n = jnp.sum(f32)
    out_pos = jnp.where(flags & (rank < K), rank, K)
    idx = (
        jnp.zeros((K + 1,), jnp.int32)
        .at[out_pos]
        .set(jnp.arange(flags.shape[0], dtype=jnp.int32))[:K]
    )
    valid = jnp.arange(K, dtype=jnp.int32) < jnp.minimum(n, K)
    return n, idx, valid


class PrefilterBank:
    """One AC automaton + one packed verify-DFA bank over the prefiltered
    columns of a PatternBank.

    ``entries``: (global column id, MatcherColumn) — every column must have
    a compiled DFA and a non-empty required-literal set.
    """

    def __init__(self, entries):
        self.global_cols = np.asarray([g for g, _ in entries], dtype=np.int32)
        self.n_cols = len(entries)
        # verify bank: the same packed-DFA layout the dense tier uses; also
        # serves as the dense fallback scan on capacity overflow
        self.verify = DfaBank([c.dfa for _, c in entries], stride=2)

        lits: list[bytes] = []
        groups: list[int] = []
        for j, (_g, col) in enumerate(entries):
            for lit in col.literals:
                lits.append(lit.fold().text)
                groups.append(j)
        self.ac = AhoCorasick.build_cached(lits, groups=groups)
        self.n_words = self.ac.n_words
        # scan RAW bytes against folded literals: compose ASCII folding into
        # the byte-class table so folding costs nothing at runtime
        byte_class = self.ac.byte_class[_FOLD]
        self.out_words = jnp.asarray(self.ac.out_words)
        # Byte-precomposed goto with the DESTINATION's has-output flag in
        # bit 30: goto_byte[s, b] = nxt | (has_out[nxt] << 30). Turns the
        # any-hit stage's three per-element random gathers per byte (class,
        # goto, has_out) into ONE — per-element random gathers are
        # scalar-unit bound on TPU (PERF.md §1), so this triples the
        # stage's throughput. The trie is capped at MAX_PREFILTER_LITERALS
        # total literal bytes, so states ≤ ~262k → table ≤ ~268 MB int32.
        goto_b = self.ac.goto[:, byte_class]  # [S, 256] int32
        packed = goto_b | (self.ac.has_out[goto_b].astype(np.int32) << 30)
        # Byte 0 is padding-only (content NULs are needs_host — encode):
        # make it a SELF-LOOP carrying the state's own flag, so past a
        # line's end the state freezes itself and the flag/out-word ORs
        # are idempotent re-ORs — both stepper stages then run gate-free
        # (no ``pos < length`` compares or selects per byte).
        s_idx = np.arange(packed.shape[0], dtype=np.int32)
        packed[:, 0] = s_idx | (self.ac.has_out[s_idx].astype(np.int32) << 30)
        self.flat_goto_byte = jnp.asarray(packed.reshape(-1))

    @staticmethod
    def select(entries, budget: int = MAX_PREFILTER_LITERALS):
        """Greedy smallest-literal-set-first selection under the trie
        budget; returns (selected, rejected) entry lists."""
        order = sorted(
            range(len(entries)), key=lambda i: len(entries[i][1].literals)
        )
        selected, rejected = [], []
        used = 0
        for i in order:
            cost = sum(len(lit.text) for lit in entries[i][1].literals)
            if used + cost <= budget:
                used += cost
                selected.append(entries[i])
            else:
                rejected.append(entries[i])
        # preserve original (column) order for deterministic layouts
        key = {id(e): i for i, e in enumerate(entries)}
        selected.sort(key=lambda e: key[id(e)])
        rejected.sort(key=lambda e: key[id(e)])
        return selected, rejected

    # ------------------------------------ stage 1: any-hit bit, in-scan

    def anyhit_stepper(self, B: int, lengths: jax.Array):
        """Composable pair-stepper for the main fused scan. Carry:
        (ac_state [B] int32, any_hit [B] bool) — ONE [B] gather per byte
        through the byte-precomposed flagged goto table, independent of
        library width."""
        mask = jnp.int32((1 << 30) - 1)
        init = (
            jnp.zeros((B,), jnp.int32),
            jnp.zeros((B,), bool),
        )

        def one(s, a, b):
            # gate-free: padding bytes (0) self-loop with the state's own
            # flag (see the packed-table build), so s freezes and a
            # re-ORs an already-recorded flag
            v = jnp.take(self.flat_goto_byte, s * 256 + b.astype(jnp.int32))
            return v & mask, a | (v >= (1 << 30))

        def step(carry, b1, b2, t):
            s, a = carry
            s, a = one(s, a, b1)
            s, a = one(s, a, b2)
            return (s, a)

        def finish(carry):
            return carry[1]

        return init, step, finish

    # ----------------------------------- stage 2: per-column hit words

    def word_stepper(self, N: int, lengths: jax.Array):
        """Composable pair-stepper accumulating full per-column words.
        Carry: (ac_state [N] int32, hit_words [N, W] uint32)."""
        init = (
            jnp.zeros((N,), jnp.int32),
            jnp.zeros((N, self.n_words), jnp.uint32),
        )

        def one(s, w, b):
            # gate-free like the any-hit stage: padding self-loops make
            # the out-word OR an idempotent re-OR of the frozen state
            v = jnp.take(self.flat_goto_byte, s * 256 + b.astype(jnp.int32))
            s = v & jnp.int32((1 << 30) - 1)
            w = w | jnp.take(self.out_words, s, axis=0)
            return s, w

        def step(carry, b1, b2, t):
            s, w = carry
            s, w = one(s, w, b1)
            s, w = one(s, w, b2)
            return (s, w)

        def finish(carry):
            return carry[1]

        return init, step, finish

    def _word_scan(self, lines_tb: jax.Array, lengths: jax.Array):
        """Run the word stepper over ``lines_tb``: uint32 [N, W]."""
        N = lines_tb.shape[1]
        init, step, finish = self.word_stepper(N, lengths)
        pairs, ts = pack_byte_pairs(lines_tb)
        carry, _ = jax.lax.scan(
            lambda c, xs: (step(c, xs[0][0], xs[0][1], xs[1]), None),
            init,
            (pairs, ts),
        )
        return finish(carry)

    def unpack_candidates(self, hits: jax.Array):
        """uint32 [N, W] -> bool [N, n_cols] candidate matrix."""
        from log_parser_tpu.ops.match import unpack_hit_words

        return unpack_hit_words(hits, self.n_cols)

    # ----------------------------------------------- stage 3: record verify

    def verify_records(
        self,
        lines_tb: jax.Array,
        lengths: jax.Array,
        rec_line: jax.Array,
        rec_pcol: jax.Array,
        rec_valid: jax.Array,
    ) -> jax.Array:
        """Advance each record's column DFA over its line; bool [K_rec]."""
        vb = self.verify
        Kr = rec_line.shape[0]
        rec_len = jnp.where(rec_valid, lengths[rec_line], 0)
        states = vb.start[rec_pcol].astype(jnp.int32)
        pairs, ts = pack_byte_pairs(lines_tb)
        smax = vb.smax

        if vb.pair_stride:
            cpad = vb.cpad
            pad_cls = jnp.int32(vb.cmax)

            def step(states, xs):
                pair, t = xs
                p0 = 2 * t
                b1 = pair[0][rec_line].astype(jnp.int32)
                b2 = pair[1][rec_line].astype(jnp.int32)
                c1 = vb.byte_class[rec_pcol, b1]
                c2 = vb.byte_class[rec_pcol, b2]
                c1 = jnp.where(p0 < rec_len, c1, pad_cls)
                c2 = jnp.where(p0 + 1 < rec_len, c2, pad_cls)
                idx = ((rec_pcol * smax + states) * cpad + c1) * cpad + c2
                return jnp.take(vb.flat_trans2, idx), None

        else:
            cmax = vb.cmax

            def one(states, b, ok):
                cls = vb.byte_class[rec_pcol, b]
                idx = (rec_pcol * smax + states) * cmax + cls
                nxt = jnp.take(vb.flat_trans, idx)
                return jnp.where(ok, nxt, states)

            def step(states, xs):
                pair, t = xs
                p0 = 2 * t
                states = one(states, pair[0][rec_line].astype(jnp.int32), p0 < rec_len)
                states = one(
                    states, pair[1][rec_line].astype(jnp.int32), p0 + 1 < rec_len
                )
                return states, None

        states, _ = jax.lax.scan(step, states, (pairs, ts))
        ok = jnp.take(vb.flat_accept, rec_pcol * smax + states)
        return ok & rec_valid

    # --------------------------------------------------------- full pipeline

    def contribution(
        self,
        lines_tb: jax.Array,
        lengths: jax.Array,
        any_hit: jax.Array,
    ) -> jax.Array:
        """Stages 2+3 (after the main scan produced ``any_hit`` [B]):
        returns the bool [B, n_cols] cube slice for the prefiltered
        columns.

        Stage 2 accumulates per-column hit words over the COMPACTED hit
        rows (capacity B//8); when the compaction overflows — common
        literals firing on most lines — a ``lax.cond`` runs the words
        pass over the whole batch instead. Stage 3 compacts candidate
        (line, column) pairs (capacity 2B — the builtin library over a
        status-heavy corpus measures ~1.7/line) and verifies each record
        against its column's exact DFA, falling back to the dense scan
        over all prefiltered columns on overflow."""
        T, B = lines_tb.shape
        # Capacities are STATIC shapes, so they tax every batch: K_hit stays
        # proportional (a 1024-row floor measured -40% on the 4096-row
        # config-4 bench), but K_rec must hold the measured real-world
        # candidate density (~1.7 (line, col) pairs per line on the builtin
        # bank: common literals fire on most lines) — shrinking it to
        # 4*K_hit would send hit-heavy corpora to the dense fallback every
        # batch, the exact cliff this tier exists to avoid.
        K_hit = min(B, max(128, B // 8))
        K_rec = 2 * B

        n_hit, hit_rows, hit_valid = _compact(any_hit, K_hit)

        # ---- stage 2: candidate records, fixed [K_rec] shapes ------------
        def words_sparse(_):
            sub_len = jnp.where(hit_valid, lengths[hit_rows], 0)
            h = self._word_scan(lines_tb[:, hit_rows], sub_len)  # [K_hit, W]
            cand = self.unpack_candidates(h) & hit_valid[:, None]
            n_rec, rec_flat, rec_valid = _compact(cand.reshape(-1), K_rec)
            rec_line = hit_rows[rec_flat // self.n_cols]
            rec_pcol = rec_flat % self.n_cols
            return n_rec, rec_line, rec_pcol, rec_valid

        def words_full(_):
            h = self._word_scan(lines_tb, lengths)  # [B, W]
            cand = self.unpack_candidates(h)
            n_rec, rec_flat, rec_valid = _compact(cand.reshape(-1), K_rec)
            return n_rec, rec_flat // self.n_cols, rec_flat % self.n_cols, rec_valid

        n_rec, rec_line, rec_pcol, rec_valid = jax.lax.cond(
            n_hit <= K_hit, words_sparse, words_full, operand=None
        )

        # ---- stage 3: exact verification ---------------------------------
        def sparse(_):
            ver = self.verify_records(
                lines_tb, lengths, rec_line, rec_pcol, rec_valid
            )
            safe_line = jnp.where(rec_valid, rec_line, B)
            contrib = jnp.zeros((B + 1, self.n_cols), bool)
            return contrib.at[safe_line, rec_pcol].max(ver)[:B]

        def dense(_):
            init, step, finish = self.verify.pair_stepper(B, lengths)
            pairs, ts = pack_byte_pairs(lines_tb)
            states, _ = jax.lax.scan(
                lambda s, xs: (step(s, xs[0][0], xs[0][1], xs[1]), None),
                init,
                (pairs, ts),
            )
            return finish(states)[:, : self.n_cols]

        return jax.lax.cond(n_rec <= K_rec, sparse, dense, operand=None)
